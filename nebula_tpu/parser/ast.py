"""nGQL AST: sentences and clauses.

Role parity with the reference's plain-C++ AST (`parser/Sentence.h:19-63`
— 43 sentence kinds — plus TraverseSentences / MutateSentences /
MaintainSentences / AdminSentences / UserSentences / Clauses). Each
node keeps `to_string()` round-trip ability like the reference.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

from ..filter.expressions import Expression


class Kind(enum.Enum):
    SEQUENTIAL = "sequential"
    PIPE = "pipe"
    ASSIGNMENT = "assignment"
    GO = "go"
    FIND_PATH = "find_path"
    FETCH_VERTICES = "fetch_vertices"
    FETCH_EDGES = "fetch_edges"
    USE = "use"
    CREATE_SPACE = "create_space"
    DROP_SPACE = "drop_space"
    DESCRIBE_SPACE = "describe_space"
    CREATE_TAG = "create_tag"
    CREATE_EDGE = "create_edge"
    ALTER_TAG = "alter_tag"
    ALTER_EDGE = "alter_edge"
    DROP_TAG = "drop_tag"
    DROP_EDGE = "drop_edge"
    DESCRIBE_TAG = "describe_tag"
    DESCRIBE_EDGE = "describe_edge"
    INSERT_VERTICES = "insert_vertices"
    INSERT_EDGES = "insert_edges"
    DELETE_VERTICES = "delete_vertices"
    DELETE_EDGES = "delete_edges"
    UPDATE_VERTEX = "update_vertex"
    UPDATE_EDGE = "update_edge"
    YIELD = "yield"
    ORDER_BY = "order_by"
    LIMIT = "limit"
    GROUP_BY = "group_by"
    SET_OP = "set_op"
    SHOW = "show"
    SHOW_CREATE = "show_create"
    CONFIG = "config"
    BALANCE = "balance"
    CREATE_USER = "create_user"
    DROP_USER = "drop_user"
    ALTER_USER = "alter_user"
    CHANGE_PASSWORD = "change_password"
    GRANT = "grant"
    REVOKE = "revoke"
    INGEST = "ingest"
    DOWNLOAD = "download"
    CREATE_SNAPSHOT = "create_snapshot"
    DROP_SNAPSHOT = "drop_snapshot"
    MATCH = "match"
    FIND = "find"
    LOOKUP = "lookup"
    GET_SUBGRAPH = "get_subgraph"
    CREATE_INDEX = "create_index"
    DROP_INDEX = "drop_index"


class Sentence:
    kind: Kind

    def to_string(self) -> str:
        raise NotImplementedError

    def __repr__(self):
        return f"<{type(self).__name__}: {self.to_string()}>"


# ---------------------------------------------------------------------------
# clauses (ref: parser/Clauses.{h,cpp})
# ---------------------------------------------------------------------------

@dataclass
class StepClause:
    steps: int = 1
    upto: bool = False

    def to_string(self) -> str:
        s = f"{self.steps} STEPS"
        return f"UPTO {s}" if self.upto else s


@dataclass
class VertexRef:
    """FROM source: literal vids / uuids, or an input/variable column ref."""
    vids: Optional[List[Expression]] = None     # literal/function vid exprs
    ref: Optional[Expression] = None            # InputPropExpr or VariablePropExpr

    def to_string(self) -> str:
        if self.ref is not None:
            return self.ref.to_string()
        return ", ".join(v.to_string() for v in self.vids or [])


@dataclass
class OverEdge:
    name: str
    alias: Optional[str] = None

    def to_string(self) -> str:
        return f"{self.name} AS {self.alias}" if self.alias else self.name


class Direction(enum.Enum):
    OUT = "out"
    IN = "in"            # REVERSELY
    BOTH = "both"        # BIDIRECT


@dataclass
class OverClause:
    edges: List[OverEdge] = field(default_factory=list)  # empty = OVER *
    direction: Direction = Direction.OUT
    is_all: bool = False

    def to_string(self) -> str:
        core = "*" if self.is_all else ", ".join(e.to_string() for e in self.edges)
        sfx = {Direction.OUT: "", Direction.IN: " REVERSELY",
               Direction.BOTH: " BIDIRECT"}[self.direction]
        return f"OVER {core}{sfx}"


@dataclass
class WhereClause:
    filter: Expression

    def to_string(self) -> str:
        return f"WHERE {self.filter.to_string()}"


@dataclass
class YieldColumn:
    expr: Expression
    alias: Optional[str] = None
    agg_fun: Optional[str] = None   # COUNT/SUM/AVG/... when used in GROUP BY

    def name(self) -> str:
        if self.alias:
            return self.alias
        if self.agg_fun:
            return f"{self.agg_fun}({self.expr.to_string()})"
        return self.expr.to_string()

    def to_string(self) -> str:
        s = (f"{self.agg_fun}({self.expr.to_string()})" if self.agg_fun
             else self.expr.to_string())
        return f"{s} AS {self.alias}" if self.alias else s


@dataclass
class YieldClause:
    columns: List[YieldColumn] = field(default_factory=list)
    distinct: bool = False

    def to_string(self) -> str:
        d = "DISTINCT " if self.distinct else ""
        return f"YIELD {d}{', '.join(c.to_string() for c in self.columns)}"


@dataclass
class OrderFactor:
    expr: Expression      # typically InputPropExpr
    ascending: bool = True

    def to_string(self) -> str:
        return f"{self.expr.to_string()}{'' if self.ascending else ' DESC'}"


@dataclass
class EdgeKeyRef:
    """src -> dst [@rank] for FETCH/DELETE EDGE."""
    src: Expression
    dst: Expression
    rank: int = 0

    def to_string(self) -> str:
        return f"{self.src.to_string()}->{self.dst.to_string()}@{self.rank}"


# ---------------------------------------------------------------------------
# traverse sentences (ref: parser/TraverseSentences.h)
# ---------------------------------------------------------------------------

@dataclass
class SequentialSentences(Sentence):
    sentences: List[Sentence]
    # `PROFILE <stmt>` prefix: execute identically but force-sample the
    # query's trace and return the rendered span tree with the response
    # (common/tracing.py; docs/manual/10-observability.md)
    profile: bool = False
    kind = Kind.SEQUENTIAL

    def to_string(self) -> str:
        prefix = "PROFILE " if self.profile else ""
        return prefix + "; ".join(s.to_string() for s in self.sentences)


@dataclass
class PipedSentence(Sentence):
    left: Sentence
    right: Sentence
    kind = Kind.PIPE

    def to_string(self) -> str:
        return f"{self.left.to_string()} | {self.right.to_string()}"


@dataclass
class AssignmentSentence(Sentence):
    var: str
    sentence: Sentence
    kind = Kind.ASSIGNMENT

    def to_string(self) -> str:
        return f"${self.var} = {self.sentence.to_string()}"


@dataclass
class GoSentence(Sentence):
    step: StepClause
    from_: VertexRef
    over: OverClause
    where: Optional[WhereClause] = None
    yield_: Optional[YieldClause] = None
    kind = Kind.GO

    def to_string(self) -> str:
        parts = ["GO", self.step.to_string(), "FROM", self.from_.to_string(),
                 self.over.to_string()]
        if self.where:
            parts.append(self.where.to_string())
        if self.yield_:
            parts.append(self.yield_.to_string())
        return " ".join(parts)


@dataclass
class FindPathSentence(Sentence):
    shortest: bool
    from_: VertexRef
    to: VertexRef
    over: OverClause
    step: StepClause = field(default_factory=lambda: StepClause(5, upto=True))
    noloop: bool = False
    kind = Kind.FIND_PATH

    def to_string(self) -> str:
        k = "SHORTEST" if self.shortest else ("NOLOOP" if self.noloop else "ALL")
        return (f"FIND {k} PATH FROM {self.from_.to_string()} TO "
                f"{self.to.to_string()} {self.over.to_string()} "
                f"UPTO {self.step.steps} STEPS")


@dataclass
class FetchVerticesSentence(Sentence):
    tag: str                       # "*" = all tags
    src: VertexRef
    yield_: Optional[YieldClause] = None
    kind = Kind.FETCH_VERTICES

    def to_string(self) -> str:
        s = f"FETCH PROP ON {self.tag} {self.src.to_string()}"
        return f"{s} {self.yield_.to_string()}" if self.yield_ else s


@dataclass
class FetchEdgesSentence(Sentence):
    edge: str
    keys: Optional[List[EdgeKeyRef]] = None
    ref: Optional[Expression] = None   # $-.col / $var.col based keys
    yield_: Optional[YieldClause] = None
    kind = Kind.FETCH_EDGES

    def to_string(self) -> str:
        ks = (", ".join(k.to_string() for k in self.keys) if self.keys
              else (self.ref.to_string() if self.ref else ""))
        s = f"FETCH PROP ON {self.edge} {ks}"
        return f"{s} {self.yield_.to_string()}" if self.yield_ else s


@dataclass
class LookupSentence(Sentence):
    """LOOKUP ON <tag|edge> [WHERE prop OP value [AND ...]] [YIELD ...]
    (ref: parser/TraverseSentences.h LookupSentence). Serves from a
    secondary index: device-resident sorted-array search when one
    covers the filter, storaged CPU prop scan otherwise."""
    on_name: str
    where: Optional[WhereClause] = None
    yield_: Optional[YieldClause] = None
    kind = Kind.LOOKUP

    def to_string(self) -> str:
        parts = [f"LOOKUP ON {self.on_name}"]
        if self.where:
            parts.append(self.where.to_string())
        if self.yield_:
            parts.append(self.yield_.to_string())
        return " ".join(parts)


@dataclass
class GetSubgraphSentence(Sentence):
    """GET SUBGRAPH [<n> STEPS] FROM <vids> [OVER edges] — bounded
    frontier expansion capturing every traversed edge (ref:
    parser/TraverseSentences.h GetSubgraphSentence)."""
    step: StepClause
    from_: VertexRef
    over: OverClause = field(default_factory=OverClause)
    kind = Kind.GET_SUBGRAPH

    def to_string(self) -> str:
        parts = ["GET SUBGRAPH"]
        if self.step.steps != 1:
            parts.append(f"{self.step.steps} STEPS")
        parts.append(f"FROM {self.from_.to_string()}")
        if self.over.edges or self.over.is_all:
            parts.append(self.over.to_string())
        return " ".join(parts)


@dataclass
class MatchPattern:
    """The supported MATCH subset:
    (a:tag {prop: value})-[e[:name][*min..max]]->(b)"""
    src_alias: str
    tag: str
    prop: str
    value: Expression
    edge_alias: Optional[str] = None
    edge_names: List[str] = field(default_factory=list)  # empty = all edges
    min_hops: int = 1
    max_hops: int = 1
    dst_alias: Optional[str] = None

    def to_string(self) -> str:
        e = self.edge_alias or ""
        if self.edge_names:
            e += ":" + "|".join(self.edge_names)
        if (self.min_hops, self.max_hops) != (1, 1):
            e += f"*{self.min_hops}..{self.max_hops}"
        return (f"({self.src_alias}:{self.tag} {{{self.prop}: "
                f"{self.value.to_string()}}})-[{e}]->({self.dst_alias or ''})")


@dataclass
class YieldSentence(Sentence):
    yield_: YieldClause
    where: Optional[WhereClause] = None
    kind = Kind.YIELD

    def to_string(self) -> str:
        s = self.yield_.to_string()
        return f"{s} {self.where.to_string()}" if self.where else s


@dataclass
class OrderBySentence(Sentence):
    factors: List[OrderFactor]
    kind = Kind.ORDER_BY

    def to_string(self) -> str:
        return "ORDER BY " + ", ".join(f.to_string() for f in self.factors)


@dataclass
class LimitSentence(Sentence):
    count: int
    offset: int = 0
    kind = Kind.LIMIT

    def to_string(self) -> str:
        return f"LIMIT {self.offset},{self.count}" if self.offset else f"LIMIT {self.count}"


@dataclass
class GroupBySentence(Sentence):
    group_cols: List[YieldColumn]
    yield_: YieldClause
    kind = Kind.GROUP_BY

    def to_string(self) -> str:
        return ("GROUP BY " + ", ".join(c.to_string() for c in self.group_cols)
                + " " + self.yield_.to_string())


class SetOp(enum.Enum):
    UNION = "UNION"
    UNION_DISTINCT = "UNION DISTINCT"
    INTERSECT = "INTERSECT"
    MINUS = "MINUS"


@dataclass
class SetSentence(Sentence):
    op: SetOp
    left: Sentence
    right: Sentence
    kind = Kind.SET_OP

    def to_string(self) -> str:
        return f"({self.left.to_string()} {self.op.value} {self.right.to_string()})"


# ---------------------------------------------------------------------------
# maintain sentences (DDL; ref: parser/MaintainSentences.h)
# ---------------------------------------------------------------------------

@dataclass
class ColumnDef:
    name: str
    type_name: str                 # INT/DOUBLE/STRING/BOOL/TIMESTAMP/VID
    default: Optional[Any] = None

    def to_string(self) -> str:
        s = f"{self.name} {self.type_name}"
        if self.default is not None:
            s += f" DEFAULT {self.default!r}"
        return s


@dataclass
class SchemaOpts:
    ttl_duration: Optional[int] = None
    ttl_col: Optional[str] = None


@dataclass
class UseSentence(Sentence):
    space: str
    kind = Kind.USE

    def to_string(self) -> str:
        return f"USE {self.space}"


@dataclass
class MatchSentence(Sentence):
    """MATCH (a:tag {prop: v})-[e*1..k]->(b) RETURN ... — when `pattern`
    is set the executor lowers it onto a LOOKUP-seeded GO plan. Any
    other MATCH text still parses to the raw form and execution reports
    unsupported (ref: graph/MatchExecutor.cpp 'Match not supported
    yet', parser Sentence.h kMatch)."""
    raw: str
    pattern: Optional["MatchPattern"] = None
    return_: Optional[YieldClause] = None
    kind = Kind.MATCH

    def to_string(self) -> str:
        return self.raw


@dataclass
class FindSentence(Sentence):
    """Grammar-level only, like the reference: FIND <props> FROM <label>
    parses but execution reports unsupported (ref: graph/FindExecutor
    .cpp:20 'Does not support')."""
    raw: str
    kind = Kind.FIND

    def to_string(self) -> str:
        return self.raw


@dataclass
class CreateSpaceSentence(Sentence):
    name: str
    partition_num: int = 100
    replica_factor: int = 1
    if_not_exists: bool = False
    kind = Kind.CREATE_SPACE

    def to_string(self) -> str:
        return (f"CREATE SPACE {self.name}(partition_num={self.partition_num}, "
                f"replica_factor={self.replica_factor})")


@dataclass
class DropSpaceSentence(Sentence):
    name: str
    if_exists: bool = False
    kind = Kind.DROP_SPACE

    def to_string(self) -> str:
        return f"DROP SPACE {self.name}"


@dataclass
class DescribeSpaceSentence(Sentence):
    name: str
    kind = Kind.DESCRIBE_SPACE

    def to_string(self) -> str:
        return f"DESCRIBE SPACE {self.name}"


@dataclass
class CreateSchemaSentence(Sentence):
    """CREATE TAG / CREATE EDGE."""
    is_edge: bool
    name: str
    columns: List[ColumnDef] = field(default_factory=list)
    opts: SchemaOpts = field(default_factory=SchemaOpts)
    if_not_exists: bool = False

    @property
    def kind(self):
        return Kind.CREATE_EDGE if self.is_edge else Kind.CREATE_TAG

    def to_string(self) -> str:
        what = "EDGE" if self.is_edge else "TAG"
        cols = ", ".join(c.to_string() for c in self.columns)
        return f"CREATE {what} {self.name}({cols})"


@dataclass
class AlterSchemaSentence(Sentence):
    is_edge: bool
    name: str
    adds: List[ColumnDef] = field(default_factory=list)
    changes: List[ColumnDef] = field(default_factory=list)
    drops: List[str] = field(default_factory=list)
    opts: SchemaOpts = field(default_factory=SchemaOpts)

    @property
    def kind(self):
        return Kind.ALTER_EDGE if self.is_edge else Kind.ALTER_TAG

    def to_string(self) -> str:
        what = "EDGE" if self.is_edge else "TAG"
        parts = [f"ALTER {what} {self.name}"]
        if self.adds:
            parts.append("ADD (" + ", ".join(c.to_string() for c in self.adds) + ")")
        if self.changes:
            parts.append("CHANGE (" + ", ".join(c.to_string() for c in self.changes) + ")")
        if self.drops:
            parts.append("DROP (" + ", ".join(self.drops) + ")")
        return " ".join(parts)


@dataclass
class DropSchemaSentence(Sentence):
    is_edge: bool
    name: str
    if_exists: bool = False

    @property
    def kind(self):
        return Kind.DROP_EDGE if self.is_edge else Kind.DROP_TAG

    def to_string(self) -> str:
        return f"DROP {'EDGE' if self.is_edge else 'TAG'} {self.name}"


@dataclass
class CreateIndexSentence(Sentence):
    """CREATE TAG|EDGE INDEX <name> ON <schema>(<fields>) (ref:
    parser/MaintainSentences.h CreateTagIndexSentence)."""
    is_edge: bool
    name: str
    schema_name: str
    fields: List[str] = field(default_factory=list)
    if_not_exists: bool = False
    kind = Kind.CREATE_INDEX

    def to_string(self) -> str:
        what = "EDGE" if self.is_edge else "TAG"
        return (f"CREATE {what} INDEX {self.name} ON "
                f"{self.schema_name}({', '.join(self.fields)})")


@dataclass
class DropIndexSentence(Sentence):
    is_edge: bool
    name: str
    if_exists: bool = False
    kind = Kind.DROP_INDEX

    def to_string(self) -> str:
        return f"DROP {'EDGE' if self.is_edge else 'TAG'} INDEX {self.name}"


@dataclass
class DescribeSchemaSentence(Sentence):
    is_edge: bool
    name: str

    @property
    def kind(self):
        return Kind.DESCRIBE_EDGE if self.is_edge else Kind.DESCRIBE_TAG

    def to_string(self) -> str:
        return f"DESCRIBE {'EDGE' if self.is_edge else 'TAG'} {self.name}"


# ---------------------------------------------------------------------------
# mutate sentences (ref: parser/MutateSentences.h)
# ---------------------------------------------------------------------------

@dataclass
class InsertVerticesSentence(Sentence):
    # tag_items: [(tag_name, [prop names])]; rows: [(vid_expr, [value exprs])]
    tag_items: List[Tuple[str, List[str]]]
    rows: List[Tuple[Expression, List[Expression]]]
    overwritable: bool = True
    kind = Kind.INSERT_VERTICES

    def to_string(self) -> str:
        tags = ", ".join(f"{t}({', '.join(ps)})" for t, ps in self.tag_items)
        rows = ", ".join(
            f"{vid.to_string()}:({', '.join(v.to_string() for v in vals)})"
            for vid, vals in self.rows)
        return f"INSERT VERTEX {tags} VALUES {rows}"


@dataclass
class InsertEdgesSentence(Sentence):
    edge: str
    props: List[str]
    # rows: [(src_expr, dst_expr, rank, [value exprs])]
    rows: List[Tuple[Expression, Expression, int, List[Expression]]]
    overwritable: bool = True
    kind = Kind.INSERT_EDGES

    def to_string(self) -> str:
        rows = ", ".join(
            f"{s.to_string()}->{d.to_string()}@{r}:"
            f"({', '.join(v.to_string() for v in vals)})"
            for s, d, r, vals in self.rows)
        return f"INSERT EDGE {self.edge}({', '.join(self.props)}) VALUES {rows}"


@dataclass
class DeleteVerticesSentence(Sentence):
    src: VertexRef
    kind = Kind.DELETE_VERTICES

    def to_string(self) -> str:
        return f"DELETE VERTEX {self.src.to_string()}"


@dataclass
class DeleteEdgesSentence(Sentence):
    edge: str
    keys: List[EdgeKeyRef]
    kind = Kind.DELETE_EDGES

    def to_string(self) -> str:
        return f"DELETE EDGE {self.edge} " + ", ".join(k.to_string() for k in self.keys)


@dataclass
class UpdateItem:
    field_name: str
    value: Expression

    def to_string(self) -> str:
        return f"{self.field_name} = {self.value.to_string()}"


@dataclass
class UpdateVertexSentence(Sentence):
    vid: Expression
    tag: Optional[str]
    items: List[UpdateItem]
    insertable: bool = False       # UPSERT
    when: Optional[WhereClause] = None
    yield_: Optional[YieldClause] = None
    kind = Kind.UPDATE_VERTEX

    def to_string(self) -> str:
        verb = "UPSERT" if self.insertable else "UPDATE"
        s = f"{verb} VERTEX {self.vid.to_string()} SET " + \
            ", ".join(i.to_string() for i in self.items)
        if self.when:
            s += f" WHEN {self.when.filter.to_string()}"
        if self.yield_:
            s += " " + self.yield_.to_string()
        return s


@dataclass
class UpdateEdgeSentence(Sentence):
    src: Expression
    dst: Expression
    rank: int
    edge: str
    items: List[UpdateItem]
    insertable: bool = False
    when: Optional[WhereClause] = None
    yield_: Optional[YieldClause] = None
    kind = Kind.UPDATE_EDGE

    def to_string(self) -> str:
        verb = "UPSERT" if self.insertable else "UPDATE"
        s = (f"{verb} EDGE {self.src.to_string()}->{self.dst.to_string()}"
             f"@{self.rank} OF {self.edge} SET "
             + ", ".join(i.to_string() for i in self.items))
        if self.when:
            s += f" WHEN {self.when.filter.to_string()}"
        if self.yield_:
            s += " " + self.yield_.to_string()
        return s


# ---------------------------------------------------------------------------
# admin sentences (ref: parser/AdminSentences.h, UserSentences.h)
# ---------------------------------------------------------------------------

class ShowKind(enum.Enum):
    SPACES = "SPACES"
    TAGS = "TAGS"
    EDGES = "EDGES"
    HOSTS = "HOSTS"
    PARTS = "PARTS"
    USERS = "USERS"
    ROLES = "ROLES"
    CONFIGS = "CONFIGS"
    VARIABLES = "VARIABLES"
    SNAPSHOTS = "SNAPSHOTS"
    TAG_INDEXES = "TAG INDEXES"
    EDGE_INDEXES = "EDGE INDEXES"
    # consistency observatory (docs/manual/10-observability.md):
    # cluster-wide per-part digest state — "consistency" stays an
    # unreserved identifier (soft keyword, like BALANCE DATA heat)
    CONSISTENCY = "CONSISTENCY"


@dataclass
class ShowSentence(Sentence):
    what: ShowKind
    arg: Optional[str] = None
    kind = Kind.SHOW

    def to_string(self) -> str:
        return f"SHOW {self.what.value}" + (f" {self.arg}" if self.arg else "")


@dataclass
class ShowCreateSentence(Sentence):
    """SHOW CREATE SPACE|TAG|EDGE <name> (ref: ShowSentence with
    ShowType::kShowCreate*, parser/AdminSentences.h)."""
    what: str          # SPACE | TAG | EDGE
    name: str
    kind = Kind.SHOW_CREATE

    def to_string(self) -> str:
        return f"SHOW CREATE {self.what} {self.name}"


@dataclass
class ConfigSentence(Sentence):
    action: str                    # SHOW | GET | SET
    module: Optional[str] = None   # GRAPH | META | STORAGE
    name: Optional[str] = None
    value: Optional[Expression] = None
    kind = Kind.CONFIG

    def to_string(self) -> str:
        # SET parses/prints as the reference's UPDATE CONFIGS form
        s = f"{'UPDATE' if self.action == 'SET' else self.action} CONFIGS"
        if self.module:
            s += f" {self.module}"
        if self.name:
            s += f":{self.name}"
        if self.value is not None:
            s += f" = {self.value.to_string()}"
        return s


@dataclass
class BalanceSentence(Sentence):
    sub: str                       # DATA | LEADER | SHOW | STOP | HEAT
    plan_id: Optional[int] = None
    remove_hosts: List[str] = field(default_factory=list)
    kind = Kind.BALANCE

    def to_string(self) -> str:
        if self.sub == "SHOW":
            return f"BALANCE DATA {self.plan_id}"
        if self.sub == "HEAT":
            return "BALANCE DATA heat"
        s = f"BALANCE {self.sub}"
        if self.remove_hosts:
            s += " REMOVE " + ", ".join(self.remove_hosts)
        return s


@dataclass
class CreateUserSentence(Sentence):
    user: str
    password: str
    if_not_exists: bool = False
    kind = Kind.CREATE_USER

    def to_string(self) -> str:
        return f"CREATE USER {self.user} WITH PASSWORD \"***\""


@dataclass
class DropUserSentence(Sentence):
    user: str
    if_exists: bool = False
    kind = Kind.DROP_USER

    def to_string(self) -> str:
        return f"DROP USER {self.user}"


@dataclass
class ChangePasswordSentence(Sentence):
    user: str
    new_password: str
    old_password: Optional[str] = None
    kind = Kind.CHANGE_PASSWORD

    def to_string(self) -> str:
        return f"CHANGE PASSWORD {self.user}"


@dataclass
class GrantSentence(Sentence):
    role: str                      # GOD/ADMIN/USER/GUEST
    user: str
    space: str
    kind = Kind.GRANT

    def to_string(self) -> str:
        return f"GRANT ROLE {self.role} ON {self.space} TO {self.user}"


@dataclass
class RevokeSentence(Sentence):
    role: str
    user: str
    space: str
    kind = Kind.REVOKE

    def to_string(self) -> str:
        return f"REVOKE ROLE {self.role} ON {self.space} FROM {self.user}"


@dataclass
class IngestSentence(Sentence):
    kind = Kind.INGEST

    def to_string(self) -> str:
        return "INGEST"


@dataclass
class DownloadSentence(Sentence):
    url: str = ""
    kind = Kind.DOWNLOAD

    def to_string(self) -> str:
        return f"DOWNLOAD HDFS \"{self.url}\""


@dataclass
class CreateSnapshotSentence(Sentence):
    kind = Kind.CREATE_SNAPSHOT

    def to_string(self) -> str:
        return "CREATE SNAPSHOT"


@dataclass
class DropSnapshotSentence(Sentence):
    name: str = ""
    kind = Kind.DROP_SNAPSHOT

    def to_string(self) -> str:
        return f"DROP SNAPSHOT {self.name}"
