"""nGQL lexer.

Role parity with the reference's flex scanner (`parser/scanner.lex`,
498 L): case-insensitive keywords, identifiers, int (dec/hex/oct) and
double literals, single/double-quoted strings with escapes, the
`$-` / `$^` / `$$` / `$var` reference sigils, and multi-char operators
(`==`, `!=`, `<=`, `>=`, `&&`, `||`, `->`, `<-`). Hand-written
table-driven scanner instead of generated flex — Python-native, and
fast enough (the parse path is not the hot path; traversal is).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

KEYWORDS = {
    "GO", "STEPS", "STEP", "UPTO", "FROM", "TO", "OVER", "WHERE", "YIELD",
    "AS", "DISTINCT", "REVERSELY", "BIDIRECT", "ALL",
    "FIND", "SHORTEST", "PATH", "NOLOOP",
    "FETCH", "PROP", "ON",
    "USE", "SPACE", "SPACES", "PARTITION_NUM", "REPLICA_FACTOR",
    "CREATE", "DROP", "ALTER", "DESCRIBE", "DESC", "SHOW", "ADD", "CHANGE",
    "IF", "NOT", "EXISTS",
    "TAG", "TAGS", "EDGE", "EDGES", "VERTEX", "VERTICES",
    "INSERT", "VALUES", "DELETE", "UPDATE", "UPSERT", "SET", "WHEN",
    "INT", "INT64", "DOUBLE", "FLOAT", "STRING", "BOOL", "TIMESTAMP", "VID",
    "TTL_DURATION", "TTL_COL", "DEFAULT",
    "ORDER", "BY", "ASC", "LIMIT", "OFFSET", "GROUP",
    "UNION", "INTERSECT", "MINUS",
    "TRUE", "FALSE", "NULL",
    "AND", "OR", "XOR", "CONTAINS", "UUID", "HOSTS", "PARTS", "PART",
    "CONFIGS", "GET", "VARIABLES", "GRAPH", "META", "STORAGE",
    "BALANCE", "DATA", "LEADER", "REMOVE", "PLAN", "STOP",
    "USER", "USERS", "PASSWORD", "CHANGE", "GRANT", "REVOKE", "ROLE",
    "ROLES", "GOD", "ADMIN", "GUEST", "WITH", "IN",
    "INGEST", "DOWNLOAD", "HDFS", "SUBMIT", "JOB", "JOBS",
    "SNAPSHOT", "SNAPSHOTS", "MATCH", "RETURN",
    "LOOKUP", "SUBGRAPH", "INDEX", "INDEXES",
}

# token types
T_EOF = "EOF"
T_ID = "ID"
T_INT = "INT_LIT"
T_DOUBLE = "DOUBLE_LIT"
T_STRING = "STR_LIT"
T_LABEL = "LABEL"  # `backticked`


@dataclass
class Token:
    type: str          # keyword name, symbol, or T_* class
    value: object      # literal value / identifier text
    pos: int           # byte offset in query (for error messages)

    def __repr__(self):
        return f"Token({self.type}, {self.value!r})"


class LexError(Exception):
    def __init__(self, msg: str, pos: int):
        super().__init__(f"{msg} near offset {pos}")
        self.pos = pos


_SYMBOLS2 = {"==", "!=", "<=", ">=", "&&", "||", "->", "<-", "=~", ".."}
_SYMBOLS1 = set("()[]{},;|.$@=<>+-*/%!^:")


def tokenize(text: str) -> List[Token]:
    toks: List[Token] = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c in " \t\r\n":
            i += 1
            continue
        if c == "#" or (c == "/" and i + 1 < n and text[i + 1] == "/"):
            # '#' and '//' line comments, like the reference scanner;
            # '--' is NOT a comment ('1--2' is double negation)
            while i < n and text[i] != "\n":
                i += 1
            continue
        if c == "/" and text[i:i + 2] == "/*":
            j = text.find("*/", i + 2)
            if j < 0:
                raise LexError("unterminated comment", i)
            i = j + 2
            continue
        start = i
        # strings
        if c in "'\"":
            quote = c
            i += 1
            out = []
            while i < n and text[i] != quote:
                if text[i] == "\\" and i + 1 < n:
                    esc = text[i + 1]
                    out.append({"n": "\n", "t": "\t", "r": "\r", "\\": "\\",
                                "'": "'", '"': '"', "0": "\0"}.get(esc, esc))
                    i += 2
                else:
                    out.append(text[i])
                    i += 1
            if i >= n:
                raise LexError("unterminated string", start)
            i += 1
            toks.append(Token(T_STRING, "".join(out), start))
            continue
        # backticked label
        if c == "`":
            j = text.find("`", i + 1)
            if j < 0:
                raise LexError("unterminated label", i)
            toks.append(Token(T_ID, text[i + 1:j], start))
            i = j + 1
            continue
        # numbers
        if c.isdigit() or (c == "." and i + 1 < n and text[i + 1].isdigit()):
            j = i
            is_double = False
            if text[j:j + 2].lower() == "0x":
                j += 2
                while j < n and text[j] in "0123456789abcdefABCDEF":
                    j += 1
                toks.append(Token(T_INT, int(text[i:j], 16), start))
                i = j
                continue
            while j < n and text[j].isdigit():
                j += 1
            if j < n and text[j] == "." and text[j + 1:j + 2] != ".":
                # (but "1..3" is INT .. INT — the MATCH hop-range form)
                if j + 1 < n and text[j + 1].isdigit():
                    is_double = True
                    j += 1
                    while j < n and text[j].isdigit():
                        j += 1
                elif not (j + 1 < n and (text[j + 1].isalpha() or text[j + 1] == "_")):
                    # "1." style double (but not "1.prop")
                    is_double = True
                    j += 1
            if j < n and text[j] in "eE":
                # exponent applies to both 1.5e3 and 1e3 forms
                k = j + 1
                if k < n and text[k] in "+-":
                    k += 1
                if k < n and text[k].isdigit():
                    is_double = True
                    j = k
                    while j < n and text[j].isdigit():
                        j += 1
            if is_double:
                toks.append(Token(T_DOUBLE, float(text[i:j]), start))
            else:
                lit = text[i:j]
                # leading-zero octal like the reference scanner
                val = int(lit, 8) if len(lit) > 1 and lit[0] == "0" else int(lit)
                toks.append(Token(T_INT, val, start))
            i = j
            continue
        # identifiers / keywords
        if c.isalpha() or c == "_":
            j = i
            while j < n and (text[j].isalnum() or text[j] == "_"):
                j += 1
            word = text[i:j]
            up = word.upper()
            if up in KEYWORDS:
                toks.append(Token(up, word, start))
            else:
                toks.append(Token(T_ID, word, start))
            i = j
            continue
        # two-char symbols
        if text[i:i + 2] in _SYMBOLS2:
            toks.append(Token(text[i:i + 2], text[i:i + 2], start))
            i += 2
            continue
        if c in _SYMBOLS1:
            toks.append(Token(c, c, start))
            i += 1
            continue
        raise LexError(f"unexpected character {c!r}", i)
    toks.append(Token(T_EOF, None, n))
    return toks
