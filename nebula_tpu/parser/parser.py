"""nGQL recursive-descent parser.

Role parity with the reference's bison grammar (`parser/parser.yy`,
1802 L; expression precedence ladder at :130-143) and `GQLParser.h`
entry point. Hand-written recursive descent with precedence climbing
instead of generated LALR — same language surface, direct AST
construction, and friendlier error messages.

Statement combinators, lowest to highest binding:
    stmt ';' stmt          SequentialSentences
    $var '=' stmt          AssignmentSentence
    stmt UNION/INTERSECT/MINUS stmt
    stmt '|' stmt          PipedSentence
Expression precedence (low→high): OR/|| < XOR < AND/&& < relational
(==,!=,<,<=,>,>=,CONTAINS) < additive < multiplicative < unary < primary.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from ..filter.expressions import (ArithmeticExpr, DestPropExpr, EdgeDstIdExpr,
                                  EdgePropExpr, EdgeRankExpr, EdgeSrcIdExpr,
                                  EdgeTypeExpr, Expression, FunctionCall,
                                  InputPropExpr, Literal, LogicalExpr,
                                  RelationalExpr, SourcePropExpr, TypeCastExpr,
                                  UnaryExpr, VariablePropExpr)
from . import ast
from .lexer import (T_DOUBLE, T_EOF, T_ID, T_INT, T_STRING, LexError, Token,
                    tokenize)

AGG_FUNS = {"COUNT", "SUM", "AVG", "MAX", "MIN", "STD",
            "BIT_AND", "BIT_OR", "BIT_XOR", "COUNT_DISTINCT", "COLLECT"}

_TYPE_KWS = {"INT", "INT64", "DOUBLE", "FLOAT", "STRING", "BOOL", "TIMESTAMP", "VID"}


class ParseError(Exception):
    def __init__(self, msg: str, tok: Optional[Token] = None):
        loc = f" (near {tok.value!r}, offset {tok.pos})" if tok and tok.value is not None else ""
        super().__init__(f"SyntaxError: {msg}{loc}")


class GQLParser:
    """parse(query) -> ast.SequentialSentences (ref: parser/GQLParser.h)."""

    def parse(self, text: str) -> ast.SequentialSentences:
        try:
            self.toks = tokenize(text)
        except LexError as e:
            raise ParseError(str(e))
        self.i = 0
        # `PROFILE <stmt>`: a statement PREFIX, not a keyword — an
        # identifier named "profile" elsewhere still lexes/parses
        # unchanged (the reference grammar's EXPLAIN/PROFILE seam)
        profile = False
        t0 = self.toks[0]
        if t0.type == T_ID and isinstance(t0.value, str) \
                and t0.value.upper() == "PROFILE" and len(self.toks) > 2:
            profile = True
            self.i = 1
        sentences = []
        while not self._at(T_EOF):
            if self._accept(";"):
                continue
            sentences.append(self._statement())
        if not sentences:
            raise ParseError("empty statement")
        return ast.SequentialSentences(sentences, profile=profile)

    # ------------------------------------------------------------------
    # token helpers
    # ------------------------------------------------------------------
    def _peek(self, k: int = 0) -> Token:
        j = min(self.i + k, len(self.toks) - 1)
        return self.toks[j]

    def _at(self, *types: str) -> bool:
        return self.toks[self.i].type in types

    def _accept(self, *types: str) -> Optional[Token]:
        if self._at(*types):
            t = self.toks[self.i]
            self.i += 1
            return t
        return None

    def _expect(self, *types: str) -> Token:
        if not self._at(*types):
            raise ParseError(f"expected {' or '.join(types)}", self._peek())
        t = self.toks[self.i]
        self.i += 1
        return t

    def _ident(self, what: str = "identifier") -> str:
        # keywords usable as identifiers where unambiguous (like the
        # reference's unreserved-keyword rule)
        t = self._peek()
        if t.type == T_ID:
            self.i += 1
            return t.value
        from .lexer import KEYWORDS
        if t.type in KEYWORDS and isinstance(t.value, str):
            self.i += 1
            return t.value
        raise ParseError(f"expected {what}", t)

    # ------------------------------------------------------------------
    # statements
    # ------------------------------------------------------------------
    def _statement(self) -> ast.Sentence:
        # $var = <set expr>
        if self._at("$") and self._peek(1).type == T_ID and self._peek(2).type == "=":
            self._expect("$")
            var = self._ident()
            self._expect("=")
            return ast.AssignmentSentence(var, self._set_expr())
        return self._set_expr()

    def _set_expr(self) -> ast.Sentence:
        left = self._piped()
        while self._at("UNION", "INTERSECT", "MINUS"):
            t = self._expect("UNION", "INTERSECT", "MINUS")
            if t.type == "UNION":
                # bare UNION implies DISTINCT, matching the reference
                # grammar (parser.yy:1110-1121 setDistinct()); UNION ALL
                # keeps duplicates
                if self._accept("ALL"):
                    op = ast.SetOp.UNION
                else:
                    self._accept("DISTINCT")
                    op = ast.SetOp.UNION_DISTINCT
            else:
                op = ast.SetOp[t.type]
            right = self._piped()
            left = ast.SetSentence(op, left, right)
        return left

    def _piped(self) -> ast.Sentence:
        left = self._simple()
        while self._accept("|"):
            right = self._simple()
            left = ast.PipedSentence(left, right)
        return left

    def _simple(self) -> ast.Sentence:
        t = self._peek()
        tt = t.type
        if tt == "GO":
            return self._go()
        if tt == "FIND":
            return self._find_path()
        if tt == "MATCH":
            return self._match()
        if tt == "LOOKUP":
            return self._lookup()
        if tt == "FETCH":
            return self._fetch()
        if tt == "USE":
            self.i += 1
            return ast.UseSentence(self._ident("space name"))
        if tt == "CREATE":
            return self._create()
        if tt == "DROP":
            return self._drop()
        if tt in ("DESCRIBE", "DESC"):
            return self._describe()
        if tt == "ALTER":
            return self._alter()
        if tt == "INSERT":
            return self._insert()
        if tt == "DELETE":
            return self._delete()
        if tt in ("UPDATE", "UPSERT"):
            return self._update()
        if tt == "YIELD":
            return self._yield_sentence()
        if tt == "ORDER":
            return self._order_by()
        if tt == "LIMIT":
            return self._limit()
        if tt == "GROUP":
            return self._group_by()
        if tt == "SHOW":
            return self._show()
        if tt == "GET":
            if self._peek(1).type == "SUBGRAPH":
                return self._get_subgraph()
            return self._configs_get()
        if tt == "BALANCE":
            return self._balance()
        if tt == "CHANGE":
            return self._change_password()
        if tt == "GRANT":
            return self._grant(revoke=False)
        if tt == "REVOKE":
            return self._grant(revoke=True)
        if tt == "INGEST":
            self.i += 1
            return ast.IngestSentence()
        if tt == "DOWNLOAD":
            self.i += 1
            self._expect("HDFS")
            return ast.DownloadSentence(self._expect(T_STRING).value)
        if tt == "(":
            self.i += 1
            inner = self._set_expr()
            self._expect(")")
            return inner
        raise ParseError("unknown statement", t)

    # --- traversals ---------------------------------------------------
    def _go(self) -> ast.GoSentence:
        self._expect("GO")
        step = ast.StepClause(1)
        if self._at(T_INT):
            n = self._expect(T_INT).value
            self._expect("STEPS", "STEP")
            step = ast.StepClause(n)
        elif self._accept("UPTO"):
            n = self._expect(T_INT).value
            self._expect("STEPS", "STEP")
            step = ast.StepClause(n, upto=True)
        self._expect("FROM")
        from_ = self._vertex_ref()
        over = self._over_clause()
        where = self._opt_where()
        yld = self._opt_yield()
        return ast.GoSentence(step, from_, over, where, yld)

    def _lookup(self) -> ast.LookupSentence:
        self._expect("LOOKUP")
        self._expect("ON")
        name = self._ident("tag or edge name")
        where = self._opt_where()
        yld = self._opt_yield()
        return ast.LookupSentence(name, where, yld)

    def _get_subgraph(self) -> ast.GetSubgraphSentence:
        self._expect("GET")
        self._expect("SUBGRAPH")
        step = ast.StepClause(1)
        if self._at(T_INT):
            n = self._expect(T_INT).value
            self._expect("STEPS", "STEP")
            step = ast.StepClause(n)
        self._expect("FROM")
        from_ = self._vertex_ref()
        # no OVER = every edge type (outbound; REVERSELY/BIDIRECT opt in)
        over = ast.OverClause(is_all=True)
        if self._at("OVER"):
            over = self._over_clause()
        return ast.GetSubgraphSentence(step, from_, over)

    def _match(self) -> ast.MatchSentence:
        # try the supported subset; anything else keeps the reference's
        # grammar-level-stub behavior (parses, executor reports
        # unsupported)
        start = self.i
        try:
            return self._match_structured()
        except ParseError:
            self.i = start
            return ast.MatchSentence(self._swallow_to_stmt_boundary())

    def _match_structured(self) -> ast.MatchSentence:
        start = self.i
        self._expect("MATCH")
        self._expect("(")
        src_alias = self._ident("node alias")
        self._expect(":")
        tag = self._ident("tag name")
        self._expect("{")
        prop = self._ident("property name")
        self._expect(":")
        value = self._expression()
        self._expect("}")
        self._expect(")")
        self._expect("-")
        self._expect("[")
        edge_alias = None
        edge_names: List[str] = []
        min_hops = max_hops = 1
        if self._at(T_ID):
            edge_alias = self._ident()
        if self._accept(":"):
            edge_names.append(self._ident("edge name"))
            while self._accept("|"):
                self._accept(":")       # both [:a|b] and [:a|:b] forms
                edge_names.append(self._ident("edge name"))
        if self._at("*"):
            min_hops, max_hops = self._match_range()
        self._expect("]")
        self._expect("->")
        self._expect("(")
        dst_alias = self._ident() if self._at(T_ID) else None
        self._expect(")")
        self._expect("RETURN")
        cols = [self._yield_column()]
        while self._accept(","):
            cols.append(self._yield_column())
        raw = " ".join(str(t.value) if t.value is not None else t.type
                       for t in self.toks[start:self.i])
        pat = ast.MatchPattern(src_alias, tag, prop, value, edge_alias,
                               edge_names, min_hops, max_hops, dst_alias)
        return ast.MatchSentence(raw, pattern=pat,
                                 return_=ast.YieldClause(cols))

    def _match_range(self) -> Tuple[int, int]:
        self._expect("*")
        lo = self._expect(T_INT).value
        if not self._accept(".."):      # "*k" fixed-length form
            return lo, lo
        hi = self._expect(T_INT).value
        if lo < 1 or hi < lo:
            raise ParseError("bad hop range", self._peek())
        return lo, hi

    def _swallow_to_stmt_boundary(self) -> str:
        """Consume tokens up to the next statement boundary (`;`, `|`,
        EOF), returning the reconstructed raw text — used by the
        grammar-level MATCH/FIND stubs."""
        toks = []
        while self._peek().type not in (";", "|", "EOF"):
            t = self._peek()
            toks.append(str(t.value) if t.value is not None else t.type)
            self.i += 1
        return " ".join(toks)

    def _find_path(self) -> ast.Sentence:
        self._expect("FIND")
        if self._peek().type not in ("SHORTEST", "NOLOOP", "ALL"):
            # plain FIND <props> FROM <label>: grammar-level stub like the
            # reference (FindExecutor: "Does not support") — swallow to
            # the statement boundary
            return ast.FindSentence(
                "FIND " + self._swallow_to_stmt_boundary())
        shortest = noloop = False
        if self._accept("SHORTEST"):
            shortest = True
        elif self._accept("NOLOOP"):
            noloop = True
        else:
            self._expect("ALL")
        self._expect("PATH")
        self._expect("FROM")
        from_ = self._vertex_ref()
        self._expect("TO")
        to = self._vertex_ref()
        over = self._over_clause()
        step = ast.StepClause(5, upto=True)
        if self._accept("UPTO"):
            n = self._expect(T_INT).value
            self._expect("STEPS", "STEP")
            step = ast.StepClause(n, upto=True)
        return ast.FindPathSentence(shortest, from_, to, over, step, noloop)

    def _fetch(self):
        self._expect("FETCH")
        self._expect("PROP")
        self._expect("ON")
        if self._accept("*"):
            name = "*"
        else:
            name = self._ident("tag or edge name")
        # input/variable ref?
        if self._at("$"):
            ref = self._expression()
            if self._at("->"):
                # FETCH PROP ON e $-.src->$-.dst (ref FetchEdgesTest)
                keys = [self._edge_key_tail(ref)]
                while self._accept(","):
                    keys.append(self._edge_key_tail(self._expression()))
                yld = self._opt_yield()
                return ast.FetchEdgesSentence(name, keys, None, yld)
            yld = self._opt_yield()
            # decided tag-vs-edge at execution time; vertices by default,
            # executor re-dispatches if name is an edge
            return ast.FetchVerticesSentence(name, ast.VertexRef(ref=ref), yld)
        first = self._expression()
        if self._at("->"):
            keys = [self._edge_key_tail(first)]
            while self._accept(","):
                keys.append(self._edge_key_tail(self._expression()))
            yld = self._opt_yield()
            return ast.FetchEdgesSentence(name, keys, None, yld)
        vids = [first]
        while self._accept(","):
            vids.append(self._expression())
        yld = self._opt_yield()
        return ast.FetchVerticesSentence(name, ast.VertexRef(vids=vids), yld)

    def _edge_key_tail(self, src: Expression) -> ast.EdgeKeyRef:
        self._expect("->")
        dst = self._expression()
        rank = 0
        if self._accept("@"):
            neg = bool(self._accept("-"))
            rank = self._expect(T_INT).value
            if neg:
                rank = -rank
        return ast.EdgeKeyRef(src, dst, rank)

    def _vertex_ref(self) -> ast.VertexRef:
        if self._at("$"):
            return ast.VertexRef(ref=self._expression())
        vids = [self._expression()]
        while self._accept(","):
            vids.append(self._expression())
        return ast.VertexRef(vids=vids)

    def _over_clause(self) -> ast.OverClause:
        self._expect("OVER")
        if self._accept("*"):
            over = ast.OverClause(is_all=True)
        else:
            edges = [self._over_edge()]
            while self._accept(","):
                edges.append(self._over_edge())
            over = ast.OverClause(edges=edges)
        if self._accept("REVERSELY"):
            over.direction = ast.Direction.IN
        elif self._accept("BIDIRECT"):
            over.direction = ast.Direction.BOTH
        return over

    def _over_edge(self) -> ast.OverEdge:
        name = self._ident("edge name")
        alias = None
        if self._accept("AS"):
            alias = self._ident("alias")
        return ast.OverEdge(name, alias)

    def _opt_where(self) -> Optional[ast.WhereClause]:
        if self._accept("WHERE"):
            return ast.WhereClause(self._expression())
        return None

    def _opt_yield(self) -> Optional[ast.YieldClause]:
        if self._at("YIELD"):
            return self._yield_clause()
        return None

    def _yield_clause(self) -> ast.YieldClause:
        self._expect("YIELD")
        distinct = bool(self._accept("DISTINCT"))
        cols = [self._yield_column()]
        while self._accept(","):
            cols.append(self._yield_column())
        return ast.YieldClause(cols, distinct)

    def _yield_column(self) -> ast.YieldColumn:
        # aggregate call? COUNT(*), SUM(expr), ...
        t = self._peek()
        if t.type == T_ID and t.value.upper() in AGG_FUNS and self._peek(1).type == "(":
            fun = t.value.upper()
            self.i += 2
            if fun == "COUNT" and self._accept("*"):
                inner: Expression = Literal(1)
            elif fun == "COUNT" and self._accept("DISTINCT"):
                fun = "COUNT_DISTINCT"
                inner = self._expression()
            else:
                inner = self._expression()
            self._expect(")")
            alias = self._ident("alias") if self._accept("AS") else None
            return ast.YieldColumn(inner, alias, agg_fun=fun)
        expr = self._expression()
        alias = self._ident("alias") if self._accept("AS") else None
        return ast.YieldColumn(expr, alias)

    def _yield_sentence(self) -> ast.YieldSentence:
        yld = self._yield_clause()
        where = self._opt_where()
        return ast.YieldSentence(yld, where)

    def _order_by(self) -> ast.OrderBySentence:
        self._expect("ORDER")
        self._expect("BY")
        factors = [self._order_factor()]
        while self._accept(","):
            factors.append(self._order_factor())
        return ast.OrderBySentence(factors)

    def _order_factor(self) -> ast.OrderFactor:
        expr = self._expression()
        asc = True
        if self._accept("DESC"):
            asc = False
        else:
            self._accept("ASC")
        return ast.OrderFactor(expr, asc)

    def _limit(self) -> ast.LimitSentence:
        self._expect("LIMIT")
        a = self._expect(T_INT).value
        if self._accept(","):
            b = self._expect(T_INT).value
            return ast.LimitSentence(count=b, offset=a)
        if self._accept("OFFSET"):
            b = self._expect(T_INT).value
            return ast.LimitSentence(count=a, offset=b)
        return ast.LimitSentence(count=a)

    def _group_by(self) -> ast.GroupBySentence:
        self._expect("GROUP")
        self._expect("BY")
        cols = [self._yield_column()]
        while self._accept(","):
            cols.append(self._yield_column())
        yld = self._yield_clause()
        return ast.GroupBySentence(cols, yld)

    # --- DDL ----------------------------------------------------------
    def _if_not_exists(self) -> bool:
        if self._at("IF") and self._peek(1).type == "NOT":
            self.i += 2
            self._expect("EXISTS")
            return True
        return False

    def _if_exists(self) -> bool:
        if self._accept("IF"):
            self._expect("EXISTS")
            return True
        return False

    def _create(self):
        self._expect("CREATE")
        if self._accept("SPACE"):
            ine = self._if_not_exists()
            name = self._ident("space name")
            part_num, replica = 100, 1
            if self._accept("("):
                while not self._accept(")"):
                    opt = self._ident("space option")
                    self._expect("=")
                    val = self._expect(T_INT).value
                    if opt.lower() == "partition_num":
                        part_num = val
                    elif opt.lower() == "replica_factor":
                        replica = val
                    else:
                        raise ParseError(f"unknown space option {opt}")
                    self._accept(",")
            return ast.CreateSpaceSentence(name, part_num, replica, ine)
        if self._at("TAG", "EDGE") and self._peek(1).type == "INDEX":
            is_edge = self._expect("TAG", "EDGE").type == "EDGE"
            self._expect("INDEX")
            ine = self._if_not_exists()
            name = self._ident("index name")
            self._expect("ON")
            schema_name = self._ident("tag or edge name")
            self._expect("(")
            fields = [self._ident("field name")]
            while self._accept(","):
                fields.append(self._ident("field name"))
            self._expect(")")
            return ast.CreateIndexSentence(is_edge, name, schema_name,
                                           fields, ine)
        if self._at("TAG", "EDGE"):
            is_edge = self._expect("TAG", "EDGE").type == "EDGE"
            ine = self._if_not_exists()
            name = self._ident()
            cols: List[ast.ColumnDef] = []
            if self._accept("("):
                while not self._at(")"):
                    cols.append(self._column_def())
                    if not self._accept(","):
                        break
                self._expect(")")
            opts = self._schema_opts()
            return ast.CreateSchemaSentence(is_edge, name, cols, opts, ine)
        if self._accept("USER"):
            ine = self._if_not_exists()
            user = self._ident("user name")
            self._expect("WITH")
            self._expect("PASSWORD")
            pw = self._expect(T_STRING).value
            return ast.CreateUserSentence(user, pw, ine)
        if self._accept("SNAPSHOT"):
            return ast.CreateSnapshotSentence()
        raise ParseError("expected SPACE, TAG, EDGE, USER or SNAPSHOT", self._peek())

    def _column_def(self) -> ast.ColumnDef:
        name = self._ident("column name")
        t = self._expect(*_TYPE_KWS)
        default = None
        if self._accept("DEFAULT"):
            d = self._expression()
            if not isinstance(d, Literal):
                try:
                    from ..filter.expressions import ExpressionContext
                    d = Literal(d.eval(ExpressionContext()))
                except Exception:
                    raise ParseError("DEFAULT value must be a constant")
            default = d.value
        return ast.ColumnDef(name, t.type, default)

    def _schema_opts(self) -> ast.SchemaOpts:
        opts = ast.SchemaOpts()
        while self._at("TTL_DURATION", "TTL_COL"):
            t = self._expect("TTL_DURATION", "TTL_COL")
            self._expect("=")
            if t.type == "TTL_DURATION":
                opts.ttl_duration = self._expect(T_INT).value
            else:
                opts.ttl_col = self._expect(T_STRING, T_ID).value
            self._accept(",")
        return opts

    def _drop(self):
        self._expect("DROP")
        if self._accept("SPACE"):
            ie = self._if_exists()
            return ast.DropSpaceSentence(self._ident(), ie)
        if self._at("TAG", "EDGE") and self._peek(1).type == "INDEX":
            is_edge = self._expect("TAG", "EDGE").type == "EDGE"
            self._expect("INDEX")
            ie = self._if_exists()
            return ast.DropIndexSentence(is_edge, self._ident("index name"), ie)
        if self._at("TAG", "EDGE"):
            is_edge = self._expect("TAG", "EDGE").type == "EDGE"
            ie = self._if_exists()
            return ast.DropSchemaSentence(is_edge, self._ident(), ie)
        if self._accept("USER"):
            ie = self._if_exists()
            return ast.DropUserSentence(self._ident(), ie)
        if self._accept("SNAPSHOT"):
            return ast.DropSnapshotSentence(self._ident())
        raise ParseError("expected SPACE, TAG, EDGE, USER or SNAPSHOT", self._peek())

    def _describe(self):
        self._expect("DESCRIBE", "DESC")
        if self._accept("SPACE"):
            return ast.DescribeSpaceSentence(self._ident())
        is_edge = self._expect("TAG", "EDGE").type == "EDGE"
        return ast.DescribeSchemaSentence(is_edge, self._ident())

    def _alter(self):
        self._expect("ALTER")
        if self._accept("USER"):
            user = self._ident()
            self._expect("WITH")
            self._expect("PASSWORD")
            pw = self._expect(T_STRING).value
            s = ast.ChangePasswordSentence(user, pw)
            s.kind = ast.Kind.ALTER_USER
            return s
        is_edge = self._expect("TAG", "EDGE").type == "EDGE"
        name = self._ident()
        out = ast.AlterSchemaSentence(is_edge, name)
        while self._at("ADD", "CHANGE", "DROP", "TTL_DURATION", "TTL_COL"):
            if self._at("TTL_DURATION", "TTL_COL"):
                out.opts = self._schema_opts()
                continue
            op = self._expect("ADD", "CHANGE", "DROP").type
            self._expect("(")
            if op == "DROP":
                out.drops.append(self._ident())
                while self._accept(","):
                    out.drops.append(self._ident())
            else:
                target = out.adds if op == "ADD" else out.changes
                target.append(self._column_def())
                while self._accept(","):
                    target.append(self._column_def())
            self._expect(")")
            self._accept(",")
        return out

    # --- DML ----------------------------------------------------------
    def _insert(self):
        self._expect("INSERT")
        what = self._expect("VERTEX", "EDGE").type
        if what == "VERTEX":
            tag_items: List[Tuple[str, List[str]]] = []
            while True:
                tag = self._ident("tag name")
                props: List[str] = []
                self._expect("(")
                while not self._at(")"):
                    props.append(self._ident("prop name"))
                    if not self._accept(","):
                        break
                self._expect(")")
                tag_items.append((tag, props))
                if not self._accept(","):
                    break
            self._expect("VALUES")
            rows = []
            while True:
                vid = self._expression()
                self._expect(":")
                self._expect("(")
                vals: List[Expression] = []
                while not self._at(")"):
                    vals.append(self._expression())
                    if not self._accept(","):
                        break
                self._expect(")")
                rows.append((vid, vals))
                if not self._accept(","):
                    break
            return ast.InsertVerticesSentence(tag_items, rows)
        edge = self._ident("edge name")
        props = []
        self._expect("(")
        while not self._at(")"):
            props.append(self._ident("prop name"))
            if not self._accept(","):
                break
        self._expect(")")
        self._expect("VALUES")
        rows = []
        while True:
            src = self._expression()
            self._expect("->")
            dst = self._expression()
            rank = 0
            if self._accept("@"):
                neg = bool(self._accept("-"))
                rank = self._expect(T_INT).value
                if neg:
                    rank = -rank
            self._expect(":")
            self._expect("(")
            vals = []
            while not self._at(")"):
                vals.append(self._expression())
                if not self._accept(","):
                    break
            self._expect(")")
            rows.append((src, dst, rank, vals))
            if not self._accept(","):
                break
        return ast.InsertEdgesSentence(edge, props, rows)

    def _delete(self):
        self._expect("DELETE")
        what = self._expect("VERTEX", "EDGE").type
        if what == "VERTEX":
            return ast.DeleteVerticesSentence(self._vertex_ref())
        edge = self._ident("edge name")
        keys = [self._edge_key_tail(self._expression())]
        while self._accept(","):
            keys.append(self._edge_key_tail(self._expression()))
        return ast.DeleteEdgesSentence(edge, keys)

    def _update(self):
        verb = self._expect("UPDATE", "UPSERT").type
        if verb == "UPDATE" and self._accept("CONFIGS"):
            # UPDATE CONFIGS [module:]name = value (ref parser rule:
            # config_sentence, UPDATE CONFIGS variant)
            module = None
            if self._at("GRAPH", "META", "STORAGE"):
                module = self._expect("GRAPH", "META", "STORAGE").type
                self._accept(":")
            name = self._ident("config name")
            self._expect("=")
            return ast.ConfigSentence("SET", module, name,
                                      self._expression())
        insertable = verb == "UPSERT"
        what = self._expect("VERTEX", "EDGE").type
        if what == "VERTEX":
            vid = self._expression()
            tag = None
            self._expect("SET")
            items = [self._update_item()]
            while self._accept(","):
                items.append(self._update_item())
            when = ast.WhereClause(self._expression()) if self._accept("WHEN") else None
            yld = self._opt_yield()
            return ast.UpdateVertexSentence(vid, tag, items, insertable, when, yld)
        src = self._expression()
        self._expect("->")
        dst = self._expression()
        rank = 0
        if self._accept("@"):
            rank = self._expect(T_INT).value
        # OF edge (lexes as ID "OF")
        t = self._peek()
        if t.type == T_ID and t.value.upper() == "OF":
            self.i += 1
        edge = self._ident("edge name")
        self._expect("SET")
        items = [self._update_item()]
        while self._accept(","):
            items.append(self._update_item())
        when = ast.WhereClause(self._expression()) if self._accept("WHEN") else None
        yld = self._opt_yield()
        return ast.UpdateEdgeSentence(src, dst, rank, edge, items, insertable, when, yld)

    def _update_item(self) -> ast.UpdateItem:
        name = self._ident("field name")
        if self._accept("."):
            name = self._ident("field name")  # tag.field form
        self._expect("=")
        return ast.UpdateItem(name, self._expression())

    # --- admin --------------------------------------------------------
    def _show(self):
        self._expect("SHOW")
        if self._accept("CREATE"):
            # SHOW CREATE SPACE|TAG|EDGE <name> (ref SchemaTest)
            what = self._expect("SPACE", "TAG", "EDGE").type
            return ast.ShowCreateSentence(what, self._ident("name"))
        if self._accept("CONFIGS"):
            module = None
            if self._at("GRAPH", "META", "STORAGE"):
                module = self._expect("GRAPH", "META", "STORAGE").type
            return ast.ConfigSentence("SHOW", module)
        # SHOW CONSISTENCY: cluster-wide digest state (consistency
        # observatory; "consistency" is an unreserved identifier —
        # the BALANCE DATA heat soft-keyword idiom)
        if self._at(T_ID) and self._peek().value.lower() == "consistency":
            self.i += 1
            return ast.ShowSentence(ast.ShowKind.CONSISTENCY)
        if self._at("TAG", "EDGE") and self._peek(1).type == "INDEXES":
            is_edge = self._expect("TAG", "EDGE").type == "EDGE"
            self._expect("INDEXES")
            return ast.ShowSentence(ast.ShowKind.EDGE_INDEXES if is_edge
                                    else ast.ShowKind.TAG_INDEXES)
        t = self._expect("SPACES", "TAGS", "EDGES", "HOSTS", "PARTS", "USERS",
                         "ROLES", "VARIABLES", "SNAPSHOTS")
        arg = None
        if t.type == "ROLES":
            self._expect("IN")
            arg = self._ident("space name")
        if t.type == "PARTS" and self._at(T_INT):
            arg = str(self._expect(T_INT).value)
        return ast.ShowSentence(ast.ShowKind[t.type], arg)

    def _configs_get(self):
        self._expect("GET")
        self._expect("CONFIGS")
        module = None
        if self._at("GRAPH", "META", "STORAGE"):
            module = self._expect("GRAPH", "META", "STORAGE").type
            self._accept(":")
        name = self._ident("config name")
        return ast.ConfigSentence("GET", module, name)

    def _balance(self):
        self._expect("BALANCE")
        if self._accept("LEADER"):
            return ast.BalanceSentence("LEADER")
        if self._accept("PLAN"):
            # BALANCE PLAN [id]: show the (persisted) plan's tasks
            pid = self._expect(T_INT).value if self._at(T_INT) else None
            return ast.BalanceSentence("SHOW", plan_id=pid)
        self._expect("DATA")
        if self._at(T_INT):
            return ast.BalanceSentence("SHOW", plan_id=self._expect(T_INT).value)
        if self._accept("STOP"):
            return ast.BalanceSentence("STOP")
        # BALANCE DATA heat: the heat-aware ADVISORY plan — current vs
        # post-plan modeled per-host heat, nothing moved ("heat" is an
        # unreserved identifier, like the reference's soft keywords)
        if self._at(T_ID) and self._peek().value.lower() == "heat":
            self.i += 1
            return ast.BalanceSentence("HEAT")
        hosts = []
        if self._accept("REMOVE"):
            while True:
                ip = self._expect(T_STRING, T_ID).value
                self._expect(":")
                port = self._expect(T_INT).value
                hosts.append(f"{ip}:{port}")
                if not self._accept(","):
                    break
        return ast.BalanceSentence("DATA", remove_hosts=hosts)

    def _change_password(self):
        self._expect("CHANGE")
        self._expect("PASSWORD")
        user = self._ident("user name")
        self._expect("FROM")
        old = self._expect(T_STRING).value
        self._expect("TO")
        new = self._expect(T_STRING).value
        return ast.ChangePasswordSentence(user, new, old)

    def _grant(self, revoke: bool):
        self._expect("REVOKE" if revoke else "GRANT")
        self._accept("ROLE")
        role = self._expect("GOD", "ADMIN", "USER", "GUEST").type
        self._expect("ON")
        space = self._ident("space name")
        self._expect("FROM" if revoke else "TO")
        user = self._ident("user name")
        if revoke:
            return ast.RevokeSentence(role, user, space)
        return ast.GrantSentence(role, user, space)

    # ------------------------------------------------------------------
    # expressions (precedence climbing)
    # ------------------------------------------------------------------
    def _expression(self) -> Expression:
        return self._or_expr()

    def _or_expr(self) -> Expression:
        left = self._xor_expr()
        while True:
            if self._accept("||") or self._accept("OR"):
                left = LogicalExpr("||", left, self._xor_expr())
            else:
                return left

    def _xor_expr(self) -> Expression:
        left = self._and_expr()
        while self._accept("XOR"):
            left = LogicalExpr("XOR", left, self._and_expr())
        return left

    def _and_expr(self) -> Expression:
        left = self._rel_expr()
        while True:
            if self._accept("&&") or self._accept("AND"):
                left = LogicalExpr("&&", left, self._rel_expr())
            else:
                return left

    _REL_OPS = {"==": "==", "!=": "!=", "<": "<", "<=": "<=", ">": ">", ">=": ">="}

    def _rel_expr(self) -> Expression:
        left = self._add_expr()
        while True:
            t = self._peek()
            if t.type in self._REL_OPS:
                self.i += 1
                left = RelationalExpr(self._REL_OPS[t.type], left, self._add_expr())
            elif t.type == "CONTAINS":
                self.i += 1
                left = RelationalExpr("CONTAINS", left, self._add_expr())
            else:
                return left

    def _add_expr(self) -> Expression:
        left = self._mul_expr()
        while self._at("+", "-"):
            op = self._expect("+", "-").type
            left = ArithmeticExpr(op, left, self._mul_expr())
        return left

    def _mul_expr(self) -> Expression:
        left = self._unary_expr()
        while self._at("*", "/", "%"):
            op = self._expect("*", "/", "%").type
            left = ArithmeticExpr(op, left, self._unary_expr())
        return left

    def _unary_expr(self) -> Expression:
        if self._at("+", "-", "!"):
            op = self._expect("+", "-", "!").type
            operand = self._unary_expr()
            if op == "-" and isinstance(operand, Literal) and \
                    isinstance(operand.value, (int, float)) and not isinstance(operand.value, bool):
                return Literal(-operand.value)
            return UnaryExpr(op, operand)
        if self._accept("NOT"):
            return UnaryExpr("!", self._unary_expr())
        return self._power_expr()

    def _power_expr(self) -> Expression:
        # '^' binds tighter than unary minus and is right-associative
        # (-2^2 == -(2^2), 2^3^2 == 2^(3^2))
        base = self._primary()
        if self._accept("^"):
            return ArithmeticExpr("^", base, self._unary_expr())
        return base

    def _primary(self) -> Expression:
        t = self._peek()
        tt = t.type
        if tt == T_INT or tt == T_DOUBLE or tt == T_STRING:
            self.i += 1
            return Literal(t.value)
        if tt == "TRUE":
            self.i += 1
            return Literal(True)
        if tt == "FALSE":
            self.i += 1
            return Literal(False)
        if tt == "NULL":
            self.i += 1
            return Literal(None)
        if tt == "(":
            # type cast "(int)expr" vs parenthesized expr
            if self._peek(1).type in _TYPE_KWS and self._peek(2).type == ")":
                self.i += 1
                type_tok = self._expect(*_TYPE_KWS)
                self._expect(")")
                tn = {"INT": "int", "INT64": "int", "DOUBLE": "double",
                      "FLOAT": "double", "STRING": "string", "BOOL": "bool",
                      "TIMESTAMP": "int", "VID": "int"}[type_tok.type]
                return TypeCastExpr(tn, self._unary_expr())
            self.i += 1
            e = self._expression()
            self._expect(")")
            return e
        if tt == "$":
            return self._dollar_ref()
        if tt == "UUID":
            self.i += 1
            self._expect("(")
            name = self._expect(T_STRING).value
            self._expect(")")
            return FunctionCall("uuid", [Literal(name)])
        if tt == T_ID:
            # function call / edge.prop / bare prop
            if self._peek(1).type == "(":
                name = t.value
                self.i += 2
                args: List[Expression] = []
                while not self._at(")"):
                    args.append(self._expression())
                    if not self._accept(","):
                        break
                self._expect(")")
                return FunctionCall(name, args)
            if self._peek(1).type == ".":
                edge = t.value
                self.i += 2
                prop = self._ident("property name")
                return _edge_prop(edge, prop)
            self.i += 1
            return _edge_prop(None, t.value)
        raise ParseError("expected expression", t)

    def _dollar_ref(self) -> Expression:
        self._expect("$")
        if self._accept("-"):
            self._expect(".")
            if self._accept("*"):
                return InputPropExpr("*")   # YIELD $-.* expansion
            return InputPropExpr(self._ident("input column"))
        if self._accept("^"):
            self._expect(".")
            tag = self._ident("tag name")
            self._expect(".")
            return SourcePropExpr(tag, self._ident("property name"))
        if self._accept("$"):
            self._expect(".")
            tag = self._ident("tag name")
            self._expect(".")
            return DestPropExpr(tag, self._ident("property name"))
        var = self._ident("variable name")
        self._expect(".")
        if self._accept("*"):
            return VariablePropExpr(var, "*")   # YIELD $var.*
        return VariablePropExpr(var, self._ident("column name"))


def _edge_prop(edge: Optional[str], prop: str) -> Expression:
    special = {"_src": EdgeSrcIdExpr, "_dst": EdgeDstIdExpr,
               "_rank": EdgeRankExpr, "_type": EdgeTypeExpr}
    if prop in special:
        return special[prop](edge)
    return EdgePropExpr(edge, prop)
