from .transport import RpcClient, RpcError, RpcServer, proxy
from . import wire

__all__ = ["RpcClient", "RpcError", "RpcServer", "proxy", "wire"]
