"""Framed TCP RPC: server hosting named services + pooled clients.

Role parity with the reference's fbthrift cpp2 stack: one server per
daemon hosts its service handlers (ref: the three daemons' thrift
setup, daemons/*.cpp), clients keep pooled connections per (host,
port) like `ThriftClientManager` (ref common/thrift/ThriftClientManager
.h). Frames are u32-length-prefixed wire.py payloads:

    request  = (service: str, method: str, args: tuple, kwargs: dict
                [, (trace_id, span_id) [, cost_flag]])
    response = (True, result[, spans[, ledger]]) | (False, exc string)

The optional 5th request element is the Dapper-style propagated trace
context (common/tracing.py): a traced caller stamps it on the
envelope, the server adopts it around the handler (child spans open
around processor + KV work) and returns the recorded spans as the
response's 3rd element, which the client grafts into its live trace —
graphd joins the full graphd->storaged span tree with zero cost on
untraced calls (the envelope stays a 4-tuple).

The optional 6th request element (v1.2, additive — docs/manual/
6-wire-protocol.md) is the cost flag: a caller with an active query
LEDGER (common/ledger.py) sets it truthy; the server then adopts a
fresh server-side ledger around the handler (rows scanned, row bytes,
WAL appends charge into it) and piggybacks it back as the response's
4th element, which the client merges into the live query ledger under
this peer's host key — per-host cost attribution with, again, zero
cost for callers carrying neither context.

Remote exceptions re-raise client-side as RpcError. The server is a
thread-per-connection loop (daemons are IO-bound python; the heavy
compute lives in XLA/native code which releases the GIL).
"""
from __future__ import annotations

import queue
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Dict, Optional, Tuple

from ..common import ledger
from ..common.faults import (InjectedConnectionFault, faults,
                             jittered_delay, pace_retry)
from ..common.stats import stats as global_stats
from ..common.tracing import tracer
from . import wire

_U32 = struct.Struct("<I")
MAX_FRAME = 1 << 30

# reconnect counters, observable in tests and /get_stats
# (rpc.reconnects): every retry of a freshly-failed connection is
# counted, and the retry loop backs off instead of hammering a
# refused/reset peer (capped, jittered exponential)
rpc_stats = {"reconnects": 0}
_rpc_stats_lock = threading.Lock()


class RpcError(Exception):
    pass


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return bytes(buf)


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_U32.pack(len(payload)) + payload)


def _recv_frame(sock: socket.socket) -> bytes:
    (n,) = _U32.unpack(_read_exact(sock, 4))
    if n > MAX_FRAME:
        raise RpcError(f"frame too large ({n})")
    return _read_exact(sock, n)


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

class RpcServer:
    """Hosts named service objects; any public method is callable."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._services: Dict[str, Any] = {}
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._stopping = False
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                sock = self.request
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                with outer._conns_lock:
                    if outer._stopping:
                        # accepted in the shutdown window: go silent
                        try:
                            sock.close()
                        except OSError:
                            pass
                        return
                    outer._conns.add(sock)
                try:
                    while True:
                        raw = _recv_frame(sock)
                        _send_frame(sock, outer._dispatch(raw))
                except (ConnectionError, OSError):
                    pass
                finally:
                    with outer._conns_lock:
                        outer._conns.discard(sock)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        self.addr = f"{self.host}:{self.port}"
        self._thread: Optional[threading.Thread] = None

    def register(self, name: str, service: Any) -> "RpcServer":
        self._services[name] = service
        return self

    def _dispatch(self, raw: bytes) -> bytes:
        try:
            envelope = wire.decode(raw)
            service_name, method, args, kwargs = envelope[:4]
            tctx = envelope[4] if len(envelope) > 4 else None
            want_cost = bool(envelope[5]) if len(envelope) > 5 else False
            svc = self._services.get(service_name)
            if svc is None:
                raise RpcError(f"no service {service_name!r}")
            if method.startswith("_"):
                raise RpcError(f"method {method!r} not callable")
            fn = getattr(svc, method, None)
            if fn is None or not callable(fn):
                raise RpcError(f"{service_name}.{method} not found")
            if tctx is None and not want_cost:
                return wire.encode((True, fn(*args, **kwargs)))
            # propagated trace context: adopt it around the handler so
            # processor/KV spans record under the caller's trace, and
            # hand the recorded fragment back in the response. The
            # cost flag likewise adopts a server-side ledger whose
            # charges piggyback back as the 4th response element.
            rt = None if tctx is None else tracer.remote(
                f"{service_name}.{method}", tctx[0], tctx[1])
            la = ledger.adopt() if want_cost else None
            if rt is not None and la is not None:
                with rt, la:
                    result = fn(*args, **kwargs)
            elif la is not None:
                with la:
                    result = fn(*args, **kwargs)
            else:
                with rt:
                    result = fn(*args, **kwargs)
            spans = rt.wire_spans if rt is not None else []
            if la is not None:
                return wire.encode((True, result, spans, la.wire))
            return wire.encode((True, result, spans))
        except Exception as e:  # noqa: BLE001 — errors cross the wire
            try:
                return wire.encode((False, f"{type(e).__name__}: {e}"))
            except Exception:
                return wire.encode((False, "unserializable server error"))

    def start(self) -> "RpcServer":
        with self._conns_lock:   # atomic vs stop(): no serve-after-close
            if self._stopping:
                return self   # stopped before serving (e.g. wrong_cluster)
            # nlint: disable=NL002 -- server-lifetime accept loop;
            # per-request traces are adopted in _handle via tracer.remote
            self._thread = threading.Thread(
                target=self._server.serve_forever,
                name=f"rpc-{self.port}", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        with self._conns_lock:
            if self._stopping:
                return              # idempotent — callers may race
            self._stopping = True   # handlers mid-accept close themselves
        if self._thread is not None:
            self._server.shutdown()
        self._server.server_close()
        # kill established connections too — a stopped daemon must go
        # silent (peers would otherwise keep talking to handler threads
        # whose services are already stopped)
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

class _ConnPool:
    """Pooled sockets to one address (ThriftClientManager's role).

    Timeouts are per-acquire, not per-pool: raft clients (1.5s
    election-scale deadlines) and bulk movers (30s) share one pool per
    peer without one silently inheriting the other's deadline."""

    def __init__(self, host: str, port: int, size: int = 4):
        self.host, self.port = host, port
        self._free: "queue.Queue[socket.socket]" = queue.Queue(maxsize=size)
        self._size = size
        self._created = 0
        self._lock = threading.Lock()

    def _connect(self, timeout: float) -> socket.socket:
        sock = socket.create_connection((self.host, self.port),
                                        timeout=timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def acquire(self, timeout: float) -> socket.socket:
        try:
            return self._free.get_nowait()
        except queue.Empty:
            pass
        with self._lock:
            if self._created < self._size:
                self._created += 1
                try:
                    return self._connect(timeout)
                except Exception:
                    self._created -= 1
                    raise
        return self._free.get(timeout=timeout)

    def release(self, sock: Optional[socket.socket]) -> None:
        if sock is None:  # connection died — allow a replacement
            with self._lock:
                self._created -= 1
            return
        try:
            self._free.put_nowait(sock)
        except queue.Full:
            sock.close()
            with self._lock:
                self._created -= 1

    def close(self) -> None:
        # each drained socket frees its creation slot: a reused client
        # (disconnect -> connect) must be able to dial fresh sockets —
        # leaving _created at size made the next acquire block the
        # full timeout and raise "no pooled connection"
        while True:
            try:
                sock = self._free.get_nowait()
            except queue.Empty:
                return
            sock.close()
            with self._lock:
                self._created -= 1


class RpcClient:
    """Calls methods on a named service at addr ("host:port")."""

    _pools: Dict[Tuple[str, int], _ConnPool] = {}
    _pools_lock = threading.Lock()

    def __init__(self, addr: str, service: str,
                 timeout: Optional[float] = None,
                 max_attempts: Optional[int] = None,
                 dedicated: bool = False,
                 src: Optional[str] = None):
        """`dedicated` gives THIS client its own private connection
        instead of the process-wide shared per-address pool. The shared
        pool (4 sockets) is right for internal control-plane fan-out
        (meta, storage admin, raft) where many short calls multiplex —
        but end-user graph clients are session-oriented and must scale
        with the number of clients, like the reference's one-socket
        GraphClient (client/cpp/GraphClient.cpp): N in-process sessions
        sharing 4 sockets capped measured query concurrency at 4
        regardless of session count.

        `src` declares the CALLER's service address for the network
        nemesis (common/faults.py): directional link rules
        (`peer=src>dst`) match against it. Callers with no service
        identity (graph clients, admin tools) leave it None and match
        only `*>dst` rules."""
        host, port_s = addr.rsplit(":", 1)
        self._key = (host, int(port_s))
        self.addr = addr
        self.service = service
        self._timeout = timeout if timeout is not None else 30.0
        self._dedicated = dedicated
        if dedicated:
            self._pool = _ConnPool(host, int(port_s), size=1)
        else:
            with RpcClient._pools_lock:
                if self._key not in RpcClient._pools:
                    RpcClient._pools[self._key] = _ConnPool(host,
                                                            int(port_s))
            self._pool = RpcClient._pools[self._key]
        # low-latency callers (raft) cap the stale-socket drain so a
        # black-holed peer costs ~1 timeout, not pool_size timeouts
        self._max_attempts = max_attempts
        self._src = src

    def close(self) -> None:
        """Release this client's private socket (dedicated clients
        own their connection — the reference GraphClient closes on
        disconnect). Shared pools are process-wide and stay up."""
        if self._dedicated:
            self._pool.close()

    # instant-failure (refused/reset) reconnect pacing: capped,
    # jittered exponential backoff so a dead peer is probed, not
    # hammered (a refused connect returns in microseconds — the old
    # loop burned its attempts instantly)
    RETRY_BACKOFF_BASE = 0.02
    RETRY_BACKOFF_CAP = 0.5

    def _reconnect_backoff(self, paced: int) -> None:
        # pace_retry, not time.sleep: a hot-lock serve-path section
        # (engine refresh) suppresses retry sleeps in its context —
        # sleeping here would hold that lock for the backoff duration
        pace_retry(jittered_delay(self.RETRY_BACKOFF_BASE,
                                  self.RETRY_BACKOFF_CAP, paced))

    def call(self, method: str, *args, **kwargs) -> Any:
        # rpc.call_us native histogram: every call (traced or not)
        # feeds the round-trip distribution; exemplars ride only the
        # traced ones (docs/manual/10-observability.md). One finally
        # for both branches — recorded after the rpc.call span closes,
        # still inside the trace's dynamic extent.
        t0 = time.perf_counter()
        try:
            tctx = tracer.current_ctx()
            costed = ledger.current() is not None
            if tctx is None:
                if not costed:
                    payload = wire.encode((self.service, method,
                                           tuple(args), kwargs))
                else:
                    # ledger without trace (sampling off): the cost
                    # flag still rides — per-host attribution must not
                    # depend on the sampling decision
                    payload = wire.encode((self.service, method,
                                           tuple(args), kwargs,
                                           None, 1))
                return self._call_framed(payload)
            # traced call: one rpc.call span covering every attempt (a
            # retry that finally succeeds still joins the remote
            # fragment under this span — the round-trip survives
            # reconnects)
            with tracer.span("rpc.call", service=self.service,
                             method=method, peer=self.addr):
                if costed:
                    payload = wire.encode((self.service, method,
                                           tuple(args), kwargs,
                                           tracer.current_ctx(), 1))
                else:
                    payload = wire.encode((self.service, method,
                                           tuple(args), kwargs,
                                           tracer.current_ctx()))
                return self._call_framed(payload)
        finally:
            global_stats.add_value(
                "rpc.call_us", (time.perf_counter() - t0) * 1e6,
                kind="histogram")

    def _budget(self) -> float:
        """Effective deadline for the next transport wait: the
        client's per-call timeout clamped to the query's remaining
        deadline budget (qos.set_query_deadline ContextVar) — a
        blackholed peer must never hold a caller past the deadline the
        admission layer promised. Raises RpcError once the budget is
        already exhausted (balks are counted, never silent)."""
        from ..common import qos
        rem = qos.deadline_remaining_s()
        if rem is None:
            return self._timeout
        if rem <= 0:
            global_stats.add_value("rpc.deadline_balk", kind="counter")
            raise RpcError(f"rpc to {self.addr}: query deadline "
                           f"exhausted before transport wait")
        return min(self._timeout, rem)

    def _note_peer_timeout(self) -> None:
        """A wait on this peer burned its full budget: count it and
        feed the flight recorder's `partition_suspected` trigger (a
        storm of these across peers is the partition signature)."""
        global_stats.add_value("rpc.peer_timeout", kind="counter")
        from ..common.flight import recorder
        recorder.record("peer_timeout", peer=self.addr,
                        service=self.service)

    def _nemesis_exchange(self, sock: socket.socket, payload: bytes,
                          acts: Dict[str, Any], budget: float) -> bytes:
        """Execute an armed nemesis action on this call (common/
        faults.py NETWORK NEMESIS): latency first, then at most one of
        drop / hang / dup. Each surfaces through the exact code path
        the genuine network failure would take."""
        lat = acts.get("latency_s")
        if lat:
            time.sleep(min(lat, budget))
        if acts.get("drop"):
            # frame loss: ConnectionError subclass — the reconnect /
            # drain retry machinery engages as for a reset socket
            raise InjectedConnectionFault(
                f"nemesis dropped frame to {self.addr}")
        if acts.get("hang"):
            # blackhole (accept-then-hang, the gray-failure shape):
            # the request is never sent; the caller waits on a reply
            # that never comes and burns its budget via socket.timeout
            return _recv_frame(sock)
        _send_frame(sock, payload)
        if acts.get("dup"):
            # duplicate delivery: the peer genuinely executes the
            # frame twice; the duplicate's response is drained so the
            # framed stream stays aligned
            _send_frame(sock, payload)
            raw = _recv_frame(sock)
            _recv_frame(sock)
            return raw
        return _recv_frame(sock)

    def _call_framed(self, payload: bytes) -> Any:
        last_err: Optional[Exception] = None
        fresh_fail = False
        paced = 0
        # after a server restart every pooled socket may be stale; allow
        # draining the whole pool plus one fresh connect
        attempts = self._max_attempts or (self._pool._size + 1)
        for attempt in range(attempts):
            if last_err is not None:
                with _rpc_stats_lock:
                    rpc_stats["reconnects"] += 1
                global_stats.add_value("rpc.reconnects", kind="counter")
                # pace only FRESH-connect failures (dead peer): a
                # stale pooled socket from a restarted-but-alive peer
                # drains instantly, like before. The final attempt's
                # failure raises below without sleeping.
                if fresh_fail:
                    self._reconnect_backoff(paced)
                    paced += 1
            # recomputed per attempt: retries shrink the remaining
            # query budget, so later attempts wait less, never more
            budget = self._budget()
            try:
                sock = self._pool.acquire(budget)
            except socket.timeout as e:
                # SYN-dropped peer: the connect already consumed the
                # caller's full budget — don't multiply it by retrying
                self._note_peer_timeout()
                raise RpcError(f"rpc to {self.addr} connect timed out "
                               f"({budget:.3g}s): {e}") from e
            except queue.Empty as e:
                raise RpcError(f"rpc to {self.addr}: no pooled connection "
                               f"within {budget:.3g}s") from e
            except OSError as e:
                last_err = e   # instant failures (refused etc.): retry
                fresh_fail = True
                continue
            sock.settimeout(budget)  # deadline is per-call + clamped
            try:
                # transport-shaped fault point: raises a ConnectionError
                # subclass, so the production retry/backoff machinery
                # engages exactly as for a genuinely broken socket
                faults.fire("rpc.send")
                acts = faults.link_actions(self._src, self.addr)
                if acts is None:
                    _send_frame(sock, payload)
                    raw = _recv_frame(sock)
                else:
                    raw = self._nemesis_exchange(sock, payload, acts,
                                                 budget)
            except socket.timeout as e:
                # a live-but-unresponsive (black-holed) peer: retrying
                # another pooled socket would multiply the deadline —
                # fail within the caller's budget instead
                sock.close()
                self._pool.release(None)
                self._note_peer_timeout()
                raise RpcError(f"rpc to {self.addr} timed out "
                               f"({budget:.3g}s): {e}") from e
            except (ConnectionError, OSError) as e:
                sock.close()
                self._pool.release(None)
                last_err = e
                fresh_fail = False   # stale pooled socket: drain fast
                continue
            self._pool.release(sock)
            resp = wire.decode(raw)
            ok, value = resp[0], resp[1]
            if not ok:
                raise RpcError(value)
            led = ledger.current()
            if led is not None:
                led.charge(rpc_calls=1, rpc_bytes_out=len(payload),
                           rpc_bytes_in=len(raw))
                if len(resp) > 3 and resp[3]:
                    # server-side cost fragment: merge under the peer's
                    # host key (per-host rows_scanned/bytes attribution)
                    led.merge_wire(resp[3], host=self.addr)
            if len(resp) > 2 and resp[2]:
                # remote span fragment: join it into the live trace
                tracer.graft(resp[2])
            return value
        raise RpcError(f"rpc to {self.addr} failed: {last_err}")

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return lambda *args, **kwargs: self.call(name, *args, **kwargs)


def proxy(addr: str, service: str, timeout: Optional[float] = None,
          max_attempts: Optional[int] = None,
          dedicated: bool = False,
          src: Optional[str] = None) -> RpcClient:
    """A client whose attribute calls mirror the remote service's
    methods — drop-in for the in-proc service objects that
    StorageClient/MetaClient hold per host. `timeout` is this client's
    per-call deadline (connect + send + recv), independent of any other
    client sharing the address's connection pool. `dedicated` opts out
    of the shared pool (see RpcClient); `src` declares the caller's
    address for directional nemesis link rules (see RpcClient)."""
    return RpcClient(addr, service, timeout=timeout,
                     max_attempts=max_attempts, dedicated=dedicated,
                     src=src)
