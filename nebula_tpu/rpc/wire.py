"""Tagged binary wire codec for inter-daemon RPC.

Role parity with the reference's thrift binary protocol (ref
src/interface/*.thrift defines the structs; fbthrift serializes them):
our request/response structs are plain dataclasses, so one generic
tagged encoder covers every service. Dataclasses and IntEnums cross the
wire by REGISTERED name — both sides import the same modules, and
unknown tags fail loudly instead of executing anything (no pickle).

Encoding (little-endian):
    N   None          T/F  bool            i  zigzag varint int
    d   f64           s  u32 len + utf8    b  u32 len + bytes
    l   u32 count + items                  t  tuple (as l, decoded tuple)
    m   u32 count + key/value pairs        e  enum: u32 reg-id + varint
    c   dataclass: u32 reg-id + field values in declared order
"""
from __future__ import annotations

import dataclasses
import enum
import struct
from typing import Any, Dict, List, Tuple, Type

_U32 = struct.Struct("<I")
_F64 = struct.Struct("<d")


class WireError(Exception):
    pass


_registry: List[type] = []
_reg_ids: Dict[type, int] = {}


def register(*types: type) -> None:
    for t in types:
        if t not in _reg_ids:
            _reg_ids[t] = len(_registry)
            _registry.append(t)


def _register_defaults() -> None:
    from ..common.status import ErrorCode, Status, StatusOr
    from ..codec.schema import PropType, Schema, SchemaField
    from ..graph.context import ExecutionResponse
    from ..kvstore.raftex import types as rt
    from ..meta.service import HostInfo, SpaceDesc
    from ..storage import types as st
    register(ErrorCode, Status, StatusOr, PropType, SchemaField, Schema,
             ExecutionResponse, SpaceDesc, HostInfo,
             st.PartResult, st.EdgeData, st.VertexData, st.BoundRequest,
             st.BoundResponse, st.PropsResponse, st.ExecResponse,
             st.NewVertex, st.NewEdge, st.EdgeKey, st.UpdateItemReq,
             st.UpdateResponse, st.StatDef, st.StatsResponse,
             # raft consensus messages (the reference's raftex.thrift)
             rt.RaftCode, rt.LogType, rt.LogRecord,
             rt.AskForVoteRequest, rt.AskForVoteResponse,
             rt.AppendLogRequest, rt.AppendLogResponse,
             rt.SendSnapshotRequest, rt.SendSnapshotResponse,
             # NEW types append at the END: registry ids are positional
             # and must stay stable across versions (wire compat)
             st.ScanPartResponse,
             # storaged-tier device serving (storage/device_serve.py)
             st.DeviceWindowRequest, st.DevicePartResult,
             st.DeviceWindowResponse,
             # LOOKUP index scans (storage/processors.py lookup_scan)
             st.LookupRequest, st.LookupRow, st.LookupResponse)


def _zigzag(n: int) -> int:
    return (n << 1) if n >= 0 else ((-n) << 1) - 1


def _unzigzag(z: int) -> int:
    return (z >> 1) if (z & 1) == 0 else -((z + 1) >> 1)


def _write_varint(out: bytearray, n: int) -> None:
    z = _zigzag(n)
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_varint(buf: bytes, off: int) -> Tuple[int, int]:
    shift = 0
    z = 0
    while True:
        b = buf[off]
        off += 1
        z |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return _unzigzag(z), off


def encode(obj: Any) -> bytes:
    if not _registry:
        _register_defaults()
    out = bytearray()
    _enc(out, obj)
    return bytes(out)


# per-class field-name tuples: dataclasses.fields() rebuilds its
# tuple on every call, which dominates encode/decode of bulk
# responses (thousands of EdgeData per device_window partial)
_fields_cache: Dict[type, Tuple[str, ...]] = {}


def _dc_fields(cls: type) -> Tuple[str, ...]:
    names = _fields_cache.get(cls)
    if names is None:
        names = tuple(f.name for f in dataclasses.fields(cls))
        _fields_cache[cls] = names
    return names


def _enc(out: bytearray, o: Any) -> None:
    if o is None:
        out.append(ord("N"))
    elif o is True:
        out.append(ord("T"))
    elif o is False:
        out.append(ord("F"))
    elif isinstance(o, enum.IntEnum):
        rid = _reg_ids.get(type(o))
        if rid is None:
            raise WireError(f"unregistered enum {type(o).__name__}")
        out.append(ord("e"))
        out += _U32.pack(rid)
        _write_varint(out, int(o))
    elif isinstance(o, int):
        out.append(ord("i"))
        _write_varint(out, o)
    elif isinstance(o, float):
        out.append(ord("d"))
        out += _F64.pack(o)
    elif isinstance(o, str):
        raw = o.encode("utf-8")
        out.append(ord("s"))
        out += _U32.pack(len(raw))
        out += raw
    elif isinstance(o, (bytes, bytearray, memoryview)):
        raw = bytes(o)
        out.append(ord("b"))
        out += _U32.pack(len(raw))
        out += raw
    elif isinstance(o, (list, set)):
        out.append(ord("l"))
        out += _U32.pack(len(o))
        for x in o:
            _enc(out, x)
    elif isinstance(o, tuple):
        out.append(ord("t"))
        out += _U32.pack(len(o))
        for x in o:
            _enc(out, x)
    elif isinstance(o, dict):
        out.append(ord("m"))
        out += _U32.pack(len(o))
        for k, v in o.items():
            _enc(out, k)
            _enc(out, v)
    elif dataclasses.is_dataclass(o) and not isinstance(o, type):
        rid = _reg_ids.get(type(o))
        if rid is None:
            raise WireError(f"unregistered dataclass {type(o).__name__}")
        out.append(ord("c"))
        out += _U32.pack(rid)
        for name in _dc_fields(type(o)):
            _enc(out, getattr(o, name))
    elif type(o).__name__ in ("Status", "StatusOr"):
        # Status/StatusOr are plain classes, not dataclasses
        rid = _reg_ids.get(type(o))
        if rid is None:
            raise WireError(f"unregistered {type(o).__name__}")
        out.append(ord("c"))
        out += _U32.pack(rid)
        if type(o).__name__ == "Status":
            _enc(out, o.code)
            _enc(out, o.msg)
        else:
            _enc(out, o.status)
            _enc(out, o._value)
    else:
        raise WireError(f"cannot encode {type(o).__name__}")


def decode(raw: bytes) -> Any:
    if not _registry:
        _register_defaults()
    v, off = _dec(raw, 0)
    if off != len(raw):
        raise WireError(f"trailing {len(raw)-off} bytes")
    return v


def _dec(buf: bytes, off: int) -> Tuple[Any, int]:
    tag = buf[off]
    off += 1
    if tag == ord("N"):
        return None, off
    if tag == ord("T"):
        return True, off
    if tag == ord("F"):
        return False, off
    if tag == ord("i"):
        return _read_varint(buf, off)
    if tag == ord("d"):
        return _F64.unpack_from(buf, off)[0], off + 8
    if tag == ord("s"):
        (n,) = _U32.unpack_from(buf, off)
        off += 4
        return buf[off:off + n].decode("utf-8"), off + n
    if tag == ord("b"):
        (n,) = _U32.unpack_from(buf, off)
        off += 4
        return buf[off:off + n], off + n
    if tag in (ord("l"), ord("t")):
        (n,) = _U32.unpack_from(buf, off)
        off += 4
        items = []
        for _ in range(n):
            v, off = _dec(buf, off)
            items.append(v)
        return (tuple(items) if tag == ord("t") else items), off
    if tag == ord("m"):
        (n,) = _U32.unpack_from(buf, off)
        off += 4
        d = {}
        for _ in range(n):
            k, off = _dec(buf, off)
            v, off = _dec(buf, off)
            d[k] = v
        return d, off
    if tag == ord("e"):
        (rid,) = _U32.unpack_from(buf, off)
        off += 4
        v, off = _read_varint(buf, off)
        return _registry[rid](v), off
    if tag == ord("c"):
        (rid,) = _U32.unpack_from(buf, off)
        off += 4
        cls = _registry[rid]
        if cls.__name__ == "Status":
            code, off = _dec(buf, off)
            msg, off = _dec(buf, off)
            from ..common.status import Status
            return Status(code, msg), off
        if cls.__name__ == "StatusOr":
            status, off = _dec(buf, off)
            value, off = _dec(buf, off)
            from ..common.status import StatusOr
            return StatusOr(status, value), off
        vals = []
        for _ in _dc_fields(cls):
            v, off = _dec(buf, off)
            vals.append(v)
        return cls(*vals), off
    raise WireError(f"bad tag {tag!r} at {off-1}")
