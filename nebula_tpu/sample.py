"""The basketballplayer/NBA sample dataset + loader.

Parity model: the reference's TraverseTestBase NBA dataset
(graph/test/TestBase.h + the docs' basketballplayer sample). Lives in
the package (not tests/) because the driver entry point and the
console demo load it too.
"""

from .cluster import InProcCluster

PLAYERS = [
    (100, "Tim Duncan", 42),
    (101, "Tony Parker", 36),
    (102, "LaMarcus Aldridge", 33),
    (103, "Rudy Gay", 32),
    (104, "Marco Belinelli", 32),
    (105, "Danny Green", 31),
    (106, "Kyle Anderson", 25),
    (107, "Aron Baynes", 32),
    (108, "Boris Diaw", 36),
    (109, "Tiago Splitter", 34),
    (110, "Cory Joseph", 27),
    (121, "Useless", 60),
]

TEAMS = [
    (200, "Warriors"),
    (201, "Nuggets"),
    (202, "Rockets"),
    (203, "Trail Blazers"),
    (204, "Spurs"),
    (205, "Thunders"),
]

# src, dst, likeness
LIKES = [
    (100, 101, 95.0),
    (100, 102, 90.0),
    (101, 100, 95.0),
    (101, 102, 91.0),
    (102, 100, 75.0),
    (103, 104, 85.0),
    (104, 105, 85.0),
    (105, 106, 90.0),
    (106, 100, 90.0),
    (107, 100, 80.0),
    (108, 101, 80.0),
    (109, 100, 80.0),
    (110, 106, 70.0),
]

# player, team, start_year, end_year
SERVES = [
    (100, 204, 1997, 2016),
    (101, 204, 1999, 2018),
    (102, 203, 2006, 2015),
    (102, 204, 2015, 2019),
    (103, 204, 2013, 2017),
    (104, 204, 2015, 2019),
    (105, 204, 2010, 2018),
    (106, 204, 2014, 2018),
    (107, 204, 2013, 2019),
    (108, 204, 2012, 2016),
    (109, 204, 2010, 2017),
    (110, 204, 2011, 2015),
]


def load_nba(cluster=None, space="nba", parts=4):
    """Create the space + schema and load the sample. -> (cluster, conn)."""
    cluster = cluster or InProcCluster()
    conn = cluster.connect()
    conn.must(f"CREATE SPACE {space}(partition_num={parts}, replica_factor=1)")
    conn.must(f"USE {space}")
    conn.must("CREATE TAG player(name string, age int)")
    conn.must("CREATE TAG team(name string)")
    conn.must("CREATE EDGE like(likeness double)")
    conn.must("CREATE EDGE serve(start_year int, end_year int)")

    rows = ", ".join(f'{vid}:("{name}", {age})' for vid, name, age in PLAYERS)
    conn.must(f"INSERT VERTEX player(name, age) VALUES {rows}")
    rows = ", ".join(f'{vid}:("{name}")' for vid, name in TEAMS)
    conn.must(f"INSERT VERTEX team(name) VALUES {rows}")
    rows = ", ".join(f"{s} -> {d}:({w})" for s, d, w in LIKES)
    conn.must(f"INSERT EDGE like(likeness) VALUES {rows}")
    rows = ", ".join(f"{s} -> {d}:({a}, {b})" for s, d, a, b in SERVES)
    conn.must(f"INSERT EDGE serve(start_year, end_year) VALUES {rows}")
    return cluster, conn
