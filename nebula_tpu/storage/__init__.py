from .types import (VertexData, EdgeData, NewVertex, NewEdge, EdgeKey,  # noqa: F401
                    BoundRequest, BoundResponse, PartResult, StatDef,
                    StatsResponse, UpdateItemReq)
from .processors import StorageService  # noqa: F401
from .client import StorageClient  # noqa: F401
