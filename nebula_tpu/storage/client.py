"""StorageClient: partition routing + scatter/gather.

Role parity with the reference's `storage/client/StorageClient.{cpp,inl}`:
the client (living inside the query engine) maps each vertex id to its
partition (`vid % num_parts + 1`, ref StorageClient.cpp:10-11), groups
work per partition per leader host, fans one request out per host, and
gathers per-part results with leader-cache fixups on E_LEADER_CHANGED
(ref StorageClient.inl:73-160, 119-134).

In a single-process deployment every partition routes to the local
StorageService; in multi-process the `hosts` map routes to RPC proxies
exposing the same method surface (rpc/storage_proxy).
"""
from __future__ import annotations

import contextvars
import threading
import time
from collections import deque
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor
from concurrent.futures import wait as futures_wait
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..common import keys as ku
from ..common import writepath as _writepath
from ..common.stats import stats
from ..common.status import ErrorCode, Status, StatusOr
from ..common.tracing import tracer
from ..meta.schema_manager import SchemaManager
from .types import (BoundRequest, BoundResponse, DevicePartResult,
                    DeviceWindowRequest, DeviceWindowResponse, EdgeData,
                    EdgeKey, ExecResponse, LookupRequest, LookupResponse,
                    NewEdge, NewVertex, PartResult,
                    PropsResponse, StatDef, StatsResponse, UpdateItemReq,
                    UpdateResponse, VertexData)


class PeerHealth:
    """Per-peer health scoring for the DATA fan-out (the CircuitBreaker
    idiom applied per peer — ISSUE 18; docs/manual/12-replication.md
    "Partitions & gray failure"). Two independent ejection signals:

    - CONSECUTIVE transport failures (`EJECT_AFTER` timeouts/errors in
      a row) — the blackholed/dead-peer shape;
    - EWMA latency OUTLIER (smoothed latency above `OUTLIER_FACTOR` x
      the cross-peer median, past an absolute floor) — the gray
      slow-but-alive shape that ruins p99 without ever erroring.

    An ejected peer leaves the data-routing candidate set until a
    background half-open probe answers HEALTHY-FAST (under the same
    outlier bar that ejected it; exponential backoff between probes)
    or its ejection window lapses and live traffic finds it fast. A
    slow-but-successful answer never re-admits — that is the gray
    shape itself — it widens the half-open window instead. The
    cross-peer recent-latency window also derives the hedge delay
    (p95) for hedged reads.

    SCOPE (ISSUE 18 satellite): only StorageClient DATA fan-out
    consults this — raft election/heartbeat/replication traffic
    (kvstore/raftex) never does, so an ejected gray storaged still
    votes, still heartbeats, and still catches up."""

    ALPHA = 0.2               # EWMA smoothing
    EJECT_AFTER = 3           # consecutive transport failures
    OUTLIER_FACTOR = 4.0      # x cross-peer median EWMA
    OUTLIER_MIN_MS = 50.0     # never eject under this absolute latency
    MIN_SAMPLES = 8
    BASE_BACKOFF_S = 1.0
    MAX_BACKOFF_S = 30.0
    HEDGE_FLOOR_S = 0.010
    HEDGE_CAP_S = 0.5
    HEDGE_DEFAULT_S = 0.05    # until the p95 window has samples

    def __init__(self, probe: Optional[Callable[[str], bool]] = None):
        self._lock = threading.Lock()
        self._peers: Dict[str, Dict[str, Any]] = {}
        self._recent: deque = deque(maxlen=256)   # cross-peer ms
        self._probe = probe
        self._closed = False
        self.counts = {"ejected": 0, "recovered": 0, "probes": 0}

    def _rec(self, host: str) -> Dict[str, Any]:
        rec = self._peers.get(host)
        if rec is None:
            rec = self._peers[host] = {
                "ewma_ms": None, "samples": 0, "consec": 0,
                "ejected": False, "until": 0.0, "probing": False,
                "backoff": self.BASE_BACKOFF_S,
                "ejections": 0, "straggles": 0}
        return rec

    # -------------------------------------------------- observations
    def observe(self, host: str, ms: float) -> None:
        ejected_now = False
        with self._lock:
            rec = self._rec(host)
            rec["consec"] = 0
            prev = rec["ewma_ms"]
            rec["ewma_ms"] = ms if prev is None \
                else prev + self.ALPHA * (ms - prev)
            rec["samples"] += 1
            self._recent.append(ms)
            if rec["ejected"]:
                # traffic reached an ejected peer (half-open window /
                # pre-ejection race / a response already in flight at
                # ejection time). Recover ONLY on a healthy-fast
                # answer — a slow-but-successful one is exactly the
                # gray shape that got it ejected, and re-admitting on
                # it makes the ejection flap (eject -> stale in-flight
                # response lands -> recover -> re-eject ...).
                if ms <= self._healthy_ms_locked(host):
                    self._recover_locked(rec)
                else:
                    # still gray: widen the half-open window
                    rec["backoff"] = min(rec["backoff"] * 2,
                                         self.MAX_BACKOFF_S)
                    rec["until"] = time.monotonic() + rec["backoff"]
            elif rec["samples"] >= self.MIN_SAMPLES:
                others = [r["ewma_ms"] for h, r in self._peers.items()
                          if h != host and r["ewma_ms"] is not None]
                if others and rec["ewma_ms"] > \
                        self._healthy_ms_locked(host):
                    ejected_now = self._eject_locked(rec)
        if ejected_now:
            self._on_ejected(host)

    def observe_failure(self, host: str) -> None:
        ejected_now = False
        with self._lock:
            rec = self._rec(host)
            rec["consec"] += 1
            if rec["ejected"]:
                # failure in the half-open window: double the backoff
                rec["backoff"] = min(rec["backoff"] * 2,
                                     self.MAX_BACKOFF_S)
                rec["until"] = time.monotonic() + rec["backoff"]
            elif rec["consec"] >= self.EJECT_AFTER:
                ejected_now = self._eject_locked(rec)
        if ejected_now:
            self._on_ejected(host)

    def straggled(self, host: str) -> None:
        """A hedge beat this peer's in-flight response (evidence of
        grayness that never became an error)."""
        with self._lock:
            self._rec(host)["straggles"] += 1

    def _healthy_ms_locked(self, host: str) -> float:
        """Latency bar for `host` to count as healthy: OUTLIER_FACTOR x
        the cross-peer median EWMA, floored at OUTLIER_MIN_MS. The same
        bar ejects (EWMA above it) and re-admits (answer below it)."""
        others = sorted(r["ewma_ms"] for h, r in self._peers.items()
                        if h != host and r["ewma_ms"] is not None)
        if not others:
            return self.OUTLIER_MIN_MS
        med = others[len(others) // 2]
        return max(self.OUTLIER_FACTOR * med, self.OUTLIER_MIN_MS)

    # ------------------------------------------- ejection lifecycle
    def _eject_locked(self, rec: Dict[str, Any]) -> bool:
        rec["ejected"] = True
        rec["ejections"] += 1
        rec["until"] = time.monotonic() + rec["backoff"]
        self.counts["ejected"] += 1
        return True

    def _recover_locked(self, rec: Dict[str, Any]) -> None:
        rec["ejected"] = False
        rec["consec"] = 0
        rec["backoff"] = self.BASE_BACKOFF_S
        rec["until"] = 0.0
        self.counts["recovered"] += 1

    def _on_ejected(self, host: str) -> None:
        from ..common.flight import recorder as _flight
        stats.add_value("storage_client.peer_ejected", kind="counter")
        _flight.record("peer_ejected", peer=host)
        if self._probe is None:
            return
        with self._lock:
            rec = self._rec(host)
            if rec["probing"]:
                return
            rec["probing"] = True
        # nlint: disable=NL002 -- ejection-lifetime half-open prober;
        # exits as soon as the peer recovers (or the client closes)
        threading.Thread(target=self._probe_loop, args=(host,),
                         name=f"peer-probe-{host}", daemon=True).start()

    def _probe_loop(self, host: str) -> None:
        try:
            while not self._closed:
                with self._lock:
                    rec = self._peers.get(host)
                    if rec is None or not rec["ejected"]:
                        return
                    delay = rec["backoff"]
                time.sleep(delay)
                if self._closed:
                    return
                with self._lock:
                    self.counts["probes"] += 1
                t0 = time.monotonic()
                try:
                    ok = bool(self._probe(host))
                except Exception:
                    ok = False
                probe_ms = (time.monotonic() - t0) * 1e3
                with self._lock:
                    rec = self._peers.get(host)
                    if rec is None or not rec["ejected"]:
                        return
                    # a slow-but-successful probe is still gray: only
                    # a healthy-fast answer closes the half-open state
                    if ok and probe_ms <= self._healthy_ms_locked(host):
                        self._recover_locked(rec)
                        return
                    rec["backoff"] = min(rec["backoff"] * 2,
                                         self.MAX_BACKOFF_S)
                    rec["until"] = time.monotonic() + rec["backoff"]
        finally:
            with self._lock:
                rec = self._peers.get(host)
                if rec is not None:
                    rec["probing"] = False

    # ------------------------------------------------------ queries
    def ejected(self, host: str) -> bool:
        """Should data routing skip this peer right now? An elapsed
        ejection window reads healthy (half-open: live traffic probes
        it; a failure re-ejects with doubled backoff)."""
        rec = self._peers.get(host)
        if rec is None or not rec["ejected"]:
            return False
        return time.monotonic() < rec["until"]

    def hedge_delay_s(self) -> float:
        """p95 of the cross-peer recent-latency window, clamped — the
        wait before a straggler's parts are re-issued elsewhere."""
        with self._lock:
            if len(self._recent) < self.MIN_SAMPLES:
                return self.HEDGE_DEFAULT_S
            xs = sorted(self._recent)
            p95 = xs[min(len(xs) - 1, int(len(xs) * 0.95))]
        return min(max(p95 / 1e3, self.HEDGE_FLOOR_S),
                   self.HEDGE_CAP_S)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            peers = {h: {"ewma_ms": (None if r["ewma_ms"] is None
                                     else round(r["ewma_ms"], 3)),
                         "samples": r["samples"],
                         "consec_failures": r["consec"],
                         "ejected": r["ejected"],
                         "ejections": r["ejections"],
                         "straggles": r["straggles"]}
                     for h, r in self._peers.items()}
            out: Dict[str, Any] = dict(self.counts)
        out["peers"] = peers
        return out

    def close(self) -> None:
        self._closed = True


class StorageClient:
    def __init__(self, sm: SchemaManager,
                 hosts: Optional[Dict[str, Any]] = None,
                 part_to_host: Optional[Callable[[int, int], str]] = None,
                 local_service=None,
                 refresh_hosts: Optional[Callable[[], None]] = None):
        """hosts: host -> service (in-proc handler or RPC proxy).
        part_to_host: (space_id, part_id) -> host name (leader lookup).
        local_service: shorthand for single-node deployments.
        refresh_hosts: called before admin fan-outs so hosts that joined
        after boot are included (re-populates the hosts mapping)."""
        self.sm = sm
        self._refresh_hosts = refresh_hosts
        if local_service is not None:
            self._hosts = {"local": local_service}
            self._part_to_host = lambda s, p: "local"
        else:
            self._hosts = hosts or {}
            self._part_to_host = part_to_host or (lambda s, p: next(iter(self._hosts)))
        self._leader_cache: Dict[Tuple[int, int], str] = {}
        self._pool = ThreadPoolExecutor(max_workers=8,
                                        thread_name_prefix="storage-client")
        # version-watch cache: host -> {space_id: write_version}, fed by
        # one long-poll thread per host (zero per-query version RPCs)
        self._vlock = threading.Lock()
        self._vcache: Dict[str, Dict[int, int]] = {}
        self._vfresh: Dict[str, bool] = {}
        self._vwatchers: Dict[str, threading.Thread] = {}
        self._local_write_seq: Dict[int, int] = {}
        self._closed = False
        self.version_stats = {"probe_rpcs": 0, "watch_rounds": 0}
        # _kv_retry retries by classification (leader hint followed /
        # hintless election wait / part-not-yet-materialized), also fed
        # to the global stats manager as storage_client.kv_retry.<cls>
        self.retry_stats = {"leader_moved": 0, "hintless": 0,
                            "no_part": 0}
        # sibling leader-cache invalidations: entries dropped because
        # another part's E_LEADER_CHANGED deposed their cached host
        # (one election moves a whole leadership signature, not one
        # part — invalidating siblings saves a redirect round-trip per
        # part)
        self.sibling_invalidations = 0
        # device_window scatter/gather counters (engine_tpu/cluster.py
        # reads these for /tpu_stats + CLUSTER_bench)
        self.device_stats = {"windows": 0, "parts_requested": 0,
                             "parts_served": 0, "follower_parts": 0,
                             "leader_retries": 0, "refused_parts": 0,
                             "max_staleness_ms": 0.0}
        # partition & gray-failure tolerance (ISSUE 18): per-peer
        # health scoring for the data fan-out, and the hedged-read
        # token bucket — hedges draw tokens refilled at HEDGE_RATE per
        # part-request, so hedging can never add more than that
        # fraction of extra cluster load (let alone double it)
        self.peer_health = PeerHealth(probe=self._probe_peer)
        self.hedge_stats = {"issued": 0, "won": 0, "capped": 0}
        self._hedge_lock = threading.Lock()
        self._hedge_tokens = self.HEDGE_BURST

    # ------------------------------------------------------------------
    # routing
    # ------------------------------------------------------------------
    def part_id(self, space_id: int, vid: int) -> int:
        n = self.sm.num_parts(space_id)
        return ku.part_id(vid, n)

    def _leader(self, space_id: int, part: int) -> str:
        return self._leader_cache.get((space_id, part)) \
            or self._part_to_host(space_id, part)

    def _note_leader(self, space_id: int, part: int, leader: Optional[str]):
        if leader:
            self._leader_cache[(space_id, part)] = leader

    def cluster_ids_to_parts(self, space_id: int,
                             vids: List[int]) -> Dict[int, List[int]]:
        # resolve the part count ONCE: num_parts checks the meta
        # catalog version per access (an RPC round-trip) — per-vid
        # resolution turns a big frontier into a meta hot loop
        n = self.sm.num_parts(space_id)
        out: Dict[int, List[int]] = {}
        for vid in vids:
            out.setdefault(ku.part_id(vid, n), []).append(vid)
        return out

    def _group_by_host(self, space_id: int,
                       parts: Dict[int, Any]) -> Dict[str, Dict[int, Any]]:
        by_host: Dict[str, Dict[int, Any]] = {}
        for part, payload in parts.items():
            by_host.setdefault(self._leader(space_id, part), {})[part] = payload
        return by_host

    def _submit(self, fn, *args):
        """Pool submit that carries the caller's trace AND ledger
        contexts into the worker thread (ContextVars don't cross
        ThreadPoolExecutor on their own) — the per-host RPC spans land
        in the query's trace, and the per-host cost fragments merge
        into the query's ledger. Callers carrying neither pay
        nothing."""
        from ..common import ledger
        if tracer.active() or ledger.current() is not None:
            return self._pool.submit(
                contextvars.copy_context().run, fn, *args)
        return self._pool.submit(fn, *args)

    def _timed_call(self, host: str, call, *args):
        """Per-host call wrapper feeding the peer-health scorer: wall
        latency on success, a failure mark on any transport-level
        exception (response-level error codes are NOT peer failures —
        a follower refusing a stale read is healthy)."""
        t0 = time.perf_counter()
        try:
            r = call(*args)
        except Exception:
            self.peer_health.observe_failure(host)
            raise
        self.peer_health.observe(host, (time.perf_counter() - t0) * 1e3)
        return r

    def _next_healthy(self, hosts_list: List[str], prev: str) -> str:
        """Hintless-rotation target: the next host after `prev`,
        skipping health-ejected peers — unless EVERY candidate is
        ejected, in which case plain rotation (something must be
        tried; total ejection is indistinguishable from a local
        network problem)."""
        idx = hosts_list.index(prev) if prev in hosts_list else 0
        for step in range(1, len(hosts_list) + 1):
            cand = hosts_list[(idx + step) % len(hosts_list)]
            if not self.peer_health.ejected(cand):
                return cand
        return hosts_list[(idx + 1) % len(hosts_list)]

    def _probe_peer(self, host: str) -> bool:
        """Half-open health probe for an ejected peer: one cheap
        version RPC on a fail-fast client (the _watch_host twin idiom
        — the shared proxy's paced reconnect backoff would slow the
        verdict down). Success proves the peer answers again."""
        svc = self._hosts.get(host)
        if svc is None or self._closed:
            return False
        from ..rpc.transport import RpcClient, proxy
        if isinstance(svc, RpcClient):
            svc = proxy(svc.addr, svc.service, timeout=1.0,
                        max_attempts=1)
        svc.watch_space_versions({}, timeout=0.05)
        return True

    # hedged-read budget: tokens refill per part-request, hedges spend
    # them — sustained hedge volume is capped at HEDGE_RATE x request
    # load with HEDGE_BURST headroom for latency spikes
    HEDGE_RATE = 0.5
    HEDGE_BURST = 64.0

    def _hedge_refill(self, parts_requested: int) -> None:
        with self._hedge_lock:
            self._hedge_tokens = min(
                self.HEDGE_BURST,
                self._hedge_tokens + self.HEDGE_RATE * parts_requested)

    def _hedge_budget(self, want: int) -> int:
        with self._hedge_lock:
            n = min(want, int(self._hedge_tokens))
            if n > 0:
                self._hedge_tokens -= n
        return n

    def _fanout(self, space_id: int, parts: Dict[int, Any], call, empty_resp,
                merge, max_retries: int = 5) -> Any:
        """Scatter per leader host, gather with leader-cache fixups and
        redirect retries (ref: collectResponse + StorageClient.inl:119-134
        leader-cache update on E_LEADER_CHANGED). Hintless rounds (an
        election in flight, a dead host) back off with bounded jitter —
        the retry budget must outlast one raft election, so a replica
        kill mid-soak surfaces as latency, never as a client error."""
        resp = empty_resp
        pending = parts
        for attempt in range(max_retries + 1):
            by_host = self._group_by_host(space_id, pending)
            tried = {part: host for host, hp in by_host.items() for part in hp}
            futures = []
            for host, host_parts in by_host.items():
                svc = self._hosts[host]
                futures.append((host_parts,
                                self._submit(self._timed_call, host,
                                             call, svc, host_parts)))
            round_resp = empty_resp.__class__()
            dead_parts: list = []
            for host_parts, fut in futures:
                try:
                    merge(round_resp, fut.result())
                except Exception:
                    # dead/unreachable host: treat its parts like a
                    # hintless leader change (failover to another
                    # replica; the reference's client rotates the same
                    # way when a storaged dies mid-request)
                    dead_parts.extend(host_parts)
            merge(resp, round_resp)
            # parts that hit a stale leader: update cache and retry them;
            # with no leader hint (election in progress / dead host),
            # rotate to the next host
            pending = {}
            hosts_list = list(self._hosts)
            saw_hintless = False
            saw_no_part = False
            redirected: list = []
            space_known = None  # one catalog probe per round, lazily
            for part in dead_parts:
                if part not in parts:
                    continue
                saw_hintless = True
                prev = tried.get(part, hosts_list[0])
                self._leader_cache[(space_id, part)] = \
                    self._next_healthy(hosts_list, prev)
                pending[part] = parts[part]
            deposed_hosts: set = set()
            for part, result in round_resp.results.items():
                if result.code == ErrorCode.E_LEADER_CHANGED and part in parts:
                    redirected.append(part)
                    deposed_hosts.add(tried.get(part))
                    if result.leader:
                        self._note_leader(space_id, part, result.leader)
                    else:
                        saw_hintless = True
                        prev = tried.get(part, hosts_list[0])
                        self._leader_cache[(space_id, part)] = \
                            self._next_healthy(hosts_list, prev)
                    pending[part] = parts[part]
                elif result.code in (ErrorCode.E_PART_NOT_FOUND,
                                     ErrorCode.E_SPACE_NOT_FOUND) \
                        and part in parts:
                    # freshly created space: the storaged topology watch
                    # hasn't materialized the part yet (the reference's
                    # load_data_interval_secs window) — wait and retry;
                    # a space the catalog doesn't know fails fast
                    if space_known is None:
                        space_known = self._space_exists(space_id)
                    if space_known:
                        saw_no_part = True
                        # the part may have MOVED (balance): drop the
                        # cached leader so routing re-consults the meta
                        # allocation
                        self._leader_cache.pop((space_id, part), None)
                        pending[part] = parts[part]
            if redirected:
                # sibling invalidation: one election moves a whole
                # leadership signature (every part that host led), not
                # just the part that happened to error — drop every
                # cached entry still pointing at a deposed host so the
                # NEXT query re-consults routing instead of paying one
                # redirect round-trip per sibling part
                deposed_hosts.discard(None)
                if deposed_hosts:
                    for key, cached in list(self._leader_cache.items()):
                        if key[0] == space_id and cached in deposed_hosts \
                                and key[1] not in pending:
                            del self._leader_cache[key]
                            self.sibling_invalidations += 1
                            stats.add_value(
                                "storage_client.sibling_invalidations",
                                kind="counter")
                # a leader moved under this query — visible in its trace
                # (the cluster-observability satellite: elections and
                # rebalances tag the traces they touched)
                tracer.tag_root("leader_changed",
                                f"s{space_id}:" + ",".join(
                                    f"p{p}" for p in sorted(redirected)))
            if not pending:
                break
            from ..common.faults import jittered_delay
            from ..common.qos import deadline_remaining_s
            # deadline budget (ISSUE 8 satellite; docs/manual/14-qos
            # .md): the retry loop must not outlive the query's own
            # tpu_query_deadline_ms — a stalled election otherwise
            # burns up to ~1.5s of hintless backoff past the deadline
            # the client was promised. Out of budget -> the pending
            # parts balk to a typed E_TIMEOUT (deadline_exceeded),
            # tagged on the trace root and counted; with budget left,
            # the sleep is clamped to what remains.
            rem = deadline_remaining_s()
            if rem is not None and rem <= 0:
                stats.add_value("storage_client.fanout_deadline_balk",
                                kind="counter")
                from ..common.flight import recorder as _flight
                _flight.record("deadline_balk", where="storage_fanout")
                tracer.tag_root("degraded", "deadline:storage_fanout")
                for part in pending:
                    # overwrite the round's retryable verdict (e.g.
                    # E_LEADER_CHANGED): the query is out of budget,
                    # and deadline_exceeded is the truthful terminal
                    # classification
                    resp.results[part] = PartResult(
                        ErrorCode.E_TIMEOUT, None)
                pending = {}
                break
            left = attempt < max_retries
            if saw_no_part:
                self._count_fanout_retry("no_part", left)
                if self._refresh_hosts is not None:
                    self._refresh_hosts()
                time.sleep(0.2 if rem is None else min(0.2, rem))
            elif saw_hintless:
                # election in progress / dead host: bounded expo jitter
                # (same policy as _kv_retry) — the cumulative budget
                # spans an election instead of burning retries in 150ms
                self._count_fanout_retry("hintless", left)
                if left:
                    d = jittered_delay(*self.KV_BACKOFF["hintless"],
                                       attempt)
                    time.sleep(d if rem is None else min(d, rem))
            else:
                self._count_fanout_retry("leader_moved", left)
                if left:
                    d = jittered_delay(
                        *self.KV_BACKOFF["leader_moved"], attempt)
                    time.sleep(d if rem is None else min(d, rem))
        # parts still unreachable after every retry must surface as
        # errors — a missing entry would read as success to executors
        for part in pending:
            resp.results.setdefault(
                part, PartResult(ErrorCode.E_HOST_NOT_FOUND, None))
        return resp

    # ------------------------------------------------------------------
    # reads
    # ------------------------------------------------------------------
    def get_neighbors(self, space_id: int, vids: List[int],
                      edge_types: List[int],
                      vertex_props: Optional[Dict[int, List[str]]] = None,
                      edge_props: Optional[List[str]] = None,
                      filter_bytes: Optional[bytes] = None,
                      max_edges_per_vertex: Optional[int] = None) -> BoundResponse:
        parts = self.cluster_ids_to_parts(space_id, vids)

        def call(svc, host_parts):
            return svc.get_bound(BoundRequest(
                space_id=space_id, parts=host_parts, edge_types=edge_types,
                vertex_props=vertex_props or {}, edge_props=edge_props,
                filter=filter_bytes,
                max_edges_per_vertex=max_edges_per_vertex))

        def merge(acc: BoundResponse, part_resp: BoundResponse):
            acc.results.update(part_resp.results)
            acc.vertices.extend(part_resp.vertices)
            acc.latency_us = max(acc.latency_us, part_resp.latency_us)

        return self._fanout(space_id, parts, call, BoundResponse(), merge)

    def lookup_scan(self, space_id: int, is_edge: bool, schema_id: int,
                    filter_bytes: Optional[bytes] = None) -> LookupResponse:
        """LOOKUP's CPU scan: fan the whole part range out (no vid
        routing — every part owns candidate rows) and gather matches."""
        parts = {p: True for p in range(1, self.sm.num_parts(space_id) + 1)}

        def call(svc, host_parts):
            return svc.lookup_scan(LookupRequest(
                space_id=space_id, parts=host_parts, is_edge=is_edge,
                schema_id=schema_id, filter=filter_bytes))

        def merge(acc: LookupResponse, part_resp: LookupResponse):
            acc.results.update(part_resp.results)
            acc.rows.extend(part_resp.rows)
            acc.latency_us = max(acc.latency_us, part_resp.latency_us)

        return self._fanout(space_id, parts, call, LookupResponse(), merge)

    def device_window(self, space_id: int, vids: List[int],
                      edge_types: List[int],
                      edge_props: Optional[List[str]] = None,
                      max_edges_per_vertex: Optional[int] = None,
                      allow_follower: bool = False,
                      follower_max_ms: int = 0) -> DeviceWindowResponse:
        """Scatter one hop of a GO window to per-host DEVICE partials
        (storaged-tier device shards, storage/device_serve.py) and
        gather BoundResponse-shaped vertices + per-part serve verdicts.

        Routing: with follower reads armed, parts spread
        deterministically across every HEALTHY host (a follower that
        passes the raft read fence serves its replica's shard — the
        capacity double; health-ejected peers leave the candidate
        set); otherwise parts route to their cached leader. Refused
        parts (fence rejected, shard stale, wrong host) get ONE leader
        retry; parts still refused come back refused — the caller
        falls back to the row-scan path per part, never whole-window.

        Hedging (ISSUE 18): spread rounds are hedged — after a
        p95-derived delay, a straggler host's unresolved parts are
        re-issued to another replica (the part's leader where it isn't
        the straggler itself, else the next healthy host), first
        response wins per part. Hedges draw from the token bucket
        (`_hedge_budget`) so they can never double cluster load, and
        wins mark the straggler in the health scorer. The abandoned
        straggler future resolves (or times out) in its pool thread
        and only feeds health stats — the window never waits on it."""
        parts = self.cluster_ids_to_parts(space_id, vids)
        self.device_stats["windows"] += 1
        self.device_stats["parts_requested"] += len(parts)
        self._hedge_refill(len(parts))
        hosts_list = sorted(self._hosts)
        resp = DeviceWindowResponse()
        num_parts = self.sm.num_parts(space_id)

        def call(svc, host_parts, af):
            return svc.device_window(DeviceWindowRequest(
                space_id=space_id, parts=host_parts,
                edge_types=edge_types, edge_props=edge_props,
                max_edges_per_vertex=max_edges_per_vertex,
                allow_follower=af, follower_max_ms=follower_max_ms))

        def hedge_target(part: int, straggler: str) -> Optional[str]:
            # another replica for the straggler's part: prefer the
            # cached leader (it can always serve), else the next
            # healthy host in rotation
            ldr = self._leader(space_id, part)
            if ldr != straggler and not self.peer_health.ejected(ldr):
                return ldr
            for h in hosts_list:
                if h != straggler and h != ldr \
                        and not self.peer_health.ejected(h):
                    return h
            return None

        def run_round(assignment: Dict[int, str], af: bool,
                      hedged: bool = False) -> None:
            by_host: Dict[str, Dict[int, List[int]]] = {}
            for part, host in assignment.items():
                by_host.setdefault(host, {})[part] = parts[part]
            futs: Dict[Any, Tuple[str, Dict[int, List[int]], bool]] = {}
            for host, hp in by_host.items():
                svc = self._hosts.get(host)
                if svc is None:
                    for p in hp:
                        resp.results[p] = DevicePartResult(
                            code=ErrorCode.E_HOST_NOT_FOUND)
                    continue
                futs[self._submit(self._timed_call, host, call,
                                  svc, hp, af)] = (host, hp, False)
            if not futs:
                return
            round_res: Dict[int, DevicePartResult] = {}

            def absorb(fut) -> None:
                host, hp, is_hedge = futs[fut]
                try:
                    r = fut.result()
                except Exception:
                    for p in hp:
                        round_res.setdefault(p, DevicePartResult(
                            code=ErrorCode.E_HOST_NOT_FOUND))
                    return
                accepted = set()
                for p, pr in r.results.items():
                    prev = round_res.get(p)
                    # first response wins per part; a later SUCCESS
                    # still replaces an earlier failure verdict
                    if prev is not None and (
                            prev.code == ErrorCode.SUCCEEDED
                            or pr.code != ErrorCode.SUCCEEDED):
                        continue
                    round_res[p] = pr
                    accepted.add(p)
                    if is_hedge and pr.code == ErrorCode.SUCCEEDED:
                        self.hedge_stats["won"] += 1
                        stats.add_value("storage_client.hedge.won",
                                        kind="counter")
                        straggler = assignment.get(p)
                        if straggler:
                            self.peer_health.straggled(straggler)
                # vertices ride only for parts whose verdict THIS
                # response supplied — a straggler's late duplicate
                # must not double-count rows
                if accepted:
                    if len(accepted) == len(r.results):
                        resp.vertices.extend(r.vertices)
                    else:
                        resp.vertices.extend(
                            v for v in r.vertices
                            if ku.part_id(v.vid, num_parts) in accepted)
                resp.latency_us = max(resp.latency_us, r.latency_us)

            pending = set(futs)
            if hedged and len(hosts_list) > 1:
                done, pending = futures_wait(
                    pending, timeout=self.peer_health.hedge_delay_s())
                for f in done:
                    absorb(f)
                if pending:
                    # stragglers: re-issue their unresolved parts to
                    # another replica, budget permitting
                    want: List[Tuple[int, str]] = []
                    for f in pending:
                        host, hp, _ = futs[f]
                        for p in hp:
                            pr = round_res.get(p)
                            if pr is not None \
                                    and pr.code == ErrorCode.SUCCEEDED:
                                continue
                            alt = hedge_target(p, host)
                            if alt is not None:
                                want.append((p, alt))
                    granted = self._hedge_budget(len(want))
                    if granted < len(want):
                        capped = len(want) - granted
                        self.hedge_stats["capped"] += capped
                        stats.add_value("storage_client.hedge.capped",
                                        kind="counter")
                    hedge_by_host: Dict[str, Dict[int, List[int]]] = {}
                    for p, alt in want[:granted]:
                        hedge_by_host.setdefault(alt, {})[p] = parts[p]
                    for alt, hp in hedge_by_host.items():
                        svc = self._hosts.get(alt)
                        if svc is None:
                            continue
                        fut = self._submit(self._timed_call, alt,
                                           call, svc, hp, af)
                        futs[fut] = (alt, hp, True)
                        pending.add(fut)
                        self.hedge_stats["issued"] += len(hp)
                        stats.add_value("storage_client.hedge.issued",
                                        kind="counter")

            def unresolved() -> bool:
                # keep waiting only while a pending future could still
                # improve some part's verdict; anything else pending is
                # an abandoned straggler (its pool thread resolves on
                # its own RPC/deadline timeout and feeds health stats)
                covered: set = set()
                for f in pending:
                    covered.update(futs[f][1])
                for p in assignment:
                    pr = round_res.get(p)
                    if (pr is None or pr.code != ErrorCode.SUCCEEDED) \
                            and p in covered:
                        return True
                return False

            while pending and unresolved():
                done, pending = futures_wait(
                    pending, return_when=FIRST_COMPLETED)
                for f in done:
                    absorb(f)
            for p in assignment:
                # abandoned-straggler parts whose hedge also failed
                # must still carry a verdict (a silent hole would read
                # as neither served nor refused to the caller)
                round_res.setdefault(p, DevicePartResult(
                    code=ErrorCode.E_HOST_NOT_FOUND))
            resp.results.update(round_res)

        spread = allow_follower and follower_max_ms > 0 and hosts_list
        assign = {}
        for part in parts:
            if spread:
                # deterministic rotation over the healthy NON-leader
                # hosts — the point of follower reads is taking load
                # OFF the leader; a non-replica pick refuses and rides
                # the one leader retry below. All followers ejected ->
                # the leader serves (it always can)
                ldr = self._leader(space_id, part)
                cands = [h for h in hosts_list if h != ldr
                         and not self.peer_health.ejected(h)] or [ldr]
                assign[part] = cands[part % len(cands)]
            else:
                assign[part] = self._leader(space_id, part)
        run_round(assign, allow_follower, hedged=bool(spread))
        retry = {}
        for part, pr in list(resp.results.items()):
            if pr.code == ErrorCode.E_LEADER_CHANGED:
                if pr.leader:
                    self._note_leader(space_id, part, pr.leader)
                retry[part] = self._leader(space_id, part)
        if retry:
            self.device_stats["leader_retries"] += len(retry)
            run_round(retry, False)
        for part, pr in resp.results.items():
            if pr.code == ErrorCode.SUCCEEDED:
                self.device_stats["parts_served"] += 1
                if pr.mode == "follower":
                    self.device_stats["follower_parts"] += 1
                if pr.staleness_ms > self.device_stats["max_staleness_ms"]:
                    self.device_stats["max_staleness_ms"] = pr.staleness_ms
            else:
                self.device_stats["refused_parts"] += 1
        return resp

    def bound_stats(self, space_id: int, vids: List[int],
                    edge_types: List[int], stat_defs: List[StatDef],
                    filter_bytes: Optional[bytes] = None,
                    max_edges_per_vertex: Optional[int] = None) -> StatsResponse:
        """Aggregate pushdown: SUM/COUNT/AVG computed storage-side, partial
        (sum, count) pairs merged here (ref: QueryStatsProcessor +
        boundStats RPC, storage.thrift:65-69)."""
        parts = self.cluster_ids_to_parts(space_id, vids)

        def call(svc, host_parts):
            return svc.bound_stats(BoundRequest(
                space_id=space_id, parts=host_parts, edge_types=edge_types,
                filter=filter_bytes,
                max_edges_per_vertex=max_edges_per_vertex), stat_defs)

        def merge(acc: StatsResponse, r: StatsResponse):
            acc.results.update(r.results)
            if len(acc.sums) < len(r.sums):
                acc.sums += [0.0] * (len(r.sums) - len(acc.sums))
                acc.counts += [0] * (len(r.counts) - len(acc.counts))
            for i in range(len(r.sums)):
                acc.sums[i] += r.sums[i]
                acc.counts[i] += r.counts[i]
            acc.latency_us = max(acc.latency_us, r.latency_us)

        return self._fanout(space_id, parts, call, StatsResponse(), merge)

    def get_vertex_props(self, space_id: int, vids: List[int],
                         tag_ids: Optional[List[int]] = None) -> PropsResponse:
        parts = self.cluster_ids_to_parts(space_id, vids)

        def call(svc, host_parts):
            return svc.get_vertex_props(space_id, host_parts, tag_ids)

        def merge(acc, r):
            acc.results.update(r.results)
            acc.vertices.extend(r.vertices)

        return self._fanout(space_id, parts, call, PropsResponse(), merge)

    def get_edge_props(self, space_id: int, eks: List[EdgeKey]) -> PropsResponse:
        parts: Dict[int, List[EdgeKey]] = {}
        for ek in eks:
            parts.setdefault(self.part_id(space_id, ek.src), []).append(ek)

        def call(svc, host_parts):
            return svc.get_edge_props(space_id, host_parts)

        def merge(acc, r):
            acc.results.update(r.results)
            acc.edges.extend(r.edges)

        return self._fanout(space_id, parts, call, PropsResponse(), merge)

    # ------------------------------------------------------------------
    # writes
    # ------------------------------------------------------------------
    def add_vertices(self, space_id: int, vertices: List[NewVertex],
                     overwritable: bool = True) -> ExecResponse:
        parts: Dict[int, List[NewVertex]] = {}
        for nv in vertices:
            parts.setdefault(self.part_id(space_id, nv.vid), []).append(nv)

        def call(svc, host_parts):
            return svc.add_vertices(space_id, host_parts, overwritable)

        def merge(acc, r):
            acc.results.update(r.results)

        # write-path observatory: the fan-out extent (leader routing +
        # per-host RPC + merge) is the `fanout` stage of the write
        # timeline (common/writepath.py) — same on all write methods
        with _writepath.timed_stage("fanout", "write_fanout_us"):
            resp = self._fanout(space_id, parts, call, ExecResponse(),
                                merge)
        self.note_local_write(space_id)   # AFTER the write lands
        return resp

    def add_edges(self, space_id: int, edges: List[NewEdge],
                  overwritable: bool = True) -> ExecResponse:
        """Writes the out-edge at src's part AND the reverse copy at dst's
        part with negated type (the reference's in/out edge pair)."""
        parts: Dict[int, List[NewEdge]] = {}
        for e in edges:
            parts.setdefault(self.part_id(space_id, e.src), []).append(e)
            rev = NewEdge(e.dst, -e.etype, e.rank, e.src, e.row)
            parts.setdefault(self.part_id(space_id, rev.src), []).append(rev)

        def call(svc, host_parts):
            return svc.add_edges(space_id, host_parts, overwritable)

        def merge(acc, r):
            acc.results.update(r.results)

        with _writepath.timed_stage("fanout", "write_fanout_us"):
            resp = self._fanout(space_id, parts, call, ExecResponse(),
                                merge)
        self.note_local_write(space_id)   # AFTER the write lands
        return resp

    def delete_vertices(self, space_id: int, vids: List[int]) -> ExecResponse:
        resp = ExecResponse()
        with _writepath.timed_stage("fanout", "write_fanout_us"):
            for vid in vids:
                part = self.part_id(space_id, vid)
                svc = self._hosts[self._leader(space_id, part)]
                pr, local_keys = svc.get_edge_keys(space_id, part, vid)
                if pr.code != ErrorCode.SUCCEEDED:
                    resp.results[part] = pr
                    continue
                # counterpart keys live on the neighbor's part
                remote: List[EdgeKey] = [EdgeKey(ek.dst, -ek.etype,
                                                 ek.rank, ek.src)
                                         for ek in local_keys]
                if remote:
                    self.delete_edges(space_id, remote)
                r = svc.delete_vertex(space_id, part, vid)
                resp.results.update(r.results)
        self.note_local_write(space_id)
        return resp

    def delete_edges(self, space_id: int, eks: List[EdgeKey]) -> ExecResponse:
        parts: Dict[int, List[EdgeKey]] = {}
        for ek in eks:
            parts.setdefault(self.part_id(space_id, ek.src), []).append(ek)
            rev = EdgeKey(ek.dst, -ek.etype, ek.rank, ek.src)
            parts.setdefault(self.part_id(space_id, rev.src), []).append(rev)

        def call(svc, host_parts):
            return svc.delete_edges(space_id, host_parts)

        def merge(acc, r):
            acc.results.update(r.results)

        with _writepath.timed_stage("fanout", "write_fanout_us"):
            resp = self._fanout(space_id, parts, call, ExecResponse(),
                                merge)
        self.note_local_write(space_id)   # AFTER the write lands
        return resp

    def update_vertex(self, space_id: int, vid: int, tag_id: int,
                      items: List[UpdateItemReq], when: Optional[bytes] = None,
                      insertable: bool = False,
                      yield_props: Optional[List[str]] = None) -> UpdateResponse:
        part = self.part_id(space_id, vid)
        with _writepath.timed_stage("fanout", "write_fanout_us"):
            svc = self._hosts[self._leader(space_id, part)]
            resp = svc.update_vertex(space_id, part, vid, tag_id, items,
                                     when, insertable, yield_props)
        if resp.code == ErrorCode.E_LEADER_CHANGED:
            self._note_leader(space_id, part, resp.leader)
        self.note_local_write(space_id)   # AFTER the write lands
        return resp

    def update_edge(self, space_id: int, ek: EdgeKey,
                    items: List[UpdateItemReq], when: Optional[bytes] = None,
                    insertable: bool = False,
                    yield_props: Optional[List[str]] = None) -> UpdateResponse:
        part = self.part_id(space_id, ek.src)
        with _writepath.timed_stage("fanout", "write_fanout_us"):
            svc = self._hosts[self._leader(space_id, part)]
            resp = svc.update_edge(space_id, part, ek, items, when,
                                   insertable, yield_props)
            if resp.code == ErrorCode.SUCCEEDED:
                # keep the reverse copy in sync (goes beyond the
                # reference, which leaves reversed scans stale after
                # UPDATE EDGE)
                rev_part = self.part_id(space_id, ek.dst)
                rev_svc = self._hosts[self._leader(space_id, rev_part)]
                rev_svc.update_edge(space_id, rev_part,
                                    EdgeKey(ek.dst, -ek.etype, ek.rank,
                                            ek.src),
                                    items, None, True, None)
        if resp.code == ErrorCode.E_LEADER_CHANGED:
            self._note_leader(space_id, part, resp.leader)
        self.note_local_write(space_id)   # AFTER the write lands
        return resp

    def get_uuid(self, space_id: int, name: str) -> Tuple[PartResult, int]:
        from ..filter.functions import _fnv1a64
        n = self.sm.num_parts(space_id)
        part = ku.part_id(_fnv1a64(name.encode("utf-8")), n)
        svc = self._hosts[self._leader(space_id, part)]
        return svc.get_uuid(space_id, part, name)

    # ------------------------------------------------------------------
    # admin fan-out to every storage host (ref: meta dispatches download/
    # ingest/checkpoint to all storaged over HTTP)
    # ------------------------------------------------------------------
    # ------------------------------------------------------------------
    # generic KV (ref: PutProcessor/GetProcessor via storage.thrift
    # put/get — used by SimpleKVVerifyTool)
    # ------------------------------------------------------------------
    def _kv_part(self, space_id: int, key: bytes) -> int:
        from ..filter.functions import _fnv1a64
        return ku.part_id(_fnv1a64(key), self.sm.num_parts(space_id))

    def _space_exists(self, space_id: int) -> bool:
        """Does the catalog still know this space? (distinguishes the
        fresh-space propagation window from a dropped space)."""
        get = getattr(self.sm, "_meta", None)
        get = getattr(get, "get_space_by_id", None)
        if get is None:
            return True
        try:
            return get(space_id).ok()
        except Exception:
            return True

    # _kv_retry backoff bases per classification (capped exponential
    # with jitter — a fixed interval either hammers an electing part
    # or oversleeps a fast redirect); a hinted leader change retries
    # near-immediately, it only backs off if the leader KEEPS moving
    KV_BACKOFF = {"leader_moved": (0.005, 0.1), "hintless": (0.05, 0.8),
                  "no_part": (0.1, 1.6)}

    def _count_fanout_retry(self, cls_key: str, retries_left: bool) -> None:
        """Fan-out retry rounds share _kv_retry's counters, so election
        waits and leader redirects are visible per classification in
        /tpu_stats + Prometheus whichever path hit them."""
        self.retry_stats[cls_key] += 1
        stats.add_value("storage_client.fanout_retry." + cls_key,
                        kind="counter")
        if not retries_left:
            stats.add_value("storage_client.fanout_exhausted",
                            kind="counter")

    def _kv_backoff(self, cls_key: str, attempt: int,
                    retries_left: bool) -> None:
        from ..common.faults import jittered_delay, pace_retry
        self.retry_stats[cls_key] += 1
        stats.add_value("storage_client.kv_retry." + cls_key,
                        kind="counter")
        if not retries_left:
            return   # terminal failure: no point sleeping before it
        base, cap = self.KV_BACKOFF[cls_key]
        # pace_retry: a first-touch snapshot refresh reaches this loop
        # while HOLDING the engine lock (scan_part_cols during
        # failover) — that context suppresses the sleep, so retries
        # rotate hints immediately and a miss degrades to the CPU pipe
        # instead of blocking every query on the lock (lock-witness
        # finding; docs/manual/15-static-analysis.md)
        pace_retry(jittered_delay(base, cap, attempt))

    def _kv_retry(self, space_id: int, part: int, call, classify,
                  max_retries: int = 3):
        """Retry loop for single-part KV ops, with the same fixups as
        _fanout: leader-redirect (note the hinted leader), fresh-space
        part-not-found (wait for the topology watch). `classify(result)`
        returns None (done), a leader hint string ("" = hintless), or
        "no_part". Retries back off exponentially (bounded, jittered)
        and are counted per classification in `retry_stats`."""
        result = None
        for attempt in range(max_retries + 1):
            result = call(self._hosts[self._leader(space_id, part)])
            cls = classify(result)
            if cls is None:
                return result
            left = attempt < max_retries
            if cls == "no_part":
                if not self._space_exists(space_id):
                    return result
                if self._refresh_hosts is not None:
                    self._refresh_hosts()
                self._kv_backoff("no_part", attempt, left)
            elif cls:
                self._note_leader(space_id, part, cls)
                self._kv_backoff("leader_moved", attempt, left)
            else:
                self._kv_backoff("hintless", attempt, left)  # election
        return result

    @staticmethod
    def _classify_status(st: Status):
        if st.code == ErrorCode.E_LEADER_CHANGED:
            return st.msg or ""
        if st.code in (ErrorCode.E_PART_NOT_FOUND,
                       ErrorCode.E_SPACE_NOT_FOUND):
            return "no_part"
        return None

    # ------------------------------------------------------------------
    # snapshot sync (TPU engine feed; see processors.scan_part_cols)
    # ------------------------------------------------------------------
    def scan_part_cols(self, space_id: int, part: int, kind: int):
        """Leader-routed columnar scan of one (part, kind) range, with
        the same leader-redirect/fresh-part retries as any KV op.
        -> ScanPartResponse (result.code != SUCCEEDED on failure)."""
        from .types import ScanPartResponse

        def call(svc):
            try:
                return svc.scan_part_cols(space_id, part, kind)
            except Exception:
                # unreachable host == hintless leader change: rotate
                return ScanPartResponse(PartResult(
                    ErrorCode.E_LEADER_CHANGED, None))

        def classify(resp):
            if resp.result.code == ErrorCode.E_LEADER_CHANGED:
                return resp.result.leader or ""
            if resp.result.code in (ErrorCode.E_PART_NOT_FOUND,
                                    ErrorCode.E_SPACE_NOT_FOUND):
                return "no_part"
            return None

        return self._kv_retry(space_id, part, call, classify)

    def space_versions(self, space_id: int) -> Optional[Tuple]:
        """Freshness token: engine write-version of every host serving
        the space's parts (from the local watch cache — ZERO per-query
        RPCs; storaged pushes changes through the `watch_space_versions`
        long-poll), the part->leader routing, and this client's own
        write sequence (read-your-writes while a push is in flight).
        None when any host's watch channel is down — the TPU engine
        then declines and the CPU fan-out path serves."""
        n = self.sm.num_parts(space_id)
        routing = tuple(sorted(
            (p, self._leader(space_id, p)) for p in range(1, n + 1)))
        hosts = sorted({h for _, h in routing})
        versions = []
        for host in hosts:
            v = self._cached_version(host, space_id)
            if v is None:
                return None
            versions.append((host, v))
        return (tuple(versions), routing,
                self._local_write_seq.get(space_id, 0))

    def _cached_version(self, host: str, space_id: int) -> Optional[int]:
        """This host's engine write-version for the space from the watch
        cache; one synchronous probe primes a cold host. None while the
        host's watch channel is broken (host unreachable)."""
        with self._vlock:
            fresh = self._vfresh.get(host)
            vmap = self._vcache.get(host)
        if fresh and vmap is not None:
            return vmap.get(space_id, -1)   # -1 = no engine (space_version)
        if fresh is False:
            return None                     # watch channel down
        self._ensure_watcher(host)          # cold host: start watching...
        svc = self._hosts.get(host)
        if svc is None:
            return None
        try:                                # ...and prime synchronously
            self.version_stats["probe_rpcs"] += 1
            # (write_version, leader_sig) tuple — or -1 for no engine;
            # opaque here, the token only ever compares by equality
            return svc.space_version(space_id)
        except Exception:
            return None

    def _ensure_watcher(self, host: str) -> None:
        with self._vlock:
            t = self._vwatchers.get(host)
            if t is not None and t.is_alive():
                return
            # nlint: disable=NL002 -- host-lifetime liveness long-poll;
            # it watches for EVERY future query, not the current one
            t = threading.Thread(target=self._watch_host, args=(host,),
                                 name=f"version-watch-{host}", daemon=True)
            self._vwatchers[host] = t
        t.start()

    def _watch_host(self, host: str) -> None:
        """One long-poll loop per storage host. A broken connection
        (storaged death) marks the host stale immediately — the TPU
        path declines until the channel re-establishes. The watch is a
        LIVENESS probe, so over RPC it uses a fail-fast twin of the
        shared proxy (max_attempts=1): the paced reconnect backoff is
        right for request traffic but would delay marking a dead host
        stale, widening the window where a device snapshot is trusted
        on an unverifiable freshness token."""
        from ..rpc.transport import RpcClient, proxy
        known: Dict[int, int] = {}
        fast = None
        while not self._closed:
            svc = self._hosts.get(host)
            if svc is None:
                break
            if isinstance(svc, RpcClient):
                if fast is None or fast.addr != svc.addr:
                    fast = proxy(svc.addr, svc.service,
                                 timeout=svc._timeout, max_attempts=1)
                svc = fast
            try:
                cur = svc.watch_space_versions(known, timeout=1.0)
            except Exception:
                with self._vlock:
                    self._vfresh[host] = False
                known = {}
                time.sleep(0.25)
                continue
            with self._vlock:
                self._vcache[host] = cur
                self._vfresh[host] = True
            self.version_stats["watch_rounds"] += 1
            known = cur

    def host_changes_since(self, host: str, space_id: int, since: int):
        """Delta-sync passthrough to one storage host (TPU engine feed;
        runs only on invalidation, never per query)."""
        svc = self._hosts.get(host)
        if svc is None:
            raise KeyError(host)
        return svc.changes_since(space_id, since)

    def routing_stats(self) -> Dict[str, Any]:
        """Routing/retry state for observability surfaces (graphd
        /tpu_stats cluster block, soak debug bundle) — the one place
        that reads the internals, so the surfaces can't diverge."""
        return {
            "leader_cache_size": len(self._leader_cache),
            "retries": dict(self.retry_stats),
            "version_watch": dict(self.version_stats),
            "peer_health": self.peer_health.snapshot(),
            "hedge": dict(self.hedge_stats),
        }

    def note_local_write(self, space_id: int) -> None:
        """Every mutation through this client bumps the space's local
        write sequence, which is part of the freshness token — so this
        client's next read rebuilds/patches the device snapshot even
        before the storaged version push lands (read-your-writes)."""
        self._local_write_seq[space_id] = \
            self._local_write_seq.get(space_id, 0) + 1

    def close(self) -> None:
        self._closed = True
        self.peer_health.close()

    def kv_put(self, space_id: int, kvs: List[Tuple[bytes, bytes]]) -> Status:
        by_part: Dict[int, List[Tuple[bytes, bytes]]] = {}
        for k, v in kvs:
            by_part.setdefault(self._kv_part(space_id, k), []).append((k, v))
        for part, part_kvs in by_part.items():
            st = self._kv_retry(
                space_id, part,
                lambda svc, p=part, pk=part_kvs: svc.kv_put(space_id, p, pk),
                self._classify_status)
            if not st.ok():
                return st
        self.note_local_write(space_id)   # AFTER the writes land
        return Status.OK()

    def kv_get(self, space_id: int, key: bytes) -> StatusOr:
        part = self._kv_part(space_id, key)
        return self._kv_retry(
            space_id, part, lambda svc: svc.kv_get(space_id, part, key),
            lambda r: self._classify_status(r.status))

    def _fanout_hosts(self, call) -> Dict[str, Any]:
        """Concurrent per-host admin fan-out: every future is DRAINED
        before returning (a first-error early return would leave stale
        tasks racing a retry into the same staging/checkpoint dirs and
        occupying pool slots), exceptions captured per host."""
        if self._refresh_hosts is not None:
            self._refresh_hosts()  # include hosts that joined after boot
        futs = {h: self._submit(call, svc)
                for h, svc in list(self._hosts.items())}
        out: Dict[str, Any] = {}
        for host, f in futs.items():
            try:
                out[host] = f.result()
            except Exception as e:      # transport-level failure
                out[host] = Status.error(ErrorCode.E_INTERNAL, str(e))
        return out

    def _all_hosts_ok(self, call) -> Status:
        for host, st in self._fanout_hosts(call).items():
            if not st.ok():
                return Status.error(st.code, f"{host}: {st.msg}")
        return Status.OK()

    def download(self, space_id: int, url: str) -> Status:
        """Every host stages ITS parts' SSTs concurrently (the Spark
        generator's cluster-parallel staging role — N hosts pull N
        disjoint part sets at once, not one after another)."""
        return self._all_hosts_ok(lambda s: s.download(space_id, url))

    def ingest(self, space_id: int) -> Tuple[Status, int]:
        """Concurrent per-host ingest of the disjoint staged part sets
        (each host loads only parts it serves — ingest_dir skips
        non-local part files)."""
        total = 0
        err: Optional[Status] = None
        for host, r in self._fanout_hosts(
                lambda s: s.ingest(space_id)).items():
            if isinstance(r, Status):     # transport failure wrapped
                st, n = r, 0
            else:
                st, n = r
            if not st.ok() and err is None:
                err = Status.error(st.code, f"{host}: {st.msg}")
            total += n
        if err is not None:
            return err, total
        self.note_local_write(space_id)   # AFTER the ingest lands
        return Status.OK(), total

    def create_checkpoint(self, name: str) -> Status:
        return self._all_hosts_ok(lambda s: s.create_checkpoint(name))

    def drop_checkpoint(self, name: str) -> Status:
        return self._all_hosts_ok(lambda s: s.drop_checkpoint(name))
