"""Storaged-tier device serving: per-host CSR shards + window serve.

PAPER.md's layer map puts storage processors next to the KVStore so
compute lands where data lives — this module is that move for the TPU
engine: every replicated storaged keeps a LOCAL CsrSnapshot (engine_tpu/
csr.py narrow-width packing) built from its own KV engine, refreshed
off the raft apply path, and serves one-hop window expansions from it
(`device_window` RPC) so graphd's scatter/gather v2 fans a GO window
out to per-host device partials instead of leader-routed row scans
(docs/manual/13-device-speed.md, "Storaged-tier device shards").

Vouching: a host answers for a part only when it can PROVE freshness —

- leadership: the part is in `store.leader_parts` (the PR 6
  leadership-signature token's set) -> authoritative, fence staleness 0;
- bounded-staleness follower read: the part's raft replica passes
  `read_fence(follower_max_ms)` (commit-index fence + time lease capped
  at the election timeout — kvstore/raftex/raft_part.py);
- shard freshness: the local CSR's version may trail the engine's
  write version by at most `device_shard_max_ms` (the refresh task
  delta-patches behind a moved version — engine_tpu/delta.py in-place
  applies from the change ring, full rebuild only on first build /
  ring truncation / delta fold; between move and patch the shard
  serves within the budget, then refuses to vouch).

A refused part returns E_LEADER_CHANGED (leadership/fence: the client
re-routes to the leader) or E_PART_NOT_FOUND (no servable shard here:
the client falls back to the row-scan path for that part). Leadership
changes invalidate the space's shard outright (`invalidate`): the old
shard refuses to vouch immediately and the next refresh rebuilds
against the new led set.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..common import writepath as _writepath
from ..common.faults import faults
from ..common.flags import storage_flags
from ..common.flight import recorder as _flight
from ..common.stats import stats as global_stats
from ..common.status import ErrorCode
from .types import (DevicePartResult, DeviceWindowRequest,
                    DeviceWindowResponse, EdgeData, VertexData)

# device window programs fuse at most this many edge types (engine
# contract — traverse.pad_edge_types); wider requests take the host path
MAX_EDGE_TYPES_ON_DEVICE = 8


class _SpaceShard:
    __slots__ = ("snap", "stale_since", "mu")

    def __init__(self, snap):
        self.snap = snap
        # monotonic instant the engine write version was first observed
        # past the build version (None = shard is current)
        self.stale_since: Optional[float] = None
        # serializes in-place delta application against window serving
        # (the same invariant graphd's engine lock provides: delta
        # applies mutate host mirrors the emit path reads)
        self.mu = threading.Lock()


class DeviceShardManager:
    """Local device-shard lifecycle + window serving for one storaged.

    `raft_lookup(space, part) -> RaftPart | None` supplies the fence;
    without it (single-node stores) every held part serves as leader.
    """

    def __init__(self, store, sm, raft_lookup=None, host: str = ""):
        self._store = store
        self._sm = sm
        self._raft = raft_lookup
        self.host = host
        self._lock = threading.Lock()
        self._spaces: Dict[int, _SpaceShard] = {}
        self._building: set = set()
        self.stats = {
            "builds": 0, "build_failures": 0, "serves": 0,
            "parts_served": 0, "parts_refused": 0,
            "follower_parts_served": 0, "leader_parts_served": 0,
            "leader_invalidations": 0, "stale_refusals": 0,
            "fence_refusals": 0, "device_launches": 0,
            "delta_applies": 0, "delta_declines": 0,
            "host_expansions": 0, "edges_emitted": 0,
            "max_staleness_ms": 0.0,
        }

    def _leader_hint(self, space: int, part: int) -> Optional[str]:
        """Client-routable leader hint for a refused part. The store
        Part's consensus hook maps the raft leader's RAFT address to
        the storage RPC address — a raw RaftPart.leader() is NOT
        dialable by the StorageClient (raft listens one port over), so
        hinting it poisons the client's leader cache until the next
        heartbeat repairs it (observed as E_HOST_NOT_FOUND retries
        that dropped whole parts to the row-scan fallback)."""
        pr = self._store.part(space, part)
        if pr.ok():
            return self.host or None
        if pr.status.code == ErrorCode.E_LEADER_CHANGED:
            return pr.status.msg or None
        return None

    # ------------------------------------------------------------------
    # shard lifecycle
    # ------------------------------------------------------------------
    def refresh(self) -> int:
        """Freshen every space whose engine write version moved past
        its shard's version (the background task's body; also builds
        first-time shards). Committed writes are patched in PLACE from
        the engine's change ring (engine_tpu/delta.py — the same
        machinery graphd's local snapshots ride); a full rebuild runs
        only first time, on ring truncation, or when the delta buffer
        needs folding. Returns refreshes performed. Runs OFF the raft
        apply path — never blocks commits."""
        n = 0
        for space_id in list(self._store.spaces()):
            engine = self._store.space_engine(space_id)
            if engine is None:
                continue
            wv = int(engine.write_version)
            with self._lock:
                ent = self._spaces.get(space_id)
                if ent is not None and ent.snap.write_version == wv:
                    ent.stale_since = None
                    continue
                if ent is not None and ent.stale_since is None:
                    ent.stale_since = time.monotonic()
                if space_id in self._building:
                    continue
                self._building.add(space_id)
            try:
                if ent is None or \
                        not self._apply_deltas(space_id, ent, engine):
                    self._rebuild(space_id)
                n += 1
            finally:
                with self._lock:
                    self._building.discard(space_id)
        return n

    def _apply_deltas(self, space_id: int, ent: _SpaceShard,
                      engine) -> bool:
        """Patch the shard in place from the engine's committed-write
        ring. False -> the caller full-rebuilds (first build, ring
        truncated past the cursor, apply capacity exhausted, or the
        delta buffer is full enough to fold into a fresh base)."""
        snap = ent.snap
        cursor = getattr(snap, "delta_cursor", None)
        if cursor is None or getattr(engine, "changes", None) is None:
            return False
        now_v, raw = engine.changes_snapshot(cursor)
        if raw is None:
            # the engine's ring truncated past our cursor (or a
            # barrier op — indistinguishable here, same consequence):
            # the rebuild that follows carries this cause forward
            _writepath.note_ring_overrun(space_id, cause="truncated",
                                         host=self.host or None,
                                         cursor=cursor)
            self.stats["delta_declines"] += 1
            return False
        if raw:
            from ..engine_tpu.delta import apply_entries
            from ..kvstore.changelog import resolve_changes
            t0 = time.perf_counter()
            try:
                faults.fire("csr.delta_apply")
                entries = resolve_changes(engine, raw)
                with ent.mu:
                    ok = apply_entries(snap, self._sm, entries,
                                       time.time())
            except Exception:
                ok = False
            if not ok:
                # the snapshot may be partially patched — it must not
                # serve until rebuilt (the rebuild replaces it)
                self.stats["delta_declines"] += 1
                return False
            snap.invalidate_aligned()
            self.stats["delta_applies"] += 1
            us = int((time.perf_counter() - t0) * 1e6)
            _writepath.stage("delta_apply", us)
            _writepath.snapshots.note(space_id, "delta_apply",
                                      dur_us=us, lock_us=us,
                                      entries=len(entries))
        with ent.mu:
            snap.delta_cursor = now_v
            snap.write_version = now_v
        with self._lock:
            ent.stale_since = None
        # device visibility on the storaged serving tier: acks keyed by
        # this host (processors._note_ack) clear against its own shard
        # cursor — never another storaged's
        _writepath.watermark.note_visible(
            space_id, {self.host: now_v} if self.host else now_v,
            cause="delta")
        d = snap.delta
        if d is not None and \
                d.edge_count + d.tomb_count > 0.75 * d.max_edges:
            return False    # fold the delta into a fresh base now
        return True

    def _rebuild(self, space_id: int) -> None:
        from ..engine_tpu.csr import build_snapshot
        try:
            num_parts = int(self._sm.num_parts(space_id))
        except Exception:
            held = self._store.parts(space_id)
            num_parts = max(held) if held else 0
        if num_parts <= 0:
            return
        t0 = time.perf_counter()
        try:
            snap = build_snapshot(self._store, self._sm, space_id,
                                  num_parts)
            # arm the incremental feed: subsequent refreshes patch in
            # place from the change ring starting at this version
            snap.delta_cursor = snap.write_version
        except Exception:
            self.stats["build_failures"] += 1
            global_stats.add_value("device_serve.build_failures",
                                   kind="counter")
            return
        with self._lock:
            replacement = self._spaces.get(space_id) is not None
            self._spaces[space_id] = _SpaceShard(snap)
        self.stats["builds"] += 1
        global_stats.add_value("device_serve.builds", kind="counter")
        _writepath.snapshots.note(
            space_id, "build",
            dur_us=int((time.perf_counter() - t0) * 1e6),
            cause="replace" if replacement else "first_touch")
        _writepath.watermark.note_visible(
            space_id,
            {self.host: snap.write_version} if self.host
            else snap.write_version,
            cause="build")

    def invalidate(self, space_id: int, part_id: int = 0) -> None:
        """Leadership moved: the old shard must refuse to vouch NOW
        (the led set it was serving under is gone) — drop it; the next
        refresh rebuilds against the new leadership signature."""
        with self._lock:
            dropped = self._spaces.pop(space_id, None)
        self.stats["leader_invalidations"] += 1
        if dropped is not None:
            _flight.record("device_shard_invalidated", space=space_id,
                           part=part_id, host=self.host)

    def shard_version(self, space_id: int) -> int:
        with self._lock:
            ent = self._spaces.get(space_id)
            return int(ent.snap.write_version) if ent else -1

    def snapshot_info(self, space_id: int) -> Dict[str, Any]:
        """Freshness view for the web surface / bench quiesce."""
        engine = self._store.space_engine(space_id)
        wv = int(engine.write_version) if engine is not None else -1
        with self._lock:
            ent = self._spaces.get(space_id)
            if ent is None:
                return {"built": False, "write_version": wv}
            d = ent.snap.delta
            return {"built": True, "shard_version":
                    int(ent.snap.write_version), "write_version": wv,
                    "fresh": int(ent.snap.write_version) == wv,
                    "total_edges": ent.snap.total_edges +
                    (d.edge_count if d is not None else 0)}

    # ------------------------------------------------------------------
    # serving
    # ------------------------------------------------------------------
    def serve(self, req: DeviceWindowRequest) -> DeviceWindowResponse:
        t0 = time.monotonic()
        resp = DeviceWindowResponse(host=self.host)
        self.stats["serves"] += 1
        space = req.space_id
        engine = self._store.space_engine(space)
        with self._lock:
            ent = self._spaces.get(space)
        # shard staleness: build version vs live write version, timed
        # from the first observation of the move
        shard_ms = 0.0
        servable = ent is not None and engine is not None
        if servable and int(engine.write_version) != \
                int(ent.snap.write_version):
            now = time.monotonic()
            with self._lock:
                if ent.stale_since is None:
                    ent.stale_since = now
                shard_ms = (now - ent.stale_since) * 1000.0
            budget = storage_flags.get_or("device_shard_max_ms", 250, int)
            if shard_ms > float(budget):
                servable = False
                self.stats["stale_refusals"] += 1
        led = set(self._store.leader_parts(space)) if servable else set()
        held = set(self._store.parts(space)) if servable else set()
        granted: Dict[int, DevicePartResult] = {}
        for part, vids in req.parts.items():
            raft = self._raft(space, part) if self._raft else None
            if part in led or (raft is None and servable
                               and part in held):
                mode, fence_ms = "leader", 0.0
            elif raft is not None and req.allow_follower and \
                    req.follower_max_ms > 0 and servable:
                ok, st, _reason = raft.read_fence(req.follower_max_ms)
                if not ok:
                    self.stats["fence_refusals"] += 1
                    self.stats["parts_refused"] += 1
                    resp.results[part] = DevicePartResult(
                        code=ErrorCode.E_LEADER_CHANGED,
                        leader=self._leader_hint(space, part))
                    continue
                mode, fence_ms = "follower", st
            else:
                self.stats["parts_refused"] += 1
                if not servable:
                    resp.results[part] = DevicePartResult(
                        code=ErrorCode.E_PART_NOT_FOUND)
                else:
                    resp.results[part] = DevicePartResult(
                        code=ErrorCode.E_LEADER_CHANGED,
                        leader=self._leader_hint(space, part))
                continue
            staleness = fence_ms + shard_ms
            granted[part] = DevicePartResult(
                mode=mode, staleness_ms=round(staleness, 3),
                shard_version=int(ent.snap.write_version))
            if staleness > self.stats["max_staleness_ms"]:
                self.stats["max_staleness_ms"] = round(staleness, 3)
        if granted:
            vids = [v for p in granted for v in req.parts[p]]
            with ent.mu:   # delta applies patch the mirrors we read
                idx_per_part = self._expand(ent.snap, vids,
                                            req.edge_types)
                self._emit(ent.snap, idx_per_part, set(granted), req,
                           resp)
        for part, pr in granted.items():
            resp.results[part] = pr
            self.stats["parts_served"] += 1
            if pr.mode == "follower":
                self.stats["follower_parts_served"] += 1
            else:
                self.stats["leader_parts_served"] += 1
        resp.latency_us = int((time.monotonic() - t0) * 1e6)
        return resp

    def _expand(self, snap, vids: List[int],
                edge_types: List[int]) -> Dict[int, np.ndarray]:
        """One-hop active-edge expansion -> {part0: ascending edge idx}.
        Device path: the snapshot's traversal kernel (the fused window
        program served against the local shard); host path when the
        request is wider than the kernel fuses or the launch fails —
        both produce the identical edge set."""
        if edge_types and len(edge_types) <= MAX_EDGE_TYPES_ON_DEVICE:
            try:
                faults.fire("kernel.launch")
                import jax.numpy as jnp
                from ..engine_tpu import traverse
                f0 = jnp.asarray(snap.frontier_from_vids(vids))
                reqt = jnp.asarray(traverse.pad_edge_types(edge_types))
                _, act = traverse.multi_hop(f0, jnp.int32(1),
                                            snap.kernel, reqt)
                act = np.asarray(act)
                self.stats["device_launches"] += 1
                return {p: np.nonzero(act[p])[0]
                        for p in range(snap.num_parts)}
            except Exception:
                pass
        self.stats["host_expansions"] += 1
        return self._expand_host(snap, vids, edge_types)

    def _expand_host(self, snap, vids: List[int],
                     edge_types: List[int]) -> Dict[int, np.ndarray]:
        from ..engine_tpu.engine import _shard_indptr
        per_part: Dict[int, List[int]] = {}
        for v in vids:
            loc = snap.locate(v)
            if loc is not None and loc[1] < snap.shards[loc[0]].num_vids_base:
                per_part.setdefault(loc[0], []).append(loc[1])
        out: Dict[int, np.ndarray] = {}
        for p0, locals_ in per_part.items():
            shard = snap.shards[p0]
            indptr = _shard_indptr(shard)
            la = np.asarray(sorted(set(locals_)), np.int64)
            lo, hi = indptr[la], indptr[la + 1]
            counts = (hi - lo).astype(np.int64)
            total = int(counts.sum())
            if total == 0:
                continue
            idx = (np.repeat(lo - np.pad(np.cumsum(counts),
                                         (1, 0))[:-1], counts)
                   + np.arange(total))
            ok = shard.edge_valid[idx]
            if edge_types:
                ok = ok & np.isin(shard.edge_etype[idx], edge_types)
            else:
                ok = ok & (shard.edge_etype[idx] > 0)
            out[p0] = np.sort(idx[ok])
        return out

    def _emit(self, snap, idx_per_part: Dict[int, np.ndarray],
              granted_parts: set, req: DeviceWindowRequest,
              resp: DeviceWindowResponse) -> None:
        """Materialize active edges into BoundResponse-shaped vertices,
        mirroring the engine's `_materialize` / the CPU getBound row
        semantics: per-(src, etype) cap, props from host mirrors with
        version-missing keys omitted, trim to `req.edge_props` AFTER
        materialization (None = all)."""
        from ..engine_tpu.csr import host_gather
        cap = req.max_edges_per_vertex or storage_flags.get_or(
            "max_edge_returned_per_vertex", 10000, int)
        want = None if req.edge_props is None else set(req.edge_props)
        per_vertex: Dict[int, VertexData] = {}
        cap_counts: Dict[tuple, int] = {}
        n_edges = 0
        for p0, idxs in idx_per_part.items():
            if (p0 + 1) not in granted_parts or len(idxs) == 0:
                continue
            shard = snap.shards[p0]
            idxs = np.asarray(idxs, np.int64)
            all_ets = shard.edge_etype[idxs]
            all_srcs = shard.vids[shard.edge_src[idxs]]
            all_ranks = shard.edge_rank[idxs]
            all_dsts = shard.edge_dst_vid[idxs]
            # per-(part, etype) column gathers: one fancy index per
            # prop column instead of a python host_item call per cell
            # (canonical order within a (src, etype) group is
            # preserved, so the per-(src, etype) cap selects the
            # same edges the per-edge walk did)
            for et in np.unique(all_ets):
                sel = np.nonzero(all_ets == et)[0]
                et_i = int(et)
                grp = idxs[sel]
                colvals = []
                for name, col in (shard.edge_props.get(et_i)
                                  or {}).items():
                    if want is not None and name not in want:
                        continue
                    vals = host_gather(col, grp).tolist()
                    miss = None if col.missing is None \
                        else col.missing[grp]
                    colvals.append((name, vals, miss))
                for k, j in enumerate(sel):
                    src_vid = int(all_srcs[j])
                    ckey = (src_vid, et_i)
                    cap_counts[ckey] = cap_counts.get(ckey, 0) + 1
                    if cap_counts[ckey] > cap:
                        continue
                    vd = per_vertex.get(src_vid)
                    if vd is None:
                        vd = VertexData(src_vid)
                        per_vertex[src_vid] = vd
                    props = {}
                    for name, vals, miss in colvals:
                        if miss is None or not miss[k]:
                            props[name] = vals[k]
                    vd.edges.append(EdgeData(src_vid, et_i,
                                             int(all_ranks[j]),
                                             int(all_dsts[j]),
                                             props))
                    n_edges += 1
        # delta-buffer ADDS (edges committed after the base build,
        # patched in by _apply_deltas) live in the ELL side buffer the
        # canonical arrays don't cover — walk them per frontier vid
        # via the by-source index, same cap/type/prop semantics
        d = snap.delta
        if d is not None and d.edge_count:
            et_ok = set(req.edge_types) if req.edge_types else None
            for part in granted_parts:
                for vid in req.parts.get(part, ()):
                    loc = snap.locate(vid)
                    if loc is None or loc[0] != part - 1:
                        continue
                    gslot = loc[0] * snap.cap_v + loc[1]
                    for lane_key in d.by_src.get(gslot, ()):
                        if not d.h_ok[lane_key]:
                            continue
                        src_vid, et, rank, dst_vid, dprops = \
                            d.info[lane_key]
                        if (et not in et_ok) if et_ok is not None \
                                else et <= 0:
                            continue
                        ckey = (src_vid, et)
                        cap_counts[ckey] = cap_counts.get(ckey, 0) + 1
                        if cap_counts[ckey] > cap:
                            continue
                        vd = per_vertex.get(src_vid)
                        if vd is None:
                            vd = VertexData(src_vid)
                            per_vertex[src_vid] = vd
                        props = dict(dprops or {})
                        if want is not None:
                            props = {k: v for k, v in props.items()
                                     if k in want}
                        vd.edges.append(EdgeData(src_vid, int(et),
                                                 int(rank),
                                                 int(dst_vid), props))
                        n_edges += 1
        resp.vertices = list(per_vertex.values())
        self.stats["edges_emitted"] += n_edges
