"""Storage service processors — the CPU data plane.

Role parity with the reference's `src/storage/` processor classes:

  get_bound        <- QueryBoundProcessor (the GetNeighbors hot path,
                      ref storage/QueryBaseProcessor.inl:292-562)
  get_vertex_props <- QueryVertexPropsProcessor
  get_edge_props   <- QueryEdgePropsProcessor
  get_edge_keys    <- QueryEdgeKeysProcessor (used by DELETE VERTEX)
  add_vertices     <- AddVerticesProcessor (decreasing versions,
                      ref AddVerticesProcessor.cpp:31-57)
  add_edges        <- AddEdgesProcessor (out-edge at src part, in-edge
                      copy at dst part with negated type)
  delete_*         <- Delete{Vertex,Edges}Processor
  update_*         <- Update{Vertex,Edge}Processor (read-modify-write as
                      an atomic op through the consensus serialization
                      point, ref UpdateVertexProcessor.cpp:331)
  kv_put/get       <- PutProcessor/GetProcessor (generic KV API)
  get_uuid         <- GetUUIDProcessor

Pushed-down WHERE filters arrive as encoded expression trees and are
evaluated per edge with getters bound to KV rows (ref:
QueryBaseProcessor.inl:415-443); only `$^` source props and edge props
are admissible storage-side, mirroring the reference's `checkExp`
whitelist (`is_pushable` below).

TTL semantics: rows whose `ttl_col + ttl_duration < now` are invisible
to reads — the read-time analogue of the reference's
StorageCompactionFilter dropping expired data.
"""
from __future__ import annotations

import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from ..codec.row import RowReader, RowUpdater, RowWriter, peek_schema_version
from ..codec.schema import Schema
from ..common import keys as ku
from ..common.cache import CacheRung, result_stage_enabled
from ..common.flags import MUTABLE, storage_flags
from ..common.status import ErrorCode, Status
from ..filter.expressions import (DestPropExpr, EdgePropExpr, EvalError,
                                  Expression, ExpressionContext, InputPropExpr,
                                  VariablePropExpr, decode_expression)
from ..kvstore.store import GraphStore
from ..kvstore import log_encoder as le
from ..meta.schema_manager import SchemaManager
from ..common import heat, ledger
from ..common import writepath as _writepath
from ..common.stats import stats
from ..common.tracing import ActiveQueryRegistry, SlowQueryLog, tracer
from .types import (BoundRequest, BoundResponse, DevicePartResult,
                    DeviceWindowRequest, DeviceWindowResponse, EdgeData,
                    EdgeKey, ExecResponse, LookupRequest, LookupResponse,
                    LookupRow, NewEdge, NewVertex, PartResult,
                    PropsResponse, StatDef, StatsResponse, UpdateItemReq,
                    UpdateResponse, VertexData)

DEFAULT_MAX_EDGES_PER_VERTEX = 10000  # FLAGS_max_edge_returned_per_vertex

# storaged's own registry (a standalone storaged's /flags and meta
# config pull serve storage_flags — the graph_flags twin declared in
# common/tracing.py is unreachable from another process)
storage_flags.declare(
    "slow_query_threshold_ms", 500, MUTABLE,
    "finished processor ops slower than this land in the slow-op log "
    "(/queries) with their ledger slice; 0 disables")


def is_pushable(expr: Expression) -> bool:
    """Can this filter be evaluated storage-side? (ref: checkExp,
    QueryBaseProcessor.inl:171-290 — no $-, $var, or $$ refs)."""
    for node in expr.walk():
        if isinstance(node, (InputPropExpr, VariablePropExpr, DestPropExpr)):
            return False
    return True


def _filter_tag_ids(sm: SchemaManager, space: int, flt) -> set:
    """Tag ids referenced by $^ props in a pushed-down filter (loop-
    invariant: computed once per request, not per vertex)."""
    from ..filter.expressions import SourcePropExpr
    out = set()
    if flt is not None:
        for node in flt.walk():
            if isinstance(node, SourcePropExpr):
                tid = sm.tag_id(space, node.tag)
                if tid is not None:
                    out.add(tid)
    return out


class _StorageExprContext(ExpressionContext):
    """Binds property refs to the current (vertex tags, edge row) pair."""

    def __init__(self, sm: SchemaManager, space_id: int):
        self._sm = sm
        self._space = space_id
        self.src_props: Dict[str, Dict[str, Any]] = {}  # tag name -> props
        self.edge_props: Dict[str, Any] = {}
        self.edge_name: str = ""
        self.src = 0
        self.dst = 0
        self.rank = 0

    def get_src_prop(self, tag: str, prop: str):
        props = self.src_props.get(tag)
        if props is None:
            # vertex doesn't carry the tag: schema default (the
            # graphd-side rule, VertexHolder::get →
            # RowReader::getDefaultProp — the pushed-down filter must
            # evaluate exactly like the local one)
            tid = self._sm.tag_id(self._space, tag)
            if tid is not None:
                r = self._sm.tag_schema(self._space, tid)
                if r.ok() and r.value().has_field(prop):
                    return r.value().default_value(prop)
            raise EvalError(f"$^.{tag}.{prop} not found")
        if prop not in props:
            raise EvalError(f"$^.{tag}.{prop} not found")
        return props[prop]

    def get_edge_prop(self, edge, prop):
        if edge is not None and edge != self.edge_name:
            raise EvalError(f"edge {edge} not in scope")
        if prop not in self.edge_props:
            raise EvalError(f"edge prop {prop} not found")
        return self.edge_props[prop]

    def get_edge_src(self, edge):
        return self.src

    def get_edge_dst(self, edge):
        return self.dst

    def get_edge_rank(self, edge):
        return self.rank

    def get_edge_type_name(self, edge):
        return self.edge_name


class StorageService:
    """One storage node: processors over a GraphStore."""

    def __init__(self, store: GraphStore, schema_manager: SchemaManager,
                 host: str = "local",
                 max_edges_per_vertex: Optional[int] = None):
        self.store = store
        self.sm = schema_manager
        self.host = host
        # explicit override wins; otherwise the MUTABLE
        # `max_edge_returned_per_vertex` storage flag supplies the
        # per-vertex truncation cap hot-settably (found by nebula-lint
        # NL003: the flag was declared but this service hardcoded the
        # default and never read it)
        self._max_edges_override = max_edges_per_vertex
        # storaged-tier device shards (storage/device_serve.py): set by
        # the storaged daemon wiring; None on plain single-node
        # services (device_window then refuses every part and the
        # client rides the row-scan path)
        self.device_serve = None
        # in-flight read processors, served by storaged's /queries (the
        # storage-side twin of the graphd active-query registry).
        # FINISHED ops over slow_query_threshold_ms land in slow_ops
        # with their ledger slice — before ISSUE 12 a completed op was
        # dropped without duration or row counts (the gap found while
        # wiring the cost ledger)
        self.active_ops = ActiveQueryRegistry()
        self.slow_ops = SlowQueryLog()
        # storaged cache rungs (common/cache.py; cache_mode=full on
        # storage_flags; docs/manual/11-caching.md): bound_stats
        # responses and (part, version) columnar scans, both keyed by
        # the space engine's monotonic write_version — the same token
        # the TPU engine's freshness watch rides, so any committed
        # write orphans old entries structurally (the RocksDB-block-
        # cache role under the storage service). The scan rung holds
        # whole part scans, hence the byte cap.
        self.stats_cache = CacheRung("storage.stats_cache", 256,
                                     stats_prefix="storage.stats_cache")
        self.scan_cache = CacheRung(
            "storage.scan_cache", 64,
            stats_prefix="storage.scan_cache",
            weigher=lambda r: (len(r.keys_blob) + len(r.vals_blob)
                               + len(r.vlens) + len(r.klens) + 256),
            # resolved per store: scan_cache_mb is MUTABLE and must
            # keep working after construction (hot memory relief)
            byte_cap=lambda: int(storage_flags.get("scan_cache_mb",
                                                   256)) * (1 << 20))

    @property
    def max_edges_per_vertex(self) -> int:
        if self._max_edges_override is not None:
            return self._max_edges_override
        return storage_flags.get_or("max_edge_returned_per_vertex",
                                    DEFAULT_MAX_EDGES_PER_VERTEX, int)

    def _catalog_version(self) -> int:
        v = getattr(self.sm, "_meta", None)
        v = getattr(v, "catalog_version", 0) if v is not None else 0
        return v() if callable(v) else v

    def _engine_version(self, space_id: int) -> Optional[int]:
        engine = self.store.space_engine(space_id)
        return None if engine is None else int(engine.write_version)

    def _note_ack(self, space_id: int) -> None:
        """Write-path observatory: one client-visible mutation ack.
        Runs AFTER the consensus/engine commit, so the engine's
        write_version already covers this write — the ack-to-visible
        watermark (common/writepath.py) pairs it against the device
        snapshot's later cursor advance. Keyed by this service's host
        identity so the RemoteStorageProvider's per-host cursor dict
        matches acks host-by-host."""
        if not _writepath.enabled():
            return
        v = self._engine_version(space_id)
        if v is not None:
            _writepath.watermark.note_ack(space_id, self.host, v)

    def _finish_op(self, tok: int, stmt: str) -> None:
        """Retire an in-flight processor op WITH its duration: ops
        over slow_query_threshold_ms land in the slow-op log with the
        trace id they adopted and their server-side ledger slice
        (ISSUE 12 satellite — completed ops used to vanish from
        /queries without duration or rows)."""
        elapsed_ms = self.active_ops.finish(tok)
        if elapsed_ms is None:
            return
        thr = storage_flags.get("slow_query_threshold_ms", 500)
        if not thr or elapsed_ms <= float(thr):
            return
        stats.add_value("storage.slow_op", kind="counter")
        ctx = tracer.current_ctx()
        led = ledger.current()
        self.slow_ops.add(stmt, int(elapsed_ms * 1000),
                          trace_id=ctx[0] if ctx else "",
                          cost=led.to_dict() if led is not None
                          else None)

    # ------------------------------------------------------------------
    # schema/row helpers
    # ------------------------------------------------------------------
    def _decode_row(self, schema_getter, space_id: int, sid: int,
                    data: bytes) -> Optional[Dict[str, Any]]:
        ver = peek_schema_version(data)
        r = schema_getter(space_id, sid, ver)
        if not r.ok():
            r = schema_getter(space_id, sid, -1)
            if not r.ok():
                return None
        schema: Schema = r.value()
        row = RowReader(schema, data).to_dict()
        if schema.ttl_col and schema.ttl_duration > 0:
            ts = row.get(schema.ttl_col)
            if isinstance(ts, (int, float)) and ts + schema.ttl_duration < time.time():
                return None  # expired (compaction-filter semantics)
        return row

    def _newest_tag_row(self, engine, space_id: int, part: int, vid: int,
                        tag_id: int) -> Optional[Dict[str, Any]]:
        it = engine.prefix(ku.vertex_prefix(part, vid, tag_id))
        for _, v in it:
            return self._decode_row(self.sm.tag_schema, space_id, tag_id, v)
        return None

    # ------------------------------------------------------------------
    # get_bound — THE hot loop (ref: collectEdgeProps .inl:380-458)
    # ------------------------------------------------------------------
    def get_bound(self, req: BoundRequest) -> BoundResponse:
        n_vids = sum(len(v) for v in req.parts.values())
        desc = (f"get_bound space={req.space_id} parts={len(req.parts)} "
                f"vids={n_vids}")
        tok = self.active_ops.register(desc)
        try:
            with tracer.span("proc.get_bound", parts=len(req.parts),
                             vids=n_vids, host=self.host):
                return self._get_bound(req)
        finally:
            self._finish_op(tok, desc)

    def _get_bound(self, req: BoundRequest) -> BoundResponse:
        t0 = time.monotonic()
        stats.add_value("storage.get_bound_qps", kind="counter")
        resp = BoundResponse()
        space = req.space_id
        flt = None
        if req.filter:
            flt = decode_expression(req.filter)
            if not is_pushable(flt):
                for part in req.parts:
                    resp.results[part] = PartResult(ErrorCode.E_INVALID_FILTER)
                return resp
        edge_types = req.edge_types or self.sm.all_edge_types(space)
        max_edges = req.max_edges_per_vertex or self.max_edges_per_vertex
        ctx = _StorageExprContext(self.sm, space)
        # tags used in the filter must be loaded too
        filter_tags = _filter_tag_ids(self.sm, space, flt)

        scanned = 0
        ret_bytes = 0
        for part, vids in req.parts.items():
            pr = self.store.part(space, part)
            if not pr.ok():
                resp.results[part] = PartResult(pr.status.code, pr.status.msg or None)
                continue
            engine = pr.value().engine
            part_scanned = scanned
            part_bytes = ret_bytes
            for vid in vids:
                vd = VertexData(vid)
                # source-vertex props for $^ refs and YIELD
                want_tags = set(req.vertex_props) | filter_tags
                for tag_id in want_tags:
                    row = self._newest_tag_row(engine, space, part, vid, tag_id)
                    scanned += 1
                    if row is not None:
                        if tag_id in req.vertex_props and req.vertex_props[tag_id]:
                            vd.tag_props[tag_id] = {
                                p: row.get(p) for p in req.vertex_props[tag_id]}
                        else:
                            vd.tag_props[tag_id] = row
                ctx.src_props = {
                    (self.sm.tag_name(space, tid) or str(tid)): props
                    for tid, props in vd.tag_props.items()}
                for etype in edge_types:
                    s, b = self._collect_edge_props(
                        engine, space, part, vid, etype, req, ctx, flt,
                        max_edges, vd)
                    scanned += s
                    ret_bytes += b
                resp.vertices.append(vd)
            resp.results[part] = PartResult(ErrorCode.SUCCEEDED)
            # per-part heat slab (workload observatory): this part's
            # share of the scan, plus the scanned src vids feeding the
            # hot-vertex sketch (both one flag read when disarmed)
            heat.accountant.charge(space, part, reads=len(vids),
                                   rows_scanned=scanned - part_scanned,
                                   bytes_returned=ret_bytes - part_bytes)
            heat.accountant.observe_vids(space, vids)
        # cost ledger, charged SERVER-side under this host's own name
        # (merged client-side from the RPC piggyback) + fleet counters
        ledger.charge_host(self.host, rows_scanned=scanned,
                           bytes_returned=ret_bytes)
        if scanned:
            stats.add_value("storage.rows_scanned", scanned,
                            kind="counter")
        if ret_bytes:
            stats.add_value("storage.bytes_returned", ret_bytes,
                            kind="counter")
        resp.latency_us = int((time.monotonic() - t0) * 1e6)
        # native histogram (was kind="timing"): real bucket series on
        # /metrics, exemplars carrying the adopted remote trace id
        stats.add_value("storage.get_bound_latency_us", resp.latency_us,
                        kind="histogram")
        return resp

    def _collect_edge_props(self, engine, space: int, part: int, vid: int,
                            etype: int, req: BoundRequest,
                            ctx: _StorageExprContext, flt, max_edges: int,
                            vd: VertexData) -> Tuple[int, int]:
        """-> (rows scanned, row-value bytes returned) — the cost-
        ledger accounting of this (vid, etype) scan: scanned counts
        every deduped edge row ITERATED (filtered-out rows cost IO
        too), bytes count the raw values of rows that made the
        response."""
        edge_name = self.sm.edge_name(space, etype) or str(abs(etype))
        ctx.edge_name = edge_name
        prefix = ku.edge_prefix(part, vid, etype)
        if hasattr(engine, "prefix_dedup"):
            # native hot loop: version dedup happens inside the engine
            # (ref collectEdgeProps .inl:403-407 done in C++)
            it = engine.prefix_dedup(prefix, group_suffix=8)
        else:
            it = engine.prefix(prefix)
        last_group: Optional[Tuple[int, int]] = None
        count = 0
        scanned = 0
        ret_bytes = 0
        for k, v in it:
            _, src, et, rank, dst, _ver = ku.parse_edge_key(k)
            group = (rank, dst)
            if group == last_group:
                continue  # older version of the same logical edge
            last_group = group
            if count >= max_edges:
                break  # cap, ref: FLAGS_max_edge_returned_per_vertex
            scanned += 1
            if not v:
                continue  # tombstone
            props = self._decode_row(self.sm.edge_schema, space, etype, v)
            if props is None:
                continue
            ctx.edge_props = props
            ctx.src, ctx.dst, ctx.rank = vid, dst, rank
            if flt is not None:
                try:
                    if not flt.eval(ctx):
                        continue
                except EvalError:
                    continue
            if req.edge_props is not None:
                props = {p: props.get(p) for p in req.edge_props if p in props}
            vd.edges.append(EdgeData(vid, et, rank, dst, props))
            count += 1
            ret_bytes += len(v)
        return scanned, ret_bytes

    # ------------------------------------------------------------------
    # bound_stats — aggregate pushdown (ref: QueryStatsProcessor,
    # storage.thrift StatType SUM/COUNT/AVG :65-69)
    # ------------------------------------------------------------------
    def bound_stats(self, req: BoundRequest,
                    stat_defs: List[StatDef]) -> StatsResponse:
        n_vids = sum(len(v) for v in req.parts.values())
        desc = (f"bound_stats space={req.space_id} "
                f"parts={len(req.parts)} vids={n_vids} "
                f"defs={len(stat_defs)}")
        tok = self.active_ops.register(desc)
        try:
            with tracer.span("proc.bound_stats", parts=len(req.parts),
                             vids=n_vids, host=self.host):
                key = self._stats_cache_key(req, stat_defs)
                if key is not None:
                    hit = self.stats_cache.get(key)
                    if hit is not None:
                        tracer.tag_root("cache_hit", "bound_stats")
                        return _copy_stats_response(hit)
                resp = self._bound_stats(req, stat_defs)
                # put-time version re-check (the engine result cache's
                # rule): a write committing mid-scan can tear the
                # response across parts — publishing it under the
                # pre-write version key would hand a same-key reader
                # partials no direct scan could return
                if key is not None and all(
                        r.code == ErrorCode.SUCCEEDED
                        for r in resp.results.values()) and \
                        self._engine_version(req.space_id) == key[1]:
                    self.stats_cache.put(key, _copy_stats_response(resp))
                return resp
        finally:
            self._finish_op(tok, desc)

    def _stats_cache_key(self, req: BoundRequest,
                         stat_defs: List[StatDef]):
        """bound_stats cache key, or None when the rung is off or the
        request is unkeyable. Keyed by the space engine's
        write_version (any committed write orphans the entry) AND the
        meta catalog version (ALTER changes defaults/visibility
        without touching storage data). Schemas with TTL columns
        never cache — their rows expire by wall clock, invisible to
        both versions."""
        if not result_stage_enabled(storage_flags):
            return None
        engine = self.store.space_engine(req.space_id)
        if engine is None:
            return None
        space = req.space_id
        edge_types = req.edge_types or self.sm.all_edge_types(space)
        for et in edge_types:
            r = self.sm.edge_schema(space, abs(et))
            if r.ok() and r.value().ttl_col:
                return None
        for d in stat_defs:
            if d.owner == "tag":
                r = self.sm.tag_schema(space, d.schema_id)
                if r.ok() and r.value().ttl_col:
                    return None
        filter_tags = set()
        if req.filter:
            try:
                filter_tags = _filter_tag_ids(
                    self.sm, space, decode_expression(req.filter))
            except Exception:
                return None
        for tid in filter_tags:
            r = self.sm.tag_schema(space, tid)
            if r.ok() and r.value().ttl_col:
                return None
        return (space, int(engine.write_version),
                self._catalog_version(),
                tuple(sorted((p, tuple(v))
                             for p, v in req.parts.items())),
                tuple(edge_types), req.filter,
                tuple((d.owner, d.schema_id, d.prop, d.stat)
                      for d in stat_defs),
                req.max_edges_per_vertex,
                tuple(sorted((t, tuple(ps)) for t, ps in
                             (req.vertex_props or {}).items())))

    def _bound_stats(self, req: BoundRequest,
                     stat_defs: List[StatDef]) -> StatsResponse:
        """Same scan as get_bound but emits partial aggregates instead of
        rows: per StatDef a (sum, count) pair the client merges across
        partitions — SUM/COUNT/AVG without shipping edges to graphd.

        The pushed-down filter applies to EDGE rows only, exactly as in
        the reference (exp_ is evaluated in collectEdgeProps,
        QueryBaseProcessor.inl:415-449; collectVertexProps has no filter
        hook) — tag-owner stats aggregate over every requested vertex."""
        t0 = time.monotonic()
        stats.add_value("storage.bound_stats_qps", kind="counter")
        resp = StatsResponse(sums=[0.0] * len(stat_defs),
                             counts=[0] * len(stat_defs))
        space = req.space_id
        flt = None
        if req.filter:
            flt = decode_expression(req.filter)
            if not is_pushable(flt):
                for part in req.parts:
                    resp.results[part] = PartResult(ErrorCode.E_INVALID_FILTER)
                return resp
        edge_types = req.edge_types or self.sm.all_edge_types(space)
        max_edges = req.max_edges_per_vertex or self.max_edges_per_vertex
        ctx = _StorageExprContext(self.sm, space)
        filter_tags = _filter_tag_ids(self.sm, space, flt)
        tag_defs = [(i, d) for i, d in enumerate(stat_defs) if d.owner == "tag"]
        edge_defs = [(i, d) for i, d in enumerate(stat_defs) if d.owner == "edge"]

        def _acc(idx: int, row: Dict[str, Any], d: StatDef) -> None:
            if d.stat == 2:  # COUNT: rows ("" prop) or non-null prop values
                if not d.prop or row.get(d.prop) is not None:
                    resp.counts[idx] += 1
                return
            v = row.get(d.prop)
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                return  # non-numeric / missing: not aggregated
            resp.sums[idx] += v
            resp.counts[idx] += 1

        scanned = 0
        for part, vids in req.parts.items():
            pr = self.store.part(space, part)
            if not pr.ok():
                resp.results[part] = PartResult(pr.status.code,
                                                pr.status.msg or None)
                continue
            engine = pr.value().engine
            part_scanned = scanned
            for vid in vids:
                # tag-owner stats + $^ bindings for the filter
                src_props: Dict[str, Dict[str, Any]] = {}
                want: Dict[int, Optional[Dict[str, Any]]] = {}
                for tid in filter_tags:
                    want[tid] = self._newest_tag_row(engine, space, part,
                                                     vid, tid)
                for idx, d in tag_defs:
                    if d.schema_id not in want:
                        want[d.schema_id] = self._newest_tag_row(
                            engine, space, part, vid, d.schema_id)
                    row = want[d.schema_id]
                    if row is not None:
                        _acc(idx, row, d)
                scanned += len(want)
                for tid, row in want.items():
                    if row is not None:
                        src_props[self.sm.tag_name(space, tid) or str(tid)] = row
                ctx.src_props = src_props
                if not edge_defs:
                    continue
                for etype in edge_types:
                    vd = VertexData(vid)
                    s, _b = self._collect_edge_props(
                        engine, space, part, vid, etype, req, ctx, flt,
                        max_edges, vd)
                    scanned += s
                    for ed in vd.edges:
                        for idx, d in edge_defs:
                            if d.schema_id and d.schema_id != ed.etype:
                                continue
                            _acc(idx, ed.props, d)
            resp.results[part] = PartResult(ErrorCode.SUCCEEDED)
            heat.accountant.charge(space, part, reads=len(vids),
                                   rows_scanned=scanned - part_scanned)
            heat.accountant.observe_vids(space, vids)
        ledger.charge_host(self.host, rows_scanned=scanned)
        if scanned:
            stats.add_value("storage.rows_scanned", scanned,
                            kind="counter")
        resp.latency_us = int((time.monotonic() - t0) * 1e6)
        stats.add_value("storage.bound_stats_latency_us",
                        resp.latency_us, kind="histogram")
        return resp

    # ------------------------------------------------------------------
    # point lookups
    # ------------------------------------------------------------------
    def get_vertex_props(self, space_id: int, parts: Dict[int, List[int]],
                         tag_ids: Optional[List[int]] = None) -> PropsResponse:
        resp = PropsResponse()
        tags = tag_ids if tag_ids else self.sm.all_tag_ids(space_id)
        for part, vids in parts.items():
            pr = self.store.part(space_id, part)
            if not pr.ok():
                resp.results[part] = PartResult(pr.status.code, pr.status.msg or None)
                continue
            engine = pr.value().engine
            for vid in vids:
                vd = VertexData(vid)
                for tag_id in tags:
                    row = self._newest_tag_row(engine, space_id, part, vid, tag_id)
                    if row is not None:
                        vd.tag_props[tag_id] = row
                if vd.tag_props:
                    resp.vertices.append(vd)
            resp.results[part] = PartResult()
        return resp

    def get_edge_props(self, space_id: int,
                       parts: Dict[int, List[EdgeKey]]) -> PropsResponse:
        resp = PropsResponse()
        for part, eks in parts.items():
            pr = self.store.part(space_id, part)
            if not pr.ok():
                resp.results[part] = PartResult(pr.status.code, pr.status.msg or None)
                continue
            engine = pr.value().engine
            for ek in eks:
                it = engine.prefix(ku.edge_group_prefix(part, ek.src, ek.etype,
                                                        ek.rank, ek.dst))
                for _, v in it:
                    if not v:
                        break
                    props = self._decode_row(self.sm.edge_schema, space_id,
                                             ek.etype, v)
                    if props is not None:
                        resp.edges.append(EdgeData(ek.src, ek.etype, ek.rank,
                                                   ek.dst, props))
                    break
            resp.results[part] = PartResult()
        return resp

    # ------------------------------------------------------------------
    # lookup_scan — the LOOKUP identity twin (ref role: the storage
    # index scans under storage/index/LookUpIndexProcessor): full part
    # scan over one schema, newest row per entity, WHERE evaluated per
    # row. The device secondary index (engine_tpu/index.py) must be
    # byte-identical to this path; anything the device declines lands
    # here.
    # ------------------------------------------------------------------
    def lookup_scan(self, req: LookupRequest) -> LookupResponse:
        desc = (f"lookup_scan space={req.space_id} parts={len(req.parts)} "
                f"{'edge' if req.is_edge else 'tag'}={req.schema_id}")
        tok = self.active_ops.register(desc)
        try:
            with tracer.span("proc.lookup_scan", parts=len(req.parts),
                             host=self.host):
                t0 = time.monotonic()
                stats.add_value("storage.lookup_scan_qps", kind="counter")
                resp = LookupResponse()
                flt = decode_expression(req.filter) if req.filter else None
                for part in req.parts:
                    self._lookup_scan_part(req, part, flt, resp)
                resp.latency_us = int((time.monotonic() - t0) * 1e6)
                stats.add_value("storage.lookup_scan_latency_us",
                                resp.latency_us, kind="histogram")
                return resp
        finally:
            self._finish_op(tok, desc)

    def _lookup_scan_part(self, req: LookupRequest, part: int, flt,
                          resp: LookupResponse) -> None:
        pr = self.store.part(req.space_id, part)
        if not pr.ok():
            resp.results[part] = PartResult(pr.status.code,
                                            pr.status.msg or None)
            return
        engine = pr.value().engine
        space = req.space_id
        name = (self.sm.edge_name(space, req.schema_id) if req.is_edge
                else self.sm.tag_name(space, req.schema_id)) or ""
        ectx = _StorageExprContext(self.sm, space)
        ectx.edge_name = name
        kind = ku.KIND_EDGE if req.is_edge else ku.KIND_VERTEX
        rows_scanned = 0
        bytes_returned = 0
        last = None
        for k, v in engine.prefix(ku.part_data_prefix(part, kind)):
            rows_scanned += 1
            if req.is_edge:
                _, src, et, rank, dst, _ = ku.parse_edge_key(k)
                if et != req.schema_id:
                    continue
                ent = (src, et, rank, dst)
            else:
                _, vid, tag_id, _ = ku.parse_vertex_key(k)
                if tag_id != req.schema_id:
                    continue
                ent = vid
            if ent == last:
                continue        # older version of the same entity
            last = ent
            if not v:
                continue        # tombstone hides every older version
            row = self._decode_row(
                self.sm.edge_schema if req.is_edge else self.sm.tag_schema,
                space, req.schema_id, v)
            if row is None:
                continue        # TTL-expired / undecodable
            if flt is not None:
                ectx.edge_props = row
                if req.is_edge:
                    ectx.src, ectx.rank, ectx.dst = src, rank, dst
                try:
                    if not flt.eval(ectx):
                        continue
                except EvalError:
                    continue    # same row-drop rule as get_bound
            if req.is_edge:
                resp.rows.append(LookupRow(src=src, rank=rank, dst=dst,
                                           props=row))
            else:
                resp.rows.append(LookupRow(vid=ent, props=row))
            bytes_returned += len(v)
        resp.results[part] = PartResult()
        ledger.charge_host(self.host, rows_scanned=rows_scanned,
                           bytes_returned=bytes_returned)
        heat.accountant.charge(space, part, reads=1,
                               rows_scanned=rows_scanned,
                               bytes_returned=bytes_returned)

    def get_edge_keys(self, space_id: int, part: int,
                      vid: int) -> Tuple[PartResult, List[EdgeKey]]:
        """All out+in edge keys stored locally for vid (DELETE support)."""
        pr = self.store.part(space_id, part)
        if not pr.ok():
            return PartResult(pr.status.code, pr.status.msg or None), []
        engine = pr.value().engine
        out: List[EdgeKey] = []
        seen = set()
        it = engine.prefix(ku.edge_prefix(part, vid))
        for k, v in it:
            _, src, et, rank, dst, _ = ku.parse_edge_key(k)
            g = (src, et, rank, dst)
            if g in seen:
                continue
            seen.add(g)
            if v:
                out.append(EdgeKey(src, et, rank, dst))
        return PartResult(), out

    # ------------------------------------------------------------------
    # mutations
    # ------------------------------------------------------------------
    def add_vertices(self, space_id: int,
                     parts: Dict[int, List[NewVertex]],
                     overwritable: bool = True) -> ExecResponse:
        resp = ExecResponse()
        ver = ku.now_version()
        any_ok = False
        for part, vertices in parts.items():
            kvs = []
            for nv in vertices:
                for tag_id, row in nv.tags:
                    kvs.append((ku.vertex_key(part, nv.vid, tag_id, ver), row))
            st = self.store.async_multi_put(space_id, part, kvs)
            resp.results[part] = _to_part_result(st)
            if st.ok():
                any_ok = True
                heat.accountant.charge(space_id, part,
                                       writes=len(vertices))
        if any_ok:
            self._note_ack(space_id)
        return resp

    def add_edges(self, space_id: int, parts: Dict[int, List[NewEdge]],
                  overwritable: bool = True) -> ExecResponse:
        """Each NewEdge lands on the part that owns `src` with its signed
        etype as given; the client is responsible for sending the reverse
        copy to the dst part (matching the reference split)."""
        resp = ExecResponse()
        ver = ku.now_version()
        any_ok = False
        for part, edges in parts.items():
            kvs = [(ku.edge_key(part, e.src, e.etype, e.rank, e.dst, ver), e.row)
                   for e in edges]
            st = self.store.async_multi_put(space_id, part, kvs)
            resp.results[part] = _to_part_result(st)
            if st.ok():
                any_ok = True
                heat.accountant.charge(space_id, part, writes=len(edges))
        if any_ok:
            self._note_ack(space_id)
        return resp

    def delete_vertex(self, space_id: int, part: int, vid: int) -> ExecResponse:
        resp = ExecResponse()
        pr = self.store.part(space_id, part)
        if not pr.ok():
            resp.results[part] = PartResult(pr.status.code, pr.status.msg or None)
            return resp
        engine = pr.value().engine
        dead = [k for k, _ in engine.prefix(ku.vertex_prefix(part, vid))]
        dead += [k for k, _ in engine.prefix(ku.edge_prefix(part, vid))]
        st = self.store.async_multi_remove(space_id, part, dead)
        resp.results[part] = _to_part_result(st)
        if st.ok():
            heat.accountant.charge(space_id, part, writes=1)
            self._note_ack(space_id)
        return resp

    def delete_edges(self, space_id: int,
                     parts: Dict[int, List[EdgeKey]]) -> ExecResponse:
        resp = ExecResponse()
        any_ok = False
        for part, eks in parts.items():
            pr = self.store.part(space_id, part)
            if not pr.ok():
                resp.results[part] = PartResult(pr.status.code, pr.status.msg or None)
                continue
            engine = pr.value().engine
            dead = []
            for ek in eks:
                prefix = ku.edge_group_prefix(part, ek.src, ek.etype, ek.rank,
                                              ek.dst)
                dead.extend(k for k, _ in engine.prefix(prefix))
            st = self.store.async_multi_remove(space_id, part, dead)
            resp.results[part] = _to_part_result(st)
            if st.ok():
                any_ok = True
                heat.accountant.charge(space_id, part, writes=len(eks))
        if any_ok:
            self._note_ack(space_id)
        return resp

    # ------------------------------------------------------------------
    # UPDATE / UPSERT as atomic ops through consensus
    # ------------------------------------------------------------------
    def update_vertex(self, space_id: int, part: int, vid: int, tag_id: int,
                      items: List[UpdateItemReq],
                      when: Optional[bytes] = None,
                      insertable: bool = False,
                      yield_props: Optional[List[str]] = None) -> UpdateResponse:
        out = UpdateResponse()
        sr = self.sm.tag_schema(space_id, tag_id)
        if not sr.ok():
            out.code = sr.status.code
            return out
        schema = sr.value()
        tag_name = self.sm.tag_name(space_id, tag_id) or str(tag_id)

        def atomic_op() -> Optional[bytes]:
            pr = self.store.part(space_id, part)
            if not pr.ok():
                out.code = pr.status.code
                return None
            engine = pr.value().engine
            cur = self._newest_tag_row(engine, space_id, part, vid, tag_id)
            if cur is None:
                if not insertable:
                    out.code = ErrorCode.E_KEY_NOT_FOUND
                    return None
                out.upsert = True
                cur = {}
            ctx = _StorageExprContext(self.sm, space_id)
            ctx.src_props = {tag_name: dict(cur)}
            # bare prop names refer to the row being updated
            ctx.edge_props = dict(cur)
            ctx.edge_name = tag_name
            if when is not None and cur:
                try:
                    if not decode_expression(when).eval(ctx):
                        out.code = ErrorCode.E_FILTER_OUT
                        return None
                except EvalError:
                    out.code = ErrorCode.E_INVALID_FILTER
                    return None
            upd = RowUpdater(schema)
            for f in schema.fields:
                if f.name in cur:
                    upd.set(f.name, cur[f.name])
            for item in items:
                try:
                    val = decode_expression(item.value).eval(ctx)
                except EvalError:
                    out.code = ErrorCode.E_INVALID_UPDATER
                    return None
                prop = item.prop.split(".")[-1]
                if not schema.has_field(prop):
                    out.code = ErrorCode.E_INVALID_UPDATER
                    return None
                upd.set(prop, val)
                ctx.edge_props[prop] = val
                ctx.src_props[tag_name][prop] = val
            new_row = upd.encode()
            if yield_props:
                rd = RowReader(schema, new_row)
                out.props = {p: rd.get(p) for p in yield_props
                             if schema.has_field(p)}
            key = ku.vertex_key(part, vid, tag_id)
            return le.encode_single(le.OP_PUT, key, new_row)

        st = self.store.async_atomic_op(space_id, part, atomic_op)
        if not st.ok() and out.code == ErrorCode.SUCCEEDED:
            out.code = st.code
        if st.ok() and out.code == ErrorCode.SUCCEEDED:
            heat.accountant.charge(space_id, part, writes=1)
            self._note_ack(space_id)
        return out

    def update_edge(self, space_id: int, part: int, ek: EdgeKey,
                    items: List[UpdateItemReq],
                    when: Optional[bytes] = None,
                    insertable: bool = False,
                    yield_props: Optional[List[str]] = None) -> UpdateResponse:
        out = UpdateResponse()
        sr = self.sm.edge_schema(space_id, ek.etype)
        if not sr.ok():
            out.code = sr.status.code
            return out
        schema = sr.value()
        edge_name = self.sm.edge_name(space_id, ek.etype) or str(ek.etype)

        def atomic_op() -> Optional[bytes]:
            pr = self.store.part(space_id, part)
            if not pr.ok():
                out.code = pr.status.code
                return None
            engine = pr.value().engine
            cur = None
            it = engine.prefix(ku.edge_group_prefix(part, ek.src, ek.etype,
                                                    ek.rank, ek.dst))
            for _, v in it:
                if v:
                    cur = self._decode_row(self.sm.edge_schema, space_id,
                                           ek.etype, v)
                break
            if cur is None:
                if not insertable:
                    out.code = ErrorCode.E_KEY_NOT_FOUND
                    return None
                out.upsert = True
                cur = {}
            ctx = _StorageExprContext(self.sm, space_id)
            ctx.edge_props = dict(cur)
            ctx.edge_name = edge_name
            ctx.src, ctx.dst, ctx.rank = ek.src, ek.dst, ek.rank
            if when is not None and cur:
                try:
                    if not decode_expression(when).eval(ctx):
                        out.code = ErrorCode.E_FILTER_OUT
                        return None
                except EvalError:
                    out.code = ErrorCode.E_INVALID_FILTER
                    return None
            upd = RowUpdater(schema)
            for f in schema.fields:
                if f.name in cur:
                    upd.set(f.name, cur[f.name])
            for item in items:
                try:
                    val = decode_expression(item.value).eval(ctx)
                except EvalError:
                    out.code = ErrorCode.E_INVALID_UPDATER
                    return None
                prop = item.prop.split(".")[-1]
                if not schema.has_field(prop):
                    out.code = ErrorCode.E_INVALID_UPDATER
                    return None
                upd.set(prop, val)
                ctx.edge_props[prop] = val
            new_row = upd.encode()
            if yield_props:
                rd = RowReader(schema, new_row)
                out.props = {p: rd.get(p) for p in yield_props
                             if schema.has_field(p)}
            key = ku.edge_key(part, ek.src, ek.etype, ek.rank, ek.dst)
            return le.encode_single(le.OP_PUT, key, new_row)

        st = self.store.async_atomic_op(space_id, part, atomic_op)
        if not st.ok() and out.code == ErrorCode.SUCCEEDED:
            out.code = st.code
        if st.ok() and out.code == ErrorCode.SUCCEEDED:
            heat.accountant.charge(space_id, part, writes=1)
            self._note_ack(space_id)
        return out

    # ------------------------------------------------------------------
    # maintenance (ref: StorageHttpAdminHandler ?op=compact|flush and the
    # StorageCompactionFilter run during RocksDB compaction,
    # storage/CompactionFilter.h: drop superseded versions, tombstoned
    # groups, TTL-expired and undecodable rows)
    # ------------------------------------------------------------------
    def admin_compact(self, space_id: int) -> Tuple[Status, int]:
        """Physically GC every part engine of the space. Runs below raft
        like the reference's compaction (engines converge independently
        because visibility semantics already hide what compact drops).
        Returns (status, keys removed)."""
        removed = 0
        for part in self.store.parts(space_id):
            pr = self.store.part(space_id, part)
            if not pr.ok():
                continue
            engine = pr.value().engine
            drop: List[bytes] = []
            last_group: Optional[bytes] = None
            # materialize the scan first: concurrent RPC writes mutate
            # the live engine while we iterate
            for k, v in list(engine.prefix(b"")):
                if ku.is_vertex_key(k):
                    decode = lambda d, kk=k: self._decode_row(
                        self.sm.tag_schema, space_id,
                        ku.parse_vertex_key(kk)[2], d)
                elif ku.is_edge_key(k):
                    decode = lambda d, kk=k: self._decode_row(
                        self.sm.edge_schema, space_id,
                        ku.parse_edge_key(kk)[2], d)
                else:
                    continue  # system/uuid/custom keys are kept
                group = k[:-8]  # strip version suffix
                if group == last_group:
                    drop.append(k)      # superseded older version
                    continue
                last_group = group
                if not v:
                    drop.append(k)      # newest is a tombstone
                    continue
                if decode(v) is None:
                    drop.append(k)      # TTL-expired or undecodable
            if drop:
                engine.multi_remove(drop)
                removed += len(drop)
        stats.add_value("storage.compact", kind="counter")
        return Status.OK(), removed

    def admin_flush(self, space_id: int) -> Status:
        """Flush every part engine that supports it (ref: ?op=flush)."""
        for part in self.store.parts(space_id):
            pr = self.store.part(space_id, part)
            if pr.ok() and hasattr(pr.value().engine, "flush"):
                st = pr.value().engine.flush()
                if st is not None and not st.ok():
                    return st
        return Status.OK()

    # ------------------------------------------------------------------
    # snapshot sync — the TPU engine's feed from remote parts (this is
    # the storage-service seam the north star designates as the engine
    # plugin boundary; ref storage/StorageServer.cpp:32-55)
    # ------------------------------------------------------------------
    def device_window(self, req: DeviceWindowRequest) -> DeviceWindowResponse:
        """Serve one hop of a graphd scatter/gather-v2 window from this
        host's LOCAL device shard (storage/device_serve.py) — the
        storaged-tier twin of the engine's fused window programs. Parts
        this host cannot vouch for (not leader, follower fence refused,
        shard too stale) come back refused per part; the client
        re-routes or falls back per part, never whole-request."""
        mgr = self.device_serve
        if mgr is None:
            resp = DeviceWindowResponse(host=self.host)
            for part in req.parts:
                resp.results[part] = DevicePartResult(
                    code=ErrorCode.E_PART_NOT_FOUND)
            return resp
        n_vids = sum(len(v) for v in req.parts.values())
        tok = self.active_ops.register(
            f"device_window space={req.space_id} parts={len(req.parts)} "
            f"vids={n_vids}")
        try:
            with tracer.span("proc.device_window", parts=len(req.parts),
                             vids=n_vids):
                resp = mgr.serve(req)
                stats.add_value("storage.device_window", kind="counter")
                return resp
        finally:
            self._finish_op(tok, "device_window")

    def space_version(self, space_id: int):
        """Freshness element for this host × space: (engine
        write-version, leadership signature) — or -1 when the space has
        no local engine. The write-version moves on any data change;
        the signature (the sorted part ids this node LEADS) moves on
        election/deposal/rebalance, so a graphd's device snapshot keyed
        on the old value structurally invalidates the moment this host
        stops being authoritative for a part — the version-watch +
        change ring follow the partition's CURRENT leader instead of a
        deposed replica's stale ring (docs/manual/12-replication.md)."""
        engine = self.store.space_engine(space_id)
        if engine is None:
            return -1
        return (int(engine.write_version),
                tuple(self.store.leader_parts(space_id)))

    def _version_map(self) -> Dict[int, Tuple[int, tuple]]:
        out: Dict[int, Tuple[int, tuple]] = {}
        for sid in self.store.spaces():
            engine = self.store.space_engine(sid)
            if engine is not None:
                out[sid] = (int(engine.write_version),
                            tuple(self.store.leader_parts(sid)))
        return out

    def watch_space_versions(self, known: Optional[Dict[int, int]] = None,
                             timeout: float = 1.0) -> Dict[int, int]:
        """Long-poll version watch: blocks until this host's per-space
        engine write-versions differ from `known` (or `timeout`
        elapses), then returns the current map. The query engine's
        freshness cache rides this channel instead of probing per query
        (ref role: MetaClient.cpp:120-193's cached 1s topology pull —
        here push-on-change, so writes invalidate within ~50ms)."""
        deadline = time.monotonic() + min(float(timeout), 5.0)
        known = dict(known or {})
        while True:
            cur = self._version_map()
            if cur != known or time.monotonic() >= deadline:
                return cur
            time.sleep(0.05)

    def changes_since(self, space_id: int, since: int):
        """Committed writes of this host's space engine since version
        `since`, resolved into logical deltas (kvstore/changelog.py) —
        the remote TPU engine's incremental snapshot feed.
        -> (now_version, entries | None); None = rebuild needed."""
        from ..kvstore.changelog import resolve_changes
        desc = f"changes_since space={space_id} since={since}"
        tok = self.active_ops.register(desc)
        try:
            with tracer.span("proc.changes_since", space=space_id,
                             host=self.host):
                engine = self.store.space_engine(space_id)
                if engine is None or \
                        getattr(engine, "changes", None) is None:
                    return -1, None
                now_v, raw = engine.changes_snapshot(since)
                if raw is None:
                    return now_v, None
                entries = resolve_changes(engine, raw)
                # delta-feed cost: every resolved change row was read
                # server-side on this query's behalf (the incremental
                # twin of the scan_part charge)
                ledger.charge_host(self.host,
                                   rows_scanned=len(entries))
                return now_v, entries
        finally:
            self._finish_op(tok, desc)

    def scan_part_cols(self, space_id: int, part: int,
                       kind: int) -> "ScanPartResponse":
        desc = f"scan_part_cols space={space_id} part={part} kind={kind}"
        tok = self.active_ops.register(desc)
        try:
            with tracer.span("proc.scan_part", part=part, kind=kind,
                             host=self.host):
                # (part, version) scan cache (cache_mode=full): the
                # snapshot-sync feed re-scans whole parts on every
                # rebuild; at an unchanged write_version the columnar
                # blobs are byte-identical — repack retries and
                # mesh demote/re-admit rebuilds stop re-reading the
                # store. Blobs are immutable bytes; the response
                # wrapper is copied per hit (latency_us is per-call).
                key = None
                if result_stage_enabled(storage_flags):
                    engine = self.store.space_engine(space_id)
                    if engine is not None:
                        key = (space_id, part, kind,
                               int(engine.write_version))
                if key is not None:
                    hit = self.scan_cache.get(key)
                    if hit is not None:
                        tracer.tag_root("cache_hit", "scan_part")
                        from .types import ScanPartResponse
                        return ScanPartResponse(
                            hit.result, hit.n, hit.keys_blob,
                            hit.vals_blob, hit.vlens, hit.klens)
                resp = self._scan_part_cols(space_id, part, kind)
                # same put-time version re-check as bound_stats: a
                # write landing mid-scan must not publish the partial
                # blob under the pre-write version
                if key is not None and \
                        resp.result.code == ErrorCode.SUCCEEDED and \
                        self._engine_version(space_id) == key[3]:
                    self.scan_cache.put(key, resp)
                # columnar scan cost (cache hits return above and
                # charge only the rung hit): rows + blob bytes shipped
                blob_bytes = (len(resp.keys_blob or b"")
                              + len(resp.vals_blob or b""))
                ledger.charge_host(
                    self.host, rows_scanned=resp.n,
                    bytes_returned=blob_bytes)
                heat.accountant.charge(space_id, part, reads=1,
                                       rows_scanned=resp.n,
                                       bytes_returned=blob_bytes)
                return resp
        finally:
            self._finish_op(tok, desc)

    def _scan_part_cols(self, space_id: int, part: int,
                        kind: int) -> "ScanPartResponse":
        """Leader-local columnar scan of one (part, kind) data range.
        Same leader guard as every read (reads are leader-only, ref
        KVStore.h) so a snapshot never mixes stale follower data."""
        from .types import ScanPartResponse
        t0 = time.monotonic()
        stats.add_value("storage.scan_part_qps", kind="counter")
        pr = self.store.part(space_id, part)
        if not pr.ok():
            leader = pr.status.msg if \
                pr.status.code == ErrorCode.E_LEADER_CHANGED else None
            return ScanPartResponse(PartResult(pr.status.code, leader))
        from ..kvstore.scan import scan_cols
        import numpy as np
        scan = scan_cols(pr.value().engine, ku.part_data_prefix(part, kind))
        if scan.vals_blob is not None:
            vals_blob = scan.vals_blob
        else:
            vals_blob = b"".join(scan.vals_list)
        resp = ScanPartResponse(
            PartResult(), scan.n, scan.keys_blob, vals_blob,
            np.asarray(scan.vlens, np.int64).tobytes(),
            np.asarray(scan.klens, np.int64).tobytes())
        resp.latency_us = int((time.monotonic() - t0) * 1e6)
        stats.add_value("storage.scan_part_latency_us",
                        resp.latency_us, kind="histogram")
        return resp

    # ------------------------------------------------------------------
    # generic KV + uuid
    # ------------------------------------------------------------------
    def kv_put(self, space_id: int, part: int,
               kvs: List[Tuple[bytes, bytes]]) -> Status:
        return self.store.async_multi_put(space_id, part, kvs)

    def kv_get(self, space_id: int, part: int, key: bytes):
        return self.store.get(space_id, part, key)

    # ------------------------------------------------------------------
    # bulk load + checkpoints (ref: StorageHttp{Download,Ingest}Handler,
    # checkpoint dispatch in the meta snapshot flow)
    # ------------------------------------------------------------------
    def _staging_dir(self, space_id: int) -> str:
        """Per-host staging (like _checkpoint_dir): hosts sharing a
        filesystem — or the in-process multi-host topology — must not
        stage into each other's directories, or the per-part selective
        download could not be observed or cleaned per host."""
        from ..common.flags import storage_flags
        import os
        return os.path.join(storage_flags.get("download_dir"),
                            f"space_{space_id}",
                            self.host.replace(":", "_"))

    def download(self, space_id: int, url: str) -> Status:
        """Stage bulk-load SST files for THIS host's parts only (ref:
        StorageHttpDownloadHandler pulls per-part SSTs from HDFS —
        each host fetches the part files it serves, so the cluster
        downloads the dataset once in aggregate)."""
        from ..common.hdfs import HdfsHelper
        from .sst import part_file
        parts = self.store.parts(space_id)
        if not parts:
            return Status.OK()  # no local parts — nothing to stage here
        return HdfsHelper().copy_to_local(
            url, self._staging_dir(space_id),
            names=[part_file(p) for p in parts])

    def ingest(self, space_id: int) -> Tuple[Status, int]:
        """Ingest previously staged SSTs into the space's parts (ref:
        StorageHttpIngestHandler → RocksEngine::ingest)."""
        from .sst import ingest_dir
        return ingest_dir(self.store, space_id, self._staging_dir(space_id))

    def _checkpoint_dir(self, name: str) -> str:
        """Per-host checkpoint dir: hosts sharing a filesystem (or the
        in-process multi-host topology) must not overwrite each other's
        dumps."""
        import os
        from ..common.flags import storage_flags
        return os.path.join(storage_flags.get("snapshot_dir"), name,
                            self.host.replace(":", "_"))

    def create_checkpoint(self, name: str) -> Status:
        """Dump every space to <snapshot_dir>/<name>/<host>/ (ref:
        storaged checkpoint dispatch behind CREATE SNAPSHOT)."""
        import os
        from .sst import write_sst
        root = self._checkpoint_dir(name)
        os.makedirs(root, exist_ok=True)
        for space_id in self.store.spaces():
            engine = self.store.space_engine(space_id)
            if engine is None:
                continue
            kvs = list(engine.prefix(b""))
            write_sst(os.path.join(root, f"space_{space_id}.nsst"), kvs)
        return Status.OK()

    def drop_checkpoint(self, name: str) -> Status:
        import os
        import shutil
        from ..common.flags import storage_flags
        root = self._checkpoint_dir(name)
        if os.path.isdir(root):
            shutil.rmtree(root)
        # remove the snapshot dir itself once the last host's dump is gone
        parent = os.path.join(storage_flags.get("snapshot_dir"), name)
        if os.path.isdir(parent) and not os.listdir(parent):
            os.rmdir(parent)
        return Status.OK()

    def restore_checkpoint(self, name: str, space_id: int) -> Status:
        """Load a snapshot dump back into the space's engine (recovery
        path — the reference restarts storaged on checkpoint dirs)."""
        import os
        from .sst import read_sst
        path = os.path.join(self._checkpoint_dir(name),
                            f"space_{space_id}.nsst")
        if not os.path.exists(path):
            return Status.error(ErrorCode.E_EXECUTION_ERROR,
                                f"no snapshot dump at {path}")
        engine = self.store.space_engine(space_id)
        if engine is None:
            return Status.error(ErrorCode.E_SPACE_NOT_FOUND,
                                f"space {space_id} not found")
        return engine.ingest(read_sst(path))

    def get_uuid(self, space_id: int, part: int, name: str) -> Tuple[PartResult, int]:
        """Stable name→vid allocation (ref: GetUUIDProcessor)."""
        key = ku.uuid_key(part, name.encode("utf-8"))
        r = self.store.get(space_id, part, key)
        if r.ok():
            import struct
            return PartResult(), struct.unpack("<q", r.value())[0]
        from ..filter.functions import _fnv1a64
        vid = _fnv1a64(name.encode("utf-8"))
        import struct
        st = self.store.async_multi_put(space_id, part,
                                        [(key, struct.pack("<q", vid))])
        return _to_part_result(st), vid


def _to_part_result(st: Status) -> PartResult:
    if st.ok():
        return PartResult()
    return PartResult(st.code, st.msg or None)


def _copy_stats_response(r: StatsResponse) -> StatsResponse:
    """Independent StatsResponse over the same numbers — the client
    merge loop mutates sums/counts in place, so the cached copy must
    never be the one handed out."""
    return StatsResponse(results=dict(r.results), sums=list(r.sums),
                         counts=list(r.counts), latency_us=r.latency_us)
