"""Bulk-load SST files: offline-generated sorted KV batches per partition.

Role parity with the reference's SST bulk-load pipeline: the Spark
sstfile-generator writes per-part RocksDB SST files to HDFS
(tools/spark-sstfile-generator), storaged pulls them with the
`/download` HTTP handler per part and `INGEST` calls
`RocksEngine::ingest` (ref: storage/StorageHttpDownloadHandler.cpp,
kvstore/RocksEngine.cpp:360).

Our container is the NSST file: magic + count + length-prefixed
key/value pairs, keys in sorted order — the simplest format the
engines' `ingest` accepts, written offline by `SstGenerator` (the
Spark-generator equivalent: rows in, per-part sorted KV files out,
including the reverse edge copy exactly as the online write path
splits them).

File layout (little-endian):
    magic  b"NSST\\x01"
    u64    pair count
    repeat: u32 klen, key, u32 vlen, value
"""
from __future__ import annotations

import os
import struct
from typing import Dict, Iterable, List, Tuple

from ..codec.row import RowWriter
from ..codec.schema import Schema
from ..common import keys as ku
from ..common.status import ErrorCode, Status

MAGIC = b"NSST\x01"
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")

KV = Tuple[bytes, bytes]


def _encode_row(schema: Schema, values: Dict) -> bytes:
    w = RowWriter(schema)
    for name, v in values.items():
        w.set(name, v)
    return w.encode()


def write_sst(path: str, kvs: Iterable[KV]) -> int:
    """Write a sorted NSST file; returns the pair count."""
    pairs = sorted(kvs)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(MAGIC)
        f.write(_U64.pack(len(pairs)))
        for k, v in pairs:
            f.write(_U32.pack(len(k)))
            f.write(k)
            f.write(_U32.pack(len(v)))
            f.write(v)
    os.replace(tmp, path)
    return len(pairs)


def read_sst(path: str) -> List[KV]:
    with open(path, "rb") as f:
        raw = f.read()
    if raw[:len(MAGIC)] != MAGIC:
        raise ValueError(f"{path}: not an NSST file")
    off = len(MAGIC)
    (n,) = _U64.unpack_from(raw, off)
    off += _U64.size
    out: List[KV] = []
    for _ in range(n):
        (klen,) = _U32.unpack_from(raw, off)
        off += _U32.size
        k = raw[off:off + klen]
        off += klen
        (vlen,) = _U32.unpack_from(raw, off)
        off += _U32.size
        v = raw[off:off + vlen]
        off += vlen
        out.append((k, v))
    return out


def part_file(part_id: int) -> str:
    return f"part_{part_id}.nsst"


class SstGenerator:
    """Offline per-part SST generation from raw rows (the Spark
    generator's role): callers add vertices/edges with python values,
    rows are encoded with the schema codec, keys shard by
    `vid % num_parts + 1` exactly like the online path, and edges get
    their reverse copy on the dst part."""

    def __init__(self, num_parts: int):
        self.num_parts = num_parts
        self._per_part: Dict[int, List[KV]] = {p: [] for p in
                                               range(1, num_parts + 1)}
        self._version = ku.now_version()

    def _part(self, vid: int) -> int:
        return ku.part_id(vid, self.num_parts)

    def add_vertex(self, vid: int, tag_id: int, schema: Schema,
                   values: Dict) -> None:
        row = _encode_row(schema, values)
        p = self._part(vid)
        self._per_part[p].append(
            (ku.vertex_key(p, vid, tag_id, self._version), row))

    def add_edge(self, src: int, etype: int, rank: int, dst: int,
                 schema: Schema, values: Dict) -> None:
        row = _encode_row(schema, values)
        sp, dp = self._part(src), self._part(dst)
        self._per_part[sp].append(
            (ku.edge_key(sp, src, etype, rank, dst, self._version), row))
        self._per_part[dp].append(
            (ku.edge_key(dp, dst, -etype, rank, src, self._version), row))

    def write(self, out_dir: str) -> Dict[int, int]:
        """Write one NSST per part into out_dir; returns part -> count."""
        os.makedirs(out_dir, exist_ok=True)
        counts = {}
        for p, kvs in self._per_part.items():
            if kvs:
                counts[p] = write_sst(os.path.join(out_dir, part_file(p)), kvs)
        return counts


def ingest_dir(store, space_id: int, staging_dir: str) -> Tuple[Status, int]:
    """INGEST: load every staged per-part NSST into the space's parts
    (ref: StorageHttpIngestHandler → RocksEngine::ingest). Returns
    (status, pairs ingested)."""
    if not os.path.isdir(staging_dir):
        return Status.OK(), 0  # nothing staged on this host
    total = 0
    for p in store.parts(space_id):
        path = os.path.join(staging_dir, part_file(p))
        if not os.path.exists(path):
            continue
        kvs = read_sst(path)
        st = store.ingest(space_id, p, kvs)
        if not st.ok():
            return st, total
        total += len(kvs)
    # zero files is not an error per host: in a multi-host topology some
    # hosts may own no parts of the dataset — the CLIENT aggregates and
    # the executor errors only if NO host ingested anything
    return Status.OK(), total
