"""Storage RPC request/response types.

Role parity with the reference's `interface/storage.thrift` structs
(GetNeighborsRequest/QueryResponse, AddVerticesRequest, EdgeKey, …):
these dataclasses are the wire contract between the query engine and
storage — the in-proc path passes them directly, the rpc/ layer
serializes them. Per-partition error codes + leader hints ride on every
response exactly like `ResponseCommon.failed_codes`.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..common.status import ErrorCode


@dataclass
class PartResult:
    code: ErrorCode = ErrorCode.SUCCEEDED
    leader: Optional[str] = None  # redirect hint on E_LEADER_CHANGED


@dataclass
class EdgeData:
    """One qualified edge emitted by getBound."""
    src: int
    etype: int          # signed: negative = in-edge (REVERSELY)
    rank: int
    dst: int
    props: Dict[str, Any] = field(default_factory=dict)


@dataclass
class VertexData:
    vid: int
    tag_props: Dict[int, Dict[str, Any]] = field(default_factory=dict)  # tag_id -> props
    edges: List[EdgeData] = field(default_factory=list)


@dataclass
class BoundRequest:
    space_id: int
    # part -> vertex ids owned by that part
    parts: Dict[int, List[int]]
    # signed edge types to expand (negative = reverse); empty = all out-edges
    edge_types: List[int]
    # tag_id -> prop names to return for source vertices ($^ props)
    vertex_props: Dict[int, List[str]] = field(default_factory=dict)
    # edge prop names to return (None = all; applies per edge schema)
    edge_props: Optional[List[str]] = None
    # encoded Expression for storage-side filtering (filter pushdown)
    filter: Optional[bytes] = None
    max_edges_per_vertex: Optional[int] = None


@dataclass
class ScanPartResponse:
    """Columnar snapshot-sync scan of one (part, kind) range — the wire
    form of engine_tpu.csr.ScanCols. Feeds the TPU engine's CSR build
    from remote storaged parts (the storage-seam role the reference
    gives its engine plugins, ref storage/StorageServer.cpp:32-55)."""
    result: PartResult = field(default_factory=PartResult)
    n: int = 0
    keys_blob: bytes = b""
    vals_blob: bytes = b""
    vlens: bytes = b""          # int64[n] little-endian
    klens: bytes = b""          # int64[n] little-endian
    latency_us: int = 0


@dataclass
class BoundResponse:
    results: Dict[int, PartResult] = field(default_factory=dict)  # per part
    vertices: List[VertexData] = field(default_factory=list)
    latency_us: int = 0


@dataclass
class LookupRequest:
    """LOOKUP scan over whole parts (ref role: the storage-side
    LookUpIndexProcessor) — the CPU identity twin of the device
    secondary-index search. The filter is the full encoded WHERE; the
    processor evaluates it per row (bare prop refs bind to the scanned
    schema's row)."""
    space_id: int
    parts: Dict[int, bool]            # part -> unused payload (fanout shape)
    is_edge: bool
    schema_id: int                    # tag_id, or positive edge type
    filter: Optional[bytes] = None    # encoded Expression; None = match all


@dataclass
class LookupRow:
    """One LOOKUP match: vid (tag form) or (src, rank, dst) (edge form),
    plus the matched row's decoded props."""
    vid: int = 0
    src: int = 0
    rank: int = 0
    dst: int = 0
    props: Dict[str, Any] = field(default_factory=dict)


@dataclass
class LookupResponse:
    results: Dict[int, PartResult] = field(default_factory=dict)
    rows: List[LookupRow] = field(default_factory=list)
    latency_us: int = 0


@dataclass
class DeviceWindowRequest:
    """One hop of a graphd scatter/gather-v2 window, served from the
    receiving storaged's LOCAL device shard (storage/device_serve.py)
    instead of a kv row scan. Shape mirrors BoundRequest so the graphd
    row assembly (`executors._emit_go_rows`) is shared verbatim — the
    identity anchor between the cluster device path and the CPU pipe."""
    space_id: int
    # part -> frontier vids owned by that part
    parts: Dict[int, List[int]]
    # signed edge types to expand (negative = reverse); empty = all out
    edge_types: List[int]
    # edge prop names to return (None = all; applies per edge schema)
    edge_props: Optional[List[str]] = None
    max_edges_per_vertex: Optional[int] = None
    # bounded-staleness follower reads (raft_part.read_fence): when
    # armed, a non-leader replica may vouch for a part it replicates
    allow_follower: bool = False
    follower_max_ms: int = 0


@dataclass
class DevicePartResult:
    code: ErrorCode = ErrorCode.SUCCEEDED
    leader: Optional[str] = None   # redirect hint on E_LEADER_CHANGED
    mode: str = ""                 # "leader" | "follower" on success
    # measured served staleness: raft fence staleness (follower) +
    # device-shard staleness (build version behind write version)
    staleness_ms: float = 0.0
    shard_version: int = 0


@dataclass
class DeviceWindowResponse:
    results: Dict[int, DevicePartResult] = field(default_factory=dict)
    vertices: List[VertexData] = field(default_factory=list)
    latency_us: int = 0
    host: str = ""


@dataclass
class NewVertex:
    vid: int
    # tag_id -> encoded row (graphd encodes with RowWriter, like reference)
    tags: List[Tuple[int, bytes]] = field(default_factory=list)


@dataclass
class NewEdge:
    src: int
    etype: int
    rank: int
    dst: int
    row: bytes = b""


@dataclass
class EdgeKey:
    src: int
    etype: int
    rank: int
    dst: int


@dataclass
class ExecResponse:
    results: Dict[int, PartResult] = field(default_factory=dict)
    latency_us: int = 0

    def ok(self) -> bool:
        return all(r.code == ErrorCode.SUCCEEDED for r in self.results.values())


@dataclass
class PropsResponse:
    results: Dict[int, PartResult] = field(default_factory=dict)
    vertices: List[VertexData] = field(default_factory=list)
    edges: List[EdgeData] = field(default_factory=list)
    latency_us: int = 0


@dataclass
class StatDef:
    """One requested aggregate (ref: storage.thrift PropDef.stat +
    StatType:65-69 — SUM=1 COUNT=2 AVG=3)."""
    owner: str          # "tag" | "edge"
    schema_id: int      # tag id or signed edge type
    prop: str           # property name ("" legal for COUNT)
    stat: int           # 1=SUM 2=COUNT 3=AVG


@dataclass
class StatsResponse:
    """Partial aggregates, mergeable across partitions/hosts (ref:
    QueryStatsProcessor::calcResult). sums/counts are parallel to the
    request's StatDef list; AVG is finalized client-side as sum/count."""
    results: Dict[int, PartResult] = field(default_factory=dict)
    sums: List[float] = field(default_factory=list)
    counts: List[int] = field(default_factory=list)
    latency_us: int = 0

    def finalize(self, defs: List["StatDef"]) -> List[Any]:
        out: List[Any] = []
        for i, d in enumerate(defs):
            # an empty fan-out (no vids) returns no partials at all
            s = self.sums[i] if i < len(self.sums) else 0.0
            c = self.counts[i] if i < len(self.counts) else 0
            if d.stat == 2:      # COUNT
                out.append(c)
            elif d.stat == 3:    # AVG
                out.append(s / c if c else None)
            else:                # SUM
                out.append(s)
        return out


@dataclass
class UpdateItemReq:
    prop: str               # field name (optionally tag.prop for vertices)
    value: bytes            # encoded Expression evaluated at the storage side


@dataclass
class UpdateResponse:
    code: ErrorCode = ErrorCode.SUCCEEDED
    leader: Optional[str] = None
    props: Dict[str, Any] = field(default_factory=dict)  # yielded values
    upsert: bool = False
