"""Operational tools (role parity with the reference's src/tools/):

  storage_perf     <- StoragePerfTool (QPS/latency driver)
  integrity_check  <- StorageIntegrityTool (big-linked-list invariant)
  kv_verify        <- SimpleKVVerifyTool (generic KV put/get roundtrip)
  importer         <- tools/importer (CSV -> INSERT statements)
  sst_generator    <- spark-sstfile-generator (offline CSV -> SST files
                      for the DOWNLOAD/INGEST bulk-load path)

Each module exposes a pure function driving client objects (testable
in-process) plus a CLI `main()` that builds networked clients from
--meta / --graph addresses, mirroring how the reference tools take
--meta_server_addrs.
"""
