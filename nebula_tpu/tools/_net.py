"""Shared CLI plumbing: build a networked StorageClient from a metad
address, the same way graphd does (ref: the tools' MetaClient +
StorageClient bootstrap, tools/storage-perf/StoragePerfTool.cpp)."""
from __future__ import annotations

from typing import Tuple

from ..meta.client import MetaClient
from ..meta.schema_manager import SchemaManager
from ..rpc import proxy
from ..storage.client import StorageClient


class _StorageHostMap(dict):
    def __missing__(self, addr: str):
        p = proxy(addr, "storage")
        self[addr] = p
        return p


def storage_client_from_meta(meta_addr: str) -> Tuple[MetaClient, SchemaManager,
                                                      StorageClient]:
    mc = MetaClient(meta_addr, role="tool")
    mc.start(heartbeat=False)
    sm = SchemaManager(mc)
    hosts = _StorageHostMap()

    def refresh_hosts():
        for h in mc.storage_hosts():
            hosts[h]

    refresh_hosts()
    client = StorageClient(sm, hosts=hosts, part_to_host=mc.part_host,
                           refresh_hosts=refresh_hosts)
    return mc, sm, client
