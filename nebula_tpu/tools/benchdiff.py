"""benchdiff: compare two bench JSON artifacts with per-metric
direction + tolerance rules — the perf-trajectory gate (ISSUE 12
satellite; docs/manual/10-observability.md).

Every BENCH_r*/CLUSTER_bench/TENANTS_bench artifact records the same
dotted-path numeric tree; until now the only regression check was
prose in CHANGES.md. benchdiff walks both trees, pairs every numeric
leaf, and judges the gated ones:

    python -m nebula_tpu.tools.benchdiff OLD.json NEW.json
        [--tolerance 0.25] [--json] [--advisory] [--rule PAT=dir ...]

Direction rules match dotted paths by glob-ish patterns (fnmatch on
the full path, case-insensitive); first match wins; unmatched leaves
are reported as informational drift, never gated. Exit status: 0 = no
gated regression, 1 = regression beyond tolerance (unless
--advisory), 2 = usage/IO error.

The verify skill runs this as an advisory step against the committed
baseline artifact — the trajectory is measured, not asserted.
"""
from __future__ import annotations

import argparse
import fnmatch
import json
import sys
from typing import Any, Dict, List, Optional, Tuple

# (pattern, direction): direction "higher" = bigger is better,
# "lower" = smaller is better, "ignore" = never judged (counts,
# configuration echoes, wall clocks of fixed-duration phases).
# First match wins; patterns are matched case-insensitively against
# the full dotted path.
DEFAULT_RULES: Tuple[Tuple[str, str], ...] = (
    # continuous-profiling block (ISSUE 13): the overhead proof's twin
    # QPS numbers are judged like any throughput, but the ratio, the
    # sampler's own bookkeeping, frame/lock/GC/compile tables and the
    # bundle-capture evidence are run-length-dependent diagnostics —
    # advisory drift, never gated
    ("*profile.qps_hz*", "higher"),
    ("*profile.qps_ratio", "ignore"),
    ("*profile.top_share", "ignore"),
    ("*profile.sampler.*", "ignore"),
    ("*profile.top_frames*", "ignore"),
    ("*profile.top_locks*", "ignore"),
    ("*profile.gc.*", "ignore"),
    ("*profile.compiles.*", "ignore"),
    ("*profile_bundle.*", "ignore"),
    # workload & data observatory (ISSUE 14, SKEW_bench.json + the
    # tier-2/3 heat blocks): sketch recall and the Zipf-phase skew
    # index are detection-quality gates judged with the normal
    # tolerance; raw heat counters, the advisory plan internals,
    # hot-part shares and staleness watermarks are run-length- and
    # layout-dependent diagnostics — advisory drift, never gated
    ("*sketch.recall", "higher"),
    ("*skew_index.zipf", "higher"),
    ("*skew_index.*", "ignore"),
    ("*sketch.*", "ignore"),
    ("*advisor.*", "ignore"),
    ("*hot_part.*", "ignore"),
    ("*overhead.ratio", "ignore"),
    ("*heat.*", "ignore"),
    ("*staleness*", "ignore"),
    # consistency observatory (ISSUE 15, CONSISTENCY_bench.json):
    # detection latency is the quality gate (smaller is better);
    # sample/verify tallies, digest echoes, shadow queue state and
    # the drill's fault bookkeeping are run-length-dependent
    # diagnostics — advisory drift, never gated
    ("*detect_s", "lower"),
    ("*shadow.mismatches", "lower"),
    ("*divergence.*", "ignore"),
    ("*shadow.*", "ignore"),
    ("*consistency.*", "ignore"),
    ("*digest*", "ignore"),
    ("*corrupt*", "ignore"),
    # write-path observatory (ISSUE 19, WRITE_bench.json): the
    # ack-to-visible latency, the per-stage/replication p99s and the
    # armed seam cost are the judged before/after numbers for ROADMAP
    # item 2 (group-commit pipelined writes); every tally — stage/
    # exemplar counts, watermark & lifecycle-ledger bookkeeping, ring
    # occupancy, durability-journal sizes, drill evidence — is
    # run-length-dependent diagnostics: advisory drift, never gated
    ("*ack_to_visible_ms.count", "ignore"),
    ("*ack_to_visible_ms.*", "lower"),
    ("*overhead.seam_frac", "lower"),
    ("*overhead.seam_us_per_write", "lower"),
    ("*stages.*.p9*", "lower"),
    ("*stages.*", "ignore"),
    ("*replicated.metrics.*.p9*", "lower"),
    ("*replicated.metrics.*", "ignore"),
    ("*watermark.*", "ignore"),
    ("*overrun.*", "ignore"),
    ("*durability.*", "ignore"),
    ("*profile_write_stages.*", "ignore"),
    ("*replicated.writes", "ignore"),
    # configuration echoes / identifiers / counts: not performance
    ("*.n", "ignore"), ("*.sessions*", "ignore"), ("*.seed", "ignore"),
    ("*graph.*", "ignore"), ("*topology.*", "ignore"),
    ("*.wall_s", "ignore"), ("*.plan", "ignore"),
    ("*batch", "ignore"), ("*.sampled_traces", "ignore"),
    ("*threshold*", "ignore"), ("*bound_ms", "ignore"),
    ("*.ts", "ignore"), ("*phase_s", "ignore"),
    # latencies / waits / impact ratios: smaller is better
    ("*p50*", "lower"), ("*p9*", "lower"), ("*_ms", "lower"),
    ("*_us", "lower"), ("*latency*", "lower"),
    ("*p99_impact*", "lower"), ("*errors*", "lower"),
    ("*overload*", "lower"), ("*fallback*", "lower"),
    # throughputs: bigger is better
    ("*qps*", "higher"), ("*value", "higher"), ("*eps*", "higher"),
    ("*gbs*", "higher"), ("*served*", "higher"),
    ("*explained*", "higher"),
)


def flatten(obj: Any, prefix: str = "",
            out: Optional[Dict[str, float]] = None) -> Dict[str, float]:
    """Numeric leaves of a JSON tree as {dotted.path: value}. Bools
    are skipped (ok flags judge themselves); lists index by position
    only when numeric (bucket vectors)."""
    if out is None:
        out = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            flatten(v, f"{prefix}{k}.", out)
    elif isinstance(obj, list):
        for i, v in enumerate(obj):
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[f"{prefix}{i}"] = float(v)
    elif isinstance(obj, (int, float)) and not isinstance(obj, bool):
        out[prefix[:-1] if prefix.endswith(".") else prefix] = \
            float(obj)
    return out


def direction_of(path: str,
                 rules: Tuple[Tuple[str, str], ...]) -> Optional[str]:
    p = path.lower()
    for pat, d in rules:
        if fnmatch.fnmatch(p, pat):
            return d
    return None


def compare(old: Dict[str, Any], new: Dict[str, Any],
            tolerance: float = 0.25,
            rules: Tuple[Tuple[str, str], ...] = DEFAULT_RULES
            ) -> Dict[str, Any]:
    """-> {"regressions": [...], "improvements": [...],
           "drift": [...], "only_old": [...], "only_new": [...]}.
    A gated metric regresses when it moves against its direction by
    more than `tolerance` (relative; absolute floor of 1e-9 guards
    zero baselines)."""
    fo, fn = flatten(old), flatten(new)
    regressions: List[Dict[str, Any]] = []
    improvements: List[Dict[str, Any]] = []
    drift: List[Dict[str, Any]] = []
    for path in sorted(set(fo) & set(fn)):
        a, b = fo[path], fn[path]
        if a == b:
            continue
        d = direction_of(path, rules)
        rel = (b - a) / abs(a) if abs(a) > 1e-9 else float("inf")
        row = {"path": path, "old": a, "new": b,
               "rel": round(rel, 4) if rel != float("inf") else None,
               "direction": d}
        if d in (None, "ignore"):
            drift.append(row)
            continue
        against = -rel if d == "higher" else rel
        if against > tolerance:
            regressions.append(row)
        elif against < 0:
            improvements.append(row)
        else:
            drift.append(row)
    return {"regressions": regressions, "improvements": improvements,
            "drift": drift,
            "only_old": sorted(set(fo) - set(fn)),
            "only_new": sorted(set(fn) - set(fo)),
            "tolerance": tolerance}


def render_text(result: Dict[str, Any]) -> str:
    lines = []

    def fmt(row):
        rel = row["rel"]
        rel_s = f"{rel * 100:+.1f}%" if rel is not None else "new!=0"
        return (f"  {row['path']}: {row['old']:g} -> {row['new']:g} "
                f"({rel_s}, {row['direction'] or 'unrated'})")

    lines.append(f"benchdiff (tolerance {result['tolerance']:.0%})")
    lines.append(f"REGRESSIONS ({len(result['regressions'])}):")
    lines.extend(fmt(r) for r in result["regressions"])
    lines.append(f"improvements ({len(result['improvements'])}):")
    lines.extend(fmt(r) for r in result["improvements"][:20])
    lines.append(f"drift/unrated ({len(result['drift'])} paths, "
                 f"{len(result['only_old'])} removed, "
                 f"{len(result['only_new'])} added)")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="benchdiff",
        description="compare two bench JSON artifacts; exit 1 on "
                    "regression beyond tolerance")
    ap.add_argument("old")
    ap.add_argument("new")
    ap.add_argument("--tolerance", type=float, default=0.25,
                    help="relative regression tolerance (default 0.25)")
    ap.add_argument("--json", action="store_true",
                    help="machine output instead of text")
    ap.add_argument("--advisory", action="store_true",
                    help="report but always exit 0 (CI advisory mode)")
    ap.add_argument("--rule", action="append", default=[],
                    metavar="PAT=DIR",
                    help="prepend a direction rule (DIR: higher|lower|"
                         "ignore); first match wins")
    args = ap.parse_args(argv)
    rules: List[Tuple[str, str]] = []
    for r in args.rule:
        pat, _, d = r.partition("=")
        if d not in ("higher", "lower", "ignore"):
            print(f"benchdiff: bad --rule {r!r} (DIR must be "
                  f"higher|lower|ignore)", file=sys.stderr)
            return 2
        rules.append((pat.lower(), d))
    try:
        with open(args.old) as f:
            old = json.load(f)
        with open(args.new) as f:
            new = json.load(f)
    except (OSError, ValueError) as e:
        print(f"benchdiff: {e}", file=sys.stderr)
        return 2
    result = compare(old, new, tolerance=args.tolerance,
                     rules=tuple(rules) + DEFAULT_RULES)
    print(json.dumps(result, indent=1) if args.json
          else render_text(result))
    if result["regressions"] and not args.advisory:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
