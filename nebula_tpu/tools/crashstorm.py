"""Crash-storm harness: a real-subprocess replicated topology plus a
client-side durability ledger (docs/manual/12-replication.md, "Crash
recovery & compaction").

`bench.py --crash` and `tools/soak.py --crash` share this machinery:

- **CrashTopology** boots metad + a TPU graphd IN-PROCESS (the parent
  keeps the engine handle for TPU-vs-CPU identity sweeps) and N
  `--replicated` storaged as detached SUBPROCESSES via the
  `scripts/services.py` spawner (`serve_storaged` + per-node
  `--data-dir`s + a shared flagfile), so a `kill -9` is a real SIGKILL
  against a real process that must come back on the SAME data dir.
  Restarts may arm per-process fault plans through `env_extra`
  (`NEBULA_TPU_FAULTS=crashpoint.wal_applied:...`), which is how the
  storm forces a crash exactly between WAL append and engine apply.

- **LedgerWriters** journals every *acknowledged* write into a
  client-side durability ledger: an INSERT only enters the ledger when
  the server said SUCCEEDED, retryable codes (leader moved, overload,
  timeout, consensus-in-flight) are retried client-side and counted,
  and anything else is a hard error. `verify_ledger` then fails the
  run unless every acked edge is readable after recovery — the
  definition of "a kill -9 is a non-event".
"""
from __future__ import annotations

import importlib.util
import json
import os
import random
import signal
import socket
import threading
import time
import urllib.request
from typing import Any, Dict, List, Optional, Set, Tuple

from ..common.status import ErrorCode

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

# client-side retry contract: these codes mean "the cluster is
# reconfiguring, re-issue"; everything else is a non-retryable client
# error and fails the storm
RETRYABLE = {ErrorCode.E_LEADER_CHANGED, ErrorCode.E_OVERLOAD,
             ErrorCode.E_TIMEOUT, ErrorCode.E_CONSENSUS_ERROR}

_services_mod = None


def services():
    """scripts/services.py loaded as a module (it is a CLI script, not
    a package member) — the daemon spawner the storm reuses."""
    global _services_mod
    if _services_mod is None:
        path = os.path.join(REPO, "scripts", "services.py")
        spec = importlib.util.spec_from_file_location(
            "nebula_tpu_services", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _services_mod = mod
    return _services_mod


# Listener ports are drawn BELOW the kernel's ephemeral range
# (32768+ by default): a crash-restarted storaged must re-bind the
# SAME port, and an ephemeral-range port can meanwhile be grabbed as
# the *source* port of any outbound connection on the box (raft peer
# dials, RPC pool reconnects — exactly what a crash storm generates),
# turning the re-bind into a flaky EADDRINUSE.
_PORT_LO, _PORT_HI = 21000, 29000
_port_rng = random.Random()


def _probe(*ports: int) -> bool:
    socks = []
    try:
        for p in ports:
            s = socket.socket()
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            socks.append(s)
            s.bind(("127.0.0.1", p))
        return True
    except OSError:
        return False
    finally:
        for s in socks:
            s.close()


def _free_port_pair() -> int:
    """A port p with p+1 also free — storaged binds raft on port+1."""
    for _ in range(512):
        p = _port_rng.randrange(_PORT_LO, _PORT_HI, 2)
        if _probe(p, p + 1):
            return p
    raise RuntimeError("no adjacent free port pair")


def _free_port() -> int:
    for _ in range(512):
        p = _port_rng.randrange(_PORT_LO, _PORT_HI)
        if _probe(p):
            return p
    raise RuntimeError("no free port")


class StoragedProc:
    def __init__(self, idx: int, port: int, ws_port: int, data_dir: str):
        self.idx = idx
        self.name = f"storaged{idx}"
        self.port = port
        self.ws_port = ws_port
        self.data_dir = data_dir
        self.pid: Optional[int] = None
        self.restarts = 0

    @property
    def addr(self) -> str:
        return f"127.0.0.1:{self.port}"


class CrashTopology:
    """metad + graphd(TPU) in-process, N replicated storaged
    subprocesses on fixed ports and per-node data dirs."""

    def __init__(self, run_dir: str, n: int = 3,
                 flag_overrides: Optional[Dict[str, Any]] = None,
                 tpu_engine=None, boot_timeout: float = 45.0):
        from ..daemons import serve_graphd, serve_metad
        self.run_dir = run_dir
        os.makedirs(run_dir, exist_ok=True)
        # a harness killed by SIGTERM (CI `timeout`) must still reach
        # its finally/stop() — otherwise the detached storaged fleet
        # outlives it and starves every later run on the box
        if threading.current_thread() is threading.main_thread():
            prev = signal.getsignal(signal.SIGTERM)

            def _term(signum, frame):
                if callable(prev) and prev not in (
                        signal.SIG_IGN, signal.SIG_DFL):
                    prev(signum, frame)
                raise SystemExit(143)

            signal.signal(signal.SIGTERM, _term)
        # the subprocess flagfile: fast raft + the compaction knobs the
        # storm asserts against (callers override per scenario)
        flags: Dict[str, Any] = {
            "heartbeat_interval_secs": 1,
            "raft_heartbeat_ms": 60,
            "raft_election_timeout_ms": 250,
            "wal_compact_interval_secs": 1.0,
            "wal_compact_lag": 300,
            "wal_file_size": 32768,
        }
        flags.update(flag_overrides or {})
        self.flags = flags
        self.flagfile = os.path.join(run_dir, "storaged.flags")
        with open(self.flagfile, "w") as f:
            for k, v in flags.items():
                f.write(f"--{k}={v}\n")
        self.metad = serve_metad()
        self.nodes: List[StoragedProc] = []
        for i in range(n):
            self.nodes.append(StoragedProc(
                i, _free_port_pair(), _free_port(),
                os.path.join(run_dir, f"s{i}")))
        for i in range(n):
            self.spawn(i)
        self.wait_registered(timeout=boot_timeout)
        self.tpu = tpu_engine
        self.graphd = serve_graphd(self.metad.addr, tpu_engine=tpu_engine)

    # ------------------------------------------------------ lifecycle
    def spawn(self, i: int, env_extra: Optional[Dict[str, str]] = None
              ) -> StoragedProc:
        node = self.nodes[i]
        argv = ["--meta", self.metad.addr, "--host", "127.0.0.1",
                "--port", str(node.port), "--ws-port", str(node.ws_port),
                "--replicated", "--data-dir", node.data_dir,
                "--cluster-id-file",
                os.path.join(node.data_dir, "cluster.id"),
                "--flagfile", self.flagfile]
        os.makedirs(node.data_dir, exist_ok=True)
        node.pid = services().spawn_daemon(
            self.run_dir, node.name, "nebula_tpu.daemons.storaged",
            argv, env_extra=env_extra)
        return node

    def _reap(self, pid: int, block: bool = False) -> bool:
        """True once the child is reaped (i.e. definitely dead). A
        SIGKILLed child stays a signalable zombie until waited."""
        try:
            done, _ = os.waitpid(pid, 0 if block else os.WNOHANG)
            return done == pid
        except ChildProcessError:
            return True

    def sigkill(self, i: int) -> None:
        node = self.nodes[i]
        if node.pid is None:
            return
        try:
            os.kill(node.pid, signal.SIGKILL)
        except OSError:
            pass
        self._reap(node.pid, block=True)
        node.pid = None

    def wait_exit(self, i: int, timeout: float = 60.0) -> bool:
        """Wait for the process to die ON ITS OWN (crashpoint aborts);
        True when it exited within the timeout."""
        node = self.nodes[i]
        if node.pid is None:
            return True
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._reap(node.pid):
                node.pid = None
                return True
            time.sleep(0.1)
        return False

    def restart(self, i: int,
                env_extra: Optional[Dict[str, str]] = None
                ) -> StoragedProc:
        node = self.nodes[i]
        assert node.pid is None, f"{node.name} still running"
        node.restarts += 1
        return self.spawn(i, env_extra=env_extra)

    def stop(self) -> None:
        try:
            if getattr(self, "graphd", None) is not None:
                self.graphd.stop()
        except Exception:
            pass
        for node in self.nodes:
            if node.pid is not None:
                try:
                    os.kill(node.pid, signal.SIGKILL)
                except OSError:
                    pass
                self._reap(node.pid, block=True)
                node.pid = None
        try:
            self.metad.stop()
        except Exception:
            pass

    # ----------------------------------------------------- inspection
    def http_json(self, i: int, path: str, timeout: float = 3.0) -> Any:
        url = f"http://127.0.0.1:{self.nodes[i].ws_port}{path}"
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.loads(r.read().decode())

    def _log_tail(self, i: int, n: int = 8) -> str:
        try:
            with open(os.path.join(self.run_dir,
                                   f"{self.nodes[i].name}.log")) as f:
                return " | ".join(f.read().splitlines()[-n:])
        except OSError:
            return "<no log>"

    def raft_parts(self, i: int) -> List[dict]:
        try:
            return self.http_json(i, "/raft").get("parts", [])
        except Exception:
            return []

    def flight_events(self, i: int, kind: Optional[str] = None
                      ) -> List[dict]:
        try:
            evs = self.http_json(i, "/flight?limit=400")["events"]
        except Exception:
            return []
        return [e for e in evs if kind is None or e.get("kind") == kind]

    def wait_registered(self, timeout: float = 45.0) -> None:
        want = {n.addr for n in self.nodes if n.pid is not None}
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            have = {h.host for h in self.metad.meta.active_hosts("storage")}
            if want <= have:
                return
            time.sleep(0.2)
        raise AssertionError(
            f"storaged fleet never registered: want {want}, "
            f"have {[h.host for h in self.metad.meta.active_hosts()]}")

    def wait_recovered(self, i: int, sid: int, nparts: int,
                       timeout: float = 60.0) -> List[dict]:
        """Block until the (re)started node serves /raft with all
        `nparts` parts of space `sid` bound, every boot WAL tail fully
        re-applied (wal_replay_done), and commitment caught up to the
        fleet within a small slack. Returns the final /raft parts."""
        deadline = time.monotonic() + timeout
        last: List[dict] = []
        node = self.nodes[i]
        while time.monotonic() < deadline:
            if node.pid is not None and self._reap(node.pid):
                node.pid = None
                raise AssertionError(
                    f"{node.name} died during recovery: "
                    f"{self._log_tail(i)}")
            parts = [p for p in self.raft_parts(i) if p["space"] == sid]
            last = parts
            if len(parts) >= nparts and \
                    all(p["wal_replay_done"] for p in parts):
                # caught up? compare against the max committed seen
                # anywhere (writers may still be appending)
                peers_max: Dict[int, int] = {}
                for j, other in enumerate(self.nodes):
                    if other.pid is None:
                        continue
                    for p in self.raft_parts(j):
                        if p["space"] == sid:
                            peers_max[p["part"]] = max(
                                peers_max.get(p["part"], 0),
                                p["committed"])
                mine = {p["part"]: p["committed"] for p in parts}
                if all(peers_max.get(pt, 0) - mine.get(pt, 0) <= 64
                       for pt in peers_max):
                    return parts
            time.sleep(0.25)
        raise AssertionError(
            f"{self.nodes[i].name} never recovered: {last}")

    def wait_leaders(self, sid: int, nparts: int,
                     timeout: float = 30.0) -> Dict[int, int]:
        """{part: node_idx of leader} once every part has exactly one
        leader among live nodes."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            leaders: Dict[int, List[int]] = {}
            for j, node in enumerate(self.nodes):
                if node.pid is None:
                    continue
                for p in self.raft_parts(j):
                    if p["space"] == sid and p["role"] == "LEADER":
                        leaders.setdefault(p["part"], []).append(j)
            if len(leaders) >= nparts and \
                    all(len(v) == 1 for v in leaders.values()):
                return {pt: v[0] for pt, v in leaders.items()}
            time.sleep(0.15)
        raise AssertionError(f"no stable leader set for space {sid}")

    def leader_counts(self, sid: int) -> Dict[int, int]:
        out = {j: 0 for j, n in enumerate(self.nodes) if n.pid is not None}
        for j in list(out):
            for p in self.raft_parts(j):
                if p["space"] == sid and p["role"] == "LEADER":
                    out[j] += 1
        return out

    def wal_spans(self, sid: int) -> List[int]:
        """last-first WAL span per live part replica — the disk/replay
        bound the compaction task enforces."""
        spans = []
        for j, node in enumerate(self.nodes):
            if node.pid is None:
                continue
            for p in self.raft_parts(j):
                if p["space"] == sid:
                    spans.append(p["last_log_id"]
                                 - max(p["wal_first_log_id"] - 1, 0))
        return spans


# ---------------------------------------------------------------------------
# graph load + durability ledger
# ---------------------------------------------------------------------------

def load_person_knows(gc, space: str, parts: int, v: int, e: int,
                      seed: int, replica_factor: int = 3,
                      settle_s: float = 30.0):
    """Schema + batch-INSERT a random person/knows graph; the first
    INSERT retries for `settle_s` while raft elections finish. Returns
    (srcs, dsts, ts) for query seeding."""
    rng = random.Random(seed)
    srcs = [rng.randrange(v) for _ in range(e)]
    dsts = [rng.randrange(v) for _ in range(e)]
    ts = [(srcs[j] + dsts[j]) % 100000 for j in range(e)]
    gc.must(f"CREATE SPACE {space}(partition_num={parts}, "
            f"replica_factor={replica_factor})")
    gc.must(f"USE {space}")
    gc.must("CREATE TAG person(age int)")
    gc.must("CREATE EDGE knows(ts int)")
    B = 400
    first = True
    for i in range(0, v, B):
        stmt = "INSERT VERTEX person(age) VALUES " + ", ".join(
            f"{j}:({20 + j % 60})" for j in range(i, min(i + B, v)))
        if first:
            deadline = time.time() + settle_s
            while True:
                r = gc.execute(stmt)
                if r.ok() or time.time() >= deadline:
                    break
                time.sleep(0.25)
            assert r.ok(), r.error_msg
            first = False
        else:
            gc.must(stmt)
    for i in range(0, e, B):
        gc.must("INSERT EDGE knows(ts) VALUES " + ", ".join(
            f"{srcs[j]} -> {dsts[j]}@{j}:({ts[j]})"
            for j in range(i, min(i + B, e))))
    return srcs, dsts, ts


class LedgerWriters:
    """Closed-loop INSERT writers journaling every ACKED write. Edge
    identity: rank = 10^6*(w+1)+seq is writer-unique, ts =
    10^7*(w+1)+seq is globally unique, so (dst, ts) alone identifies a
    write when read back through GO."""

    def __init__(self, graphd_addr: str, space: str, v: int,
                 n_writers: int = 2, pace_s: float = 0.008,
                 retry_budget_s: float = 25.0):
        self.addr = graphd_addr
        self.space = space
        self.v = v
        self.pace_s = pace_s
        self.retry_budget_s = retry_budget_s
        self.ledger: List[Tuple[int, int, int, int]] = []  # a,b,rank,ts
        self.errors: List[Tuple[str, str]] = []            # stmt, msg
        self.retried = 0
        self.unacked = 0        # submitted, never acked (crash window)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._pause = threading.Event()
        self._busy = [False] * n_writers
        # nlint: disable=NL002 -- load-origin storm writers; no inbound
        # trace to propagate
        self._threads = [threading.Thread(target=self._run, args=(w,),
                                          daemon=True,
                                          name=f"crash-writer-{w}")
                         for w in range(n_writers)]

    def start(self):
        for t in self._threads:
            t.start()
        return self

    def pause(self):
        self._pause.set()

    def quiesce(self, timeout: float = 30.0) -> bool:
        """Pause AND wait until no write is in flight — identity
        verifies must not race a statement that was already submitted
        (a mid-retry write can land seconds later, between a TPU read
        and its CPU twin). True when fully drained."""
        self._pause.set()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if not any(self._busy):
                return True
            time.sleep(0.02)
        return False

    def resume(self):
        self._pause.clear()

    def stop(self, timeout: float = 60.0):
        self._stop.set()
        self._pause.clear()
        for t in self._threads:
            t.join(timeout=timeout)

    def _run(self, w: int) -> None:
        from ..client import GraphClient
        rng = random.Random(5200 + w)
        c = GraphClient(self.addr).connect()
        c.must(f"USE {self.space}")
        seq = 0
        while not self._stop.is_set():
            if self._pause.is_set():
                time.sleep(0.02)
                continue
            a = rng.randrange(self.v)
            b = rng.randrange(self.v)
            rank = 1_000_000 * (w + 1) + seq
            ts = 10_000_000 * (w + 1) + seq
            stmt = (f"INSERT EDGE knows(ts) VALUES "
                    f"{a} -> {b}@{rank}:({ts})")
            self._busy[w] = True
            if self._pause.is_set():
                # a quiesce() raced the pause check at loop top: with
                # busy now visible, re-check — either we abort here or
                # quiesce sees the flag and waits the write out; no
                # interleaving lets a write slip between a verifier's
                # paired reads
                self._busy[w] = False
                continue
            try:
                acked = self._exec_retry(c, stmt)
            finally:
                self._busy[w] = False
            if acked:
                with self._lock:
                    self.ledger.append((a, b, rank, ts))
            else:
                with self._lock:
                    self.unacked += 1
            seq += 1
            time.sleep(self.pace_s)

    def _exec_retry(self, c, stmt: str) -> bool:
        deadline = time.monotonic() + self.retry_budget_s
        attempt = 0
        while True:
            r = c.execute(stmt)
            if r.ok():
                return True
            if r.code in RETRYABLE and time.monotonic() < deadline:
                with self._lock:
                    self.retried += 1
                attempt += 1
                time.sleep(min(0.05 * (2 ** min(attempt, 5)), 1.0)
                           * (0.5 + random.random() * 0.5))
                continue
            if r.code in RETRYABLE:
                # budget exhausted on a retryable code: the write is
                # UNACKED, not a contract violation — the ledger just
                # never records it
                return False
            with self._lock:
                self.errors.append((stmt, f"{r.code}: {r.error_msg}"))
            return False

    # ------------------------------------------------------ verification
    def verify_ledger(self, gc) -> List[Tuple[int, Tuple[int, int]]]:
        """Every acked write must be readable: for each source vertex,
        GO over knows and check the acked (dst, ts) pairs all appear.
        Returns the missing pairs (empty == durable)."""
        with self._lock:
            entries = list(self.ledger)
        by_src: Dict[int, Set[Tuple[int, int]]] = {}
        for a, b, rank, ts in entries:
            by_src.setdefault(a, set()).add((b, ts))
        missing: List[Tuple[int, Tuple[int, int]]] = []
        for a, want in sorted(by_src.items()):
            r = gc.must(f"GO FROM {a} OVER knows "
                        f"YIELD knows._dst, knows.ts")
            got = {(int(row[0]), int(row[1])) for row in r.rows}
            for pair in want - got:
                missing.append((a, pair))
        return missing

    def summary(self) -> dict:
        with self._lock:
            return {"acked": len(self.ledger),
                    "unacked": self.unacked,
                    "retried": self.retried,
                    "errors": len(self.errors),
                    "error_samples": self.errors[:5]}
