"""Randomized CPU/TPU result-identity fuzzer.

The north-star property (BASELINE.json: "identical result sets") gets
hand-written identity matrices in tests/; this tool SEARCHES for
counterexamples instead: random property graphs, random mutations, and
random nGQL (GO with steps/UPTO/REVERSELY/BIDIRECT, WHERE trees over
int/double/string/tag props, YIELD mixes, pipes with $- refs, FIND
SHORTEST/ALL/NOLOOP PATH) executed against a device-engine cluster and
a CPU-only cluster built from the same statement stream.

    python -m nebula_tpu.tools.identity_fuzz --rounds 200 --seed 3

Any divergence prints the reproducing statement stream and exits 1.
"""
from __future__ import annotations

import argparse
import json
import random
from typing import List


def _build_graph(rnd: random.Random, n_v: int, n_e: int) -> List[str]:
    stmts = [
        "CREATE SPACE fz(partition_num=3)",
        "USE fz",
        "CREATE TAG person(age int, name string)",
        "CREATE TAG city(pop int)",
        "CREATE EDGE knows(w int, s string)",
        "CREATE EDGE likes(score double)",
    ]
    vrows = ", ".join(f'{i}:({rnd.randrange(18, 80)}, "p{i % 13}")'
                      for i in range(n_v))
    stmts.append(f"INSERT VERTEX person(age, name) VALUES {vrows}")
    # a second tag on a subset (vertices can carry several tags; some
    # sources/dests will lack a referenced tag -> EvalError paths)
    crows = ", ".join(f"{i}:({i * 10})" for i in range(0, n_v, 3))
    stmts.append(f"INSERT VERTEX city(pop) VALUES {crows}")
    krows = []
    lrows = []
    for _ in range(n_e):
        s, d = rnd.randrange(n_v), rnd.randrange(n_v)
        if rnd.random() < 0.7:
            krows.append(f'{s} -> {d}:({rnd.randrange(100)}, '
                         f'"t{rnd.randrange(5)}")')
        else:
            lrows.append(f"{s} -> {d}:({rnd.uniform(0, 10):.3f})")
    if krows:
        stmts.append("INSERT EDGE knows(w, s) VALUES " + ", ".join(krows))
    if lrows:
        stmts.append("INSERT EDGE likes(score) VALUES " + ", ".join(lrows))
    return stmts


def _rand_filter(rnd: random.Random, edge: str,
                 alters: List[int] = ()) -> str:
    # post-ALTER fields get their own heavily-weighted branch: buried
    # as one uniform leaf among nine they would essentially never run,
    # and the missing-prop/EvalError machinery they exercise is the
    # highest-risk identity surface
    if edge == "knows" and alters and rnd.random() < 0.35:
        zi = rnd.choice(alters)
        z = (f"knows.z{zi} {rnd.choice(['>', '!=', '=='])} "
             f"{rnd.randrange(50)}")
        if rnd.random() < 0.4:
            return f"{z} {rnd.choice(['&&', '||'])} knows.w > "                    f"{rnd.randrange(100)}"
        return z
    leaves = []
    if edge == "knows":
        leaves += [f"knows.w {rnd.choice(['>', '<', '>=', '==', '!='])} "
                   f"{rnd.randrange(100)}",
                   f'knows.s == "t{rnd.randrange(6)}"',
                   f'knows.s != "t{rnd.randrange(6)}"',
                   f"knows.w % {rnd.randrange(2, 7)} == "
                   f"{rnd.randrange(3)}"]
    else:
        leaves += [f"likes.score {rnd.choice(['>', '<'])} "
                   f"{rnd.uniform(0, 10):.2f}"]
    leaves += [f"$^.person.age {rnd.choice(['>', '<'])} "
               f"{rnd.randrange(18, 80)}",
               f"$$.person.age {rnd.choice(['>', '<='])} "
               f"{rnd.randrange(18, 80)}",
               f"$^.city.pop > {rnd.randrange(0, 500)}",
               # most vertices lack `city`: pop reads as the schema
               # default 0 (ref getDefaultProp semantics) — both the
               # >-side (drops) and the <=-side (keeps) must agree
               f"$$.city.pop {rnd.choice(['>', '<='])} "
               f"{rnd.randrange(0, 500)}",
               "$$.city.pop == 0",
               "!($$.person.age > 50)"]
    a = rnd.choice(leaves)
    if rnd.random() < 0.5:
        b = rnd.choice(leaves)
        return f"{a} {rnd.choice(['&&', '||'])} {b}"
    return a


def _rand_query(rnd: random.Random, n_v: int,
                alters: List[int] = ()) -> str:
    kind = rnd.random()
    seeds = ", ".join(str(rnd.randrange(n_v))
                      for _ in range(rnd.choice([1, 1, 2, 3])))
    if kind < 0.6:
        edge = rnd.choice(["knows", "knows", "likes"])
        steps = rnd.choice(["", "2 STEPS ", "3 STEPS ", "UPTO 2 STEPS "])
        direction = rnd.choice(["", "", " REVERSELY", " BIDIRECT"])
        where = ""
        if rnd.random() < 0.7:
            where = f" WHERE {_rand_filter(rnd, edge, alters)}"
        yields = rnd.choice([
            "", f" YIELD {edge}._dst, {edge}._src",
            f" YIELD {edge}._dst AS d, $^.person.name",
            f" YIELD DISTINCT {edge}._dst",
            f" YIELD {edge}._dst, $$.person.age",
            # city is on a vertex subset: default-fill YIELD cells
            f" YIELD {edge}._dst, $$.city.pop, $^.city.pop"])
        return f"GO {steps}FROM {seeds} OVER {edge}{direction}{where}{yields}"
    if kind < 0.72:   # pipe with $- back-reference
        cut = rnd.randrange(100)
        return (f"GO FROM {seeds} OVER knows YIELD knows._dst AS id, "
                f"knows.w AS w | GO FROM $-.id OVER knows "
                f"WHERE knows.w > {cut} YIELD $-.w AS base, knows._dst")
    if kind < 0.85:   # aggregation pipes (device reduction pushdown)
        steps = rnd.choice(["", "2 STEPS "])
        where = ""
        if rnd.random() < 0.4:
            where = f" WHERE {_rand_filter(rnd, 'knows', alters)}"
        if rnd.random() < 0.5:
            return (f"GO {steps}FROM {seeds} OVER knows{where} "
                    f"YIELD knows.w AS w | YIELD COUNT(*) AS n, "
                    f"SUM($-.w) AS s, AVG($-.w) AS a, MIN($-.w) AS lo, "
                    f"MAX($-.w) AS hi")
        return (f"GO {steps}FROM {seeds} OVER knows{where} "
                f"YIELD knows._dst AS d, knows.w AS w | GROUP BY $-.d "
                f"YIELD $-.d AS d, COUNT(*) AS n, SUM($-.w) AS s")
    form = rnd.choice(["SHORTEST", "ALL", "NOLOOP"])
    a, b = rnd.randrange(n_v), rnd.randrange(n_v)
    k = rnd.choice([3, 4]) if form != "ALL" else 3
    return f"FIND {form} PATH FROM {a} TO {b} OVER knows UPTO {k} STEPS"


def _rand_mutation(rnd: random.Random, n_v: int, fresh: List[int],
                   alters: List[int]) -> str:
    r = rnd.random()
    # disjoint ranges: the z-INSERT branch must be reachable while
    # ALTERs are still landing, or z-filters would only ever see the
    # all-missing case instead of mixed present/missing rows
    if r < 0.15 and len(alters) < 3:
        # schema evolution mid-stream: old rows now lack the new field
        # (missing -> EvalError semantics), new rows carry it
        zi = len(alters) + 1
        alters.append(zi)
        return f"ALTER EDGE knows ADD (z{zi} int)"
    if r < 0.28 and alters:
        zi = rnd.choice(alters)
        s, d = rnd.randrange(n_v), rnd.randrange(n_v)
        cols = "w, s" + "".join(f", z{j}" for j in alters if j <= zi)
        vals = (f"{rnd.randrange(100)}, \"t{rnd.randrange(5)}\""
                + "".join(f", {rnd.randrange(50)}"
                          for j in alters if j <= zi))
        return f"INSERT EDGE knows({cols}) VALUES {s} -> {d}:({vals})"
    if r < 0.35:
        s, d = rnd.randrange(n_v), rnd.randrange(n_v)
        return (f"INSERT EDGE knows(w, s) VALUES {s} -> {d}:"
                f'({rnd.randrange(100)}, "t{rnd.randrange(5)}")')
    if r < 0.5:
        vid = n_v + len(fresh)
        fresh.append(vid)
        return (f"INSERT VERTEX person(age, name) VALUES "
                f'{vid}:({rnd.randrange(18, 80)}, "new")')
    if r < 0.6 and fresh:
        vid = fresh[rnd.randrange(len(fresh))]
        return (f"INSERT EDGE knows(w, s) VALUES "
                f'{rnd.randrange(n_v)} -> {vid}:(7, "t1")')
    if r < 0.72:
        # prop patch through the CAS path (UPSERT creates when absent)
        s, d = rnd.randrange(n_v), rnd.randrange(n_v)
        verb = rnd.choice(["UPDATE", "UPSERT"])
        return (f"{verb} EDGE {s} -> {d} OF knows "
                f"SET w = {rnd.randrange(100)}")
    if r < 0.82:
        vid = rnd.randrange(n_v)
        verb = rnd.choice(["UPDATE", "UPSERT"])
        return (f"{verb} VERTEX {vid} SET "
                f"person.age = {rnd.randrange(18, 80)}")
    if r < 0.9:
        return f"DELETE VERTEX {rnd.randrange(n_v)}"
    s, d = rnd.randrange(n_v), rnd.randrange(n_v)
    return f"DELETE EDGE knows {s} -> {d}"


def run_fuzz(rounds: int = 100, seed: int = 0, n_v: int = 120,
             n_e: int = 700, mutate_every: int = 7,
             sparse_budget: int = None, progress=None) -> dict:
    from ..cluster import InProcCluster
    from ..engine_tpu import TpuGraphEngine

    rnd = random.Random(seed)
    stmts = _build_graph(rnd, n_v, n_e)
    tpu = TpuGraphEngine()
    if sparse_budget is not None:
        tpu.sparse_edge_budget = sparse_budget   # 0: non-empty frontiers go dense
    conns = []
    for cluster in (InProcCluster(), InProcCluster(tpu_engine=tpu)):
        c = cluster.connect()
        for s in stmts:
            c.must(s)
        conns.append(c)
    cpu, dev = conns
    history: List[str] = []
    fresh: List[int] = []
    alters: List[int] = []
    checked = 0
    failed_mutations = 0   # identical-failure mutations still lose
                           # coverage; surface the count
    for i in range(rounds):
        if mutate_every and i and i % mutate_every == 0:
            m = _rand_mutation(rnd, n_v, fresh, alters)
            history.append(m)
            # mutations may legitimately fail (UPDATE of a missing
            # edge) — the two engines must fail IDENTICALLY
            mc, mt = cpu.execute(m), dev.execute(m)
            if mc.code.name != "SUCCEEDED":
                failed_mutations += 1
            if mc.code != mt.code:
                return {"ok": False, "at": i, "query": m,
                        "cpu_code": mc.code.name,
                        "tpu_code": mt.code.name,
                        "cpu_rows": [], "tpu_rows": [],
                        "history": history}
            continue
        q = _rand_query(rnd, n_v, alters)
        history.append(q)
        rc = cpu.execute(q)
        rt = dev.execute(q)
        if rc.code != rt.code or (
                rc.code.name == "SUCCEEDED"
                and sorted(map(repr, rc.rows)) != sorted(map(repr,
                                                             rt.rows))):
            return {"ok": False, "at": i, "query": q,
                    "cpu_code": rc.code.name, "tpu_code": rt.code.name,
                    "cpu_rows": sorted(map(repr, rc.rows or []))[:10],
                    "tpu_rows": sorted(map(repr, rt.rows or []))[:10],
                    "history": history}
        checked += 1
        if progress and checked % 50 == 0:
            progress(checked)
    return {"ok": True, "rounds": rounds, "queries_checked": checked,
            "mutations": len(history) - checked,
            "failed_mutations": failed_mutations, "seed": seed,
            "served": {k: tpu.stats[k] for k in
                       ("go_served", "path_served", "sparse_served",
                        "agg_served", "fallbacks",
                        "host_filter_vectorized")}}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="randomized CPU/TPU result-identity fuzzer")
    ap.add_argument("--rounds", type=int, default=200)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--vertices", type=int, default=120)
    ap.add_argument("--edges", type=int, default=700)
    ap.add_argument("--sparse-budget", type=int, default=None,
                    help="override the pull budget (0 sends every GO with "
                         "a non-empty frontier through the dense "
                         "device dispatch)")
    args = ap.parse_args(argv)
    out = run_fuzz(args.rounds, args.seed, args.vertices, args.edges,
                   sparse_budget=args.sparse_budget,
                   progress=lambda n: print(f"  ... {n} queries checked",
                                            flush=True))
    print(json.dumps(out if out["ok"] else
                     {k: v for k, v in out.items() if k != "history"}))
    if not out["ok"]:
        print("REPRO STATEMENT STREAM:")
        for s in out["history"]:
            print("   ", s)
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
