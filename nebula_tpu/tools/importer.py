"""CSV importer (role parity: the reference's Java tools/importer —
CSV files + a mapping config -> batched INSERT statements through the
graph service).

Mapping format (JSON, modeled on the Spark generator's mapping.json):

    {
      "space": "nba",
      "vertices": [{"file": "players.csv", "tag": "player",
                    "vid_col": "id", "props": ["name", "age"]}],
      "edges":    [{"file": "likes.csv", "edge": "like",
                    "src_col": "src", "dst_col": "dst",
                    "rank_col": null, "props": ["likeness"]}]
    }

CSV files need a header row. Property values are typed from the live
schema (DESCRIBE TAG/EDGE), so strings are quoted and numerics are not.
`execute` is any callable stmt -> ExecutionResponse (a GraphClient's
.execute or an in-proc Connection's)."""
from __future__ import annotations

import argparse
import csv
import json
from typing import Any, Callable, Dict, List


def _schema_types(execute: Callable, kind: str, name: str) -> Dict[str, str]:
    resp = execute(f"DESCRIBE {kind} {name}")
    if not resp.ok():
        raise RuntimeError(f"DESCRIBE {kind} {name} failed: {resp.error_msg}")
    return {row[0]: row[1] for row in resp.rows}


def _lit(value: str, typ: str) -> str:
    if typ in ("int", "timestamp"):
        return str(int(value))
    if typ == "double":
        return str(float(value))
    if typ == "bool":
        return "true" if value.strip().lower() in ("1", "true", "yes") else "false"
    return '"' + str(value).replace("\\", "\\\\").replace('"', '\\"') + '"'


def import_csv(execute: Callable, mapping: Dict[str, Any],
               base_dir: str = ".", batch: int = 256) -> Dict[str, int]:
    """Run the import; returns {"vertices": n, "edges": n}."""
    use = execute(f"USE {mapping['space']}")
    if not use.ok():
        raise RuntimeError(f"USE {mapping['space']} failed: {use.error_msg}")
    import os
    counts = {"vertices": 0, "edges": 0}

    def flush(stmt_prefix: str, values: List[str]):
        if not values:
            return
        resp = execute(stmt_prefix + ", ".join(values))
        if not resp.ok():
            raise RuntimeError(f"insert failed: {resp.error_msg}")

    for vm in mapping.get("vertices", []):
        types = _schema_types(execute, "TAG", vm["tag"])
        props = vm["props"]
        prefix = f"INSERT VERTEX {vm['tag']}({', '.join(props)}) VALUES "
        pending: List[str] = []
        with open(os.path.join(base_dir, vm["file"]), newline="") as f:
            for row in csv.DictReader(f):
                vals = ", ".join(_lit(row[p], types.get(p, "string"))
                                 for p in props)
                pending.append(f"{int(row[vm['vid_col']])}:({vals})")
                counts["vertices"] += 1
                if len(pending) >= batch:
                    flush(prefix, pending)
                    pending = []
        flush(prefix, pending)

    for em in mapping.get("edges", []):
        types = _schema_types(execute, "EDGE", em["edge"])
        props = em["props"]
        prefix = f"INSERT EDGE {em['edge']}({', '.join(props)}) VALUES "
        pending = []
        with open(os.path.join(base_dir, em["file"]), newline="") as f:
            for row in csv.DictReader(f):
                vals = ", ".join(_lit(row[p], types.get(p, "string"))
                                 for p in props)
                rank = ""
                if em.get("rank_col"):
                    rank = f"@{int(row[em['rank_col']])}"
                pending.append(
                    f"{int(row[em['src_col']])}->{int(row[em['dst_col']])}"
                    f"{rank}:({vals})")
                counts["edges"] += 1
                if len(pending) >= batch:
                    flush(prefix, pending)
                    pending = []
        flush(prefix, pending)
    return counts


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="CSV importer")
    ap.add_argument("--graph", required=True, help="graphd host:port")
    ap.add_argument("--mapping", required=True, help="mapping.json path")
    ap.add_argument("--base-dir", default=None,
                    help="dir containing CSVs (default: the mapping "
                         "file's directory)")
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--user", default="root")
    ap.add_argument("--password", default="")
    args = ap.parse_args(argv)

    import os
    from ..client import GraphClient
    with GraphClient(args.graph).connect(args.user, args.password) as gc:
        with open(args.mapping) as f:
            mapping = json.load(f)
        base = args.base_dir or os.path.dirname(os.path.abspath(args.mapping))
        counts = import_csv(gc.execute, mapping, base_dir=base,
                            batch=args.batch)
        print(json.dumps(counts))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
