"""Storage integrity check (role parity: tools/storage-perf/
StorageIntegrityTool.cpp — HBase "IntegrationTestBigLinkedList" style).

Writes width*height vertices forming one big circle where each vertex's
single int property points at the next vertex, then traverses from the
first vertex and verifies the walk returns home in exactly width*height
steps — any lost or corrupted write breaks the circle.

The walk additionally folds every (vid -> next) hop it observes through
the consistency observatory's shared hashing authority (common/
consistency.py — the same fold the online per-part digests use) and
compares against the digest of what was WRITTEN: a corrupted property
that still happens to close the circle (e.g. a swapped pair) is caught
by the content digest even when the step count looks right."""
from __future__ import annotations

import argparse
from typing import Any, Dict

from ..codec.row import RowWriter
from ..common import consistency
from ..storage.types import NewVertex


def _hop_digest(pairs) -> int:
    """Order-independent digest over (vid, next_vid) hops via the one
    shared authority — used for both the written and observed sides."""
    return consistency.digest_items(
        (str(vid).encode(), str(nxt).encode()) for vid, nxt in pairs)


def prepare_data(client, sm, space_id: int, tag_id: int, prop: str,
                 width: int, height: int, first_vid: int = 1,
                 batch: int = 512) -> None:
    """Insert the circle: vid i -> i+1, last -> first (ref:
    StorageIntegrityTool prepareData's matrix walk)."""
    schema = sm.tag_schema(space_id, tag_id).value()
    n = width * height
    pending = []
    for i in range(n):
        vid = first_vid + i
        nxt = first_vid + ((i + 1) % n)
        row = RowWriter(schema).set(prop, nxt).encode()
        pending.append(NewVertex(vid, [(tag_id, row)]))
        if len(pending) >= batch:
            if not client.add_vertices(space_id, pending).ok():
                raise RuntimeError(f"insert failed near vid {vid}")
            pending = []
    if pending and not client.add_vertices(space_id, pending).ok():
        raise RuntimeError("final insert batch failed")


def validate(client, sm, space_id: int, tag_id: int, prop: str,
             start_vid: int, expected_steps: int,
             expected_digest=None) -> Dict[str, Any]:
    """Walk the circle from start_vid; OK iff we return to start in
    exactly expected_steps hops AND (when the writer's digest is
    known) the observed hop digest matches it. The chain is sequential
    pointer chasing, so it is one get_vertex_props RPC per hop, exactly
    like the reference's traversal loop."""
    cur = start_vid
    steps = 0
    observed = 0
    while steps < expected_steps:
        resp = client.get_vertex_props(space_id, [cur], [tag_id])
        nxt = None
        for vd in resp.vertices:
            if vd.vid == cur and tag_id in vd.tag_props:
                nxt = vd.tag_props[tag_id].get(prop)
        if nxt is None:
            return {"ok": False, "steps": steps, "broken_at": cur,
                    "reason": "missing vertex or property"}
        observed = consistency.fold_add(
            observed, consistency.kv_hash(str(cur).encode(),
                                          str(nxt).encode()))
        cur = nxt
        steps += 1
        if cur == start_vid:
            break
    ok = (cur == start_vid and steps == expected_steps)
    out = {"ok": ok, "steps": steps,
           "observed_digest": consistency.hex_digest(observed),
           "reason": None if ok else
           f"walk closed after {steps} steps, expected {expected_steps}"}
    if expected_digest is not None:
        match = observed == expected_digest
        out["written_digest"] = consistency.hex_digest(expected_digest)
        out["digests_equal"] = match
        if not match:
            out["ok"] = False
            out["reason"] = out["reason"] or \
                "content digest diverged from what was written"
    return out


def run_integrity(client, sm, space_id: int, tag_id: int, prop: str,
                  width: int, height: int, first_vid: int = 1) -> Dict[str, Any]:
    prepare_data(client, sm, space_id, tag_id, prop, width, height, first_vid)
    n = width * height
    written = _hop_digest(
        (first_vid + i, first_vid + ((i + 1) % n)) for i in range(n))
    return validate(client, sm, space_id, tag_id, prop, first_vid, n,
                    expected_digest=written)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="storage integrity tool")
    ap.add_argument("--meta", required=True, help="metad host:port")
    ap.add_argument("--space", required=True)
    ap.add_argument("--tag", default="test_tag")
    ap.add_argument("--prop", default="test_prop")
    ap.add_argument("--width", type=int, default=100)
    ap.add_argument("--height", type=int, default=100)
    ap.add_argument("--first-vid", type=int, default=1)
    args = ap.parse_args(argv)

    from ._net import storage_client_from_meta
    mc, sm, client = storage_client_from_meta(args.meta)
    try:
        space_id = mc.get_space(args.space).value().space_id
        tag_id = sm.tag_id(space_id, args.tag)
        if tag_id is None:
            print(f"tag {args.tag!r} not found")
            return 1
        out = run_integrity(client, sm, space_id, tag_id, args.prop,
                            args.width, args.height, args.first_vid)
        import json
        print(json.dumps(out))
        return 0 if out["ok"] else 1
    finally:
        mc.stop()


if __name__ == "__main__":
    raise SystemExit(main())
