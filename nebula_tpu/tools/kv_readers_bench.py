"""Concurrent-reader microbench for the native LSM engine.

The round-2 verdict flagged the old engine's single mutex (zero read
parallelism). The LSM read path takes a SHARED lock; this driver
measures aggregate get() throughput at 1..N reader threads (ctypes
releases the GIL inside native calls, so threads overlap in the
engine even from Python).

Usage: python -m nebula_tpu.tools.kv_readers_bench [n_keys]
"""
import struct
import sys
import threading
import time

from ..kvstore.nativeengine import NativeEngine


def main(argv=None):
    n_keys = int((argv or sys.argv[1:] or [200_000])[0])
    e = NativeEngine()
    rows = b"".join(struct.pack("<I", 8) + b"k%07d" % i
                    + struct.pack("<I", 8) + b"v" * 8
                    for i in range(n_keys))
    st = e.ingest_packed(rows, n_keys)
    assert st.ok(), st
    keys = [b"k%07d" % (i * 37 % n_keys) for i in range(4096)]

    # batched gets (multi_get = one native call per 4096 keys): the GIL
    # releases for the whole batch, so reader threads genuinely overlap
    # inside the engine's shared-lock read path — per-call gets would
    # measure Python call overhead, not engine concurrency
    from ..native import usable_cpus
    cores = usable_cpus()
    print(f"usable cores: {cores}" + (
        " — NOTE: thread scaling cannot show on a single-core "
        "affinity; numbers below measure overhead, not concurrency"
        if cores == 1 else ""))
    batch = keys            # exactly one 4096-key batch per call
    for threads in (1, 2, 4, 8):
        stop = threading.Event()
        counts = [0] * threads

        def reader(slot):
            i = 0
            while not stop.is_set():
                e.multi_get(batch)
                i += len(batch)
                counts[slot] = i

        # nlint: disable=NL002 -- load-origin bench workers; there is
        # no inbound trace to carry
        ts = [threading.Thread(target=reader, args=(i,),
                               name=f"kvbench-reader-{i}")
              for i in range(threads)]
        t0 = time.time()
        for t in ts:
            t.start()
        time.sleep(1.0)
        stop.set()
        for t in ts:
            t.join()
        dt = time.time() - t0
        total = sum(counts)
        print(f"{threads} reader(s): {total/dt:,.0f} gets/s aggregate")
    e.close()


if __name__ == "__main__":
    main()
