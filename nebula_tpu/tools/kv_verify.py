"""Generic-KV roundtrip verification (role parity: tools/simple-kv-verify
/SimpleKVVerifyTool.cpp): put N random key/values through the storage
generic KV API, read them all back, compare.

The comparison runs through the consistency observatory's shared
hashing authority (common/consistency.py kv_hash/fold_add — the SAME
implementation the online per-part digests, shadow reads and snapshot
audit fold), so the offline checker and the online observatory can
never diverge on what "identical content" means."""
from __future__ import annotations

import argparse
import random
from typing import Any, Dict

from ..common import consistency


def run_kv_verify(client, space_id: int, count: int = 1000,
                  value_size: int = 64, seed: int = 0) -> Dict[str, Any]:
    rng = random.Random(seed)
    kvs = []
    for i in range(count):
        k = f"kv_verify_{seed}_{i}".encode()
        v = bytes(rng.randrange(256) for _ in range(value_size))
        kvs.append((k, v))
    st = client.kv_put(space_id, kvs)
    if not st.ok():
        return {"ok": False, "reason": f"put failed: {st.msg}"}
    # fold what we WROTE and what we READ BACK through the one shared
    # digest; per-key mismatches are still counted for the report
    written = consistency.digest_items(kvs)
    read_back = 0
    mismatches = 0
    for k, v in kvs:
        r = client.kv_get(space_id, k)
        got = r.value() if r.ok() else b"\x00<missing>"
        read_back = consistency.fold_add(
            read_back, consistency.kv_hash(k, got))
        if not r.ok() or got != v:
            mismatches += 1
    digests_equal = read_back == written
    return {"ok": mismatches == 0 and digests_equal, "count": count,
            "mismatches": mismatches,
            "written_digest": consistency.hex_digest(written),
            "read_digest": consistency.hex_digest(read_back),
            "digests_equal": digests_equal}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="simple KV verify tool")
    ap.add_argument("--meta", required=True, help="metad host:port")
    ap.add_argument("--space", required=True)
    ap.add_argument("--count", type=int, default=1000)
    ap.add_argument("--value-size", type=int, default=64)
    args = ap.parse_args(argv)

    from ._net import storage_client_from_meta
    mc, sm, client = storage_client_from_meta(args.meta)
    try:
        space_id = mc.get_space(args.space).value().space_id
        out = run_kv_verify(client, space_id, args.count, args.value_size)
        import json
        print(json.dumps(out))
        return 0 if out["ok"] else 1
    finally:
        mc.stop()


if __name__ == "__main__":
    raise SystemExit(main())
