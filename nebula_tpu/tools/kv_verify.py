"""Generic-KV roundtrip verification (role parity: tools/simple-kv-verify
/SimpleKVVerifyTool.cpp): put N random key/values through the storage
generic KV API, read them all back, compare."""
from __future__ import annotations

import argparse
import random
from typing import Any, Dict


def run_kv_verify(client, space_id: int, count: int = 1000,
                  value_size: int = 64, seed: int = 0) -> Dict[str, Any]:
    rng = random.Random(seed)
    kvs = []
    for i in range(count):
        k = f"kv_verify_{seed}_{i}".encode()
        v = bytes(rng.randrange(256) for _ in range(value_size))
        kvs.append((k, v))
    st = client.kv_put(space_id, kvs)
    if not st.ok():
        return {"ok": False, "reason": f"put failed: {st.msg}"}
    mismatches = 0
    for k, v in kvs:
        r = client.kv_get(space_id, k)
        if not r.ok() or r.value() != v:
            mismatches += 1
    return {"ok": mismatches == 0, "count": count, "mismatches": mismatches}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="simple KV verify tool")
    ap.add_argument("--meta", required=True, help="metad host:port")
    ap.add_argument("--space", required=True)
    ap.add_argument("--count", type=int, default=1000)
    ap.add_argument("--value-size", type=int, default=64)
    args = ap.parse_args(argv)

    from ._net import storage_client_from_meta
    mc, sm, client = storage_client_from_meta(args.meta)
    try:
        space_id = mc.get_space(args.space).value().space_id
        out = run_kv_verify(client, space_id, args.count, args.value_size)
        import json
        print(json.dumps(out))
        return 0 if out["ok"] else 1
    finally:
        mc.stop()


if __name__ == "__main__":
    raise SystemExit(main())
