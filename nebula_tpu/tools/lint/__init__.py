"""nebula-lint: invariant-enforcing static analysis for this repo.

Eight PRs of review-hardening notes in CHANGES.md are a hand-maintained
invariant catalog — locks that must not be held across device launches,
threads that must carry trace context, counters that must declare a
kind, fault points that must be registered and documented, a frozen
wire spec. This package machine-checks those invariants with stdlib
`ast` (no third-party deps), so a refactor cannot silently regress
them (docs/manual/15-static-analysis.md).

Usage:
    python -m nebula_tpu.tools.lint                # text report, exit 1 on findings
    python -m nebula_tpu.tools.lint --json         # machine-readable
    python -m nebula_tpu.tools.lint --update-baseline

The companion RUNTIME check — the lock-order witness that records the
cross-thread lock acquisition graph and fails on cycles — lives in
`nebula_tpu.common.lockwitness`.
"""
from .core import (Finding, Project, load_baseline, run_lint,  # noqa: F401
                   write_baseline)
from .rules import RULES  # noqa: F401
