"""CLI: `python -m nebula_tpu.tools.lint` (docs/manual/15-static-analysis.md).

Exit status: 0 when every finding is inline-suppressed or baselined,
1 when new findings exist, 2 on usage errors. `--update-baseline`
rewrites the committed baseline from the current findings and exits 0.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from .core import (BASELINE_NAME, Project, load_baseline, run_lint,
                   split_baseline, write_baseline)
from .rules import RULES


def _default_root() -> str:
    # nebula_tpu/tools/lint/__main__.py -> repo root three levels up
    here = os.path.dirname(os.path.abspath(__file__))
    root = os.path.dirname(os.path.dirname(os.path.dirname(here)))
    return root if os.path.isdir(os.path.join(root, "nebula_tpu")) \
        else os.getcwd()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m nebula_tpu.tools.lint",
        description="nebula-lint: repo-specific invariant checks "
                    "NL001-NL007")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to scan (default: nebula_tpu/, "
                         "scripts/, bench.py, __graft_entry__.py)")
    ap.add_argument("--root", default=_default_root(),
                    help="repo root (baseline + docs anchors)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default <root>/{BASELINE_NAME})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report grandfathered findings too")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from current findings")
    ap.add_argument("--select", default=None,
                    help="comma-separated rule codes to run "
                         "(e.g. NL001,NL004)")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for code in sorted(RULES):
            r = RULES[code]
            print(f"{code}  {r.title}")
        return 0

    select = None
    if args.select:
        select = [c.strip() for c in args.select.split(",") if c.strip()]
        unknown = [c for c in select if c not in RULES]
        if unknown:
            print(f"unknown rule code(s): {', '.join(unknown)}",
                  file=sys.stderr)
            return 2

    project = Project(args.root, args.paths or None)
    findings, n_suppressed = run_lint(project, RULES, select)

    baseline_path = args.baseline or os.path.join(args.root, BASELINE_NAME)
    if args.update_baseline:
        write_baseline(baseline_path, findings)
        print(f"nebula-lint: baseline written to {baseline_path} "
              f"({len(findings)} finding(s))")
        return 0

    baseline = {} if args.no_baseline else load_baseline(baseline_path)
    new, grandfathered = split_baseline(findings, baseline)

    if args.as_json:
        print(json.dumps({
            "files_scanned": len(project.files),
            "rules": sorted(RULES),
            "findings": [f.to_dict() for f in new],
            "grandfathered": [f.to_dict() for f in grandfathered],
            "suppressed": n_suppressed,
        }, indent=1))
        return 1 if new else 0

    for f in new:
        print(f.render())
    status = "FAIL" if new else "OK"
    print(f"nebula-lint: {status} — {len(new)} finding(s), "
          f"{len(grandfathered)} baselined, {n_suppressed} suppressed "
          f"inline, {len(project.files)} files, "
          f"{len(select or RULES)} rules")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
