"""Rule engine for nebula-lint: file model, suppressions, baseline.

The engine is deliberately small: a `Project` parses every scanned
file once (stdlib `ast`), rules are plain functions `Project ->
[Finding]` registered under a stable NLxxx code, and two escape
hatches exist for findings that are intentional or grandfathered:

- inline suppression on the finding's line (or the line above):
      x = risky()   # nlint: disable=NL001 -- reason why this is safe
  A reason after `--` is required policy for this repo (the lint
  itself only enforces the grammar; review enforces the reason).
- a committed baseline file (`.nlint-baseline.json`) keyed by
  (rule, file, enclosing qualname, message) — line-number drift does
  not invalidate entries, real changes to the finding do.
"""
from __future__ import annotations

import ast
import json
import os
import re
from typing import Callable, Dict, Iterable, List, Optional, Set, Tuple

_SUPPRESS_RE = re.compile(
    r"#\s*nlint:\s*disable(?P<file>-file)?\s*=\s*"
    r"(?P<codes>NL\d{3}(?:\s*,\s*NL\d{3})*)")

# default scan roots, relative to the repo root
DEFAULT_SCAN = ("nebula_tpu", "scripts", "bench.py", "__graft_entry__.py")
SKIP_DIRS = {"__pycache__", ".git", ".claude", "node_modules"}


class Finding:
    """One rule violation at one site."""

    __slots__ = ("rule", "path", "line", "col", "message", "context")

    def __init__(self, rule: str, path: str, line: int, col: int,
                 message: str, context: str = ""):
        self.rule = rule
        self.path = path          # repo-relative, forward slashes
        self.line = int(line)
        self.col = int(col)
        self.message = message
        self.context = context    # enclosing def/class qualname

    def key(self) -> str:
        """Line-independent identity used by the baseline."""
        return f"{self.rule}|{self.path}|{self.context}|{self.message}"

    def render(self) -> str:
        where = f"{self.path}:{self.line}:{self.col}"
        ctx = f" [{self.context}]" if self.context else ""
        return f"{where}: {self.rule} {self.message}{ctx}"

    def to_dict(self) -> Dict[str, object]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "context": self.context}


class SourceFile:
    """One parsed file: AST, qualname map, inline suppressions."""

    def __init__(self, root: str, path: str):
        self.abspath = path
        self.rel = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path, encoding="utf-8", errors="replace") as f:
            self.text = f.read()
        self.tree: Optional[ast.AST] = None
        self.syntax_error: Optional[str] = None
        try:
            self.tree = ast.parse(self.text, filename=self.rel)
        except SyntaxError as e:
            self.syntax_error = f"{e.msg} (line {e.lineno})"
        self.line_suppressions: Dict[int, Set[str]] = {}
        self.file_suppressions: Set[str] = set()
        self._comment_lines: Set[int] = set()
        for i, line in enumerate(self.text.splitlines(), 1):
            if line.lstrip().startswith("#"):
                self._comment_lines.add(i)
            if "nlint" not in line:
                continue
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            codes = {c.strip() for c in m.group("codes").split(",")}
            if m.group("file"):
                self.file_suppressions |= codes
            else:
                self.line_suppressions.setdefault(i, set()).update(codes)
        self._qualnames: Optional[Dict[ast.AST, str]] = None
        self._parents: Optional[Dict[ast.AST, ast.AST]] = None

    # ------------------------------------------------------------- maps
    def qualnames(self) -> Dict[ast.AST, str]:
        """node -> enclosing `Class.method`-style qualname (the node's
        own name for def/class nodes)."""
        if self._qualnames is None:
            self._qualnames = {}
            if self.tree is not None:
                self._walk_qual(self.tree, "")
        return self._qualnames

    def _walk_qual(self, node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                q = f"{prefix}.{child.name}" if prefix else child.name
                self._qualnames[child] = q
                self._walk_qual(child, q)
            else:
                if prefix:
                    self._qualnames[child] = prefix
                self._walk_qual(child, prefix)

    def parents(self) -> Dict[ast.AST, ast.AST]:
        if self._parents is None:
            self._parents = {}
            if self.tree is not None:
                for node in ast.walk(self.tree):
                    for child in ast.iter_child_nodes(node):
                        self._parents[child] = node
        return self._parents

    def qualname_at(self, node: ast.AST) -> str:
        return self.qualnames().get(node, "")

    def suppressed(self, finding: Finding) -> bool:
        """Suppressed by a marker on the finding's line or anywhere in
        the contiguous comment block directly above it (reasons often
        wrap to several comment lines)."""
        if finding.rule in self.file_suppressions:
            return True
        if finding.rule in self.line_suppressions.get(finding.line, ()):
            return True
        line = finding.line - 1
        while line in self._comment_lines:
            if finding.rule in self.line_suppressions.get(line, ()):
                return True
            line -= 1
        return False


class Project:
    """All scanned files plus repo-level resources rules may consult."""

    def __init__(self, root: str, paths: Optional[Iterable[str]] = None):
        self.root = os.path.abspath(root)
        self.files: List[SourceFile] = []
        for p in self._discover(paths or DEFAULT_SCAN):
            self.files.append(SourceFile(self.root, p))
        self.files.sort(key=lambda f: f.rel)

    def _discover(self, paths: Iterable[str]) -> List[str]:
        out: List[str] = []
        for p in paths:
            full = p if os.path.isabs(p) else os.path.join(self.root, p)
            if os.path.isfile(full) and full.endswith(".py"):
                out.append(full)
            elif os.path.isdir(full):
                for dirpath, dirnames, filenames in os.walk(full):
                    dirnames[:] = [d for d in dirnames
                                   if d not in SKIP_DIRS]
                    for fn in sorted(filenames):
                        if fn.endswith(".py"):
                            out.append(os.path.join(dirpath, fn))
        return out

    def read_text(self, rel: str) -> Optional[str]:
        """A non-scanned repo file (docs, specs); None when absent."""
        full = os.path.join(self.root, rel)
        try:
            with open(full, encoding="utf-8", errors="replace") as f:
                return f.read()
        except OSError:
            return None

    def read_json(self, rel: str):
        text = self.read_text(rel)
        if text is None:
            return None
        try:
            return json.loads(text)
        except ValueError:
            return None


# ---------------------------------------------------------------------------
# AST helpers shared by rules
# ---------------------------------------------------------------------------

def dotted(node: ast.AST) -> Optional[str]:
    """`self._lock` / `threading.Thread` -> dotted string, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def last_segment(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def import_map(tree: ast.AST) -> Dict[str, str]:
    """Local name -> fully qualified imported name for top-level (and
    nested) imports: `import numpy as np` -> {np: numpy}; `from time
    import sleep` -> {sleep: time.sleep}."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

BASELINE_NAME = ".nlint-baseline.json"


def load_baseline(path: str) -> Dict[str, int]:
    """Baseline file -> multiset of finding keys (key -> count)."""
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except OSError:
        return {}
    out: Dict[str, int] = {}
    for e in data.get("findings", []):
        k = f"{e['rule']}|{e['path']}|{e.get('context', '')}|{e['message']}"
        out[k] = out.get(k, 0) + 1
    return out


def write_baseline(path: str, findings: List[Finding]) -> None:
    data = {
        "comment": "nebula-lint grandfathered findings; regenerate with "
                   "`python -m nebula_tpu.tools.lint --update-baseline`. "
                   "Entries are line-independent: (rule, path, context, "
                   "message). Policy: NEW code never lands baseline "
                   "entries — fix the finding or inline-suppress with a "
                   "reason (docs/manual/15-static-analysis.md).",
        "findings": [{"rule": f.rule, "path": f.path,
                      "context": f.context, "message": f.message}
                     for f in sorted(findings, key=lambda f: f.key())],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=False)
        f.write("\n")


def split_baseline(findings: List[Finding], baseline: Dict[str, int]
                   ) -> Tuple[List[Finding], List[Finding]]:
    """-> (new findings, grandfathered findings). The baseline is a
    multiset: N entries absorb at most N identical findings."""
    budget = dict(baseline)
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        k = f.key()
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            old.append(f)
        else:
            new.append(f)
    return new, old


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------

def run_lint(project: Project,
             rules: Dict[str, "object"],
             select: Optional[Iterable[str]] = None
             ) -> Tuple[List[Finding], int]:
    """Run rules over the project. Returns (findings after inline
    suppressions, count of inline-suppressed findings). Baseline
    filtering is the caller's concern (CLI / tier-1 test)."""
    by_rel = {f.rel: f for f in project.files}
    selected = set(select) if select else None
    raw: List[Finding] = []
    for code in sorted(rules):
        if selected is not None and code not in selected:
            continue
        raw.extend(rules[code].check(project))
    for f in project.files:
        if f.syntax_error:
            raw.append(Finding("NL000", f.rel, 1, 0,
                               f"syntax error: {f.syntax_error}"))
    kept: List[Finding] = []
    n_suppressed = 0
    for fd in raw:
        sf = by_rel.get(fd.path)
        if sf is not None and sf.suppressed(fd):
            n_suppressed += 1
        else:
            kept.append(fd)
    kept.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return kept, n_suppressed


class Rule:
    """A registered rule: stable code, one-line title, check fn."""

    def __init__(self, code: str, title: str,
                 fn: Callable[[Project], List[Finding]]):
        self.code = code
        self.title = title
        self.fn = fn
        self.doc = (fn.__doc__ or "").strip()

    def check(self, project: Project) -> List[Finding]:
        out = []
        for f in self.fn(project):
            assert f.rule == self.code, f"{self.code} emitted {f.rule}"
            out.append(f)
        return out
