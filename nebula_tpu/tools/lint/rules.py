"""NL001-NL008: the rule catalog (docs/manual/15-static-analysis.md).

Every rule encodes an invariant this repo already states in prose
(CHANGES.md review-hardening notes, the manuals); the rule docstrings
cite the source. Rules are AST-only — nothing here imports or executes
repo code, so the lint runs in milliseconds and cannot be confused by
import-time side effects.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import (Finding, Project, Rule, const_str, dotted, import_map,
                   last_segment)

RULES: Dict[str, Rule] = {}


def rule(code: str, title: str):
    def deco(fn):
        RULES[code] = Rule(code, title, fn)
        return fn
    return deco


def _in_package(f) -> bool:
    return f.rel.startswith("nebula_tpu/")


# ---------------------------------------------------------------------------
# NL001 — blocking call under a hot lock
# ---------------------------------------------------------------------------

# names that make a `with <expr>:` subject a lock/condition guard
_LOCK_NAME_RE = re.compile(r"(?:^|_)(?:lock|wlock|vlock|qlock|rlock|"
                           r"mu|mutex|cv|cond)$")


def is_lock_name(name: Optional[str]) -> bool:
    return bool(name) and bool(_LOCK_NAME_RE.search(name.lstrip("_")))


# module-level calls that block: {qualified prefix: why}
_BLOCKING_QUALIFIED = {
    "time.sleep": "sleeps",
    "subprocess.run": "spawns a subprocess",
    "subprocess.Popen": "spawns a subprocess",
    "subprocess.call": "spawns a subprocess",
    "subprocess.check_call": "spawns a subprocess",
    "subprocess.check_output": "spawns a subprocess",
    "jax.device_put": "synchronous device transfer",
    "jax.device_get": "synchronous device fetch",
}
# method names that block regardless of receiver type
_BLOCKING_METHODS = {
    "block_until_ready": "blocks on the device kernel",
    "sendall": "blocking socket send",
    "recv": "blocking socket receive",
    "recvfrom": "blocking socket receive",
    "accept": "blocking socket accept",
}
# numpy fetch: np.asarray/np.array on a device buffer is a synchronous
# D2H copy (CHANGES.md: "the blocking np.asarray fetch happens outside
# the engine lock")
_NUMPY_FETCH = {"asarray", "array"}


@rule("NL001", "blocking call inside a `with <hot-lock>:` body")
def nl001(project: Project) -> List[Finding]:
    """Locks on the serve path are HOT: dispatcher cv, engine snapshot
    lock, stats leaf lock, cache rungs, raft part lock. The degradation
    ladder and the dispatcher's tail latency both assume none of them
    is ever held across a blocking operation — a device launch, a
    blocking `np.asarray` fetch, `time.sleep`, a socket send, a
    subprocess (CHANGES.md PR 1/3/6 hardening notes). `<cv>.wait()` on
    the lock itself is exempt (wait releases); any other blocking call
    under a held lock is a finding. The runtime twin of this rule is
    the lock-order witness's blocked-under-lock event stream."""
    out: List[Finding] = []
    for f in project.files:
        if f.tree is None or not _in_package(f):
            continue
        imports = import_map(f.tree)
        np_aliases = {a for a, m in imports.items() if m == "numpy"}

        def classify(call: ast.Call) -> Optional[str]:
            fn = call.func
            d = dotted(fn)
            if d is not None:
                head = d.split(".")[0]
                full = imports.get(head, head) + d[len(head):]
                for q, why in _BLOCKING_QUALIFIED.items():
                    if full == q:
                        return f"`{d}()` {why}"
                if isinstance(fn, ast.Attribute) and \
                        isinstance(fn.value, ast.Name) and \
                        fn.value.id in np_aliases and \
                        fn.attr in _NUMPY_FETCH:
                    return (f"`{d}()` may be a synchronous "
                            f"device-to-host fetch")
            if isinstance(fn, ast.Attribute) and \
                    fn.attr in _BLOCKING_METHODS:
                return f"`.{fn.attr}()` {_BLOCKING_METHODS[fn.attr]}"
            return None

        def visit(node: ast.AST, held: List[str]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                # a nested def's body runs later, outside this hold
                for child in ast.iter_child_nodes(node):
                    visit(child, [])
                return
            if isinstance(node, ast.With):
                locks = [dotted(item.context_expr) or "<lock>"
                         for item in node.items
                         if is_lock_name(last_segment(item.context_expr))]
                for item in node.items:
                    visit(item.context_expr, held)
                inner = held + locks
                for stmt in node.body:
                    visit(stmt, inner)
                return
            if isinstance(node, ast.Call) and held:
                why = classify(node)
                if why is not None:
                    fn = node.func
                    # cv.wait()/cv.wait_for() on a HELD lock releases it
                    is_wait = (isinstance(fn, ast.Attribute)
                               and fn.attr in ("wait", "wait_for")
                               and dotted(fn.value) in held)
                    if not is_wait:
                        out.append(Finding(
                            "NL001", f.rel, node.lineno, node.col_offset,
                            f"{why} while holding hot lock "
                            f"`{held[-1]}`", f.qualname_at(node)))
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        visit(f.tree, [])
    return out


# ---------------------------------------------------------------------------
# NL002 — raw Thread spawn without trace-context propagation
# ---------------------------------------------------------------------------

@rule("NL002", "Thread() spawn without contextvars.copy_context()")
def nl002(project: Project) -> List[Finding]:
    """ContextVars don't cross threads on their own: a thread spawned
    on a serve/fan-out path while a trace is live records its spans
    into nothing (docs/manual/10-observability.md; the storage client's
    `_submit` shows the required pattern). A `threading.Thread(...)`
    spawn is compliant only when THE SPAWN ITSELF carries the context:
    its target subtree references `copy_context` directly, a name
    bound from `contextvars.copy_context()` in the enclosing scope, or
    a local def whose body does (the `common.threads.traced_thread`
    pattern) — a compliant spawn elsewhere in the same function does
    NOT whitewash a raw one. Long-lived daemon loops that must NOT
    adopt a request's trace (they outlive it) carry an inline
    suppression naming that reason."""

    def _references(tree: ast.AST, ctx_names: set,
                    local_defs: Dict[str, List[ast.AST]],
                    depth: int = 0) -> bool:
        for sub in ast.walk(tree):
            if isinstance(sub, (ast.Name, ast.Attribute)):
                seg = last_segment(sub)
                if seg in ("copy_context", "traced_thread"):
                    return True
                if isinstance(sub, ast.Name) and sub.id in ctx_names:
                    return True
            # target is a local def: its BODY may carry the context
            # (ctx.run inside `run`, the traced_thread helper shape)
            if depth == 0 and isinstance(sub, ast.Name) \
                    and sub.id in local_defs:
                for d in local_defs[sub.id]:
                    if _references(d, ctx_names, local_defs, 1):
                        return True
        return False

    out: List[Finding] = []
    for f in project.files:
        if f.tree is None or not _in_package(f):
            continue
        parents = f.parents()
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if d not in ("threading.Thread", "Thread"):
                continue
            # enclosing function scope (module, if top-level)
            scope: ast.AST = node
            while scope in parents and not isinstance(
                    scope, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Module)):
                scope = parents[scope]
            ctx_names = set()
            local_defs: Dict[str, List[ast.AST]] = {}
            for sub in ast.walk(scope):
                if isinstance(sub, ast.Assign) and \
                        isinstance(sub.value, ast.Call):
                    vd = dotted(sub.value.func) or ""
                    if vd.split(".")[-1] == "copy_context":
                        for tgt in sub.targets:
                            if isinstance(tgt, ast.Name):
                                ctx_names.add(tgt.id)
                elif isinstance(sub, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)) \
                        and sub is not scope:
                    local_defs.setdefault(sub.name, []).append(sub)
            if not _references(node, ctx_names, local_defs):
                out.append(Finding(
                    "NL002", f.rel, node.lineno, node.col_offset,
                    "raw Thread() spawn: target will not carry the "
                    "caller's trace context (wrap with "
                    "contextvars.copy_context().run or "
                    "common.threads.traced_thread)",
                    f.qualname_at(node)))
    return out


# ---------------------------------------------------------------------------
# NL003 — flag declare/get cross-check
# ---------------------------------------------------------------------------

def _is_flags_receiver(fn: ast.AST) -> Optional[str]:
    """`graph_flags.get` / `storage_flags.declare` -> receiver name
    when it looks like a FlagRegistry, else None. A bare `flags` /
    `_flags` receiver is the registry's INTERNAL dict (or the module
    object), not a registry instance — excluded."""
    if not isinstance(fn, ast.Attribute):
        return None
    seg = last_segment(fn.value)
    if seg is None:
        return None
    stripped = seg.lstrip("_")
    if stripped.endswith("flags") and stripped != "flags":
        return seg
    return None


@rule("NL003", "undeclared flag read / dead declared flag")
def nl003(project: Project) -> List[Finding]:
    """Every `flags.get(name)` must have a matching `declare(...)`
    (an undeclared read silently returns the fallback forever — the
    gflags parity contract in common/flags.py), and every declared
    flag must be READ somewhere (a declared-but-never-read flag is
    dead weight that /flags and the meta config registry still
    advertise). A flag consumed via a watcher or flagfile counts as
    read when its name literal appears outside the declare call."""
    declares: Dict[str, List[Tuple[str, int, int, str]]] = {}
    reads: Set[str] = set()
    read_sites: List[Tuple[str, str, int, int, str]] = []
    literal_count: Dict[str, int] = {}

    for f in project.files:
        if f.tree is None:
            continue
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str):
                literal_count[node.value] = \
                    literal_count.get(node.value, 0) + 1
            if not isinstance(node, ast.Call):
                continue
            recv = _is_flags_receiver(node.func)
            if recv is None:
                continue
            method = node.func.attr  # type: ignore[union-attr]
            name = const_str(node.args[0]) if node.args else None
            if name is None:
                continue
            if method == "declare":
                declares.setdefault(name, []).append(
                    (f.rel, node.lineno, node.col_offset,
                     f.qualname_at(node)))
            elif method in ("get", "get_or"):
                reads.add(name)
                read_sites.append((name, f.rel, node.lineno,
                                   node.col_offset, f.qualname_at(node)))

    out: List[Finding] = []
    for name, rel, line, col, ctx in read_sites:
        if name not in declares:
            out.append(Finding(
                "NL003", rel, line, col,
                f"flag {name!r} is read but never declare()d — the "
                f"read silently returns its fallback forever", ctx))
    for name, sites in declares.items():
        if name in reads:
            continue
        # watcher/flagfile-consumed flags: the literal shows up beyond
        # its declare site(s)
        if literal_count.get(name, 0) > len(sites):
            continue
        rel, line, col, ctx = sites[0]
        out.append(Finding(
            "NL003", rel, line, col,
            f"flag {name!r} is declared but never read anywhere "
            f"(dead flag)", ctx))
    return out


# ---------------------------------------------------------------------------
# NL004 — StatsManager.add_value kind consistency
# ---------------------------------------------------------------------------

_NL004_KINDS = ("counter", "timing", "histogram")

# Metric-family kind CONTRACTS by name prefix: every add_value whose
# name starts with (or is an f-string/concat whose constant prefix
# reaches into) one of these families must declare exactly this kind.
# graph.cost.* are the ISSUE-12 per-tenant/per-verb cost rollups —
# dynamic names (f"graph.cost.{space}.{field}") skip the per-name
# conflict check below, so the prefix contract is what keeps a typo'd
# kind from silently registering an untagged (or counter-shaped)
# cost family.
_NL004_FAMILY_KINDS = {
    "graph.cost.": "histogram",
    # continuous-profiling families (common/profiler.py): lock
    # acquire-wait distributions (nebula_lock_wait_us_* on /metrics)
    # and GC pause distributions are contractually native histograms —
    # the strict-OpenMetrics scrape tests and the SLO engine's
    # window_le reads both depend on the bucket series existing
    "lock.wait_us.": "histogram",
    "graph.gc.": "histogram",
    "tpu_engine.compile_us": "histogram",
    # workload & data observatory (ISSUE 14, common/heat.py): the
    # hot-vertex sketch feed counters are monotonic events, and the
    # replica-staleness distribution is contractually a native
    # histogram — the staleness SLO / federation conformance tests
    # read its bucket series (the nebula_part_heat_* and
    # nebula_heat_skew_index_* families are metric-SOURCE gauges, not
    # add_value sites, so they carry no kind tag to pin)
    "heat.": "counter",
    "raftex.staleness_ms": "histogram",
    # consistency observatory (ISSUE 15, common/consistency.py):
    # digest checks/divergence/audit and shadow-read sample/verify/
    # mismatch streams are all monotonic events — counters, so the
    # disarm byte-identity contract (no families until a site fires,
    # plain _total series after) holds uniformly
    "consistency.": "counter",
    "shadow.": "counter",
    # partition & gray-failure tolerance (ISSUE 18): nemesis
    # injections, per-peer transport timeouts/balks, hedge outcomes
    # and health ejections are all monotonic event streams — counters,
    # so the strict-OpenMetrics flatteners expose plain _total twins
    "rpc.nemesis.": "counter",
    "rpc.peer_timeout": "counter",
    "rpc.deadline_balk": "counter",
    "storage_client.hedge.": "counter",
    "storage_client.peer_ejected": "counter",
    "raftex.replicate.": "counter",
    # write-path observatory (ISSUE 19, common/writepath.py): every
    # per-stage write seam and the raft group-commit occupancy series
    # are contractually native histograms (the write bench reads their
    # bucket series + exemplars), the ack/visible/ring event streams
    # and per-event snapshot lifecycle tallies are monotonic counters,
    # and the WAL fsync distribution is the fsync_stall trigger's
    # histogram source
    "write.stage.": "histogram",
    "write.raft.": "histogram",
    "write.ack_to_visible_ms": "histogram",
    "write.acked": "counter",
    "write.visible": "counter",
    "write.ring.": "counter",
    "snapshot.": "counter",
    "wal.fsync_us": "histogram",
}


def _const_prefix(node) -> Optional[str]:
    """Best-effort constant PREFIX of a metric-name expression:
    handles plain constants, f-strings (leading literal), and
    string concatenation ('a.' + x). None when nothing constant
    leads the name."""
    s = const_str(node)
    if s is not None:
        return s
    if isinstance(node, ast.JoinedStr) and node.values:
        first = node.values[0]
        if isinstance(first, ast.Constant) and \
                isinstance(first.value, str):
            return first.value
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        return _const_prefix(node.left)
    return None


@rule("NL004", "add_value kind inconsistent across sites for one metric")
def nl004(project: Project) -> List[Finding]:
    """A metric's kind ("counter" | "timing" | "histogram" | untagged)
    is fixed at FIRST registration (common/stats.py) — when call sites
    disagree, whichever site runs first wins and the snapshot/
    Prometheus shape of the metric becomes load-order-dependent. One
    name, one kind, across every `add_value` site; every site must
    declare one (an untagged metric keeps the legacy emit-everything
    shape — p95 gauges over pure counters are noise on /metrics); and
    the declared kind must be a REAL kind (a typo like "histograms"
    silently registers an untagged metric — histogram-on-counter and
    cousins are exactly the misuse this rule exists to catch)."""
    sites: Dict[str, List[Tuple[Optional[str], str, int, int, str]]] = {}
    out: List[Finding] = []
    for f in project.files:
        if f.tree is None or not _in_package(f):
            continue
        if f.rel == "nebula_tpu/common/stats.py":
            continue      # the registry itself (Duration's generic feed)
        for node in ast.walk(f.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "add_value"):
                continue
            recv = last_segment(node.func.value)
            if recv is None or "stats" not in recv.lstrip("_").lower():
                continue
            name = const_str(node.args[0]) if node.args else None
            kind: Optional[str] = None
            has_kind = False
            if len(node.args) >= 3:
                kind = const_str(node.args[2])
                has_kind = True
            for kw in node.keywords:
                if kw.arg == "kind":
                    kind = const_str(kw.value)
                    has_kind = True
            if not has_kind:
                shown = name if name is not None else "<dynamic>"
                out.append(Finding(
                    "NL004", f.rel, node.lineno, node.col_offset,
                    f"metric {shown!r} reported without a kind tag — "
                    f"declare kind=\"counter\", kind=\"timing\" or "
                    f"kind=\"histogram\" so the snapshot/Prometheus "
                    f"shape is explicit", f.qualname_at(node)))
            elif kind is not None and kind not in _NL004_KINDS:
                shown = name if name is not None else "<dynamic>"
                out.append(Finding(
                    "NL004", f.rel, node.lineno, node.col_offset,
                    f"metric {shown!r} declares unknown kind {kind!r} "
                    f"— common/stats.py registers it UNTAGGED (legacy "
                    f"emit-everything shape); expected one of "
                    f"{_NL004_KINDS}", f.qualname_at(node)))
            # family-prefix kind contracts (covers DYNAMIC names too:
            # the f-string's constant prefix identifies the family)
            prefix = _const_prefix(node.args[0]) if node.args else None
            if prefix is not None:
                for fam_prefix, want_kind in _NL004_FAMILY_KINDS.items():
                    if prefix.startswith(fam_prefix) and \
                            kind != want_kind:
                        out.append(Finding(
                            "NL004", f.rel, node.lineno,
                            node.col_offset,
                            f"metric family {fam_prefix}* is "
                            f"contractually kind={want_kind!r} but "
                            f"this site declares {kind!r} — the cost "
                            f"rollups must stay native histograms "
                            f"(docs/manual/10-observability.md)",
                            f.qualname_at(node)))
            if name is None:
                continue          # dynamic names: per-family, skip
            sites.setdefault(name, []).append(
                (kind, f.rel, node.lineno, node.col_offset,
                 f.qualname_at(node)))

    for name, ss in sites.items():
        # untagged sites are already reported above; conflict detection
        # runs over the explicitly tagged ones
        tagged = sorted({k for k, *_ in ss if k is not None})
        if len(tagged) <= 1:
            continue
        canonical = tagged[0]
        for kind, rel, line, col, ctx in ss:
            if kind is not None and kind != canonical:
                out.append(Finding(
                    "NL004", rel, line, col,
                    f"metric {name!r} reported here as {kind!r} but as "
                    f"{canonical!r} elsewhere — kind is fixed at first "
                    f"registration, so the metric's shape depends on "
                    f"call order", ctx))
    return out


# ---------------------------------------------------------------------------
# NL005 — fault points: fired => registered => documented
# ---------------------------------------------------------------------------

_FAULT_DOC = "docs/manual/9-robustness.md"


@rule("NL005", "faults.fire() point unregistered or undocumented")
def nl005(project: Project) -> List[Finding]:
    """Chaos plans arm fault points BY NAME; a fired-but-unregistered
    point is invisible in the /faults catalog and un-armable by name
    review, and an undocumented one breaks the docs/manual/
    9-robustness.md contract that the manual lists every injectable
    site (CHANGES.md PR 3)."""
    registered: Set[str] = set()
    fire_sites: List[Tuple[str, str, int, int, str]] = []
    reg_sites: Dict[str, Tuple[str, int, int, str]] = {}
    for f in project.files:
        if f.tree is None:
            continue
        for node in ast.walk(f.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            recv = last_segment(node.func.value)
            if recv is None or "faults" not in recv.lstrip("_").lower():
                continue
            name = const_str(node.args[0]) if node.args else None
            if name is None:
                continue
            if node.func.attr == "register":
                registered.add(name)
                reg_sites.setdefault(
                    name, (f.rel, node.lineno, node.col_offset,
                           f.qualname_at(node)))
            elif node.func.attr == "fire":
                fire_sites.append((name, f.rel, node.lineno,
                                   node.col_offset, f.qualname_at(node)))

    doc = project.read_text(_FAULT_DOC)
    out: List[Finding] = []
    fired_names: Set[str] = set()
    for name, rel, line, col, ctx in fire_sites:
        fired_names.add(name)
        if name not in registered:
            out.append(Finding(
                "NL005", rel, line, col,
                f"fault point {name!r} is fired but never "
                f"register()ed — invisible in the /faults catalog", ctx))
    for name in sorted(fired_names & registered):
        if doc is None or name not in doc:
            rel, line, col, ctx = reg_sites[name]
            out.append(Finding(
                "NL005", rel, line, col,
                f"fault point {name!r} is not listed in "
                f"{_FAULT_DOC}", ctx))
    return out


# ---------------------------------------------------------------------------
# NL006 — jit purity
# ---------------------------------------------------------------------------

_NP_DTYPES = {"int8", "int16", "int32", "int64", "uint8", "uint16",
              "uint32", "uint64", "float16", "float32", "float64",
              "bool_", "dtype", "iinfo", "finfo"}
_HOST_METHODS = {"item", "tolist"}


def _jit_function_nodes(f) -> List[ast.AST]:
    """Function nodes handed to jax.jit / shard_map in this file:
    decorated defs, `jax.jit(fn)` / `shard_map(fn, ...)` on a local
    def, and inline lambdas."""
    tree = f.tree
    defs_by_name: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)

    def is_jit_expr(e: ast.AST) -> bool:
        d = dotted(e)
        if d in ("jax.jit", "jit", "shard_map",
                 "jax.experimental.shard_map.shard_map", "pjit",
                 "jax.pjit"):
            return True
        # partial(jax.jit, ...) / functools.partial(jax.jit, ...)
        if isinstance(e, ast.Call) and \
                dotted(e.func) in ("partial", "functools.partial") and \
                e.args and is_jit_expr(e.args[0]):
            return True
        return False

    jitted: List[ast.AST] = []
    seen: Set[int] = set()

    def add(node: ast.AST) -> None:
        if id(node) not in seen:
            seen.add(id(node))
            jitted.append(node)

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if is_jit_expr(dec):
                    add(node)
        elif isinstance(node, ast.Call) and is_jit_expr(node.func) \
                and node.args:
            target = node.args[0]
            if isinstance(target, ast.Lambda):
                add(target)
            elif isinstance(target, ast.Name):
                for d in defs_by_name.get(target.id, ()):
                    add(d)
    return jitted


@rule("NL006", "host-side operation inside a jit-compiled function")
def nl006(project: Project) -> List[Finding]:
    """Functions handed to `jax.jit`/`shard_map`/the fused program
    builders are traced: host numpy materialization (`np.asarray`),
    `.item()`/`.tolist()`, Python RNG, `print`, clock reads and I/O
    either poison the trace with a hidden synchronization or bake one
    trace-time value into every later execution (docs/manual/
    5-tpu-engine.md; /opt/skills jit guidance)."""
    out: List[Finding] = []
    for f in project.files:
        if f.tree is None or not _in_package(f):
            continue
        imports = import_map(f.tree)
        np_aliases = {a for a, m in imports.items() if m == "numpy"}
        rng_aliases = {a for a, m in imports.items() if m == "random"}
        time_aliases = {a for a, m in imports.items() if m == "time"}
        for fn_node in _jit_function_nodes(f):
            for node in ast.walk(fn_node):
                if node is fn_node or not isinstance(node, ast.Call):
                    continue
                d = dotted(node.func)
                msg = None
                if d == "print":
                    msg = "print() inside a jit-traced function"
                elif d == "open":
                    msg = "file I/O inside a jit-traced function"
                elif isinstance(node.func, ast.Attribute) and \
                        isinstance(node.func.value, ast.Name):
                    head, attr = node.func.value.id, node.func.attr
                    if head in np_aliases and attr not in _NP_DTYPES:
                        msg = (f"host numpy call `{d}()` inside a "
                               f"jit-traced function")
                    elif head in rng_aliases:
                        msg = (f"Python RNG `{d}()` inside a jit-traced "
                               f"function (value freezes at trace time)")
                    elif head in time_aliases:
                        msg = (f"clock read `{d}()` inside a jit-traced "
                               f"function (value freezes at trace time)")
                if msg is None and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _HOST_METHODS and \
                        not node.args:
                    msg = (f"`.{node.func.attr}()` forces a host sync "
                           f"inside a jit-traced function")
                if msg is not None:
                    out.append(Finding(
                        "NL006", f.rel, node.lineno, node.col_offset,
                        msg, f.qualname_at(node)))
    return out


# ---------------------------------------------------------------------------
# NL007 — frozen wire spec conformance
# ---------------------------------------------------------------------------

_WIRE_SPEC = "docs/manual/wire-vectors.json"
_WIRE_MODULE = "nebula_tpu/rpc/wire.py"
_TRANSPORT_MODULE = "nebula_tpu/rpc/transport.py"


def _dataclass_fields(cls: ast.ClassDef) -> Optional[List[str]]:
    """Ordered field names when `cls` is a dataclass, else None."""
    is_dc = False
    for dec in cls.decorator_list:
        d = dotted(dec if not isinstance(dec, ast.Call) else dec.func)
        if d in ("dataclass", "dataclasses.dataclass"):
            is_dc = True
    if not is_dc:
        return None
    fields: List[str] = []
    for stmt in cls.body:
        if isinstance(stmt, ast.AnnAssign) and \
                isinstance(stmt.target, ast.Name):
            ann = dotted(stmt.annotation) or ""
            if isinstance(stmt.annotation, ast.Subscript):
                ann = dotted(stmt.annotation.value) or ""
            if ann.split(".")[-1] == "ClassVar":
                continue
            fields.append(stmt.target.id)
    return fields


def _init_params(cls: ast.ClassDef) -> Optional[List[str]]:
    """Positional `__init__` params after self — the wire field order
    for the plain (non-dataclass) registered classes the codec
    special-cases (Status/StatusOr's hand-rolled encoding)."""
    for stmt in cls.body:
        if isinstance(stmt, ast.FunctionDef) and stmt.name == "__init__":
            return [a.arg for a in stmt.args.args[1:]]
    return None


@rule("NL007", "wire-frozen struct or envelope drifted from v1 spec")
def nl007(project: Project) -> List[Finding]:
    """The v1 wire spec is FROZEN (docs/manual/6-wire-protocol.md):
    registry ids are positional, struct fields encode by declared
    order, the rpc envelope is a 4/5-tuple request and 2/3-tuple
    response. The conformance vectors (docs/manual/wire-vectors.json)
    record that contract; this rule diffs the live dataclasses, the
    `register(...)` order in rpc/wire.py and the envelope tuples in
    rpc/transport.py against it, so an innocent-looking field
    insertion fails lint before it fails every peer."""
    out: List[Finding] = []
    spec = project.read_json(_WIRE_SPEC)
    if not isinstance(spec, dict) or "registry" not in spec:
        out.append(Finding(
            "NL007", _WIRE_MODULE, 1, 0,
            f"wire conformance spec {_WIRE_SPEC} missing or unreadable "
            f"— the frozen v1 registry cannot be checked"))
        return out

    # 1. every registered struct's declared fields match the spec
    classes: Dict[str, List[Tuple[object, "SourceFile"]]] = {}
    for f in project.files:
        if f.tree is None or not _in_package(f):
            continue
        for node in ast.walk(f.tree):
            if isinstance(node, ast.ClassDef):
                classes.setdefault(node.name, []).append((node, f))
    for entry in spec["registry"]:
        name, kind = entry["name"], entry["kind"]
        cands = classes.get(name, [])
        if not cands:
            out.append(Finding(
                "NL007", _WIRE_MODULE, 1, 0,
                f"registered wire type {name!r} (id {entry['id']}) has "
                f"no class definition in the tree"))
            continue
        if kind != "struct":
            continue
        want = entry["fields"]
        matched = False
        candidate_fields: List[Tuple[object, object, List[str]]] = []
        for node, f in cands:
            got = _dataclass_fields(node)
            if got is None:
                got = _init_params(node)
            if got is None:
                continue
            candidate_fields.append((node, f, got))
            if got == want:
                matched = True
        if not matched:
            if candidate_fields:
                node, f, got = candidate_fields[0]
                out.append(Finding(
                    "NL007", f.rel, node.lineno, node.col_offset,
                    f"wire struct {name!r} fields {got} drifted from "
                    f"frozen v1 spec {want} — adding/reordering fields "
                    f"breaks every conformance vector and every peer",
                    name))
            else:
                out.append(Finding(
                    "NL007", _WIRE_MODULE, 1, 0,
                    f"registered wire type {name!r} is not a checkable "
                    f"dataclass anywhere in the tree"))

    # 2. register(...) order in wire.py matches the positional ids
    want_names = [e["name"] for e in spec["registry"]]
    for f in project.files:
        if f.rel != _WIRE_MODULE or f.tree is None:
            continue
        for node in ast.walk(f.tree):
            if not (isinstance(node, ast.FunctionDef)
                    and node.name == "_register_defaults"):
                continue
            got_names: List[str] = []
            reg_node = node
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and \
                        dotted(sub.func) == "register":
                    reg_node = sub
                    for a in sub.args:
                        seg = last_segment(a)
                        if seg:
                            got_names.append(seg)
            if got_names != want_names:
                drift = next((i for i, (a, b) in enumerate(
                    zip(got_names, want_names)) if a != b),
                    min(len(got_names), len(want_names)))
                out.append(Finding(
                    "NL007", f.rel, reg_node.lineno, reg_node.col_offset,
                    f"wire registry order drifted from the frozen v1 "
                    f"spec at id {drift}: got "
                    f"{got_names[drift:drift + 2]}, spec "
                    f"{want_names[drift:drift + 2]} — ids are "
                    f"positional; append new types at the END",
                    "_register_defaults"))

    # 3. envelope arity in transport.py: requests 4/5/6, responses
    # 2/3/4 (v1.1 added the trace context + span fragment; v1.2 the
    # cost flag + ledger fragment — both additive, manual 6 §2)
    for f in project.files:
        if f.rel != _TRANSPORT_MODULE or f.tree is None:
            continue
        for node in ast.walk(f.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "encode"
                    and last_segment(node.func.value) == "wire"
                    and node.args
                    and isinstance(node.args[0], ast.Tuple)):
                continue
            tup = node.args[0]
            arity = len(tup.elts)
            first = tup.elts[0]
            is_resp = isinstance(first, ast.Constant) and \
                isinstance(first.value, bool)
            ok = arity in ((2, 3, 4) if is_resp else (4, 5, 6))
            if not ok:
                shape = "response" if is_resp else "request"
                out.append(Finding(
                    "NL007", f.rel, node.lineno, node.col_offset,
                    f"rpc {shape} envelope arity {arity} violates the "
                    f"frozen wire contract "
                    f"({'2/3/4' if is_resp else '4/5/6'}"
                    f"-tuple; docs/manual/6-wire-protocol.md)",
                    f.qualname_at(node)))
    return out


# ---------------------------------------------------------------------------
# NL008 — thread spawns must carry a stable name
# ---------------------------------------------------------------------------

@rule("NL008", "Thread spawn without a descriptive name=")
def nl008(project: Project) -> List[Finding]:
    """The continuous-profiling observatory (common/profiler.py)
    attributes stack samples and lock-wait blame per thread ROLE —
    the thread's `name=` with digit runs normalized. A spawn without
    `name=` samples as `Thread-N`, which aggregates every anonymous
    background task into one meaningless role and breaks last-holder
    attribution in the /profile?locks=1 table. Every
    `threading.Thread(...)` / `traced_thread(...)` spawn under
    nebula_tpu/ must pass a descriptive `name=` (constant or
    f-string; per-instance digits are fine — roles normalize them)."""
    out: List[Finding] = []
    for f in project.files:
        if f.tree is None or not _in_package(f):
            continue
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if d not in ("threading.Thread", "Thread", "traced_thread",
                         "threads.traced_thread",
                         "common.threads.traced_thread"):
                continue
            if any(kw.arg == "name" for kw in node.keywords):
                continue
            out.append(Finding(
                "NL008", f.rel, node.lineno, node.col_offset,
                f"`{d}(...)` spawn without name= — it samples as "
                f"Thread-N, breaking the profiler's per-role "
                f"attribution (docs/manual/10-observability.md, "
                f"continuous profiling)", f.qualname_at(node)))
    return out
