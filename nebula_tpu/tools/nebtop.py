"""nebtop: a live top-style view of the whole cluster from ONE scrape
(docs/manual/10-observability.md, "Cluster rollup / nebtop").

Reads graphd's `/cluster_metrics` — the federated OpenMetrics document
carrying every daemon's families under instance/role labels — and
renders, per refresh:

  - per-instance liveness (nebula_cluster_scrape), role, uptime
  - cluster QPS + error rate (deltas of nebula_graph_query_total
    between scrapes), p95/p99 latency gauges
  - device utilization proxies (kernel_us avg, fused launches/s,
    dispatcher queue depth + lane occupancy)
  - per-tenant COST rates from the graph.cost.* histogram _sum deltas
    (device us/s, rows scanned/s, rpc bytes/s per space)
  - raft leader distribution (storage.raft.*.is_leader gauges per
    instance) — a skewed leader column is tomorrow's hotspot
  - HOT FRAMES: the continuous profiler's top self-time frames per
    thread role + the top contended locks, pulled from graphd's
    /profile endpoint next to the scrape (ISSUE 13; the panel is
    omitted when the daemon predates /profile)

    python -m nebula_tpu.tools.nebtop --url http://127.0.0.1:13000 \
        [--interval 2.0] [--once] [--json]

`--once` prints a single snapshot (totals, no rates) and exits —
scriptable and testable; the loop mode redraws with ANSI clears.
Parsing is self-contained (sample-line subset) so the tool runs
against any conformant exposition without importing the test parser.
"""
from __future__ import annotations

import argparse
import json
import re
import sys
import time
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

_SAMPLE_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*?\})? "
                        r"(-?[0-9.eE+]+|[+-]?Inf)")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_samples(text: str) -> List[Tuple[str, Dict[str, str], float]]:
    """[(name, labels, value)] for every sample line; comments,
    exemplars and timestamps are ignored (the rollup view needs
    values, not full conformance — tests/openmetrics.py does that)."""
    out = []
    for line in text.split("\n"):
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, lbl, val = m.group(1), m.group(2), m.group(3)
        labels = dict(_LABEL_RE.findall(lbl)) if lbl else {}
        try:
            v = float(val)
        except ValueError:
            continue
        out.append((name, labels, v))
    return out


class Snapshot:
    """One scrape, indexed for the views nebtop renders."""

    def __init__(self, samples: List[Tuple[str, Dict[str, str], float]],
                 t: float):
        self.t = t
        self.samples = samples

    def get(self, name: str, **labels) -> Optional[float]:
        for n, lbl, v in self.samples:
            if n == name and all(lbl.get(k) == w
                                 for k, w in labels.items()):
                return v
        return None

    def sum(self, name: str, **labels) -> float:
        return sum(v for n, lbl, v in self.samples
                   if n == name and all(lbl.get(k) == w
                                        for k, w in labels.items()))

    def by_instance(self, name: str) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for n, lbl, v in self.samples:
            if n == name:
                inst = lbl.get("instance", "?")
                out[inst] = out.get(inst, 0.0) + v
        return out

    def instances(self) -> List[Dict[str, Any]]:
        out = []
        for n, lbl, v in self.samples:
            if n == "nebula_cluster_scrape":
                out.append({"instance": lbl.get("instance", "?"),
                            "role": lbl.get("role", "?"),
                            "up": v >= 1})
        return sorted(out, key=lambda r: (r["role"], r["instance"]))

    def leader_counts(self) -> Dict[str, int]:
        """instance -> parts led (storage.raft.sX.pY.is_leader
        gauges, federated as nebula_storage_raft_*_is_leader)."""
        out: Dict[str, int] = {}
        for n, lbl, v in self.samples:
            if n.startswith("nebula_storage_raft_") and \
                    n.endswith("_is_leader") and v >= 1:
                inst = lbl.get("instance", "?")
                out[inst] = out.get(inst, 0) + 1
        return out

    _HEAT_RE = re.compile(r"^nebula_part_heat_s(\d+)_p(\d+)_"
                          r"(reads|writes|rows_scanned|bytes_returned|"
                          r"device_us|raft_appends|score)$")
    _SKEW_RE = re.compile(r"^nebula_heat_skew_index_s(\d+)$")

    def part_heat(self) -> Dict[str, Any]:
        """The workload-observatory panel inputs: per-(space, part,
        instance) 60s heat fields (nebula_part_heat_* families) and
        the per-space skew indices. Empty when heat is disarmed —
        those families then don't exist at all."""
        parts: Dict[Tuple[int, int, str], Dict[str, float]] = {}
        skew: Dict[str, float] = {}
        for n, lbl, v in self.samples:
            m = self._HEAT_RE.match(n)
            if m:
                key = (int(m.group(1)), int(m.group(2)),
                       lbl.get("instance", "?"))
                parts.setdefault(key, {})[m.group(3)] = v
                continue
            m = self._SKEW_RE.match(n)
            if m:
                skew[m.group(1)] = max(skew.get(m.group(1), 0.0), v)
        return {"parts": parts, "skew": skew}

    def tenant_cost(self) -> Dict[str, Dict[str, float]]:
        """space -> {field: histogram _sum total} from the
        nebula_graph_cost_<space>_<field>_sum families."""
        out: Dict[str, Dict[str, float]] = {}
        pat = re.compile(r"^nebula_graph_cost_(?!verb_)(.+)_"
                         r"(device_us|rows_scanned|rpc_bytes|"
                         r"h2d_bytes|d2h_bytes|queue_wait_us|"
                         r"bytes_returned|wal_bytes)_sum$")
        for n, _lbl, v in self.samples:
            m = pat.match(n)
            if m:
                space, field = m.group(1), m.group(2)
                out.setdefault(space, {})[field] = \
                    out.setdefault(space, {}).get(field, 0.0) + v
        return out


def scrape(url: str, timeout: float = 5.0) -> Snapshot:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        text = r.read().decode()
    return Snapshot(parse_samples(text), time.time())


def fetch_consistency(base_url: str,
                      timeout: float = 5.0) -> Optional[Dict[str, Any]]:
    """graphd /consistency JSON (shadow verifier + federated per-part
    digest state), or None when the endpoint is absent/unreachable —
    the panel is optional like the profile panel."""
    try:
        with urllib.request.urlopen(
                base_url.rstrip("/") + "/consistency",
                timeout=timeout) as r:
            return json.loads(r.read())
    except Exception:
        return None


def render_consistency(cons: Optional[Dict[str, Any]]) -> List[str]:
    """The consistency panel rows (docs/manual/10-observability.md,
    "Consistency observatory"): per-part digest_ok / last-verified
    anchor across the fleet + the shadow-read sample/mismatch rates.
    Empty when the endpoint is absent or the observatory disarmed."""
    if not cons or not cons.get("enabled", False):
        return []
    lines = [""]
    sh = cons.get("shadow") or {}
    lines.append(
        f"consistency — shadow rate {sh.get('rate', 0):g}  "
        f"sampled {sh.get('sampled', 0)}  "
        f"verified {sh.get('verified', 0)}  "
        f"MISMATCH {sh.get('mismatches', 0)}  "
        f"stale-skip {sh.get('skipped_stale', 0)}")
    divergent = cons.get("divergent") or []
    if divergent:
        for d in divergent[:4]:
            lines.append(f"  DIVERGED s{d['space']}:p{d['part']} "
                         f"replica {d['replica']} @ {d['host']}")
    parts = [(h.get("addr") or h.get("host", "?"), p)
             for h in (cons.get("cluster") or [])
             for p in (h.get("parts") or [])]
    if parts:
        lines.append(f"{'SPACE:PART':<12}{'HOST':<24}{'ROLE':<10}"
                     f"{'ANCHOR':>10}{'REPLICAS':>9}{'DIGEST_OK':>10}")
        shown = sorted(
            parts, key=lambda hp: (bool(hp[1].get('digest_divergent')),
                                   hp[1].get('space', 0),
                                   hp[1].get('part', 0)),
            reverse=True)[:6]
        for host, p in shown:
            dig = p.get("digest") or {}
            anchor = dig.get("anchor_id") if isinstance(dig, dict) \
                else p.get("anchor_id")
            reps = p.get("replicas") or []
            oks = [m.get("digest_ok") for m in reps]
            verdict = "DIVERGED" if p.get("digest_divergent") else (
                "ok" if any(o is True for o in oks) else
                ("-" if not reps else "?"))
            sp = "%s:%s" % (p.get("space"), p.get("part"))
            lines.append(
                f"{sp:<12}"
                f"{str(host)[:23]:<24}{p.get('role', '?'):<10}"
                f"{anchor if anchor is not None else '-':>10}"
                f"{len(reps):>9}{verdict:>10}")
    return lines


def fetch_profile(base_url: str,
                  timeout: float = 5.0) -> Optional[Dict[str, Any]]:
    """graphd /profile JSON (top self-time + lock table), or None when
    the endpoint is absent/unreachable — the panel is optional."""
    try:
        with urllib.request.urlopen(
                base_url.rstrip("/") + "/profile?top=8",
                timeout=timeout) as r:
            return json.loads(r.read())
    except Exception:
        return None


def render_profile(prof: Optional[Dict[str, Any]]) -> List[str]:
    """The hot-frames panel rows (empty when no profile available)."""
    if not prof or not prof.get("frames"):
        return []
    lines = [""]
    st = prof.get("state", {})
    lines.append(f"hot frames ({prof.get('samples', 0)} samples @ "
                 f"{st.get('hz', 0):g} Hz, window "
                 f"{prof.get('window_s')}s)")
    lines.append(f"{'ROLE':<26}{'FRAME':<40}{'SELF_S':>8}{'PCT':>7}")
    for f in prof["frames"][:8]:
        lines.append(f"{f['role'][:25]:<26}{f['frame'][:39]:<40}"
                     f"{f['self_s']:>8.2f}{f['share'] * 100:>6.1f}%")
    locks = [l for l in prof.get("locks", ()) if l.get("contended")]
    if locks:
        lines.append(f"{'LOCK':<26}{'CONTENDED':>10}{'WAIT_MS':>10}"
                     f"{'LAST HOLDER':>24}")
        for l in locks[:4]:
            lines.append(f"{l['name'][:25]:<26}{l['contended']:>10}"
                         f"{l['wait_us_total'] / 1000:>10.1f}"
                         f"{l['last_holder'][:23]:>24}")
    return lines


def _rate(new: Snapshot, old: Optional[Snapshot], name: str) -> float:
    if old is None:
        return 0.0
    dt = max(new.t - old.t, 1e-6)
    return max((new.sum(name) - old.sum(name)) / dt, 0.0)


def render(new: Snapshot, old: Optional[Snapshot],
           prof: Optional[Dict[str, Any]] = None,
           cons: Optional[Dict[str, Any]] = None) -> str:
    lines: List[str] = []
    insts = new.instances()
    up = sum(1 for i in insts if i["up"])
    lines.append(f"nebtop — {up}/{len(insts)} daemons up    "
                 f"{time.strftime('%H:%M:%S')}")
    leaders = new.leader_counts()
    lines.append(f"{'INSTANCE':<24}{'ROLE':<9}{'UP':<4}{'LEADERS':<8}"
                 f"{'UPTIME_S':<10}")
    for i in insts:
        upt = new.get("nebula_process_uptime_seconds",
                      instance=i["instance"])
        lines.append(
            f"{i['instance']:<24}{i['role']:<9}"
            f"{'y' if i['up'] else 'N':<4}"
            f"{leaders.get(i['instance'], 0):<8}"
            f"{upt if upt is not None else '-':<10}")
    qps = _rate(new, old, "nebula_graph_query_total")
    errs = _rate(new, old, "nebula_graph_query_error_total")
    p99 = new.get("nebula_graph_query_latency_us_p99_60s") or 0.0
    lines.append("")
    lines.append(f"queries: {qps:8.1f} qps   errors: {errs:6.2f}/s   "
                 f"p99(60s): {p99 / 1000:8.2f} ms")
    qd = new.sum("nebula_tpu_engine_qos_queue_depth")
    kern = new.get("nebula_tpu_engine_kernel_us_avg_60s") or 0.0
    fl = _rate(new, old, "nebula_tpu_engine_fused_launches")
    lines.append(f"device:  kernel avg {kern:8.0f} us   "
                 f"fused {fl:6.1f} launch/s   queue depth {qd:.0f}")
    cost = new.tenant_cost()
    if cost:
        lines.append("")
        lines.append(f"{'TENANT':<16}{'DEV_US':>12}{'ROWS':>12}"
                     f"{'RPC_B':>12}")
        old_cost = old.tenant_cost() if old is not None else {}
        dt = max(new.t - old.t, 1e-6) if old is not None else None

        def cell(space, f):
            total = cost[space].get(f, 0.0)
            if dt is None:
                return f"{total:.0f}"
            prev = old_cost.get(space, {}).get(f, 0.0)
            return f"{max(total - prev, 0) / dt:.0f}/s"

        for space in sorted(cost):
            lines.append(f"{space:<16}{cell(space, 'device_us'):>12}"
                         f"{cell(space, 'rows_scanned'):>12}"
                         f"{cell(space, 'rpc_bytes'):>12}")
    lines.extend(render_writes(new, old))
    lines.extend(render_heat(new.part_heat()))
    lines.extend(render_consistency(cons))
    lines.extend(render_profile(prof))
    return "\n".join(lines)


_WM_RE = re.compile(r"^nebula_write_(visible_lag_ms|pending_acks|"
                    r"ring_ops|ring_kvs|ring_dropped)_s(\d+)$")


def render_writes(new: Snapshot, old: Optional[Snapshot]) -> List[str]:
    """The write-path panel (write-path observatory, common/
    writepath.py): acked-write rate, ack-to-visible p99, per-space
    visibility lag / pending acks / change-ring occupancy and the WAL
    fsync p99. Empty when the observatory is disarmed — none of these
    families scrape at all then (the byte-identity contract)."""
    spaces: Dict[str, Dict[str, float]] = {}
    for n, _lbl, v in new.samples:
        m = _WM_RE.match(n)
        if m:
            row = spaces.setdefault(m.group(2), {})
            row[m.group(1)] = row.get(m.group(1), 0.0) + v
    acked = _rate(new, old, "nebula_write_acked_total")
    visible = _rate(new, old, "nebula_write_visible_total")
    if not spaces and not acked and not new.sum("nebula_write_acked_total"):
        return []
    a2v = new.get("nebula_write_ack_to_visible_ms_p99_60s") or 0.0
    fsync = new.get("nebula_wal_fsync_us_p99_60s") or 0.0
    overruns = new.sum("nebula_write_ring_overrun_total")
    lines = [""]
    lines.append(f"writes:  acked {acked:7.1f}/s   visible "
                 f"{visible:7.1f}/s   ack→visible p99(60s) "
                 f"{a2v:7.2f} ms   fsync p99 {fsync / 1000:6.2f} ms   "
                 f"ring overruns {overruns:.0f}")
    if spaces:
        lines.append(f"{'SPACE':<8}{'LAG_MS':>10}{'PENDING':>9}"
                     f"{'RING_OPS':>10}{'RING_KVS':>10}{'DROPPED':>9}")
        for sid in sorted(spaces, key=int)[:6]:
            f = spaces[sid]
            lines.append(f"{sid:<8}"
                         f"{f.get('visible_lag_ms', 0.0):>10.1f}"
                         f"{f.get('pending_acks', 0.0):>9.0f}"
                         f"{f.get('ring_ops', 0.0):>10.0f}"
                         f"{f.get('ring_kvs', 0.0):>10.0f}"
                         f"{f.get('ring_dropped', 0.0):>9.0f}")
    return lines


def render_heat(ph: Dict[str, Any]) -> List[str]:
    """The hot-parts panel (workload & data observatory): top parts by
    60s heat score + per-space skew indices. Empty when heat is
    disarmed (the families don't scrape at all)."""
    parts = ph.get("parts") or {}
    if not parts:
        return []
    lines = [""]
    skew = ph.get("skew") or {}
    skew_s = "  ".join(f"s{s}:{v:g}" for s, v in sorted(skew.items()))
    lines.append(f"hot parts (60s heat score)"
                 f"{('   skew ' + skew_s) if skew_s else ''}")
    lines.append(f"{'SPACE:PART':<12}{'INSTANCE':<24}{'SCORE':>10}"
                 f"{'READS':>9}{'WRITES':>9}{'ROWS':>10}{'DEV_US':>10}")
    top = sorted(parts.items(),
                 key=lambda kv: kv[1].get("score", 0.0),
                 reverse=True)[:6]
    for (sid, pid, inst), f in top:
        lines.append(f"{f'{sid}:{pid}':<12}{inst[:23]:<24}"
                     f"{f.get('score', 0.0):>10.1f}"
                     f"{f.get('reads', 0.0):>9.0f}"
                     f"{f.get('writes', 0.0):>9.0f}"
                     f"{f.get('rows_scanned', 0.0):>10.0f}"
                     f"{f.get('device_us', 0.0):>10.0f}")
    return lines


def snapshot_dict(s: Snapshot,
                  prof: Optional[Dict[str, Any]] = None,
                  cons: Optional[Dict[str, Any]] = None
                  ) -> Dict[str, Any]:
    """--once --json machine form (totals, no rates)."""
    ph = s.part_heat()
    out = {"instances": s.instances(),
           "leaders": s.leader_counts(),
           "query_total": s.sum("nebula_graph_query_total"),
           "writes": {
               "acked_total": s.sum("nebula_write_acked_total"),
               "visible_total": s.sum("nebula_write_visible_total"),
               "ring_overruns": s.sum("nebula_write_ring_overrun_total"),
               "spaces": {m.group(2) + "." + m.group(1): v
                          for n, _l, v in s.samples
                          for m in [_WM_RE.match(n)] if m}},
           "tenant_cost": s.tenant_cost(),
           "heat": {"skew": ph["skew"],
                    "parts": {f"{sid}:{pid}@{inst}": f
                              for (sid, pid, inst), f
                              in ph["parts"].items()}}}
    if prof is not None:
        out["profile"] = {"frames": prof.get("frames", []),
                          "locks": prof.get("locks", []),
                          "state": prof.get("state", {})}
    if cons is not None:
        out["consistency"] = {
            "enabled": cons.get("enabled"),
            "shadow": cons.get("shadow", {}),
            "divergent": cons.get("divergent", []),
            "parts": sum(len(h.get("parts") or [])
                         for h in (cons.get("cluster") or []))}
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="nebtop", description="cluster top over /cluster_metrics")
    ap.add_argument("--url", default="http://127.0.0.1:13000",
                    help="graphd admin base URL (or a full "
                         "/cluster_metrics URL)")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--once", action="store_true",
                    help="one snapshot, no rates, exit")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    url = args.url if args.url.endswith("/cluster_metrics") \
        else args.url.rstrip("/") + "/cluster_metrics"
    base = url[:-len("/cluster_metrics")]
    try:
        snap = scrape(url)
    except Exception as e:
        print(f"nebtop: scrape failed: {e}", file=sys.stderr)
        return 2
    if args.once:
        prof = fetch_profile(base)
        cons = fetch_consistency(base)
        print(json.dumps(snapshot_dict(snap, prof, cons), indent=1)
              if args.json else render(snap, None, prof, cons))
        return 0
    prev = snap
    # the profile panel must never stall the dashboard: sub-interval
    # timeout, and after 3 consecutive failures (a pre-/profile
    # daemon, a wedged endpoint) stop asking — the panel is optional
    prof_timeout = min(2.0, max(0.5, args.interval / 2))
    prof_fails = 0
    cons_fails = 0        # independent: a dead /profile must not
    try:                  # kill a healthy consistency panel
        while True:
            time.sleep(max(args.interval, 0.2))
            try:
                cur = scrape(url)
            except Exception as e:
                print(f"nebtop: scrape failed: {e}", file=sys.stderr)
                continue
            prof = None
            cons = None
            if prof_fails < 3:
                prof = fetch_profile(base, timeout=prof_timeout)
                prof_fails = 0 if prof is not None else prof_fails + 1
            if cons_fails < 3:
                cons = fetch_consistency(base, timeout=prof_timeout)
                cons_fails = 0 if cons is not None else cons_fails + 1
            sys.stdout.write("\x1b[2J\x1b[H")
            print(render(cur, prev, prof, cons))
            prev = cur
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
