"""Multi-session graphd concurrency bench.

The reference's StoragePerfTool methodology (tools/storage-perf/
README.md: fixed thread count, sustained load, latency percentiles)
applied one layer up: N INDEPENDENT client sessions fire mixed GO
traffic at ONE graphd over real TCP, measuring how aggregate QPS and
per-query latency scale with N. This is the measurement the per-batch
tier-1 numbers can't give — graphd is thread-per-connection Python, so
host-side planning/materialization serializes on the GIL while device
dispatches release it; the sweep shows where that cap bites.

Caveat printed with every run: a container pinned to one CPU core
(sched_getaffinity -> 1) measures GIL/scheduling overhead only — real
scaling needs cores.
"""
from __future__ import annotations

import argparse
import threading
import time
from typing import Any, Dict, List, Sequence


def _percentile(sorted_ms: List[float], p: float) -> float:
    if not sorted_ms:
        return 0.0
    idx = min(len(sorted_ms) - 1, int(p / 100.0 * len(sorted_ms)))
    return sorted_ms[idx]


def run_sessions(addr: str, queries: Sequence[str], n_sessions: int,
                 duration_s: float = 5.0, user: str = "root",
                 password: str = "",
                 use_space: str = "") -> Dict[str, Any]:
    """N threads, each with its OWN authenticated session/connection,
    cycling through `queries` (offset per thread so the mix interleaves)
    for `duration_s`. Returns {n_sessions, qps, errors, latency_ms}."""
    from ..client import GraphClient

    stop = threading.Event()
    lats: List[List[float]] = [[] for _ in range(n_sessions)]
    errs = [0] * n_sessions
    clients = []
    for _ in range(n_sessions):
        c = GraphClient(addr).connect(user, password)
        if use_space:
            r = c.execute(f"USE {use_space}")
            if not r.ok():
                raise RuntimeError(f"USE {use_space}: {r.error_msg}")
        clients.append(c)

    def worker(i: int) -> None:
        c = clients[i]
        k = i  # per-thread offset interleaves the mix
        while not stop.is_set():
            q = queries[k % len(queries)]
            k += 1
            t1 = time.time()
            r = c.execute(q)
            lats[i].append((time.time() - t1) * 1000)
            if not r.ok():
                errs[i] += 1

    # nlint: disable=NL002 -- load-origin bench workers; no inbound trace
    threads = [threading.Thread(target=worker, args=(i,), daemon=True,
                                name=f"session-bench-{i}")
               for i in range(n_sessions)]
    t0 = time.time()
    for t in threads:
        t.start()
    time.sleep(duration_s)
    stop.set()
    for t in threads:
        t.join(timeout=10)
    wall = time.time() - t0
    for c in clients:
        try:
            c.disconnect()
        except Exception:
            pass
    all_ms = sorted(x for ls in lats for x in ls)
    total = len(all_ms)
    return {
        "n_sessions": n_sessions,
        "total_queries": total,
        "errors": sum(errs),
        "qps": round(total / wall, 1),
        "latency_ms": {
            "p50": round(_percentile(all_ms, 50), 2),
            "p95": round(_percentile(all_ms, 95), 2),
            "p99": round(_percentile(all_ms, 99), 2),
            "avg": round(sum(all_ms) / total, 2) if total else 0.0,
        },
    }


def sweep(addr: str, queries: Sequence[str],
          session_counts: Sequence[int] = (1, 2, 4, 8, 16),
          duration_s: float = 5.0, use_space: str = "",
          user: str = "root", password: str = ""
          ) -> List[Dict[str, Any]]:
    """run_sessions over increasing N; returns one record per N. The
    scaling knee (QPS flat while p99 grows ~linearly with N) is the
    GIL/host-side cap."""
    import os
    out = []
    try:
        cores = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        cores = os.cpu_count() or 1
    if cores == 1:
        print("WARNING: this process is pinned to 1 CPU core — the sweep "
              "measures GIL/scheduling overhead, not parallel capacity")
    for n in session_counts:
        rec = run_sessions(addr, queries, n, duration_s,
                           use_space=use_space, user=user,
                           password=password)
        rec["cores"] = cores
        out.append(rec)
        print(f"sessions={n:3d}: {rec['qps']:8.1f} QPS  "
              f"p50={rec['latency_ms']['p50']:.1f}ms "
              f"p99={rec['latency_ms']['p99']:.1f}ms "
              f"errors={rec['errors']}")
    return out


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        description="nebula-tpu multi-session graphd concurrency bench")
    ap.add_argument("--graphd", required=True, help="graphd host:port")
    ap.add_argument("--space", default="", help="USE this space first")
    ap.add_argument("--query", action="append", required=True,
                    help="query to mix in (repeatable)")
    ap.add_argument("--sessions", default="1,2,4,8,16",
                    help="comma-separated session counts to sweep")
    ap.add_argument("--duration", type=float, default=5.0,
                    help="seconds per sweep point")
    ap.add_argument("--user", default="root")
    ap.add_argument("--password", default="")
    args = ap.parse_args(argv)
    counts = [int(x) for x in args.sessions.split(",") if x]
    import json
    out = sweep(args.graphd, args.query, counts, args.duration,
                use_space=args.space, user=args.user,
                password=args.password)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
