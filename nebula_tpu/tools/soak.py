"""Mixed-workload soak: sustained INSERT+GO against the TPU engine with
continuous identity checking (the sustained-validation sibling of
integrity_check — role parity with running StorageIntegrityTool against
a live cluster, plus the device-engine invariants the reference doesn't
have: zero per-write rebuilds, delta applies flowing, background
repacks folding the delta).

    python -m nebula_tpu.tools.soak --seconds 30 --write-ratio 0.3

Runs in-process (metad+storaged+graphd semantics through InProcCluster)
so every N-th query can be re-executed with the device engine disabled
and compared row-for-row — a divergence fails the soak immediately.
Prints one JSON summary line.
"""
from __future__ import annotations

import argparse
import json
import random
import time
from typing import List


def run_soak(seconds: float = 10.0, write_ratio: float = 0.3,
             verify_every: int = 20, v: int = 2000, e: int = 10000,
             seed: int = 7, progress=None) -> dict:
    import numpy as np
    from ..cluster import InProcCluster
    from ..engine_tpu import TpuGraphEngine

    rng = random.Random(seed)
    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    conn = cluster.connect()
    conn.must("CREATE SPACE soak(partition_num=4)")
    conn.must("USE soak")
    conn.must("CREATE TAG person(age int)")
    conn.must("CREATE EDGE knows(w int)")
    for i in range(0, v, 2000):
        vrows = ", ".join(f"{j}:({j % 80})"
                          for j in range(i, min(i + 2000, v)))
        conn.must(f"INSERT VERTEX person(age) VALUES {vrows}")
    np_rng = np.random.default_rng(seed)
    srcs = np_rng.integers(0, v, e)
    dsts = np_rng.integers(0, v, e)
    for i in range(0, e, 2000):
        rows = ", ".join(
            f"{int(s)} -> {int(d)}:({int((s + d) % 101)})"
            for s, d in zip(srcs[i:i + 2000], dsts[i:i + 2000]))
        conn.must(f"INSERT EDGE knows(w) VALUES {rows}")
    conn.must("GO FROM 0 OVER knows")          # snapshot up
    base_rebuilds = tpu.stats["rebuilds"]

    lats: List[float] = []
    next_vid = v
    writes = queries = verifies = 0
    deadline = time.monotonic() + seconds
    # floor on query count so a slow machine still produces identity
    # verifies (the pass condition) instead of timing out at zero
    min_queries = 2 * verify_every
    while time.monotonic() < deadline or queries < min_queries:
        if rng.random() < write_ratio:
            op = rng.random()
            if op < 0.5:                        # new edge
                s, d = rng.randrange(v), rng.randrange(v)
                conn.must(f"INSERT EDGE knows(w) VALUES "
                          f"{s} -> {d}:({(s + d) % 101})")
            elif op < 0.8:                      # new vertex + edge to it
                conn.must(f"INSERT VERTEX person(age) VALUES "
                          f"{next_vid}:({next_vid % 80})")
                conn.must(f"INSERT EDGE knows(w) VALUES "
                          f"{rng.randrange(v)} -> {next_vid}:(7)")
                next_vid += 1
            else:                               # delete an edge
                s, d = int(srcs[writes % e]), int(dsts[writes % e])
                conn.must(f"DELETE EDGE knows {s} -> {d}")
            writes += 1
            continue
        seed_vid = rng.randrange(v)
        steps = rng.choice([1, 2, 2, 3])
        cut = rng.randrange(0, 101)
        if rng.random() < 0.15:    # aggregation pipes in the soak mix
            q = (f"GO {steps} STEPS FROM {seed_vid} OVER knows "
                 f"WHERE knows.w > {cut} YIELD knows.w AS w "
                 f"| YIELD COUNT(*) AS n, SUM($-.w) AS s, AVG($-.w) AS a")
        else:
            q = (f"GO {steps} STEPS FROM {seed_vid} OVER knows "
                 f"WHERE knows.w > {cut} YIELD knows._dst, knows.w")
        t0 = time.monotonic()
        r = conn.must(q)
        lats.append((time.monotonic() - t0) * 1e3)
        queries += 1
        if queries % verify_every == 0:
            tpu.enabled = False
            try:
                rc = conn.must(q)
            finally:
                tpu.enabled = True
            if sorted(map(repr, r.rows)) != sorted(map(repr, rc.rows)):
                raise AssertionError(
                    f"IDENTITY DIVERGENCE on: {q}\n"
                    f"tpu={sorted(r.rows)[:5]}... "
                    f"cpu={sorted(rc.rows)[:5]}...")
            verifies += 1
        if progress and queries % 200 == 0:
            progress(queries, writes)

    # settle in-flight background repacks, then read the counters under
    # the engine lock — the repack thread increments rebuilds and
    # bg_repacks non-atomically, and racing that pair could report a
    # phantom foreground rebuild
    settle = time.monotonic() + 10
    while any(tpu._repacking.values()) and time.monotonic() < settle:
        time.sleep(0.02)
    with tpu._lock:
        stats = dict(tpu.stats)
    lat = np.sort(np.asarray(lats)) if lats else np.zeros(1)
    out = {
        "seconds": seconds, "queries": queries, "writes": writes,
        "identity_verifies": verifies,
        "qps": round(queries / seconds, 1),
        "latency_ms": {"p50": round(float(np.percentile(lat, 50)), 2),
                       "p99": round(float(np.percentile(lat, 99)), 2)},
        "rebuilds_during_soak": stats["rebuilds"] - base_rebuilds,
        "bg_repacks": stats["bg_repacks"],
        "delta_applies": stats["delta_applies"],
        "served": {k: stats[k] for k in
                   ("go_served", "sparse_served", "fallbacks",
                    "host_filter_vectorized")},
    }
    # foreground rebuilds during the soak mean a write forced a
    # stop-the-world snapshot rebuild — the delta buffer's whole job
    # is keeping that at zero (background repacks are fine)
    out["ok"] = (out["rebuilds_during_soak"] <= out["bg_repacks"]
                 and verifies > 0)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="mixed INSERT+GO soak with continuous CPU/TPU "
                    "identity checks")
    ap.add_argument("--seconds", type=float, default=10.0)
    ap.add_argument("--write-ratio", type=float, default=0.3)
    ap.add_argument("--verify-every", type=int, default=20)
    ap.add_argument("--vertices", type=int, default=2000)
    ap.add_argument("--edges", type=int, default=10000)
    args = ap.parse_args(argv)
    out = run_soak(args.seconds, args.write_ratio, args.verify_every,
                   args.vertices, args.edges,
                   progress=lambda q, w: print(f"  ... {q} queries, "
                                               f"{w} writes", flush=True))
    print(json.dumps(out))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
