"""Mixed-workload soak: sustained INSERT+GO against the TPU engine with
continuous identity checking (the sustained-validation sibling of
integrity_check — role parity with running StorageIntegrityTool against
a live cluster, plus the device-engine invariants the reference doesn't
have: zero per-write rebuilds, delta applies flowing, background
repacks folding the delta).

    python -m nebula_tpu.tools.soak --seconds 30 --write-ratio 0.3

Runs in-process (metad+storaged+graphd semantics through InProcCluster)
so every N-th query can be re-executed with the device engine disabled
and compared row-for-row — a divergence fails the soak immediately.
Prints one JSON summary line.
"""
from __future__ import annotations

import argparse
import json
import random
import time
from typing import List


def _setup_cluster(space: str, v: int, e: int, seed: int):
    """Shared soak scaffolding: in-proc cluster with the TPU engine,
    person(age)/knows(w) schema, a zipf-free random graph of v
    vertices / e edges, and a warmed snapshot.
    -> (cluster, conn, tpu, srcs, dsts)."""
    import numpy as np
    from ..cluster import InProcCluster
    from ..engine_tpu import TpuGraphEngine

    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    conn = cluster.connect()
    conn.must(f"CREATE SPACE {space}(partition_num=4)")
    conn.must(f"USE {space}")
    conn.must("CREATE TAG person(age int)")
    conn.must("CREATE EDGE knows(w int)")
    for i in range(0, v, 2000):
        conn.must("INSERT VERTEX person(age) VALUES " + ", ".join(
            f"{j}:({j % 80})" for j in range(i, min(i + 2000, v))))
    np_rng = np.random.default_rng(seed)
    srcs = np_rng.integers(0, v, e)
    dsts = np_rng.integers(0, v, e)
    for i in range(0, e, 2000):
        conn.must("INSERT EDGE knows(w) VALUES " + ", ".join(
            f"{int(s)} -> {int(d)}:({int((s + d) % 101)})"
            for s, d in zip(srcs[i:i + 2000], dsts[i:i + 2000])))
    conn.must("GO FROM 0 OVER knows")          # snapshot up
    # absorb the background warmup (kernel + dispatcher-bucket
    # compiles + calibration) BEFORE any measured burst: on a 1-core
    # host a compile racing the burst starves the sessions
    sid = cluster.meta.get_space(space).value().space_id
    tpu.prewarm(sid, block=True)
    return cluster, conn, tpu, srcs, dsts


def _arm_consistency(rate: float = 0.15) -> dict:
    """Arm the consistency observatory's continuous assertions for a
    soak run (ISSUE 15 satellite): shadow-read sampling on, counters
    reset, divergence baseline captured. Returns the token
    _settle_consistency consumes."""
    from ..common import consistency as _cons
    from ..common.flags import graph_flags
    from ..common.stats import stats as _gstats
    _cons.shadow.reset()
    graph_flags.set("shadow_read_rate", rate)
    return {"div0": _gstats.lifetime_total("consistency.divergence"),
            "aud0": _gstats.lifetime_total(
                "consistency.audit_mismatch")}


def _settle_consistency(tok: dict) -> dict:
    """Disarm sampling, drain the shadow queue and return the
    continuous-consistency block: the soak FAILS unless shadow
    mismatches and replica divergence stayed zero for the whole run
    (no corruption fault is ever armed here — the observatory must be
    silent on a healthy cluster, however hard the device faults
    fire)."""
    from ..common import consistency as _cons
    from ..common.flags import graph_flags
    from ..common.stats import stats as _gstats
    graph_flags.set("shadow_read_rate", 0.0)
    _cons.shadow.drain(20)
    sh = _cons.shadow.stats()
    block = {
        "shadow": {k: sh[k] for k in
                   ("sampled", "verified", "mismatches",
                    "skipped_stale", "errors", "dropped")},
        "divergence": _gstats.lifetime_total("consistency.divergence")
        - tok["div0"],
        "audit_mismatches": _gstats.lifetime_total(
            "consistency.audit_mismatch") - tok["aud0"],
    }
    block["ok"] = (sh["mismatches"] == 0 and block["divergence"] == 0
                   and block["audit_mismatches"] == 0)
    return block


def _debug_bundle(cluster, tpu, extra: dict,
                  path: str = "SOAK_DEBUG_BUNDLE.json") -> str:
    """First-class debug bundle: on any identity-check failure the soak
    dumps the trace ring, the /queries surfaces (active statements +
    slow-query log) and the engine's counters to one JSON artifact, so
    a divergence on a remote box arrives with its own evidence instead
    of a bare assertion line. The failure is also recorded into the
    flight recorder (identity_failure trigger -> its own capture), and
    that flight bundle rides INSIDE this artifact — one artifact per
    incident, not two (docs/manual/10-observability.md)."""
    import os
    from ..common.flight import recorder as flight_recorder
    from ..common.tracing import tracer
    path = os.environ.get("SOAK_BUNDLE_OUT", path)
    from ..common.lockwitness import witness
    # the identity_failure trigger captures the flight side: event
    # ring + collectors + last sampled traces, and arms aftermath
    # sampling for whatever the soak does next
    flight_recorder.record("identity_failure", source="soak",
                           detail=str(extra.get("query",
                                                extra.get("phase",
                                                          "")))[:256])
    # bundle enrichment (collectors/stats/dump) runs on a capture
    # thread — wait for it so the attached bundle is complete
    flight_recorder.flush(5.0)
    from ..common import profiler as _prof
    out = {
        "trace_ring": tracer.ring.snapshot(),
        "flight": {
            "state": flight_recorder.describe(limit=64),
            "bundle": flight_recorder.last_bundle(),
        },
        # what the process was DOING at failure time (ISSUE 13): top
        # self-time frames per thread role, trace-tagged samples, top
        # contended locks, GC/compile tables — the same capture every
        # flight bundle embeds
        "profile": _prof.flight_block(),
        # the observed lock-order graph rides every bundle: a
        # divergence that involved a lock-ordering surprise arrives
        # with the evidence attached (empty unless --witness /
        # NEBULA_TPU_LOCK_WITNESS armed the witness)
        "lock_witness": witness.report(),
        "queries": {
            "active": cluster.service.active_queries.snapshot(),
            "slow": cluster.service.slow_log.snapshot(),
        },
        "robustness": tpu.robustness_stats(),
        # routing state at failure time: a divergence that rode a
        # leader change / election shows up here as non-zero retry
        # classifications (docs/manual/12-replication.md)
        "cluster": cluster.client.routing_stats(),
    }
    with tpu._lock:
        out["tpu_stats"] = dict(tpu.stats)
    out.update(extra)
    with open(path, "w") as f:
        json.dump(out, f, default=str)
    print(f"soak: debug bundle written to {path}", flush=True)
    return path


def _chaos_trace_check(out: dict, ring) -> None:
    """`--chaos` pass condition: with sampling forced on, the sampled
    traces of degraded serves must carry their degradation tags — the
    observable promise of docs/manual/10-observability.md, proven
    under injected faults."""
    degraded = [t for t in ring.snapshot()
                if "degraded" in t.get("tags", {})]
    out["chaos_degraded_traces"] = len(degraded)
    out["chaos_degraded_kinds"] = sorted(
        {str(t["tags"]["degraded"]) for t in degraded})[:8]
    out["ok"] = out["ok"] and len(degraded) > 0


def _fault_schedule(stop, period: float = 0.8, seed: int = 7):
    """Background fault schedule for `--faults`: alternates an armed
    plan (kernel launch + delta apply + native encode failures) with
    quiet windows, so the soak's continuous identity checks prove the
    degradation ladder under churn — every injected failure must
    degrade to the CPU pipe, never to a client error or a divergent
    row. Returns the toggler thread (joined by the caller)."""
    import threading
    from ..common.faults import faults

    plans = [
        f"seed={seed};kernel.launch:p=0.25;encode.rows:p=0.25",
        "",                                       # quiet window
        f"seed={seed + 1};kernel.launch:p=0.5;csr.delta_apply:n=1",
        "",
    ]

    def run():
        i = 0
        while not stop.wait(period):
            faults.set_plan(plans[i % len(plans)])
            i += 1
        faults.clear()

    # nlint: disable=NL002 -- run-lifetime chaos scheduler, not request work
    t = threading.Thread(target=run, daemon=True, name="fault-schedule")
    t.start()
    return t


def _cache_full_wrap(run, enabled: bool) -> dict:
    """`--faults`/`--chaos` soaks run with the FULL cache ladder armed
    (cache_mode=full on both damon registries; docs/manual/
    11-caching.md): the soak's continuous write + identity-verify mix
    is exactly the staleness gauntlet the snapshot-versioned result
    cache must survive byte-identically — and the fault schedule's
    csr.delta_apply failures exercise the poison -> cache-purge path.
    Restored in a finally (the designed failure mode is RAISING on a
    divergence, and a leaked process-global mode would change whatever
    runs next)."""
    if not enabled:
        return run()
    from ..common.flags import graph_flags, storage_flags
    g0 = graph_flags.get("cache_mode")
    s0 = storage_flags.get("cache_mode")
    graph_flags.set("cache_mode", "full")
    storage_flags.set("cache_mode", "full")
    try:
        return run()
    finally:
        graph_flags.set("cache_mode", g0)
        storage_flags.set("cache_mode", s0)


def _chaos_wrap(run, chaos: bool) -> dict:
    """Chaos mode samples EVERY query (so degraded serves provably
    carry their degradation tags) — the forced rate is restored in a
    finally because the soak's designed failure mode is RAISING on an
    identity divergence, and a process-global sample rate left at 1.0
    would poison whatever runs next in this process."""
    if not chaos:
        return run()
    from ..common.flags import graph_flags
    from ..common.tracing import TraceRing, tracer
    rate0 = graph_flags.get("trace_sample_rate", 0.0)
    graph_flags.set("trace_sample_rate", 1.0)
    # a private, soak-sized ring: the production default (256) can
    # evict the degraded-serve traces before the end-of-run check —
    # and the process ring shouldn't be flooded by a chaos run anyway
    ring0 = tracer.ring
    tracer.ring = ring = TraceRing(65536)
    try:
        out = run()
    finally:
        tracer.ring = ring0
        graph_flags.set("trace_sample_rate", rate0)
    _chaos_trace_check(out, ring)
    return out


def run_soak(seconds: float = 10.0, write_ratio: float = 0.3,
             verify_every: int = 20, v: int = 2000, e: int = 10000,
             seed: int = 7, progress=None, fault_schedule: bool = False,
             chaos: bool = False) -> dict:
    return _cache_full_wrap(
        lambda: _chaos_wrap(
            lambda: _run_soak(seconds, write_ratio, verify_every, v, e,
                              seed, progress,
                              fault_schedule or chaos),
            chaos),
        fault_schedule or chaos)


def _run_soak(seconds, write_ratio, verify_every, v, e, seed, progress,
              fault_schedule) -> dict:
    import threading

    import numpy as np
    from ..common.faults import faults

    rng = random.Random(seed)
    cluster, conn, tpu, srcs, dsts = _setup_cluster("soak", v, e, seed)
    base_rebuilds = tpu.stats["rebuilds"]
    fstop = threading.Event()
    fthread = None
    if fault_schedule:
        # a tight ladder so trips AND half-open recoveries both happen
        # within a short soak. Breakers already created by the setup
        # queries captured the production params at construction —
        # drop them so they rebuild with these (engine._breaker reads
        # the attrs only when it instantiates).
        tpu.breaker_threshold = 2
        tpu.breaker_base_s = 0.2
        tpu.breaker_max_s = 2.0
        with tpu._stats_lock:
            tpu._breakers.clear()
        fthread = _fault_schedule(fstop, seed=seed)
    # continuous-consistency assertion (ISSUE 15): shadow-read
    # sampling runs for the whole faulted soak; mismatches and
    # replica divergence must stay zero
    ctok = _arm_consistency() if fault_schedule else None

    lats: List[float] = []
    next_vid = v
    writes = queries = verifies = 0
    deadline = time.monotonic() + seconds
    # floor on query count so a slow machine still produces identity
    # verifies (the pass condition) instead of timing out at zero
    min_queries = 2 * verify_every
    while time.monotonic() < deadline or queries < min_queries:
        if rng.random() < write_ratio:
            op = rng.random()
            if op < 0.5:                        # new edge
                s, d = rng.randrange(v), rng.randrange(v)
                conn.must(f"INSERT EDGE knows(w) VALUES "
                          f"{s} -> {d}:({(s + d) % 101})")
            elif op < 0.8:                      # new vertex + edge to it
                conn.must(f"INSERT VERTEX person(age) VALUES "
                          f"{next_vid}:({next_vid % 80})")
                conn.must(f"INSERT EDGE knows(w) VALUES "
                          f"{rng.randrange(v)} -> {next_vid}:(7)")
                next_vid += 1
            else:                               # delete an edge
                s, d = int(srcs[writes % e]), int(dsts[writes % e])
                conn.must(f"DELETE EDGE knows {s} -> {d}")
            writes += 1
            continue
        seed_vid = rng.randrange(v)
        steps = rng.choice([1, 2, 2, 3])
        cut = rng.randrange(0, 101)
        if rng.random() < 0.15:    # aggregation pipes in the soak mix
            q = (f"GO {steps} STEPS FROM {seed_vid} OVER knows "
                 f"WHERE knows.w > {cut} YIELD knows.w AS w "
                 f"| YIELD COUNT(*) AS n, SUM($-.w) AS s, AVG($-.w) AS a")
        else:
            q = (f"GO {steps} STEPS FROM {seed_vid} OVER knows "
                 f"WHERE knows.w > {cut} YIELD knows._dst, knows.w")
        t0 = time.monotonic()
        r = conn.must(q)
        lats.append((time.monotonic() - t0) * 1e3)
        queries += 1
        if queries % verify_every == 0:
            tpu.enabled = False
            try:
                rc = conn.must(q)
            finally:
                tpu.enabled = True
            if sorted(map(repr, r.rows)) != sorted(map(repr, rc.rows)):
                _debug_bundle(cluster, tpu, {
                    "failure": "identity_divergence", "query": q,
                    "tpu_rows": sorted(map(repr, r.rows))[:20],
                    "cpu_rows": sorted(map(repr, rc.rows))[:20]})
                raise AssertionError(
                    f"IDENTITY DIVERGENCE on: {q}\n"
                    f"tpu={sorted(r.rows)[:5]}... "
                    f"cpu={sorted(rc.rows)[:5]}...")
            verifies += 1
        if progress and queries % 200 == 0:
            progress(queries, writes)

    if fthread is not None:
        fstop.set()
        fthread.join(timeout=5)
        faults.clear()
    # settle in-flight background repacks, then read the counters under
    # the engine lock — the repack thread increments rebuilds and
    # bg_repacks non-atomically, and racing that pair could report a
    # phantom foreground rebuild
    settle = time.monotonic() + 10
    while any(tpu._repacking.values()) and time.monotonic() < settle:
        time.sleep(0.02)
    with tpu._lock:
        stats = dict(tpu.stats)
    lat = np.sort(np.asarray(lats)) if lats else np.zeros(1)
    out = {
        "seconds": seconds, "queries": queries, "writes": writes,
        "identity_verifies": verifies,
        "qps": round(queries / seconds, 1),
        "latency_ms": {"p50": round(float(np.percentile(lat, 50)), 2),
                       "p99": round(float(np.percentile(lat, 99)), 2)},
        "rebuilds_during_soak": stats["rebuilds"] - base_rebuilds,
        "bg_repacks": stats["bg_repacks"],
        "delta_applies": stats["delta_applies"],
        "served": {k: stats[k] for k in
                   ("go_served", "sparse_served", "fallbacks",
                    "host_filter_vectorized")},
    }
    if fault_schedule:
        out["robustness"] = tpu.robustness_stats()
        out["cache"] = tpu.cache_stats()   # full ladder is armed here
        out["consistency"] = _settle_consistency(ctok)
    # foreground rebuilds during the soak mean a write forced a
    # stop-the-world snapshot rebuild — the delta buffer's whole job
    # is keeping that at zero (background repacks are fine). Under an
    # injected fault schedule a poisoned snapshot legitimately
    # rebuilds in the background; the identity verifies remain the
    # pass condition, plus proof that faults actually landed.
    out["ok"] = (out["rebuilds_during_soak"] <= out["bg_repacks"]
                 and verifies > 0)
    if fault_schedule:
        out["ok"] = out["ok"] and \
            sum(out["robustness"]["faults_injected"].values()) > 0 \
            and out["consistency"]["ok"]
    return out


def run_soak_concurrent(seconds: float = 8.0, threads: int = 6,
                        v: int = 2000, e: int = 10000,
                        seed: int = 11,
                        fault_schedule: bool = False,
                        chaos: bool = False) -> dict:
    return _cache_full_wrap(
        lambda: _chaos_wrap(
            lambda: _run_soak_concurrent(seconds, threads, v, e, seed,
                                         fault_schedule or chaos),
            chaos),
        fault_schedule or chaos)


def _run_soak_concurrent(seconds, threads, v, e, seed,
                         fault_schedule) -> dict:
    """Concurrency soak: N sessions hammer one engine through the
    cross-session dispatcher while writers mutate the graph (delta
    applies + aligned-layout invalidation racing multi-query rounds),
    in burst/quiesce phases:

      A. mixed burst — default routing, 2 writer + N-2 reader threads;
      B. dense burst — pull budget pinned 0, every GO rides the
         batched dispatcher (vmapped or lane-matrix rounds);
      C. read-only burst — aligned layout force-built, multi-query
         rounds take the shared lane-matrix kernel.

    After EVERY burst the cluster quiesces and a deterministic query
    sweep re-runs with the device engine disabled — any row divergence
    fails the soak. Returns a JSON-able summary; ok = no thread
    errors, identity green, dispatcher exercised."""
    import threading

    import numpy as np

    from ..common.faults import faults

    cluster, conn, tpu, srcs, dsts = _setup_cluster("csoak", v, e, seed)
    sid = cluster.meta.get_space("csoak").value().space_id
    fstop = threading.Event()
    fthread = None
    if fault_schedule:
        # same tight-ladder wiring as run_soak (breakers created by
        # the setup queries captured production params — rebuild them)
        tpu.breaker_threshold = 2
        tpu.breaker_base_s = 0.2
        tpu.breaker_max_s = 2.0
        with tpu._stats_lock:
            tpu._breakers.clear()
        fthread = _fault_schedule(fstop, seed=seed)
    ctok = _arm_consistency() if fault_schedule else None
    deg = np.bincount(srcs, minlength=v)
    hubs = [int(x) for x in np.argsort(deg)[-3:]]
    errors: List[str] = []
    queries = writes = 0
    qlock = threading.Lock()

    def reader(k, stop, dense):
        nonlocal queries
        rng = random.Random(seed * 100 + k)
        c = cluster.connect()
        c.must("USE csoak")
        while not stop.is_set():
            seed_vid = rng.choice(hubs) if (dense or rng.random() < .3) \
                else rng.randrange(v)
            # dense phases share one query SHAPE so concurrent sessions
            # land in the same dispatcher group (space, steps, types)
            steps = 3 if dense else rng.choice([1, 2, 3])
            try:
                if not dense and rng.random() < 0.2:
                    c.must(f"GO {steps} STEPS FROM {seed_vid} OVER knows"
                           f" YIELD knows.w AS w | YIELD COUNT(*) AS n,"
                           f" SUM($-.w) AS s")
                else:
                    c.must(f"GO {steps} STEPS FROM {seed_vid} OVER "
                           f"knows WHERE knows.w > 50 "
                           f"YIELD knows._dst, knows.w")
                with qlock:
                    queries += 1
            except Exception as ex:   # noqa: BLE001 — recorded, fails ok
                errors.append(f"reader: {ex!r}")
                return

    def writer(k, stop):
        nonlocal writes
        rng = random.Random(seed * 999 + k)
        c = cluster.connect()
        c.must("USE csoak")
        while not stop.is_set():
            try:
                s, d = rng.randrange(v), rng.randrange(v)
                if rng.random() < 0.75:
                    c.must(f"INSERT EDGE knows(w) VALUES "
                           f"{s} -> {d}:({(s + d) % 101})")
                else:
                    c.must(f"DELETE EDGE knows {s} -> {d}")
                with qlock:
                    writes += 1
                time.sleep(0.002)
            except Exception as ex:   # noqa: BLE001
                errors.append(f"writer: {ex!r}")
                return

    def burst(n_writers, dense, dur):
        stop = threading.Event()
        # nlint: disable=NL002 -- load-origin soak workers; no inbound
        # trace to carry (each query starts its own)
        ts = [threading.Thread(target=writer, args=(i, stop),
                               name=f"soak-writer-{i}")
              for i in range(n_writers)]
        # nlint: disable=NL002 -- load-origin soak workers (above)
        ts += [threading.Thread(target=reader, args=(i, stop, dense),
                                name=f"soak-reader-{i}")
               for i in range(threads - n_writers)]
        for t in ts:
            t.start()
        time.sleep(dur)
        stop.set()
        for t in ts:
            t.join(timeout=30)
        # a straggler still running would mutate the graph DURING the
        # verify sweep and fake a divergence — fail loudly instead
        alive = [t.name for t in ts if t.is_alive()]
        if alive:
            errors.append(f"burst stragglers did not stop: {alive}")

    def verify_sweep():
        settle = time.monotonic() + 10
        while any(tpu._repacking.values()) and time.monotonic() < settle:
            time.sleep(0.02)
        checked = 0
        for q in ([f"GO 2 STEPS FROM {h} OVER knows "
                   f"YIELD knows._dst, knows.w" for h in hubs]
                  + [f"GO 3 STEPS FROM {hubs[0]} OVER knows "
                     f"WHERE knows.w > 50 YIELD knows._dst"]
                  + [f"GO FROM {hubs[1]}, {hubs[2]} OVER knows YIELD "
                     f"knows.w AS w | YIELD COUNT(*) AS n, SUM($-.w)"
                     f" AS s, MIN($-.w) AS lo"]):
            rt = conn.must(q)
            tpu.enabled = False
            try:
                rc = conn.must(q)
            finally:
                tpu.enabled = True
            a = sorted(map(repr, rt.rows))
            b = sorted(map(repr, rc.rows))
            if a != b:
                with tpu._lock:
                    s0 = tpu._snapshots.get(sid)
                    diag = (f"snapv={getattr(s0, 'write_version', None)} "
                            f"tok={tpu._provider.version(sid)} "
                            f"stale={getattr(s0, 'stale', None)}")
                r2 = sorted(map(repr, conn.must(q).rows))
                _debug_bundle(cluster, tpu, {
                    "failure": "identity_divergence", "query": q,
                    "diag": diag,
                    "tpu_only": sorted(set(a) - set(b))[:20],
                    "cpu_only": sorted(set(b) - set(a))[:20]})
                errors.append(
                    f"IDENTITY DIVERGENCE after burst: {q} "
                    f"tpu_only={sorted(set(a) - set(b))[:4]} "
                    f"cpu_only={sorted(set(b) - set(a))[:4]} "
                    f"{diag} retry_heals={r2 == b}")
                return checked
            checked += 1
        return checked

    per = max(seconds / 3.0, 1.0)
    verifies = 0
    burst(2, False, per)                     # A: mixed, default routing
    verifies += verify_sweep()
    tpu.sparse_edge_budget = 0
    burst(2, True, per)                      # B: dense + writers
    verifies += verify_sweep()
    with tpu._lock:                          # fold bursts A/B's deltas
        snap = tpu.refresh(sid)              # fresh base, empty delta
    if snap is not None:
        snap.aligned_kernel()
    # phase C paces each dispatcher round by 10ms so window formation
    # is deterministic: on a 1-core GIL-serialized closed loop, fast
    # rounds rarely overlap arrivals naturally (coalescing under real
    # load needs either cores or slow rounds — exactly the regimes the
    # dispatcher targets)
    orig_sb = tpu._serve_batch

    def paced(batch, ex):
        time.sleep(0.01)
        orig_sb(batch, ex)

    # with the full cache ladder armed (--faults/--chaos), burst B's
    # results are still version-valid after the refresh (same token) —
    # phase C would be all cache hits and never form the lane windows
    # this phase exists to exercise; dropping the rung's entries makes
    # the first paced barrage miss -> coalesce deterministically
    tpu.result_cache.clear()
    tpu._serve_batch = paced
    try:
        burst(0, True, per)                  # C: read-only lane rounds
    finally:
        tpu._serve_batch = orig_sb
    verifies += verify_sweep()
    if fthread is not None:
        fstop.set()
        fthread.join(timeout=5)
        faults.clear()
    with tpu._lock:
        stats = dict(tpu.stats)
    out = {
        "seconds": seconds, "threads": threads, "queries": queries,
        "writes": writes, "identity_verifies": verifies,
        "errors": errors[:5],
        "dispatcher": {k: stats[k] for k in
                       ("batched_dispatches", "batched_queries",
                        "batched_max_window", "batched_lane_rounds")},
        "delta_applies": stats["delta_applies"],
    }
    if fault_schedule:
        out["robustness"] = tpu.robustness_stats()
        out["cache"] = tpu.cache_stats()   # full ladder is armed here
        out["consistency"] = _settle_consistency(ctok)
    out["ok"] = (not errors and verifies >= 15 and queries > 0
                 and stats["batched_queries"] > 0)
    if fault_schedule:
        out["ok"] = out["ok"] and \
            sum(out["robustness"]["faults_injected"].values()) > 0 \
            and out["consistency"]["ok"]
    return out


def run_soak_tenants(seconds: float = 8.0, seed: int = 21) -> dict:
    """`--tenants`: skewed multi-tenant load under the QoS ladder
    (docs/manual/14-qos.md) — one abusive tenant firing closed-loop
    bulk scans against small tenants running interactive reads, with
    per-space admission + lanes + a shed watermark armed, and the
    small tenants' CPU/TPU identity checks running CONTINUOUSLY (the
    soak's signature move). ok requires: identity green, the abuser
    throttled (admission denials + typed E_OVERLOAD observed), zero
    overloads on the small tenants, and zero non-overload errors."""
    import threading

    import numpy as np
    from ..cluster import InProcCluster
    from ..common.flags import graph_flags
    from ..common.qos import admission
    from ..common.status import ErrorCode
    from ..engine_tpu import TpuGraphEngine

    rng = random.Random(seed)
    admission.reset()
    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    conn = cluster.connect()
    tenants = ["t_a", "t_b"]
    np_rng = np.random.default_rng(seed)

    def load(space, v, e):
        conn.must(f"CREATE SPACE {space}(partition_num=2)")
        conn.must(f"USE {space}")
        conn.must("CREATE TAG person(age int)")
        conn.must("CREATE EDGE knows(w int)")
        for i in range(0, v, 2000):
            conn.must("INSERT VERTEX person(age) VALUES " + ", ".join(
                f"{j}:({j % 80})" for j in range(i, min(i + 2000, v))))
        srcs = np_rng.integers(0, v, e)
        dsts = np_rng.integers(0, v, e)
        for i in range(0, e, 2000):
            conn.must("INSERT EDGE knows(w) VALUES " + ", ".join(
                f"{int(s)} -> {int(d)}:({int((s + d) % 101)})"
                for s, d in zip(srcs[i:i + 2000], dsts[i:i + 2000])))
        sid = cluster.meta.get_space(space).value().space_id
        tpu.prewarm(sid, block=True)
        return [int(x) for x in
                np.argsort(np.bincount(srcs, minlength=v))[-3:]], v

    hubs = {}
    for t in tenants:
        hubs[t], _ = load(t, 600, 3000)
    ab_hubs, ab_v = load("t_abuser", 800, 5000)

    # QoS armed: the abuser throttled + bulk-laned, shed standing by
    graph_flags.set("qos_plan", "t_abuser:rate=6,burst=6,lane=bulk")
    graph_flags.set("qos_shed_queue_depth", 24)

    errors: list = []
    overloads = {"abuser": 0, "small": 0}
    counts = {"queries": 0, "abuser_served": 0, "verifies": 0}
    lock = threading.Lock()
    vlock = threading.Lock()   # one identity verify at a time: the
    # engine-enable toggle is global, and overlapped toggles would
    # compare TPU-vs-TPU instead of TPU-vs-CPU
    stop = threading.Event()

    def verify(c, q, rows):
        with vlock:
            tpu.enabled = False
            try:
                rc = c.must(q)
            finally:
                tpu.enabled = True
        if sorted(map(repr, rows)) != sorted(map(repr, rc.rows)):
            _debug_bundle(cluster, tpu, {
                "failure": "identity_divergence", "query": q,
                "tpu_rows": sorted(map(repr, rows))[:20],
                "cpu_rows": sorted(map(repr, rc.rows))[:20]})
            errors.append(f"IDENTITY DIVERGENCE: {q}")
            stop.set()
            return
        with lock:
            counts["verifies"] += 1

    def tenant_worker(t, k):
        rr = random.Random(seed * 50 + k)
        c = cluster.connect()
        c.must(f"USE {t}")
        n = 0
        while not stop.is_set():
            h = rr.choice(hubs[t])
            steps = rr.choice([1, 2, 2])
            q = (f"GO {steps} STEPS FROM {h} OVER knows "
                 f"WHERE knows.w > {rr.randrange(80)} "
                 f"YIELD knows._dst, knows.w")
            r = c.execute(q)
            if r.ok():
                with lock:
                    counts["queries"] += 1
                n += 1
                if n % 15 == 0:
                    verify(c, q, r.rows)
            elif r.code == ErrorCode.E_OVERLOAD:
                with lock:
                    overloads["small"] += 1
            else:
                errors.append(f"{t}: [{r.code.name}] {r.error_msg}")
                stop.set()

    def abuser_worker(k):
        rr = random.Random(seed * 77 + k)
        c = cluster.connect()
        c.must("USE t_abuser")
        while not stop.is_set():
            if rr.random() < 0.1:
                # light write mix on the ABUSER's own space only (the
                # small tenants stay static so their continuous
                # identity checks can't race a mutation)
                s, d = rr.randrange(ab_v), rr.randrange(ab_v)
                q = (f"INSERT EDGE knows(w) VALUES "
                     f"{s} -> {d}:({(s + d) % 101})")
            else:
                q = (f"GO 3 STEPS FROM {rr.choice(ab_hubs)} OVER knows "
                     f"YIELD knows._dst")
            r = c.execute(q)
            if r.ok():
                with lock:
                    counts["abuser_served"] += 1
            elif r.code == ErrorCode.E_OVERLOAD:
                with lock:
                    overloads["abuser"] += 1
                time.sleep(0.02)        # the retryable contract
            else:
                errors.append(f"abuser: [{r.code.name}] {r.error_msg}")
                stop.set()

    # nlint: disable=NL002 -- load-origin tenant workers; no inbound trace
    threads = [threading.Thread(target=tenant_worker, args=(t, k),
                                daemon=True,
                                name=f"soak-tenant-{k}")
               for k, t in enumerate(tenants)]
    # nlint: disable=NL002 -- load-origin abuser workers (above)
    threads += [threading.Thread(target=abuser_worker, args=(k,),
                                 daemon=True,
                                 name=f"soak-abuser-{k}")
                for k in range(2)]
    try:
        for th in threads:
            th.start()
        deadline = time.monotonic() + seconds
        # floor: enough verifies to mean something even on a slow box
        # — but BOUNDED (4x the budget): if verifies stall without an
        # error the soak must exit with a failing report, not hang
        hard_stop = time.monotonic() + 4 * max(seconds, 1.0)
        while (time.monotonic() < deadline
               or counts["verifies"] < 6) and not stop.is_set() \
                and time.monotonic() < hard_stop:
            time.sleep(0.05)
        stop.set()
        for th in threads:
            th.join(timeout=60)
    finally:
        graph_flags.set("qos_plan", "")
        graph_flags.set("qos_shed_queue_depth", 0)
    adm = admission.describe()
    denied = adm["spaces"].get("t_abuser", {}).get("denied", 0)
    out = {
        "seconds": seconds, "tenants": len(tenants),
        "queries": counts["queries"],
        "identity_verifies": counts["verifies"],
        "abuser": {"served": counts["abuser_served"],
                   "overloads": overloads["abuser"],
                   "denied": denied},
        "small_tenant_overloads": overloads["small"],
        "errors": errors[:5],
        "qos": {"admission": adm, "dispatcher": tpu.qos_stats()},
    }
    out["ok"] = (not errors and counts["verifies"] >= 6
                 and counts["queries"] > 0 and denied > 0
                 and overloads["abuser"] > 0
                 and counts["abuser_served"] > 0
                 and overloads["small"] == 0)
    return out


def run_soak_skew(seconds: float = 8.0, seed: int = 31,
                  v: int = 800, e: int = 6000) -> dict:
    """Skewed-workload soak (`soak --skew`; ISSUE 14): the bench
    tier's Zipf start-vid generator drives a mixed read/write load
    with the workload observatory ARMED, under CONTINUOUS identity
    verifies — proving the heat/sketch charge seams never perturb
    serving while the sketch's top-K recall vs the soak's own ground
    truth stays >= 0.9 and the per-space skew index reads the
    concentration the generator injected."""
    import numpy as np

    from ..common import heat as heat_mod
    from ..common.flags import graph_flags, storage_flags

    rng = random.Random(seed)
    np_rng = np.random.default_rng(seed)
    heat_mod.accountant.reset()
    graph_flags.set("heat_enabled", True)
    storage_flags.set("heat_enabled", True)
    graph_flags.set("heat_vertices_k", 64)
    storage_flags.set("heat_vertices_k", 64)
    # own setup (not _setup_cluster): 8 parts so the per-part skew
    # index has room to separate — 4 parts average the hot vids out
    from ..cluster import InProcCluster
    from ..engine_tpu import TpuGraphEngine
    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    conn = cluster.connect()
    conn.must("CREATE SPACE skewsoak(partition_num=8)")
    conn.must("USE skewsoak")
    conn.must("CREATE TAG person(age int)")
    conn.must("CREATE EDGE knows(w int)")
    for i in range(0, v, 2000):
        conn.must("INSERT VERTEX person(age) VALUES " + ", ".join(
            f"{j}:({j % 80})" for j in range(i, min(i + 2000, v))))
    srcs = np_rng.integers(0, v, e)
    dsts = np_rng.integers(0, v, e)
    for i in range(0, e, 2000):
        conn.must("INSERT EDGE knows(w) VALUES " + ", ".join(
            f"{int(s)} -> {int(d)}:({int((s + d) % 101)})"
            for s, d in zip(srcs[i:i + 2000], dsts[i:i + 2000])))
    conn.must("GO FROM 0 OVER knows")
    sid = cluster.meta.get_space("skewsoak").value().space_id
    tpu.prewarm(sid, block=True)

    def zipf_vid() -> int:
        # the bench tier's generator, sharpened (alpha 1.5): clipped
        # zipf rank -> a scattered vid (deterministic map so ground
        # truth is countable; the sketch additionally sees the
        # identity verifies' CPU-pipe scanned src vids, so the hot
        # starts must dominate with margin)
        while True:
            r = int(np_rng.zipf(1.5))
            if r <= v:
                return (r * 131 + 7) % v

    truth: dict = {}
    lats: List[float] = []
    queries = writes = verifies = 0
    deadline = time.monotonic() + seconds
    min_queries = 200
    try:
        while time.monotonic() < deadline or queries < min_queries:
            if rng.random() < 0.15:
                s, d = zipf_vid(), rng.randrange(v)
                conn.must(f"INSERT EDGE knows(w) VALUES "
                          f"{s} -> {d}:({(s + d) % 101})")
                writes += 1
                continue
            start = zipf_vid()
            truth[start] = truth.get(start, 0) + 1
            steps = rng.choice([1, 2, 2])
            q = (f"GO {steps} STEPS FROM {start} OVER knows "
                 f"YIELD knows._dst, knows.w")
            t0 = time.monotonic()
            r = conn.must(q)
            lats.append((time.monotonic() - t0) * 1e3)
            queries += 1
            if queries % 20 == 0:          # continuous identity
                tpu.enabled = False
                try:
                    rc = conn.must(q)
                finally:
                    tpu.enabled = True
                if sorted(map(repr, r.rows)) != \
                        sorted(map(repr, rc.rows)):
                    _debug_bundle(cluster, tpu, {
                        "failure": "identity_divergence", "query": q})
                    raise AssertionError(
                        f"IDENTITY DIVERGENCE on: {q}")
                verifies += 1
    finally:
        graph_flags.set("heat_vertices_k", 0)
        storage_flags.set("heat_vertices_k", 0)
    # the soak sketch legitimately merges TWO streams — the Zipf
    # start vids AND the identity verifies' CPU-pipe scanned src vids
    # (both are "hot vertex" signal) — while `truth` counts only the
    # starts. The gate is therefore the unambiguous hot HEAD: the
    # top-5 start vids dominate any scan-stream vid by an order of
    # magnitude and must all be recalled; the full top-10 recall is
    # recorded (and gated at the pure-stream bench tier, where it
    # must be >= 0.9).
    K = 10
    true_sorted = sorted(truth.items(), key=lambda kv: kv[1],
                         reverse=True)
    true_top = [x for x, _ in true_sorted[:K]]
    sk = heat_mod.accountant.sketch(sid)
    est_top = [int(r["vid"]) for r in (sk.topk(K) if sk else [])]
    recall = len(set(true_top) & set(est_top)) / K
    head_recalled = set(true_top[:5]) <= set(est_top)
    skew = heat_mod.accountant.skew_index(sid, window=600)
    lat = np.sort(np.asarray(lats)) if lats else np.zeros(1)
    out = {
        "seconds": seconds, "queries": queries, "writes": writes,
        "identity_verifies": verifies,
        "qps": round(queries / max(seconds, 1e-9), 1),
        "latency_ms": {
            "p50": round(float(np.percentile(lat, 50)), 2),
            "p99": round(float(np.percentile(lat, 99)), 2)},
        "sketch": {"recall": round(recall, 3),
                   "head_recalled": head_recalled,
                   "k": sk.k if sk else 0,
                   "tracked": len(sk.counts) if sk else 0,
                   "true_topk": true_top, "est_topk": est_top},
        "skew_index": skew,
        "heat_parts": len(heat_mod.accountant.parts_snapshot()),
    }
    # head_recalled is the robust gate; the tail floors are loose on
    # purpose — a short soak on a loaded box draws few zipf samples
    # and the rank-7..10 counts get noisy (the tight >= 0.9 recall
    # gate lives at the pure-stream bench tier)
    out["ok"] = (verifies > 0 and head_recalled and recall >= 0.5
                 and skew["index"] > 1.05
                 and (sk is not None and len(sk.counts) <= sk.k))
    return out


def run_soak_churn(seconds: float = 10.0, seed: int = 43,
                   v: int = 1500, e: int = 8000,
                   bound_ms: float = 2000.0) -> dict:
    """`--churn` (ISSUE 19): sustained write-heavy churn with the
    write-path observatory armed and the change ring deliberately
    small (REBOOT-effective cap captured at CREATE SPACE), so write
    bursts genuinely roll the ring — overrun -> snapshot poison ->
    full host repack cycling CONTINUOUSLY under load — with the soak's
    signature continuous TPU-vs-CPU identity verifies, and the
    ack-to-visible watermark as a GATE: at quiesce every acked write
    must have become visible (pending drains to zero over anchor
    reads) and the run's observed ack-to-visible p99 must stay within
    bound_ms (docs/manual/10-observability.md, "Write-path
    observatory")."""
    import numpy as np

    from ..common import writepath as wp
    from ..common.flags import graph_flags, storage_flags
    from ..common.stats import stats as _gstats

    rng = random.Random(seed)
    saved = {"g": graph_flags.get("write_obs_enabled"),
             "s": storage_flags.get("write_obs_enabled"),
             "ring": storage_flags.get("change_ring_ops")}
    graph_flags.set("write_obs_enabled", True)
    storage_flags.set("write_obs_enabled", True)
    # a production-sized ring never overruns at soak scale; a tiny one
    # makes the bursts below a real overrun workload
    storage_flags.set("change_ring_ops", 64)
    try:
        cluster, conn, tpu, srcs, dsts = _setup_cluster(
            "churn", v, e, seed)
    finally:
        storage_flags.set("change_ring_ops", saved["ring"])
    sid = cluster.meta.get_space("churn").value().space_id
    ov0 = _gstats.lifetime_total("write.ring.overrun")
    led0 = dict(wp.snapshots.view()["counts"])
    try:
        lats: List[float] = []
        queries = writes = verifies = 0
        max_lag_ms = 0.0
        deadline = time.monotonic() + seconds
        min_queries = 60
        while time.monotonic() < deadline or queries < min_queries:
            # write burst long enough to roll the 64-op ring past its
            # floor before the next read pulls the delta
            for _ in range(rng.randrange(40, 120)):
                s, d = rng.randrange(v), rng.randrange(v)
                if rng.random() < 0.85:
                    conn.must(f"INSERT EDGE knows(w) VALUES "
                              f"{s} -> {d}:({(s + d) % 101})")
                else:
                    conn.must(f"DELETE EDGE knows {s} -> {d}")
                writes += 1
            wm = wp.watermark.stats_view().get(sid) or {}
            max_lag_ms = max(max_lag_ms, wm.get("lag_ms", 0.0))
            seed_vid = rng.randrange(v)
            steps = rng.choice([1, 2, 2])
            q = (f"GO {steps} STEPS FROM {seed_vid} OVER knows "
                 f"WHERE knows.w > {rng.randrange(0, 101)} "
                 f"YIELD knows._dst, knows.w")
            t0 = time.monotonic()
            r = conn.must(q)
            lats.append((time.monotonic() - t0) * 1e3)
            queries += 1
            if queries % 4 == 0:      # continuous identity, mid-churn
                tpu.enabled = False
                try:
                    rc = conn.must(q)
                finally:
                    tpu.enabled = True
                if sorted(map(repr, r.rows)) != \
                        sorted(map(repr, rc.rows)):
                    _debug_bundle(cluster, tpu, {
                        "failure": "identity_divergence", "query": q})
                    raise AssertionError(
                        f"IDENTITY DIVERGENCE on: {q}")
                verifies += 1
        # quiesce: anchor reads pull the remaining deltas (or wait out
        # an in-flight repack) until every acked write became visible
        wmv: dict = {}
        drain_deadline = time.monotonic() + 20
        while time.monotonic() < drain_deadline:
            conn.must("GO FROM 0 OVER knows")
            wmv = dict(wp.watermark.stats_view().get(sid) or {})
            if wmv.get("pending", 1) == 0 \
                    and not any(tpu._repacking.values()):
                break
            time.sleep(0.05)
    finally:
        graph_flags.set("write_obs_enabled", saved["g"])
        storage_flags.set("write_obs_enabled", saved["s"])
    overruns = _gstats.lifetime_total("write.ring.overrun") - ov0
    counts = wp.snapshots.view()["counts"]
    led = {k: counts.get(k, 0) - led0.get(k, 0)
           for k in ("overrun", "poison", "repack", "build")}
    h = _gstats.histogram_snapshot("write.ack_to_visible_ms")
    p99 = _gstats.read_stats("write.ack_to_visible_ms.p99.600")
    stage_counts = {}
    for stg in ("execute", "fanout", "commit_apply", "ring_publish",
                "delta_apply", "repack"):
        sh = _gstats.histogram_snapshot(f"write.stage.{stg}_us")
        stage_counts[stg] = int(sh["count"]) if sh else 0
    with tpu._lock:
        stats = dict(tpu.stats)
    lat = np.sort(np.asarray(lats)) if lats else np.zeros(1)
    out = {
        "seconds": seconds, "queries": queries, "writes": writes,
        "identity_verifies": verifies,
        "latency_ms": {"p50": round(float(np.percentile(lat, 50)), 2),
                       "p99": round(float(np.percentile(lat, 99)), 2)},
        "watermark": {**wmv, "bound_ms": bound_ms,
                      "max_lag_ms": round(max_lag_ms, 2)},
        "ack_to_visible_ms": {"count": int(h["count"]) if h else 0,
                              "p99_600s": p99},
        "ring": {"overruns": overruns, "lifecycle": led},
        "stages": stage_counts,
        "bg_repacks": stats["bg_repacks"],
        "delta_applies": stats["delta_applies"],
    }
    out["ok"] = (verifies >= 5
                 and wmv.get("pending", 1) == 0
                 and (h is not None and h["count"] > 0)
                 and p99 is not None and p99 <= bound_ms
                 and overruns >= 1 and led["repack"] >= 1
                 and all(stage_counts[s] > 0 for s in
                         ("execute", "fanout", "commit_apply")))
    return out


def run_soak_crash(seconds: float = 45.0, seed: int = 29) -> dict:
    """`--crash`: periodic SIGKILL/restart of one SUBPROCESS storaged
    (crashstorm topology: real processes on per-node data dirs, same
    machinery as `bench --crash`) under continuous TPU-vs-CPU identity
    verifies and ledger-journaling writers. ok requires: >= 2 crash/
    restart cycles completed with recovery, every acked write readable
    at the end, identity green throughout, zero non-retryable errors,
    and >= 1 wal_replay flight event observed across the restarts."""
    import shutil
    import tempfile
    import threading

    from ..client import GraphClient
    from ..engine_tpu import TpuGraphEngine
    from .crashstorm import (RETRYABLE, CrashTopology, LedgerWriters,
                             load_person_knows)

    v, e, parts, space = 240, 1500, 3, "soakcrash"
    run_dir = tempfile.mkdtemp(prefix="nebula_tpu_soakcrash_")
    rng = random.Random(seed)
    crashes = 0
    replay_events = 0
    verifies = 0
    errors: list = []
    topo = None
    try:
        tpu = TpuGraphEngine()
        topo = CrashTopology(run_dir, n=3, tpu_engine=tpu)
        gc = GraphClient(topo.graphd.addr).connect()
        srcs, _dsts, _ts = load_person_knows(
            gc, space, parts, v, e, seed, settle_s=30.0)
        sid = topo.metad.meta.get_space(space).value().space_id
        deg: dict = {}
        for s in srcs:
            deg[s] = deg.get(s, 0) + 1
        hubs = [s for s, _ in sorted(deg.items(), key=lambda kv: -kv[1])
                [:3]]
        queries = [
            f"GO 2 STEPS FROM {hubs[0]} OVER knows YIELD knows._dst",
            f"GO FROM {hubs[1]}, {hubs[2]} OVER knows "
            f"YIELD knows._dst, knows.ts",
        ]
        for q in queries:
            gc.must(q)
        topo.wait_leaders(sid, parts)
        # continuous-consistency assertion (ISSUE 15): shadow-read
        # sampling on the in-proc graphd for the whole storm; replica
        # divergence polled from the SUBPROCESS storagds' /consistency
        # at the end (their digest exchange runs in their processes)
        ctok = _arm_consistency(rate=0.1)

        def divergent_replicas() -> list:
            import json as _json
            import urllib.request
            found = []
            for n in topo.nodes:
                if n.pid is None:
                    continue
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{n.ws_port}/consistency",
                            timeout=3) as r:
                        doc = _json.loads(r.read())
                except Exception:
                    continue
                for p in doc.get("parts") or []:
                    for rep in p.get("digest_divergent") or []:
                        found.append({"node": n.name,
                                      "space": p["space"],
                                      "part": p["part"],
                                      "replica": rep})
            return found

        writers = LedgerWriters(topo.graphd.addr, space, v,
                                n_writers=1, pace_s=0.015).start()
        stop = threading.Event()

        def verifier():
            nonlocal verifies
            rr = random.Random(seed + 1)
            c = GraphClient(topo.graphd.addr).connect()
            c.must(f"USE {space}")
            while not stop.is_set():
                time.sleep(0.15)
                q = queries[rr.randrange(len(queries))]
                # writes quiesced for the TPU/CPU pair — an in-flight
                # write landing between the two reads would diverge
                # them legitimately (the one-engine-toggle-at-a-time
                # idiom every soak verify uses)
                if not writers.quiesce(timeout=30.0):
                    writers.resume()
                    continue
                try:
                    rt = c.execute(q)
                    if not rt.ok():
                        if rt.code in RETRYABLE:
                            continue
                        errors.append(f"verify: [{rt.code.name}] "
                                      f"{rt.error_msg}")
                        stop.set()
                        return
                    tpu.enabled = False
                    try:
                        rc = c.execute(q)
                    finally:
                        tpu.enabled = True
                    if not rc.ok():
                        continue  # cluster reconfiguring: skip compare
                    if sorted(map(repr, rt.rows)) != \
                            sorted(map(repr, rc.rows)):
                        errors.append(f"IDENTITY DIVERGENCE: {q}")
                        stop.set()
                        return
                    verifies += 1
                finally:
                    writers.resume()

        # nlint: disable=NL002 -- soak-lifetime verifier; no inbound trace
        vt = threading.Thread(target=verifier, daemon=True,
                              name="soak-crash-verifier")
        vt.start()
        deadline = time.monotonic() + seconds
        while time.monotonic() < deadline and not stop.is_set():
            time.sleep(min(3.0, max(seconds / 4, 1.0)))
            if stop.is_set():
                break
            i = rng.choice([j for j, n in enumerate(topo.nodes)
                            if n.pid is not None])
            topo.sigkill(i)
            time.sleep(0.8)
            topo.restart(i)
            try:
                topo.wait_recovered(i, sid, parts, timeout=90)
            except AssertionError as ex:
                errors.append(str(ex))
                stop.set()
                break
            replay_events += len(topo.flight_events(i, "wal_replay"))
            crashes += 1
        writers.pause()
        time.sleep(0.3)
        missing = writers.verify_ledger(gc)
        wsum = writers.summary()
        stop.set()
        writers.stop()
        vt.join(timeout=30)
        cons = _settle_consistency(ctok)
        div = divergent_replicas()
        cons["divergent_replicas"] = div
        out = {
            "seconds": seconds, "crashes": crashes,
            "identity_verifies": verifies,
            "wal_replay_events": replay_events,
            "ledger": {**wsum, "missing": len(missing),
                       "missing_samples": missing[:5]},
            "consistency": cons,
            "errors": errors[:5],
        }
        # shadow errors are tolerated here (a re-execution can land in
        # a kill window); mismatches and divergence are not — crash
        # recovery must leave every replica's content digest verifying
        out["ok"] = (not errors and crashes >= 2
                     and len(missing) == 0 and wsum["errors"] == 0
                     and wsum["acked"] > 0 and verifies >= 10
                     and replay_events >= 1
                     and cons["shadow"]["mismatches"] == 0
                     and not div)
        return out
    finally:
        try:
            if topo is not None:
                topo.stop()
        finally:
            shutil.rmtree(run_dir, ignore_errors=True)


def run_soak_cluster_reads(seconds: float = 20.0,
                           seed: int = 37) -> dict:
    """`--cluster-reads`: continuous-identity soak of the storaged-tier
    device shards WITH bounded-staleness follower reads armed (ISSUE
    16; docs/manual/12-replication.md "Follower reads"). An in-proc
    replicated 3-storaged topology serves GO windows from per-host CSR
    shards while a paced writer keeps versions moving; identity verify
    pairs (TPU cluster path vs CPU pipe, writer quiesced per pair) run
    for the whole soak. ok requires: identity green throughout, zero
    client errors, follower-SERVED parts > 0, and every served
    staleness within follower_read_max_ms + the shard-freshness slack."""
    import shutil
    import tempfile
    import threading

    from ..client import GraphClient
    from ..common.flags import storage_flags
    from ..daemons import serve_graphd, serve_metad, serve_storaged
    from ..engine_tpu import TpuGraphEngine

    v, e, parts, space, bound_ms = 240, 1500, 4, "soakreads", 150
    run_dir = tempfile.mkdtemp(prefix="nebula_tpu_soakreads_")
    rng = random.Random(seed)
    saved = {f: storage_flags.get(f) for f in
             ("heartbeat_interval_secs", "raft_heartbeat_ms",
              "raft_election_timeout_ms", "follower_read_max_ms")}
    storage_flags.set("heartbeat_interval_secs", 0.4)
    storage_flags.set("raft_heartbeat_ms", 60)
    storage_flags.set("raft_election_timeout_ms", 250)
    metad = graphd = None
    storers: list = []
    verifies = 0
    errors: list = []
    try:
        metad = serve_metad()
        for i in range(3):
            storers.append(serve_storaged(
                metad.addr, replicated=True, engine="mem",
                data_dir=f"{run_dir}/s{i}", load_interval=0.15))
        tpu = TpuGraphEngine()
        graphd = serve_graphd(metad.addr, tpu_engine=tpu)
        gc = GraphClient(graphd.addr).connect()
        for q in (f"CREATE SPACE {space}(partition_num={parts}, "
                  f"replica_factor=3)", f"USE {space}",
                  "CREATE TAG person(name string)",
                  "CREATE EDGE knows(ts int)"):
            r = gc.execute(q)
            assert r.ok(), (q, r.error_msg)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            r = gc.execute('INSERT VERTEX person(name) VALUES 0:("p")')
            if r.ok():
                break
            time.sleep(0.2)     # part elections still settling
        assert r.ok(), r.error_msg
        rows = ", ".join(f'{i}:("p{i}")' for i in range(1, v))
        assert gc.execute(
            f"INSERT VERTEX person(name) VALUES {rows}").ok()
        srcs = [rng.randrange(v) for _ in range(e)]
        dsts = [(s * 7 + k) % v for k, s in enumerate(srcs)]
        for lo in range(0, e, 500):
            chunk = ", ".join(
                f"{a} -> {b}:({(a + b) % 97})"
                for a, b in zip(srcs[lo:lo + 500], dsts[lo:lo + 500]))
            assert gc.execute(
                f"INSERT EDGE knows(ts) VALUES {chunk}").ok()
        deg: dict = {}
        for s in srcs:
            deg[s] = deg.get(s, 0) + 1
        hubs = [s for s, _ in sorted(deg.items(),
                                     key=lambda kv: -kv[1])[:3]]
        queries = [
            f"GO 2 STEPS FROM {hubs[0]} OVER knows YIELD knows._dst",
            f"GO FROM {hubs[1]}, {hubs[2]} OVER knows "
            f"YIELD knows._dst, knows.ts",
            f"GO 2 STEPS FROM {hubs[1]} OVER knows "
            f"WHERE knows.ts > 40 YIELD knows._dst, knows.ts",
        ]
        for q in queries:
            gc.must(q)
        # arm through the cluster config registry (the production
        # path) — a bare local flag set would be overwritten by the
        # next meta heartbeat pull
        gc.must(f"UPDATE CONFIGS STORAGE:follower_read_max_ms = "
                f"{bound_ms}")
        deadline = time.monotonic() + 15
        while storage_flags.get("follower_read_max_ms") != bound_ms \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        assert storage_flags.get("follower_read_max_ms") == bound_ms

        stop = threading.Event()
        pause = threading.Event()
        paused = threading.Event()

        def writer():
            wc = GraphClient(graphd.addr).connect()
            wc.must(f"USE {space}")
            rank = e + 1
            while not stop.is_set():
                if pause.is_set():
                    paused.set()
                    time.sleep(0.02)
                    continue
                paused.clear()
                a, b = rng.randrange(v), rng.randrange(v)
                r = wc.execute(f"INSERT EDGE knows(ts) VALUES "
                               f"{a} -> {b}@{rank}:({(a + b) % 97})")
                rank += 1
                if not r.ok():
                    errors.append(f"write: {r.error_msg}")
                time.sleep(0.02)

        # nlint: disable=NL002 -- soak-lifetime writer; no inbound trace
        wt = threading.Thread(target=writer, daemon=True,
                              name="soak-reads-writer")
        wt.start()
        deadline = time.monotonic() + seconds
        while time.monotonic() < deadline and not errors:
            q = queries[rng.randrange(len(queries))]
            pause.set()
            if not paused.wait(timeout=10.0):
                pause.clear()
                continue
            # writer quiesced + staleness drained: a follower partial
            # may trail by the bound; let it catch up so the TPU/CPU
            # pair compares one version (the identity contract is
            # bounded-stale, not time-travel)
            time.sleep((bound_ms + 100) / 1000.0)
            try:
                rt = gc.execute(q)
                if not rt.ok():
                    errors.append(f"verify: {rt.error_msg}")
                    break
                tpu.enabled = False
                try:
                    rc = gc.execute(q)
                finally:
                    tpu.enabled = True
                if not rc.ok():
                    errors.append(f"verify-cpu: {rc.error_msg}")
                    break
                if sorted(map(repr, rt.rows)) != \
                        sorted(map(repr, rc.rows)):
                    errors.append(f"IDENTITY DIVERGENCE: {q}")
                    break
                verifies += 1
            finally:
                pause.clear()
            time.sleep(0.05)
        stop.set()
        pause.clear()
        wt.join(timeout=20)
        cdev = dict(graphd.engine.client.device_stats)
        per_host = {}
        stal = [float(cdev.get("max_staleness_ms", 0.0))]
        for h in storers:
            mgr = getattr(h, "device_shards", None)
            if mgr is not None:
                per_host[h.addr] = dict(mgr.stats)
                stal.append(float(mgr.stats.get("max_staleness_ms", 0)))
        slack = int(storage_flags.get_or("device_shard_max_ms", 250,
                                         int))
        max_stal = round(max(stal), 2)
        follower_served = sum(s.get("follower_parts_served", 0)
                              for s in per_host.values())
        out = {
            "seconds": seconds, "identity_verifies": verifies,
            "bound_ms": bound_ms, "shard_slack_ms": slack,
            "max_served_staleness_ms": max_stal,
            "staleness_bounded": max_stal <= bound_ms + slack,
            "follower_parts_served": follower_served,
            "client_device": cdev, "per_host": per_host,
            "cluster_served": tpu.stats.get("cluster_served", 0),
            "errors": errors[:5],
        }
        out["ok"] = (not errors and verifies >= 5
                     and out["staleness_bounded"]
                     and follower_served > 0
                     and out["cluster_served"] > 0)
        return out
    finally:
        try:
            if graphd is not None:
                graphd.stop()
            for h in storers:
                try:
                    h.stop()
                except Exception:
                    pass
            if metad is not None:
                metad.stop()
        finally:
            for f, val in saved.items():
                storage_flags.set(f, val)
            shutil.rmtree(run_dir, ignore_errors=True)


def run_soak_nemesis(seconds: float = 25.0, seed: int = 41) -> dict:
    """`--nemesis`: the cluster-reads soak under a CYCLING network
    nemesis (ISSUE 18; docs/manual/9-robustness.md "Network nemesis").
    The same replicated 3-storaged topology with bounded-staleness
    follower reads armed, but a background scenario thread rotates
    link failures through the live transport — a symmetric raft split
    of one storaged, a gray (slow-not-dead) node, a lossy data link —
    healing between rounds, while the consistency observatory samples
    shadow reads the whole time. Writers tolerate RETRYABLE codes
    (that's the failover contract); ok requires identity green
    throughout, zero NON-retryable errors, every served staleness
    within the bound, zero shadow mismatches / replica divergence, and
    the nemesis having actually fired."""
    import shutil
    import tempfile
    import threading

    from ..client import GraphClient
    from ..common.faults import Nemesis, faults
    from ..common.flags import graph_flags, storage_flags
    from ..daemons import serve_graphd, serve_metad, serve_storaged
    from ..engine_tpu import TpuGraphEngine
    from ..meta.net_admin import raft_addr_of
    from .crashstorm import RETRYABLE

    v, e, parts, space, bound_ms = 240, 1500, 4, "soaknem", 150
    run_dir = tempfile.mkdtemp(prefix="nebula_tpu_soaknem_")
    rng = random.Random(seed)
    saved = {f: storage_flags.get(f) for f in
             ("heartbeat_interval_secs", "raft_heartbeat_ms",
              "raft_election_timeout_ms", "follower_read_max_ms",
              "consistency_enabled")}
    saved_g = {f: graph_flags.get(f) for f in
               ("consistency_enabled", "storage_client_timeout_ms")}
    storage_flags.set("heartbeat_interval_secs", 0.4)
    storage_flags.set("raft_heartbeat_ms", 60)
    storage_flags.set("raft_election_timeout_ms", 250)
    storage_flags.set("consistency_enabled", True)
    graph_flags.set("consistency_enabled", True)
    graph_flags.set("storage_client_timeout_ms", 2000)
    metad = graphd = None
    storers: list = []
    verifies = 0
    errors: list = []
    retried = [0]
    nemesis = Nemesis()
    try:
        metad = serve_metad(expired_threshold_secs=5)
        for i in range(3):
            storers.append(serve_storaged(
                metad.addr, replicated=True, engine="mem",
                data_dir=f"{run_dir}/s{i}", load_interval=0.15))
        tpu = TpuGraphEngine()
        graphd = serve_graphd(metad.addr, tpu_engine=tpu)
        gc = GraphClient(graphd.addr).connect()
        for q in (f"CREATE SPACE {space}(partition_num={parts}, "
                  f"replica_factor=3)", f"USE {space}",
                  "CREATE TAG person(name string)",
                  "CREATE EDGE knows(ts int)"):
            r = gc.execute(q)
            assert r.ok(), (q, r.error_msg)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            r = gc.execute('INSERT VERTEX person(name) VALUES 0:("p")')
            if r.ok():
                break
            time.sleep(0.2)     # part elections still settling
        assert r.ok(), r.error_msg
        rows = ", ".join(f'{i}:("p{i}")' for i in range(1, v))
        assert gc.execute(
            f"INSERT VERTEX person(name) VALUES {rows}").ok()
        srcs = [rng.randrange(v) for _ in range(e)]
        dsts = [(s * 7 + k) % v for k, s in enumerate(srcs)]
        for lo in range(0, e, 500):
            chunk = ", ".join(
                f"{a} -> {b}:({(a + b) % 97})"
                for a, b in zip(srcs[lo:lo + 500], dsts[lo:lo + 500]))
            assert gc.execute(
                f"INSERT EDGE knows(ts) VALUES {chunk}").ok()
        deg: dict = {}
        for s in srcs:
            deg[s] = deg.get(s, 0) + 1
        hubs = [s for s, _ in sorted(deg.items(),
                                     key=lambda kv: -kv[1])[:3]]
        queries = [
            f"GO 2 STEPS FROM {hubs[0]} OVER knows YIELD knows._dst",
            f"GO FROM {hubs[1]}, {hubs[2]} OVER knows "
            f"YIELD knows._dst, knows.ts",
            f"GO 2 STEPS FROM {hubs[1]} OVER knows "
            f"WHERE knows.ts > 40 YIELD knows._dst, knows.ts",
        ]
        for q in queries:
            gc.must(q)
        gc.must(f"UPDATE CONFIGS STORAGE:follower_read_max_ms = "
                f"{bound_ms}")
        deadline = time.monotonic() + 15
        while storage_flags.get("follower_read_max_ms") != bound_ms \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        assert storage_flags.get("follower_read_max_ms") == bound_ms
        cons_tok = _arm_consistency(rate=0.1)

        stop = threading.Event()
        pause = threading.Event()
        paused = threading.Event()

        def writer():
            wc = GraphClient(graphd.addr).connect()
            wc.must(f"USE {space}")
            rank = e + 1
            while not stop.is_set():
                if pause.is_set():
                    paused.set()
                    time.sleep(0.02)
                    continue
                paused.clear()
                a, b = rng.randrange(v), rng.randrange(v)
                stmt = (f"INSERT EDGE knows(ts) VALUES "
                        f"{a} -> {b}@{rank}:({(a + b) % 97})")
                rank += 1
                r = wc.execute(stmt)
                n = 0
                while (not r.ok() and r.code in RETRYABLE and n < 8
                       and not stop.is_set()):
                    n += 1
                    retried[0] += 1
                    time.sleep(min(0.05 * n, 0.4))
                    r = wc.execute(stmt)
                if not r.ok() and r.code not in RETRYABLE:
                    errors.append(f"write: {r.code}: {r.error_msg}")
                time.sleep(0.02)

        def scenario():
            """Rotate nemesis shapes; ALWAYS healed while the identity
            pair runs (pause is the verify window)."""
            while not stop.is_set():
                i = rng.randrange(len(storers))
                s_addr = storers[i].addr
                v_raft = raft_addr_of(s_addr)
                o_rafts = [raft_addr_of(h.addr)
                           for h in storers if h.addr != s_addr]
                plan = rng.choice([
                    Nemesis.symmetric_split([v_raft], o_rafts),
                    Nemesis.slow_node([s_addr], latency_ms=200.0,
                                      jitter_ms=80.0),
                    Nemesis.lossy_link([s_addr], drop=0.3),
                ])
                if pause.is_set():      # verify window: stay healed
                    time.sleep(0.1)
                    continue
                nemesis.apply(plan)
                stop.wait(0.8)
                nemesis.heal()
                stop.wait(0.6)          # let elections/hints settle

        # nlint: disable=NL002 -- soak-lifetime threads; no inbound trace
        wt = threading.Thread(target=writer, daemon=True,
                              name="soak-nemesis-writer")
        # nlint: disable=NL002 -- soak-lifetime scenario driver (above)
        nt = threading.Thread(target=scenario, daemon=True,
                              name="soak-nemesis-scenario")
        wt.start()
        nt.start()
        deadline = time.monotonic() + seconds
        while time.monotonic() < deadline and not errors:
            q = queries[rng.randrange(len(queries))]
            pause.set()
            if not paused.wait(timeout=10.0):
                pause.clear()
                continue
            nemesis.heal()              # verify on a healed network
            time.sleep((bound_ms + 100) / 1000.0)
            try:
                rt = gc.execute(q)
                if not rt.ok():
                    errors.append(f"verify: {rt.error_msg}")
                    break
                tpu.enabled = False
                try:
                    rc = gc.execute(q)
                finally:
                    tpu.enabled = True
                if not rc.ok():
                    errors.append(f"verify-cpu: {rc.error_msg}")
                    break
                if sorted(map(repr, rt.rows)) != \
                        sorted(map(repr, rc.rows)):
                    errors.append(f"IDENTITY DIVERGENCE: {q}")
                    break
                verifies += 1
            finally:
                pause.clear()
            time.sleep(0.05)
        stop.set()
        pause.clear()
        wt.join(timeout=20)
        nt.join(timeout=20)
        nemesis.heal()
        fired = dict(faults.counts())
        cons_block = _settle_consistency(cons_tok)
        client = graphd.engine.client
        cdev = dict(client.device_stats)
        per_host = {}
        stal = [float(cdev.get("max_staleness_ms", 0.0))]
        for h in storers:
            mgr = getattr(h, "device_shards", None)
            if mgr is not None:
                per_host[h.addr] = dict(mgr.stats)
                stal.append(float(mgr.stats.get("max_staleness_ms", 0)))
        slack = int(storage_flags.get_or("device_shard_max_ms", 250,
                                         int))
        max_stal = round(max(stal), 2)
        out = {
            "seconds": seconds, "identity_verifies": verifies,
            "bound_ms": bound_ms, "shard_slack_ms": slack,
            "max_served_staleness_ms": max_stal,
            "staleness_bounded": max_stal <= bound_ms + slack,
            "nemesis_fired": fired,
            "write_retries": retried[0],
            "peer_health": client.peer_health.snapshot(),
            "hedge": dict(client.hedge_stats),
            "consistency": cons_block,
            "client_device": cdev, "per_host": per_host,
            "errors": errors[:5],
        }
        out["ok"] = (not errors and verifies >= 5
                     and out["staleness_bounded"]
                     and cons_block["ok"]
                     and sum(fired.values()) > 0)
        return out
    finally:
        faults.reset()
        try:
            if graphd is not None:
                graphd.stop()
            for h in storers:
                try:
                    h.stop()
                except Exception:
                    pass
            if metad is not None:
                metad.stop()
        finally:
            for f, val in saved.items():
                storage_flags.set(f, val)
            for f, val in saved_g.items():
                graph_flags.set(f, val)
            shutil.rmtree(run_dir, ignore_errors=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="mixed INSERT+GO soak with continuous CPU/TPU "
                    "identity checks")
    ap.add_argument("--seconds", type=float, default=10.0)
    ap.add_argument("--write-ratio", type=float, default=0.3)
    ap.add_argument("--verify-every", type=int, default=20)
    ap.add_argument("--vertices", type=int, default=2000)
    ap.add_argument("--edges", type=int, default=10000)
    ap.add_argument("--concurrent", action="store_true",
                    help="multi-session dispatcher soak (burst/quiesce "
                         "phases) instead of the single-session mix")
    ap.add_argument("--threads", type=int, default=6)
    ap.add_argument("--faults", action="store_true",
                    help="run a background fault schedule (kernel/"
                         "encode/delta-apply injection windows) under "
                         "the soak WITH the full cache ladder armed "
                         "(cache_mode=full); identity checks must stay "
                         "green and no client may see an error")
    ap.add_argument("--chaos", action="store_true",
                    help="--faults plus forced trace sampling: the "
                         "soak additionally FAILS unless degraded "
                         "serves carry their degradation tags in the "
                         "sampled traces (trace-visibility proof)")
    ap.add_argument("--witness", action="store_true",
                    help="install the runtime lock-order witness "
                         "(common/lockwitness.py) for the whole soak: "
                         "the run additionally FAILS on a cycle in the "
                         "cross-thread lock acquisition graph or on a "
                         "sleep observed under a witnessed lock; the "
                         "observed graph lands in the output and in "
                         "the debug bundle on identity failure")
    ap.add_argument("--crash", action="store_true",
                    help="periodic SIGKILL/restart of one subprocess "
                         "storaged (the bench --crash topology) under "
                         "continuous identity verifies + a durability "
                         "ledger: every acked write must be readable "
                         "after each recovery (docs/manual/"
                         "12-replication.md)")
    ap.add_argument("--tenants", action="store_true",
                    help="skewed multi-tenant load under the QoS "
                         "ladder (one abusive tenant vs small ones; "
                         "docs/manual/14-qos.md): the abuser must be "
                         "throttled with typed E_OVERLOAD only, small "
                         "tenants unaffected, identity checks green")
    ap.add_argument("--cluster-reads", action="store_true",
                    help="replicated 3-storaged topology with bounded-"
                         "staleness follower reads ARMED under a paced "
                         "writer + continuous TPU-vs-CPU identity "
                         "verifies: follower-served parts must be > 0, "
                         "every served staleness within the bound, "
                         "identity green, zero errors (docs/manual/"
                         "12-replication.md)")
    ap.add_argument("--nemesis", action="store_true",
                    help="the --cluster-reads topology under a cycling "
                         "network nemesis (symmetric raft split / gray "
                         "node / lossy link, healed between rounds; "
                         "common/faults.py link rules in the live "
                         "transport) with the consistency observatory "
                         "sampling throughout: identity green, zero "
                         "non-retryable errors, staleness bounded, "
                         "zero shadow mismatches / divergence (docs/"
                         "manual/9-robustness.md)")
    ap.add_argument("--churn", action="store_true",
                    help="write-heavy sustained churn with the write-"
                         "path observatory armed and a deliberately "
                         "tiny change ring (overrun -> poison -> "
                         "repack cycling under load) under continuous "
                         "identity verifies: the ack-to-visible "
                         "watermark must drain to zero at quiesce and "
                         "its p99 stay within --churn-bound-ms "
                         "(docs/manual/10-observability.md)")
    ap.add_argument("--churn-bound-ms", type=float, default=2000.0,
                    help="ack-to-visible p99 gate for --churn")
    ap.add_argument("--skew", action="store_true",
                    help="Zipf-distributed start vids with the "
                         "workload observatory armed (common/heat.py) "
                         "under continuous identity verifies: the "
                         "hot-vertex sketch must recall >= 0.9 of the "
                         "soak's own ground-truth top-K and the skew "
                         "index must read the injected concentration")
    args = ap.parse_args(argv)
    # the continuous-profiling observatory rides every soak (ISSUE
    # 13): the sampler runs at profile_hz so an identity-failure debug
    # bundle arrives with the hot frames / lock contention / GC state
    # of the failure window, not an empty profile block
    from ..common import profiler as _prof
    _prof.ensure_started()
    if args.witness:
        # install before the run boots anything so every serve-path
        # lock construction is wrapped (module-level locks created by
        # earlier imports are only covered via NEBULA_TPU_LOCK_WITNESS)
        from ..common.lockwitness import witness
        witness.install()
    if args.crash:
        out = run_soak_crash(args.seconds)
    elif args.cluster_reads:
        out = run_soak_cluster_reads(args.seconds)
    elif args.nemesis:
        out = run_soak_nemesis(args.seconds)
    elif args.churn:
        out = run_soak_churn(args.seconds,
                             bound_ms=args.churn_bound_ms)
    elif args.skew:
        out = run_soak_skew(args.seconds)
    elif args.tenants:
        out = run_soak_tenants(args.seconds)
    elif args.concurrent:
        out = run_soak_concurrent(args.seconds, args.threads,
                                  args.vertices, args.edges,
                                  fault_schedule=args.faults,
                                  chaos=args.chaos)
    else:
        out = run_soak(args.seconds, args.write_ratio, args.verify_every,
                       args.vertices, args.edges,
                       progress=lambda q, w: print(
                           f"  ... {q} queries, {w} writes", flush=True),
                       fault_schedule=args.faults, chaos=args.chaos)
    if args.witness:
        from ..common.lockwitness import LockOrderViolation, witness
        out["lock_witness"] = witness.summary()
        if not out["lock_witness"]["clean"]:
            try:
                witness.assert_clean()
            except LockOrderViolation as e:
                print(f"soak: LOCK WITNESS VIOLATION: {e}", flush=True)
            out["ok"] = False
    print(json.dumps(out))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
