"""Offline CSV -> SST bulk-load generator (role parity: the reference's
spark-sstfile-generator — build per-partition SST files WITHOUT a
running cluster, stage them at a URL, then `DOWNLOAD`/`INGEST`).

Because there is no meta service in the offline path, the mapping
carries explicit ids and prop types:

    {
      "num_parts": 4,
      "vertices": [{"file": "players.csv", "tag_id": 1, "vid_col": "id",
                    "props": {"name": "string", "age": "int"}}],
      "edges":    [{"file": "likes.csv", "edge_type": 1,
                    "src_col": "src", "dst_col": "dst", "rank_col": null,
                    "props": {"likeness": "double"}}]
    }
"""
from __future__ import annotations

import argparse
import csv
import json
import os
from typing import Any, Dict

from ..codec.schema import PropType, Schema, SchemaField
from ..storage.sst import SstGenerator

_TYPES = {"int": PropType.INT, "string": PropType.STRING,
          "double": PropType.DOUBLE, "bool": PropType.BOOL,
          "timestamp": PropType.TIMESTAMP}


def _schema(props: Dict[str, str]) -> Schema:
    return Schema([SchemaField(name, _TYPES[t]) for name, t in props.items()])


def _coerce(value: str, t: str) -> Any:
    if t in ("int", "timestamp"):
        return int(value)
    if t == "double":
        return float(value)
    if t == "bool":
        return value.strip().lower() in ("1", "true", "yes")
    return value


def generate(mapping: Dict[str, Any], out_dir: str,
             base_dir: str = ".") -> Dict[int, int]:
    """Build per-part SSTs under out_dir; returns part -> kv pairs."""
    gen = SstGenerator(mapping["num_parts"])
    for vm in mapping.get("vertices", []):
        schema = _schema(vm["props"])
        with open(os.path.join(base_dir, vm["file"]), newline="") as f:
            for row in csv.DictReader(f):
                values = {p: _coerce(row[p], t)
                          for p, t in vm["props"].items()}
                gen.add_vertex(int(row[vm["vid_col"]]), vm["tag_id"],
                               schema, values)
    for em in mapping.get("edges", []):
        schema = _schema(em["props"])
        with open(os.path.join(base_dir, em["file"]), newline="") as f:
            for row in csv.DictReader(f):
                values = {p: _coerce(row[p], t)
                          for p, t in em["props"].items()}
                rank = int(row[em["rank_col"]]) if em.get("rank_col") else 0
                gen.add_edge(int(row[em["src_col"]]), em["edge_type"], rank,
                             int(row[em["dst_col"]]), schema, values)
    return gen.write(out_dir)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="offline SST generator")
    ap.add_argument("--mapping", required=True, help="mapping.json path")
    ap.add_argument("--out", required=True, help="output dir for SSTs")
    ap.add_argument("--base-dir", default=None, help="dir containing CSVs")
    args = ap.parse_args(argv)
    with open(args.mapping) as f:
        mapping = json.load(f)
    base = args.base_dir or os.path.dirname(os.path.abspath(args.mapping))
    counts = generate(mapping, args.out, base_dir=base)
    print(json.dumps({str(k): v for k, v in sorted(counts.items())}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
