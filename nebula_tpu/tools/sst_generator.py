"""Offline CSV -> SST bulk-load generator (role parity: the reference's
spark-sstfile-generator — build per-partition SST files WITHOUT a
running cluster, stage them at a URL, then `DOWNLOAD`/`INGEST`).

Because there is no meta service in the offline path, the mapping
carries explicit ids and prop types:

    {
      "num_parts": 4,
      "vertices": [{"file": "players.csv", "tag_id": 1, "vid_col": "id",
                    "props": {"name": "string", "age": "int"}}],
      "edges":    [{"file": "likes.csv", "edge_type": 1,
                    "src_col": "src", "dst_col": "dst", "rank_col": null,
                    "props": {"likeness": "double"}}]
    }
"""
from __future__ import annotations

import argparse
import csv
import json
import os
from typing import Any, Dict

from ..codec.schema import PropType, Schema, SchemaField
from ..storage.sst import SstGenerator

_TYPES = {"int": PropType.INT, "string": PropType.STRING,
          "double": PropType.DOUBLE, "bool": PropType.BOOL,
          "timestamp": PropType.TIMESTAMP}


def _schema(props: Dict[str, str]) -> Schema:
    return Schema([SchemaField(name, _TYPES[t]) for name, t in props.items()])


def _coerce(value: str, t: str) -> Any:
    if t in ("int", "timestamp"):
        return int(value)
    if t == "double":
        return float(value)
    if t == "bool":
        return value.strip().lower() in ("1", "true", "yes")
    return value


def _csv_chunk(path: str, w: int, nw: int):
    """DictReader over this worker's byte-range slice of the CSV (the
    Spark generator's input-split role): boundaries land between rows
    — worker w owns lines starting in [boundary(w), boundary(w+1)),
    with boundary(i) snapped forward to the next line start."""
    import io
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        header = f.readline()
        data_start = f.tell()
        span = size - data_start

        def snapped(i: int) -> int:
            if i <= 0:
                return data_start
            if i >= nw:
                return size
            f.seek(data_start + span * i // nw)
            f.readline()
            return min(f.tell(), size)

        lo, hi = snapped(w), snapped(w + 1)
        f.seek(lo)
        chunk = f.read(hi - lo)
    return csv.DictReader(io.StringIO((header + chunk).decode()))


def _feed(gen: SstGenerator, mapping: Dict[str, Any], base_dir: str,
          w: int, nw: int) -> None:
    for vm in mapping.get("vertices", []):
        schema = _schema(vm["props"])
        path = os.path.join(base_dir, vm["file"])
        for row in _csv_chunk(path, w, nw):
            values = {p: _coerce(row[p], t)
                      for p, t in vm["props"].items()}
            gen.add_vertex(int(row[vm["vid_col"]]), vm["tag_id"],
                           schema, values)
    for em in mapping.get("edges", []):
        schema = _schema(em["props"])
        path = os.path.join(base_dir, em["file"])
        for row in _csv_chunk(path, w, nw):
            values = {p: _coerce(row[p], t)
                      for p, t in em["props"].items()}
            rank = int(row[em["rank_col"]]) if em.get("rank_col") else 0
            gen.add_edge(int(row[em["src_col"]]), em["edge_type"], rank,
                         int(row[em["dst_col"]]), schema, values)


def generate(mapping: Dict[str, Any], out_dir: str,
             base_dir: str = ".") -> Dict[int, int]:
    """Build per-part SSTs under out_dir; returns part -> kv pairs."""
    gen = SstGenerator(mapping["num_parts"])
    _feed(gen, mapping, base_dir, 0, 1)
    return gen.write(out_dir)


def _worker_generate(args) -> None:
    mapping, base_dir, run_root, w, nw = args
    gen = SstGenerator(mapping["num_parts"])
    _feed(gen, mapping, base_dir, w, nw)
    gen.write(os.path.join(run_root, f"w{w}"))


def generate_parallel(mapping: Dict[str, Any], out_dir: str,
                      base_dir: str = ".",
                      workers: int = 0) -> Dict[int, int]:
    """Scale-out build (role parity: the reference's distributed Spark
    SST generator, tools/spark-sstfile-generator): the CSVs are split
    into per-worker byte ranges, each worker process encodes its slice
    into per-part sorted runs, and a k-way merge folds the runs into
    one final NSST per part. The same architecture runs across hosts:
    ship each worker a (w, nw) pair and merge the run directories."""
    import heapq
    import multiprocessing as mp
    import shutil

    if workers <= 0:
        from .. import native
        workers = min(8, native.usable_cpus())
    if workers <= 1:
        return generate(mapping, out_dir, base_dir)
    run_root = os.path.join(out_dir, "_runs")
    os.makedirs(run_root, exist_ok=True)
    # fork, not spawn: a fresh interpreter would re-run site
    # customization (which may dial an accelerator relay) per worker
    ctx = mp.get_context("fork" if "fork" in mp.get_all_start_methods()
                         else "spawn")
    jobs = [(mapping, base_dir, run_root, w, workers)
            for w in range(workers)]
    with ctx.Pool(workers) as pool:
        pool.map(_worker_generate, jobs)
    from ..storage.sst import part_file, read_sst, write_sst
    counts: Dict[int, int] = {}
    for p in range(1, mapping["num_parts"] + 1):
        runs = []
        for w in range(workers):
            f = os.path.join(run_root, f"w{w}", part_file(p))
            if os.path.exists(f):
                runs.append(read_sst(f))
        if runs:
            counts[p] = write_sst(os.path.join(out_dir, part_file(p)),
                                  list(heapq.merge(*runs)))
    shutil.rmtree(run_root, ignore_errors=True)
    return counts


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="offline SST generator")
    ap.add_argument("--mapping", required=True, help="mapping.json path")
    ap.add_argument("--out", required=True, help="output dir for SSTs")
    ap.add_argument("--base-dir", default=None, help="dir containing CSVs")
    ap.add_argument("--workers", type=int, default=1,
                    help="worker processes (0 = one per usable CPU); "
                         ">1 scales the build out over input splits")
    args = ap.parse_args(argv)
    with open(args.mapping) as f:
        mapping = json.load(f)
    base = args.base_dir or os.path.dirname(os.path.abspath(args.mapping))
    if args.workers == 1:
        counts = generate(mapping, args.out, base_dir=base)
    else:
        counts = generate_parallel(mapping, args.out, base_dir=base,
                                   workers=args.workers)
    print(json.dumps({str(k): v for k, v in sorted(counts.items())}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
