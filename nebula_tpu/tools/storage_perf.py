"""Storage perf driver (role parity: tools/storage-perf/StoragePerfTool
.cpp — flags threads/qps/totalReqs/method/min,max_vertex_id/size).

Measures sustained QPS and latency percentiles of one storage RPC kind
against a live cluster (or an in-proc client in tests)."""
from __future__ import annotations

import argparse
import random
import threading
import time
from typing import Any, Callable, Dict, List

from ..codec.row import RowWriter
from ..storage.types import NewEdge, NewVertex


def _percentile(sorted_us: List[float], p: float) -> float:
    if not sorted_us:
        return 0.0
    idx = min(len(sorted_us) - 1, int(p / 100.0 * len(sorted_us)))
    return sorted_us[idx]


def run_perf(client, sm, space_id: int, tag_id: int, etype: int,
             method: str = "getNeighbors", total_reqs: int = 1000,
             concurrency: int = 2, size: int = 16,
             min_vid: int = 1, max_vid: int = 10000,
             seed: int = 0) -> Dict[str, Any]:
    """Fire `total_reqs` requests of `method` from `concurrency` threads;
    returns {qps, total_reqs, errors, latency_us: {p50, p95, p99, avg}}."""
    tag_schema = sm.tag_schema(space_id, tag_id).value()
    edge_schema = sm.edge_schema(space_id, etype).value()
    rng = random.Random(seed)

    def vrow(i: int) -> bytes:
        w = RowWriter(tag_schema)
        for f in tag_schema.fields:
            w.set(f.name, i if f.type.name == "INT" else f"v{i}"
                  if f.type.name == "STRING" else float(i))
        return w.encode()

    def erow(i: int) -> bytes:
        w = RowWriter(edge_schema)
        for f in edge_schema.fields:
            w.set(f.name, i if f.type.name == "INT" else f"e{i}"
                  if f.type.name == "STRING" else float(i))
        return w.encode()

    def vid() -> int:
        return rng.randint(min_vid, max_vid)

    calls: Dict[str, Callable[[], Any]] = {
        "getNeighbors": lambda: client.get_neighbors(
            space_id, [vid() for _ in range(size)], [etype]),
        "getVertices": lambda: client.get_vertex_props(
            space_id, [vid() for _ in range(size)], [tag_id]),
        "addVertices": lambda: client.add_vertices(
            space_id, [NewVertex(vid(), [(tag_id, vrow(i))])
                       for i in range(size)]),
        "addEdges": lambda: client.add_edges(
            space_id, [NewEdge(vid(), etype, 0, vid(), erow(i))
                       for i in range(size)]),
    }
    if method not in calls:
        raise ValueError(f"unknown method {method!r}; one of {sorted(calls)}")
    call = calls[method]

    lock = threading.Lock()
    latencies: List[float] = []
    errors = [0]
    remaining = [total_reqs]

    def worker():
        while True:
            with lock:
                if remaining[0] <= 0:
                    return
                remaining[0] -= 1
            t0 = time.monotonic()
            try:
                resp = call()
                ok = resp.ok() if hasattr(resp, "ok") else all(
                    r.code.value == 0 for r in resp.results.values())
            except Exception:
                ok = False
            us = (time.monotonic() - t0) * 1e6
            with lock:
                latencies.append(us)
                if not ok:
                    errors[0] += 1

    t0 = time.monotonic()
    # nlint: disable=NL002 -- load-origin bench workers; no inbound trace
    threads = [threading.Thread(target=worker,
                                name=f"storage-perf-{i}")
               for i in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    latencies.sort()
    return {
        "method": method,
        "total_reqs": total_reqs,
        "errors": errors[0],
        "wall_s": round(wall, 3),
        "qps": round(total_reqs / wall, 1) if wall > 0 else 0.0,
        "latency_us": {
            "avg": round(sum(latencies) / len(latencies), 1) if latencies else 0,
            "p50": round(_percentile(latencies, 50), 1),
            "p95": round(_percentile(latencies, 95), 1),
            "p99": round(_percentile(latencies, 99), 1),
        },
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="storage perf tool")
    ap.add_argument("--meta", required=True, help="metad host:port")
    ap.add_argument("--space", required=True)
    ap.add_argument("--tag", default="test_tag")
    ap.add_argument("--edge", default="test_edge")
    ap.add_argument("--method", default="getNeighbors")
    ap.add_argument("--total-reqs", type=int, default=10000)
    ap.add_argument("--threads", type=int, default=2)
    ap.add_argument("--size", type=int, default=16)
    ap.add_argument("--min-vid", type=int, default=1)
    ap.add_argument("--max-vid", type=int, default=10000)
    args = ap.parse_args(argv)

    from ._net import storage_client_from_meta
    mc, sm, client = storage_client_from_meta(args.meta)
    try:
        space_id = mc.get_space(args.space).value().space_id
        tag_id = sm.tag_id(space_id, args.tag)
        etype = sm.edge_type(space_id, args.edge)
        if tag_id is None or etype is None:
            print(f"tag {args.tag!r} or edge {args.edge!r} not found in "
                  f"space {args.space!r}")
            return 1
        out = run_perf(client, sm, space_id, tag_id, etype,
                       method=args.method, total_reqs=args.total_reqs,
                       concurrency=args.threads, size=args.size,
                       min_vid=args.min_vid, max_vid=args.max_vid)
        import json
        print(json.dumps(out))
        return 0
    finally:
        mc.stop()


if __name__ == "__main__":
    raise SystemExit(main())
