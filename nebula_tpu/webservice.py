"""HTTP admin endpoints.

Role parity with the reference's `src/webservice/` (proxygen HTTP server
per daemon): `/status` liveness, `/flags` get/set (GET ?name=a,b / PUT
body name=value), `/get_stats?stats=metric.method.window,...` — plus
custom handlers a daemon registers (the reference's storage admin/
download/ingest endpoints hang off the same seam, WebService.h:31-49).

Observability surface (docs/manual/10-observability.md): every daemon
serves `/metrics` (OpenMetrics text exposition of the StatsManager —
native histograms with trace exemplars included — plus any registered
metric sources, the process-global flight-recorder/SLO gauges, a
`nebula_build_info` join-key gauge and process uptime), `/flight`
(the flight recorder's event ring, trigger states and captured
bundles) and `/slo` (declarative objectives + multi-window burn
rates). Daemons that opt in via `register_observability` additionally
serve `/traces` (the finished-trace ring: list/filter/get-by-id, plus
the ?arm=N X-Trace force knob) and `/queries` (active-query registry
+ slow-query log).

Implemented over http.server (stdlib) on a daemon thread; handlers are
plain callables `(query_params, body) -> (code, obj)`. A handler that
returns `bytes` is served verbatim as text/plain; a `(bytes, ctype)`
pair sets the content type (the OpenMetrics exposition); anything
else is JSON-encoded.
"""
from __future__ import annotations

import json
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from .common.flags import FlagRegistry
from .common.stats import StatsManager
# eager, not lazy: importing these DECLARES their graph_flags
# (slo_plan, flight_*) at daemon boot — a lazy handler-time import
# would make `PUT /flags slo_plan=...` on a fresh daemon silently
# fail (FlagRegistry.set returns False for undeclared names) until
# the first /slo or /metrics request happened to land
from .common import flight as _flight_mod
from .common import slo as _slo_mod
# likewise eager: declares profile_hz/profile_capture_hz/
# gc_pause_flight_ms on every registry at daemon boot (the continuous
# profiling observatory, common/profiler.py)
from .common import profiler as _profiler_mod
# likewise eager: declares heat_enabled/heat_vertices_k/
# heat_hot_part_pct/staleness_breach_ms on every registry at daemon
# boot and registers the flight "heat" collector (the workload & data
# observatory, common/heat.py)
from .common import heat as _heat_mod  # noqa: F401
# likewise eager: declares write_obs_enabled/visibility_stall_ms/
# fsync_stall_ms/change_ring_* on every registry at daemon boot and
# registers the flight "writepath" collector (the write-path
# observatory, common/writepath.py)
from .common import writepath as _writepath_mod

Handler = Callable[[Dict[str, str], bytes], Tuple[int, Any]]

OPENMETRICS_CTYPE = \
    "application/openmetrics-text; version=1.0.0; charset=utf-8"


def _escape_label(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"') \
        .replace("\n", r"\n")


class WebService:
    def __init__(self, name: str = "daemon",
                 flags: Optional[FlagRegistry] = None,
                 stats: Optional[StatsManager] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 build_labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.flags = flags
        self.stats = stats
        self._handlers: Dict[str, Handler] = {}
        self._metric_sources: List[Callable[[], Dict[str, Any]]] = []
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._host = host
        self._port = port
        # the fleet-dashboard join key + uptime (satellite: every
        # daemon's /metrics carries a static build-info gauge)
        self.build_labels: Dict[str, str] = dict(build_labels or {})
        self._t_start = time.monotonic()

        self.register("/status", self._status_handler)
        self.register("/flags", self._flags_handler)
        self.register("/get_stats", self._stats_handler)
        self.register("/metrics", self._metrics_handler)
        self.register("/flight", self._flight_handler)
        self.register("/slo", self._slo_handler)
        self.register("/profile", self._profile_handler)
        self.register("/nemesis", self._nemesis_handler)
        self.register("/snapshots", self._snapshots_handler)

    # ------------------------------------------------------------------
    def register(self, path: str, handler: Handler) -> None:
        self._handlers[path] = handler

    def add_metrics_source(self, fn: Callable[[], Dict[str, Any]]) -> None:
        """Extra /metrics gauges: `fn()` returns {name: number} — the
        seam daemons use to expose engine counter dicts (e.g. the TPU
        engine's serving/dispatcher/robustness counters) without
        double-counting them into the StatsManager windows."""
        self._metric_sources.append(fn)

    def start(self) -> int:
        ws = self

        class _Req(BaseHTTPRequestHandler):
            def log_message(self, *a):   # quiet
                pass

            def _serve(self, body: bytes):
                u = urlparse(self.path)
                h = ws._handlers.get(u.path)
                if h is None:
                    self.send_response(404)
                    self.end_headers()
                    self.wfile.write(b'{"error": "not found"}')
                    return
                params = {k: v[0] for k, v in parse_qs(u.query).items()}
                try:
                    code, obj = h(params, body)
                except Exception as e:   # handler bug -> 500
                    code, obj = 500, {"error": str(e)}
                if isinstance(obj, tuple) and len(obj) == 2 \
                        and isinstance(obj[0], bytes):
                    # (payload, content-type) — the OpenMetrics
                    # exposition declares its own media type
                    data, ctype = obj
                elif isinstance(obj, bytes):
                    # raw text responses (line-oriented text, not JSON)
                    data, ctype = obj, "text/plain; version=0.0.4"
                else:
                    data, ctype = json.dumps(obj).encode(), \
                        "application/json"
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                self._serve(b"")

            def do_PUT(self):
                n = int(self.headers.get("Content-Length", 0))
                self._serve(self.rfile.read(n))

            do_POST = do_PUT

        self._server = ThreadingHTTPServer((self._host, self._port), _Req)
        self._port = self._server.server_address[1]
        # a daemon serving /profile is a daemon being profiled: arm
        # the continuous-profiling observatory (sampler at the
        # profile_hz flag — 0 means no sampler thread at all — GC
        # callbacks, flight profile collector). Idempotent and
        # process-global, like the flight recorder.
        _profiler_mod.ensure_started()
        # nlint: disable=NL002 -- daemon-lifetime admin HTTP server
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True,
                                        name=f"webservice-{self.name}")
        self._thread.start()
        return self._port

    @property
    def port(self) -> int:
        return self._port

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    # ------------------------------------------------------------------
    # built-in handlers
    # ------------------------------------------------------------------
    def _status_handler(self, params, body) -> Tuple[int, Any]:
        return 200, {"status": "running", "name": self.name}

    def _flags_handler(self, params, body) -> Tuple[int, Any]:
        if self.flags is None:
            return 200, {}
        if body:
            # PUT name=value[&name2=value2]
            updates = {k: v[0] for k, v in parse_qs(body.decode()).items()}
            applied = {}
            for name, raw in updates.items():
                try:
                    val = json.loads(raw)
                except ValueError:
                    val = raw
                applied[name] = self.flags.set(name, val)
            return 200, applied
        names = params.get("name")
        items = self.flags.items()
        if names:
            want = set(names.split(","))
            items = [it for it in items if it[0] in want]
        return 200, {n: {"value": v, "mode": m} for n, v, m in items}

    def _stats_handler(self, params, body) -> Tuple[int, Any]:
        if self.stats is None:
            return 200, {}
        spec = params.get("stats")
        if not spec:
            return 200, self.stats.snapshot()
        out = {}
        for s in spec.split(","):
            v = self.stats.read_stats(s.strip())
            if v is not None:
                out[s.strip()] = v
        return 200, out

    def _metrics_handler(self, params, body) -> Tuple[int, Any]:
        """OpenMetrics text exposition: StatsManager families (# TYPE
        annotated per metric kind, histograms with exemplars) + the
        build-info/uptime gauges + the process-global flight/SLO
        gauges + every registered metric source rendered as gauges
        with stable names, `# EOF`-terminated. Family names are
        deduplicated (first writer wins — a source gauge whose name
        collides with a StatsManager family is skipped: its value
        already scrapes as that family's `_total` twin)."""
        from .common.stats import _prom_name, _prom_num
        lines: List[str] = []
        seen: set = set()
        if self.stats is not None:
            stat_lines = self.stats.prometheus_lines()
            lines.extend(stat_lines)
            for ln in stat_lines:
                if ln.startswith("# TYPE "):
                    seen.add(ln.split(" ", 3)[2])
        # build info: the standard fleet-dashboard join key (daemon
        # role + versions + runtime backend), plus process uptime
        labels = {"daemon": self.name, "version": _build_version(),
                  "python": "%d.%d" % sys.version_info[:2],
                  "jax_backend": _jax_backend()}
        labels.update(self.build_labels)
        lbl = ",".join(f'{k}="{_escape_label(v)}"'
                       for k, v in sorted(labels.items()))
        lines.append("# TYPE nebula_build_info gauge")
        lines.append(f"nebula_build_info{{{lbl}}} 1")
        lines.append("# TYPE nebula_process_uptime_seconds gauge")
        lines.append(f"nebula_process_uptime_seconds "
                     f"{time.monotonic() - self._t_start:.3f}")
        seen.update(("nebula_build_info",
                     "nebula_process_uptime_seconds"))
        # gauge sources: flight-recorder + SLO burn rates (process-
        # global, every daemon) then the daemon's registered sources
        sources: List[Callable[[], Dict[str, Any]]] = \
            [_flight_gauges, _slo_gauges, _profiler_gauges,
             _writepath_gauges] \
            + list(self._metric_sources)
        for src in sources:
            try:
                extra = src()
            except Exception:
                continue   # a broken source must not take down scrapes
            for name in sorted(extra):
                v = extra[name]
                if not isinstance(v, (int, float)) or isinstance(v, bool):
                    continue
                mn = _prom_name("nebula", name)
                if mn in seen:
                    continue
                seen.add(mn)
                lines.append(f"# TYPE {mn} gauge")
                lines.append(f"{mn} {_prom_num(v)}")
        lines.append("# EOF")
        return 200, (("\n".join(lines) + "\n").encode(),
                     OPENMETRICS_CTYPE)

    def _nemesis_handler(self, params, body) -> Tuple[int, Any]:
        """/nemesis: the network-nemesis admin surface, served by
        EVERY daemon (link rules evaluate in the caller's process, so
        a scenario driver must reach each node — docs/manual/
        9-robustness.md "Nemesis catalog"). GET = armed link rules +
        fire counts; PUT body `plan=<grammar>` installs the link plan
        (replacing only link rules — armed point specs survive);
        `?clear=1` heals every link. Only `peer=` link entries are
        accepted (400 otherwise); /faults owns point specs."""
        from .common.faults import faults as freg
        if body:
            fields = {k: v[0] for k, v in
                      parse_qs(body.decode(),
                               keep_blank_values=True).items()}
            if "plan" not in fields:
                return 400, {"error": "body must carry plan=<spec>"}
            try:
                freg.set_link_plan(fields["plan"])
            except ValueError as e:
                return 400, {"error": str(e)}
        elif params.get("clear"):
            freg.clear_links()
        d = freg.describe()
        return 200, {"links": d["links"], "fired": d["fired"]}

    # ------------------------------------------------------------------
    # flight recorder + SLO surfaces (process-global, every daemon —
    # docs/manual/10-observability.md)
    # ------------------------------------------------------------------
    def _flight_handler(self, params, body) -> Tuple[int, Any]:
        """/flight: GET = event ring + trigger states + bundle
        summaries (?limit=N); ?bundle=<id> = one full bundle;
        ?fire=<rule> = manual trigger (ops knob; 409 while the rule
        is cooling down — never a stale bundle passed off as fresh)."""
        recorder = _flight_mod.recorder
        if "bundle" in params:
            try:
                b = recorder.get_bundle(int(params["bundle"]))
            except ValueError:
                return 400, {"error": "bundle must be an integer id"}
            if b is None:
                return 404, {"error": f"no bundle {params['bundle']!r} "
                                      f"in memory"}
            return 200, b
        if "fire" in params:
            b, known = recorder.trigger(params["fire"])
            if not known:
                return 404, {"error": f"unknown trigger rule "
                                      f"{params['fire']!r}"}
            if b is None:
                return 409, {"error": f"rule {params['fire']!r} is "
                                      f"cooling down "
                                      f"(flight_cooldown_s)"}
            return 200, {"fired": params["fire"], "bundle_id": b["id"]}
        try:
            limit = int(params.get("limit", 100))
        except ValueError:
            return 400, {"error": "limit must be an integer"}
        return 200, recorder.describe(limit=limit)

    def _profile_handler(self, params, body) -> Tuple[int, Any]:
        """/profile (docs/manual/10-observability.md, "Continuous
        profiling"): top-N self-time per thread role, ?format=collapsed
        flamegraph output, ?seconds=N on-demand capture, ?thread=<role>
        filter, ?locks=1 contention table, ?compiles=1 XLA compile
        table."""
        return _profiler_mod.profile_endpoint(params, body)

    def _slo_handler(self, params, body) -> Tuple[int, Any]:
        """/slo: GET = objectives + multi-window burn rates; PUT body
        `plan=<grammar>` installs a plan (400 keeps the previous one);
        ?clear=1 disarms."""
        engine = _slo_mod.engine
        if body:
            fields = {k: v[0] for k, v in
                      parse_qs(body.decode(),
                               keep_blank_values=True).items()}
            if "plan" not in fields:
                return 400, {"error": "body must carry plan=<spec>"}
            try:
                engine.set_plan(fields["plan"])
            except ValueError as e:
                return 400, {"error": str(e)}
        elif params.get("clear"):
            engine.clear()
        return 200, engine.describe()

    def _snapshots_handler(self, params, body) -> Tuple[int, Any]:
        """/snapshots: the write-path observatory's snapshot lifecycle
        surface (common/writepath.py) — ack-to-visible watermark per
        space, build/delta/poison/repack event history with durations
        and causes, change-ring occupancy, and each registered engine's
        live snapshot status. Served by every daemon (graphd's TPU
        engine AND storaged device serving both register); disarmed ->
        {"enabled": false}."""
        return 200, _writepath_mod.snapshots_view()

    # ------------------------------------------------------------------
    # tracing + query-visibility endpoints (opt-in per daemon)
    # ------------------------------------------------------------------
    def register_observability(self, ring=None, active=None,
                               slow=None) -> None:
        """Wire /traces and /queries. `ring` defaults to the process
        tracer's ring; `active` is an ActiveQueryRegistry, `slow` a
        SlowQueryLog (either may be None — the endpoint still serves
        with the section empty)."""
        from .common import tracing

        def traces_handler(params, body) -> Tuple[int, Any]:
            # resolve the ring per request: tracer.ring is swappable
            # (tools/soak.py gives chaos runs a private ring) and a
            # capture at registration time would serve a frozen deque
            trace_ring = ring if ring is not None else \
                tracing.tracer.ring
            # ?arm=N — the X-Trace admin knob: force-sample the next N
            # queries regardless of trace_sample_rate
            if "arm" in params:
                try:
                    n = int(params["arm"])
                except ValueError:
                    return 400, {"error": "arm must be an integer"}
                return 200, {"armed": tracing.tracer.arm(n)}
            # ?critpath=<id> — fold one trace (remote fragments
            # included) into its dominant-path attribution
            # (common/critpath.py; "73% proc.scan_part on host B")
            cp = params.get("critpath")
            if cp:
                t = trace_ring.get(cp)
                if t is None:
                    return 404, {"error": f"trace {cp!r} not in ring"}
                from .common import critpath
                return 200, critpath.analyze(t)
            tid = params.get("id")
            if tid:
                t = trace_ring.get(tid)
                if t is None:
                    return 404, {"error": f"trace {tid!r} not in ring"}
                if params.get("render"):
                    return 200, {"trace_id": tid,
                                 "tree": tracing.render_tree(t)}
                return 200, t
            try:
                min_dur_us = int(float(params.get("min_dur_ms", 0))
                                 * 1000)
                limit = int(params.get("limit", 50))
            except ValueError:
                return 400, {"error": "min_dur_ms/limit must be numeric"}
            return 200, {"traces": trace_ring.list(
                min_dur_us=min_dur_us, feature=params.get("feature"),
                limit=limit), "ring_size": len(trace_ring),
                "armed": tracing.tracer.armed()}

        def queries_handler(params, body) -> Tuple[int, Any]:
            try:
                limit = int(params.get("limit", 50))
            except ValueError:
                return 400, {"error": "limit must be an integer"}
            return 200, {
                "active": active.snapshot() if active is not None else [],
                "slow": slow.snapshot(limit) if slow is not None else [],
            }

        self.register("/traces", traces_handler)
        self.register("/queries", queries_handler)


def _build_version() -> str:
    try:
        from . import __version__
        return __version__
    except Exception:
        return "unknown"


def _jax_backend() -> str:
    """Backend label WITHOUT importing (let alone initializing) jax in
    daemons that never use it — metad's scrape must not drag a second
    XLA runtime up."""
    jx = sys.modules.get("jax")
    if jx is None:
        return "none"
    try:
        return str(jx.default_backend())
    except Exception:
        return "error"


def _flight_gauges() -> Dict[str, float]:
    return _flight_mod.recorder.gauges()


def _slo_gauges() -> Dict[str, float]:
    return _slo_mod.engine.gauges()


def _writepath_gauges() -> Dict[str, float]:
    """Write-path observatory per-space gauges (ack-to-visible lag,
    pending acks, change-ring occupancy). Disarmed -> {} so /metrics
    stays byte-identical to an observatory-free build."""
    return _writepath_mod.gauges()


def _profiler_gauges() -> Dict[str, float]:
    """Sampler health + GC/compile gauges. Empty (no families at all)
    until ensure_started ran AND the sampler is armed — the
    profile_hz=0 fast path keeps /metrics byte-identical to a
    profiler-free build."""
    if not _profiler_mod.profiler.thread_alive() and \
            _profiler_mod.profiler.samples == 0:
        return {}
    return _profiler_mod.gauges()
