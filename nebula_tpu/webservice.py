"""HTTP admin endpoints.

Role parity with the reference's `src/webservice/` (proxygen HTTP server
per daemon): `/status` liveness, `/flags` get/set (GET ?name=a,b / PUT
body name=value), `/get_stats?stats=metric.method.window,...` — plus
custom handlers a daemon registers (the reference's storage admin/
download/ingest endpoints hang off the same seam, WebService.h:31-49).

Implemented over http.server (stdlib) on a daemon thread; handlers are
plain callables `(query_params, body) -> (code, obj)`.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from .common.flags import FlagRegistry
from .common.stats import StatsManager

Handler = Callable[[Dict[str, str], bytes], Tuple[int, Any]]


class WebService:
    def __init__(self, name: str = "daemon",
                 flags: Optional[FlagRegistry] = None,
                 stats: Optional[StatsManager] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.name = name
        self.flags = flags
        self.stats = stats
        self._handlers: Dict[str, Handler] = {}
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._host = host
        self._port = port

        self.register("/status", self._status_handler)
        self.register("/flags", self._flags_handler)
        self.register("/get_stats", self._stats_handler)

    # ------------------------------------------------------------------
    def register(self, path: str, handler: Handler) -> None:
        self._handlers[path] = handler

    def start(self) -> int:
        ws = self

        class _Req(BaseHTTPRequestHandler):
            def log_message(self, *a):   # quiet
                pass

            def _serve(self, body: bytes):
                u = urlparse(self.path)
                h = ws._handlers.get(u.path)
                if h is None:
                    self.send_response(404)
                    self.end_headers()
                    self.wfile.write(b'{"error": "not found"}')
                    return
                params = {k: v[0] for k, v in parse_qs(u.query).items()}
                try:
                    code, obj = h(params, body)
                except Exception as e:   # handler bug -> 500
                    code, obj = 500, {"error": str(e)}
                data = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                self._serve(b"")

            def do_PUT(self):
                n = int(self.headers.get("Content-Length", 0))
                self._serve(self.rfile.read(n))

            do_POST = do_PUT

        self._server = ThreadingHTTPServer((self._host, self._port), _Req)
        self._port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True,
                                        name=f"webservice-{self.name}")
        self._thread.start()
        return self._port

    @property
    def port(self) -> int:
        return self._port

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    # ------------------------------------------------------------------
    # built-in handlers
    # ------------------------------------------------------------------
    def _status_handler(self, params, body) -> Tuple[int, Any]:
        return 200, {"status": "running", "name": self.name}

    def _flags_handler(self, params, body) -> Tuple[int, Any]:
        if self.flags is None:
            return 200, {}
        if body:
            # PUT name=value[&name2=value2]
            updates = {k: v[0] for k, v in parse_qs(body.decode()).items()}
            applied = {}
            for name, raw in updates.items():
                try:
                    val = json.loads(raw)
                except ValueError:
                    val = raw
                applied[name] = self.flags.set(name, val)
            return 200, applied
        names = params.get("name")
        items = self.flags.items()
        if names:
            want = set(names.split(","))
            items = [it for it in items if it[0] in want]
        return 200, {n: {"value": v, "mode": m} for n, v, m in items}

    def _stats_handler(self, params, body) -> Tuple[int, Any]:
        if self.stats is None:
            return 200, {}
        spec = params.get("stats")
        if not spec:
            return 200, self.stats.snapshot()
        out = {}
        for s in spec.split(","):
            v = self.stats.read_stats(s.strip())
            if v is not None:
                out[s.strip()] = v
        return 200, out
