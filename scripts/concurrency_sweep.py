"""Multi-session concurrency sweep driver (PARITY.md Concurrency).

Spins up the real 3-daemon TCP topology (metad, native-engine storaged,
--tpu graphd), bulk-loads the zipf person/knows graph through the
native sorted-ingest path (bench.bulk_load_snb), and runs
tools/session_bench.sweep over two traffic mixes:

- "mixed": the round-4 load — 1/2-hop GO + filtered GO from ordinary
  seeds; at this scale these ride the sparse host pull, so the sweep
  measures the GIL/host ceiling.
- "dense": 3-hop GO from hub seeds with the pull budget pinned to 0 so
  every query takes the device path — the traffic the cross-session
  group-commit dispatcher (engine_tpu/engine.py _go_via_dispatcher)
  exists for. Round 4 measured aggregate QPS flat at ~630 from N=2;
  with shared batched dispatches the device half amortizes across the
  window.

Prints ONE JSON object {graph, cores, mixed: [...], dense: [...],
dispatcher: {...}} and a human table on stderr.

Ref methodology: tools/storage-perf/README.md (fixed thread count,
sustained load, percentiles), applied at the query layer.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--v", type=int, default=100_000)
    ap.add_argument("--e", type=int, default=1_000_000)
    ap.add_argument("--parts", type=int, default=8)
    ap.add_argument("--sessions", default="1,2,4,8,16,32")
    ap.add_argument("--duration", type=float, default=4.0)
    ap.add_argument("--skip-mixed", action="store_true")
    ap.add_argument("--skip-dense", action="store_true")
    args = ap.parse_args(argv)
    counts = [int(x) for x in args.sessions.split(",") if x]

    import bench
    from nebula_tpu import native as native_mod
    from nebula_tpu.client import GraphClient
    from nebula_tpu.daemons import serve_graphd, serve_metad, serve_storaged
    from nebula_tpu.engine_tpu import TpuGraphEngine
    from nebula_tpu.tools.session_bench import sweep

    if not native_mod.available():
        raise SystemExit("needs the native engine (make -C native)")

    metad = serve_metad()
    sd = serve_storaged(metad.addr, load_interval=0.1)
    tpu = TpuGraphEngine()
    gd = serve_graphd(metad.addr, tpu_engine=tpu)
    try:
        c = GraphClient(gd.addr).connect()
        for stmt in (f"CREATE SPACE zipf(partition_num={args.parts})",
                     "USE zipf", "CREATE TAG person(age int)",
                     "CREATE EDGE knows(ts int)"):
            r = c.execute(stmt)
            assert r.ok(), (stmt, r.error_msg)
        # wait for the storaged to pick the parts up
        sm = gd.engine.sm
        sid = gd.meta_client.get_space("zipf").value().space_id
        for _ in range(100):
            if sd.store.space_engine(sid) is not None:
                break
            time.sleep(0.1)
        engine = sd.store.space_engine(sid)
        assert engine is not None, "storaged never mounted the space"
        tag_id = sm.tag_id(sid, "person")
        etype = sm.edge_type(sid, "knows")
        rng = np.random.default_rng(7)
        log(f"loading zipf graph V={args.v} E={args.e}...")
        srcs, _dsts = bench.bulk_load_snb(
            engine, tag_id, etype, sm.tag_schema(sid, tag_id).value(),
            sm.edge_schema(sid, etype).value(), args.v, args.e,
            args.parts, rng)
        # hubs = highest out-degree sources (zipf head)
        deg = np.bincount(srcs, minlength=args.v)
        hubs = [int(x) for x in np.argsort(deg)[-4:]]
        seeds = [int(s) for s in rng.choice(args.v, 8, replace=False)]
        out = {"graph": {"V": args.v, "E": args.e, "parts": args.parts},
               "duration_s": args.duration}

        if not args.skip_mixed:
            mixed = ([f"GO FROM {s} OVER knows YIELD knows._dst"
                      for s in seeds[:3]]
                     + [f"GO 2 STEPS FROM {s} OVER knows YIELD knows._dst"
                        for s in seeds[3:6]]
                     + [f"GO FROM {s} OVER knows WHERE knows.ts > "
                        f"500000000 YIELD knows._dst, knows.ts"
                        for s in seeds[6:8]])
            c.execute(mixed[0])    # warm snapshot + compile
            log("== mixed sweep (sparse-served, GIL-bound) ==")
            out["mixed"] = sweep(gd.addr, mixed, counts, args.duration,
                                 use_space="zipf")

        if not args.skip_dense:
            # pin routing to the dense device path: every GO rides the
            # batched dispatcher. The tight device-compiled WHERE keeps
            # result sets small so the sweep measures the traversal
            # path, not python row serialization of ~10^5-row answers.
            tpu.sparse_edge_budget = 0
            dense = [f"GO 3 STEPS FROM {h} OVER knows "
                     f"WHERE knows.ts > 999000000 "
                     f"YIELD knows._dst, knows.ts" for h in hubs]
            r = c.execute(dense[0])    # warm: snapshot + dense compile
            assert r.ok(), r.error_msg
            # warm each dispatcher bucket shape (multi_hop_roots
            # specializes on the padded root count): fire b concurrent
            # queries per power-of-two bucket once, so no XLA compile
            # lands inside a measured window
            import threading as _th
            from nebula_tpu.tools.session_bench import run_sessions
            for b in sorted({2 ** k for k in range(1, 7)
                             if 2 ** k <= max(counts)} | {max(counts)}):
                log(f"  warming dispatcher bucket ~{b}...")
                run_sessions(gd.addr, dense, b, duration_s=0.8,
                             use_space="zipf")
            # report only MEASURED windows: warm-up ran at max(counts)
            # concurrency and would otherwise dominate the stat
            tpu.stats["batched_max_window"] = 0
            before = dict(tpu.stats)
            log("== dense sweep (batched device dispatch) ==")
            out["dense"] = sweep(gd.addr, dense, counts, args.duration,
                                 use_space="zipf")
            out["dispatcher"] = {
                k: tpu.stats[k] - before.get(k, 0)
                for k in ("batched_dispatches", "batched_queries",
                          "go_served")}
            out["dispatcher"]["batched_max_window"] = \
                tpu.stats["batched_max_window"]
        print(json.dumps(out))
    finally:
        for h in (gd, sd, metad):
            try:
                h.stop()
            except Exception:
                pass


if __name__ == "__main__":
    main()
