#!/bin/sh
# flame.sh: pull a collapsed-stack profile from a daemon's /profile
# endpoint into a flamegraph-ready file (docs/manual/
# 10-observability.md, "Continuous profiling").
#
#   scripts/flame.sh [URL] [OUT] [SECONDS]
#
#   URL      daemon admin base (default http://127.0.0.1:13000);
#            a full /profile URL also works
#   OUT      output file (default ./profile.collapsed)
#   SECONDS  optional: run an on-demand high-rate capture of this
#            many seconds instead of reading the always-on 600s
#            window (bounded to 30 by the daemon)
#
# The output is flamegraph.pl / inferno collapsed-stack input — one
# "role;frame;frame;... weight" line (weight = sampled wall ms) per
# distinct sampled stack:
#
#   scripts/flame.sh http://127.0.0.1:13000 /tmp/g.collapsed 5
#   flamegraph.pl /tmp/g.collapsed > /tmp/g.svg     # or:
#   inferno-flamegraph /tmp/g.collapsed > /tmp/g.svg
#
# Requires only curl (or python3 as fallback). The sampler must be
# armed (profile_hz > 0, the default 19 Hz); `?thread=<role>` can be
# appended to URL to narrow to one thread role.
set -e

URL="${1:-http://127.0.0.1:13000}"
OUT="${2:-profile.collapsed}"
SECONDS_ARG="${3:-}"

case "$URL" in
  */profile*) BASE_Q="$URL" ;;
  *) BASE_Q="${URL%/}/profile" ;;
esac
case "$BASE_Q" in
  *\?*) Q="$BASE_Q&format=collapsed" ;;
  *) Q="$BASE_Q?format=collapsed" ;;
esac
if [ -n "$SECONDS_ARG" ]; then
  Q="$Q&seconds=$SECONDS_ARG"
else
  # the always-on 600s window (the endpoint's bare default is 60s)
  case "$Q" in
    *window=*) ;;
    *) Q="$Q&window=600" ;;
  esac
fi

if command -v curl >/dev/null 2>&1; then
  curl -fsS "$Q" -o "$OUT"
else
  python3 -c "import sys, urllib.request; \
sys.stdout.buffer.write(urllib.request.urlopen('$Q').read())" > "$OUT"
fi

LINES=$(wc -l < "$OUT")
echo "flame.sh: $LINES collapsed stacks -> $OUT"
echo "  render: flamegraph.pl $OUT > profile.svg"
