#!/bin/sh
# nebula-lint gate: run the repo-specific invariant suite (NL001-NL007,
# docs/manual/15-static-analysis.md) BEFORE the tier-1 pytest sweep.
# Exit 0 only when every finding is inline-suppressed (with a reason)
# or in the committed baseline (.nlint-baseline.json).
#
#   scripts/lint.sh            # text report
#   scripts/lint.sh --json     # machine-readable
#   scripts/lint.sh --update-baseline
#
# Any extra args pass straight through to `python -m nebula_tpu.tools.lint`.
set -e
cd "$(dirname "$0")/.."
exec python -m nebula_tpu.tools.lint "$@"
