#!/usr/bin/env python3
"""Local cluster lifecycle manager (role parity: the reference's
scripts/services.sh + systemd units — start/stop/status/restart the
three daemons with pidfiles).

    python scripts/services.py start   [--storaged-count 2] [--tpu]
    python scripts/services.py start --cluster    # 3x replicated storaged
    python scripts/services.py status
    python scripts/services.py stop
    python scripts/services.py restart

Ports: metad 45500, storaged 44500+i, graphd 3699. Pidfiles and logs
live under --run-dir (default /tmp/nebula_tpu_cluster); each storaged
gets its own data dir under <run-dir>/data/storaged<i> so WALs and
engines survive restarts independently. `--cluster` is the replicated
topology shorthand: 3 storaged with raft on port+1 (storaged ports
spaced by 10), replica_factor=3 spaces survive one host loss
(docs/manual/12-replication.md)."""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DAEMONS = ("metad", "storaged", "graphd")


def _pidfile(run_dir: str, name: str) -> str:
    return os.path.join(run_dir, f"{name}.pid")


def _alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except OSError:
        return False


def _read_pid(run_dir: str, name: str):
    try:
        with open(_pidfile(run_dir, name)) as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return None


def spawn_daemon(run_dir: str, name: str, module: str, args,
                 env_extra=None) -> int:
    """Start one daemon as a detached subprocess: appending log at
    <run-dir>/<name>.log, pidfile at <run-dir>/<name>.pid, repo on
    PYTHONPATH, own session (a SIGKILL storm can't splash the
    parent). Shared by the CLI below and the crash-storm harness
    (nebula_tpu/tools/crashstorm.py — `bench --crash` boots its
    storaged fleet through exactly this path). `env_extra` lets a
    harness arm per-process fault plans (NEBULA_TPU_FAULTS
    crashpoints) without touching its own environment."""
    log = open(os.path.join(run_dir, f"{name}.log"), "a")
    env = dict(os.environ, PYTHONPATH=REPO + os.pathsep +
               os.environ.get("PYTHONPATH", ""))
    if env_extra:
        env.update(env_extra)
    p = subprocess.Popen([sys.executable, "-m", module, *args],
                         stdout=log, stderr=subprocess.STDOUT, env=env,
                         start_new_session=True)
    with open(_pidfile(run_dir, name), "w") as f:
        f.write(str(p.pid))
    return p.pid


def _spawn(run_dir: str, name: str, module: str, args) -> int:
    return spawn_daemon(run_dir, name, module, args)


def start(args) -> int:
    os.makedirs(args.run_dir, exist_ok=True)
    meta_addr = f"{args.host}:{args.meta_port}"
    etc = os.path.join(REPO, "etc")

    def ff(name):
        p = os.path.join(etc, f"nebula-{name}.conf.default")
        return ["--flagfile", p] if os.path.exists(p) else []

    started = []
    if _read_pid(args.run_dir, "metad") and _alive(_read_pid(args.run_dir, "metad")):
        print("metad already running")
    else:
        pid = _spawn(args.run_dir, "metad", "nebula_tpu.daemons.metad",
                     ["--host", args.host, "--port", str(args.meta_port),
                      *ff("metad")])
        started.append(("metad", pid))
        time.sleep(0.5)  # metad must accept before storaged registers
    for i in range(args.storaged_count):
        name = f"storaged{i}"
        pid0 = _read_pid(args.run_dir, name)
        if pid0 and _alive(pid0):
            print(f"{name} already running")
            continue
        data_dir = os.path.join(args.run_dir, "data", name)
        os.makedirs(data_dir, exist_ok=True)
        extra_s = ["--data-dir", data_dir,
                   "--cluster-id-file",
                   os.path.join(data_dir, "cluster.id")]
        if args.replicated:
            extra_s.append("--replicated")
        pid = _spawn(args.run_dir, name, "nebula_tpu.daemons.storaged",
                     ["--meta", meta_addr, "--host", args.host,
                      "--port", str(args.storaged_port +
                                    i * (10 if args.replicated else 1)),
                      "--ws-port", str(12000 + i), *extra_s, *ff("storaged")])
        started.append((name, pid))
    time.sleep(0.5)
    pid0 = _read_pid(args.run_dir, "graphd")
    if pid0 and _alive(pid0):
        print("graphd already running")
    else:
        extra = ["--tpu"] if args.tpu else []
        pid = _spawn(args.run_dir, "graphd", "nebula_tpu.daemons.graphd",
                     ["--meta", meta_addr, "--host", args.host,
                      "--port", str(args.graphd_port), *extra, *ff("graphd")])
        started.append(("graphd", pid))
    for name, pid in started:
        print(f"started {name} (pid {pid})")
    print(f"console: python -m nebula_tpu.console "
          f"--addr {args.host}:{args.graphd_port}")
    return 0


def _iter_names(run_dir: str):
    if not os.path.isdir(run_dir):
        return
    for f in sorted(os.listdir(run_dir)):
        if f.endswith(".pid"):
            yield f[:-4]


def status(args) -> int:
    any_up = False
    for name in _iter_names(args.run_dir):
        pid = _read_pid(args.run_dir, name)
        up = pid is not None and _alive(pid)
        any_up |= up
        print(f"{name}: {'UP (pid %d)' % pid if up else 'DOWN'}")
    if not any_up:
        print("no services running")
    return 0


def stop(args) -> int:
    # graphd first, metad last — reverse of start order
    names = sorted(_iter_names(args.run_dir),
                   key=lambda n: (n != "graphd", n.startswith("metad")))
    for name in names:
        pid = _read_pid(args.run_dir, name)
        if pid and _alive(pid):
            os.kill(pid, signal.SIGTERM)
            for _ in range(50):
                if not _alive(pid):
                    break
                time.sleep(0.1)
            if _alive(pid):      # wedged: escalate so ports free up
                os.kill(pid, signal.SIGKILL)
                for _ in range(20):
                    if not _alive(pid):
                        break
                    time.sleep(0.1)
                print(f"killed {name} (pid {pid}, ignored SIGTERM)")
            else:
                print(f"stopped {name} (pid {pid})")
        os.unlink(_pidfile(args.run_dir, name))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="nebula-tpu cluster manager")
    ap.add_argument("action", choices=["start", "stop", "status", "restart"])
    ap.add_argument("--run-dir", default="/tmp/nebula_tpu_cluster")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--meta-port", type=int, default=45500)
    ap.add_argument("--storaged-port", type=int, default=44500)
    ap.add_argument("--graphd-port", type=int, default=3699)
    ap.add_argument("--storaged-count", type=int, default=1)
    ap.add_argument("--tpu", action="store_true",
                    help="enable the TPU engine in graphd")
    ap.add_argument("--replicated", action="store_true",
                    help="raft-replicate storaged parts (raft on port+1; "
                         "storaged ports are spaced by 10)")
    ap.add_argument("--cluster", action="store_true",
                    help="replicated 3-storaged topology shorthand "
                         "(= --replicated --storaged-count 3): "
                         "replica_factor=3 spaces survive one host "
                         "loss; BALANCE DATA moves parts online")
    args = ap.parse_args(argv)
    if args.cluster:
        args.replicated = True
        args.storaged_count = max(args.storaged_count, 3)
    if args.action == "start":
        return start(args)
    if args.action == "status":
        return status(args)
    if args.action == "stop":
        return stop(args)
    stop(args)
    return start(args)


if __name__ == "__main__":
    raise SystemExit(main())
