"""TPU-capture watchdog: probe the accelerator relay continuously and
run a trimmed benchmark the moment it answers.

Round-4 verdict, item 1: the relay flaps; BENCH_r03/r04 both recorded
`platform: cpu-fallback` because the relay happened to be dead at the
single moment the driver ran bench.py. This watchdog inverts that: it
probes all round and captures the on-chip number inside whatever
up-window occurs, writing `BENCH_tpu_onchip.json` (platform: tpu/axon)
plus a timestamped probe log (`TPU_WATCHDOG.log`) proving coverage
either way.

Design constraints (see common/accel.py for the history):
- A dead relay hangs ANY normal `python` start via sitecustomize, so
  the watchdog itself must be launched with `python -S` and do every
  JAX-touching thing in a timeout-bounded SUBPROCESS.
- A probe success can be a narrow window: the trimmed bench must fit
  in ~5 min end-to-end (graph gen + ingest + compile + measure), so
  the scale knobs are cut relative to bench.py's SNB defaults while
  keeping the SNB shape (clipped-zipf knows).
- The relay can die MID-bench: the bench subprocess gets a hard
  timeout; a timeout/failure is logged and probing resumes.

Escalation: after the first trimmed capture succeeds, the next
successful probe attempts the FULL-scale bench (bench.py defaults,
longer timeout) to `BENCH_tpu_onchip_full.json`. Trimmed evidence in
hand is never overwritten by a failed full run.

Usage:
  env JAX_PLATFORMS= python -S scripts/tpu_watchdog.py [--once] [--fake-up]
(launched detached by the round driver / builder; stdlib-only parent).

--fake-up is a SELF-TEST mode: the probe is forced to report success
and the bench runs against the CPU XLA backend (cpu-platform artifacts
accepted in this mode only), so the capture + escalation path — which
otherwise only runs inside a real accelerator up-window — is
exercisable by the tier-1 suite. Combine with WATCHDOG_OUT_TRIM /
WATCHDOG_OUT_FULL / WATCHDOG_LOG / WATCHDOG_BENCH_SCRIPT to keep the
self-test away from the real artifacts.

No reference analogue: QueryBoundBenchmark.cpp:181-191 assumes local
devices; a tunneled flaky accelerator needs capture-on-recovery.
"""
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.environ.get("WATCHDOG_LOG", os.path.join(REPO, "TPU_WATCHDOG.log"))
OUT_TRIM = os.environ.get("WATCHDOG_OUT_TRIM",
                          os.path.join(REPO, "BENCH_tpu_onchip.json"))
OUT_FULL = os.environ.get("WATCHDOG_OUT_FULL",
                          os.path.join(REPO, "BENCH_tpu_onchip_full.json"))
# the bench the success branch launches — overridable so the --fake-up
# self-test can substitute a fast stand-in and still exercise the real
# launch/parse/capture/escalation machinery
BENCH_SCRIPT = os.environ.get("WATCHDOG_BENCH_SCRIPT",
                              os.path.join(REPO, "bench.py"))
# --fake-up: self-test mode — treat the CPU backend as a successful
# probe so the success branch (trimmed bench -> capture -> full-bench
# escalation), which only ever runs inside a real accelerator
# up-window, is exercisable by a test. cpu-platform artifacts are
# accepted in this mode ONLY.
FAKE_UP = False

PROBE_TIMEOUT = float(os.environ.get("WATCHDOG_PROBE_TIMEOUT", 60))
PROBE_INTERVAL = float(os.environ.get("WATCHDOG_PROBE_INTERVAL", 120))
BENCH_TIMEOUT = float(os.environ.get("WATCHDOG_BENCH_TIMEOUT", 900))
FULL_BENCH_TIMEOUT = float(os.environ.get("WATCHDOG_FULL_BENCH_TIMEOUT", 3600))

# Trimmed SNB scale: same shape as bench.py defaults (V=1.2M/E=50M cut
# 8x/10x), sized so gen+ingest+compile+measure lands well under the
# bench subprocess timeout on a healthy chip.
TRIM_ENV = {
    "BENCH_V": "150000", "BENCH_E": "5000000", "BENCH_BATCH": "64",
    "BENCH_ITERS": "5", "BENCH_LAT_N": "10", "BENCH_PY_E": "400000",
}


def log(msg: str) -> None:
    line = f"{time.strftime('%Y-%m-%dT%H:%M:%S')} {msg}"
    print(line, flush=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def probe() -> str:
    """-> platform string of a real accelerator, or '' (down/cpu/hang).

    Runs a fresh non`-S` interpreter (so sitecustomize dials the relay)
    under a hard deadline; mirrors nebula_tpu/common/accel.py but kept
    stdlib-inline so the `-S` parent needs no repo imports.
    """
    if FAKE_UP:
        # self-test: skip the relay probe entirely and report "up" so
        # the success branch runs deterministically on a CPU-only box
        return "fake-up(cpu)"
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)      # let the relay platform win
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); "
             "print('NEBULA_PROBE', d[0].platform, len(d))"],
            capture_output=True, timeout=PROBE_TIMEOUT, text=True, env=env)
        # the child is a full (non -S) interpreter: sitecustomize /
        # runtime banners may share stdout, so parse only the marker
        # line — and never let a malformed line kill the watchdog loop
        marker = [ln for ln in (out.stdout or "").splitlines()
                  if ln.startswith("NEBULA_PROBE ")]
        if out.returncode == 0 and marker:
            parts = marker[-1].split()
            plat = parts[1] if len(parts) >= 2 else ""
            if plat and plat != "cpu":
                return plat
            log("probe: backend up but platform=cpu (no accelerator)")
        else:
            err = (out.stderr or "").strip().splitlines()
            log(f"probe: rc={out.returncode} {err[-1] if err else ''}")
    except subprocess.TimeoutExpired:
        log(f"probe: HANG >{PROBE_TIMEOUT:.0f}s (relay dead/flapping)")
    except Exception as e:          # noqa: BLE001 — the loop must live
        log(f"probe: error {e!r}")
    return ""


def _foreign_bench_running() -> bool:
    """True when another process is already running bench.py — the
    accelerator is exclusive-access, so racing the round driver's own
    bench would steal the chip and force IT onto the CPU fallback
    (the exact failure this watchdog exists to prevent)."""
    me = os.getpid()
    try:
        for pid in os.listdir("/proc"):
            if not pid.isdigit() or int(pid) == me:
                continue
            try:
                with open(f"/proc/{pid}/cmdline", "rb") as f:
                    argv = f.read().decode("utf-8", "replace").split("\0")
            except OSError:
                continue
            # a PYTHON process whose own argv carries bench.py as a
            # script path — NOT any process that merely mentions it in
            # a prompt/flag blob (the round driver's harness does)
            if argv and "python" in os.path.basename(argv[0]) and any(
                    a.endswith("bench.py") for a in argv[1:4]):
                return True
    except OSError:
        pass
    return False


def run_bench(out_path: str, extra_env: dict, timeout: float) -> bool:
    # the self-test must be deterministic: it never touches the chip,
    # so an unrelated bench.py (e.g. the driver's round-end run) is
    # not a reason to defer
    if not FAKE_UP and _foreign_bench_running():
        log(f"bench -> {os.path.basename(out_path)}: DEFERRED — another "
            f"bench.py process is running (driver round-end bench?); "
            f"not contending for the exclusive-access chip")
        return False
    env = dict(os.environ)
    if FAKE_UP:
        env["JAX_PLATFORMS"] = "cpu"    # the self-test pins the backend
    else:
        env.pop("JAX_PLATFORMS", None)
    env.update(extra_env)
    tag = os.path.basename(out_path)
    log(f"bench -> {tag} starting (timeout {timeout:.0f}s, env {extra_env})")
    t0 = time.time()
    try:
        out = subprocess.run(
            [sys.executable, BENCH_SCRIPT],
            capture_output=True, timeout=timeout, text=True, env=env,
            cwd=REPO)
    except subprocess.TimeoutExpired:
        log(f"bench -> {tag}: TIMEOUT after {timeout:.0f}s (relay died "
            f"mid-run?)")
        return False
    dt = time.time() - t0
    line = out.stdout.strip().splitlines()[-1] if out.stdout.strip() else ""
    try:
        data = json.loads(line)
    except ValueError:
        err = (out.stderr or "").strip().splitlines()
        log(f"bench -> {tag}: FAILED rc={out.returncode} in {dt:.0f}s: "
            f"{err[-1] if err else 'no output'}")
        return False
    plat = str(data.get("platform", ""))
    if plat.startswith("cpu") and not FAKE_UP:
        log(f"bench -> {tag}: completed but platform={plat} (relay died "
            f"between probe and backend init) — NOT capturing")
        return False
    data["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    data["captured_by"] = "tpu_watchdog"
    with open(out_path, "w") as f:
        json.dump(data, f, indent=1)
    log(f"bench -> {tag}: CAPTURED platform={plat} "
        f"value={data.get('value')} {data.get('unit')} "
        f"vs_baseline={data.get('vs_baseline')} in {dt:.0f}s")
    return True


def main() -> int:
    global FAKE_UP
    once = "--once" in sys.argv
    if "--fake-up" in sys.argv:
        FAKE_UP = True
        if "WATCHDOG_OUT_TRIM" not in os.environ or \
                "WATCHDOG_OUT_FULL" not in os.environ:
            # the self-test writes cpu-platform artifacts — refuse to
            # point it at the REAL capture files (trimmed evidence in
            # hand must never be overwritten by a fake run)
            print("--fake-up requires WATCHDOG_OUT_TRIM and "
                  "WATCHDOG_OUT_FULL to redirect the self-test "
                  "artifacts away from the real captures", flush=True)
            return 2
    log(f"watchdog start pid={os.getpid()} interval={PROBE_INTERVAL:.0f}s "
        f"probe_timeout={PROBE_TIMEOUT:.0f}s"
        + (" FAKE-UP self-test" if FAKE_UP else ""))
    n = 0
    while True:
        n += 1
        plat = ""
        try:
            plat = probe()
            if plat:
                log(f"probe #{n}: ACCELERATOR UP platform={plat}")
                if not os.path.exists(OUT_TRIM):
                    run_bench(OUT_TRIM, TRIM_ENV, BENCH_TIMEOUT)
                elif not os.path.exists(OUT_FULL):
                    run_bench(OUT_FULL, {}, FULL_BENCH_TIMEOUT)
                else:
                    log("both artifacts captured; watchdog idling "
                        "(re-probe continues for the log record)")
            else:
                log(f"probe #{n}: down")
        except Exception as e:      # noqa: BLE001 — the loop must live
            log(f"watchdog iteration error: {e!r}")
        if once:
            return 0 if plat else 1
        time.sleep(PROBE_INTERVAL)


if __name__ == "__main__":
    sys.exit(main())
