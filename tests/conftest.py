"""Test harness config.

Tests run on CPU with 8 virtual XLA devices so that multi-partition
mesh/`all_to_all` paths are exercised without real multi-chip hardware
(the reference's analogue: booting real servers in-process on ephemeral
ports, ref graph/test/TestEnv.cpp:29-71). Must run before jax imports.
"""
import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# jax may already be imported by site customization with a hardware platform
# selected; override via the config API, which works as long as the backend
# hasn't been initialized yet.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Opt-in runtime lock-order witness for the WHOLE tier-1 sweep: with
# NEBULA_TPU_LOCK_WITNESS=1 the witness installs here — before any test
# imports nebula_tpu — so every lock the serve path creates is wrapped
# and the acquisition-order graph accumulates across all tests
# (docs/manual/15-static-analysis.md). The dedicated witness coverage
# that always runs lives in test_lock_witness.py and the chaos/cluster
# smokes (their bench subprocesses set the env var themselves).
if os.environ.get("NEBULA_TPU_LOCK_WITNESS"):
    import nebula_tpu.common.lockwitness  # noqa: F401  (installs)
