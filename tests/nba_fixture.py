"""Compat shim: the NBA sample moved into the package."""
from nebula_tpu.sample import (LIKES, PLAYERS, SERVES, TEAMS,  # noqa: F401
                               load_nba)
