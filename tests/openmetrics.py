"""Strict OpenMetrics text-format parser (ISSUE 10 satellite).

Validates the exposition every daemon serves at /metrics
(docs/manual/10-observability.md): line grammar, family TYPE
declarations ahead of (and contiguous with) their samples, the
counter `_total` naming contract, histogram bucket monotonicity and
`_count`/+Inf consistency, exemplar placement, duplicate-series
detection and the trailing `# EOF`. Deliberately a PARSER, not a
regex sieve — a malformed line raises with its line number, so a
conformance regression in any exposition source fails tier-1 with
the exact offending line.

Not a general-purpose client: it accepts exactly the subset the
repo's daemons emit (counter/gauge/histogram families, optional HELP/
UNIT, exemplars on counter `_total` and histogram `_bucket` samples)
and errors on everything else, which is the point.
"""
from __future__ import annotations

import math
import re
from typing import Dict, List, Optional, Tuple

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*$")

# sample-name suffixes a family's samples may carry, per metric type
_SUFFIXES = {
    "counter": ("_total", "_created"),
    "gauge": ("",),
    "histogram": ("_bucket", "_sum", "_count", "_created"),
}
# suffixes allowed to carry exemplars
_EXEMPLAR_OK = {("counter", "_total"), ("histogram", "_bucket")}


class OpenMetricsError(ValueError):
    def __init__(self, lineno: int, msg: str, line: str = ""):
        self.lineno = lineno
        super().__init__(f"line {lineno}: {msg}"
                         + (f"  [{line!r}]" if line else ""))


class Sample:
    __slots__ = ("name", "labels", "value", "exemplar")

    def __init__(self, name: str, labels: Dict[str, str], value: float,
                 exemplar: Optional[Tuple[Dict[str, str], float]]):
        self.name = name
        self.labels = labels
        self.value = value
        self.exemplar = exemplar


class Family:
    __slots__ = ("name", "type", "samples")

    def __init__(self, name: str, type_: str):
        self.name = name
        self.type = type_
        self.samples: List[Sample] = []


def _parse_labels(s: str, lineno: int, line: str
                  ) -> Tuple[Dict[str, str], int]:
    """Parse `{k="v",...}` starting at s[0] == '{'; returns (labels,
    index one past the closing brace)."""
    assert s[0] == "{"
    labels: Dict[str, str] = {}
    i = 1
    while i < len(s):
        if s[i] == "}":
            return labels, i + 1
        m = re.match(r"([a-zA-Z_][a-zA-Z0-9_]*)=\"", s[i:])
        if not m:
            raise OpenMetricsError(lineno, "bad label syntax", line)
        key = m.group(1)
        i += m.end()
        val = []
        while i < len(s) and s[i] != '"':
            if s[i] == "\\":
                if i + 1 >= len(s):
                    raise OpenMetricsError(lineno, "dangling escape",
                                           line)
                esc = s[i + 1]
                if esc not in ('"', "\\", "n"):
                    raise OpenMetricsError(
                        lineno, f"bad escape \\{esc}", line)
                val.append("\n" if esc == "n" else esc)
                i += 2
            else:
                val.append(s[i])
                i += 1
        if i >= len(s):
            raise OpenMetricsError(lineno, "unterminated label value",
                                   line)
        i += 1   # closing quote
        if key in labels:
            raise OpenMetricsError(lineno,
                                   f"duplicate label {key!r}", line)
        labels[key] = "".join(val)
        if i < len(s) and s[i] == ",":
            i += 1
    raise OpenMetricsError(lineno, "unterminated label set", line)


def _parse_number(tok: str, lineno: int, line: str) -> float:
    if tok in ("+Inf", "Inf"):
        return math.inf
    if tok == "-Inf":
        return -math.inf
    try:
        return float(tok)
    except ValueError:
        raise OpenMetricsError(lineno, f"bad number {tok!r}", line)


def _parse_sample(line: str, lineno: int) -> Sample:
    # name[{labels}] value [timestamp] [# {exemplar-labels} value [ts]]
    m = re.match(r"([a-zA-Z_:][a-zA-Z0-9_:]*)", line)
    if not m:
        raise OpenMetricsError(lineno, "bad sample name", line)
    name = m.group(1)
    rest = line[m.end():]
    labels: Dict[str, str] = {}
    if rest.startswith("{"):
        labels, used = _parse_labels(rest, lineno, line)
        rest = rest[used:]
    if not rest.startswith(" "):
        raise OpenMetricsError(lineno, "expected space before value",
                               line)
    rest = rest[1:]
    exemplar: Optional[Tuple[Dict[str, str], float]] = None
    ex_part = None
    if " # " in rest:
        rest, _, ex_part = rest.partition(" # ")
    toks = rest.split(" ")
    if not toks or not toks[0]:
        raise OpenMetricsError(lineno, "missing sample value", line)
    value = _parse_number(toks[0], lineno, line)
    if len(toks) == 2:
        _parse_number(toks[1], lineno, line)   # optional timestamp
    elif len(toks) > 2:
        raise OpenMetricsError(lineno, "trailing junk after value",
                               line)
    if ex_part is not None:
        if not ex_part.startswith("{"):
            raise OpenMetricsError(lineno, "exemplar must start with "
                                           "a label set", line)
        ex_labels, used = _parse_labels(ex_part, lineno, line)
        ex_rest = ex_part[used:].strip()
        ex_toks = ex_rest.split(" ") if ex_rest else []
        if not ex_toks:
            raise OpenMetricsError(lineno, "exemplar missing value",
                                   line)
        ex_value = _parse_number(ex_toks[0], lineno, line)
        if len(ex_toks) == 2:
            _parse_number(ex_toks[1], lineno, line)
        elif len(ex_toks) > 2:
            raise OpenMetricsError(lineno, "trailing junk after "
                                           "exemplar", line)
        ex_len = sum(len(k) + len(v) for k, v in ex_labels.items())
        if ex_len > 128:
            raise OpenMetricsError(lineno, "exemplar label set over "
                                           "128 chars", line)
        exemplar = (ex_labels, ex_value)
    return Sample(name, labels, value, exemplar)


def _family_of(name: str, fam: Optional[Family]) -> Optional[str]:
    """Which suffix ties `name` to the current family (None = not this
    family's sample)."""
    if fam is None:
        return None
    for suffix in _SUFFIXES[fam.type]:
        if name == fam.name + suffix:
            return suffix
    return None


def parse(text: str) -> Dict[str, Family]:
    """Strictly parse one OpenMetrics exposition; returns families by
    name. Raises OpenMetricsError on the first violation."""
    lines = text.split("\n")
    if lines and lines[-1] == "":
        lines.pop()
    if not lines or lines[-1] != "# EOF":
        raise OpenMetricsError(len(lines), "missing trailing # EOF")
    families: Dict[str, Family] = {}
    series_seen: set = set()
    current: Optional[Family] = None
    for idx, line in enumerate(lines):
        lineno = idx + 1
        if line == "# EOF":
            if idx != len(lines) - 1:
                raise OpenMetricsError(lineno, "content after # EOF")
            break
        if not line:
            raise OpenMetricsError(lineno, "blank line")
        if line != line.strip():
            raise OpenMetricsError(lineno,
                                   "leading/trailing whitespace", line)
        if line.startswith("#"):
            toks = line.split(" ")
            kind = toks[1] if len(toks) > 1 else ""
            if kind == "TYPE":
                if len(toks) != 4:
                    raise OpenMetricsError(lineno, "bad TYPE line",
                                           line)
                _, _, name, type_ = toks
                if not _NAME_RE.match(name):
                    raise OpenMetricsError(lineno,
                                           f"bad family name {name!r}",
                                           line)
                if type_ not in _SUFFIXES:
                    raise OpenMetricsError(
                        lineno, f"unsupported family type {type_!r}",
                        line)
                if name in families:
                    raise OpenMetricsError(
                        lineno, f"duplicate family {name!r}", line)
                current = families[name] = Family(name, type_)
            elif kind in ("HELP", "UNIT") and len(toks) >= 3:
                pass
            else:
                raise OpenMetricsError(lineno, "unknown comment form",
                                       line)
            continue
        sample = _parse_sample(line, lineno)
        suffix = _family_of(sample.name, current)
        if suffix is None:
            # strict: every sample belongs to the family declared
            # immediately above it — no interleaving, no orphans
            raise OpenMetricsError(
                lineno,
                f"sample {sample.name!r} outside its family "
                f"(current: {current.name if current else None!r}) — "
                f"missing/misplaced TYPE, or a counter named without "
                f"_total", line)
        if sample.exemplar is not None and \
                (current.type, suffix) not in _EXEMPLAR_OK:
            raise OpenMetricsError(
                lineno, f"exemplar not allowed on {current.type} "
                        f"sample {sample.name!r}", line)
        series_key = (sample.name,
                      tuple(sorted(sample.labels.items())))
        if series_key in series_seen:
            raise OpenMetricsError(
                lineno, f"duplicate series {sample.name!r} "
                        f"{sample.labels!r}", line)
        series_seen.add(series_key)
        current.samples.append(sample)
    _validate_families(families)
    return families


def _validate_families(families: Dict[str, Family]) -> None:
    for fam in families.values():
        names = [s.name for s in fam.samples]
        if fam.type == "counter":
            if not any(n == fam.name + "_total" for n in names):
                raise OpenMetricsError(
                    0, f"counter family {fam.name!r} has no _total "
                       f"sample")
        elif fam.type == "gauge":
            if not names:
                raise OpenMetricsError(
                    0, f"gauge family {fam.name!r} has no sample")
        elif fam.type == "histogram":
            # validated PER LABEL SERIES (labels minus `le`): a
            # federated family carries one complete bucket ladder per
            # instance — cross-series bucket ordering is meaningless,
            # per-series monotonicity/consistency is the contract
            # (graphd's /cluster_metrics merges every daemon's
            # exposition into one document)
            def series_key(s: Sample) -> Tuple:
                return tuple(sorted((k, v) for k, v in
                                    s.labels.items() if k != "le"))

            buckets_by: Dict[Tuple, List[Sample]] = {}
            for s in fam.samples:
                if s.name == fam.name + "_bucket":
                    buckets_by.setdefault(series_key(s), []).append(s)
            if not buckets_by:
                raise OpenMetricsError(
                    0, f"histogram {fam.name!r} has no buckets")
            counts_by = {series_key(s): s.value for s in fam.samples
                         if s.name == fam.name + "_count"}
            sums_by = {series_key(s) for s in fam.samples
                       if s.name == fam.name + "_sum"}
            for key, buckets in buckets_by.items():
                les = []
                for b in buckets:
                    if "le" not in b.labels:
                        raise OpenMetricsError(
                            0, f"histogram {fam.name!r} bucket "
                               f"without le label")
                    les.append(math.inf if b.labels["le"] == "+Inf"
                               else float(b.labels["le"]))
                if les != sorted(les) or les[-1] != math.inf:
                    raise OpenMetricsError(
                        0, f"histogram {fam.name!r} series {key!r} "
                           f"buckets not ascending / missing +Inf")
                counts = [b.value for b in buckets]
                if counts != sorted(counts):
                    raise OpenMetricsError(
                        0, f"histogram {fam.name!r} series {key!r} "
                           f"bucket counts not cumulative")
                if counts_by.get(key) != counts[-1]:
                    raise OpenMetricsError(
                        0, f"histogram {fam.name!r} series {key!r} "
                           f"_count != +Inf bucket")
                if key not in sums_by:
                    raise OpenMetricsError(
                        0, f"histogram {fam.name!r} series {key!r} "
                           f"missing _sum")


def exemplar_trace_ids(families: Dict[str, Family]) -> Dict[str, str]:
    """{trace_id: family name} for every exemplar in the exposition —
    the metric -> trace join the flight-recorder acceptance check
    correlates on (bench.py --chaos)."""
    out: Dict[str, str] = {}
    for fam in families.values():
        for s in fam.samples:
            if s.exemplar and "trace_id" in s.exemplar[0]:
                out[s.exemplar[0]["trace_id"]] = fam.name
    return out
