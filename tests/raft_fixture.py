"""Raft test harness.

Mirrors the reference's test idiom (ref kvstore/raftex/test/
RaftexTestBase.{h,cpp} + TestShard.{h,cpp}): spin N real raft services
in-process, each hosting a minimal state machine that records its
commits, plus helpers to wait for leader election and to kill/restart
replicas via network isolation.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from nebula_tpu.kvstore.raftex import (InProcNetwork, RaftPart, RaftexService)

FAST = dict(heartbeat_interval=0.06, election_timeout=0.2, rpc_timeout=0.5)


class TestShard:
    """Minimal state machine capturing commits (ref TestShard.h)."""

    def __init__(self):
        self.commits: List[Tuple[int, int, bytes]] = []
        self.snapshot_rows: List[Tuple[bytes, bytes]] = []
        self.lock = threading.Lock()

    def on_commit(self, logs):
        with self.lock:
            self.commits.extend(logs)

    def on_snapshot(self, rows, cid, cterm, done):
        with self.lock:
            self.snapshot_rows.extend(rows)

    def data(self) -> List[bytes]:
        with self.lock:
            return [d for _, _, d in self.commits if d]


class RaftCluster:
    def __init__(self, n: int, tmp_path, learners: int = 0, **kw):
        self.net = InProcNetwork()
        self.addrs = [f"127.0.0.1:{9000 + i}" for i in range(n + learners)]
        self.voting = self.addrs[:n]
        self.services: Dict[str, RaftexService] = {}
        self.parts: Dict[str, RaftPart] = {}
        self.shards: Dict[str, TestShard] = {}
        self.tmp = tmp_path
        self.kw = {**FAST, **kw}
        for i, addr in enumerate(self.addrs):
            self._spawn(addr, is_learner=i >= n)

    def _spawn(self, addr: str, is_learner: bool = False) -> RaftPart:
        svc = RaftexService(addr, self.net)
        shard = TestShard()
        part = RaftPart(
            space_id=1, part_id=1, addr=addr, peers=list(self.voting),
            wal_dir=str(self.tmp / addr.replace(":", "_")),
            service=svc, on_commit=shard.on_commit,
            on_snapshot=shard.on_snapshot,
            snapshot_rows=lambda s=shard: list(s.snapshot_rows) or
                [(b"k%d" % i, d) for i, d in enumerate(s.data())],
            is_learner=is_learner, **self.kw)
        part.start()
        self.services[addr] = svc
        self.parts[addr] = part
        self.shards[addr] = shard
        return part

    # ------------------------------------------------------------- helpers
    def wait_leader(self, timeout: float = 5.0,
                    among: Optional[List[str]] = None) -> RaftPart:
        """Wait until exactly one reachable voting member is leader."""
        deadline = time.monotonic() + timeout
        candidates = among or self.voting
        while time.monotonic() < deadline:
            leaders = [self.parts[a] for a in candidates
                       if a in self.parts and self.parts[a].is_leader()]
            if len(leaders) == 1:
                return leaders[0]
            time.sleep(0.02)
        raise AssertionError(
            f"no single leader; status: "
            f"{[self.parts[a].status() for a in candidates if a in self.parts]}")

    def wait_commit(self, n_entries: int, timeout: float = 5.0,
                    addrs: Optional[List[str]] = None) -> None:
        deadline = time.monotonic() + timeout
        addrs = addrs or list(self.parts)
        while time.monotonic() < deadline:
            if all(len(self.shards[a].data()) >= n_entries for a in addrs):
                return
            time.sleep(0.02)
        raise AssertionError(
            f"commits not propagated: "
            f"{{a: len(self.shards[a].data()) for a in addrs}} = "
            f"{ {a: len(self.shards[a].data()) for a in addrs} }")

    def isolate(self, addr: str) -> None:
        self.net.isolate(addr)

    def heal(self, addr: str) -> None:
        self.net.heal(addr)

    def kill(self, addr: str) -> None:
        self.parts[addr].stop()
        self.services[addr].stop()
        del self.parts[addr]
        del self.services[addr]

    def restart(self, addr: str, is_learner: bool = False) -> RaftPart:
        applied = 0
        shard = self.shards.get(addr)
        if shard and shard.commits:
            applied = shard.commits[-1][0]
        svc = RaftexService(addr, self.net)
        part = RaftPart(
            space_id=1, part_id=1, addr=addr, peers=list(self.voting),
            wal_dir=str(self.tmp / addr.replace(":", "_")),
            service=svc, on_commit=shard.on_commit,
            on_snapshot=shard.on_snapshot,
            snapshot_rows=lambda s=shard: [(b"k%d" % i, d)
                                           for i, d in enumerate(s.data())],
            applied_id=applied, is_learner=is_learner, **self.kw)
        part.start()
        self.services[addr] = svc
        self.parts[addr] = part
        return part

    def stop(self) -> None:
        for part in list(self.parts.values()):
            part.stop()
        for svc in list(self.services.values()):
            svc.stop()
        self.net.shutdown()
