"""Raft test harness.

Mirrors the reference's test idiom (ref kvstore/raftex/test/
RaftexTestBase.{h,cpp} + TestShard.{h,cpp}): spin N real raft services
in-process, each hosting a minimal state machine that records its
commits, plus helpers to wait for leader election and to kill/restart
replicas via network isolation.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from nebula_tpu.kvstore.raftex import (InProcNetwork, RaftPart, RaftexService)
from nebula_tpu.kvstore.raftex.service import (RpcTransport,
                                               _unreachable_response)

FAST = dict(heartbeat_interval=0.06, election_timeout=0.2, rpc_timeout=0.5)


class TestShard:
    """Minimal state machine capturing commits (ref TestShard.h)."""

    def __init__(self):
        self.commits: List[Tuple[int, int, bytes]] = []
        self.snapshot_rows: List[Tuple[bytes, bytes]] = []
        self.lock = threading.Lock()

    def on_commit(self, logs):
        with self.lock:
            self.commits.extend(logs)

    def on_snapshot(self, rows, cid, cterm, done):
        with self.lock:
            self.snapshot_rows.extend(rows)

    def data(self) -> List[bytes]:
        with self.lock:
            return [d for _, _, d in self.commits if d]


class RaftCluster:
    def __init__(self, n: int, tmp_path, learners: int = 0, **kw):
        self.net = InProcNetwork()
        self.addrs = [f"127.0.0.1:{9000 + i}" for i in range(n + learners)]
        self.voting = self.addrs[:n]
        self.services: Dict[str, RaftexService] = {}
        self.parts: Dict[str, RaftPart] = {}
        self.shards: Dict[str, TestShard] = {}
        self.tmp = tmp_path
        self.kw = {**FAST, **kw}
        for i, addr in enumerate(self.addrs):
            self._spawn(addr, is_learner=i >= n)

    def _spawn(self, addr: str, is_learner: bool = False) -> RaftPart:
        svc = RaftexService(addr, self.net)
        shard = TestShard()
        part = RaftPart(
            space_id=1, part_id=1, addr=addr, peers=list(self.voting),
            wal_dir=str(self.tmp / addr.replace(":", "_")),
            service=svc, on_commit=shard.on_commit,
            on_snapshot=shard.on_snapshot,
            snapshot_rows=lambda s=shard: list(s.snapshot_rows) or
                [(b"k%d" % i, d) for i, d in enumerate(s.data())],
            is_learner=is_learner, **self.kw)
        part.start()
        self.services[addr] = svc
        self.parts[addr] = part
        self.shards[addr] = shard
        return part

    # ------------------------------------------------------------- helpers
    def wait_leader(self, timeout: float = 5.0,
                    among: Optional[List[str]] = None) -> RaftPart:
        """Wait until exactly one reachable voting member is leader."""
        deadline = time.monotonic() + timeout
        candidates = among or self.voting
        while time.monotonic() < deadline:
            leaders = [self.parts[a] for a in candidates
                       if a in self.parts and self.parts[a].is_leader()]
            if len(leaders) == 1:
                return leaders[0]
            time.sleep(0.02)
        raise AssertionError(
            f"no single leader; status: "
            f"{[self.parts[a].status() for a in candidates if a in self.parts]}")

    def wait_commit(self, n_entries: int, timeout: float = 5.0,
                    addrs: Optional[List[str]] = None) -> None:
        deadline = time.monotonic() + timeout
        addrs = addrs or list(self.parts)
        while time.monotonic() < deadline:
            if all(len(self.shards[a].data()) >= n_entries for a in addrs):
                return
            time.sleep(0.02)
        raise AssertionError(
            f"commits not propagated: "
            f"{{a: len(self.shards[a].data()) for a in addrs}} = "
            f"{ {a: len(self.shards[a].data()) for a in addrs} }")

    def isolate(self, addr: str) -> None:
        self.net.isolate(addr)

    def heal(self, addr: str) -> None:
        self.net.heal(addr)

    def kill(self, addr: str) -> None:
        self.parts[addr].stop()
        self.services[addr].stop()
        del self.parts[addr]
        del self.services[addr]

    def restart(self, addr: str, is_learner: bool = False) -> RaftPart:
        applied = 0
        shard = self.shards.get(addr)
        if shard and shard.commits:
            applied = shard.commits[-1][0]
        svc = RaftexService(addr, self.net)
        part = RaftPart(
            space_id=1, part_id=1, addr=addr, peers=list(self.voting),
            wal_dir=str(self.tmp / addr.replace(":", "_")),
            service=svc, on_commit=shard.on_commit,
            on_snapshot=shard.on_snapshot,
            snapshot_rows=lambda s=shard: [(b"k%d" % i, d)
                                           for i, d in enumerate(s.data())],
            applied_id=applied, is_learner=is_learner, **self.kw)
        part.start()
        self.services[addr] = svc
        self.parts[addr] = part
        return part

    def stop(self) -> None:
        for part in list(self.parts.values()):
            part.stop()
        for svc in list(self.services.values()):
            svc.stop()
        self.net.shutdown()


class FilteredRpcTransport(RpcTransport):
    """RpcTransport with a partition switch: messages from OR to an
    isolated address are dropped before the socket — a two-way network
    partition over the real TCP raft transport (the production path,
    storaged --replicated), controllable like InProcNetwork.isolate."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.isolated: set = set()

    def call(self, from_addr: str, to_addr: str, method: str, req):
        if from_addr in self.isolated or to_addr in self.isolated:
            from concurrent.futures import Future
            f = Future()
            f.set_result(_unreachable_response(method))
            return f
        return super().call(from_addr, to_addr, method, req)


class RpcRaftCluster:
    """N real raft services over framed-TCP rpc/ servers — the
    raftex-over-rpc production shape (RaftexService registered as
    "raftex" on a real socket, peers dialed by host:port), with
    partition injection via the shared FilteredRpcTransport."""

    def __init__(self, n: int, tmp_path, **kw):
        from nebula_tpu.rpc import RpcServer

        self.net = FilteredRpcTransport()
        self.kw = {**FAST, **kw}
        self.tmp = tmp_path
        self.servers: Dict[str, "RpcServer"] = {}
        self.services: Dict[str, RaftexService] = {}
        self.parts: Dict[str, RaftPart] = {}
        self.shards: Dict[str, TestShard] = {}
        servers = [RpcServer("127.0.0.1", 0) for _ in range(n)]
        self.addrs = [s.addr for s in servers]
        for addr, server in zip(self.addrs, servers):
            svc = RaftexService(addr, self.net)
            server.register("raftex", svc).start()
            shard = TestShard()
            part = RaftPart(
                space_id=1, part_id=1, addr=addr,
                peers=list(self.addrs),
                wal_dir=str(tmp_path / addr.replace(":", "_")),
                service=svc, on_commit=shard.on_commit,
                on_snapshot=shard.on_snapshot,
                snapshot_rows=lambda s=shard: [
                    (b"k%d" % i, d) for i, d in enumerate(s.data())],
                **self.kw)
            part.start()
            self.servers[addr] = server
            self.services[addr] = svc
            self.parts[addr] = part
            self.shards[addr] = shard

    # same helper surface as RaftCluster ------------------------------
    wait_leader = RaftCluster.wait_leader
    wait_commit = RaftCluster.wait_commit

    @property
    def voting(self):
        return self.addrs

    def isolate(self, addr: str) -> None:
        self.net.isolated.add(addr)

    def heal(self, addr: str) -> None:
        self.net.isolated.discard(addr)

    def stop(self) -> None:
        for part in list(self.parts.values()):
            part.stop()
        for server in self.servers.values():
            server.stop()
        self.net.shutdown()
