"""DDL / admin / RBAC query tests (parity model: graph/test/SchemaTest.cpp,
graph/test/PermissionTest-style checks)."""
import pytest

from nebula_tpu.cluster import InProcCluster
from nebula_tpu.common.status import ErrorCode


@pytest.fixture()
def conn():
    c = InProcCluster().connect()
    yield c
    c.close()


def test_space_lifecycle(conn):
    conn.must("CREATE SPACE s1(partition_num=3, replica_factor=1)")
    r = conn.must("SHOW SPACES")
    assert ("s1",) in r.rows
    r = conn.must("DESCRIBE SPACE s1")
    assert r.rows[0][1:] == ("s1", 3, 1)
    resp = conn.execute("CREATE SPACE s1")
    assert resp.code == ErrorCode.E_EXISTED
    conn.must("CREATE SPACE IF NOT EXISTS s1")
    conn.must("DROP SPACE s1")
    r = conn.must("SHOW SPACES")
    assert ("s1",) not in r.rows
    resp = conn.execute("DROP SPACE s1")
    assert resp.code == ErrorCode.E_SPACE_NOT_FOUND
    conn.must("DROP SPACE IF EXISTS s1")


def test_schema_lifecycle(conn):
    conn.must("CREATE SPACE s2")
    conn.must("USE s2")
    conn.must("CREATE TAG t(name string, age int DEFAULT 18)")
    r = conn.must("DESCRIBE TAG t")
    assert ("name", "string", "NO", "") in r.rows
    assert ("age", "int", "NO", 18) in r.rows
    conn.must("CREATE EDGE e(weight double)")
    r = conn.must("SHOW TAGS")
    assert any(row[1] == "t" for row in r.rows)
    r = conn.must("SHOW EDGES")
    assert any(row[1] == "e" for row in r.rows)
    # tag/edge name conflict rejected
    resp = conn.execute("CREATE EDGE t(x int)")
    assert resp.code == ErrorCode.E_CONFLICT
    # alter: add + drop
    conn.must("ALTER TAG t ADD (height double)")
    r = conn.must("DESCRIBE TAG t")
    assert any(row[0] == "height" for row in r.rows)
    conn.must("ALTER TAG t DROP (age)")
    r = conn.must("DESCRIBE TAG t")
    assert not any(row[0] == "age" for row in r.rows)
    # old rows still decodable after alter: insert with new schema
    conn.must('INSERT VERTEX t(name, height) VALUES 1:("a", 1.8)')
    r = conn.must("FETCH PROP ON t 1")
    assert r.rows[0][1] == "a"
    conn.must("DROP TAG t")
    resp = conn.execute("DESCRIBE TAG t")
    assert resp.code == ErrorCode.E_TAG_NOT_FOUND


def test_schema_versioning_old_rows(conn):
    conn.must("CREATE SPACE s3")
    conn.must("USE s3")
    conn.must("CREATE TAG p(a int)")
    conn.must("INSERT VERTEX p(a) VALUES 1:(7)")
    conn.must("ALTER TAG p ADD (b string)")
    conn.must('INSERT VERTEX p(a, b) VALUES 2:(8, "x")')
    r = conn.must("FETCH PROP ON p 1, 2")
    by_vid = {row[0]: row for row in r.rows}
    assert by_vid[1][1] == 7          # old row, old schema version
    assert by_vid[2][1:] == (8, "x")  # new row


def test_duplicate_column_rejected(conn):
    conn.must("CREATE SPACE s4")
    conn.must("USE s4")
    resp = conn.execute("CREATE TAG bad(x int, x string)")
    assert resp.code == ErrorCode.E_INVALID_ARGUMENT


def test_users_and_rbac():
    cluster = InProcCluster()
    root = cluster.connect()
    root.must("CREATE SPACE rb")
    root.must('CREATE USER alice WITH PASSWORD "pw"')
    root.must('CREATE USER bob WITH PASSWORD "pw2"')
    root.must("GRANT ROLE ADMIN ON rb TO alice")
    root.must("GRANT ROLE GUEST ON rb TO bob")
    r = root.must("SHOW USERS")
    users = [row[0] for row in r.rows]
    assert "alice" in users and "bob" in users and "root" in users

    # wrong password rejected at authenticate
    assert not cluster.service.authenticate("alice", "wrong").ok()
    alice = cluster.connect("alice", "pw")
    alice.must("USE rb")
    alice.must("CREATE TAG adm_t(x int)")      # ADMIN can do schema DDL
    bob = cluster.connect("bob", "pw2")
    bob.must("USE rb")
    resp = bob.execute("CREATE TAG guest_t(x int)")
    assert resp.code == ErrorCode.E_BAD_PERMISSION
    resp = bob.execute("INSERT VERTEX adm_t(x) VALUES 1:(1)")
    assert resp.code == ErrorCode.E_BAD_PERMISSION
    resp = alice.execute("CREATE SPACE nope")  # GOD-only
    assert resp.code == ErrorCode.E_BAD_PERMISSION
    # revoke
    root.must("REVOKE ROLE ADMIN ON rb FROM alice")
    resp = alice.execute("CREATE TAG t2(x int)")
    assert resp.code == ErrorCode.E_BAD_PERMISSION
    # change password
    root.must('CHANGE PASSWORD alice FROM "pw" TO "pw3"')
    assert cluster.service.authenticate("alice", "pw3").ok()


def test_configs(conn):
    conn.must("SHOW CONFIGS")
    cluster_meta = conn._service.engine.meta
    cluster_meta.reg_config("GRAPH", "slow_op_threshold_ms", 100)
    r = conn.must("SHOW CONFIGS GRAPH")
    assert any(row[1] == "slow_op_threshold_ms" for row in r.rows)
    r = conn.must("GET CONFIGS GRAPH:slow_op_threshold_ms")
    assert r.rows == [("slow_op_threshold_ms", "100")]


def test_show_hosts_and_parts(conn):
    meta = conn._service.engine.meta
    meta.heartbeat("127.0.0.1:44500")
    r = conn.must("SHOW HOSTS")
    assert r.columns[:3] == ["Ip:Port", "Status", "Leader count"]
    assert ("127.0.0.1:44500", "online") in {row[:2] for row in r.rows}
    conn.must("CREATE SPACE sp(partition_num=2, replica_factor=1)")
    conn.must("USE sp")
    r = conn.must("SHOW PARTS")
    assert len(r.rows) == 2
    assert r.columns == ["Partition ID", "Leader", "Peers", "Losts",
                         "Heat", "Staleness ms"]


def test_drop_user_exact_role_match():
    cluster = InProcCluster()
    root = cluster.connect()
    root.must("CREATE SPACE rx")
    root.must('CREATE USER bob WITH PASSWORD "1"')
    root.must('CREATE USER jacob WITH PASSWORD "2"')
    root.must("GRANT ROLE GUEST ON rx TO bob")
    root.must("GRANT ROLE ADMIN ON rx TO jacob")
    root.must("DROP USER bob")
    r = root.must("SHOW ROLES IN rx")
    assert r.rows == [("jacob", "ADMIN")]


def test_root_password_enforced():
    cluster = InProcCluster()
    assert cluster.service.authenticate("root", "").ok()
    assert not cluster.service.authenticate("root", "guess").ok()
    root = cluster.connect()
    root.must('CHANGE PASSWORD root FROM "" TO "s3cret"')
    assert not cluster.service.authenticate("root", "").ok()
    assert cluster.service.authenticate("root", "s3cret").ok()


def test_alter_user_requires_god_and_grant_checks_target_space():
    cluster = InProcCluster()
    root = cluster.connect()
    root.must("CREATE SPACE a")
    root.must("CREATE SPACE b")
    root.must('CREATE USER eve WITH PASSWORD "pw"')
    root.must("GRANT ROLE ADMIN ON a TO eve")
    eve = cluster.connect("eve", "pw")
    eve.must("USE a")
    # account takeover path is closed: ALTER USER by non-root fails
    resp = eve.execute('ALTER USER root WITH PASSWORD "owned"')
    assert resp.code == ErrorCode.E_BAD_PERMISSION
    assert cluster.service.authenticate("root", "").ok()
    # cross-space escalation closed: eve is ADMIN on a, nothing on b
    resp = eve.execute("GRANT ROLE GOD ON b TO eve")
    assert resp.code == ErrorCode.E_BAD_PERMISSION
    resp = eve.execute("GRANT ROLE ADMIN ON b TO eve")
    assert resp.code == ErrorCode.E_BAD_PERMISSION
    # ADMIN cannot mint a peer ADMIN, but can grant USER/GUEST in a
    resp = eve.execute("GRANT ROLE ADMIN ON a TO eve")
    assert resp.code == ErrorCode.E_BAD_PERMISSION
    root.must('CREATE USER mallory WITH PASSWORD "m"')
    eve.must("GRANT ROLE USER ON a TO mallory")
    # ADMIN cannot revoke a peer ADMIN; GOD can
    root.must("GRANT ROLE ADMIN ON a TO mallory")
    resp = eve.execute("REVOKE ROLE ADMIN ON a FROM mallory")
    assert resp.code == ErrorCode.E_BAD_PERMISSION
    root.must("REVOKE ROLE ADMIN ON a FROM mallory")
    # self-service password change with old password still works
    eve.must('CHANGE PASSWORD eve FROM "pw" TO "pw2"')
    assert cluster.service.authenticate("eve", "pw2").ok()


def test_ttl_col_validation_reference_parity(conn):
    """TTL columns must be int/timestamp and can't be dropped while
    active (ref SchemaTest: 'ttl_col on not integer and timestamp
    column' fails)."""
    conn.must("CREATE SPACE ttlsp(partition_num=1)")
    conn.must("USE ttlsp")
    conn.must("CREATE TAG woman(name string, age int, "
              "row_timestamp timestamp) "
              "ttl_duration = 100, ttl_col = row_timestamp")
    conn.must("ALTER TAG woman ttl_duration = 50, "
              "ttl_col = row_timestamp")
    r = conn.execute("ALTER TAG woman ttl_col = name")
    assert not r.ok()                      # string ttl col rejected
    r = conn.execute("CREATE TAG bad(name string) "
                     "ttl_duration = 10, ttl_col = name")
    assert not r.ok()
    r = conn.execute("CREATE TAG bad2(age int) "
                     "ttl_duration = 10, ttl_col = nope")
    assert not r.ok()                      # unknown ttl col rejected
    r = conn.execute("ALTER TAG woman DROP (row_timestamp)")
    assert not r.ok()                      # active ttl col undropable
    conn.must('ALTER TAG woman ttl_col = ""')   # disable ttl...
    conn.must("ALTER TAG woman DROP (row_timestamp)")   # ...then drop


def test_show_create_reference_parity(conn):
    """SHOW CREATE SPACE|TAG|EDGE renders recreating DDL (ref
    SchemaTest.cpp:101-110, :238-250)."""
    conn.must("CREATE SPACE sc_sp(partition_num=9, replica_factor=1)")
    r = conn.must("SHOW CREATE SPACE sc_sp")
    assert r.rows == [("sc_sp", "CREATE SPACE sc_sp (partition_num = 9,"
                       " replica_factor = 1)")]
    conn.must("USE sc_sp")
    conn.must("CREATE TAG person(name string, age int, "
              "row_timestamp timestamp)")
    r = conn.must("SHOW CREATE TAG person")
    assert r.rows == [("person",
                       "CREATE TAG person (\n  name string,\n"
                       "  age int,\n  row_timestamp timestamp\n) "
                       'ttl_duration = 0, ttl_col = ""')]
    # round-trip: the rendered DDL recreates the schema
    conn.must("DROP TAG person")
    create = r.rows[0][1]
    conn.must(create)
    r2 = conn.must("SHOW CREATE TAG person")
    assert r2.rows[0][1] == create
    assert not conn.execute("SHOW CREATE TAG nope").ok()
