"""Balancer + failure-detector tests (ref meta/test/BalancerTest.cpp,
BalanceIntegrationTest.cpp, and the ActiveHostsMan liveness rules)."""
import time

import pytest

from nebula_tpu.kvstore.raftex import InProcNetwork
from nebula_tpu.kvstore.raft_store import AdminClient, StorageNode
from nebula_tpu.meta.balancer import ST_SUCCEEDED, Balancer
from nebula_tpu.meta.service import MetaService

FAST = dict(heartbeat_interval=0.06, election_timeout=0.2, rpc_timeout=0.5)
HOSTS = ["hostA", "hostB", "hostC"]


class BalanceEnv:
    def __init__(self, tmp_path, live=("hostA",)):
        self.net = InProcNetwork()
        self.nodes = {h: StorageNode(h, str(tmp_path), self.net, **FAST)
                      for h in HOSTS}
        self.meta = MetaService()
        self.meta._expired_threshold = 3600
        for h in live:
            self.meta.heartbeat(h)
        self.admin = AdminClient(self.nodes)
        self.balancer = Balancer(self.meta, self.admin)

    def create_space(self, name, parts, replica=1):
        sid = self.meta.create_space(name, parts, replica).value()
        alloc = self.meta.get_parts_alloc(sid)
        for part, hosts in alloc.items():
            for h in hosts:
                self.nodes[h].add_part(sid, part, hosts)
        # wait for leaders everywhere
        for part in alloc:
            self.admin.leader_of(sid, part)
        return sid

    def put(self, sid, part, key, value):
        leader = self.admin.leader_of(sid, part)
        st = self.nodes[leader].store.async_multi_put(
            sid, part, [(key, value)])
        assert st.ok(), st

    def hosting(self, sid):
        """host -> set(parts) as actually instantiated on the nodes."""
        return {h: set(p for (s, p) in n.hooks if s == sid)
                for h, n in self.nodes.items()}

    def stop(self):
        self.balancer.wait()
        for n in self.nodes.values():
            n.stop()
        self.net.shutdown()


@pytest.fixture
def env(tmp_path):
    e = BalanceEnv(tmp_path)
    yield e
    e.stop()


def test_balance_spreads_parts_to_new_hosts(env):
    sid = env.create_space("s1", parts=4)          # all on hostA
    for p in range(1, 5):
        env.put(sid, p, b"\x01key%d" % p, b"val%d" % p)
    assert env.hosting(sid)["hostA"] == {1, 2, 3, 4}

    env.meta.heartbeat("hostB")
    env.meta.heartbeat("hostC")
    plan = env.balancer.balance()
    assert plan.ok(), plan.status
    env.balancer.wait()

    rows = env.balancer.show_plan(plan.value())
    assert rows and all(r[5] == ST_SUCCEEDED for r in rows), rows
    counts = {h: len(ps) for h, ps in env.hosting(sid).items()}
    assert max(counts.values()) - min(counts.values()) <= 1, counts
    # meta allocation agrees with reality
    alloc = env.meta.get_parts_alloc(sid)
    for part, hosts in alloc.items():
        for h in hosts:
            assert part in env.hosting(sid)[h]
    # data moved with the parts
    for p in range(1, 5):
        owner = alloc[p][0]
        eng = env.nodes[owner].store.space_engine(sid)
        assert eng.get(b"\x01key%d" % p) == b"val%d" % p, (p, owner)


def test_balance_remove_host_evacuates(env):
    env.meta.heartbeat("hostB")
    env.meta.heartbeat("hostC")
    sid = env.create_space("s2", parts=3)
    for p in range(1, 4):
        env.put(sid, p, b"\x01k%d" % p, b"v%d" % p)

    plan = env.balancer.balance(remove_hosts=("hostA",))
    if plan.ok():
        env.balancer.wait()
    alloc = env.meta.get_parts_alloc(sid)
    for part, hosts in alloc.items():
        assert "hostA" not in hosts, alloc
    assert env.hosting(sid)["hostA"] == set()
    for p in range(1, 4):
        owner = alloc[p][0]
        assert env.nodes[owner].store.space_engine(sid).get(b"\x01k%d" % p) \
            == b"v%d" % p


def test_balance_noop_when_balanced(env):
    env.meta.heartbeat("hostB")
    env.meta.heartbeat("hostC")
    sid = env.create_space("s3", parts=3)   # round-robin: already even
    plan = env.balancer.balance()
    assert not plan.ok()   # nothing to do


def test_leader_balance(tmp_path):
    env = BalanceEnv(tmp_path, live=HOSTS)
    try:
        sid = env.create_space("s4", parts=4, replica=3)
        # concentrate every leader on hostA
        for p in range(1, 5):
            assert env.admin.trans_leader(sid, p, "hostA")
        assert env.balancer.leader_balance().ok()
        leaders = env.admin.leader_map(sid, [1, 2, 3, 4])
        counts = {}
        for l in leaders.values():
            counts[l] = counts.get(l, 0) + 1
        assert max(counts.values()) <= 2, counts   # ceil(4/3) = 2
    finally:
        env.stop()


def test_active_hosts_expiry():
    meta = MetaService()
    meta._expired_threshold = 0.2
    meta.heartbeat("h1")
    meta.heartbeat("h2")
    assert {h.host for h in meta.active_hosts()} == {"h1", "h2"}
    time.sleep(0.3)
    meta.heartbeat("h2")
    assert {h.host for h in meta.active_hosts()} == {"h2"}
    # all_hosts reports liveness flags
    flags = {h.host: alive for h, alive in meta.all_hosts()}
    assert flags == {"h1": False, "h2": True}
