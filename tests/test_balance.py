"""Balancer + failure-detector tests (ref meta/test/BalancerTest.cpp,
BalanceIntegrationTest.cpp, and the ActiveHostsMan liveness rules)."""
import time

import pytest

from nebula_tpu.kvstore.raftex import InProcNetwork
from nebula_tpu.kvstore.raft_store import AdminClient, StorageNode
from nebula_tpu.meta.balancer import ST_SUCCEEDED, Balancer
from nebula_tpu.meta.service import MetaService

FAST = dict(heartbeat_interval=0.06, election_timeout=0.2, rpc_timeout=0.5)
HOSTS = ["hostA", "hostB", "hostC"]


class BalanceEnv:
    def __init__(self, tmp_path, live=("hostA",)):
        self.net = InProcNetwork()
        self.nodes = {h: StorageNode(h, str(tmp_path), self.net, **FAST)
                      for h in HOSTS}
        self.meta = MetaService()
        self.meta._expired_threshold = 3600
        for h in live:
            self.meta.heartbeat(h)
        self.admin = AdminClient(self.nodes)
        self.balancer = Balancer(self.meta, self.admin)

    def create_space(self, name, parts, replica=1):
        sid = self.meta.create_space(name, parts, replica).value()
        alloc = self.meta.get_parts_alloc(sid)
        for part, hosts in alloc.items():
            for h in hosts:
                self.nodes[h].add_part(sid, part, hosts)
        # wait for leaders everywhere
        for part in alloc:
            self.admin.leader_of(sid, part)
        return sid

    def put(self, sid, part, key, value):
        leader = self.admin.leader_of(sid, part)
        st = self.nodes[leader].store.async_multi_put(
            sid, part, [(key, value)])
        assert st.ok(), st

    def hosting(self, sid):
        """host -> set(parts) as actually instantiated on the nodes."""
        return {h: set(p for (s, p) in n.hooks if s == sid)
                for h, n in self.nodes.items()}

    def stop(self):
        self.balancer.wait()
        for n in self.nodes.values():
            n.stop()
        self.net.shutdown()


@pytest.fixture
def env(tmp_path):
    e = BalanceEnv(tmp_path)
    yield e
    e.stop()


def test_balance_spreads_parts_to_new_hosts(env):
    sid = env.create_space("s1", parts=4)          # all on hostA
    for p in range(1, 5):
        env.put(sid, p, b"\x01key%d" % p, b"val%d" % p)
    assert env.hosting(sid)["hostA"] == {1, 2, 3, 4}

    env.meta.heartbeat("hostB")
    env.meta.heartbeat("hostC")
    plan = env.balancer.balance()
    assert plan.ok(), plan.status
    env.balancer.wait()

    rows = env.balancer.show_plan(plan.value())
    assert rows and all(r[5] == ST_SUCCEEDED for r in rows), rows
    counts = {h: len(ps) for h, ps in env.hosting(sid).items()}
    assert max(counts.values()) - min(counts.values()) <= 1, counts
    # meta allocation agrees with reality
    alloc = env.meta.get_parts_alloc(sid)
    for part, hosts in alloc.items():
        for h in hosts:
            assert part in env.hosting(sid)[h]
    # data moved with the parts
    for p in range(1, 5):
        owner = alloc[p][0]
        eng = env.nodes[owner].store.space_engine(sid)
        assert eng.get(b"\x01key%d" % p) == b"val%d" % p, (p, owner)


def test_balance_remove_host_evacuates(env):
    env.meta.heartbeat("hostB")
    env.meta.heartbeat("hostC")
    sid = env.create_space("s2", parts=3)
    for p in range(1, 4):
        env.put(sid, p, b"\x01k%d" % p, b"v%d" % p)

    plan = env.balancer.balance(remove_hosts=("hostA",))
    if plan.ok():
        env.balancer.wait()
    alloc = env.meta.get_parts_alloc(sid)
    for part, hosts in alloc.items():
        assert "hostA" not in hosts, alloc
    assert env.hosting(sid)["hostA"] == set()
    for p in range(1, 4):
        owner = alloc[p][0]
        assert env.nodes[owner].store.space_engine(sid).get(b"\x01k%d" % p) \
            == b"v%d" % p


def test_balance_noop_when_balanced(env):
    env.meta.heartbeat("hostB")
    env.meta.heartbeat("hostC")
    sid = env.create_space("s3", parts=3)   # round-robin: already even
    plan = env.balancer.balance()
    assert not plan.ok()   # nothing to do


def test_leader_balance(tmp_path):
    env = BalanceEnv(tmp_path, live=HOSTS)
    try:
        sid = env.create_space("s4", parts=4, replica=3)
        # concentrate every leader on hostA
        for p in range(1, 5):
            assert env.admin.trans_leader(sid, p, "hostA")
        assert env.balancer.leader_balance().ok()
        leaders = env.admin.leader_map(sid, [1, 2, 3, 4])
        counts = {}
        for l in leaders.values():
            counts[l] = counts.get(l, 0) + 1
        assert max(counts.values()) <= 2, counts   # ceil(4/3) = 2
    finally:
        env.stop()


def test_active_hosts_expiry():
    meta = MetaService()
    meta._expired_threshold = 0.2
    meta.heartbeat("h1")
    meta.heartbeat("h2")
    assert {h.host for h in meta.active_hosts()} == {"h1", "h2"}
    time.sleep(0.3)
    meta.heartbeat("h2")
    assert {h.host for h in meta.active_hosts()} == {"h2"}
    # all_hosts reports liveness flags
    flags = {h.host: alive for h, alive in meta.all_hosts()}
    assert flags == {"h1": False, "h2": True}


def test_balancer_resumes_after_metad_restart(tmp_path):
    """Satellite (ISSUE 6): a BalancePlan persisted in the meta KV
    survives the balancer-owning metad dying mid-flight — a fresh
    metad on the same store resumes the SAME plan (Balancer::recovery,
    ref Balancer.cpp:67-106), skips the already-terminal task, and
    drives the remaining tasks to SUCCEEDED over the storaged admin
    services."""
    import socket

    from nebula_tpu.client import GraphClient
    from nebula_tpu.daemons import (serve_graphd, serve_metad,
                                    serve_storaged)
    from nebula_tpu.kvstore.store import GraphStore
    from nebula_tpu.meta.balancer import ST_START, BalanceTask

    store = GraphStore()            # the "disk" both metad boots share
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    metad = serve_metad(port=port, store=store)
    s0 = serve_storaged(metad.addr, replicated=True,
                        data_dir=str(tmp_path / "s0"), load_interval=0.1)
    s1 = serve_storaged(metad.addr, replicated=True,
                        data_dir=str(tmp_path / "s1"), load_interval=0.1)
    graphd = serve_graphd(metad.addr)
    gc = GraphClient(graphd.addr).connect()
    metad2 = None
    try:
        for stmt in ("CREATE SPACE rebal(partition_num=4, "
                     "replica_factor=1)", "USE rebal",
                     "CREATE TAG t(x int)"):
            r = gc.execute(stmt)
            assert r.ok(), (stmt, r.error_msg)
        deadline = time.time() + 15
        while time.time() < deadline:
            r = gc.execute(
                "INSERT VERTEX t(x) VALUES 1:(1), 2:(2), 3:(3), 4:(4)")
            if r.ok():
                break
            time.sleep(0.2)
        assert r.ok(), r.error_msg
        space_id = metad.meta.get_space("rebal").value().space_id
        alloc = metad.meta.get_parts_alloc(space_id)
        moves = sorted(p for p, hosts in alloc.items()
                       if hosts == [s0.addr])
        assert len(moves) >= 2, alloc

        # persist a mid-flight plan: first task already terminal (it
        # "ran" before the crash), the rest still START
        plan_id = metad.meta._next_id("balance_plan")
        tasks = [BalanceTask(plan_id, space_id, p, s0.addr, s1.addr,
                             status=ST_START) for p in moves]
        tasks[0].status = "SUCCEEDED"
        for t in tasks:
            metad.meta._put((t.key(), t.value()))

        # metad dies; a new one boots on the same store and port — the
        # catalog, cluster id and the unfinished plan all persist
        metad.stop()
        metad2 = serve_metad(port=port, store=store)
        r = gc.must("BALANCE DATA")
        assert r.rows[0][0] == plan_id, \
            "resume must drive the persisted plan, not mint a new one"
        metad2.meta._balancer.wait(60)
        rows = metad2.meta.balance_show(plan_id)
        assert rows and all(row[-1] == "SUCCEEDED" for row in rows), rows

        # the unfinished moves actually happened
        alloc = metad2.meta.get_parts_alloc(space_id)
        for p in moves[1:]:
            assert alloc[p] == [s1.addr], (p, alloc)
        # data reachable after the moves
        deadline = time.time() + 10
        while time.time() < deadline:
            r = gc.execute("FETCH PROP ON t 1,2,3,4 YIELD t.x")
            if r.ok() and len(r.rows) == 4:
                break
            time.sleep(0.25)
        assert r.ok() and sorted(x[-1] for x in r.rows) == [1, 2, 3, 4]
    finally:
        gc.disconnect()
        graphd.stop()
        s0.stop()
        s1.stop()
        (metad2 or metad).stop()
