"""benchdiff + nebtop + federation units (ISSUE 12 satellites): the
perf-trajectory gate over crafted fixtures, the cluster-metrics merge
(strict-parsed), and nebtop's exposition reader."""
import json

from nebula_tpu.tools import benchdiff

import openmetrics


# ------------------------------------------------------------ fixtures

OLD = {
    "parsed": {
        "value": 100.0,
        "tier2_full_query_ms": {"p50": 2.0, "p99": 5.0,
                                "qps_batch1": 300.0},
        "tier3": {"qps": 40.0, "sessions": 8},
    },
    "phases": {"baseline": {"n": 100, "p99_ms": 120.0, "qps": 75.0}},
}


def _new(**over):
    new = json.loads(json.dumps(OLD))
    for path, v in over.items():
        cur = new
        keys = path.split("__")
        for k in keys[:-1]:
            cur = cur[k]
        cur[keys[-1]] = v
    return new


def test_no_change_passes(tmp_path):
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(OLD))
    b.write_text(json.dumps(OLD))
    assert benchdiff.main([str(a), str(b)]) == 0


def test_latency_regression_fails(tmp_path):
    new = _new(parsed__tier2_full_query_ms__p99=9.0)   # 5 -> 9 ms
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(OLD))
    b.write_text(json.dumps(new))
    assert benchdiff.main([str(a), str(b)]) == 1
    # advisory mode reports but exits 0 (the verify-skill CI step)
    assert benchdiff.main([str(a), str(b), "--advisory"]) == 0


def test_qps_drop_fails_and_direction_is_respected(tmp_path):
    new = _new(parsed__tier3__qps=20.0)               # 40 -> 20
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(OLD))
    b.write_text(json.dumps(new))
    assert benchdiff.main([str(a), str(b)]) == 1
    # a qps INCREASE is an improvement, never a regression
    new2 = _new(parsed__tier3__qps=80.0)
    b.write_text(json.dumps(new2))
    assert benchdiff.main([str(a), str(b)]) == 0


def test_tolerance_absorbs_noise(tmp_path):
    new = _new(parsed__tier2_full_query_ms__p99=5.5)  # +10% < 25%
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(OLD))
    b.write_text(json.dumps(new))
    assert benchdiff.main([str(a), str(b)]) == 0
    assert benchdiff.main([str(a), str(b), "--tolerance", "0.05"]) == 1


def test_config_echoes_are_ignored():
    new = _new(parsed__tier3__sessions=16, phases__baseline__n=1)
    r = benchdiff.compare(OLD, new)
    assert not r["regressions"]
    paths = {d["path"] for d in r["drift"]}
    assert "parsed.tier3.sessions" in paths


def test_profile_block_rules(tmp_path):
    """ISSUE 13 satellite: the tier-3 `profile` block's diagnostics
    (sampler bookkeeping, lock-wait totals, GC/compile tables,
    top-frame shares) are advisory drift — never gated — while the
    overhead proof's twin QPS numbers judge as throughput."""
    old = {"tier3": {"profile": {
        "qps_hz19": 40.0, "qps_ratio": 0.99, "top_share": 0.8,
        "sampler": {"self_us": 1000, "ticks": 100},
        "top_locks": [{"contended": 3, "wait_us": 9000}],
        "gc": {"pause_us_total": 500},
        "compiles": {"total_us": 100000},
    }}}
    new = json.loads(json.dumps(old))
    p = new["tier3"]["profile"]
    # wild diagnostic swings: all advisory
    p["qps_ratio"] = 0.5
    p["top_share"] = 0.1
    p["sampler"]["self_us"] = 99999
    p["top_locks"][0]["wait_us"] = 900000
    p["gc"]["pause_us_total"] = 50000
    p["compiles"]["total_us"] = 9999999
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(old))
    b.write_text(json.dumps(new))
    assert benchdiff.main([str(a), str(b)]) == 0
    # ... but the profiled-twin QPS collapsing IS a regression
    p["qps_hz19"] = 10.0
    b.write_text(json.dumps(new))
    assert benchdiff.main([str(a), str(b)]) == 1
    assert benchdiff.main([str(a), str(b), "--advisory"]) == 0


def test_skew_block_rules(tmp_path):
    """ISSUE 14 satellite: SKEW_bench.json diffs — sketch recall and
    the Zipf-phase skew index judge with tolerance; raw heat counters,
    the advisory plan, hot-part shares and staleness watermarks are
    advisory drift, never gated."""
    old = {
        "sketch": {"recall": 1.0, "evictions": 12, "tracked": 64},
        "skew_index": {"uniform": 1.05, "zipf": 2.8,
                       "separation": 2.6},
        "advisor": {"spread_before": 155.0, "spread_after": 85.0},
        "hot_part": {"top_share_pct": 31.0, "armed_pct": 26.0},
        "overhead": {"qps_disarmed": 900.0, "qps_armed": 890.0,
                     "ratio": 0.989},
        "heat": {"parts_tracked": 8,
                 "top_parts": [{"score_600s": 300.0}]},
        "staleness_ms": 4.0,
    }
    new = json.loads(json.dumps(old))
    # wild diagnostic swings: all advisory
    new["advisor"]["spread_after"] = 300.0
    new["hot_part"]["top_share_pct"] = 99.0
    new["heat"]["top_parts"][0]["score_600s"] = 9.0
    new["staleness_ms"] = 900.0
    new["overhead"]["ratio"] = 0.5
    new["sketch"]["evictions"] = 9999
    new["skew_index"]["uniform"] = 3.0
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(old))
    b.write_text(json.dumps(new))
    assert benchdiff.main([str(a), str(b)]) == 0
    # ... but recall collapsing IS a regression
    new["sketch"]["recall"] = 0.4
    b.write_text(json.dumps(new))
    assert benchdiff.main([str(a), str(b)]) == 1
    # ... and so is the Zipf skew index no longer separating
    new["sketch"]["recall"] = 1.0
    new["skew_index"]["zipf"] = 1.0
    b.write_text(json.dumps(new))
    assert benchdiff.main([str(a), str(b)]) == 1
    assert benchdiff.main([str(a), str(b), "--advisory"]) == 0


def test_consistency_block_rules(tmp_path):
    """ISSUE 15 satellite: CONSISTENCY_bench.json diffs — the
    detection latency judges as a latency (smaller is better); sample
    tallies, digest echoes, fault bookkeeping and shadow queue state
    are run-length diagnostics, advisory only."""
    old = {
        "drill": {"corrupt_fired": 1, "detect_s": 0.02,
                  "digest_ok_gauge_lines": 6, "show_rows": 12,
                  "shadow": {"sampled": 40, "verified": 9,
                             "skipped_stale": 3}},
        "shadow": {"sampled": 120, "verified": 30, "dropped": 50,
                   "skipped_stale": 9},
        "clean": {"writes": 200, "verified_replicas": 6},
        "audit": {"checked": 1, "skipped": 0},
    }
    new = json.loads(json.dumps(old))
    # diagnostic swings: all advisory
    new["shadow"]["sampled"] = 3
    new["shadow"]["dropped"] = 900
    new["clean"]["verified_replicas"] = 1
    new["drill"]["digest_ok_gauge_lines"] = 1
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(old))
    b.write_text(json.dumps(new))
    assert benchdiff.main([str(a), str(b)]) == 0
    # ... but detection latency blowing up IS a regression
    new["drill"]["detect_s"] = 4.5
    b.write_text(json.dumps(new))
    assert benchdiff.main([str(a), str(b)]) == 1
    assert benchdiff.main([str(a), str(b), "--advisory"]) == 0


def test_custom_rule_wins(tmp_path):
    new = _new(parsed__value=50.0)
    r = benchdiff.compare(OLD, new)
    assert any(x["path"] == "parsed.value" for x in r["regressions"])
    # --rule can demote it to ignore (first match wins)
    r2 = benchdiff.compare(
        OLD, new, rules=(("parsed.value", "ignore"),)
        + benchdiff.DEFAULT_RULES)
    assert not r2["regressions"]


def test_bad_usage_exits_2(tmp_path):
    assert benchdiff.main(["/nope/a.json", "/nope/b.json"]) == 2
    a = tmp_path / "a.json"
    a.write_text("{}")
    assert benchdiff.main([str(a), str(a), "--rule", "x=sideways"]) == 2


def test_json_output_shape(tmp_path, capsys):
    a = tmp_path / "a.json"
    a.write_text(json.dumps(OLD))
    assert benchdiff.main([str(a), str(a), "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert set(out) >= {"regressions", "improvements", "drift"}


# --------------------------------------------------------- federation

def test_merge_expositions_strict_parses():
    from nebula_tpu.common.promfed import merge_expositions
    graph_text = (
        "# TYPE nebula_graph_query counter\n"
        "nebula_graph_query_total 12\n"
        "# TYPE nebula_lat histogram\n"
        'nebula_lat_bucket{le="1"} 1\n'
        'nebula_lat_bucket{le="+Inf"} 2\n'
        "nebula_lat_sum 3\n"
        "nebula_lat_count 2\n"
        "# TYPE nebula_build_info gauge\n"
        'nebula_build_info{daemon="graphd",role="graph"} 1\n'
        "# EOF\n")
    storage_text = (
        "# TYPE nebula_graph_query counter\n"
        "nebula_graph_query_total 0\n"
        "# TYPE nebula_lat histogram\n"
        'nebula_lat_bucket{le="1"} 5\n'
        'nebula_lat_bucket{le="+Inf"} 6\n'
        "nebula_lat_sum 9\n"
        "nebula_lat_count 6\n"
        "# EOF\n")
    doc = merge_expositions([
        ("127.0.0.1:13000", "graph", graph_text),
        ("127.0.0.1:12000", "storage", storage_text),
        ("127.0.0.1:12001", "storage", None),       # dead daemon
    ])
    fams = openmetrics.parse(doc)
    # one family per name, samples from both instances
    q = fams["nebula_graph_query"]
    insts = {s.labels["instance"] for s in q.samples}
    assert insts == {"127.0.0.1:13000", "127.0.0.1:12000"}
    # per-series histogram consistency survives federation
    assert "nebula_lat" in fams
    # the pre-labeled role on build_info is NOT duplicated
    bi = fams["nebula_build_info"].samples[0]
    assert bi.labels["role"] == "graph"
    assert bi.labels["instance"] == "127.0.0.1:13000"
    # scrape-health family marks the dead daemon down
    scrape = {s.labels["instance"]: s.value
              for s in fams["nebula_cluster_scrape"].samples}
    assert scrape["127.0.0.1:12001"] == 0
    assert scrape["127.0.0.1:12000"] == 1


def test_merge_type_conflict_drops_dissenter():
    from nebula_tpu.common.promfed import merge_expositions
    a = "# TYPE nebula_x gauge\nnebula_x 1\n# EOF\n"
    b = "# TYPE nebula_x counter\nnebula_x_total 2\n# EOF\n"
    doc = merge_expositions([("i1", "graph", a), ("i2", "storage", b)])
    fams = openmetrics.parse(doc)
    assert fams["nebula_x"].type == "gauge"
    assert len(fams["nebula_x"].samples) == 1


# -------------------------------------------------------------- nebtop

def test_nebtop_parse_and_views():
    from nebula_tpu.tools import nebtop
    text = (
        "# TYPE nebula_cluster_scrape gauge\n"
        'nebula_cluster_scrape{instance="a:1",role="graph"} 1\n'
        'nebula_cluster_scrape{instance="b:2",role="storage"} 0\n'
        "# TYPE nebula_graph_query counter\n"
        'nebula_graph_query_total{instance="a:1",role="graph"} 42\n'
        "# TYPE nebula_storage_raft_s1_p1_is_leader gauge\n"
        'nebula_storage_raft_s1_p1_is_leader{instance="b:2"} 1\n'
        "# TYPE nebula_graph_cost_myspace_device_us histogram\n"
        'nebula_graph_cost_myspace_device_us_bucket'
        '{instance="a:1",le="+Inf"} 3\n'
        'nebula_graph_cost_myspace_device_us_sum{instance="a:1"} 777\n'
        'nebula_graph_cost_myspace_device_us_count{instance="a:1"} 3\n'
        "# EOF\n")
    snap = nebtop.Snapshot(nebtop.parse_samples(text), t=100.0)
    insts = snap.instances()
    assert [i["instance"] for i in insts] == ["a:1", "b:2"]
    assert insts[1]["up"] is False
    assert snap.sum("nebula_graph_query_total") == 42
    assert snap.leader_counts() == {"b:2": 1}
    assert snap.tenant_cost()["myspace"]["device_us"] == 777
    # render must not raise with or without a previous snapshot
    assert "nebtop" in nebtop.render(snap, None)
    assert nebtop.snapshot_dict(snap)["query_total"] == 42


def test_nebtop_heat_panel():
    """ISSUE 14: the hot-parts panel reads the nebula_part_heat_* and
    nebula_heat_skew_index_* families and renders the top parts; the
    panel is absent when heat is disarmed (families missing)."""
    from nebula_tpu.tools import nebtop
    text = (
        "# TYPE nebula_part_heat_s1_p3_reads gauge\n"
        'nebula_part_heat_s1_p3_reads{instance="b:2"} 120\n'
        "# TYPE nebula_part_heat_s1_p3_score gauge\n"
        'nebula_part_heat_s1_p3_score{instance="b:2"} 250.5\n'
        "# TYPE nebula_part_heat_s1_p1_score gauge\n"
        'nebula_part_heat_s1_p1_score{instance="b:2"} 10\n'
        "# TYPE nebula_heat_skew_index_s1 gauge\n"
        'nebula_heat_skew_index_s1{instance="b:2"} 2.75\n'
        "# EOF\n")
    snap = nebtop.Snapshot(nebtop.parse_samples(text), t=1.0)
    ph = snap.part_heat()
    assert ph["parts"][(1, 3, "b:2")]["score"] == 250.5
    assert ph["parts"][(1, 3, "b:2")]["reads"] == 120
    assert ph["skew"]["1"] == 2.75
    lines = nebtop.render_heat(ph)
    assert any("hot parts" in ln for ln in lines)
    assert any("1:3" in ln for ln in lines)
    # hottest part renders first
    rows = [ln for ln in lines if ln.startswith("1:")]
    assert rows[0].startswith("1:3")
    # disarmed: no families -> no panel
    empty = nebtop.Snapshot([], t=1.0)
    assert nebtop.render_heat(empty.part_heat()) == []
    d = nebtop.snapshot_dict(snap)
    assert d["heat"]["parts"]["1:3@b:2"]["score"] == 250.5
