"""Cluster-scale offline bulk build (round-4 verdict item 6).

The reference scales offline ingest across a Spark cluster
(tools/spark-sstfile-generator: per-part SST files on HDFS, each
storaged downloads ITS parts via StorageHttpDownloadHandler, then
INGEST). This test drives the same posture end-to-end on the real TCP
topology: a >=1M-row CSV built into per-part NSSTs by the scale-out
generator, THREE storaged staging disjoint part sets (the per-part
selective download) and ingesting them CONCURRENTLY, then verified by
spot queries plus the integrity circle walk.
"""
import os
import time

import numpy as np
import pytest

from nebula_tpu.client import GraphClient
from nebula_tpu.daemons import serve_graphd, serve_metad, serve_storaged

E = 1_000_000        # edge CSV rows (stored as 2E kv pairs: fwd + rev)
V = 100_000
CIRCLE = 1500        # integrity circle vertices (serial walk = 1 RPC/hop)


def test_cluster_bulk_build_three_storaged(tmp_path):
    from nebula_tpu.common.flags import storage_flags
    from nebula_tpu.storage.sst import part_file
    from nebula_tpu.tools.integrity_check import validate
    from nebula_tpu.tools.sst_generator import generate_parallel

    # set BEFORE boot: storaged syncs its flags into the meta registry
    # at start and the heartbeat hot-pull would revert a later local set
    prev = storage_flags.get("download_dir")
    storage_flags.set("download_dir", str(tmp_path / "staging"))
    metad = serve_metad()
    sds = [serve_storaged(metad.addr, load_interval=0.1)
           for _ in range(3)]
    gd = serve_graphd(metad.addr)
    try:
        c = GraphClient(gd.addr).connect()
        for stmt in ("CREATE SPACE bulk(partition_num=6)", "USE bulk",
                     "CREATE TAG person(nxt int)",
                     "CREATE EDGE knows(ts int)"):
            r = c.execute(stmt)
            assert r.ok(), (stmt, r.error_msg)
        sid = gd.meta_client.get_space("bulk").value().space_id
        for _ in range(100):
            if all(sd.store.parts(sid) for sd in sds):
                break
            time.sleep(0.1)
        part_sets = [set(sd.store.parts(sid)) for sd in sds]
        assert sum(len(s) for s in part_sets) == 6 and \
            set.union(*part_sets) == set(range(1, 7)), part_sets

        # ---- offline build: 1M-row edge CSV + integrity circle ------
        rng = np.random.default_rng(5)
        src = rng.integers(1, V, E)
        dst = rng.integers(1, V, E)
        ts = rng.integers(0, 10 ** 9, E)
        with open(tmp_path / "edges.csv", "w") as f:
            f.write("src,dst,ts\n")
            f.writelines(f"{a},{b},{w}\n"
                         for a, b, w in zip(src, dst, ts))
        with open(tmp_path / "circle.csv", "w") as f:
            f.write("id,nxt\n")
            f.writelines(f"{i},{i % CIRCLE + 1}\n"
                         for i in range(1, CIRCLE + 1))
        sm = gd.engine.sm
        tag_id = sm.tag_id(sid, "person")
        etype = sm.edge_type(sid, "knows")
        mapping = {
            "num_parts": 6,
            "vertices": [{"file": "circle.csv", "tag_id": tag_id,
                          "vid_col": "id", "props": {"nxt": "int"}}],
            "edges": [{"file": "edges.csv", "edge_type": etype,
                       "src_col": "src", "dst_col": "dst",
                       "rank_col": None, "props": {"ts": "int"}}],
        }
        out_dir = tmp_path / "sst_out"
        counts = generate_parallel(mapping, str(out_dir),
                                   base_dir=str(tmp_path), workers=3)
        assert sum(counts.values()) == 2 * E + CIRCLE

        # ---- per-part selective download: each host stages ONLY its
        # parts' files, concurrently across the 3 hosts --------------
        r = c.execute(f'DOWNLOAD HDFS "{out_dir}"')
        assert r.ok(), r.error_msg
        for sd, parts in zip(sds, part_sets):
            host_dir = (tmp_path / "staging" / f"space_{sid}"
                        / sd.addr.replace(":", "_"))
            assert set(os.listdir(host_dir)) == \
                {part_file(p) for p in parts}, sd.addr

        # ---- concurrent ingest of the disjoint part sets ------------
        t0 = time.time()
        r = c.execute("INGEST")
        assert r.ok(), r.error_msg
        ingest_s = time.time() - t0
        per_host = [sd.store.space_engine(sid).total_keys()
                    for sd in sds]
        assert all(n > 0 for n in per_host), per_host
        # duplicate (src, dst) draws collapse to one key when they land
        # in the same generator worker (same build version) and stay
        # versioned otherwise — bound from both sides
        uniq = len(set(zip(src.tolist(), dst.tolist())))
        assert 2 * uniq + CIRCLE <= sum(per_host) <= 2 * E + CIRCLE, \
            (sum(per_host), uniq)

        # ---- verification: spot query + integrity circle walk -------
        s0 = int(src[0])
        r = c.execute(f"GO FROM {s0} OVER knows YIELD knows._dst")
        assert r.ok() and len(r.rows) >= 1
        expect = sorted({int(d) for a, d in zip(src, dst) if a == s0})
        assert sorted(x for (x,) in r.rows) == expect
        out = validate(gd.engine.client, sm, sid, tag_id, "nxt",
                       start_vid=1, expected_steps=CIRCLE)
        assert out["ok"], out
        print(f"bulk build: {2 * E + CIRCLE} pairs over 3 storaged "
              f"({per_host}), ingest {ingest_s:.1f}s, circle OK")
    finally:
        storage_flags.set("download_dir", prev)
        for h in [gd] + sds + [metad]:
            try:
                h.stop()
            except Exception:
                pass
