"""Bulk-load pipeline (SST generate → DOWNLOAD → INGEST) and snapshots.

Mirrors the reference's offline load flow: Spark generator writes
per-part SSTs, DOWNLOAD stages them per storaged, INGEST loads them
into the engine (ref: tools/spark-sstfile-generator,
storage/StorageHttp{Download,Ingest}Handler, RocksEngine::ingest), and
CREATE/DROP SNAPSHOT checkpoints every space.
"""
import os

import pytest

from nebula_tpu.cluster import InProcCluster
from nebula_tpu.common.flags import storage_flags
from nebula_tpu.storage.sst import SstGenerator, read_sst, write_sst


@pytest.fixture
def cluster(tmp_path):
    storage_flags.set("download_dir", str(tmp_path / "staging"))
    storage_flags.set("snapshot_dir", str(tmp_path / "snapshots"))
    c = InProcCluster()
    conn = c.connect()
    conn.execute("CREATE SPACE bulk(partition_num=4, replica_factor=1)")
    conn.execute("USE bulk")
    conn.execute("CREATE TAG person(name string)")
    conn.execute("CREATE EDGE knows(weight int)")
    return c, conn


def _gen_sst_dir(c, tmp_path):
    """Offline generation: 6 people in a chain 1->2->...->6."""
    space = c.meta.get_space("bulk").value()
    sm = c.sm
    person = sm.tag_schema(space.space_id,
                           sm.tag_id(space.space_id, "person")).value()
    knows = sm.edge_schema(space.space_id,
                           sm.edge_type(space.space_id, "knows")).value()
    gen = SstGenerator(space.partition_num)
    for vid in range(1, 7):
        gen.add_vertex(vid, sm.tag_id(space.space_id, "person"), person,
                       {"name": f"p{vid}"})
    eid = sm.edge_type(space.space_id, "knows")
    for vid in range(1, 6):
        gen.add_edge(vid, eid, 0, vid + 1, knows, {"weight": vid * 10})
    out = tmp_path / "sst_out"
    counts = gen.write(str(out))
    assert sum(counts.values()) == 6 + 2 * 5  # tags + fwd/rev edges
    return str(out)


def test_sst_roundtrip(tmp_path):
    kvs = [(b"b", b"2"), (b"a", b"1"), (b"c", b"3")]
    p = str(tmp_path / "x.nsst")
    assert write_sst(p, kvs) == 3
    assert read_sst(p) == sorted(kvs)


def test_download_ingest_go(cluster, tmp_path):
    c, conn = cluster
    src = _gen_sst_dir(c, tmp_path)
    r = conn.execute(f'DOWNLOAD HDFS "{src}"')
    assert r.ok(), r.error_msg
    r = conn.execute("INGEST")
    assert r.ok(), r.error_msg
    assert r.rows[0][0] == 16
    r = conn.execute("GO 2 STEPS FROM 1 OVER knows YIELD knows._dst")
    assert r.ok(), r.error_msg
    assert [row[0] for row in r.rows] == [3]
    r = conn.execute("FETCH PROP ON person 4 YIELD person.name")
    assert r.rows[0][1] == "p4"


def test_download_missing_dir(cluster, tmp_path):
    _, conn = cluster
    r = conn.execute(f'DOWNLOAD HDFS "{tmp_path}/nope"')
    assert not r.ok()


def test_ingest_without_download(cluster):
    _, conn = cluster
    r = conn.execute("INGEST")
    assert not r.ok()


def test_snapshot_lifecycle(cluster, tmp_path):
    c, conn = cluster
    conn.execute('INSERT VERTEX person(name) VALUES 42:("alice")')
    r = conn.execute("CREATE SNAPSHOT")
    assert r.ok(), r.error_msg
    name = r.rows[0][0]
    # record is VALID and the dump exists
    r = conn.execute("SHOW SNAPSHOTS")
    assert (name, "VALID") in r.rows
    space_id = c.meta.get_space("bulk").value().space_id
    dump = os.path.join(storage_flags.get("snapshot_dir"), name, "local",
                        f"space_{space_id}.nsst")
    assert os.path.exists(dump)
    # wipe the space data, restore from the snapshot, data is back
    engine = c.store.space_engine(space_id)
    engine.remove_prefix(b"")
    r = conn.execute("FETCH PROP ON person 42 YIELD person.name")
    assert r.ok() and not r.rows
    assert c.storage.restore_checkpoint(name, space_id).ok()
    r = conn.execute("FETCH PROP ON person 42 YIELD person.name")
    assert r.rows and r.rows[0][1] == "alice"
    # drop removes record + files
    r = conn.execute(f"DROP SNAPSHOT {name}")
    assert r.ok(), r.error_msg
    assert not os.path.exists(dump)
    r = conn.execute("SHOW SNAPSHOTS")
    assert r.rows == []
