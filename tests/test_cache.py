"""Snapshot-versioned multi-level caching (common/cache.py; docs/
manual/11-caching.md): the plan / filter-plan / result / negative /
in-window-dedupe rungs and the storaged stats/scan rungs, with the
staleness contract tested by construction — a write between two
identical statements must make the second reflect the write, a delta
apply landing mid-serve must never publish the pre-write rows under
the post-write key, and a poisoned snapshot must purge its entries."""
import threading
import time

import pytest

from nebula_tpu.cluster import InProcCluster
from nebula_tpu.common.cache import CacheRung
from nebula_tpu.common.faults import faults
from nebula_tpu.common.flags import graph_flags, storage_flags
from nebula_tpu.engine_tpu import TpuGraphEngine


@pytest.fixture(autouse=True)
def _restore_modes():
    """cache_mode is process-global flag state: every test leaves it
    exactly as found (tier-1 runs unrelated suites after this one)."""
    g0 = graph_flags.get("cache_mode")
    s0 = storage_flags.get("cache_mode")
    faults.reset()
    yield
    graph_flags.set("cache_mode", g0)
    storage_flags.set("cache_mode", s0)
    faults.reset()


def _mini(parts=2, v=50, e=200, seed=5):
    import numpy as np
    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    conn = cluster.connect()
    conn.must(f"CREATE SPACE cz(partition_num={parts})")
    conn.must("USE cz")
    conn.must("CREATE TAG person(age int)")
    conn.must("CREATE EDGE knows(w int)")
    conn.must("CREATE EDGE rated(score double)")
    conn.must("INSERT VERTEX person(age) VALUES " + ", ".join(
        f"{i}:({i % 70})" for i in range(v)))
    rng = np.random.default_rng(seed)
    srcs = rng.integers(0, v, e)
    dsts = rng.integers(0, v, e)
    for i in range(0, e, 200):
        conn.must("INSERT EDGE knows(w) VALUES " + ", ".join(
            f"{int(s)} -> {int(d)}@{j}:({int((s + d) % 50)})"
            for j, (s, d) in enumerate(zip(srcs[i:i + 200],
                                           dsts[i:i + 200]), start=i)))
    conn.must("INSERT EDGE rated(score) VALUES 1 -> 2:(1.5)")
    sid = cluster.meta.get_space("cz").value().space_id
    return cluster, conn, tpu, sid


@pytest.fixture()
def mini():
    return _mini()


def _cpu_rows(conn, tpu, q):
    tpu.enabled = False
    try:
        return sorted(map(repr, conn.must(q).rows))
    finally:
        tpu.enabled = True


# ---------------------------------------------------------------------------
# CacheRung unit behavior
# ---------------------------------------------------------------------------

def test_rung_lru_and_counters():
    r = CacheRung("t", capacity=2)
    assert r.get("a") is None and r.misses == 1
    r.put("a", 1)
    r.put("b", 2)
    assert r.get("a") == 1                 # a is now most-recent
    r.put("c", 3)                          # evicts b (LRU)
    assert r.get("b") is None
    assert r.get("a") == 1 and r.get("c") == 3
    assert r.evictions == 1
    assert r.invalidate_where(lambda k: k == "a") == 1
    assert r.get("a") is None
    st = r.stats()
    assert st["invalidations"] == 1 and st["entries"] == 1


def test_rung_byte_cap_evicts_and_rejects_oversize():
    r = CacheRung("t", capacity=10, weigher=len, byte_cap=10)
    r.put("a", b"xxxx")
    r.put("b", b"xxxx")
    r.put("c", b"xxxx")                    # 12 bytes > 10: a evicts
    assert r.get("a") is None and r.get("b") == b"xxxx"
    r.put("huge", b"x" * 100)              # larger than the whole cap
    assert r.get("huge") is None           # rejected, rung untouched
    assert r.stats()["bytes"] <= 10


# ---------------------------------------------------------------------------
# rung 1: graphd plan cache
# ---------------------------------------------------------------------------

def test_plan_cache_hits_and_profile_shares_entry(mini):
    cluster, conn, tpu, sid = mini
    pc = cluster.service.engine.plan_cache
    q = "GO FROM 1 OVER knows YIELD knows._dst"
    conn.must(q)
    h0, m0 = pc.stats()["hits"], pc.stats()["misses"]
    r = conn.must(q)                       # same text -> plan hit
    assert pc.stats()["hits"] == h0 + 1
    # PROFILE-prefix-aware key (split_profile_prefix): the profiled
    # twin rides the SAME entry — and still returns its span tree
    rp = conn.must("PROFILE " + q)
    assert pc.stats()["hits"] == h0 + 2
    assert pc.stats()["misses"] == m0
    assert sorted(rp.rows) == sorted(r.rows)
    assert rp.trace_spans                  # PROFILE semantics intact


def test_plan_cache_off_mode_and_parse_errors(mini):
    cluster, conn, tpu, sid = mini
    pc = cluster.service.engine.plan_cache
    graph_flags.set("cache_mode", "off")
    q = "GO FROM 2 OVER knows YIELD knows._dst"
    conn.must(q)
    s0 = pc.stats()["stores"]
    conn.must(q)
    assert pc.stats()["stores"] == s0      # off: rung never touched
    # parse errors are never cached and keep their exact message
    for _ in range(2):
        r = conn.execute("GO FRM 1 OVER knows")
        assert not r.ok() and "SyntaxError" in (r.error_msg or "")


# ---------------------------------------------------------------------------
# rung 2: device result cache — hits, staleness by construction
# ---------------------------------------------------------------------------

def test_result_cache_hit_counts_and_identity(mini):
    cluster, conn, tpu, sid = mini
    graph_flags.set("cache_mode", "full")
    q = "GO 2 STEPS FROM 1 OVER knows YIELD knows._dst, knows.w"
    r1 = conn.must(q)
    h0 = tpu.result_cache.stats()["hits"]
    g0 = tpu.stats["go_served"]
    r2 = conn.must(q)
    assert tpu.result_cache.stats()["hits"] == h0 + 1
    assert tpu.stats["go_served"] == g0    # hit never re-serves
    assert r2.rows == r1.rows              # bit-identical
    assert sorted(map(repr, r2.rows)) == _cpu_rows(conn, tpu, q)


def test_write_between_identical_queries_reflects_write(mini):
    """Satellite: the staleness hazard is closed by construction — a
    committed write moves the freshness token, so the second identical
    statement misses and re-serves against the post-write snapshot."""
    cluster, conn, tpu, sid = mini
    graph_flags.set("cache_mode", "full")
    q = "GO FROM 1 OVER knows YIELD knows._dst"
    conn.must(q)
    before = conn.must(q).rows             # cached
    conn.must("INSERT EDGE knows(w) VALUES 1 -> 4999:(7)")
    after = conn.must(q).rows
    assert (4999,) in after and (4999,) not in before
    assert sorted(map(repr, after)) == _cpu_rows(conn, tpu, q)


def test_store_rechecks_token_mid_round(mini):
    """A delta apply landing MID-SERVE must not publish the pre-write
    rows under the post-write key: _result_cache_put re-checks the
    provider token at store time (the dispatcher's snapshot-version
    redo check re-serves the query itself; this guards the cache)."""
    from nebula_tpu.common.status import StatusOr
    from nebula_tpu.graph.interim import InterimResult
    cluster, conn, tpu, sid = mini
    graph_flags.set("cache_mode", "full")
    q = "GO FROM 3 OVER knows YIELD knows._dst"
    r = StatusOr.of(InterimResult(["knows._dst"],
                                  list(conn.must(q).rows)))
    # forge a key whose token predates a write that lands "mid-round"
    stale_token = tpu._provider.version(sid)
    conn.must("INSERT EDGE knows(w) VALUES 3 -> 4888:(1)")
    ck = ("go", sid, 1, stale_token, tpu._catalog_version(),
          (1,), (3,), (), None, (), False)
    s0 = tpu.result_cache.stats()["stores"]
    tpu._result_cache_put(ck, r)           # token moved: must refuse
    assert tpu.result_cache.stats()["stores"] == s0
    # and with the CURRENT token it stores fine
    ck_now = ck[:3] + (tpu._provider.version(sid),) + ck[4:]
    tpu._result_cache_put(ck_now, r)
    assert tpu.result_cache.stats()["stores"] == s0 + 1


def test_poisoned_snapshot_purges_cache_entries(mini):
    """Satellite: a failed delta apply poisons the snapshot AND purges
    the space's cached results (counted as invalidations); the query
    itself serves correctly on the CPU pipe."""
    cluster, conn, tpu, sid = mini
    graph_flags.set("cache_mode", "full")
    q = "GO FROM 1 OVER knows YIELD knows._dst, knows.w"
    conn.must(q)
    conn.must(q)                           # entry cached
    assert len(tpu.result_cache) > 0
    faults.set_plan("csr.delta_apply:n=1")
    conn.must("INSERT EDGE knows(w) VALUES 1 -> 2:(9)")
    p0 = tpu.stats["snapshot_poisoned"]
    i0 = tpu.result_cache.stats()["invalidations"]
    r = conn.must(q)                       # apply fires -> poison
    faults.clear()
    assert tpu.stats["snapshot_poisoned"] == p0 + 1
    assert tpu.result_cache.stats()["invalidations"] > i0
    assert sorted(map(repr, r.rows)) == _cpu_rows(conn, tpu, q)


# ---------------------------------------------------------------------------
# filter-plan rung: compiled WHERE plans survive across windows
# ---------------------------------------------------------------------------

def test_filter_plan_reused_across_queries(mini):
    cluster, conn, tpu, sid = mini
    tpu.sparse_edge_budget = 0             # dense: _plan_filter path
    q = ("GO 2 STEPS FROM 1 OVER knows WHERE knows.w > 10 "
         "YIELD knows._dst, knows.w")
    conn.must(q)
    h0 = tpu.filter_plan_counters["hits"]
    # a DIFFERENT statement with the same WHERE shape (other roots)
    # reuses the compiled plan — per-snapshot, not per-window
    r = conn.must("GO 2 STEPS FROM 2 OVER knows WHERE knows.w > 10 "
                  "YIELD knows._dst, knows.w")
    assert tpu.filter_plan_counters["hits"] > h0
    assert sorted(map(repr, r.rows)) == _cpu_rows(
        conn, tpu, "GO 2 STEPS FROM 2 OVER knows WHERE knows.w > 10 "
                   "YIELD knows._dst, knows.w")
    # a write bumps write_version: the old plan is version-orphaned
    # and the next compile records the invalidation
    conn.must("INSERT EDGE knows(w) VALUES 1 -> 2:(3)")
    i0 = tpu.filter_plan_counters["invalidations"]
    conn.must(q)
    assert tpu.filter_plan_counters["invalidations"] >= i0


# ---------------------------------------------------------------------------
# negative rung: structural declines cached, counters still count
# ---------------------------------------------------------------------------

def test_negative_cache_agg_decline(mini):
    cluster, conn, tpu, sid = mini
    graph_flags.set("cache_mode", "full")
    q = ("GO FROM 1 OVER rated YIELD rated.score AS s "
         "| YIELD SUM($-.s) AS total")
    d0 = tpu.stats["agg_declined"]
    r1 = conn.must(q)                      # double prop: declines
    h0 = tpu.negative_cache.stats()["hits"]
    r2 = conn.must(q)                      # verdict cached...
    assert tpu.negative_cache.stats()["hits"] > h0
    assert tpu.stats["agg_declined"] == d0 + 2   # ...still counted
    assert tpu.agg_decline_reasons.get("non_int_prop", 0) >= 2
    assert r1.rows == r2.rows              # CPU pipe serves both


# ---------------------------------------------------------------------------
# rung 3: in-window dedupe
# ---------------------------------------------------------------------------

def test_in_window_dedupe_collapses_and_fans_out(mini):
    cluster, conn, tpu, sid = mini
    graph_flags.set("cache_mode", "full")
    q = "GO 2 STEPS FROM 1 OVER knows YIELD knows._dst"
    ref = _cpu_rows(conn, tpu, q)
    orig = tpu._serve_batch

    def paced(batch, ex):                  # let arrivals pile up
        time.sleep(0.05)
        orig(batch, ex)

    rows, errs = [], []

    def worker():
        try:
            c = cluster.connect()
            c.must("USE cz")
            rows.append(sorted(map(repr, c.must(q).rows)))
        except Exception as ex:  # noqa: BLE001 — recorded, fails test
            errs.append(repr(ex))

    tpu._serve_batch = paced
    try:
        for _ in range(5):                 # window formation is a
            d0 = tpu.stats["dedup_collapsed"]   # scheduling fact:
            rows.clear()                        # retry a few times
            tpu.result_cache.clear()       # misses must reach the
            threads = [threading.Thread(target=worker)  # dispatcher
                       for _ in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            if tpu.stats["dedup_collapsed"] > d0:
                break
    finally:
        tpu._serve_batch = orig
    assert not errs, errs[:2]
    assert tpu.stats["dedup_collapsed"] > 0
    assert rows and all(r == ref for r in rows)


def test_dedupe_off_in_plan_mode(mini):
    cluster, conn, tpu, sid = mini
    graph_flags.set("cache_mode", "plan")
    # plan mode never computes a dedupe identity: requests keep their
    # own lanes (the pre-cache dispatcher semantics, bit-identical)
    q = "GO FROM 5 OVER knows YIELD knows._dst"
    r = conn.must(q)
    assert tpu.stats["dedup_collapsed"] == 0
    assert sorted(map(repr, r.rows)) == _cpu_rows(conn, tpu, q)


# ---------------------------------------------------------------------------
# rung 4: storaged bound-stats / scan caches
# ---------------------------------------------------------------------------

def test_storaged_stats_cache_hit_and_write_invalidate(mini):
    from nebula_tpu.storage.types import StatDef
    cluster, conn, tpu, sid = mini
    storage_flags.set("cache_mode", "full")
    etype = cluster.sm.edge_type(sid, "knows")
    defs = [StatDef("edge", etype, "w", 1), StatDef("edge", etype, "", 2)]
    s1 = cluster.client.bound_stats(sid, [1, 2, 3], [etype], defs)
    h0 = cluster.storage.stats_cache.stats()["hits"]
    s2 = cluster.client.bound_stats(sid, [1, 2, 3], [etype], defs)
    assert cluster.storage.stats_cache.stats()["hits"] > h0
    assert s1.sums == s2.sums and s1.counts == s2.counts
    # a committed write moves the engine version: the key misses and
    # the fresh scan sees the new row
    conn.must("INSERT EDGE knows(w) VALUES 2 -> 3:(41)")
    s3 = cluster.client.bound_stats(sid, [1, 2, 3], [etype], defs)
    assert s3.counts[1] == s2.counts[1] + 1
    assert s3.sums[0] == s2.sums[0] + 41


def test_storaged_scan_cache_versioned(mini):
    cluster, conn, tpu, sid = mini
    storage_flags.set("cache_mode", "full")
    part = sorted(cluster.store.parts(sid))[0]
    r1 = cluster.storage.scan_part_cols(sid, part, 2)
    h0 = cluster.storage.scan_cache.stats()["hits"]
    r2 = cluster.storage.scan_part_cols(sid, part, 2)
    assert cluster.storage.scan_cache.stats()["hits"] == h0 + 1
    assert (r2.keys_blob, r2.vals_blob) == (r1.keys_blob, r1.vals_blob)
    conn.must("INSERT EDGE knows(w) VALUES 7 -> 8:(1)")
    m0 = cluster.storage.scan_cache.stats()["misses"]
    cluster.storage.scan_part_cols(sid, part, 2)
    assert cluster.storage.scan_cache.stats()["misses"] == m0 + 1


# ---------------------------------------------------------------------------
# bisection: cache_mode=off is bit-identical to cached serves
# ---------------------------------------------------------------------------

def test_off_mode_bit_identical_to_full(mini):
    cluster, conn, tpu, sid = mini
    queries = [
        "GO 2 STEPS FROM 1 OVER knows YIELD knows._dst, knows.w",
        "GO FROM 1, 2 OVER knows WHERE knows.w > 5 YIELD knows._dst",
        "GO 2 STEPS FROM 2 OVER knows YIELD knows.w AS w "
        "| YIELD COUNT(*) AS n, SUM($-.w) AS s",
    ]
    graph_flags.set("cache_mode", "off")
    off = [conn.must(q).rows for q in queries]
    graph_flags.set("cache_mode", "full")
    first = [conn.must(q).rows for q in queries]   # populate
    cached = [conn.must(q).rows for q in queries]  # serve from cache
    assert off == first == cached
