"""Tier-1-safe cache smoke: `bench.py --cache-smoke` in a SUBPROCESS
on XLA:CPU (no accelerator, no native engine — same isolation pattern
as the chaos/mesh smokes). The tier asserts the whole cache ladder on
one small cluster: repeated statements HIT the plan + result +
storaged rungs, a write between two identical statements INVALIDATES
(the second result reflects the write and matches the CPU pipe),
cache_mode=off is BIT-IDENTICAL to cached serves, and identical
in-window requests DEDUPE to one lane with identical fan-out
(docs/manual/11-caching.md)."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def cache_smoke(tmp_path_factory):
    out = tmp_path_factory.mktemp("cache") / "CACHE_smoke.json"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_CACHE_OUT"] = str(out)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--cache-smoke"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, (proc.stdout[-2000:],
                                  proc.stderr[-2000:])
    with open(out) as f:
        return json.load(f)


def test_cache_smoke_hits_occur(cache_smoke):
    c = cache_smoke["checks"]
    assert c["hits_occurred"]
    assert c["result_hits"] >= 3
    assert c["plan_hits"] > 0
    assert c["storaged_hits_occurred"]


def test_cache_smoke_invalidation_fires_on_write(cache_smoke):
    assert cache_smoke["checks"]["write_invalidates"]


def test_cache_smoke_off_mode_bit_identical(cache_smoke):
    c = cache_smoke["checks"]
    assert c["off_deterministic"]
    assert c["bit_identical_vs_off"]
    assert c["stats_cache_identical"]


def test_cache_smoke_dedupe_collapses_with_identical_fanout(cache_smoke):
    c = cache_smoke["checks"]
    assert c["dedup_occurred"] and c["dedup_collapsed"] > 0
    assert c["dedup_fanout_identical"]


def test_cache_smoke_overall_ok(cache_smoke):
    assert cache_smoke["ok"] is True
