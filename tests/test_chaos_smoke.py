"""Tier-1-safe chaos smoke: `bench.py --chaos --trim` in a SUBPROCESS
on XLA:CPU with a seeded fault plan — the 8-session workload under
injected kernel/mesh/encode faults must return CPU-pipe-identical
results with zero client-visible errors, trip the breaker, and recover
to the device path through half-open probes once faults stop
(docs/manual/9-robustness.md). The subprocess keeps the parent's JAX
backend state out of the picture, exactly like the mesh smoke tier."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def chaos_smoke(tmp_path_factory):
    out = tmp_path_factory.mktemp("chaos") / "CHAOS_smoke.json"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_CHAOS_SEED"] = "7"           # deterministic fault plan
    env["BENCH_CHAOS_OUT"] = str(out)
    # arm the lock-order witness from import time so module-level locks
    # are wrapped too (common/lockwitness.py); the run fails on a lock
    # cycle or a sleep under a witnessed lock, and the report rides the
    # output JSON asserted below
    env["NEBULA_TPU_LOCK_WITNESS"] = "1"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--chaos", "--trim"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, (proc.stdout[-2000:],
                                  proc.stderr[-2000:])
    with open(out) as f:
        return json.load(f)


def test_chaos_zero_client_errors_and_identity(chaos_smoke):
    assert chaos_smoke["client_errors"] == []
    assert chaos_smoke["mismatches"] == []


def test_chaos_faults_actually_landed(chaos_smoke):
    fired = chaos_smoke["faults_injected"]
    assert sum(fired.values()) > 0
    assert fired.get("kernel.launch", 0) > 0


def test_chaos_ladder_tripped_and_recovered(chaos_smoke):
    assert chaos_smoke["breaker_trips"] > 0
    assert chaos_smoke["recovered"] is True
    rb = chaos_smoke["robustness"]
    assert rb["breaker_recoveries"] > 0
    assert rb["degraded_serves"] > 0
    assert all(s == "closed" for s in rb["breaker_state"].values())


def test_chaos_lock_witness_green(chaos_smoke):
    """The lock-order witness rode the whole chaos run (armed from
    import time via NEBULA_TPU_LOCK_WITNESS): the cross-thread lock
    acquisition graph over the failure/degradation paths must be
    acyclic and no thread may have slept under a witnessed lock
    (common/lockwitness.py; docs/manual/15-static-analysis.md)."""
    lw = chaos_smoke["lock_witness"]
    assert lw["installed"] is True
    # real coverage, not a vacuous pass: dozens of wrapped serve-path
    # locks and thousands of recorded acquisitions
    assert lw["locks_wrapped"] >= 20
    assert lw["acquisitions"] >= 1000
    assert lw["edges"] > 0          # multi-lock holds were observed
    assert lw["cycle"] is None
    assert lw["blocking"] == []
    assert lw["clean"] is True
