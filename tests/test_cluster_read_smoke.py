"""Tier-1-safe follower-read smoke: `bench.py --cluster --trim` in a
SUBPROCESS on XLA:CPU with bounded-staleness follower reads ARMED
(BENCH_CLUSTER_READS_ONLY stops the tier after the armed phase —
failover/balance ride tests/test_cluster_smoke.py). The run must show
ZERO client errors, follower-SERVED parts > 0 (the rotation actually
took load off the leaders through the raft read fence), every served
staleness within the bound (follower_read_max_ms + the shard-freshness
slack), and TPU-vs-CPU byte identity with mixed leader/follower
partials (docs/manual/12-replication.md "Follower reads")."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BOUND_MS = 150


@pytest.fixture(scope="module")
def reads_smoke(tmp_path_factory):
    out = tmp_path_factory.mktemp("creads") / "CLUSTER_reads.json"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_CLUSTER_SEED"] = "23"
    env["BENCH_CLUSTER_OUT"] = str(out)
    env["BENCH_CLUSTER_READS_ONLY"] = "1"
    env["BENCH_FOLLOWER_READ_MS"] = str(BOUND_MS)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--cluster", "--trim"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, (proc.stdout[-2000:],
                                  proc.stderr[-2000:])
    with open(out) as f:
        return json.load(f)


def test_reads_zero_client_errors(reads_smoke):
    assert reads_smoke["client_error_count"] == 0
    assert reads_smoke["client_errors"] == []


def test_reads_followers_actually_served(reads_smoke):
    fr = reads_smoke["follower_reads"]
    # storaged-side proof: parts GRANTED by the fence and served from
    # the local device shard in follower mode
    assert fr["follower_parts_served"] > 0
    assert fr["fence_grants"] > 0
    # client-side proof: the gather saw follower-mode partials
    assert fr["client"]["follower_parts"] > 0
    assert fr["client"]["parts_served"] > 0


def test_reads_staleness_bounded(reads_smoke):
    fr = reads_smoke["follower_reads"]
    assert fr["bound_ms"] == BOUND_MS
    assert fr["staleness_bounded"] is True
    assert fr["max_served_staleness_ms"] <= \
        fr["bound_ms"] + fr["shard_slack_ms"]


def test_reads_identity_with_mixed_partials(reads_smoke):
    fr = reads_smoke["follower_reads"]
    assert fr["identity"] is True
    assert fr["device_served"] is True
    # both routing modes carried traffic
    for ph in ("baseline", "follower_reads"):
        assert reads_smoke["phases"][ph]["n"] > 0
