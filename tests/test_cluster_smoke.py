"""Tier-1-safe replicated-cluster smoke: `bench.py --cluster --trim`
in a SUBPROCESS on XLA:CPU — boots metad + 3 raft-replicated storaged
(replica_factor=3 over the TCP transport) + a TPU-engine graphd, kills
the storaged leading the most partitions mid-soak, and completes a
BALANCE DATA onto a replacement host under live traffic. The run must
show ZERO client errors, TPU-vs-CPU byte identity after both the
failover and the rebalance, and every persisted balance task at
SUCCEEDED (docs/manual/12-replication.md). The subprocess keeps the
parent's JAX backend state out of the picture, like the chaos and mesh
smoke tiers."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def cluster_smoke(tmp_path_factory):
    out = tmp_path_factory.mktemp("cluster") / "CLUSTER_smoke.json"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_CLUSTER_SEED"] = "17"
    env["BENCH_CLUSTER_OUT"] = str(out)
    # arm the lock-order witness across elections/failover/rebalance —
    # the heaviest cross-thread lock traffic in the tree; the report
    # rides the output JSON asserted below (common/lockwitness.py)
    env["NEBULA_TPU_LOCK_WITNESS"] = "1"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--cluster", "--trim"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, (proc.stdout[-2000:],
                                  proc.stderr[-2000:])
    with open(out) as f:
        return json.load(f)


def test_cluster_zero_client_errors(cluster_smoke):
    assert cluster_smoke["client_error_count"] == 0
    assert cluster_smoke["client_errors"] == []


def test_cluster_identity_after_failover_and_balance(cluster_smoke):
    assert cluster_smoke["identity"]["after_failover"] is True
    assert cluster_smoke["identity"]["after_balance"] is True
    # the device path itself resumed against the NEW leaders — the
    # freshness token followed the election, not a deposed replica
    assert cluster_smoke["device"]["post_failover_served"] is True


def test_cluster_balance_completed_under_load(cluster_smoke):
    bal = cluster_smoke["balance"]
    assert bal["all_succeeded"] is True
    assert bal["tasks"].get("SUCCEEDED", 0) > 0
    assert bal["dead_host_evacuated"] is True
    assert bal["fully_replicated"] is True
    # every phase actually carried traffic, and none starved queries
    for ph, st in cluster_smoke["phases"].items():
        assert st["n"] > 0, (ph, st)
        assert st["p99_ms"] < 15000, (ph, st)


def test_cluster_lock_witness_green(cluster_smoke):
    """Witnessed lock order across elections, leader failover and
    online rebalance — the heaviest cross-thread lock traffic in the
    tree (raft part locks x host locks x wal locks x engine locks) —
    must stay acyclic with no sleep observed under a held lock
    (common/lockwitness.py; docs/manual/15-static-analysis.md)."""
    lw = cluster_smoke["lock_witness"]
    assert lw["installed"] is True
    assert lw["locks_wrapped"] >= 50
    assert lw["acquisitions"] >= 1000
    assert lw["edges"] > 0
    assert lw["cycle"] is None
    assert lw["blocking"] == []
    assert lw["clean"] is True
