"""Consistency observatory (ISSUE 15; common/consistency.py,
docs/manual/10-observability.md "Consistency observatory"): the part
content digests, the leader-side replica digest exchange, shadow-read
verification and the device-snapshot audit."""
import threading
import time

import pytest

from nebula_tpu.common import consistency as cons
from nebula_tpu.common import keys as keyutils
from nebula_tpu.common.faults import faults
from nebula_tpu.common.flags import graph_flags, storage_flags
from nebula_tpu.common.flight import recorder as flight
from nebula_tpu.common.stats import stats as global_stats
from nebula_tpu.kvstore.memengine import MemEngine
from nebula_tpu.kvstore.part import Part


def vkey(part, vid, ver=5):
    return keyutils.vertex_key(part, vid, 7, version=ver)


@pytest.fixture(autouse=True)
def _consistency_hygiene():
    """Every test here starts armed with shadow off and leaves the
    process flags the way it found them."""
    graph_flags.set("consistency_enabled", True)
    storage_flags.set("consistency_enabled", True)
    graph_flags.set("shadow_read_rate", 0.0)
    faults.clear()
    yield
    graph_flags.set("consistency_enabled", True)
    storage_flags.set("consistency_enabled", True)
    graph_flags.set("shadow_read_rate", 0.0)
    faults.clear()


# ---------------------------------------------------------------------------
# the hashing authority
# ---------------------------------------------------------------------------

def test_fold_is_order_independent_and_duplicate_safe():
    h1 = cons.kv_hash(b"a", b"1")
    h2 = cons.kv_hash(b"b", b"2")
    assert cons.fold_add(cons.fold_add(0, h1), h2) == \
        cons.fold_add(cons.fold_add(0, h2), h1)
    # duplicates must NOT cancel (the XOR failure mode)
    two = cons.fold_add(cons.fold_add(0, h1), h1)
    assert two != 0
    # add/sub roundtrip
    assert cons.fold_sub(two, h1) == cons.fold_add(0, h1)
    # row digests: same multiset in any order, different multiset not
    d1 = cons.digest_rows([(1, 2), (3, 4), (1, 2)])
    d2 = cons.digest_rows([(3, 4), (1, 2), (1, 2)])
    d3 = cons.digest_rows([(1, 2), (3, 4)])
    assert d1 == d2
    assert d1 != d3


def test_kv_hash_length_separation():
    # (k="ab", v="c") must never alias (k="a", v="bc")
    assert cons.kv_hash(b"ab", b"c") != cons.kv_hash(b"a", b"bc")


# ---------------------------------------------------------------------------
# part digests: incremental == full rebuild, under every op class
# ---------------------------------------------------------------------------

def test_incremental_digest_matches_full_rebuild():
    eng = MemEngine()
    p = Part(1, 1, eng)
    p.async_put(vkey(1, 1), b"v1")
    p.async_multi_put([(vkey(1, 2), b"v2"), (vkey(1, 3), b"v3"),
                       (vkey(1, 2), b"v2b")])   # in-batch overwrite
    p.async_put(vkey(1, 1), b"v1b")              # cross-batch overwrite
    p.async_remove(vkey(1, 3))
    p.async_remove_range(vkey(1, 2), vkey(1, 2) + b"\xff")
    scrub = p.digest_scrub()
    assert scrub["ok"] is True, scrub
    anc = p.digest_anchor()
    assert anc is not None and anc[1] == p.last_committed_log_id
    # the scan-side digest via the SAME authority agrees
    scanned = cons.digest_items(
        (k, v) for k, v in eng.prefix(keyutils.part_prefix(1))
        if cons.is_digestable_key(k))
    assert scanned == anc[2]


def test_digest_excludes_system_keys():
    eng = MemEngine()
    p = Part(1, 1, eng)
    p.async_put(vkey(1, 9), b"x")
    anc1 = p.digest_anchor()
    # another commit (the marker rewrites) with no data change beyond
    # one put must change the digest by exactly that put
    p.async_put(vkey(1, 9), b"x")      # same key+value re-put
    anc2 = p.digest_anchor()
    assert anc1[2] == anc2[2]          # marker churn is invisible


def test_disarm_invalidates_and_rearm_rebuilds():
    eng = MemEngine()
    p = Part(1, 1, eng)
    p.async_put(vkey(1, 1), b"a")
    assert p.digest_anchor() is not None
    graph_flags.set("consistency_enabled", False)
    storage_flags.set("consistency_enabled", False)
    assert p.digest_anchor() is None            # disarmed: no claim
    p.async_put(vkey(1, 2), b"b")               # writes don't track
    assert not p.digest.valid
    graph_flags.set("consistency_enabled", True)
    storage_flags.set("consistency_enabled", True)
    anc = p.digest_anchor()                     # lazy rebuild
    assert anc is not None
    assert p.digest_scrub()["ok"] is True


def test_disarm_mid_snapshot_install_invalidates():
    """Review fix: a disarm window DURING a snapshot install must not
    let the incomplete digest be anchored as valid at `finished` (or
    after a re-arm) — chunks applied while disarmed were never
    folded."""
    eng = MemEngine()
    p = Part(1, 1, eng)
    p.commit_snapshot([(vkey(1, 1), b"a")], 10, 2, False)   # armed
    graph_flags.set("consistency_enabled", False)
    storage_flags.set("consistency_enabled", False)
    p.commit_snapshot([(vkey(1, 2), b"b")], 10, 2, False)   # disarmed
    graph_flags.set("consistency_enabled", True)
    storage_flags.set("consistency_enabled", True)
    p.commit_snapshot([(vkey(1, 3), b"c")], 10, 2, True)    # re-armed
    # the incomplete fold was invalidated, not anchored; the next
    # probe rebuilds from the full engine and scrubs green
    anc = p.digest_anchor()
    assert anc is not None and anc[1] == 10
    assert p.digest_scrub()["ok"] is True


def test_ingest_invalidates_digest():
    eng = MemEngine()
    p = Part(1, 1, eng)
    p.async_put(vkey(1, 1), b"a")
    p.ingest([(vkey(1, 50), b"bulk")])
    assert not p.digest.valid
    anc = p.digest_anchor()                     # rebuild covers ingest
    assert anc is not None
    assert p.digest_scrub()["ok"] is True


# ---------------------------------------------------------------------------
# replicated digest exchange (raft fixture)
# ---------------------------------------------------------------------------

def _put(store, i, val=b"x"):
    st = store.async_multi_put(1, 1, [(vkey(1, 100 + i), val)])
    assert st.ok(), st


def _wait(pred, timeout=8.0, interval=0.03):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def _all_anchors_equal(rs):
    ancs = []
    for a in rs.addrs:
        h = rs.hooks[a][(1, 1)]
        anc = h.part.digest_anchor()
        if anc is None:
            return None
        ancs.append(anc)
    ids = {a[1] for a in ancs}
    digs = {a[2] for a in ancs}
    return ancs if len(ids) == 1 and len(digs) == 1 else None


def test_digest_equal_across_leader_change_and_snapshot_install(tmp_path):
    from nebula_tpu.kvstore.raft_store import ReplicatedStores
    # tiny WAL segments so compact_wal can seal + drop a prefix and
    # force the snapshot path (the bench --crash sizing idiom)
    rs = ReplicatedStores(3, str(tmp_path), heartbeat_interval=0.05,
                          election_timeout=0.2, wal_file_size=256)
    rs.add_part(1, 1)
    leader = rs.leader_of(1, 1)
    for i in range(20):
        _put(rs.stores[leader], i)
    # leader-side verdicts converge green
    raft = rs.hooks[leader][(1, 1)].raft
    assert _wait(lambda: all(
        m["digest_ok"] is True
        for m in raft.status_with_replicas()["replicas"]))
    assert _wait(lambda: _all_anchors_equal(rs) is not None)

    # ---- leader change: isolate the leader, survivors elect + write
    rs.net.isolate(leader)
    others = [a for a in rs.addrs if a != leader]
    assert _wait(lambda: any(
        rs.hooks[a][(1, 1)].is_leader() for a in others), timeout=10)
    leader2 = next(a for a in others if rs.hooks[a][(1, 1)].is_leader())
    for i in range(20, 35):
        _put(rs.stores[leader2], i)
    raft2 = rs.hooks[leader2][(1, 1)].raft

    # ---- heal; the deposed leader catches up via append replay
    rs.net.heal(leader)
    assert _wait(lambda: all(
        rs.hooks[a][(1, 1)].raft.committed_id == raft2.committed_id
        for a in rs.addrs), timeout=10)
    assert _wait(lambda: _all_anchors_equal(rs) is not None, timeout=10)
    assert _wait(lambda: all(
        m["digest_ok"] is True
        for m in raft2.status_with_replicas()["replicas"]), timeout=10)

    # ---- snapshot install: isolate one follower, compact the
    # survivors' WALs so its gap is unservable, write, heal — it must
    # re-sync by snapshot and STILL digest-verify
    victim = next(a for a in rs.addrs if a != leader2)
    rs.net.isolate(victim)
    for i in range(35, 90):
        _put(rs.stores[leader2], i, val=b"x" * 64)
    for a in rs.addrs:
        if a != victim:
            rs.hooks[a][(1, 1)].raft.compact_wal(0)
    assert rs.hooks[leader2][(1, 1)].raft.wal.first_log_id > 1
    rs.net.heal(victim)
    assert _wait(lambda: rs.hooks[victim][(1, 1)].raft.committed_id
                 == raft2.committed_id, timeout=15)
    assert _wait(lambda: _all_anchors_equal(rs) is not None, timeout=10)
    marks = raft2.status_with_replicas()["replicas"]
    assert _wait(lambda: all(
        m["digest_ok"] is True
        for m in raft2.status_with_replicas()["replicas"]),
        timeout=10), marks
    rs.stop()


def test_corruption_detected_and_flight_recorded(tmp_path):
    from nebula_tpu.kvstore.raft_store import ReplicatedStores
    flight.reset()
    div0 = global_stats.lifetime_total("consistency.divergence")
    rs = ReplicatedStores(3, str(tmp_path), heartbeat_interval=0.05,
                          election_timeout=0.2)
    rs.add_part(1, 1)
    leader = rs.leader_of(1, 1)
    for i in range(8):
        _put(rs.stores[leader], i)
    raft = rs.hooks[leader][(1, 1)].raft
    assert _wait(lambda: all(
        m["digest_ok"] is True
        for m in raft.status_with_replicas()["replicas"]))
    faults.set_plan("consistency.corrupt:n=1")
    try:
        for i in range(8, 24):
            _put(rs.stores[leader], i, val=b"y")
            time.sleep(0.01)
        assert faults.counts().get("consistency.corrupt") == 1
        assert _wait(lambda: raft.status_with_replicas()
                     ["digest_divergent"], timeout=6)
    finally:
        faults.clear()
    assert global_stats.lifetime_total("consistency.divergence") > div0
    flight.flush()
    bundles = [b for b in flight.bundles
               if b["trigger"] == "replica_divergence"]
    assert bundles, "replica_divergence bundle not captured"
    ev = bundles[-1]["event"]
    assert ev["part"] == 1 and ev["replica"] and \
        ev["anchor"] is not None
    # the storaged-style consistency view names it too
    st = raft.status_with_replicas()
    assert st["digest_divergent"]
    rs.stop()


def test_raft_response_digest_none_when_disarmed(tmp_path):
    from nebula_tpu.kvstore.raft_store import ReplicatedStores
    graph_flags.set("consistency_enabled", False)
    storage_flags.set("consistency_enabled", False)
    rs = ReplicatedStores(3, str(tmp_path), heartbeat_interval=0.05,
                          election_timeout=0.2)
    rs.add_part(1, 1)
    leader = rs.leader_of(1, 1)
    _put(rs.stores[leader], 1)
    time.sleep(0.3)
    raft = rs.hooks[leader][(1, 1)].raft
    st = raft.status_with_replicas()
    assert st["digest"] is None
    assert all(m["digest_ok"] is None for m in st["replicas"])
    rs.stop()


# ---------------------------------------------------------------------------
# shadow-read verification
# ---------------------------------------------------------------------------

def test_shadow_sampling_never_blocks_and_respects_bounds():
    sv = cons.ShadowVerifier()
    started = threading.Event()
    release = threading.Event()

    def slow_runner(space, text):
        started.set()
        release.wait(5)
        return []

    sv.install(slow_runner, version_fn=lambda s: 0)
    graph_flags.set("shadow_read_rate", 1.0)
    try:
        t0 = time.perf_counter()
        for i in range(cons.SHADOW_QUEUE_CAP + 40):
            sv.maybe_sample("sp", "go", f"GO {i}", [(i,)])
        elapsed = time.perf_counter() - t0
        # serve-path seam: hundreds of samples in well under a second
        # even with the worker wedged on the first one
        assert elapsed < 1.0, elapsed
        st = sv.stats()
        assert st["queue"] <= cons.SHADOW_QUEUE_CAP
        assert st["dropped"] > 0              # drop-oldest engaged
        assert st["sampled"] == cons.SHADOW_QUEUE_CAP + 40
    finally:
        release.set()
        graph_flags.set("shadow_read_rate", 0.0)


def test_shadow_budget_bounds_reexecutions():
    clock = [1000.0]
    sv = cons.ShadowVerifier(clock=lambda: clock[0])
    ran = []
    sv.install(lambda space, text: ran.append(text) or [],
               version_fn=lambda s: 0)
    graph_flags.set("shadow_read_rate", 1.0)
    graph_flags.set("shadow_read_budget", 3)
    try:
        for i in range(10):
            assert sv.maybe_sample("sp", "go", f"GO {i}", [])
        assert sv.drain(10)
        time.sleep(0.2)
        st = sv.stats()
        # within ONE budget second at most 3 re-executions ran; the
        # rest dropped (never deferred load)
        assert st["verified"] <= 3
        assert st["verified"] + st["dropped"] == 10, st
    finally:
        graph_flags.set("shadow_read_rate", 0.0)
        graph_flags.set("shadow_read_budget", 20)


def test_shadow_mismatch_counts_and_fires_flight():
    flight.reset()
    sv = cons.ShadowVerifier()
    sv.install(lambda space, text: [("WRONG",)],
               version_fn=lambda s: 0)
    graph_flags.set("shadow_read_rate", 1.0)
    try:
        assert sv.maybe_sample("spx", "go", "GO FROM 1 OVER e",
                               [("right",)], trace_id="t-123")
        assert sv.drain(10)
        assert _wait(lambda: sv.stats()["mismatches"] == 1)
        st = sv.stats()
        assert st["mismatch_by_verb"] == {"go": 1}
        assert st["mismatch_by_space"] == {"spx": 1}
        assert st["last_mismatch"]["verb"] == "go"
        evs = [e for e in flight.describe()["events"]
               if e["kind"] == "shadow_mismatch"]
        assert evs and evs[0]["trace_id"] == "t-123"
        assert global_stats.lifetime_total("shadow.mismatch.go") >= 1
    finally:
        graph_flags.set("shadow_read_rate", 0.0)


def test_shadow_pre_serve_version_pins_the_comparison():
    """Review fix: the freshness token is pinned BEFORE the rows were
    computed (the engine captures it at execute start), so a write
    landing between row computation and the sampling seam SKIPS the
    comparison instead of false-positiving."""
    ver = [0]
    sv = cons.ShadowVerifier()
    sv.install(lambda space, text: [("rows", "at", "v1")],
               version_fn=lambda s: ver[0])
    graph_flags.set("shadow_read_rate", 1.0)
    try:
        pinned = sv.current_version("sp")     # before rows computed
        # ... rows computed at v0, then a concurrent write commits ...
        ver[0] = 1
        # ... and only now does the sampling seam run
        assert sv.maybe_sample("sp", "go", "GO", [("rows", "at", "v0")],
                               version=pinned)
        assert sv.drain(10)
        st = sv.stats()
        assert st["skipped_stale"] == 1 and st["mismatches"] == 0, st
    finally:
        graph_flags.set("shadow_read_rate", 0.0)


def test_drain_covers_in_flight_verification():
    """Review fix: drain() must not return while the worker is still
    verifying a popped sample — gates read stats right after."""
    sv = cons.ShadowVerifier()

    def slow_wrong(space, text):
        time.sleep(0.3)
        return [("WRONG",)]

    sv.install(slow_wrong, version_fn=lambda s: 0)
    graph_flags.set("shadow_read_rate", 1.0)
    try:
        assert sv.maybe_sample("sp", "go", "GO", [("right",)])
        assert sv.drain(10)
        # the verdict has ALREADY landed when drain returns
        assert sv.stats()["mismatches"] == 1, sv.stats()
    finally:
        graph_flags.set("shadow_read_rate", 0.0)


def test_shadow_stale_version_skips_comparison():
    ver = [0]
    sv = cons.ShadowVerifier()
    sv.install(lambda space, text: [("DIFFERENT",)],
               version_fn=lambda s: ver[0])
    graph_flags.set("shadow_read_rate", 1.0)
    try:
        assert sv.maybe_sample("sp", "go", "GO", [("orig",)])
        ver[0] = 1          # a write landed before the shadow ran
        assert sv.drain(10)
        assert _wait(lambda: sv.stats()["skipped_stale"] == 1)
        assert sv.stats()["mismatches"] == 0
    finally:
        graph_flags.set("shadow_read_rate", 0.0)


def test_shadow_end_to_end_identity_green():
    """InProcCluster + TPU engine: sampled GO/FETCH serves re-execute
    through the CPU pipe and verify; a write in between skips."""
    from nebula_tpu.cluster import InProcCluster
    from nebula_tpu.engine_tpu import TpuGraphEngine
    tpu = TpuGraphEngine()
    c = InProcCluster(tpu_engine=tpu)
    conn = c.connect()
    conn.must("CREATE SPACE shsp(partition_num=3)")
    conn.must("USE shsp")
    conn.must("CREATE TAG person(age int)")
    conn.must("CREATE EDGE knows(w int)")
    conn.must("INSERT VERTEX person(age) VALUES " + ", ".join(
        f"{i}:({i % 50})" for i in range(50)))
    conn.must("INSERT EDGE knows(w) VALUES " + ", ".join(
        f"{i} -> {(i * 7 + 1) % 50}:({i % 20})" for i in range(150)))
    sid = c.meta.get_space("shsp").value().space_id
    tpu.prewarm(sid, block=True)
    cons.shadow.reset()
    graph_flags.set("shadow_read_rate", 1.0)
    try:
        conn.must("GO 2 STEPS FROM 3 OVER knows YIELD knows._dst")
        conn.must("FETCH PROP ON person 1,2,3")
        # settle the two read samples BEFORE the write: a mutation
        # moves the freshness token and would legitimately skip them
        assert cons.shadow.drain(15)
        assert _wait(lambda: cons.shadow.stats()["verified"] >= 2)
        # a mutation statement is NEVER sampled
        conn.must("INSERT EDGE knows(w) VALUES 1 -> 3:(5)")
        assert cons.shadow.drain(15)
        st = cons.shadow.stats()
        assert st["sampled"] == 2, st
        assert st["mismatches"] == 0 and st["errors"] == 0, st
    finally:
        graph_flags.set("shadow_read_rate", 0.0)


def test_shadow_disarmed_is_one_flag_read():
    sv = cons.ShadowVerifier()
    graph_flags.set("shadow_read_rate", 0.0)
    assert not sv.maybe_sample("sp", "go", "GO", [(1,)])
    assert sv.stats()["sampled"] == 0


# ---------------------------------------------------------------------------
# device-snapshot audit
# ---------------------------------------------------------------------------

def _small_cluster():
    from nebula_tpu.cluster import InProcCluster
    from nebula_tpu.engine_tpu import TpuGraphEngine
    tpu = TpuGraphEngine()
    c = InProcCluster(tpu_engine=tpu)
    conn = c.connect()
    conn.must("CREATE SPACE audsp(partition_num=2)")
    conn.must("USE audsp")
    conn.must("CREATE TAG person(age int)")
    conn.must("CREATE EDGE knows(w int)")
    conn.must("INSERT VERTEX person(age) VALUES " + ", ".join(
        f"{i}:({i})" for i in range(20)))
    conn.must("INSERT EDGE knows(w) VALUES " + ", ".join(
        f"{i} -> {(i + 1) % 20}:({i})" for i in range(20)))
    sid = c.meta.get_space("audsp").value().space_id
    tpu.prewarm(sid, block=True)
    return c, conn, tpu, sid


def test_snapshot_audit_clean_and_lineage_mismatch():
    flight.reset()
    c, conn, tpu, sid = _small_cluster()
    conn.must("GO FROM 1 OVER knows")      # snapshot at live version
    # clean: checked with zero mismatches (retry while a background
    # repack settles)
    out = None
    for _ in range(50):
        out = tpu.audit_snapshots()
        if out["checked"]:
            break
        conn.must("GO FROM 1 OVER knows")
        time.sleep(0.05)
    assert out["checked"] >= 1 and out["mismatches"] == 0, out
    # break the recorded lineage: the engine content no longer matches
    # what the snapshot claims it was built from at the same version
    snap = tpu._snapshots[sid]
    snap.store_digest = cons.fold_add(snap.store_digest, 12345)
    out = tpu.audit_snapshots()
    assert out["mismatches"] == 1, out
    assert global_stats.lifetime_total("consistency.audit_mismatch") >= 1
    flight.flush()
    assert any(b["trigger"] == "replica_divergence"
               and b["event"]["kind"] == "snapshot_audit_mismatch"
               for b in flight.bundles)


def test_audit_registry_runs_registered_engines():
    c, conn, tpu, sid = _small_cluster()
    conn.must("GO FROM 1 OVER knows")
    assert cons.run_audits() >= 1
    assert tpu.audit_state()["last"] is not None


def test_audit_skips_when_version_moved():
    c, conn, tpu, sid = _small_cluster()
    conn.must("GO FROM 1 OVER knows")
    # a write the snapshot hasn't absorbed: version differs -> skip
    conn.must("INSERT EDGE knows(w) VALUES 1 -> 5:(9)")
    out = tpu.audit_snapshots()
    assert out["mismatches"] == 0


# ---------------------------------------------------------------------------
# SHOW CONSISTENCY + /consistency surfaces
# ---------------------------------------------------------------------------

def test_show_consistency_local_rows_and_soft_keyword():
    from nebula_tpu.cluster import InProcCluster
    c = InProcCluster()
    conn = c.connect()
    conn.must("CREATE SPACE scs(partition_num=2)")
    conn.must("USE scs")
    conn.must("CREATE TAG t(a int)")
    conn.must("INSERT VERTEX t(a) VALUES 1:(1), 2:(2)")
    r = conn.must("SHOW CONSISTENCY")
    assert r.columns[0] == "Host"
    assert len(r.rows) == 2
    assert all(row[6] for row in r.rows)      # digest hex present
    # "consistency" stays a legal identifier
    conn.must("CREATE TAG consistency(x int)")
    conn.must("INSERT VERTEX consistency(x) VALUES 5:(1)")


def test_store_rows_empty_when_disarmed():
    from nebula_tpu.cluster import InProcCluster
    c = InProcCluster()
    conn = c.connect()
    conn.must("CREATE SPACE scd(partition_num=2)")
    graph_flags.set("consistency_enabled", False)
    storage_flags.set("consistency_enabled", False)
    try:
        assert cons.store_rows(c.store) == []
        sid = c.meta.get_space("scd").value().space_id
        assert c.store.space_digest(sid) is None
    finally:
        graph_flags.set("consistency_enabled", True)
        storage_flags.set("consistency_enabled", True)


# ---------------------------------------------------------------------------
# offline tools ride the same authority
# ---------------------------------------------------------------------------

def test_integrity_and_kv_verify_share_the_digest_authority():
    from nebula_tpu.cluster import InProcCluster
    from nebula_tpu.tools.integrity_check import run_integrity
    from nebula_tpu.tools.kv_verify import run_kv_verify
    c = InProcCluster()
    conn = c.connect()
    conn.must("CREATE SPACE itg(partition_num=2)")
    conn.must("USE itg")
    conn.must("CREATE TAG test_tag(test_prop int)")
    sid = c.meta.get_space("itg").value().space_id
    tag_id = c.sm.tag_id(sid, "test_tag")
    out = run_integrity(c.client, c.sm, sid, tag_id, "test_prop", 4, 3)
    assert out["ok"] is True
    assert out["digests_equal"] is True
    assert out["observed_digest"] == out["written_digest"]
    kv = run_kv_verify(c.client, sid, count=50, value_size=16)
    assert kv["ok"] is True and kv["digests_equal"] is True
    assert kv["written_digest"] == kv["read_digest"]


# ---------------------------------------------------------------------------
# 3-daemon e2e: the /consistency surfaces + federated SHOW CONSISTENCY
# ---------------------------------------------------------------------------

def test_consistency_observatory_3daemon(tmp_path):
    """Acceptance (ISSUE 15): the consistency observatory e2e on a
    real topology — storaged /consistency serves per-part digest
    anchors with replica verdicts converging green, graphd
    /consistency federates them next to the shadow verifier and
    snapshot-audit state, and SHOW CONSISTENCY renders the cluster
    table over the same endpoints."""
    import json as _json
    import urllib.request
    from nebula_tpu.client import GraphClient
    from nebula_tpu.daemons import (serve_graphd, serve_metad,
                                    serve_storaged)
    from nebula_tpu.engine_tpu import TpuGraphEngine

    old_hb = storage_flags.get("heartbeat_interval_secs")
    storage_flags.set("heartbeat_interval_secs", 0.2)
    metad = serve_metad(ws_port=0)
    s0 = serve_storaged(metad.addr, replicated=True,
                        data_dir=str(tmp_path / "s0"),
                        load_interval=0.1, ws_port=0)
    s1 = serve_storaged(metad.addr, replicated=True,
                        data_dir=str(tmp_path / "s1"),
                        load_interval=0.1, ws_port=0)
    tpu = TpuGraphEngine()
    graphd = serve_graphd(metad.addr, tpu_engine=tpu, ws_port=0)

    def http(port, path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}") as r:
            return _json.loads(r.read()), r.status

    try:
        gc = GraphClient(graphd.addr).connect()
        assert gc.execute("CREATE SPACE consobs(partition_num=2, "
                          "replica_factor=2)").ok()
        assert gc.execute("USE consobs").ok()
        assert gc.execute("CREATE TAG t(x int)").ok()
        assert gc.execute("CREATE EDGE e(w int)").ok()
        deadline = time.time() + 15
        while time.time() < deadline:
            r = gc.execute("INSERT VERTEX t(x) VALUES " + ", ".join(
                f"{i}:({i})" for i in range(12)))
            if r.ok():
                break
            time.sleep(0.2)
        assert r.ok(), r.error_msg
        assert gc.execute("INSERT EDGE e(w) VALUES " + ", ".join(
            f"{i} -> {(i + 1) % 12}:({i})" for i in range(12))).ok()

        # ---- storaged /consistency: digests + green replica verdicts
        def leader_verdicts():
            ok = 0
            for sd in (s0, s1):
                body, st = http(sd.ws_port, "/consistency")
                assert st == 200 and body["enabled"]
                for p in body["parts"]:
                    assert p["digest"] is None or \
                        len(p["digest"]["digest"]) == 32
                    ok += sum(1 for m in p["replicas"]
                              if m.get("digest_ok") is True)
            return ok

        assert _wait(lambda: leader_verdicts() >= 2, timeout=10)
        # deep scrub over HTTP stays green
        for sd in (s0, s1):
            body, _ = http(sd.ws_port, "/consistency?scrub=1")
            assert all(r["ok"] in (True, None) for r in body["scrub"])

        # ---- graphd /consistency: shadow + audit + federation
        body, st = http(graphd.ws_port, "/consistency?audit=1")
        assert st == 200 and body["enabled"]
        assert "shadow" in body and "audit" in body
        assert body["divergent"] == []
        assert len(body["cluster"]) == 2
        assert all(h.get("parts") for h in body["cluster"]), body

        # ---- SHOW CONSISTENCY federates the same endpoints
        r = gc.execute("SHOW CONSISTENCY")
        assert r.ok(), r.error_msg
        assert len(r.rows) >= 2, r.rows
        assert any(row[10] == "ok" for row in r.rows), r.rows
        assert not any(row[10] == "DIVERGED" for row in r.rows)
    finally:
        storage_flags.set("heartbeat_interval_secs", old_hb)
        graphd.stop()
        s0.stop()
        s1.stop()
        metad.stop()


# ---------------------------------------------------------------------------
# nebtop panel
# ---------------------------------------------------------------------------

def test_nebtop_consistency_panel_renders():
    from nebula_tpu.tools.nebtop import render_consistency
    doc = {
        "enabled": True,
        "shadow": {"rate": 0.25, "sampled": 10, "verified": 8,
                   "mismatches": 1, "skipped_stale": 1},
        "divergent": [{"host": "h1", "space": 1, "part": 2,
                       "replica": "r1"}],
        "cluster": [{"host": "h1", "addr": "s1", "parts": [
            {"space": 1, "part": 2, "role": "LEADER",
             "digest": {"anchor_id": 31},
             "digest_divergent": ["r1"],
             "replicas": [{"digest_ok": False}]}]}],
    }
    lines = render_consistency(doc)
    text = "\n".join(lines)
    assert "MISMATCH 1" in text
    assert "DIVERGED" in text
    assert render_consistency({"enabled": False}) == []
    assert render_consistency(None) == []
