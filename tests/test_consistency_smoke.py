"""Tier-1-safe consistency-observatory smoke: `bench.py --consistency
--trim` in a SUBPROCESS on XLA:CPU — the corruption drill that proves
an injected single-replica byte flip is DETECTED within the declared
window (divergence gauge + replica_divergence flight bundle naming the
part/replica/anchor), the clean phase has zero false positives,
shadow-read verification stays identity-green, and the fully disarmed
path leaves the metrics surface untouched (docs/manual/
10-observability.md, "Consistency observatory"). The subprocess keeps
the parent's JAX backend state out of the picture, exactly like the
chaos/cluster/skew smoke tiers."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def cons_smoke(tmp_path_factory):
    out = tmp_path_factory.mktemp("cons") / "CONSISTENCY_smoke.json"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_CONSISTENCY_SEED"] = "23"   # deterministic graph/draws
    env["BENCH_CONSISTENCY_OUT"] = str(out)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--consistency", "--trim"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, (proc.stdout[-2000:],
                                  proc.stderr[-2000:])
    with open(out) as f:
        return json.load(f)


def test_consistency_all_gates_green(cons_smoke):
    assert cons_smoke["ok"] is True, cons_smoke["gates"]
    assert all(cons_smoke["gates"].values()), cons_smoke["gates"]


def test_consistency_disarmed_left_no_trace(cons_smoke):
    assert cons_smoke["disarmed"]["metric_lines"] == 0


def test_consistency_shadow_identity_green(cons_smoke):
    sh = cons_smoke["shadow"]
    assert sh["sampled"] > 0 and sh["verified"] > 0, sh
    assert sh["mismatches"] == 0 and sh["errors"] == 0, sh
    # the replicated phase rode shadow too
    sh2 = cons_smoke["drill"]["shadow"]
    assert sh2["mismatches"] == 0, sh2


def test_consistency_corruption_detected_in_window(cons_smoke):
    drill = cons_smoke["drill"]
    assert drill["corrupt_fired"] == 1, drill
    assert drill["detect_s"] is not None
    assert drill["detect_s"] <= cons_smoke["detect_window_s"], drill
    # the bundle names the offending part, replica and anchor
    ev = drill["bundle_event"]
    assert ev["part"] is not None and ev["replica"], ev
    assert ev["anchor"] is not None, ev
    assert drill["divergent"], drill


def test_consistency_clean_phase_no_false_positives(cons_smoke):
    clean = cons_smoke["clean"]
    assert clean["verified_replicas"] > 0, clean
    assert clean["divergent"] == [], clean
    # audit + scrub both green on the single-host phase
    assert cons_smoke["audit"]["mismatches"] == 0
    assert all(s["ok"] for s in cons_smoke["scrub"])
