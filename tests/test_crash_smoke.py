"""Tier-1-safe crash-storm smoke: `bench.py --crash --trim` in a
SUBPROCESS on XLA:CPU — metad + TPU graphd in-process, 3 replicated
storaged as real SUBPROCESSES, a SIGKILL/restart-on-same-data-dir
cycle plus a `crashpoint.wal_applied`-forced crash exactly between WAL
append and engine apply, under ledger-journaling writers. The run must
show every ACKED write readable after recovery, zero non-retryable
client errors, TPU-vs-CPU identity green post-recovery, and >= 1
`wal_replay` flight event per recovery (docs/manual/12-replication.md,
"Crash recovery & compaction")."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def crash_smoke(tmp_path_factory):
    out = tmp_path_factory.mktemp("crash") / "CRASH_smoke.json"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_CRASH_SEED"] = "23"
    env["BENCH_CRASH_OUT"] = str(out)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--crash", "--trim"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, (proc.stdout[-2000:],
                                  proc.stderr[-2000:])
    with open(out) as f:
        return json.load(f)


def test_crash_ledger_green(crash_smoke):
    """The durability contract: every write the client was told
    SUCCEEDED is readable after the storm — zero acked-write loss —
    and no client ever saw a non-retryable error."""
    led = crash_smoke["ledger"]
    assert led["acked"] > 0
    assert led["missing"] == 0, led["missing_samples"]
    assert led["errors"] == 0, led["error_samples"]
    assert crash_smoke["readers"]["errors"] == 0, \
        crash_smoke["readers"]["error_samples"]


def test_crash_recovery_replayed_and_flight_recorded(crash_smoke):
    """Each SIGKILL/restart cycle (including the crashpoint-forced
    crash between WAL append and engine apply) replayed its WAL tail,
    captured >= 1 wal_replay flight event, and stayed under the
    compaction replay bound."""
    assert crash_smoke["cycles"] >= 2
    labels = {r["cycle"] for r in crash_smoke["recoveries"]}
    assert "crashpoint_wal_applied" in labels
    for r in crash_smoke["recoveries"]:
        assert r["replay_events"] >= 1, r
        assert r["replay_max_n"] <= crash_smoke["replay"]["bound"], r
    assert sum(r["replayed_total"]
               for r in crash_smoke["recoveries"]) > 0


def test_crash_identity_and_bounds(crash_smoke):
    assert crash_smoke["identity_post_recovery"] is True
    assert crash_smoke["device_served_post_recovery"] is True
    assert crash_smoke["wal_spans"]["max"] <= \
        crash_smoke["wal_spans"]["bound"]
    assert crash_smoke["ok"] is True
