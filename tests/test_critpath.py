"""Critical-path analyzer (ISSUE 12): self-time attribution, the
dominant path, remote-fragment host attribution, and the degenerate
trees /traces?critpath must survive (common/critpath.py)."""
from nebula_tpu.common import critpath


def _span(sid, parent, name, t0_us, dur_us, **tags):
    return {"span_id": sid, "parent_id": parent, "name": name,
            "t0_us": t0_us, "dur_us": dur_us, "tags": tags}


def _trace(spans, trace_id="t1"):
    return {"trace_id": trace_id, "spans": spans}


def test_nested_tree_attribution_and_path():
    # root(1000) -> exec(900) -> kernel(600), materialize(200)
    spans = [
        _span("r", "", "query", 0, 1000),
        _span("e", "r", "exec.go", 50, 900),
        _span("k", "e", "kernel", 100, 600),
        _span("m", "e", "materialize", 700, 200),
    ]
    a = critpath.analyze(_trace(spans))
    assert a["wall_us"] == 1000
    by_name = {(row["name"]): row for row in a["attribution"]}
    # kernel/materialize are leaves: full self time
    assert by_name["kernel"]["self_us"] == 600
    assert by_name["materialize"]["self_us"] == 200
    # exec self = 900 - (600 + 200) covered
    assert by_name["exec.go"]["self_us"] == 100
    # dominant path descends by largest child duration
    assert [p["name"] for p in a["critical_path"]] == \
        ["query", "exec.go", "kernel"]
    # explained excludes the ROOT's own self time (900/1000 here)
    assert a["explained"] == 0.9


def test_concurrent_children_not_double_subtracted():
    # two children overlap in time: coverage merges their intervals
    spans = [
        _span("r", "", "query", 0, 1000),
        _span("a", "r", "fan.a", 0, 600),
        _span("b", "r", "fan.b", 300, 600),
    ]
    a = critpath.analyze(_trace(spans))
    root_row = [x for x in a["attribution"] if x["name"] == "query"]
    # merged coverage [0,900) -> root self = 100
    assert root_row and root_row[0]["self_us"] == 100


def test_remote_fragment_host_attribution():
    # graphd root -> rpc.call -> (grafted) storage.get_bound ->
    # proc.scan_part tagged host=B; host inherits downward
    spans = [
        _span("r", "", "query", 0, 1000),
        _span("c", "r", "rpc.call", 0, 800, peer="B:45500"),
        _span("f", "c", "storage.get_bound", 10, 700),
        _span("p", "f", "proc.scan_part", 20, 650, host="B:45500"),
    ]
    a = critpath.analyze(_trace(spans))
    rows = {(x["name"], x["host"]): x for x in a["attribution"]}
    assert rows[("proc.scan_part", "B:45500")]["self_us"] == 650
    # the fragment root inherits no host of its own; its child's tag
    # does not leak UP
    assert ("storage.get_bound", None) in rows
    # dominant path reaches the remote processor with its host
    path = a["critical_path"]
    assert path[-1]["name"] == "proc.scan_part"
    assert path[-1]["host"] == "B:45500"


def test_degenerate_single_span():
    a = critpath.analyze(_trace([_span("r", "", "query", 0, 500)]))
    assert a["wall_us"] == 500
    assert a["critical_path"][0]["name"] == "query"
    # nothing but root self time -> nothing is EXPLAINED
    assert a["explained"] == 0.0


def test_empty_trace():
    a = critpath.analyze(_trace([]))
    assert a["wall_us"] == 0 and a["attribution"] == [] \
        and a["critical_path"] == [] and a["explained"] == 0.0


def test_missing_parent_becomes_extra_root():
    # an orphaned subtree (graft raced the finish): still attributed
    spans = [
        _span("r", "", "query", 0, 1000),
        _span("x", "GONE", "proc.get_bound", 0, 400, host="C:1"),
    ]
    a = critpath.analyze(_trace(spans))
    rows = {(x["name"], x["host"]) for x in a["attribution"]}
    assert ("proc.get_bound", "C:1") in rows
    # root selection: the longest root wins
    assert a["wall_us"] == 1000


def test_cycle_guard_in_dominant_path():
    # malformed self-parenting must not loop forever
    spans = [_span("r", "r", "query", 0, 100)]
    a = critpath.analyze(_trace(spans))
    assert len(a["critical_path"]) <= 2


def test_aggregate_over_traces():
    t1 = _trace([
        _span("r", "", "query", 0, 1000),
        _span("k", "r", "kernel", 0, 900),
    ], "t1")
    t2 = _trace([
        _span("r2", "", "query", 0, 1000),
        _span("k2", "r2", "kernel", 0, 700),
        _span("w2", "r2", "dispatcher.wait", 700, 300),
    ], "t2")
    agg = critpath.aggregate([t1, t2])
    assert agg["sampled_traces"] == 2
    assert agg["wall_us_total"] == 2000
    top = agg["attribution"][0]
    assert top["name"] == "kernel" and top["self_us"] == 1600
    assert 0.0 < agg["explained"] <= 1.0
