"""CSR builder equivalence: the native one-call extract path
(ncsr_build) must produce shards identical to the generic vectorized
scan path, on a property-rich graph with versions, tombstones and
cross-part edges (builder semantics ref: the getBound read rules,
storage/QueryBaseProcessor.inl:380-458)."""
import numpy as np
import pytest

from nba_fixture import load_nba
from nebula_tpu.cluster import InProcCluster
from nebula_tpu.engine_tpu import csr as csr_mod
from nebula_tpu.kvstore.nativeengine import NativeEngine


@pytest.fixture(scope="module")
def nba_native():
    """NBA data loaded into a cluster whose space engines are native."""
    cluster = InProcCluster()
    cluster.store._engine_factory = lambda sid: NativeEngine()
    _, conn = load_nba(cluster)
    # exercise versions + tombstones: overwrite and delete some rows
    conn.must("INSERT VERTEX player(name, age) VALUES "
              '100:("Tim Duncan", 43)')
    conn.must("INSERT EDGE like(likeness) VALUES 100 -> 101:(96.0)")
    conn.must("DELETE EDGE like 103 -> 104")
    return cluster


def _build_both(cluster, space_id, num_parts):
    engine = cluster.store.space_engine(space_id)
    assert isinstance(engine, NativeEngine)
    src = csr_mod._EngineScanSource(engine)
    native = csr_mod.build_shards(src, cluster.sm, space_id, num_parts)

    class NoExtract:
        def scan(self, part, kind):
            return src.scan(part, kind)

    generic = csr_mod.build_shards(NoExtract(), cluster.sm, space_id,
                                   num_parts)
    return native, generic


def test_native_extract_matches_generic(nba_native):
    cluster = nba_native
    space_id = cluster.meta.get_space("nba").value().space_id
    num_parts = cluster.sm.num_parts(space_id)
    (ns, ncv, nce, ndicts), (gs, gcv, gce, gdicts) = _build_both(
        cluster, space_id, num_parts)
    assert (ncv, nce) == (gcv, gce)
    assert ndicts == gdicts
    assert len(ns) == len(gs)
    for a, b in zip(ns, gs):
        assert np.array_equal(a.vids, b.vids)
        assert a.num_edges == b.num_edges
        for f in ("edge_src", "edge_etype", "edge_rank", "edge_dst_vid",
                  "edge_dst_part", "edge_dst_local", "edge_valid"):
            assert np.array_equal(getattr(a, f), getattr(b, f)), f
        assert set(a.edge_props) == set(b.edge_props)
        assert set(a.tag_props) == set(b.tag_props)
        for et in a.edge_props:
            for name, col in a.edge_props[et].items():
                other = b.edge_props[et][name]
                assert np.array_equal(col.present, other.present)
                assert list(col.host) == list(other.host), (et, name)
        for t in a.tag_props:
            for name, col in a.tag_props[t].items():
                other = b.tag_props[t][name]
                assert np.array_equal(col.present, other.present)
                assert list(col.host) == list(other.host), (t, name)


def test_versions_and_tombstones_respected(nba_native):
    """The overwrite shows its newest value; the deleted edge is gone."""
    cluster = nba_native
    space_id = cluster.meta.get_space("nba").value().space_id
    num_parts = cluster.sm.num_parts(space_id)
    snap = csr_mod.build_snapshot(cluster.store, cluster.sm, space_id,
                                  num_parts)
    loc = snap.locate(100)
    assert loc is not None
    p, i = loc
    player_tag = cluster.sm.tag_id(space_id, "player")
    like_et = cluster.sm.edge_type(space_id, "like")
    assert snap.shards[p].tag_props[player_tag]["age"].host[i] == 43
    # deleted 103->104 like edge absent in every shard's arrays
    for s in snap.shards:
        for j in range(s.num_edges):
            assert not (int(s.vids[s.edge_src[j]]) == 103
                        and int(s.edge_dst_vid[j]) == 104
                        and int(s.edge_etype[j]) == like_et)
