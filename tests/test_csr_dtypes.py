"""Narrow-width CSR packing tests (docs/manual/13-device-speed.md):
int16 local indices / int8 edge types when the caps allow must be
BIT-IDENTICAL to a forced-int32 build across the whole serving surface
— plain GO, device-compiled WHERE, aggregation pushdown, ALL-path,
delta apply, meshed serves — and the int32 fallback must engage for
spaces past either cap."""
import time

import numpy as np
import pytest

from nba_fixture import load_nba
from nebula_tpu.cluster import InProcCluster
from nebula_tpu.engine_tpu import TpuGraphEngine, csr
from nebula_tpu.engine_tpu import distributed as dist


def _drain_engine(tpu):
    for t in list(tpu._prewarm_threads.values()):
        t.join(timeout=300)
    for _ in range(600):
        if not tpu._recalibrating:
            return
        time.sleep(0.05)


# every device-servable shape in one sweep: multi-hop GO, compiled
# WHERE (int compare + string eq through dict codes), reverse edges,
# aggregation pushdown (ungrouped + grouped), ALL/NOLOOP path,
# shortest path
SUITE = [
    "GO FROM 100 OVER like YIELD like._dst, like.likeness",
    "GO 3 STEPS FROM 100 OVER like YIELD like._dst",
    "GO 2 STEPS FROM 100 OVER like WHERE $$.player.age > 33 "
    "YIELD like._dst, $$.player.age",
    'GO FROM 100, 101, 102 OVER serve WHERE $$.team.name == "Spurs" '
    "YIELD serve.start_year",
    "GO FROM 100 OVER like REVERSELY YIELD like._dst AS id",
    "GO FROM 100, 101, 102 OVER serve YIELD serve.start_year AS y | "
    "YIELD COUNT(*) AS n, SUM($-.y) AS s, MIN($-.y) AS lo, "
    "MAX($-.y) AS hi, AVG($-.y) AS a",
    "GO FROM 100, 101, 102 OVER serve YIELD serve._dst AS t, "
    "serve.start_year AS y | GROUP BY $-.t YIELD $-.t AS t, "
    "COUNT(*) AS n, SUM($-.y) AS s",
    "FIND ALL PATH FROM 100 TO 102 OVER like UPTO 3 STEPS",
    "FIND NOLOOP PATH FROM 103 TO 100 OVER like UPTO 4 STEPS",
    "FIND SHORTEST PATH FROM 100 TO 102 OVER like UPTO 4 STEPS",
]

MUTATIONS = [
    'INSERT VERTEX player(name, age) VALUES 777:("Packed", 25)',
    "INSERT EDGE like(likeness) VALUES 100 -> 777:(91.0)",
    "INSERT EDGE like(likeness) VALUES 777 -> 101:(77.0)",
    "DELETE EDGE like 100 -> 102",
]

POST_DELTA = [
    "GO FROM 100 OVER like YIELD like._dst, like.likeness",
    "GO 2 STEPS FROM 100 OVER like YIELD like._dst",
]


def _suite(conn, queries=SUITE):
    return {q: sorted(map(repr, conn.must(q).rows)) for q in queries}


def _build(space, force_wide):
    old = csr.FORCE_WIDE_DTYPES
    csr.FORCE_WIDE_DTYPES = force_wide
    try:
        tpu = TpuGraphEngine()
        cluster = InProcCluster(tpu_engine=tpu)
        _, conn = load_nba(cluster, space=space)
        tpu.sparse_edge_budget = 0   # dense: the packed device arrays serve
        sid = cluster.meta.get_space(space).value().space_id
        snap = tpu.snapshot(sid)
        assert snap is not None
    finally:
        csr.FORCE_WIDE_DTYPES = old
    return cluster, conn, tpu, sid, snap


@pytest.fixture(scope="module")
def narrow_wide():
    """Two TPU clusters over identical NBA data: default (narrow)
    widths vs forced int32."""
    n = _build("dtn", force_wide=False)
    w = _build("dtw", force_wide=True)
    yield n, w
    _drain_engine(n[2])
    _drain_engine(w[2])


def test_narrow_widths_are_on_by_default(narrow_wide):
    (_, _, _, _, nsnap), (_, _, _, _, wsnap) = \
        (narrow_wide[0][:1] + narrow_wide[0][1:],
         narrow_wide[1][:1] + narrow_wide[1][1:])
    nw = nsnap.dtype_widths()
    assert nw == {"edge_src": 2, "edge_etype": 1, "edge_dst_local": 2}, nw
    ww = wsnap.dtype_widths()
    assert ww == {"edge_src": 4, "edge_etype": 4, "edge_dst_local": 4}, ww
    # device kernels carry the packed widths through
    assert str(nsnap.kernel.src.dtype) == "int16"
    assert str(nsnap.kernel.etype.dtype) == "int8"
    assert str(nsnap.kernel.etype_sorted.dtype) == "int8"
    assert str(nsnap.kernel.src_sorted.dtype) == "int32"   # global slots


def test_narrow_vs_wide_bit_identical(narrow_wide):
    """GO / WHERE / agg pushdown / ALL path / shortest: every row of
    the narrow build equals the forced-int32 build exactly."""
    (ncl, nconn, ntpu, _, _), (wcl, wconn, wtpu, _, _) = narrow_wide
    rn = _suite(nconn)
    rw = _suite(wconn)
    assert rn == rw
    # and both actually served on device (not a CPU-fallback tie)
    assert ntpu.stats["go_served"] > 0 and wtpu.stats["go_served"] > 0
    assert ntpu.stats["agg_served"] > 0 and wtpu.stats["agg_served"] > 0


def test_narrow_vs_wide_delta_apply(narrow_wide):
    """Writes patch the narrow snapshot in place (delta buffer +
    tombstone point-updates over the packed arrays) — results after
    the same mutations stay identical to the wide build's."""
    (_, nconn, ntpu, _, _), (_, wconn, wtpu, _, _) = narrow_wide
    applies0 = ntpu.stats["delta_applies"]
    for m in MUTATIONS:
        nconn.must(m)
        wconn.must(m)
    rn = _suite(nconn, POST_DELTA)
    rw = _suite(wconn, POST_DELTA)
    assert rn == rw
    assert "'777'" not in repr(rn) or True
    assert ntpu.stats["delta_applies"] > applies0, \
        "mutation forced a rebuild instead of a delta apply"
    assert any("777" in r for rs in rn.values() for r in rs)


def test_narrow_fallback_past_caps(narrow_wide):
    """A space sized just past the packing caps falls back to int32
    and still serves identically. The caps are patched DOWN (64 local
    slots / 0 max etype) so the NBA space — cap_v=128, etypes 1..2 —
    is 'just past' both; building 33k vertices to cross the real
    1<<15 bound would prove the same branch at 1000x the cost."""
    (_, nconn, ntpu, nsid, _), _ = narrow_wide
    old_idx, old_et = csr.NARROW_IDX_CAP, csr.NARROW_ETYPE_MAX
    csr.NARROW_IDX_CAP, csr.NARROW_ETYPE_MAX = 64, 0
    try:
        with ntpu._lock:
            snap2 = ntpu.refresh(nsid)
        assert snap2.dtype_widths() == {"edge_src": 4, "edge_etype": 4,
                                        "edge_dst_local": 4}
        r1 = _suite(nconn, POST_DELTA)
    finally:
        csr.NARROW_IDX_CAP, csr.NARROW_ETYPE_MAX = old_idx, old_et
    with ntpu._lock:
        snap3 = ntpu.refresh(nsid)
    assert snap3.dtype_widths()["edge_src"] == 2
    r2 = _suite(nconn, POST_DELTA)
    assert r1 == r2


def test_dtype_helpers_real_thresholds():
    """The un-patched cap arithmetic: cap_v = 1<<15 still packs (max
    local index 32767 fits int16), one lane-width past it does not;
    |etype| 127 packs, 128 does not."""
    assert csr.edge_index_dtype(1 << 15) == np.dtype(np.int16)
    assert csr.edge_index_dtype((1 << 15) + 128) == np.dtype(np.int32)
    assert csr.edge_type_dtype(127) == np.dtype(np.int8)
    assert csr.edge_type_dtype(128) == np.dtype(np.int32)
    old = csr.FORCE_WIDE_DTYPES
    csr.FORCE_WIDE_DTYPES = True
    try:
        assert csr.edge_index_dtype(128) == np.dtype(np.int32)
        assert csr.edge_type_dtype(1) == np.dtype(np.int32)
    finally:
        csr.FORCE_WIDE_DTYPES = old


def test_narrow_meshed_identity():
    """Meshed serving over the packed arrays: the sharded kernel
    carries the narrow dtypes and the full suite equals the CPU
    pipe's rows."""
    _, cpu_conn = load_nba(space="dtmcpu", parts=8)
    tpu = TpuGraphEngine(mesh=dist.make_mesh())
    cluster = InProcCluster(tpu_engine=tpu)
    _, conn = load_nba(cluster, space="dtmtpu", parts=8)
    try:
        sid = cluster.meta.get_space("dtmtpu").value().space_id
        snap = tpu.snapshot(sid)
        assert snap is not None and snap.sharded_kernel is not None
        assert str(snap.sharded_kernel.src.dtype) == "int16"
        assert str(snap.sharded_kernel.etype.dtype) == "int8"
        queries = [q for q in SUITE if "GROUP BY" not in q]
        rc = {q: sorted(map(repr, cpu_conn.must(q).rows))
              for q in queries}
        rt = {q: sorted(map(repr, conn.must(q).rows)) for q in queries}
        assert rc == rt
    finally:
        _drain_engine(tpu)
