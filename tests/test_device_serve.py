"""Storaged-tier device shards + graphd scatter/gather v2
(storage/device_serve.py, engine_tpu/cluster.py;
docs/manual/13-device-speed.md "Storaged-tier device shards").

Real 3-storaged replicated topology over TCP raft: every storaged
keeps a LOCAL CSR shard of the parts it replicates, graphd fans GO
windows out as `device_window` RPCs and merges the per-host partials
with the SAME row assembly the CPU pipe uses — so the identity anchor
is testable end-to-end: cluster-device rows == CPU-pipe rows, with
leader-only routing AND with bounded-staleness follower reads armed
(mixed leader/follower partials), and across a live leadership
transfer (the old shard must refuse to vouch, the client re-routes,
the rebuilt shard serves again)."""
import time

import pytest

from nebula_tpu.client import GraphClient
from nebula_tpu.common.flags import storage_flags
from nebula_tpu.daemons import serve_graphd, serve_metad, serve_storaged
from nebula_tpu.engine_tpu import TpuGraphEngine

V = 30
EDGES = [(a, (a * 7 + k) % V, (a + k) % 97)
         for a in range(V) for k in (1, 2, 3)]
QUERIES = [
    "GO 2 STEPS FROM 1 OVER knows YIELD knows._dst",
    "GO FROM 1, 8, 15 OVER knows YIELD knows._dst, knows.ts",
    "GO 2 STEPS FROM 3 OVER knows WHERE knows.ts > 40 "
    "YIELD knows._dst, knows.ts",
]


@pytest.fixture(scope="module")
def rf_cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("devserve")
    saved = {f: storage_flags.get(f) for f in
             ("heartbeat_interval_secs", "raft_heartbeat_ms",
              "raft_election_timeout_ms", "follower_read_max_ms")}
    storage_flags.set("heartbeat_interval_secs", 0.4)
    storage_flags.set("raft_heartbeat_ms", 60)
    storage_flags.set("raft_election_timeout_ms", 250)
    metad = serve_metad()
    storers = [serve_storaged(metad.addr, replicated=True, engine="mem",
                              data_dir=str(tmp / f"s{i}"),
                              load_interval=0.15)
               for i in range(3)]
    tpu = TpuGraphEngine()
    graphd = serve_graphd(metad.addr, tpu_engine=tpu)
    gc = GraphClient(graphd.addr).connect()
    for q in ("CREATE SPACE dev(partition_num=4, replica_factor=3)",
              "USE dev", "CREATE TAG person(name string)",
              "CREATE EDGE knows(ts int)"):
        r = gc.execute(q)
        assert r.ok(), (q, r.error_msg)
    # first write retries while the 12 part elections settle
    deadline = time.time() + 15
    while time.time() < deadline:
        r = gc.execute('INSERT VERTEX person(name) VALUES 0:("p0")')
        if r.ok():
            break
        time.sleep(0.2)
    assert r.ok(), r.error_msg
    rows = ", ".join(f'{v}:("p{v}")' for v in range(1, V))
    assert gc.execute(
        f"INSERT VERTEX person(name) VALUES {rows}").ok()
    rows = ", ".join(f"{a} -> {b}:({t})" for a, b, t in EDGES)
    assert gc.execute(f"INSERT EDGE knows(ts) VALUES {rows}").ok()
    sid = metad.meta.get_space("dev").value().space_id
    yield gc, tpu, graphd, storers, sid
    gc.disconnect()
    graphd.stop()
    for h in storers:
        h.stop()
    metad.stop()
    for f, v in saved.items():
        storage_flags.set(f, v)


def _wait_shards_fresh(storers, sid, timeout=20.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        infos = [h.device_shards.snapshot_info(sid) for h in storers
                 if h.device_shards is not None]
        if len(infos) == len(storers) and \
                all(i.get("built") and i.get("fresh") for i in infos):
            return infos
        time.sleep(0.1)
    raise AssertionError(f"device shards never went fresh: {infos}")


def _identity(gc, tpu, q):
    rt = gc.must(q)
    tpu.enabled = False
    try:
        rc = gc.must(q)
    finally:
        tpu.enabled = True
    assert sorted(map(repr, rt.rows)) == sorted(map(repr, rc.rows)), q
    return rt


def test_shards_build_and_cluster_path_serves(rf_cluster):
    gc, tpu, graphd, storers, sid = rf_cluster
    infos = _wait_shards_fresh(storers, sid)
    assert all(i["total_edges"] > 0 for i in infos)
    served0 = tpu.stats["cluster_served"]
    for q in QUERIES:
        _identity(gc, tpu, q)
    assert tpu.stats["cluster_served"] > served0, \
        (tpu.stats, tpu.path_decline_reasons)
    # the partials actually came from the storaged-tier shards
    assert sum(h.device_shards.stats["parts_served"]
               for h in storers) > 0


def test_incremental_refresh_serves_new_edges(rf_cluster):
    """Committed writes freshen shards by in-place delta patches from
    the engine change ring — not full rebuilds — and the cluster
    device path serves the new edge identity-green."""
    gc, tpu, graphd, storers, sid = rf_cluster
    _wait_shards_fresh(storers, sid)
    builds0 = sum(h.device_shards.stats["builds"] for h in storers)
    da0 = sum(h.device_shards.stats["delta_applies"] for h in storers)
    assert gc.execute(
        "INSERT EDGE knows(ts) VALUES 1 -> 29@777:(99)").ok()
    _wait_shards_fresh(storers, sid)
    assert sum(h.device_shards.stats["delta_applies"]
               for h in storers) > da0
    assert sum(h.device_shards.stats["builds"]
               for h in storers) == builds0
    r = _identity(gc, tpu, "GO FROM 1 OVER knows YIELD knows._dst")
    assert any("29" in repr(row) for row in r.rows), r.rows


def test_mixed_leader_follower_partials_identity(rf_cluster):
    gc, tpu, graphd, storers, sid = rf_cluster
    _wait_shards_fresh(storers, sid)
    client = graphd.engine.client
    # arm via UPDATE CONFIGS (the production path: meta registry ->
    # heartbeat pull); a bare local set would be overwritten by the
    # next meta pull
    assert gc.execute(
        "UPDATE CONFIGS STORAGE:follower_read_max_ms = 150").ok()
    deadline = time.time() + 15
    while storage_flags.get("follower_read_max_ms") != 150 and \
            time.time() < deadline:
        time.sleep(0.05)
    assert storage_flags.get("follower_read_max_ms") == 150
    try:
        fparts0 = client.device_stats["follower_parts"]
        served0 = tpu.stats["cluster_served"]
        deadline = time.time() + 10
        while time.time() < deadline:
            for q in QUERIES:
                _identity(gc, tpu, q)
            if client.device_stats["follower_parts"] > fparts0:
                break
            time.sleep(0.2)   # followers may still be fence-refused
        assert tpu.stats["cluster_served"] > served0
        # mixed merge: some parts served by followers under the fence
        assert client.device_stats["follower_parts"] > fparts0
        assert sum(h.device_shards.stats["follower_parts_served"]
                   for h in storers) > 0
        # every follower-served staleness stayed within the bound plus
        # the shard-freshness slack
        slack = storage_flags.get_or("device_shard_max_ms", 250, int)
        assert client.device_stats["max_staleness_ms"] <= 150 + slack
    finally:
        gc.execute("UPDATE CONFIGS STORAGE:follower_read_max_ms = 0")
        deadline = time.time() + 15
        while storage_flags.get("follower_read_max_ms") != 0 and \
                time.time() < deadline:
            time.sleep(0.05)


def test_leadership_change_invalidates_shard_and_reroutes(rf_cluster):
    gc, tpu, graphd, storers, sid = rf_cluster
    _wait_shards_fresh(storers, sid)
    part = 1
    rafts = [h.node.raft(sid, part) for h in storers]
    leader_i = next(i for i, r in enumerate(rafts)
                    if r is not None and r.is_leader())
    target_i = (leader_i + 1) % len(storers)
    inval0 = sum(h.device_shards.stats["leader_invalidations"]
                 for h in storers)
    fut = rafts[leader_i].transfer_leader_async(rafts[target_i].addr)
    fut.result(timeout=5)
    deadline = time.time() + 10
    while time.time() < deadline and not rafts[target_i].is_leader():
        time.sleep(0.05)
    assert rafts[target_i].is_leader()
    # the leadership change dropped shards outright (they refused to
    # keep vouching under the old led set)...
    deadline = time.time() + 10
    while time.time() < deadline and sum(
            h.device_shards.stats["leader_invalidations"]
            for h in storers) <= inval0:
        time.sleep(0.05)
    assert sum(h.device_shards.stats["leader_invalidations"]
               for h in storers) > inval0
    # ...and the refresh task rebuilds, the client re-routes, and the
    # cluster device path serves identity-green against the new leader
    _wait_shards_fresh(storers, sid)
    served0 = tpu.stats["cluster_served"]
    deadline = time.time() + 15
    while time.time() < deadline:
        for q in QUERIES:
            _identity(gc, tpu, q)
        if tpu.stats["cluster_served"] > served0:
            break
        time.sleep(0.2)
    assert tpu.stats["cluster_served"] > served0, \
        (tpu.stats, tpu.path_decline_reasons)


def test_device_window_rpc_partials_shape(rf_cluster):
    """Direct `device_window` call: per-part verdicts + vertices."""
    gc, tpu, graphd, storers, sid = rf_cluster
    _wait_shards_fresh(storers, sid)
    client = graphd.engine.client
    etype = graphd.engine.sm.edge_type(sid, "knows")
    from nebula_tpu.common.status import ErrorCode
    # superset: earlier tests in this module may have inserted edges
    want = {(a, etype, b) for a, b, _ in EDGES}
    # retry while leadership from the transfer test above settles —
    # a refused part rides the one leader retry once caches catch up
    deadline = time.time() + 15
    got = None
    while time.time() < deadline:
        resp = client.device_window(sid, list(range(V)), [etype])
        got = {(e.src, e.etype, e.dst)
               for v in resp.vertices for e in v.edges}
        if want <= got and all(
                pr.code == ErrorCode.SUCCEEDED
                for pr in resp.results.values()):
            break
        time.sleep(0.2)
    assert want <= got
    # without allow_follower every granted part is leader-vouched
    assert all(pr.mode == "leader" for pr in resp.results.values()
               if pr.code == ErrorCode.SUCCEEDED)
    assert any(pr.code == ErrorCode.SUCCEEDED
               for pr in resp.results.values()), resp.results
