"""Distributed traversal tests over the 8-virtual-device CPU mesh
(conftest sets xla_force_host_platform_device_count=8): the sharded
shard_map/all_to_all path must agree exactly with the single-device path."""
import jax
import numpy as np
import pytest

import jax.numpy as jnp

from nba_fixture import load_nba
from nebula_tpu.cluster import InProcCluster
from nebula_tpu.engine_tpu import TpuGraphEngine, traverse
from nebula_tpu.engine_tpu import distributed as dist


@pytest.fixture(scope="module")
def snap8():
    """NBA data in an 8-partition space + its CSR snapshot."""
    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    _, conn = load_nba(cluster, space="dist8", parts=8)
    space_id = cluster.meta.get_space("dist8").value().space_id
    return tpu.snapshot(space_id), conn


def test_eight_virtual_devices():
    assert len(jax.devices()) == 8


@pytest.mark.parametrize("starts,steps,etypes", [
    ([100], 1, [1]),
    ([100], 3, [1]),
    ([100, 101, 107], 2, [1]),
    ([100], 2, [1, -1]),
    ([103], 4, [1]),
])
def test_sharded_matches_single_device(snap8, starts, steps, etypes):
    snap, _ = snap8
    mesh = dist.make_mesh()
    f0 = jnp.asarray(snap.frontier_from_vids(starts))
    req = jnp.asarray(traverse.pad_edge_types(etypes))

    f_single, a_single = traverse.multi_hop(f0, steps, snap.kernel, req)
    kern = traverse.stack_kernels(traverse.build_kernel(
        *snap._np_edge_stacks(), snap.np_gidx, snap.num_parts, snap.cap_v,
        num_blocks=mesh.devices.size))
    f_shard, a_shard = dist.multi_hop_sharded(mesh, f0, steps, kern, req)
    assert np.array_equal(np.asarray(f_single), np.asarray(f_shard))
    assert np.array_equal(np.asarray(a_single), np.asarray(a_shard))


def test_sharded_count_matches(snap8):
    snap, _ = snap8
    mesh = dist.make_mesh()
    f0 = jnp.asarray(snap.frontier_from_vids([100, 101]))
    req = jnp.asarray(traverse.pad_edge_types([1]))
    n_single = int(traverse.multi_hop_count(f0, 3, snap.kernel, req))
    kern = traverse.stack_kernels(traverse.build_kernel(
        *snap._np_edge_stacks(), snap.np_gidx, snap.num_parts, snap.cap_v,
        num_blocks=mesh.devices.size))
    n_shard = int(dist.multi_hop_count_sharded(mesh, f0, 3, kern, req))
    assert n_single == n_shard > 0


# ---------------------------------------------------------------------------
# EXECUTOR-level distributed identity: real nGQL through the query
# engine with a meshed TpuGraphEngine — the round-2 requirement that
# the distributed kernels are driven by the query path, not just
# kernel-level tests (VERDICT item 2).
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def meshed_pair():
    """(cpu_conn, meshed_tpu_conn, engine): same NBA data, the TPU
    engine running every traversal through the 8-device sharded path."""
    _, cpu_conn = load_nba(space="dist8cpu", parts=8)
    tpu = TpuGraphEngine(mesh=dist.make_mesh())
    cluster = InProcCluster(tpu_engine=tpu)
    _, conn = load_nba(cluster, space="dist8tpu", parts=8)
    return cpu_conn, conn, tpu


MESH_QUERIES = [
    "GO FROM 100 OVER like YIELD like._dst AS id, like.likeness AS w",
    "GO 2 STEPS FROM 100 OVER like YIELD DISTINCT like._dst",
    "GO 3 STEPS FROM 100 OVER like YIELD like._dst",
    "GO FROM 100 OVER like REVERSELY YIELD like._dst",
    "GO FROM 100, 101, 107 OVER like YIELD like._dst, like.likeness",
    "GO FROM 100 OVER like WHERE like.likeness > 80 YIELD like._dst, "
    "like.likeness",
    'GO FROM 100 OVER like WHERE $^.player.age > 40 YIELD like._dst, '
    '$^.player.name',
    'GO FROM 100 OVER serve YIELD $$.team.name AS team',
    "FIND SHORTEST PATH FROM 103 TO 100 OVER like UPTO 8 STEPS",
    "FIND SHORTEST PATH FROM 100, 101 TO 105, 106 OVER like UPTO 6 STEPS",
    "FIND SHORTEST PATH FROM 100 TO 121 OVER like UPTO 4 STEPS",  # no path
]


@pytest.mark.parametrize("query", MESH_QUERIES)
def test_executor_sharded_identity(meshed_pair, query):
    cpu_conn, tpu_conn, tpu = meshed_pair
    r_cpu = cpu_conn.must(query)
    r_tpu = tpu_conn.must(query)
    assert r_cpu.columns == r_tpu.columns
    assert sorted(map(str, r_cpu.rows)) == sorted(map(str, r_tpu.rows)), \
        (query, r_cpu.rows, r_tpu.rows)


def test_executor_sharded_actually_sharded(meshed_pair):
    _, tpu_conn, tpu = meshed_pair
    before = tpu.stats["sharded_queries"]
    tpu_conn.must("GO 2 STEPS FROM 100 OVER like YIELD like._dst")
    tpu_conn.must("FIND SHORTEST PATH FROM 103 TO 100 OVER like UPTO 8 STEPS")
    assert tpu.stats["sharded_queries"] - before == 2, tpu.stats
    assert tpu.stats["go_served"] > 0 and tpu.stats["path_served"] > 0


def test_sharded_bfs_dist_matches_single(snap8):
    snap, _ = snap8
    mesh = dist.make_mesh()
    kern = dist.shard_snapshot_arrays(mesh, snap)
    f0 = jnp.asarray(snap.frontier_from_vids([103]))
    req = jnp.asarray(traverse.pad_edge_types([1]))
    d_single = np.asarray(traverse.bfs_dist(f0, jnp.int32(6), snap.kernel,
                                            req))
    d_shard = np.asarray(dist.bfs_dist_sharded(mesh, f0, jnp.int32(6),
                                               kern, req))
    assert np.array_equal(d_single, d_shard)


def test_sharded_with_placed_arrays(snap8):
    """Explicitly shard the snapshot arrays over the mesh and re-run —
    exercising the NamedSharding placement path used on real hardware."""
    snap, _ = snap8
    mesh = dist.make_mesh()
    kern = dist.shard_snapshot_arrays(mesh, snap)
    f0 = jnp.asarray(snap.frontier_from_vids([100]))
    req = jnp.asarray(traverse.pad_edge_types([1]))
    f, a = dist.multi_hop_sharded(mesh, f0, 2, kern, req)
    # compare against a fresh single-device run
    f1, a1 = traverse.multi_hop(f0, 2, snap.kernel, req)
    assert np.array_equal(np.asarray(f), np.asarray(f1))
    assert np.array_equal(np.asarray(a), np.asarray(a1))


def test_sharded_batched_count_matches(snap8):
    """The distributed flagship counter (replicated packed frontier
    matrix, per-device aligned blocks, pmax merge + psum counts) must
    count exactly what the per-query single-device kernel counts."""
    snap, _ = snap8
    mesh = dist.make_mesh()
    ak, chunk, group = dist.shard_aligned_blocks(mesh, snap)
    seeds = [[100], [101, 102], [103, 104, 105], [100, 110]]
    f_batch = jnp.asarray(np.stack(
        [snap.frontier_from_vids(s) for s in seeds]))
    for req_list in ([1], [1, -1]):
        req = jnp.asarray(traverse.pad_edge_types(req_list))
        for steps in (1, 2, 3):
            out = np.asarray(dist.multi_hop_count_batch_sharded(
                mesh, f_batch, jnp.int32(steps), ak, req, chunk, group))
            for i, s in enumerate(seeds):
                single = int(traverse.multi_hop_count(
                    jnp.asarray(snap.frontier_from_vids(s)),
                    jnp.int32(steps), snap.kernel, req))
                assert int(out[i]) == single, \
                    (req_list, steps, s, out[i], single)


def test_executor_sharded_aggregate_identity(meshed_pair):
    """GO | YIELD <aggregates> through the MESHED engine: the reduction
    runs over the sharded multi-hop mask (note: runs before the
    mutation test below in module order)."""
    cpu_conn, tpu_conn, tpu = meshed_pair
    before = tpu.stats["agg_served"]
    q = ("GO FROM 100, 101, 102 OVER serve YIELD serve.start_year AS y"
         " | YIELD COUNT(*) AS n, SUM($-.y) AS s, MIN($-.y) AS lo")
    rc, rt = cpu_conn.must(q), tpu_conn.must(q)
    assert rc.rows == rt.rows, (rc.rows, rt.rows)
    assert tpu.stats["agg_served"] == before + 1, tpu.stats


def test_executor_sharded_grouped_aggregate_identity(meshed_pair):
    """GROUP BY $-.<dst> segment reduction over the MESHED engine's
    sharded multi-hop mask (runs before the mutation test)."""
    cpu_conn, tpu_conn, tpu = meshed_pair
    before = tpu.stats["agg_served"]
    q = ("GO FROM 100, 101, 102 OVER serve YIELD serve._dst AS t,"
         " serve.start_year AS y | GROUP BY $-.t YIELD $-.t AS t,"
         " COUNT(*) AS n, SUM($-.y) AS s")
    rc, rt = cpu_conn.must(q), tpu_conn.must(q)
    assert sorted(map(repr, rc.rows)) == sorted(map(repr, rt.rows))
    assert tpu.stats["agg_served"] == before + 1, tpu.stats


def test_executor_sharded_identity_after_mutation(meshed_pair):
    """Writes flow into the MESHED snapshot (delta patches / rebuilds)
    and the sharded path keeps CPU≡TPU identity afterwards — the one
    executor-level scenario the dryrun entry point exercises that the
    per-query identity tests above don't. Runs last in this module:
    it mutates the module-scoped fixture's data."""
    cpu_conn, tpu_conn, tpu = meshed_pair
    for stmt in ('INSERT VERTEX player(name, age) VALUES 888:("Mesh", 30)',
                 "INSERT EDGE like(likeness) VALUES 100 -> 888:(77.0)",
                 "DELETE EDGE like 100 -> 101"):
        cpu_conn.must(stmt)
        tpu_conn.must(stmt)
    for q in ("GO FROM 100 OVER like YIELD like._dst, like.likeness",
              "GO 2 STEPS FROM 100 OVER like YIELD like._dst",
              "FIND SHORTEST PATH FROM 103 TO 888 OVER like UPTO 8 STEPS"):
        r_cpu, r_tpu = cpu_conn.must(q), tpu_conn.must(q)
        assert sorted(map(str, r_cpu.rows)) == sorted(map(str, r_tpu.rows)), \
            (q, r_cpu.rows, r_tpu.rows)

