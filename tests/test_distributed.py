"""Distributed traversal tests over the 8-virtual-device CPU mesh
(conftest sets xla_force_host_platform_device_count=8): the sharded
shard_map/all_to_all path must agree exactly with the single-device path."""
import jax
import numpy as np
import pytest

import jax.numpy as jnp

from nba_fixture import load_nba
from nebula_tpu.cluster import InProcCluster
from nebula_tpu.engine_tpu import TpuGraphEngine, traverse
from nebula_tpu.engine_tpu import distributed as dist


@pytest.fixture(scope="module")
def snap8():
    """NBA data in an 8-partition space + its CSR snapshot."""
    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    _, conn = load_nba(cluster, space="dist8", parts=8)
    space_id = cluster.meta.get_space("dist8").value().space_id
    return tpu.snapshot(space_id), conn


def test_eight_virtual_devices():
    assert len(jax.devices()) == 8


@pytest.mark.parametrize("starts,steps,etypes", [
    ([100], 1, [1]),
    ([100], 3, [1]),
    ([100, 101, 107], 2, [1]),
    ([100], 2, [1, -1]),
    ([103], 4, [1]),
])
def test_sharded_matches_single_device(snap8, starts, steps, etypes):
    snap, _ = snap8
    mesh = dist.make_mesh()
    f0 = jnp.asarray(snap.frontier_from_vids(starts))
    req = jnp.asarray(traverse.pad_edge_types(etypes))

    f_single, a_single = traverse.multi_hop(f0, steps, snap.kernel, req)
    kern = traverse.stack_kernels(traverse.build_kernel(
        *snap._np_edge_stacks(), snap.np_gidx, snap.num_parts, snap.cap_v,
        num_blocks=mesh.devices.size))
    f_shard, a_shard = dist.multi_hop_sharded(mesh, f0, steps, kern, req)
    assert np.array_equal(np.asarray(f_single), np.asarray(f_shard))
    assert np.array_equal(np.asarray(a_single), np.asarray(a_shard))


def test_sharded_count_matches(snap8):
    snap, _ = snap8
    mesh = dist.make_mesh()
    f0 = jnp.asarray(snap.frontier_from_vids([100, 101]))
    req = jnp.asarray(traverse.pad_edge_types([1]))
    n_single = int(traverse.multi_hop_count(f0, 3, snap.kernel, req))
    kern = traverse.stack_kernels(traverse.build_kernel(
        *snap._np_edge_stacks(), snap.np_gidx, snap.num_parts, snap.cap_v,
        num_blocks=mesh.devices.size))
    n_shard = int(dist.multi_hop_count_sharded(mesh, f0, 3, kern, req))
    assert n_single == n_shard > 0


def test_sharded_with_placed_arrays(snap8):
    """Explicitly shard the snapshot arrays over the mesh and re-run —
    exercising the NamedSharding placement path used on real hardware."""
    snap, _ = snap8
    mesh = dist.make_mesh()
    kern = dist.shard_snapshot_arrays(mesh, snap)
    f0 = jnp.asarray(snap.frontier_from_vids([100]))
    req = jnp.asarray(traverse.pad_edge_types([1]))
    f, a = dist.multi_hop_sharded(mesh, f0, 2, kern, req)
    # compare against a fresh single-device run
    f1, a1 = traverse.multi_hop(f0, 2, snap.kernel, req)
    assert np.array_equal(np.asarray(f), np.asarray(f1))
    assert np.array_equal(np.asarray(a), np.asarray(a1))
