"""Expression eval + serialization tests (parity model: common/filter tests,
storage-side decode at QueryBaseProcessor.inl:146-167)."""
import pytest

from nebula_tpu.filter import (ArithmeticExpr, EvalError, ExpressionContext,
                               FunctionCall, FunctionManager, Literal,
                               LogicalExpr, RelationalExpr, UnaryExpr,
                               decode_expression, encode_expression)
from nebula_tpu.parser import GQLParser


def parse_expr(text):
    """Parse an expression through a YIELD statement."""
    stmts = GQLParser().parse(f"YIELD {text} AS x")
    return stmts.sentences[0].yield_.columns[0].expr


class Ctx(ExpressionContext):
    def __init__(self, edge_props=None, src_props=None, dst_props=None,
                 input_props=None, variables=None):
        self.edge_props = edge_props or {}
        self.src_props = src_props or {}
        self.dst_props = dst_props or {}
        self.input_props = input_props or {}
        self.variables = variables or {}

    def get_edge_prop(self, edge, prop):
        return self.edge_props[prop]

    def get_src_prop(self, tag, prop):
        return self.src_props[(tag, prop)]

    def get_dst_prop(self, tag, prop):
        return self.dst_props[(tag, prop)]

    def get_input_prop(self, prop):
        return self.input_props[prop]

    def get_variable_prop(self, var, prop):
        return self.variables[(var, prop)]

    def get_edge_src(self, edge):
        return 100

    def get_edge_dst(self, edge):
        return 200

    def get_edge_rank(self, edge):
        return 3


CTX = Ctx()


@pytest.mark.parametrize("text,expected", [
    ("1 + 2 * 3", 7),
    ("(1 + 2) * 3", 9),
    ("7 / 2", 3),            # C-style int division
    ("-7 / 2", -3),          # truncation toward zero, not floor
    ("7 % 3", 1),
    ("-7 % 3", -1),          # C-style remainder
    ("7.0 / 2", 3.5),
    ('"a" + "b"', "ab"),
    ('"n" + 1', "n1"),       # string concat coerces
    ("1 < 2", True),
    ("2 <= 1", False),
    ('"abc" CONTAINS "b"', True),
    ("1 == 1.0", True),
    ('1 == "1"', False),     # cross-type equality is false, not an error
    ('1 != "1"', True),
    ("true && false", False),
    ("true || false", True),
    ("true XOR true", False),
    ("!true", False),
    ("NOT false", True),
    ("-(3)", -3),
    ("(int)3.9", 3),
    ("(string)42", "42"),
    ("(bool)0", False),
    ("NULL == NULL", True),
    ("NULL != 1", True),
    ("udf_is_in(2, 1, 2, 3)", True),
    ("udf_is_in(9, 1, 2, 3)", False),
    ("abs(0-5)", 5),
    ("pow(2, 10)", 1024),
    ("lower(\"ABC\")", "abc"),
    ("substr(\"hello\", 1, 3)", "ell"),
    ("length(\"hello\")", 5),
])
def test_eval(text, expected):
    assert parse_expr(text).eval(CTX) == expected


def test_div_by_zero():
    with pytest.raises(EvalError):
        parse_expr("1 / 0").eval(CTX)


def test_prop_refs_bind_to_context():
    ctx = Ctx(edge_props={"likeness": 95.0},
              src_props={("player", "name"): "Tim Duncan"},
              dst_props={("player", "age"): 33},
              input_props={"id": 7},
              variables={("var", "col"): "v"})
    assert parse_expr("like.likeness").eval(ctx) == 95.0
    assert parse_expr("$^.player.name").eval(ctx) == "Tim Duncan"
    assert parse_expr("$$.player.age + 1").eval(ctx) == 34
    assert parse_expr("$-.id * 2").eval(ctx) == 14
    assert parse_expr("$var.col").eval(ctx) == "v"
    assert parse_expr("like._src").eval(ctx) == 100
    assert parse_expr("like._dst").eval(ctx) == 200
    assert parse_expr("_rank").eval(ctx) == 3


def test_missing_getter_raises():
    with pytest.raises(EvalError):
        parse_expr("$-.absent").eval(ExpressionContext())


@pytest.mark.parametrize("text", [
    "1 + 2 * 3",
    "$^.player.age >= 30 && like.likeness > 90.0",
    '$$.team.name == "Spurs" || udf_is_in($-.id, 1, 2, 3)',
    "(int)(abs(0 - $-.x) % 7)",
    "like._dst",
    "_rank == 0",
    "$var.col CONTAINS \"a\"",
])
def test_encode_decode_roundtrip(text):
    e = parse_expr(text)
    data = encode_expression(e)
    e2 = decode_expression(data)
    assert e2.to_string() == e.to_string()
    # both evaluate the same under the same context
    ctx = Ctx(edge_props={"likeness": 95.0},
              src_props={("player", "age"): 33},
              dst_props={("team", "name"): "Spurs"},
              input_props={"id": 2, "x": -10},
              variables={("var", "col"): "abc"})
    assert e.eval(ctx) == e2.eval(ctx)


def test_function_manager_arity_and_unknown():
    with pytest.raises(EvalError):
        FunctionManager.invoke("abs", [1, 2])
    with pytest.raises(EvalError):
        FunctionManager.invoke("no_such_fn", [])
    assert FunctionManager.exists("now")
    assert len(FunctionManager.names()) >= 30


def test_hash_is_stable_int64():
    h1 = FunctionManager.invoke("hash", ["hello"])
    h2 = FunctionManager.invoke("hash", ["hello"])
    assert h1 == h2
    assert -(1 << 63) <= h1 < (1 << 63)


def test_pad_functions():
    # ref: FunctionManager.cpp lpad/rpad — pad to size, truncate if shorter
    assert FunctionManager.invoke("lpad", ["abc", 6, "xy"]) == "xyxabc"
    assert FunctionManager.invoke("rpad", ["abc", 6, "xy"]) == "abcxyx"
    assert FunctionManager.invoke("lpad", ["abcdef", 3, "x"]) == "abc"
    assert FunctionManager.invoke("rpad", ["abcdef", 3, "x"]) == "abc"
    assert FunctionManager.invoke("lpad", ["abc", 3, "x"]) == "abc"
