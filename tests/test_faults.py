"""Fault-injection framework + degradation ladder tests
(common/faults.py, engine ladder wiring, transport/storage-client
backoff satellites; docs/manual/9-robustness.md).

Everything here must prove the one invariant the chaos tier enforces
at scale: an injected device-path failure NEVER reaches a client —
queries degrade (mesh -> single-device -> CPU pipe) with results
byte-identical to the CPU pipe, every fire is counted, and breakers
recover through half-open probes once faults stop."""
import socket
import threading
import time

import pytest

from nebula_tpu.cluster import InProcCluster
from nebula_tpu.common.faults import (CircuitBreaker, FaultRegistry,
                                      InjectedFault, faults)
from nebula_tpu.engine_tpu import TpuGraphEngine


@pytest.fixture(autouse=True)
def _clean_faults():
    """The registry is process-global: never leak a plan (a stray
    kernel fault would fail unrelated identity tests) or stale fire
    counts into another test."""
    faults.reset()
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# registry unit tests
# ---------------------------------------------------------------------------

def test_registry_noop_without_plan():
    reg = FaultRegistry()
    reg.register("x")
    reg.fire("x")                      # nothing armed: no-op
    assert reg.total_fired() == 0


def test_registry_fire_n_times_then_disarm():
    reg = FaultRegistry()
    reg.register("x")
    reg.set_plan("x:n=2")
    for _ in range(2):
        with pytest.raises(InjectedFault):
            reg.fire("x")
    reg.fire("x")                      # budget spent: disarmed
    assert reg.counts() == {"x": 2}


def test_registry_latency_mode_sleeps_not_raises():
    reg = FaultRegistry()
    reg.set_plan("x:latency=30,n=1")
    t0 = time.monotonic()
    reg.fire("x")                      # latency mode: no exception
    assert time.monotonic() - t0 >= 0.02
    assert reg.counts()["x"] == 1


def test_registry_after_skips_then_arms():
    reg = FaultRegistry()
    reg.set_plan("x:after=2,n=1")
    reg.fire("x")
    reg.fire("x")                      # first two evaluations skipped
    with pytest.raises(InjectedFault):
        reg.fire("x")


def test_registry_probability_seeded():
    reg = FaultRegistry()
    reg.set_plan("seed=7;x:p=0.5")
    hits = 0
    for _ in range(200):
        try:
            reg.fire("x")
        except InjectedFault:
            hits += 1
    assert 50 < hits < 150             # ~p=0.5, seeded
    assert reg.counts()["x"] == hits


def test_registry_bad_plan_rejected_and_previous_kept():
    reg = FaultRegistry()
    reg.set_plan("x:n=1")
    with pytest.raises(ValueError):
        reg.set_plan("x:wat=1")
    with pytest.raises(InjectedFault):
        reg.fire("x")                  # old plan still armed
    reg.set_plan("")                   # empty plan clears
    reg.fire("x")


def test_registry_describe_catalog():
    d = faults.describe()
    # the load-bearing serve-path sites are pre-registered
    for point in ("csr.build", "csr.delta_apply", "kernel.launch",
                  "mesh.collective", "encode.rows", "rpc.send"):
        assert point in d["points"]


def test_fault_plan_flag_applies():
    from nebula_tpu.common.flags import graph_flags
    assert graph_flags.set("fault_plan", "kernel.launch:n=1")
    try:
        assert "kernel.launch" in faults.describe()["active"]
    finally:
        graph_flags.set("fault_plan", "")
    assert not faults.describe()["active"]


# ---------------------------------------------------------------------------
# circuit breaker state machine
# ---------------------------------------------------------------------------

def test_breaker_state_machine():
    t = [0.0]
    b = CircuitBreaker(threshold=2, base_backoff_s=1.0,
                       max_backoff_s=4.0, clock=lambda: t[0])
    assert b.state == b.CLOSED and b.allow()
    assert b.record_failure() is False          # 1 of 2
    assert b.state == b.CLOSED
    assert b.record_failure() is True           # trips
    assert b.trips == 1
    assert b.state == b.OPEN and not b.allow()
    t[0] = 1.1                                  # backoff elapsed
    assert b.state == b.HALF_OPEN and b.allow()
    assert b.half_open_probes == 1
    b.record_failure()                          # probe fails: backoff x2
    assert b.state == b.OPEN
    t[0] = 3.0
    assert b.state == b.OPEN                    # 1.1 + 2.0 not reached
    t[0] = 3.2
    assert b.allow()                            # half-open again
    b.record_success()
    assert b.state == b.CLOSED and b.recoveries == 1
    # consecutive-failure counter reset by the success
    b.record_failure()
    assert b.state == b.CLOSED


def test_breaker_success_resets_consecutive():
    b = CircuitBreaker(threshold=2)
    b.record_failure()
    b.record_success()
    b.record_failure()
    assert b.state == b.CLOSED                  # never 2 consecutive


# ---------------------------------------------------------------------------
# engine ladder: injected device failures degrade to the CPU pipe
# ---------------------------------------------------------------------------

def _mini_cluster(parts=2, v=60, e=240, seed=3):
    import numpy as np
    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    conn = cluster.connect()
    conn.must(f"CREATE SPACE fz(partition_num={parts})")
    conn.must("USE fz")
    conn.must("CREATE TAG person(age int)")
    conn.must("CREATE EDGE knows(w int)")
    conn.must("INSERT VERTEX person(age) VALUES " + ", ".join(
        f"{i}:({i % 70})" for i in range(v)))
    rng = np.random.default_rng(seed)
    srcs = rng.integers(0, v, e)
    dsts = rng.integers(0, v, e)
    for i in range(0, e, 200):
        conn.must("INSERT EDGE knows(w) VALUES " + ", ".join(
            f"{int(s)} -> {int(d)}@{j}:({int((s + d) % 50)})"
            for j, (s, d) in enumerate(zip(srcs[i:i + 200],
                                           dsts[i:i + 200]), start=i)))
    sid = cluster.meta.get_space("fz").value().space_id
    return cluster, conn, tpu, sid


@pytest.fixture()
def mini():
    return _mini_cluster()


def _ref_rows(conn, tpu, q):
    tpu.enabled = False
    try:
        return sorted(map(repr, conn.must(q).rows))
    finally:
        tpu.enabled = True


def test_kernel_fault_degrades_to_cpu_identical(mini):
    cluster, conn, tpu, sid = mini
    tpu.sparse_edge_budget = 0          # pin dense: the launch path
    q = "GO 2 STEPS FROM 1 OVER knows YIELD knows._dst, knows.w"
    conn.must(q)                        # snapshot + compile warm
    ref = _ref_rows(conn, tpu, q)
    d0 = tpu.stats["degraded_serves"]
    faults.set_plan("kernel.launch:n=1")
    r = conn.must(q)                    # fault fires; client never sees it
    assert sorted(map(repr, r.rows)) == ref
    assert tpu.stats["degraded_serves"] == d0 + 1
    assert faults.counts()["kernel.launch"] == 1
    # and with faults cleared the device path serves again
    g0 = tpu.stats["go_served"]
    conn.must(q)
    assert tpu.stats["go_served"] == g0 + 1


def test_breaker_trips_then_half_open_recovers(mini):
    cluster, conn, tpu, sid = mini
    tpu.sparse_edge_budget = 0
    tpu.breaker_threshold = 2
    tpu.breaker_base_s = 30.0           # stays OPEN until forced
    q = "GO 2 STEPS FROM 2 OVER knows YIELD knows._dst"
    conn.must(q)
    ref = _ref_rows(conn, tpu, q)
    faults.set_plan("kernel.launch:p=1")
    for _ in range(3):
        assert sorted(map(repr, conn.must(q).rows)) == ref
    assert tpu.stats["breaker_trips"] == 1
    assert tpu.breaker_states()["go"] == "open"
    faults.clear()
    # open breaker: device path declined pre-dispatch, CPU serves
    f0 = faults.total_fired()
    d0 = tpu.stats["degraded_serves"]
    assert sorted(map(repr, conn.must(q).rows)) == ref
    assert faults.total_fired() == f0            # no fire: not launched
    assert tpu.stats["degraded_serves"] == d0 + 1
    # force the half-open window; the next query is the probe
    tpu._breakers["go"]._next_probe = 0.0
    assert tpu.breaker_states()["go"] == "half_open"
    g0 = tpu.stats["go_served"]
    assert sorted(map(repr, conn.must(q).rows)) == ref
    assert tpu.stats["go_served"] == g0 + 1      # device served again
    assert tpu.breaker_states()["go"] == "closed"
    assert tpu.stats["breaker_recoveries"] == 1


def test_leader_fault_isolates_group_and_releases_round(mini):
    """Satellite audit (_serve_group/_release_round/_mark_done):
    a group leader dying mid-round must wake exactly its group's
    waiters (result degraded to the CPU pipe, correct rows), hand the
    round key back, and leave no waiter hanging."""
    cluster, conn, tpu, sid = mini
    tpu.sparse_edge_budget = 0
    q = "GO 2 STEPS FROM 3 OVER knows YIELD knows._dst, knows.w"
    conn.must(q)                        # warm the batched shapes
    ref = _ref_rows(conn, tpu, q)
    faults.set_plan("kernel.launch:n=1")
    errs, rows_seen = [], []

    def worker():
        try:
            c = cluster.connect()
            c.must("USE fz")
            rows_seen.append(sorted(map(repr, c.must(q).rows)))
        except Exception as ex:   # noqa: BLE001 — the test's subject
            errs.append(repr(ex))

    threads = [threading.Thread(target=worker) for _ in range(6)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not [t for t in threads if t.is_alive()], "waiter stranded"
    assert not errs, errs
    assert all(r == ref for r in rows_seen)
    assert faults.counts().get("kernel.launch", 0) == 1
    assert not tpu._disp_serving, "round key never handed back"
    assert time.monotonic() - t0 < 120


def test_dispatcher_deadline_unclaimed_waiter_balks(mini):
    """A queued-but-unclaimed dispatcher waiter whose deadline expires
    balks out of the queue and serves on the CPU pipe — it never
    blocks on a slow round it doesn't belong to."""
    cluster, conn, tpu, sid = mini
    tpu.sparse_edge_budget = 0
    q = "GO 2 STEPS FROM 4 OVER knows YIELD knows._dst"
    conn.must(q)
    ref = _ref_rows(conn, tpu, q)
    tpu.query_deadline_ms = 150
    orig = tpu._serve_batch

    def slow(batch, ex):
        time.sleep(1.5)
        orig(batch, ex)

    tpu._serve_batch = slow
    try:
        leader = threading.Thread(
            target=lambda: conn.must(q))
        leader.start()
        time.sleep(0.3)                # leader's round is in flight
        c2 = cluster.connect()
        c2.must("USE fz")
        dl0 = tpu.stats["deadline_exceeded"]
        t0 = time.monotonic()
        r = c2.must(q)                 # queued behind the slow round
        waited = time.monotonic() - t0
        leader.join(timeout=60)
    finally:
        tpu._serve_batch = orig
        tpu.query_deadline_ms = None
    assert sorted(map(repr, r.rows)) == ref
    assert waited < 1.2, "waiter blocked past its deadline"
    assert tpu.stats["deadline_exceeded"] > dl0


def test_snapshot_poisoning_recovery(mini):
    """Satellite: a failed delta apply poisons ONLY that snapshot
    (counted), the query serves on the CPU pipe, and a subsequent
    refresh()/repack rebuilds cleanly and re-serves on device."""
    cluster, conn, tpu, sid = mini
    q = "GO FROM 1 OVER knows YIELD knows._dst, knows.w"
    conn.must(q)                        # snapshot up
    faults.set_plan("csr.delta_apply:n=1")
    conn.must("INSERT EDGE knows(w) VALUES 1 -> 2:(9)")
    p0 = tpu.stats["snapshot_poisoned"]
    r = conn.must(q)                    # apply fires -> poison -> CPU
    assert tpu.stats["snapshot_poisoned"] == p0 + 1
    assert faults.counts()["csr.delta_apply"] == 1
    assert sorted(map(repr, r.rows)) == _ref_rows(conn, tpu, q)
    faults.clear()
    # the background repack (or an explicit refresh) rebuilds cleanly
    deadline = time.monotonic() + 30
    while tpu._repacking.get(sid) and time.monotonic() < deadline:
        time.sleep(0.02)
    with tpu._lock:
        snap = tpu.refresh(sid)
    assert snap is not None and not snap.stale
    g0 = tpu.stats["go_served"]
    r2 = conn.must(q)
    assert tpu.stats["go_served"] == g0 + 1     # device serves again
    assert sorted(map(repr, r2.rows)) == _ref_rows(conn, tpu, q)


def test_csr_build_fault_declines_to_cpu(mini):
    cluster, conn, tpu, sid = mini
    q = "GO FROM 5 OVER knows YIELD knows._dst"
    conn.must(q)
    ref = _ref_rows(conn, tpu, q)
    with tpu._lock:                     # drop the snapshot: force build
        tpu._snapshots.clear()
    faults.set_plan("csr.build:n=1")
    r = conn.must(q)                    # build fails -> CPU serves
    assert sorted(map(repr, r.rows)) == ref
    assert faults.counts()["csr.build"] == 1


def test_encode_fault_falls_back_to_python_codec(mini):
    """encode.rows degrades INSIDE the device path: the native encode
    raises, the pure-python twin produces identical bytes, the query
    still device-serves."""
    from nebula_tpu import native
    if not native.available():
        pytest.skip("native codec not built")
    cluster, conn, tpu, sid = mini
    q = "GO FROM 6 OVER knows YIELD knows._dst, knows.w"
    conn.must(q)
    ref = _ref_rows(conn, tpu, q)
    faults.set_plan("encode.rows:p=1")
    fb0 = tpu.stats["encode_fallback_rows"]
    g0 = tpu.stats["go_served"]
    r = conn.must(q)
    assert sorted(map(repr, r.rows)) == ref
    assert tpu.stats["go_served"] == g0 + 1      # still device-served
    assert tpu.stats["encode_fallback_rows"] > fb0
    assert faults.counts()["encode.rows"] >= 1


def test_agg_fault_degrades_to_cpu_pipe(mini):
    cluster, conn, tpu, sid = mini
    tpu.sparse_edge_budget = 0
    q = ("GO 2 STEPS FROM 7 OVER knows YIELD knows.w AS w | "
         "YIELD COUNT(*) AS n, SUM($-.w) AS s")
    conn.must(q)
    ref = _ref_rows(conn, tpu, q)
    faults.set_plan("kernel.launch:p=1")
    r = conn.must(q)
    assert sorted(map(repr, r.rows)) == ref
    assert faults.counts()["kernel.launch"] >= 1
    assert tpu.breaker_states().get("agg") == "closed"  # 1 < threshold


def test_mesh_fault_demotes_to_single_device_then_readmits():
    """The mesh rung of the ladder: a failing sharded collective trips
    the mesh breaker -> the space DEMOTES to single-device serving
    (unsharded rebuild), still on device — and a half-open probe
    re-admits the mesh once faults stop."""
    from nebula_tpu.engine_tpu import distributed as dist
    tpu = TpuGraphEngine(mesh=dist.make_mesh())
    tpu.breaker_threshold = 1
    tpu.breaker_base_s = 30.0           # OPEN until the test forces it
    cluster = InProcCluster(tpu_engine=tpu)
    conn = cluster.connect()
    conn.must("CREATE SPACE fzm(partition_num=8)")
    conn.must("USE fzm")
    conn.must("CREATE TAG person(age int)")
    conn.must("CREATE EDGE knows(w int)")
    conn.must("INSERT VERTEX person(age) VALUES " + ", ".join(
        f"{i}:({20 + i})" for i in range(24)))
    conn.must("INSERT EDGE knows(w) VALUES " + ", ".join(
        f"{i} -> {(i + 1) % 24}:({i})" for i in range(24)))
    sid = cluster.meta.get_space("fzm").value().space_id
    q = "FIND ALL PATH FROM 0 TO 3 OVER knows UPTO 3 STEPS"

    def _settle_repack():
        deadline = time.monotonic() + 60
        while tpu._repacking.get(sid) and time.monotonic() < deadline:
            time.sleep(0.02)

    try:
        conn.must(q)                    # warm; serves meshed
        snap = tpu.snapshot(sid)
        assert snap is not None and snap.sharded_kernel is not None
        ref = _ref_rows(conn, tpu, q)
        faults.set_plan("mesh.collective:p=1")
        r = conn.must(q)                # collective fails -> demote
        assert sorted(map(repr, r.rows)) == ref
        assert tpu.stats["mesh_demotions"] == 1
        assert sid in tpu._mesh_demoted
        faults.clear()
        _settle_repack()
        snap = tpu.snapshot(sid)        # the single-device rung
        assert snap is not None and snap.sharded_kernel is None
        p0 = tpu.stats["path_served"]
        assert sorted(map(repr, conn.must(q).rows)) == ref
        assert tpu.stats["path_served"] == p0 + 1   # still on device
        # half-open probe re-admits the mesh: sharded rebuild kicked
        tpu._breakers["mesh"]._next_probe = 0.0
        conn.must(q)                    # triggers the re-admission
        assert sid not in tpu._mesh_demoted
        _settle_repack()
        snap = tpu.snapshot(sid)
        assert snap is not None and snap.sharded_kernel is not None
        m0 = tpu.mesh_served.get("path_all", 0)
        assert sorted(map(repr, conn.must(q).rows)) == ref
        assert tpu.mesh_served["path_all"] == m0 + 1
        assert tpu.breaker_states()["mesh"] == "closed"
    finally:
        for t in list(tpu._prewarm_threads.values()):
            t.join(timeout=300)


# ---------------------------------------------------------------------------
# satellite: transport reconnect backoff
# ---------------------------------------------------------------------------

def test_rpc_reconnect_backoff_dead_listener():
    """Refused sockets used to retry instantly with no pacing: the
    reconnect loop must back off (capped, jittered exponential) and
    count each retry."""
    from nebula_tpu.rpc import transport
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()                           # nothing listens: refused
    c = transport.proxy(f"127.0.0.1:{port}", "svc", timeout=5.0)
    n0 = transport.rpc_stats["reconnects"]
    t0 = time.monotonic()
    with pytest.raises(transport.RpcError):
        c.ping()
    dt = time.monotonic() - t0
    retries = transport.rpc_stats["reconnects"] - n0
    # shared pool (size 4): 5 attempts -> 4 paced retries, min total
    # sleep = (0.02+0.04+0.08+0.16)/2 = 0.15s of jittered backoff
    assert retries == 4
    assert 0.1 < dt < 10.0


def test_rpc_send_fault_point_retries_transparently():
    """An injected transport fault is a ConnectionError subclass, so
    the production reconnect machinery absorbs it — the caller sees a
    successful call, plus a counted reconnect."""
    from nebula_tpu.rpc import transport

    class Echo:
        def ping(self, x):
            return x + 1

    srv = transport.RpcServer().register("svc", Echo()).start()
    try:
        c = transport.proxy(srv.addr, "svc", timeout=5.0)
        assert c.ping(1) == 2           # pool primed, no faults
        faults.set_plan("rpc.send:n=1")
        n0 = transport.rpc_stats["reconnects"]
        assert c.ping(41) == 42         # fault absorbed by the retry
        assert faults.counts()["rpc.send"] == 1
        assert transport.rpc_stats["reconnects"] - n0 >= 1
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# satellite: storage-client _kv_retry backoff + classification stats
# ---------------------------------------------------------------------------

def test_kv_retry_leader_moved_twice():
    from nebula_tpu.storage.client import StorageClient

    class _SM:
        def num_parts(self, s):
            return 1

    client = StorageClient(_SM(), hosts={"h1": "s1", "h2": "s2",
                                         "h3": "s3"},
                           part_to_host=lambda s, p: "h1")
    calls = []
    cls_seq = ["h2", "h3", None]        # leader moved twice, then ok

    def call(svc):
        calls.append(svc)
        return len(calls)

    result = client._kv_retry(1, 1, call, lambda r: cls_seq[r - 1])
    assert result == 3
    assert calls == ["s1", "s2", "s3"]  # both leader hints followed
    assert client.retry_stats["leader_moved"] == 2
    assert client._leader_cache[(1, 1)] == "h3"


def test_kv_retry_hintless_backs_off():
    from nebula_tpu.storage.client import StorageClient

    class _SM:
        def num_parts(self, s):
            return 1

    client = StorageClient(_SM(), hosts={"h1": "s1"},
                           part_to_host=lambda s, p: "h1")
    cls_seq = ["", "", None]            # election in progress x2

    calls = []

    def call(svc):
        calls.append(svc)
        return len(calls)

    t0 = time.monotonic()
    result = client._kv_retry(1, 1, call, lambda r: cls_seq[r - 1])
    dt = time.monotonic() - t0
    assert result == 3
    assert client.retry_stats["hintless"] == 2
    # jittered expo backoff: min (0.05 + 0.1)/2 = 0.075s total
    assert dt >= 0.05
