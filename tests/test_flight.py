"""Flight recorder (ISSUE 10 tentpole): lock-free event ring, trigger
rules, bundle capture + atomic disk dump, aftermath sampling arm, and
the engine-site integration (a breaker trip records AND triggers)."""
import json
import os
import threading
import time

import pytest

from nebula_tpu.common.flags import graph_flags
from nebula_tpu.common.flight import (AFTERMATH_EVENTS, FlightRecorder,
                                      recorder as global_recorder)
from nebula_tpu.common.tracing import tracer


@pytest.fixture
def rec():
    r = FlightRecorder(ring_size=64)
    yield r


@pytest.fixture(autouse=True)
def _isolate():
    """Tests that touch the process-global recorder/tracer/flags leave
    them as found."""
    arm0 = tracer.armed()
    yield
    global_recorder.reset()
    tracer.arm(arm0)
    graph_flags.set("flight_cooldown_s", 30)
    graph_flags.set("flight_dir", "")


def test_ring_is_bounded_and_events_structured(rec):
    for i in range(200):
        rec.record("shed", reason="queue_depth", lane="bulk", space=i)
    d = rec.describe(limit=10)
    assert d["ring"] == 64            # bounded
    assert d["event_count"] == 200    # lifetime
    ev = d["events"][0]               # newest-first
    assert ev["kind"] == "shed" and ev["space"] == 199
    assert ev["ts"] > 0 and ev["seq"] == 200


def test_record_captures_live_trace_id(rec):
    h = tracer.begin("q", force=True)
    try:
        ev = rec.record("deadline_balk", where="kernel")
        assert ev["trace_id"] == h.trace_id
    finally:
        h.finish()
    # unsampled: no trace_id key
    assert "trace_id" not in rec.record("deadline_balk", where="x")


def test_immediate_trigger_captures_bundle_and_arms_sampling(rec):
    rec.add_collector("test.state", lambda: {"answer": 42})
    tracer.arm(0)
    rec.record("noise", x=1)
    rec.record("breaker_trip", feature="go")
    # the skeleton publishes synchronously...
    assert len(rec.bundles) == 1
    b = rec.bundles[-1]
    assert b["trigger"] == "breaker_open"
    assert b["event"]["feature"] == "go"
    # the ring AT fire time rode along
    assert [e["kind"] for e in b["events"]] == ["noise", "breaker_trip"]
    # ...enrichment (collectors/stats/traces) lands on the capture
    # thread — flush before reading it
    assert rec.flush(5.0)
    assert b["collectors"]["test.state"] == {"answer": 42}
    assert "stats" in b and "traces" in b
    # aftermath sampling armed for the next N queries
    assert tracer.armed() == int(graph_flags.get("flight_arm_samples"))


def test_cooldown_one_bundle_per_storm(rec):
    for _ in range(5):
        rec.record("breaker_trip", feature="go")
    assert len(rec.bundles) == 1
    rule = [r for r in rec._rules if r.name == "breaker_open"][0]
    assert rule.fires == 1


def test_windowed_rule_needs_threshold_in_window():
    clock = [1000.0]
    rec = FlightRecorder(ring_size=64, clock=lambda: clock[0])
    # 19 denials: under the shed_storm threshold (20 in 5 s)
    for _ in range(19):
        rec.record("admission_denied", space="abuser")
    assert not rec.bundles
    # the 20th, but 10 s later: the early ones aged out of the window
    clock[0] += 10.0
    rec.record("admission_denied", space="abuser")
    assert not rec.bundles
    # a real storm: 20 shed/denial events inside the window fire once
    for _ in range(20):
        rec.record("shed", reason="wait_p95", lane="bulk", space=1)
    assert len(rec.bundles) == 1
    assert rec.bundles[-1]["trigger"] == "shed_storm"


def test_aftermath_events_append_and_close(rec):
    rec.record("breaker_trip", feature="go")
    b = rec.bundles[-1]
    for i in range(AFTERMATH_EVENTS + 10):
        rec.record("device_failure", feature="go", i=i)
    # exactly the window, then it closed
    assert len(b["aftermath_events"]) == AFTERMATH_EVENTS
    assert b["aftermath_events"][0]["i"] == 0


def test_atomic_disk_dump_and_redump_after_aftermath(tmp_path, rec):
    graph_flags.set("flight_dir", str(tmp_path))
    try:
        rec.record("snapshot_poisoned", space=7)
        assert rec.flush(5.0)   # capture thread writes the artifact
        b = rec.bundles[-1]
        assert b["path"] and os.path.exists(b["path"])
        assert not any(p.endswith(".tmp") for p in os.listdir(tmp_path))
        with open(b["path"]) as f:
            on_disk = json.load(f)
        assert on_disk["trigger"] == "snapshot_poison"
        assert on_disk["aftermath_events"] == []
        # drain the aftermath window -> the artifact is re-dumped with it
        for i in range(AFTERMATH_EVENTS):
            rec.record("device_failure", i=i)
        assert rec.flush(5.0)   # the close re-dump is async too
        with open(b["path"]) as f:
            assert len(json.load(f)["aftermath_events"]) \
                == AFTERMATH_EVENTS
    finally:
        graph_flags.set("flight_dir", "")


def test_manual_trigger_and_get_bundle(rec):
    assert rec.trigger("no_such_rule") == (None, False)
    b, known = rec.trigger("identity_failure")
    assert known and b is not None and b["trigger"] == "identity_failure"
    assert rec.get_bundle(b["id"]) is b
    assert rec.get_bundle(999) is None
    # within the cooldown: known rule, no fresh bundle (the endpoint
    # turns this into a 409, never a stale bundle passed off as new)
    b2, known = rec.trigger("identity_failure")
    assert known and b2 is None


def test_lock_free_record_under_concurrency(rec):
    """8 threads hammering record() — no lock on the hot path, no lost
    ring structure, triggers fire exactly once per cooldown."""
    stop = threading.Event()

    def worker(k):
        for i in range(500):
            rec.record("shed", reason="queue_depth", lane="bulk",
                       space=k)

    ts = [threading.Thread(target=worker, args=(k,)) for k in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    stop.set()
    assert rec.describe()["event_count"] == 4000
    assert len(rec.bundles) == 1     # one shed_storm, cooldown held


def test_engine_breaker_trip_records_and_triggers():
    """Integration: the degradation ladder's trip site feeds the
    recorder — the flight loop's designed entry point."""
    from nebula_tpu.engine_tpu import TpuGraphEngine

    global_recorder.reset()
    eng = TpuGraphEngine()
    eng.breaker_threshold = 1
    try:
        eng._device_failed("go", RuntimeError("injected boom"))
        d = global_recorder.describe()
        kinds = [e["kind"] for e in d["events"]]
        assert "breaker_trip" in kinds
        assert len(global_recorder.bundles) == 1
        assert global_recorder.bundles[-1]["trigger"] == "breaker_open"
        # recovery is an event too (no trigger): force the half-open
        # window open, probe, succeed
        eng._breaker("go")._next_probe = 0.0
        assert eng._breaker("go").allow()
        eng._device_ok("go")
        kinds = [e["kind"]
                 for e in global_recorder.describe()["events"]]
        assert "breaker_recovered" in kinds
    finally:
        global_recorder.reset()


def test_qos_admission_denial_records_event():
    from nebula_tpu.common.qos import admission

    global_recorder.reset()
    admission.set_plan("fr_space:rate=0")
    try:
        ok, retry_ms, _ = admission.admit("fr_space")
        assert not ok
        evs = global_recorder.describe()["events"]
        assert evs[0]["kind"] == "admission_denied"
        assert evs[0]["space"] == "fr_space"
        assert evs[0]["retry_after_ms"] == retry_ms
    finally:
        admission.clear()
        global_recorder.reset()
