"""Bounded-staleness follower reads — the raft read fence
(kvstore/raftex/raft_part.py `read_fence`; docs/manual/12-replication.md
"Follower reads").

The fence is two INDEPENDENT checks: a commit-index fence (everything
the leader last reported committed is applied here — a pure index
comparison no clock lie can forge) and a time lease capped at the
election timeout (the window in which a new leader could have committed
writes this replica hasn't heard about). These tests pin the safety
arguments: the lease can never outlive the election timeout no matter
how loose the operator flag is, a lagging replica is rejected on the
index alone, and the `followerread.stale` fault — a replica LYING about
its time watermark — still bounces off the commit fence
(docs/manual/9-robustness.md)."""
import time

import pytest

from nebula_tpu.common.faults import faults
from nebula_tpu.kvstore.raftex import RaftCode
from raft_fixture import RaftCluster


@pytest.fixture
def cluster3(tmp_path):
    c = RaftCluster(3, tmp_path)
    yield c
    c.stop()


def _follower(c, leader):
    return next(c.parts[a] for a in c.voting if a != leader.addr)


def _wait_granted(part, max_ms, timeout=4.0):
    """Poll until the fence grants (a heartbeat round must carry the
    leader's commit index first)."""
    deadline = time.monotonic() + timeout
    res = part.read_fence(max_ms)
    while not res[0] and time.monotonic() < deadline:
        time.sleep(0.02)
        res = part.read_fence(max_ms)
    return res


def test_leader_always_grants_at_staleness_zero(cluster3):
    leader = cluster3.wait_leader()
    ok, staleness, reason = leader.read_fence(0.001)
    assert ok and staleness == 0.0 and reason == "leader"


def test_caught_up_follower_granted_within_bound(cluster3):
    leader = cluster3.wait_leader()
    assert leader.append_async(b"x").result(timeout=3) is \
        RaftCode.SUCCEEDED
    cluster3.wait_commit(1)
    f = _follower(cluster3, leader)
    ok, staleness, reason = _wait_granted(f, 1000.0)
    assert ok and reason == "follower", (ok, staleness, reason)
    # granted staleness is a real measurement within the bound
    bound = min(1000.0, f._election_timeout * 1000.0)
    assert 0.0 <= staleness <= bound
    assert f.follower_read_stats["granted"] >= 1


def test_lease_never_outlives_election_timeout(cluster3):
    """The safety cap: even with follower_read_max_ms set absurdly
    high, an isolated follower stops granting within the election
    timeout — the window in which a new leader could exist."""
    leader = cluster3.wait_leader()
    f = _follower(cluster3, leader)
    assert _wait_granted(f, 1e9)[0]
    cluster3.isolate(f.addr)
    time.sleep(f._election_timeout + 0.4)
    ok, staleness, reason = f.read_fence(1e9)
    assert not ok and reason == "stale", (ok, staleness, reason)
    assert staleness > f._election_timeout * 1000.0
    assert f.follower_read_stats["rejected_stale"] >= 1


def test_commit_index_fence_rejects_lagging_follower(cluster3):
    leader = cluster3.wait_leader()
    assert leader.append_async(b"y").result(timeout=3) is \
        RaftCode.SUCCEEDED
    cluster3.wait_commit(1)
    f = _follower(cluster3, leader)
    assert _wait_granted(f, 1e9)[0]
    # forge a leader commit index ahead of what this replica applied,
    # with a perfectly FRESH time watermark: the index comparison must
    # reject on its own
    with f._lock:
        f._fence_leader_commit = f.committed_id + 5
        f._fence_caught_up_ts = time.monotonic()
    ok, _staleness, reason = f.read_fence(1e9)
    assert not ok and reason == "commit_fence"
    assert f.follower_read_stats["rejected_commit"] >= 1


def test_partitioned_leader_fences_instead_of_lying(cluster3):
    """A leader cut off from the quorum mid-write must DEMOTE (check-
    quorum) and then refuse reads, never serve from its frozen state:
    the majority side may have elected a new leader and committed
    writes it cannot see (ISSUE 18; docs/manual/12-replication.md
    "Partitions & gray failure")."""
    leader = cluster3.wait_leader()
    assert leader.append_async(b"pre").result(timeout=3) is \
        RaftCode.SUCCEEDED
    cluster3.wait_commit(1)
    cluster3.isolate(leader.addr)
    # check-quorum: no follower ack within 2x election timeout demotes
    deadline = time.monotonic() + leader._election_timeout * 6 + 2.0
    while leader.is_leader() and time.monotonic() < deadline:
        time.sleep(0.02)
    assert not leader.is_leader(), "isolated leader never demoted"
    # the majority side carries on without it
    survivors = [a for a in cluster3.voting if a != leader.addr]
    new_leader = cluster3.wait_leader(among=survivors)
    assert new_leader.append_async(b"during").result(timeout=3) is \
        RaftCode.SUCCEEDED
    cluster3.wait_commit(2, addrs=survivors)
    # the demoted replica's lease lapses within the election timeout;
    # past it the fence must reject — decline, not lie
    time.sleep(leader._election_timeout + 0.3)
    ok, _staleness, reason = leader.read_fence(1e9)
    assert not ok and reason in ("stale", "commit_fence"), \
        (ok, _staleness, reason)
    assert (leader.follower_read_stats["rejected_stale"]
            + leader.follower_read_stats["rejected_commit"]) >= 1
    cluster3.heal(leader.addr)
    cluster3.wait_commit(2)


def test_follower_heal_recovers_watermark(cluster3):
    """An isolated follower stops granting; after heal it catches up
    and the SAME fence grants again with a fresh watermark — the
    recovery half of the partition story."""
    leader = cluster3.wait_leader()
    assert leader.append_async(b"a").result(timeout=3) is \
        RaftCode.SUCCEEDED
    cluster3.wait_commit(1)
    f = _follower(cluster3, leader)
    assert _wait_granted(f, 1e9)[0]
    cluster3.isolate(f.addr)
    for payload in (b"b", b"c", b"d"):
        assert leader.append_async(payload).result(timeout=3) is \
            RaftCode.SUCCEEDED
    cluster3.wait_commit(4, addrs=[a for a in cluster3.voting
                                   if a != f.addr])
    time.sleep(f._election_timeout + 0.3)
    ok, staleness, reason = f.read_fence(1e9)
    assert not ok and reason == "stale", (ok, staleness, reason)
    cluster3.heal(f.addr)
    cluster3.wait_commit(4)          # catch-up includes the follower
    ok, staleness, reason = _wait_granted(f, 1000.0)
    assert ok and reason == "follower", (ok, staleness, reason)
    assert staleness <= min(1000.0, f._election_timeout * 1000.0)
    assert f.committed_id == leader.committed_id


def test_stale_fault_lie_bounces_off_commit_fence(cluster3):
    """`followerread.stale` forges the time watermark (staleness -> 0).
    A lagging replica armed with the lie must STILL be rejected — by
    the commit-index fence alone — proving the two checks are
    independent (the fault-catalog contract)."""
    assert "followerread.stale" in faults.describe()["points"]
    leader = cluster3.wait_leader()
    assert leader.append_async(b"z").result(timeout=3) is \
        RaftCode.SUCCEEDED
    cluster3.wait_commit(1)
    f = _follower(cluster3, leader)
    assert _wait_granted(f, 1e9)[0]
    with f._lock:
        f._fence_leader_commit = f.committed_id + 5
        f._fence_caught_up_ts = time.monotonic() - 999.0  # truly stale
    faults.set_plan("followerread.stale:n=1")
    try:
        ok, staleness, reason = f.read_fence(1e9)
    finally:
        faults.reset()
    assert not ok and reason == "commit_fence", (ok, staleness, reason)
    assert staleness == 0.0            # the lie was told...
    assert f.follower_read_stats["fault_lies"] >= 1
    assert f.follower_read_stats["rejected_commit"] >= 1  # ...and caught
