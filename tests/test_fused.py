"""Fused device-resident serve loop tests (engine_tpu/fused.py;
docs/manual/13-device-speed.md): one launch per dispatcher chunk with
the compiled WHERE masks fused in, fused aggregation partials, the
bounded-recompile signature contract, and the frontier double-buffer
pool's accounting. Everything must stay byte-identical to the CPU
pipe — the fusion moves work, never semantics."""
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from nba_fixture import load_nba
from nebula_tpu.cluster import InProcCluster
from nebula_tpu.engine_tpu import TpuGraphEngine, fused, traverse


def _drain_engine(tpu):
    for t in list(tpu._prewarm_threads.values()):
        t.join(timeout=300)
    for _ in range(600):
        if not tpu._recalibrating:
            return
        time.sleep(0.05)


@pytest.fixture(scope="module")
def fused_pair():
    """(cpu_conn, tpu cluster, tpu conn, engine) with dense routing
    pinned so every plain GO rides the dispatcher's fused windows."""
    _, cpu_conn = load_nba(space="fucpu")
    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    _, conn = load_nba(cluster, space="futpu")
    tpu.sparse_edge_budget = 0   # pin dense: windows, not host pulls
    yield cpu_conn, cluster, conn, tpu
    _drain_engine(tpu)


# ---------------------------------------------------------------------------
# kernel level: in-program lane filters == kernel + host AND
# ---------------------------------------------------------------------------

def test_window_filter_fusion_identity(fused_pair):
    """fused.window_vmap with stacked filter masks must equal the
    unfused kernel followed by the per-request host AND, lane by lane
    (including unfiltered lanes, fsel=-1)."""
    _, cluster, conn, tpu = fused_pair
    conn.must("USE futpu")
    sid = cluster.meta.get_space("futpu").value().space_id
    snap = tpu.snapshot(sid)
    assert snap is not None
    seeds = [[100], [101, 102], [103], [100, 107]]
    f0s = jnp.asarray(np.stack([snap.frontier_from_vids(s)
                                for s in seeds]))
    req = jnp.asarray(traverse.pad_edge_types([1]))
    shape = (snap.num_parts, snap.cap_e)
    rng = np.random.default_rng(7)
    m0 = jnp.asarray(rng.random(shape) > 0.5)
    m1 = jnp.asarray(rng.random(shape) > 0.2)
    fmasks = jnp.stack([m0, m1])
    fsel = jnp.asarray(np.array([0, -1, 1, 0], np.int32))
    got = np.asarray(fused.window_vmap(
        f0s, jnp.int32(2), snap.kernel, req, fmasks, fsel))
    ref_masks = np.asarray(traverse.multi_hop_roots(
        jnp.asarray(np.stack([snap.frontier_from_vids(s)
                              for s in seeds])),
        jnp.int32(2), snap.kernel, req))
    hosts = [np.asarray(m0), None, np.asarray(m1), np.asarray(m0)]
    for i, hm in enumerate(hosts):
        want = ref_masks[i] if hm is None else ref_masks[i] & hm
        assert (got[i] == want).all(), f"lane {i} diverged"


def test_window_lane_filter_fusion_identity(fused_pair):
    """Same contract for the lane-matrix variant (the aligned-layout
    window program the dispatcher launches on TPU)."""
    _, cluster, conn, tpu = fused_pair
    sid = cluster.meta.get_space("futpu").value().space_id
    snap = tpu.snapshot(sid)
    ak, chunk, group = snap.aligned_kernel()
    seeds = [[100], [101, 102], [103, 100]]
    f0s = jnp.asarray(np.stack([snap.frontier_from_vids(s)
                                for s in seeds]))
    req = jnp.asarray(traverse.pad_edge_types([1]))
    rng = np.random.default_rng(11)
    m0 = jnp.asarray(rng.random((snap.num_parts, snap.cap_e)) > 0.4)
    fsel = jnp.asarray(np.array([-1, 0, 0], np.int32))
    got = np.asarray(fused.window_lane(
        f0s, jnp.int32(2), ak, snap.kernel, req, jnp.stack([m0]),
        fsel, chunk=chunk, group=group))
    ref = np.asarray(traverse.multi_hop_masks_batch(
        jnp.asarray(np.stack([snap.frontier_from_vids(s)
                              for s in seeds])),
        jnp.int32(2), ak, snap.kernel, req, chunk=chunk, group=group))
    m0h = np.asarray(m0)
    assert (got[0] == ref[0]).all()
    assert (got[1] == (ref[1] & m0h)).all()
    assert (got[2] == (ref[2] & m0h)).all()


# ---------------------------------------------------------------------------
# engine level: fused windows + fused aggregates vs the CPU pipe
# ---------------------------------------------------------------------------

def test_fused_windows_serve_identically(fused_pair):
    """Concurrent sessions coalesce into fused window launches —
    including a window that MIXES two compilable WHERE shapes and
    unfiltered requests — and every result equals the CPU pipe."""
    cpu_conn, cluster, conn, tpu = fused_pair
    queries = [
        "GO 2 STEPS FROM 100 OVER like YIELD like._dst",
        "GO 2 STEPS FROM 101 OVER like WHERE $$.player.age > 33 "
        "YIELD like._dst, $$.player.age",
        "GO 2 STEPS FROM 102 OVER like WHERE $$.player.age > 30 "
        "YIELD like._dst",
        "GO FROM 100, 101, 102 OVER serve "
        'WHERE $$.team.name == "Spurs" YIELD serve.start_year',
    ]
    expected = {q: sorted(map(repr, cpu_conn.must(q).rows))
                for q in queries}
    before = tpu.stats["fused_launches"]
    errors = []

    def worker(q, reps):
        try:
            c = cluster.connect()
            c.must("USE futpu")
            for _ in range(reps):
                got = sorted(map(repr, c.must(q).rows))
                assert got == expected[q], q
        except Exception as e:   # noqa: BLE001 — surfaced below
            errors.append((q, repr(e)))

    threads = [threading.Thread(target=worker, args=(q, 4))
               for q in queries for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors[:3]
    assert tpu.stats["fused_launches"] > before, tpu.fused_stats()
    assert tpu.stats["batched_dispatches"] > 0


def test_fused_aggregate_identity(fused_pair):
    """The fused ungrouped aggregate program (traversal + err audit +
    exact partials, one launch/one fetch) and the fused grouped
    prologue serve device-side with CPU-identical rows."""
    cpu_conn, _cluster, conn, tpu = fused_pair
    served0 = tpu.stats["agg_served"]
    fused0 = tpu.stats["fused_launches"]
    for q in ("GO FROM 100, 101, 102 OVER serve YIELD "
              "serve.start_year AS y | YIELD COUNT(*) AS n, "
              "SUM($-.y) AS s, MIN($-.y) AS lo, MAX($-.y) AS hi, "
              "AVG($-.y) AS a",
              "GO FROM 100, 101, 102 OVER serve YIELD serve._dst AS t,"
              " serve.start_year AS y | GROUP BY $-.t YIELD $-.t AS t,"
              " COUNT(*) AS n, SUM($-.y) AS s, AVG($-.y) AS a"):
        rc, rt = cpu_conn.must(q), conn.must(q)
        assert sorted(map(repr, rc.rows)) == sorted(map(repr, rt.rows)), \
            (q, rc.rows, rt.rows)
    assert tpu.stats["agg_served"] == served0 + 2, \
        tpu.agg_decline_reasons
    assert tpu.stats["fused_launches"] >= fused0 + 2


def test_fused_agg_err_cells_still_decline(fused_pair):
    """The err-cell audit rides the fused program now — a query whose
    YIELD the CPU walk would raise EvalError for must still decline to
    the CPU pipe (identical rows, agg_declined counted)."""
    cpu_conn, _cluster, conn, tpu = fused_pair
    # add a second schema version so some rows' version lacks the field
    conn.must("ALTER EDGE serve ADD (note int)")
    cpu_conn.must("ALTER EDGE serve ADD (note int)")
    try:
        q = ("GO FROM 100 OVER serve YIELD serve.note AS x | "
             "YIELD COUNT(*) AS n")
        # pre-ALTER rows lack the field: the CPU walk raises EvalError
        # — the fused err audit must DECLINE device serving so the TPU
        # side fails exactly like the CPU side (a data-dependent
        # error, not a silently-wrong device answer)
        declined0 = tpu.stats["agg_declined"]
        with pytest.raises(RuntimeError):
            cpu_conn.must(q)
        with pytest.raises(RuntimeError):
            conn.must(q)
        assert tpu.stats["agg_declined"] > declined0, \
            tpu.agg_decline_reasons
        assert tpu.agg_decline_reasons.get("err_cells", 0) >= 1
    finally:
        conn.must("ALTER EDGE serve DROP (note)")
        cpu_conn.must("ALTER EDGE serve DROP (note)")


# ---------------------------------------------------------------------------
# bounded recompile guard (the recompile-bound contract)
# ---------------------------------------------------------------------------

def test_fused_signature_count_bounded(fused_pair):
    """A mixed workload — varied steps, edge types, WHERE shapes and
    aggregate specs, sequential AND windowed — must keep the fused-
    program signature count under a fixed bound: steps/types/WHERE
    constants are traced operands and WHERE shapes collapse to the
    filter-arity bucket, so only (kind x batch bucket x filter bucket
    x layout) can mint signatures. A recompile-per-window regression
    (e.g. keying on steps or the filter expression) blows well past
    the bound."""
    cpu_conn, cluster, conn, tpu = fused_pair
    cache0 = fused.compile_cache_size()
    sigs0 = set(tpu._fused_signatures)
    mixed = [
        "GO FROM 100 OVER like YIELD like._dst",
        "GO 2 STEPS FROM 100 OVER like YIELD like._dst",
        "GO 3 STEPS FROM 100 OVER like YIELD like._dst",
        "GO 2 STEPS FROM 100 OVER serve YIELD serve._dst",
        "GO FROM 100 OVER like, serve YIELD _dst AS d",
        "GO 2 STEPS FROM 100 OVER like WHERE $$.player.age > 33 "
        "YIELD like._dst",
        "GO 2 STEPS FROM 100 OVER like WHERE $$.player.age > 40 "
        "YIELD like._dst",
        'GO FROM 100 OVER serve WHERE $$.team.name == "Spurs" '
        "YIELD serve._dst",
        "GO FROM 100 OVER serve YIELD serve.start_year AS y | "
        "YIELD COUNT(*) AS n, SUM($-.y) AS s",
        "GO FROM 100 OVER serve YIELD serve.start_year AS y | "
        "YIELD MIN($-.y) AS lo, MAX($-.y) AS hi",
        "GO FROM 100, 101 OVER serve YIELD serve._dst AS t, "
        "serve.start_year AS y | GROUP BY $-.t YIELD $-.t AS t, "
        "COUNT(*) AS n",
    ]
    for q in mixed:
        conn.must(q)
    # the same mix again, concurrently, so windows of varied width form
    def worker(q):
        c = cluster.connect()
        c.must("USE futpu")
        for _ in range(2):
            c.must(q)

    threads = [threading.Thread(target=worker, args=(q,))
               for q in mixed for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    sigs = tpu._fused_signatures
    assert len(sigs) <= 20, sorted(sigs)
    # and the REAL XLA compile cache GROWTH over this workload stays
    # in the same ballpark — a signature that retraced per call would
    # blow past this by an entry per query repetition. Growth, not the
    # absolute size: the jit caches are module-level and carry entries
    # from every other engine/test in the process (including this
    # module's background prewarm, hence the slack)
    grown = fused.compile_cache_size() - cache0
    assert grown <= 2 * len(sigs - sigs0) + 12, \
        (grown, sorted(sigs - sigs0))
    st = tpu.fused_stats()
    assert st["hits"] >= 1 and st["launches"] >= 1
    assert set(st) >= {"hits", "misses", "signatures", "launches",
                       "declined", "xla_cache_entries"}


# ---------------------------------------------------------------------------
# frontier double-buffer pool accounting
# ---------------------------------------------------------------------------

def test_frontier_pool_overlap_accounting():
    """stage() during an in-flight fetch counts as overlapped and
    credits h2d_overlap_us at take(); a launch that was expected to
    donate but left the buffer alive counts a donation fallback."""
    pool = fused.FrontierPool()
    a = np.zeros((2, 2, 4), bool)
    s1 = pool.stage(a)
    s1.take()
    st = pool.snapshot()
    assert st["stages"] == 1 and st["overlapped"] == 0
    pool.fetch_begin()
    try:
        s2 = pool.stage(a)
    finally:
        pool.fetch_end()
    s2.take()
    st = pool.snapshot()
    assert st["overlapped"] == 1
    assert st["h2d_overlap_us"] >= 0
    # the serve loop's OWN prefetch: staged first, then the loop
    # blocks on the current chunk's masks — the fetch beginning AFTER
    # the stage must still count the overlap, at take time
    s3 = pool.stage(a)
    pool.fetch_begin()
    pool.fetch_end()
    s3.take()
    st = pool.snapshot()
    assert st["overlapped"] == 2
    # the buffer was never donated (no launch consumed it): expected-
    # donation audit must count a fallback
    s2.after_launch(donate_expected=True)
    assert pool.snapshot()["donation_fallbacks"] == 1
    # and an expected no-donation launch counts nothing
    s1.after_launch(donate_expected=False)
    assert pool.snapshot()["donation_fallbacks"] == 1


def test_tpu_stats_blocks_present(fused_pair):
    """/tpu_stats-facing accessors carry the fused_programs and
    frontier_prefetch blocks with stable keys (flattened into
    Prometheus by graphd's metric source)."""
    _, _cluster, _conn, tpu = fused_pair
    fs = tpu.fused_stats()
    for k in ("hits", "misses", "signatures", "launches", "declined",
              "xla_cache_entries"):
        assert isinstance(fs[k], int), fs
    ps = tpu.prefetch_stats()
    for k in ("stages", "prefetch_hits", "prefetch_misses",
              "overlapped", "h2d_overlap_us", "donation_fallbacks"):
        assert isinstance(ps[k], int), ps
