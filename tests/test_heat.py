"""Workload & data observatory tests (ISSUE 14, common/heat.py):
space-saving sketch bounds under adversarial streams, heat-slab
window math, skew indices, disarmed byte-identity, the hot_part /
staleness_breach flight triggers, the heartbeat heat carry into
metad's views, the heat-aware BALANCE advisor, and replica staleness
watermarks across a leader change in the raft fixture."""
import time

import pytest

from nebula_tpu.common import heat
from nebula_tpu.common.flags import graph_flags, storage_flags
from nebula_tpu.common.heat import (FIELDS, HeatAccountant, SpaceSaving,
                                    score_of)
from raft_fixture import RaftCluster


@pytest.fixture(autouse=True)
def _heat_isolation():
    """Every test runs with a clean process-global accountant and the
    observatory flags back at defaults afterwards."""
    heat.accountant.reset()
    yield
    heat.accountant.reset()
    for reg in (graph_flags, storage_flags):
        reg.set("heat_enabled", True)
        reg.set("heat_vertices_k", 0)
        reg.set("heat_hot_part_pct", 0)
        reg.set("staleness_breach_ms", 0)


# ------------------------------------------------------------- sketch

def test_space_saving_error_bound_on_rotating_hot_set():
    """Adversarial stream: the hot set ROTATES every phase (the
    classic space-saving stressor — each new hot set must displace
    the old one through the min-counter eviction path). Invariants:
    reported count OVERestimates truth by at most err, and any item
    with true frequency > total/k is tracked."""
    k = 16
    sk = SpaceSaving(k)
    truth: dict = {}
    vid = 10_000
    for phase in range(6):
        hot = [phase * 100 + i for i in range(6)]
        for rep in range(40):
            for h in hot:
                sk.observe(h)
                truth[h] = truth.get(h, 0) + 1
            # two one-off background vids per hot sweep (churn that
            # pressures the eviction path without dominating)
            for _ in range(2):
                sk.observe(vid)
                truth[vid] = 1
                vid += 1
    assert len(sk.counts) <= k            # cardinality cap held
    total = sum(truth.values())
    assert sk.total == total
    tracked = {r["vid"]: r for r in sk.topk()}
    for v, r in tracked.items():
        t = truth.get(v, 0)
        assert r["count"] >= t            # never underestimates
        assert r["count"] - r["err"] <= t  # err bounds the inflation
    # guaranteed-present: anything with true freq > total/k
    for v, t in truth.items():
        if t > total / k:
            assert v in tracked, (v, t, total / k)
    # the final phase's hot set displaced its predecessors
    last_hot = [500 + i for i in range(6)]
    est_top = [r["vid"] for r in sk.topk(6)]
    assert set(last_hot) & set(est_top)


def test_space_saving_cardinality_cap_under_distinct_flood():
    sk = SpaceSaving(32)
    for v in range(10_000):
        sk.observe(v)
        assert len(sk.counts) <= 32
    assert sk.evictions > 0
    assert sk.total == 10_000


def test_observe_vids_disarmed_is_flag_read_only():
    """heat_vertices_k=0 (default): no sketch object is ever created,
    whatever flows through the observe seam."""
    acct = HeatAccountant()
    acct.observe_vids(1, list(range(100)))
    assert acct.sketch(1) is None
    # armed: sketch materializes at the flag's k
    graph_flags.set("heat_vertices_k", 8)
    try:
        acct.observe_vids(1, list(range(100)))
        assert acct.sketch(1) is not None
        assert acct.sketch(1).k == 8
    finally:
        graph_flags.set("heat_vertices_k", 0)


# ------------------------------------------------------------- slabs

def test_slab_windows_roll_and_lifetime_persists():
    t = [1000.0]
    acct = HeatAccountant(clock=lambda: t[0])
    acct.charge(1, 2, reads=5, rows_scanned=100)
    row = acct.parts_snapshot()[0]
    assert row["60s"]["reads"] == 5 and row["600s"]["reads"] == 5
    # +120 s: out of the 60s window, still inside 600s
    t[0] += 120
    row = acct.parts_snapshot()[0]
    assert row["60s"]["reads"] == 0
    assert row["600s"]["reads"] == 5
    # +700 s total: out of every window; lifetime survives
    t[0] += 600
    row = acct.parts_snapshot()[0]
    assert row["600s"]["reads"] == 0
    assert row["life"]["reads"] == 5
    assert row["life"]["rows_scanned"] == 100


def test_charge_parts_splits_evenly_and_score_weights():
    acct = HeatAccountant()
    acct.charge_parts(7, (1, 2), device_us=2000)
    scores = acct.space_scores(600)[7]
    assert scores[1] == scores[2] == pytest.approx(
        score_of({"device_us": 1000}))
    fields = {f: 1 for f in FIELDS}
    assert score_of(fields) == pytest.approx(
        sum(heat.SCORE_WEIGHTS.values()))


def test_skew_index_separates_uniform_from_concentrated():
    acct = HeatAccountant()
    for p in range(1, 9):
        acct.charge(1, p, reads=100)
    uniform = acct.skew_index(1)
    assert uniform["index"] == pytest.approx(1.0, abs=0.01)
    acct2 = HeatAccountant()
    acct2.charge(1, 1, reads=930)
    for p in range(2, 9):
        acct2.charge(1, p, reads=10)
    skewed = acct2.skew_index(1)
    assert skewed["index"] > 4 * uniform["index"]
    # empty space: defined, zeroed
    assert acct2.skew_index(99) == {"index": 0.0, "p99": 0.0,
                                    "mean": 0.0, "parts": 0}


# --------------------------------------------- disarmed byte-identity

def test_disarmed_charges_leave_no_trace():
    """The profile_hz=0 idiom: with heat_enabled=false every charge/
    observe seam is a flag read — no slabs, no sketches, no metric
    families, so /metrics is byte-identical to a heat-free build (the
    gauge source contributes zero families)."""
    graph_flags.set("heat_enabled", False)
    try:
        acct = HeatAccountant()
        acct.charge(1, 1, reads=50, writes=10)
        acct.charge_parts(1, (1, 2, 3), device_us=9000)
        graph_flags.set("heat_vertices_k", 16)
        acct.observe_vids(1, list(range(64)))
        tok = heat.observe_query(1, [1, 2, 3], 4)
        assert tok is None
        heat.charge_device(12345)
        assert acct.parts_snapshot() == []
        assert acct.gauges() == {}
        assert acct.sketch(1) is None
        assert heat.accountant.parts_snapshot() == []
    finally:
        graph_flags.set("heat_enabled", True)
        graph_flags.set("heat_vertices_k", 0)


def test_disarm_after_arming_silences_metric_families():
    """Flipping heat_enabled off mid-flight hides the families on the
    very next scrape (operator kill-switch), even though slab history
    is retained for re-arming."""
    acct = HeatAccountant()
    acct.charge(1, 1, reads=5)
    assert acct.gauges() != {}
    graph_flags.set("heat_enabled", False)
    try:
        assert acct.gauges() == {}
    finally:
        graph_flags.set("heat_enabled", True)
    assert acct.gauges() != {}


# ------------------------------------------------- device attribution

def test_observe_query_notes_parts_and_charges_device():
    tok = heat.observe_query(3, [0, 1, 2, 3, 4, 5, 6, 7], 4)
    try:
        heat.charge_device(4000)
    finally:
        heat.restore(tok)
    # one read per start, spread over its owner part (vid % 4 + 1)
    scores = heat.accountant.space_scores(600)[3]
    assert set(scores) == {1, 2, 3, 4}
    snap = {r["part"]: r for r in heat.accountant.parts_snapshot()}
    assert sum(r["600s"]["reads"] for r in snap.values()) == 8
    assert sum(r["600s"]["device_us"]
               for r in snap.values()) == pytest.approx(4000)
    # outside the note: device charges go nowhere
    heat.charge_device(100000)
    snap2 = {r["part"]: r for r in heat.accountant.parts_snapshot()}
    assert sum(r["600s"]["device_us"]
               for r in snap2.values()) == pytest.approx(4000)


# ------------------------------------------------------ flight wiring

def test_hot_part_trigger_captures_bundle_with_heat_collector():
    from nebula_tpu.common.flight import recorder
    recorder.reset()
    graph_flags.set("heat_hot_part_pct", 50)
    try:
        # one part draws ~97% of the space's 60s heat, over the floor
        heat.accountant.charge(5, 1, reads=400)
        heat.accountant.charge(5, 2, reads=10)
        heat.accountant.check_hot_part(5)
        assert recorder.flush(5)
        bundles = [b for b in recorder.bundles
                   if b["trigger"] == "hot_part"]
        assert bundles, recorder.describe()
        b = bundles[-1]
        assert b["event"]["space"] == 5 and b["event"]["part"] == 1
        assert b["event"]["share"] > 90
        # the registered collector embeds the /heat capture
        assert "heat" in b.get("collectors", {})
        assert b["collectors"]["heat"]["parts"]
    finally:
        graph_flags.set("heat_hot_part_pct", 0)
        recorder.reset()


def test_hot_part_disarmed_and_idle_space_never_fire():
    from nebula_tpu.common.flight import recorder
    recorder.reset()
    # disarmed (pct=0): nothing fires no matter the concentration
    heat.accountant.charge(6, 1, reads=500)
    heat.accountant.check_hot_part(6)
    # armed but under the minimum-score floor: idle spaces are quiet
    graph_flags.set("heat_hot_part_pct", 10)
    try:
        heat.accountant.charge(7, 1, reads=3)
        heat.accountant.check_hot_part(7)
        recorder.flush(5)
        assert not [b for b in recorder.bundles
                    if b["trigger"] == "hot_part"]
    finally:
        graph_flags.set("heat_hot_part_pct", 0)
        recorder.reset()


# ------------------------------------- heartbeat carry + metad views

def _meta_with_heat():
    from nebula_tpu.meta.service import MetaService
    meta = MetaService(expired_threshold_secs=3600)
    hosts = ["10.1.0.1:1", "10.1.0.2:1"]
    for h in hosts:
        meta.heartbeat(h, "storage")
    sid = meta.create_space("hv", partition_num=4,
                            replica_factor=2).value()
    alloc = meta.get_parts_alloc(sid)
    leaders = {p: hs[0] for p, hs in alloc.items()}
    for h in hosts:
        led = sorted(p for p, l in leaders.items() if l == h)
        payload = {
            "parts": {sid: {p: {"reads": 10.0 * p, "score": 10.0 * p}
                            for p in led}},
            "staleness": {sid: {p: {"max_ms": 7.5 * p,
                                    "replicas": {"r": 7.5 * p}}
                                for p in led}},
        }
        meta.heartbeat(h, "storage", leader_parts={sid: led},
                       part_heat=payload)
    return meta, sid, hosts, leaders


def test_heartbeat_heat_carry_feeds_meta_views():
    meta, sid, hosts, leaders = _meta_with_heat()
    ho = {h["host"]: h for h in meta.hosts_overview()}
    for h in hosts:
        led = [p for p, l in leaders.items() if l == h]
        assert ho[h]["leader_heat"] == pytest.approx(
            sum(10.0 * p for p in led), abs=0.1)
    rows = meta.parts_overview(sid)
    assert len(rows[0]) == 6            # + heat, staleness columns
    for pid, leader, _hosts, _losts, score, stale in rows:
        assert score == pytest.approx(10.0 * pid, abs=0.1)
        assert stale == pytest.approx(7.5 * pid, abs=0.1)
    hv = meta.heat_overview()
    assert set(hv["hosts"]) == set(hosts)
    assert hv["staleness"]
    # a malformed payload never fails the beat or poisons the view
    st = meta.heartbeat(hosts[0], "storage", part_heat="garbage")
    assert st.ok()
    assert set(meta.heat_overview()["hosts"]) == set(hosts)


def test_heat_advisor_reduces_modeled_spread():
    meta, sid, hosts, leaders = _meta_with_heat()
    from nebula_tpu.meta.balancer import Balancer
    bal = Balancer(meta, admin=None)
    meta.attach_balancer(bal)
    # make host 1 deliberately hot: re-beat with a skewed ladder
    led0 = sorted(p for p, l in leaders.items() if l == hosts[0])
    meta.heartbeat(
        hosts[0], "storage", leader_parts={sid: led0},
        part_heat={"parts": {sid: {p: {"score": 200.0 + i}
                                   for i, p in enumerate(led0)}}})
    advise = meta.balance_advise_heat().value()
    assert advise["advisory"] is True
    assert advise["moves"], advise
    assert advise["spread_after"] < advise["spread_before"]
    for m in advise["moves"]:
        assert m["src"] != m["dst"] and m["score"] > 0
        assert m["kind"] in ("leader", "move")
    # modeled totals are conserved: moves shuffle heat, never mint it
    assert sum(advise["planned"].values()) == pytest.approx(
        sum(advise["current"].values()), abs=0.5)


def test_disarmed_storage_beat_drops_meta_heat_view():
    """The disarm kill-switch reaches metad: once a storage node's
    heartbeats stop carrying part_heat (heat_enabled=false ->
    heat_source returns None), its frozen telemetry leaves SHOW
    HOSTS/PARTS and the advisor within one beat."""
    meta, sid, hosts, leaders = _meta_with_heat()
    assert set(meta.heat_overview()["hosts"]) == set(hosts)
    meta.heartbeat(hosts[0], "storage")          # no part_heat field
    assert set(meta.heat_overview()["hosts"]) == {hosts[1]}
    ho = {h["host"]: h for h in meta.hosts_overview()}
    assert ho[hosts[0]]["leader_heat"] == 0.0
    # graph-role beats never clear storage telemetry
    meta.heartbeat("10.9.9.9:1", "graph")
    assert set(meta.heat_overview()["hosts"]) == {hosts[1]}


def test_heat_advisor_prefers_replica_holder_over_cooler_nonreplica():
    """Among spread-improving destinations, a replica holder wins
    outright (a TRANS_LEADER-shaped move) even when a non-replica
    host would model slightly cooler — the preference is real, not a
    float-equality tie-break."""
    from types import SimpleNamespace

    from nebula_tpu.meta.balancer import Balancer

    class FakeMeta:
        def heat_overview(self):
            return {"hosts": {
                "A": {"parts": {"1:1": 30.0, "1:3": 20.0},
                      "total": 50.0},
                "B": {"parts": {"1:2": 4.0}, "total": 4.0},
                "C": {"parts": {}, "total": 0.0},
            }, "staleness": []}

        def list_spaces(self):
            return [SimpleNamespace(space_id=1)]

        def get_parts_alloc(self, sid):
            return {1: ["A", "B"], 2: ["B", "C"], 3: ["A", "C"]}

    bal = Balancer(FakeMeta(), admin=None,
                   get_active_hosts=lambda: ["A", "B", "C"])
    advise = bal.advise_heat()
    assert advise["moves"], advise
    m = advise["moves"][0]
    # part 1 (score 30) off hot host A: C models cooler after the
    # move, but B holds a replica — B must win, as kind="leader"
    assert (m["space"], m["part"]) == (1, 1)
    assert m["dst"] == "B" and m["kind"] == "leader"
    assert advise["spread_after"] < advise["spread_before"]


def test_heat_advisor_empty_view_is_a_noop_plan():
    from nebula_tpu.meta.balancer import Balancer
    from nebula_tpu.meta.service import MetaService
    meta = MetaService(expired_threshold_secs=3600)
    meta.heartbeat("10.2.0.1:1", "storage")
    meta.attach_balancer(Balancer(meta, admin=None))
    advise = meta.balance_advise_heat().value()
    assert advise["moves"] == []
    assert advise["spread_after"] == advise["spread_before"]


def test_balance_data_heat_parses():
    from nebula_tpu.parser import GQLParser
    from nebula_tpu.parser.ast import BalanceSentence

    def parse(text):
        return GQLParser().parse(text).sentences[0]

    s = parse("BALANCE DATA heat")
    assert isinstance(s, BalanceSentence) and s.sub == "HEAT"
    assert "heat" in s.to_string()
    s2 = parse("BALANCE DATA")
    assert s2.sub == "DATA"


# ------------------------------------------- staleness watermarks

def test_staleness_watermarks_across_leader_change(tmp_path):
    """Leader-side replica watermarks: caught-up followers read ~0
    staleness, an isolated follower's staleness grows with wall time
    and its applied watermark pins at the pre-partition commit; after
    a LEADER CHANGE the new leader owns the measurement (the old
    leader reports none) and the healed replica's staleness collapses
    once it catches up."""
    c = RaftCluster(3, tmp_path)
    try:
        leader = c.wait_leader()
        for i in range(5):
            assert leader.append_async(b"w%d" % i).result(timeout=3) \
                .name == "SUCCEEDED"
        c.wait_commit(5)
        time.sleep(0.2)                 # one replication round
        marks = leader.replica_watermarks()
        assert len(marks) == 2
        for m in marks:
            assert m["applied"] == m["commit"] == leader.committed_id
            assert m["lag"] == 0
            assert m["staleness_ms"] < 2000
        # isolate one follower; its watermark must stall and age
        behind = marks[0]["addr"]
        c.isolate(behind)
        pre_commit = leader.committed_id
        for i in range(3):
            assert leader.append_async(b"x%d" % i).result(timeout=3) \
                .name == "SUCCEEDED"
        time.sleep(0.6)
        by_addr = {m["addr"]: m for m in leader.replica_watermarks()}
        assert by_addr[behind]["lag"] >= 3
        assert by_addr[behind]["applied"] <= pre_commit
        assert by_addr[behind]["staleness_ms"] >= 400
        healthy = [a for a in by_addr if a != behind][0]
        assert by_addr[healthy]["lag"] == 0
        assert by_addr[healthy]["staleness_ms"] < \
            by_addr[behind]["staleness_ms"]
        # status_with_replicas surfaces the same marks (the /raft row)
        st = leader.status_with_replicas()
        assert st["staleness_ms"] == pytest.approx(
            max(m["staleness_ms"] for m in st["replicas"]), abs=50)
        # ---- leader change: depose the current leader
        c.heal(behind)
        old = leader.addr
        c.isolate(old)
        others = [a for a in c.voting if a != old]
        new_leader = c.wait_leader(among=others)
        c.heal(old)
        c.wait_commit(8, addrs=others)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            nm = {m["addr"]: m for m in
                  new_leader.replica_watermarks()}
            if old in nm and nm[old]["lag"] == 0 and \
                    nm[behind if behind != new_leader.addr
                       else old]["lag"] == 0:
                break
            time.sleep(0.05)
        nm = {m["addr"]: m for m in new_leader.replica_watermarks()}
        assert set(nm) == {a for a in c.voting
                           if a != new_leader.addr}
        for m in nm.values():
            assert m["lag"] == 0, nm
        # the deposed leader measures nothing
        time.sleep(0.3)
        assert c.parts[old].replica_watermarks() == []
        assert c.parts[old].status_with_replicas()["replicas"] == []
    finally:
        c.stop()


def test_staleness_breach_flight_event(tmp_path):
    """staleness_breach_ms armed: a follower held behind long enough
    records a breach event that fires the flight rule."""
    from nebula_tpu.common.flight import recorder
    recorder.reset()
    storage_flags.set("staleness_breach_ms", 200)
    graph_flags.set("staleness_breach_ms", 200)
    c = RaftCluster(3, tmp_path)
    try:
        leader = c.wait_leader()
        assert leader.append_async(b"a").result(timeout=3).name == \
            "SUCCEEDED"
        c.wait_commit(1)
        behind = [a for a in c.voting if a != leader.addr][0]
        c.isolate(behind)
        assert leader.append_async(b"b").result(timeout=3).name == \
            "SUCCEEDED"
        deadline = time.monotonic() + 6
        ev = None
        while time.monotonic() < deadline and ev is None:
            ev = next((e for e in list(recorder._ring)
                       if e["kind"] == "staleness_breach"), None)
            time.sleep(0.1)
        assert ev is not None, recorder.describe()
        assert ev["replica"] == behind
        assert ev["staleness_ms"] > 200
        recorder.flush(5)
        assert [b for b in recorder.bundles
                if b["trigger"] == "staleness_breach"]
    finally:
        c.stop()
        graph_flags.set("staleness_breach_ms", 0)
        storage_flags.set("staleness_breach_ms", 0)
        recorder.reset()


def test_raft_append_charges_write_heat(tmp_path):
    c = RaftCluster(3, tmp_path)
    try:
        leader = c.wait_leader()
        heat.accountant.reset()
        for i in range(4):
            assert leader.append_async(b"h%d" % i).result(timeout=3) \
                .name == "SUCCEEDED"
        snap = {(r["space"], r["part"]): r
                for r in heat.accountant.parts_snapshot()}
        assert snap[(1, 1)]["600s"]["raft_appends"] >= 4
    finally:
        c.stop()


# -------------------------------------------------- degree-skew stats

def test_degree_stats_once_per_build():
    import numpy as np

    from nebula_tpu.cluster import InProcCluster
    from nebula_tpu.engine_tpu import TpuGraphEngine

    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    conn = cluster.connect()
    conn.must("CREATE SPACE deg(partition_num=2, replica_factor=1)")
    conn.must("USE deg")
    conn.must("CREATE TAG person(age int)")
    conn.must("CREATE EDGE knows(ts int)")
    conn.must("INSERT VERTEX person(age) VALUES " + ", ".join(
        f"{i}:({i})" for i in range(20)))
    # vid 0 is the hub: degree 12; everyone else degree <= 2
    edges = [(0, d) for d in range(1, 13)] + [(5, 6), (7, 8), (7, 9)]
    conn.must("INSERT EDGE knows(ts) VALUES " + ", ".join(
        f"{s} -> {d}:({i})" for i, (s, d) in enumerate(edges)))
    sid = cluster.meta.get_space("deg").value().space_id
    tpu.prewarm(sid, block=True)
    snap = tpu.snapshot(sid)
    ds = snap.degree_stats
    assert ds["max"] == 12
    # 2x stored rows: every forward edge has a reverse copy under the
    # dst vid (negative etype) — the stats describe the built layout
    assert ds["edges"] == 2 * len(edges)
    assert ds["vertices"] == 20
    assert ds["cap_e"] == snap.cap_e
    hubs = ds["hubs"]
    assert hubs[0]["vid"] == 0 and hubs[0]["out_degree"] == 12
    assert hubs[0]["cap_e_share"] == pytest.approx(12 / snap.cap_e,
                                                   abs=1e-4)
    assert all(hubs[i]["out_degree"] >= hubs[i + 1]["out_degree"]
               for i in range(len(hubs) - 1))
    assert ds["p99"] <= ds["max"] and ds["mean"] > 0
