"""Device secondary-index subsystem tests (ISSUE 17; engine_tpu/
index.py, docs/manual/16-indexes.md): DDL through the metad catalog
(including a metad restart round-trip), LOOKUP / GET SUBGRAPH / MATCH
byte-identity between the device sorted-array path and the storaged
CPU-scan twin (narrow, forced-wide and meshed builds), the
write-invalidates-index regression, fault degradation through the
"index" breaker (device failure NEVER reaches a client), and
shadow-read sampling of the new verbs."""
import time

import pytest

from nba_fixture import load_nba
from nebula_tpu.cluster import InProcCluster
from nebula_tpu.common import consistency as cons
from nebula_tpu.common.faults import faults
from nebula_tpu.common.flags import graph_flags
from nebula_tpu.common.status import ErrorCode
from nebula_tpu.engine_tpu import TpuGraphEngine, csr
from nebula_tpu.engine_tpu import distributed as dist
from nebula_tpu.parser import GQLParser, ast


def _drain_engine(tpu):
    for t in list(tpu._prewarm_threads.values()):
        t.join(timeout=300)
    for _ in range(600):
        if not tpu._recalibrating:
            return
        time.sleep(0.05)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


INDEX_DDL = [
    "CREATE TAG INDEX player_age ON player(age)",
    "CREATE TAG INDEX player_name ON player(name)",
    "CREATE EDGE INDEX serve_start ON serve(start_year)",
]

# every index-verb shape in one sweep: range + equality LOOKUP over
# int and string props (dict-coded on device), reversed operands,
# no-yield and aliased yields, an edge LOOKUP (storaged scan on both
# pipes), bounded subgraph expansions, and the supported MATCH subset
LOOKUP_SUITE = [
    "LOOKUP ON player WHERE player.age > 33 "
    "YIELD player.name, player.age",
    "LOOKUP ON player WHERE player.age >= 36 YIELD player.age",
    "LOOKUP ON player WHERE player.age < 30 YIELD player.name AS n",
    "LOOKUP ON player WHERE player.age <= 27",
    "LOOKUP ON player WHERE player.age == 32 YIELD player.name",
    "LOOKUP ON player WHERE 36 <= player.age YIELD player.age AS a",
    'LOOKUP ON player WHERE player.name == "Tim Duncan" '
    "YIELD player.age",
    "LOOKUP ON serve WHERE serve.start_year >= 2000 "
    "YIELD serve.start_year",
]
SUBGRAPH_SUITE = [
    "GET SUBGRAPH FROM 100",
    "GET SUBGRAPH 2 STEPS FROM 100 OVER like",
    "GET SUBGRAPH 3 STEPS FROM 100, 101 OVER like, serve",
    "GET SUBGRAPH 2 STEPS FROM 121",
]
MATCH_SUITE = [
    'MATCH (a:player {name: "Tim Duncan"})-[e:like]->(b) RETURN a, b',
    "MATCH (a:player {age: 36})-[e*1..2]->(b) RETURN a.name, b",
    "MATCH (a:player {age: 33})-[e:like|:serve*2]->(b) RETURN a, b",
]


def _suite(conn, queries):
    return {q: sorted(map(repr, conn.must(q).rows)) for q in queries}


@pytest.fixture(scope="module")
def pair():
    """CPU-only and TPU clusters over identical NBA data, indexes
    created on both (read-only: mutation tests build their own)."""
    _, cpu_conn = load_nba(space="idxcpu")
    for q in INDEX_DDL:
        cpu_conn.must(q)
    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    _, conn = load_nba(cluster, space="idxtpu")
    for q in INDEX_DDL:
        conn.must(q)
    sid = cluster.meta.get_space("idxtpu").value().space_id
    tpu.prewarm(sid, block=True)
    yield cpu_conn, conn, tpu, cluster
    _drain_engine(tpu)


# ---------------------------------------------------------------------------
# parser round-trips
# ---------------------------------------------------------------------------

def parse1(text):
    seq = GQLParser().parse(text)
    assert len(seq.sentences) == 1
    return seq.sentences[0]


def test_parse_lookup_roundtrip():
    s = parse1("LOOKUP ON player WHERE player.age > 33 "
               "YIELD player.name AS n, player.age")
    assert isinstance(s, ast.LookupSentence)
    assert s.on_name == "player"
    assert s.where is not None and s.yield_ is not None
    assert "LOOKUP ON player" in s.to_string()


def test_parse_get_subgraph_roundtrip():
    s = parse1("GET SUBGRAPH 3 STEPS FROM 100, 101 OVER like, serve")
    assert isinstance(s, ast.GetSubgraphSentence)
    assert s.step.steps == 3
    assert [v.to_string() for v in s.from_.vids] == ["100", "101"]
    assert [e.name for e in s.over.edges] == ["like", "serve"]
    s2 = parse1("GET SUBGRAPH FROM 7")
    assert s2.step.steps == 1 and s2.over.is_all


def test_parse_match_structured_subset():
    s = parse1('MATCH (a:player {name: "x"})-[e:like*1..3]->(b) '
               "RETURN a, b.name")
    assert isinstance(s, ast.MatchSentence)
    p = s.pattern
    assert p is not None
    assert (p.src_alias, p.tag, p.prop) == ("a", "player", "name")
    assert p.edge_names == ["like"]
    assert (p.min_hops, p.max_hops) == (1, 3)
    assert p.dst_alias == "b"
    assert len(s.return_.columns) == 2


def test_parse_match_unsupported_keeps_raw():
    s = parse1("MATCH (a)-[e]->(b) WHERE a.x > 1 RETURN a")
    assert isinstance(s, ast.MatchSentence)
    assert s.pattern is None      # grammar-level stub: parses, raw


def test_parse_index_ddl():
    s = parse1("CREATE TAG INDEX pa ON player(age)")
    assert isinstance(s, ast.CreateIndexSentence)
    assert (s.is_edge, s.name, s.schema_name, s.fields) == \
        (False, "pa", "player", ["age"])
    s = parse1("CREATE EDGE INDEX IF NOT EXISTS sl ON serve"
               "(start_year, end_year)")
    assert s.is_edge and s.if_not_exists
    assert s.fields == ["start_year", "end_year"]
    s = parse1("DROP TAG INDEX IF EXISTS pa")
    assert isinstance(s, ast.DropIndexSentence)
    assert not s.is_edge and s.if_exists and s.name == "pa"


# ---------------------------------------------------------------------------
# DDL through the metad catalog
# ---------------------------------------------------------------------------

def test_ddl_show_create_drop():
    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    _, conn = load_nba(cluster, space="idxddl")
    conn.must("CREATE TAG INDEX pa ON player(age)")
    conn.must("CREATE EDGE INDEX sl ON serve(start_year)")
    rows = conn.must("SHOW TAG INDEXES").rows
    assert [(r[1], r[2], r[3]) for r in rows] == \
        [("pa", "player", "age")]
    erows = conn.must("SHOW EDGE INDEXES").rows
    assert [(r[1], r[2], r[3]) for r in erows] == \
        [("sl", "serve", "start_year")]

    assert conn.execute("CREATE TAG INDEX pa ON player(age)").code \
        == ErrorCode.E_EXISTED
    conn.must("CREATE TAG INDEX IF NOT EXISTS pa ON player(age)")
    assert not conn.execute(
        "CREATE TAG INDEX bad ON player(nope)").ok()
    assert conn.execute(
        "CREATE TAG INDEX bad ON ghost(age)").code \
        == ErrorCode.E_TAG_NOT_FOUND

    conn.must("DROP TAG INDEX pa")
    assert conn.must("SHOW TAG INDEXES").rows == []
    assert not conn.execute("DROP TAG INDEX pa").ok()
    conn.must("DROP TAG INDEX IF EXISTS pa")
    _drain_engine(tpu)


def test_ddl_survives_metad_restart():
    """The catalog rides the meta KV: a fresh MetaService over the
    same store (same-dir metad restart) sees identical descriptors."""
    from nebula_tpu.meta.service import MetaService
    cluster, conn = load_nba(space="idxmeta")
    conn.must("CREATE TAG INDEX pa ON player(age)")
    conn.must("CREATE EDGE INDEX sl ON serve(start_year)")
    sid = cluster.meta.get_space("idxmeta").value().space_id
    before = sorted(cluster.meta.list_indexes(sid),
                    key=lambda d: d["index_id"])
    assert [d["name"] for d in before] == ["pa", "sl"]
    restarted = MetaService(store=cluster.meta._store)
    after = sorted(restarted.list_indexes(sid),
                   key=lambda d: d["index_id"])
    assert after == before


def test_lookup_without_index_is_client_error(pair):
    cpu_conn, conn, _, _ = pair
    q = 'LOOKUP ON team WHERE team.name == "Spurs"'
    for c in (cpu_conn, conn):
        r = c.execute(q)
        assert r.code == ErrorCode.E_INDEX_NOT_FOUND, r.error_msg


# ---------------------------------------------------------------------------
# per-verb TPU-vs-CPU byte identity
# ---------------------------------------------------------------------------

def test_lookup_identity(pair):
    cpu_conn, conn, tpu, _ = pair
    assert _suite(conn, LOOKUP_SUITE) == _suite(cpu_conn, LOOKUP_SUITE)
    # tag lookups genuinely rode the device index, not a fallback tie
    assert tpu.stats["lookup_served"] > 0
    assert tpu.stats["index_builds"] > 0
    assert tpu.stats["index_hits"] > 0


def test_subgraph_identity(pair):
    cpu_conn, conn, tpu, _ = pair
    assert _suite(conn, SUBGRAPH_SUITE) == \
        _suite(cpu_conn, SUBGRAPH_SUITE)
    assert tpu.stats["subgraph_served"] > 0


def test_match_identity(pair):
    cpu_conn, conn, _, _ = pair
    assert _suite(conn, MATCH_SUITE) == _suite(cpu_conn, MATCH_SUITE)


def test_lookup_rows_shape(pair):
    """Headers + row ordering are part of the identity contract:
    VertexID first, rows sorted by vid, yields in YIELD order."""
    _, conn, _, _ = pair
    r = conn.must("LOOKUP ON player WHERE player.age >= 36 "
                  "YIELD player.name, player.age")
    assert r.columns == ["VertexID", "player.name", "player.age"]
    vids = [row[0] for row in r.rows]
    assert vids == sorted(vids)
    assert [100, "Tim Duncan", 42] in r.rows


def test_subgraph_rows_shape(pair):
    _, conn, _, _ = pair
    r = conn.must("GET SUBGRAPH 2 STEPS FROM 100 OVER like")
    assert r.columns == ["Step", "SrcVID", "EdgeName", "Ranking",
                         "DstVID"]
    steps = sorted({row[0] for row in r.rows})
    assert steps == [1, 2]
    assert all(row[2] == "like" for row in r.rows)


def test_wide_csr_lookup_identity():
    """NEBULA_TPU_WIDE_CSR=1 (forced int32 packing): the index rides
    the same per-snapshot columns, so the whole verb suite must stay
    identical to the device's own CPU twin."""
    old = csr.FORCE_WIDE_DTYPES
    csr.FORCE_WIDE_DTYPES = True
    try:
        tpu = TpuGraphEngine()
        cluster = InProcCluster(tpu_engine=tpu)
        _, conn = load_nba(cluster, space="idxwide")
        for q in INDEX_DDL:
            conn.must(q)
        sid = cluster.meta.get_space("idxwide").value().space_id
        tpu.prewarm(sid, block=True)
        queries = LOOKUP_SUITE + SUBGRAPH_SUITE
        dev = _suite(conn, queries)
        tpu.enabled = False
        try:
            ref = _suite(conn, queries)
        finally:
            tpu.enabled = True
        assert dev == ref
        assert tpu.stats["lookup_served"] > 0
        assert tpu.stats["subgraph_served"] > 0
    finally:
        csr.FORCE_WIDE_DTYPES = old
    _drain_engine(tpu)


def test_meshed_lookup_subgraph_identity():
    """Meshed/sharded snapshots: LOOKUP serves off the host columns'
    sorted arrays and GET SUBGRAPH through the sharded kernel — both
    byte-identical to a plain CPU cluster."""
    _, cpu_conn = load_nba(space="idxmcpu", parts=8)
    for q in INDEX_DDL:
        cpu_conn.must(q)
    tpu = TpuGraphEngine(mesh=dist.make_mesh())
    cluster = InProcCluster(tpu_engine=tpu)
    _, conn = load_nba(cluster, space="idxmtpu", parts=8)
    for q in INDEX_DDL:
        conn.must(q)
    try:
        sid = cluster.meta.get_space("idxmtpu").value().space_id
        tpu.prewarm(sid, block=True)
        assert tpu.snapshot(sid).sharded_kernel is not None
        queries = LOOKUP_SUITE + SUBGRAPH_SUITE
        assert _suite(conn, queries) == _suite(cpu_conn, queries)
        assert tpu.stats["lookup_served"] > 0
        assert tpu.stats["subgraph_served"] > 0
    finally:
        _drain_engine(tpu)


# ---------------------------------------------------------------------------
# write invalidation
# ---------------------------------------------------------------------------

def test_write_invalidates_index():
    """INSERT between two identical LOOKUPs: the sorted arrays drop
    (counted), the rebuild includes the new vertex, and the device
    result stays identical to the CPU scan."""
    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    _, conn = load_nba(cluster, space="idxwrite")
    conn.must("CREATE TAG INDEX pa ON player(age)")
    sid = cluster.meta.get_space("idxwrite").value().space_id
    tpu.prewarm(sid, block=True)
    q = "LOOKUP ON player WHERE player.age == 97 YIELD player.age"
    assert conn.must(q).rows == []
    inv0 = tpu.index_stats()["invalidations"]
    conn.must('INSERT VERTEX player(name, age) VALUES '
              '999888:("Old Man", 97)')
    after = conn.must(q).rows
    tpu.enabled = False
    try:
        cpu_after = conn.must(q).rows
    finally:
        tpu.enabled = True
    assert after == cpu_after == [[999888, 97]]
    assert tpu.index_stats()["invalidations"] > inv0
    # the rebuilt index (not a decline) served the post-write query
    assert tpu.stats["lookup_served"] >= 2
    _drain_engine(tpu)


# ---------------------------------------------------------------------------
# fault degradation (common/faults.py index.build / index.search)
# ---------------------------------------------------------------------------

def _fault_cluster(space):
    tpu = TpuGraphEngine()
    tpu.breaker_threshold = 2
    tpu.breaker_base_s = 0.1
    tpu.breaker_max_s = 0.5
    cluster = InProcCluster(tpu_engine=tpu)
    _, conn = load_nba(cluster, space=space)
    conn.must("CREATE TAG INDEX pa ON player(age)")
    sid = cluster.meta.get_space(space).value().space_id
    tpu.prewarm(sid, block=True)
    return tpu, conn


def test_index_search_fault_degrades_then_recovers():
    """index.search faults: every LOOKUP still succeeds with rows
    identical to the CPU scan (never a client error), the "index"
    breaker trips, and a half-open probe re-admits the device."""
    tpu, conn = _fault_cluster("idxflt1")
    q = LOOKUP_SUITE[0]
    ref = sorted(map(repr, conn.must(q).rows))
    served0 = tpu.stats["lookup_served"]
    trips0 = tpu.stats["breaker_trips"]
    faults.set_plan("index.search:p=1")
    try:
        for _ in range(5):
            tpu.result_cache.clear()
            r = conn.execute(q)
            assert r.ok(), r.error_msg
            assert sorted(map(repr, r.rows)) == ref
    finally:
        faults.clear()
    assert tpu.stats["breaker_trips"] > trips0
    assert tpu.stats["lookup_served"] == served0   # all degraded
    deadline = time.time() + 30
    recovered = False
    while time.time() < deadline:
        tpu.result_cache.clear()
        conn.must(q)
        if tpu.stats["lookup_served"] > served0:
            recovered = True
            break
        time.sleep(0.05)
    assert recovered, tpu.breaker_states()
    _drain_engine(tpu)


def test_index_build_fault_degrades_to_scan():
    """A failing index BUILD never surfaces: the engine declines and
    the storaged scan serves identical rows."""
    tpu, conn = _fault_cluster("idxflt2")
    q = "LOOKUP ON player WHERE player.age > 40 YIELD player.name"
    tpu.enabled = False
    try:
        ref = sorted(map(repr, conn.must(q).rows))
    finally:
        tpu.enabled = True
    # drop the prebuilt arrays so the next serve must rebuild —
    # straight into the armed build fault
    for snap in list(tpu._snapshots.values()):
        tpu._invalidate_prop_indexes(snap)
    faults.set_plan("index.build:p=1")
    try:
        tpu.result_cache.clear()
        r = conn.execute(q)
        assert r.ok(), r.error_msg
        assert sorted(map(repr, r.rows)) == ref
    finally:
        faults.clear()
    _drain_engine(tpu)


# ---------------------------------------------------------------------------
# shadow-read sampling of the new verbs (PR 15 observatory)
# ---------------------------------------------------------------------------

def test_shadow_samples_lookup_and_subgraph():
    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    _, conn = load_nba(cluster, space="idxshadow")
    conn.must("CREATE TAG INDEX pa ON player(age)")
    sid = cluster.meta.get_space("idxshadow").value().space_id
    tpu.prewarm(sid, block=True)
    cons.shadow.reset()
    graph_flags.set("shadow_read_rate", 1.0)
    try:
        conn.must(LOOKUP_SUITE[0])
        conn.must("GET SUBGRAPH 2 STEPS FROM 100 OVER like")
        assert cons.shadow.drain(15)
        deadline = time.time() + 10
        while time.time() < deadline and \
                cons.shadow.stats()["verified"] < 2:
            time.sleep(0.05)
        st = cons.shadow.stats()
        assert st["sampled"] >= 2, st
        assert st["verified"] >= 2, st
        assert st["mismatches"] == 0 and st["errors"] == 0, st
    finally:
        graph_flags.set("shadow_read_rate", 0.0)
    _drain_engine(tpu)
