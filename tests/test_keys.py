"""Key codec golden tests (parity model: common/base/test/NebulaKeyUtilsTest.cpp)."""
import pytest

from nebula_tpu.common import keys


def test_vertex_key_roundtrip():
    k = keys.vertex_key(3, 12345, 7, version=99)
    assert keys.is_vertex_key(k)
    assert not keys.is_edge_key(k)
    assert keys.parse_vertex_key(k) == (3, 12345, 7, 99)


def test_vertex_key_negative_vid():
    k = keys.vertex_key(1, -42, 2, version=5)
    assert keys.parse_vertex_key(k) == (1, -42, 2, 5)


def test_edge_key_roundtrip():
    k = keys.edge_key(2, 100, -5, 0, 200, version=1)
    assert keys.is_edge_key(k)
    assert keys.parse_edge_key(k) == (2, 100, -5, 0, 200, 1)


def test_prefix_containment():
    k = keys.vertex_key(3, 12345, 7)
    assert k.startswith(keys.vertex_prefix(3, 12345))
    assert k.startswith(keys.vertex_prefix(3, 12345, 7))
    assert k.startswith(keys.part_prefix(3))
    e = keys.edge_key(3, 12345, 9, 4, 777)
    assert e.startswith(keys.edge_prefix(3, 12345))
    assert e.startswith(keys.edge_prefix(3, 12345, 9))
    assert not e.startswith(keys.vertex_prefix(3, 12345))


def test_ordering_newest_version_first():
    v1 = keys.now_version()
    # later wall-clock → smaller version → sorts first
    import time
    time.sleep(0.001)
    v2 = keys.now_version()
    assert v2 < v1
    k_old = keys.vertex_key(1, 10, 1, version=v1)
    k_new = keys.vertex_key(1, 10, 1, version=v2)
    assert k_new < k_old  # newest sorts first within the group


def test_ordering_signed_fields():
    # byte order must equal numeric order for vids and ranks
    ks = [keys.vertex_key(1, v, 0, version=0) for v in (-100, -1, 0, 1, 100)]
    assert ks == sorted(ks)
    es = [keys.edge_key(1, 5, 2, r, 9, version=0) for r in (-7, -1, 0, 3, 1 << 40)]
    assert es == sorted(es)


def test_partitioner_stable_and_in_range():
    for vid in [0, 1, -1, 123456789, -987654321]:
        p = keys.part_id(vid, 8)
        assert 1 <= p <= 8
        assert p == keys.part_id(vid, 8)  # deterministic


def test_commit_value_roundtrip():
    v = keys.encode_commit_value(12345, 7)
    assert keys.decode_commit_value(v) == (12345, 7)
