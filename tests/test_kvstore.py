"""KV engine / store / part tests (parity model: kvstore/test/RocksEngineTest,
NebulaStoreTest, PartTest, LogEncoderTest)."""
import pytest

from nebula_tpu.common import keys
from nebula_tpu.common.status import ErrorCode
from nebula_tpu.kvstore import GraphStore, MemEngine
from nebula_tpu.kvstore import log_encoder as le


def test_engine_basic_ops():
    e = MemEngine()
    assert e.get(b"k") is None
    e.put(b"k", b"v")
    assert e.get(b"k") == b"v"
    e.put(b"k", b"v2")
    assert e.get(b"k") == b"v2"
    e.remove(b"k")
    assert e.get(b"k") is None
    assert e.total_keys() == 0


def test_engine_prefix_and_range():
    e = MemEngine()
    e.multi_put([(f"a{i}".encode(), str(i).encode()) for i in range(5)])
    e.multi_put([(f"b{i}".encode(), str(i).encode()) for i in range(3)])
    assert [k for k, _ in e.prefix(b"a")] == [b"a0", b"a1", b"a2", b"a3", b"a4"]
    assert [k for k, _ in e.prefix(b"b")] == [b"b0", b"b1", b"b2"]
    assert [k for k, _ in e.prefix(b"c")] == []
    assert [k for k, _ in e.range(b"a3", b"b1")] == [b"a3", b"a4", b"b0"]
    e.remove_range(b"a1", b"a4")
    assert [k for k, _ in e.prefix(b"a")] == [b"a0", b"a4"]
    e.remove_prefix(b"a")
    assert [k for k, _ in e.prefix(b"a")] == []
    assert e.total_keys() == 3


def test_engine_prefix_upper_bound_edge():
    e = MemEngine()
    e.put(b"\xff\xff", b"1")
    e.put(b"\xff\xfe", b"2")
    assert [k for k, _ in e.prefix(b"\xff")] == [b"\xff\xfe", b"\xff\xff"]


def test_log_encoder_roundtrip():
    op, payload = le.decode(le.encode_single(le.OP_PUT, b"k", b"v"))
    assert op == le.OP_PUT and payload == (b"k", b"v")
    op, payload = le.decode(le.encode_multi_put([(b"a", b"1"), (b"b", b"2")]))
    assert op == le.OP_MULTI_PUT and payload[0] == [(b"a", b"1"), (b"b", b"2")]
    op, payload = le.decode(le.encode_multi_remove([b"x", b"y"]))
    assert payload[0] == [b"x", b"y"]
    op, payload = le.decode(le.encode_remove_range(b"a", b"z"))
    assert payload == (b"a", b"z")
    op, payload = le.decode(le.encode_host(le.OP_ADD_LEARNER, "h:1"))
    assert op == le.OP_ADD_LEARNER and payload == ("h:1",)


def test_store_space_part_topology():
    st = GraphStore()
    st.add_space(1)
    st.add_part(1, 1)
    st.add_part(1, 2)
    assert st.spaces() == [1]
    assert st.parts(1) == [1, 2]
    st.remove_part(1, 2)
    assert st.parts(1) == [1]
    st.remove_space(1)
    assert st.spaces() == []


def test_store_routing_errors():
    st = GraphStore()
    r = st.get(9, 1, b"k")
    assert r.status.code == ErrorCode.E_SPACE_NOT_FOUND
    st.add_space(9)
    r = st.get(9, 1, b"k")
    assert r.status.code == ErrorCode.E_PART_NOT_FOUND


def test_store_write_read_through_part():
    st = GraphStore()
    st.add_part(1, 3)
    vk = keys.vertex_key(3, 7, 1, version=0)
    assert st.async_multi_put(1, 3, [(vk, b"row")]).ok()
    assert st.get(1, 3, vk).value() == b"row"
    r = st.get(1, 3, b"missing")
    assert r.status.code == ErrorCode.E_KEY_NOT_FOUND


def test_part_commit_marker_persists():
    st = GraphStore()
    part = st.add_part(1, 1)
    part.async_put(b"a", b"1")
    part.async_put(b"b", b"2")
    assert part.last_committed_log_id == 2
    v = part.engine.get(keys.system_commit_key(1))
    assert keys.decode_commit_value(v)[0] == 2


def test_part_atomic_op():
    st = GraphStore()
    part = st.add_part(1, 1)
    part.async_put(b"cnt", b"5")

    def cas():
        cur = int(part.engine.get(b"cnt"))
        if cur != 5:
            return None
        return le.encode_single(le.OP_PUT, b"cnt", str(cur + 1).encode())

    assert part.async_atomic_op(cas).ok()
    assert part.engine.get(b"cnt") == b"6"
    # second run aborts (value no longer 5)
    st2 = part.async_atomic_op(cas)
    assert not st2.ok()
    assert part.engine.get(b"cnt") == b"6"


def test_part_cleanup_only_touches_own_prefix():
    st = GraphStore()
    p1 = st.add_part(1, 1)
    p2 = st.add_part(1, 2)
    p1.async_put(keys.vertex_key(1, 5, 1, version=0), b"x")
    p2.async_put(keys.vertex_key(2, 5, 1, version=0), b"y")
    st.remove_part(1, 1)
    eng = st.space_engine(1)
    assert eng.get(keys.vertex_key(1, 5, 1, version=0)) is None
    assert eng.get(keys.vertex_key(2, 5, 1, version=0)) == b"y"


def test_multi_version_scan_newest_first():
    """Mirrors the reference's decreasing-version semantics: a prefix scan
    over (vid, tag) sees the newest write first."""
    st = GraphStore()
    part = st.add_part(1, 1)
    part.async_put(keys.vertex_key(1, 42, 7, version=keys.now_version()), b"old")
    import time
    time.sleep(0.001)
    part.async_put(keys.vertex_key(1, 42, 7, version=keys.now_version()), b"new")
    it = part.engine.prefix(keys.vertex_prefix(1, 42, 7))
    vals = [v for _, v in it]
    assert vals[0] == b"new"
