"""Per-query resource ledger (ISSUE 12): accumulator semantics, the
cross-host RPC piggyback merge, context propagation rules, and the
off-path cost guard (common/ledger.py; rpc/transport.py v1.2
envelope)."""
import threading
import time

import pytest

from nebula_tpu.common import ledger
from nebula_tpu.common.flags import graph_flags
from nebula_tpu.rpc import proxy, wire
from nebula_tpu.rpc.transport import RpcServer


# ---------------------------------------------------------------- unit

def test_fields_and_charges():
    led = ledger.Ledger()
    assert all(getattr(led, f) == 0 for f in ledger.FIELDS)
    led.charge(device_us=100, launches=1)
    led.charge(device_us=50)
    assert led.device_us == 150 and led.launches == 1
    led.charge_host("hostA:1", rows_scanned=10, bytes_returned=99)
    assert led.rows_scanned == 10
    assert led.hosts["hostA:1"] == {"rows_scanned": 10,
                                    "bytes_returned": 99}
    d = led.to_dict()
    assert d["device_us"] == 150 and d["rows_scanned"] == 10
    assert d["hosts"]["hostA:1"]["rows_scanned"] == 10
    # stable shape: every field present even when zero
    for f in ledger.FIELDS:
        assert f in d


def test_wire_roundtrip_and_merge_across_hosts():
    server_led = ledger.Ledger()
    server_led.charge_host("hostB:2", rows_scanned=7, bytes_returned=70)
    server_led.charge(wal_bytes=33)
    # the fragment crosses the real wire codec (the v1.2 response
    # element is wire-encoded with everything else)
    w = wire.decode(wire.encode(server_led.to_wire()))
    client_led = ledger.Ledger()
    client_led.charge(rpc_calls=1)
    client_led.merge_wire(w, host="peer:9")
    assert client_led.rows_scanned == 7
    assert client_led.wal_bytes == 33
    # the nested per-host slice survives under its original name;
    # only the UNATTRIBUTED remainder (wal_bytes here) lands under
    # the peer's key — already-attributed rows must not double-count
    assert client_led.hosts["hostB:2"]["rows_scanned"] == 7
    assert client_led.hosts["peer:9"] == {"wal_bytes": 33}


def test_merge_wire_malformed_fragment_is_dropped():
    led = ledger.Ledger()
    led.merge_wire(("garbage",), host="x")
    led.merge_wire(None, host="x")
    assert led.rows_scanned == 0 and not led.hosts


def test_begin_end_and_ambient_charge():
    assert ledger.current() is None
    led, tok = ledger.begin()
    try:
        assert ledger.current() is led
        ledger.charge(h2d_bytes=5)
        assert led.h2d_bytes == 5
    finally:
        ledger.end(tok)
    assert ledger.current() is None
    ledger.charge(h2d_bytes=1)     # no ledger: silently dropped


def test_use_repoints_and_detaches():
    owner = ledger.Ledger()
    led, tok = ledger.begin()
    try:
        with ledger.use(owner):
            ledger.charge(device_us=9)
        # a None ledger DETACHES (serving a ledger-less request must
        # not charge the leader's own query)
        with ledger.use(None):
            ledger.charge(device_us=100)
        assert owner.device_us == 9
        assert led.device_us == 0
    finally:
        ledger.end(tok)


def test_concurrent_charges_do_not_lose_increments():
    led = ledger.Ledger()

    def worker():
        for _ in range(500):
            led.charge(rpc_calls=1)
            led.charge_host("h", rows_scanned=1)

    ts = [threading.Thread(target=worker) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert led.rpc_calls == 2000
    assert led.hosts["h"]["rows_scanned"] == 2000


# ------------------------------------------------------- off-path guard

def test_cost_ledger_flag_off_means_no_ledger():
    graph_flags.set("cost_ledger_enabled", False)
    try:
        led, tok = ledger.begin()
        assert led is None and tok is None
        ledger.end(tok)               # no-op, no raise
        assert ledger.current() is None
    finally:
        graph_flags.set("cost_ledger_enabled", True)


def test_off_path_charge_is_cheap():
    """The off-path contract: a charge with no active ledger is one
    ContextVar read. Generous bound (20x a bare function call) so CI
    jitter can't flake it — the point is catching an accidental
    allocation or lock on the no-ledger path."""
    def bare():
        pass

    n = 20000
    t0 = time.perf_counter()
    for _ in range(n):
        bare()
    base = time.perf_counter() - t0
    assert ledger.current() is None
    t0 = time.perf_counter()
    for _ in range(n):
        ledger.charge(device_us=1)
    off = time.perf_counter() - t0
    assert off < max(base, 1e-4) * 20


# ------------------------------------------------- RPC piggyback (v1.2)

class _CostedService:
    def scan(self, n):
        ledger.charge_host("server-host:7", rows_scanned=n,
                           bytes_returned=n * 10)
        return n * 2

    def plain(self, x):
        return x + 1


@pytest.fixture()
def costed_server():
    srv = RpcServer().register("svc", _CostedService())
    srv.start()
    yield srv
    srv.stop()


def test_rpc_carries_cost_flag_and_merges_fragment(costed_server):
    client = proxy(costed_server.addr, "svc")
    led, tok = ledger.begin()
    try:
        assert client.scan(5) == 10
    finally:
        ledger.end(tok)
    assert led.rpc_calls == 1
    assert led.rpc_bytes_out > 0 and led.rpc_bytes_in > 0
    assert led.rows_scanned == 5 and led.bytes_returned == 50
    # per-host attribution: the server's explicit host slice survives
    # EXACTLY ONCE (no re-label under the dialed address — the server
    # already attributed these rows)
    assert led.hosts["server-host:7"]["rows_scanned"] == 5
    assert led.to_dict()["hosts"]["server-host:7"]["rows_scanned"] == 5
    assert sum(d.get("rows_scanned", 0)
               for d in led.hosts.values()) == 5


def test_rpc_without_ledger_stays_v1_envelope(costed_server):
    """No ledger, no trace -> the request is the byte-identical v1.0
    4-tuple and the response a 2-tuple (the off-path guard's wire
    half)."""
    assert ledger.current() is None
    payload = wire.encode(("svc", "plain", (1,), {}))
    import socket
    from nebula_tpu.rpc.transport import _recv_frame, _send_frame
    sock = socket.create_connection(
        (costed_server.host, costed_server.port), timeout=5)
    try:
        _send_frame(sock, payload)
        resp = wire.decode(_recv_frame(sock))
    finally:
        sock.close()
    assert resp == (True, 2)      # exactly 2 elements: v1.0 shape


def test_rpc_cost_flag_without_trace(costed_server):
    """Sampling off + ledger on: the envelope carries (None, 1) and
    the response 4-tuple still merges — cost attribution must not
    depend on the trace sampling decision."""
    from nebula_tpu.common.tracing import tracer
    assert tracer.current_ctx() is None
    client = proxy(costed_server.addr, "svc")
    led, tok = ledger.begin()
    try:
        client.scan(3)
    finally:
        ledger.end(tok)
    assert led.rows_scanned == 3


# ------------------------------------------------- cache rung charging

def test_cache_rung_charges_ledger():
    from nebula_tpu.common.cache import CacheRung
    rung = CacheRung("test.ledger_rung", 4)
    led, tok = ledger.begin()
    try:
        assert rung.get("k") is None
        rung.put("k", 1)
        assert rung.get("k") == 1
    finally:
        ledger.end(tok)
    assert led.cache_misses == 1 and led.cache_hits == 1
