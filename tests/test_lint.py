"""nebula-lint (nebula_tpu/tools/lint): every rule NL001-NL008 proven
LIVE on a minimal tripping snippet plus a negative twin, suppression
and baseline semantics, and the full-tree gate — the committed tree
must carry zero non-baselined findings."""
import json
import os
import textwrap

from nebula_tpu.tools.lint import RULES, Project, run_lint
from nebula_tpu.tools.lint.core import (load_baseline, split_baseline,
                                        write_baseline)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def proj(tmp_path, files):
    rels = []
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
        if rel.endswith(".py"):
            rels.append(rel)
    return Project(str(tmp_path), rels)


def lint(tmp_path, files, select=None):
    findings, suppressed = run_lint(proj(tmp_path, files), RULES, select)
    return findings, suppressed


def codes(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------- NL001

def test_nl001_trips_on_sleep_under_hot_lock(tmp_path):
    fs, _ = lint(tmp_path, {"nebula_tpu/m.py": """
        import threading
        import time

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def bad(self):
                with self._lock:
                    time.sleep(0.1)
    """}, ["NL001"])
    assert codes(fs) == ["NL001"]
    assert "time.sleep" in fs[0].message and "_lock" in fs[0].message


def test_nl001_numpy_fetch_and_socket_send_under_lock(tmp_path):
    fs, _ = lint(tmp_path, {"nebula_tpu/m.py": """
        import numpy as np

        def bad(self, sock, x):
            with self._lock:
                y = np.asarray(x)
                sock.sendall(b"hi")
            return y
    """}, ["NL001"])
    assert codes(fs) == ["NL001", "NL001"]


def test_nl001_clean_cases(tmp_path):
    fs, _ = lint(tmp_path, {"nebula_tpu/m.py": """
        import time

        def ok(self):
            with self._disp_cv:
                self._disp_cv.wait(0.1)     # wait on the HELD cv: exempt
            time.sleep(0.1)                 # off-lock: fine

        def nested_def_runs_later(self):
            with self._lock:
                def cb():
                    time.sleep(1)           # not under this hold
                return cb
    """}, ["NL001"])
    assert fs == []


# ---------------------------------------------------------------- NL002

def test_nl002_trips_on_raw_thread_spawn(tmp_path):
    fs, _ = lint(tmp_path, {"nebula_tpu/m.py": """
        import threading

        def spawn(fn):
            threading.Thread(target=fn, daemon=True).start()
    """}, ["NL002"])
    assert codes(fs) == ["NL002"]


def test_nl002_copy_context_and_helper_compliant(tmp_path):
    fs, _ = lint(tmp_path, {"nebula_tpu/m.py": """
        import contextvars
        import threading
        from nebula_tpu.common.threads import traced_thread

        def ok1(fn):
            ctx = contextvars.copy_context()
            threading.Thread(target=lambda: ctx.run(fn)).start()

        def ok2(fn):
            traced_thread(fn).start()
    """}, ["NL002"])
    assert fs == []


def test_nl002_compliant_spawn_does_not_whitewash_raw_one(tmp_path):
    """Compliance is judged at THE SPAWN, not per enclosing scope: a
    traced spawn (or a stray copy_context import) must not silence a
    raw spawn beside it."""
    fs, _ = lint(tmp_path, {"nebula_tpu/m.py": """
        import contextvars
        import threading
        from contextvars import copy_context

        def mixed(fn, gn):
            ctx = contextvars.copy_context()
            threading.Thread(target=lambda: ctx.run(fn)).start()   # ok
            threading.Thread(target=gn, daemon=True).start()       # raw

        threading.Thread(target=print).start()   # top-level raw spawn
    """}, ["NL002"])
    assert codes(fs) == ["NL002", "NL002"]
    assert {f.line for f in fs} == {9, 11}


def test_nl002_local_def_carrying_context_is_compliant(tmp_path):
    fs, _ = lint(tmp_path, {"nebula_tpu/m.py": """
        import contextvars
        import threading

        def spawn(target):
            ctx = contextvars.copy_context()

            def run():
                ctx.run(target)

            return threading.Thread(target=run, daemon=True)
    """}, ["NL002"])
    assert fs == []


def test_nl002_out_of_package_not_flagged(tmp_path):
    fs, _ = lint(tmp_path, {"scripts/x.py": """
        import threading
        threading.Thread(target=print).start()
    """}, ["NL002"])
    assert fs == []


# ---------------------------------------------------------------- NL003

def test_nl003_undeclared_read_and_dead_flag(tmp_path):
    fs, _ = lint(tmp_path, {"nebula_tpu/m.py": """
        graph_flags.declare("dead_flag", 1)
        graph_flags.declare("live_flag", 2)
        x = graph_flags.get("live_flag")
        y = graph_flags.get("never_declared")
    """}, ["NL003"])
    msgs = sorted(f.message for f in fs)
    assert len(fs) == 2
    assert "'never_declared' is read but never declare()d" in msgs[1]
    assert "'dead_flag' is declared but never read" in msgs[0]


def test_nl003_watcher_consumed_flag_counts_as_read(tmp_path):
    fs, _ = lint(tmp_path, {"nebula_tpu/m.py": """
        graph_flags.declare("hooked", "")

        def watcher(name, value):
            if name == "hooked":
                apply(value)
    """}, ["NL003"])
    assert fs == []


# ---------------------------------------------------------------- NL004

def test_nl004_kind_conflict_and_missing_kind(tmp_path):
    fs, _ = lint(tmp_path, {"nebula_tpu/m.py": """
        def f():
            stats.add_value("mixed", 1, kind="counter")
            stats.add_value("mixed", 2.5, kind="timing")
            global_stats.add_value("untagged_metric", 1)
    """}, ["NL004"])
    assert len(fs) == 2
    conflict = [f for f in fs if "mixed" in f.message]
    missing = [f for f in fs if "untagged_metric" in f.message]
    assert len(conflict) == 1 and "'timing'" in conflict[0].message
    assert len(missing) == 1 and "without a kind" in missing[0].message


def test_nl004_consistent_sites_clean(tmp_path):
    fs, _ = lint(tmp_path, {"nebula_tpu/m.py": """
        def f():
            stats.add_value("n", 1, kind="counter")
            stats.add_value("n", 3, kind="counter")
            stats.add_value("lat", 12.5, kind="histogram")
            stats.add_value("lat", 7.5, kind="histogram")
    """}, ["NL004"])
    assert fs == []


def test_nl004_histogram_kind_known_and_misuse_flagged(tmp_path):
    # histogram is a REAL kind (PR 10); histogram-on-counter is the
    # cross-site conflict; a typo'd kind registers untagged — flagged
    fs, _ = lint(tmp_path, {"nebula_tpu/m.py": """
        def f():
            stats.add_value("evt", 1, kind="counter")
            stats.add_value("evt", 33.0, kind="histogram")
            stats.add_value("typo", 1, kind="histograms")
    """}, ["NL004"])
    assert len(fs) == 2
    conflict = [f for f in fs if "evt" in f.message]
    typo = [f for f in fs if "typo" in f.message]
    assert len(conflict) == 1 and "'histogram'" in conflict[0].message
    assert len(typo) == 1 and "unknown kind" in typo[0].message


# ---------------------------------------------------------------- NL005

def test_nl005_unregistered_fire_and_undocumented_point(tmp_path):
    fs, _ = lint(tmp_path, {
        "docs/manual/9-robustness.md": "catalog: `known.point` only\n",
        "nebula_tpu/m.py": """
            faults.register("known.point")
            faults.register("silent.point")

            def f():
                faults.fire("known.point")
                faults.fire("silent.point")
                faults.fire("ghost.point")
        """}, ["NL005"])
    msgs = " | ".join(sorted(f.message for f in fs))
    assert len(fs) == 2
    assert "'ghost.point' is fired but never register()ed" in msgs
    assert "'silent.point' is not listed" in msgs


# ---------------------------------------------------------------- NL006

def test_nl006_host_ops_inside_jit(tmp_path):
    fs, _ = lint(tmp_path, {"nebula_tpu/m.py": """
        import functools
        import random
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def f(x):
            print(x)
            return np.asarray(x) + x.item()

        @functools.partial(jax.jit, static_argnames=("n",))
        def g(x, n):
            return x * random.random()

        def build():
            def run(x):
                return jnp.sum(x)          # pure: jnp, not np
            return jax.jit(run)
    """}, ["NL006"])
    assert len(fs) == 4
    blob = " | ".join(f.message for f in fs)
    assert "print()" in blob and "np.asarray" in blob
    assert ".item()" in blob and "RNG" in blob


def test_nl006_dtype_names_and_unjitted_host_code_clean(tmp_path):
    fs, _ = lint(tmp_path, {"nebula_tpu/m.py": """
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def f(x):
            return jnp.zeros(3, np.int32) + x    # dtype ref is fine

        def host_side(x):
            print(x)                             # not jitted
            return np.asarray(x)
    """}, ["NL006"])
    assert fs == []


# ---------------------------------------------------------------- NL007

def test_nl007_struct_field_drift(tmp_path):
    spec = {"registry": [
        {"id": 0, "name": "Foo", "kind": "struct", "fields": ["a", "b"]}]}
    fs, _ = lint(tmp_path, {
        "docs/manual/wire-vectors.json": json.dumps(spec),
        "nebula_tpu/m.py": """
            from dataclasses import dataclass

            @dataclass
            class Foo:
                a: int = 0
                b: str = ""
                sneaky: float = 0.0
        """}, ["NL007"])
    assert codes(fs) == ["NL007"]
    assert "drifted from" in fs[0].message and "sneaky" in fs[0].message


def test_nl007_matching_struct_clean(tmp_path):
    spec = {"registry": [
        {"id": 0, "name": "Foo", "kind": "struct", "fields": ["a", "b"]}]}
    fs, _ = lint(tmp_path, {
        "docs/manual/wire-vectors.json": json.dumps(spec),
        "nebula_tpu/m.py": """
            from dataclasses import dataclass

            @dataclass
            class Foo:
                a: int = 0
                b: str = ""
        """}, ["NL007"])
    assert fs == []


def test_nl007_missing_spec_is_a_finding(tmp_path):
    fs, _ = lint(tmp_path, {"nebula_tpu/m.py": "x = 1\n"}, ["NL007"])
    assert codes(fs) == ["NL007"]
    assert "spec" in fs[0].message


# ------------------------------------------------------- suppressions

def test_inline_suppression_same_line_and_block_above(tmp_path):
    fs, suppressed = lint(tmp_path, {"nebula_tpu/m.py": """
        def f():
            stats.add_value("a", 1)   # nlint: disable=NL004 -- legacy
            # nlint: disable=NL004 -- reason wraps over
            # two comment lines above the site
            stats.add_value("b", 1)
            stats.add_value("c", 1)   # NOT suppressed
    """}, ["NL004"])
    assert suppressed == 2
    assert len(fs) == 1 and "'c'" in fs[0].message


def test_file_level_suppression(tmp_path):
    fs, suppressed = lint(tmp_path, {"nebula_tpu/m.py": """
        # nlint: disable-file=NL004
        def f():
            stats.add_value("a", 1)
            stats.add_value("b", 1)
    """}, ["NL004"])
    assert fs == [] and suppressed == 2


def test_suppression_is_per_rule(tmp_path):
    fs, _ = lint(tmp_path, {"nebula_tpu/m.py": """
        import threading

        def f():
            # nlint: disable=NL001 -- wrong code for this finding
            threading.Thread(target=print).start()
    """}, ["NL002"])
    assert codes(fs) == ["NL002"]


# ------------------------------------------------------------ baseline

def test_baseline_absorbs_then_new_findings_surface(tmp_path):
    files = {"nebula_tpu/m.py": """
        def f():
            stats.add_value("a", 1)
    """}
    findings, _ = lint(tmp_path, files, ["NL004"])
    base_path = tmp_path / ".nlint-baseline.json"
    write_baseline(str(base_path), findings)
    baseline = load_baseline(str(base_path))
    new, old = split_baseline(findings, baseline)
    assert new == [] and len(old) == 1

    files2 = {"nebula_tpu/m.py": """
        def f():
            x = 1   # shifted lines: baseline key is line-independent
            stats.add_value("a", 1)
            stats.add_value("fresh", 1)
    """}
    findings2, _ = lint(tmp_path, files2, ["NL004"])
    new2, old2 = split_baseline(findings2, baseline)
    assert len(old2) == 1
    assert len(new2) == 1 and "fresh" in new2[0].message


def test_baseline_is_a_multiset(tmp_path):
    files = {"nebula_tpu/m.py": """
        def f():
            stats.add_value("a", 1)
            stats.add_value("a", 1)
    """}
    findings, _ = lint(tmp_path, files, ["NL004"])
    assert len(findings) == 2          # identical keys, two sites
    base_path = tmp_path / "b.json"
    write_baseline(str(base_path), findings[:1])
    new, old = split_baseline(findings, load_baseline(str(base_path)))
    assert len(old) == 1 and len(new) == 1


# ------------------------------------------------------ full-tree gate

# ---------------------------------------------------------------- NL008

def test_nl008_trips_on_unnamed_thread_spawn(tmp_path):
    fs, _ = lint(tmp_path, {"nebula_tpu/m.py": """
        import threading
        from nebula_tpu.common.threads import traced_thread

        def spawn(fn):
            threading.Thread(target=fn, daemon=True).start()
            traced_thread(fn).start()
    """}, ["NL008"])
    assert codes(fs) == ["NL008", "NL008"]


def test_nl008_named_spawn_clean_and_out_of_package_ignored(tmp_path):
    fs, _ = lint(tmp_path, {
        "nebula_tpu/m.py": """
            import threading

            def spawn(fn, i):
                threading.Thread(target=fn, daemon=True,
                                 name=f"worker-{i}").start()
        """,
        "scripts/x.py": """
            import threading
            threading.Thread(target=print).start()
        """}, ["NL008"])
    assert fs == []


def test_nl004_profiler_family_kinds_pinned(tmp_path):
    """lock.wait_us.* / graph.gc.* / tpu_engine.compile_us are
    contractually native histograms (the continuous-profiling metric
    families) — a site declaring any other kind is a finding even
    through an f-string name."""
    fs, _ = lint(tmp_path, {"nebula_tpu/m.py": """
        from nebula_tpu.common.stats import stats

        def feed(site, us):
            stats.add_value(f"lock.wait_us.{site}", us, kind="timing")
            stats.add_value("graph.gc.pause_us", us, kind="counter")
            stats.add_value("tpu_engine.compile_us", us,
                            kind="histogram")
    """}, ["NL004"])
    assert codes(fs) == ["NL004", "NL004"]
    assert all("contractually" in f.message for f in fs)


def test_nl004_heat_family_kinds_pinned(tmp_path):
    """ISSUE 14: the workload-observatory families are pinned —
    heat.* feed counters are contractually counters and
    raftex.staleness_ms is a native histogram (its bucket series
    feeds the staleness SLO / federation tests); f-string prefixes
    included."""
    fs, _ = lint(tmp_path, {"nebula_tpu/m.py": """
        from nebula_tpu.common.stats import stats

        def feed(n, ms, space):
            stats.add_value("heat.sketch.observed", n, kind="counter")
            stats.add_value(f"heat.sketch.{space}", n, kind="timing")
            stats.add_value("raftex.staleness_ms", ms, kind="counter")
    """}, ["NL004"])
    assert codes(fs) == ["NL004", "NL004"]
    assert all("contractually" in f.message for f in fs)


def test_full_tree_has_zero_non_baselined_findings():
    """THE gate: the committed tree, scanned with every rule, carries
    no finding that is neither inline-suppressed (with a reason) nor
    in the committed baseline — the same check scripts/lint.sh runs
    before the tier-1 sweep."""
    project = Project(REPO)
    findings, _suppressed = run_lint(project, RULES)
    baseline = load_baseline(os.path.join(REPO, ".nlint-baseline.json"))
    new, old = split_baseline(findings, baseline)
    assert new == [], "non-baselined lint findings:\n" + "\n".join(
        f.render() for f in new)
    # acceptance bound: the grandfathered set stays small
    assert len(old) <= 25


def test_rule_catalog_complete():
    assert sorted(RULES) == [f"NL00{i}" for i in range(1, 9)]
    for code, r in RULES.items():
        assert r.title and r.doc, f"{code} must carry title + doc"
