"""Runtime lock-order witness (nebula_tpu/common/lockwitness.py).

Synthetic scenarios prove the detector detects (ABBA cycle, sleep
under a held lock, Condition round-trips, RLock recursion), then a
real in-process serve run proves the production lock graph — engine
snapshot lock, dispatcher cv, stats leaf lock, cache rungs, session
lock — is cycle-free with no blocking observed under a hot lock
(docs/manual/15-static-analysis.md)."""
import threading
import time

import pytest

from nebula_tpu.common.lockwitness import (LockOrderViolation,
                                           LockWitness)


@pytest.fixture
def w():
    """A private, wrap-everything witness, always uninstalled."""
    wit = LockWitness(scope=None).install()
    yield wit
    wit.uninstall()


def _run(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join()


def test_abba_cycle_detected(w):
    a = threading.Lock()
    b = threading.Lock()

    def t1():
        with a:
            with b:
                pass

    def t2():
        with b:
            with a:
                pass

    _run(t1)   # sequential, so the test itself can never deadlock
    _run(t2)
    cycle = w.find_cycle()
    assert cycle is not None and len(cycle) >= 3
    with pytest.raises(LockOrderViolation, match="ABBA"):
        w.assert_clean()
    assert w.report()["clean"] is False


def test_consistent_order_is_clean(w):
    a = threading.Lock()
    b = threading.Lock()

    def t1():
        with a:
            with b:
                pass

    _run(t1)
    _run(t1)
    assert w.find_cycle() is None
    rep = w.assert_clean()
    assert rep["clean"] is True
    assert len(rep["edges"]) == 1      # a -> b, recorded once


def test_sleep_under_lock_flagged(w):
    a = threading.Lock()
    with a:
        time.sleep(0.002)
    rep = w.report()
    assert len(rep["blocking"]) == 1
    ev = rep["blocking"][0]
    assert "time.sleep" in ev["op"]
    assert ev["locks_held"]
    with pytest.raises(LockOrderViolation, match="blocking"):
        w.assert_clean()


def test_sleep_outside_lock_not_flagged(w):
    a = threading.Lock()
    with a:
        pass
    time.sleep(0.002)
    assert w.report()["blocking"] == []


def test_condition_wait_releases_held_stack(w):
    """cv.wait() must POP the lock from the held stack: a lock taken
    by another thread while the waiter sleeps is not 'under' the cv,
    and the waiter's re-acquire after notify must re-push."""
    cv = threading.Condition()
    other = threading.Lock()
    done = []

    def waiter():
        with cv:
            while not done:
                cv.wait(1.0)
            with other:   # held AFTER re-acquire: cv -> other edge
                pass

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    with other:           # while waiter is parked in wait(): no locks
        pass              # held by it, so no other -> cv edge
    with cv:
        done.append(1)
        cv.notify_all()
    t.join()
    rep = w.assert_clean()          # would raise if both edges formed
    edges = {(e["held"], e["acquired"]) for e in rep["edges"]}
    assert len(edges) == 1          # only cv -> other, never reversed


def test_rlock_recursion_no_self_edge(w):
    r = threading.RLock()
    with r:
        with r:
            pass
    rep = w.assert_clean()
    assert rep["edges"] == []
    assert rep["self_edges"] == []


def test_same_site_nesting_reported_as_self_edge_not_cycle(w):
    def make():
        return threading.Lock()     # one creation site, two instances

    a, b = make(), make()
    with a:
        with b:
            pass
    rep = w.report()
    assert rep["cycle"] is None     # site-level graph has no cycle
    assert len(rep["self_edges"]) == 1
    rep2 = w.assert_clean()         # self-edges are visible, not fatal
    assert rep2["self_edges"]


def test_scope_filter_skips_foreign_creation_sites():
    wit = LockWitness(scope=("nebula_tpu",)).install()
    try:
        lk = threading.Lock()       # created from tests/ -> out of scope
        assert type(lk).__name__ != "_WitnessProxy"
        assert wit.wrapped == 0
    finally:
        wit.uninstall()


def test_uninstall_restores_patches():
    before = (threading.Lock, threading.RLock, time.sleep)
    wit = LockWitness(scope=None).install()
    assert threading.Lock is not before[0]
    wit.uninstall()
    assert (threading.Lock, threading.RLock, time.sleep) == before


def test_reset_clears_observations(w):
    a = threading.Lock()
    with a:
        time.sleep(0.002)
    assert w.report()["blocking"]
    w.reset()
    assert w.report()["blocking"] == []
    assert w.report()["clean"] is True


# ---------------------------------------------------------------------------
# the real serve path under the witness
# ---------------------------------------------------------------------------

def test_serve_path_lock_graph_is_clean():
    """Boot the in-process cluster with the witness installed FIRST,
    so every lock the serve path constructs (engine RLock + stats
    leaf lock + dispatcher cv, session lock, cache rungs, client
    pools) is wrapped; run traced queries and a write, then require
    an acyclic graph and zero blocked-under-lock events — the runtime
    form of the CHANGES.md locking invariants."""
    wit = LockWitness(scope=("nebula_tpu",)).install()
    try:
        from nebula_tpu.cluster import InProcCluster
        from nebula_tpu.engine_tpu import TpuGraphEngine

        tpu = TpuGraphEngine()
        cluster = InProcCluster(tpu_engine=tpu)
        conn = cluster.connect()
        conn.must("CREATE SPACE wit(partition_num=2)")
        conn.must("USE wit")
        conn.must("CREATE EDGE knows(ts int)")
        conn.must("CREATE TAG person(name string)")
        edges = ",".join(f"{s}->{d}:({s + d})"
                         for s in range(8) for d in range(8) if s != d)
        conn.must(f"INSERT EDGE knows(ts) VALUES {edges}")
        sid = cluster.meta.get_space("wit").value().space_id
        tpu.prewarm(sid, block=True)
        for q in ("GO FROM 1 OVER knows YIELD knows._dst",
                  "GO 2 STEPS FROM 2 OVER knows YIELD knows._dst",
                  "PROFILE GO FROM 3 OVER knows WHERE knows.ts > 4 "
                  "YIELD knows._dst, knows.ts"):
            r = conn.must(q)
            assert r.rows
        conn.must("INSERT EDGE knows(ts) VALUES 1->1:(99)")
        conn.must("GO FROM 1 OVER knows YIELD knows._dst")
        rep = wit.assert_clean()
        # meaningful coverage: the engine + session + stats locks were
        # wrapped and actually exercised under multi-lock holds
        assert rep["locks_wrapped"] >= 10
        assert rep["acquisitions"] >= 100
        assert rep["edges"], "no nested holds observed — witness inert?"
    finally:
        wit.uninstall()
