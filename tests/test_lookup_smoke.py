"""Tier-1-safe index-verb smoke: `bench.py --lookup-smoke` in a
SUBPROCESS on XLA:CPU (no accelerator, no native engine — same
isolation pattern as the cache/chaos/mesh smokes). The tier asserts
the device secondary-index subsystem on one small cluster: the
LOOKUP / GET SUBGRAPH / MATCH mix SERVES on device (nonzero counters
in the artifact), every result is BIT-IDENTICAL to the storaged
CPU-scan twin, a write between identical LOOKUPs INVALIDATES, and
index.search faults DEGRADE to the scan with breaker recovery
(docs/manual/16-indexes.md)."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def lookup_smoke(tmp_path_factory):
    out = tmp_path_factory.mktemp("lookup") / "LOOKUP_smoke.json"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_LOOKUP_OUT"] = str(out)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--lookup-smoke"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, (proc.stdout[-2000:],
                                  proc.stderr[-2000:])
    with open(out) as f:
        return json.load(f)


def test_lookup_smoke_device_serves(lookup_smoke):
    c = lookup_smoke["checks"]
    assert c["device_served"]
    assert c["lookup_served"] > 0
    assert c["subgraph_served"] > 0
    assert lookup_smoke["index"]["builds"] > 0


def test_lookup_smoke_identity(lookup_smoke):
    c = lookup_smoke["checks"]
    assert c["identity"] and not lookup_smoke["mismatches"]
    assert c["nonempty_mix"]


def test_lookup_smoke_write_invalidates(lookup_smoke):
    assert lookup_smoke["checks"]["write_invalidates"]
    assert lookup_smoke["index"]["invalidations"] > 0


def test_lookup_smoke_degrades_and_recovers(lookup_smoke):
    c = lookup_smoke["checks"]
    assert c["degrades_to_scan"]
    assert c["breaker_recovered"]


def test_lookup_smoke_perf_recorded(lookup_smoke):
    perf = lookup_smoke["perf"]
    for verb in ("lookup", "subgraph", "match"):
        assert perf[verb]["qps"] > 0
        assert perf[verb]["p99_ms"] > 0


def test_lookup_smoke_overall_ok(lookup_smoke):
    assert lookup_smoke["ok"] is True
