"""Mesh execution service tests (engine_tpu/mesh_exec.py): the full
device query surface on SHARDED snapshots — batched dispatcher windows,
distributed aggregation partials, ALL/NOLOOP path expansion — must be
identical to the single-device kernels AND to the CPU pipe, on the
8-virtual-device CPU mesh conftest provisions."""
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nba_fixture import load_nba
from nebula_tpu.cluster import InProcCluster
from nebula_tpu.engine_tpu import TpuGraphEngine, aggregate, traverse
from nebula_tpu.engine_tpu import distributed as dist
from nebula_tpu.engine_tpu import mesh_exec


def _drain_engine(tpu):
    """Join the engine's background threads (prewarm compiles, budget
    refits) so no daemon thread is still inside XLA when the
    interpreter exits — that aborts the whole pytest process."""
    for t in list(tpu._prewarm_threads.values()):
        t.join(timeout=300)
    import time
    for _ in range(600):
        if not tpu._recalibrating:
            return
        time.sleep(0.05)


@pytest.fixture(scope="module")
def snap8():
    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    _, conn = load_nba(cluster, space="mex8", parts=8)
    space_id = cluster.meta.get_space("mex8").value().space_id
    yield tpu.snapshot(space_id)
    _drain_engine(tpu)


# ---------------------------------------------------------------------------
# kernel level: sharded window masks / per-step masks == single-device
# ---------------------------------------------------------------------------

def test_batched_masks_sharded_identity(snap8):
    """The sharded lane-matrix window kernel must emit exactly the
    per-query multi_hop final masks, lane by lane."""
    mesh = dist.make_mesh()
    kern = dist.shard_snapshot_arrays(mesh, snap8)
    ak, chunk, group = dist.shard_aligned_blocks(mesh, snap8)
    seeds = [[100], [101, 102], [103], [100, 107, 109]]
    f_batch = jnp.asarray(np.stack(
        [snap8.frontier_from_vids(s) for s in seeds]))
    for req_list in ([1], [1, -1]):
        req = jnp.asarray(traverse.pad_edge_types(req_list))
        for steps in (1, 2, 3):
            out = np.asarray(mesh_exec.multi_hop_masks_batch_sharded(
                mesh, f_batch, jnp.int32(steps), ak, kern, req,
                chunk, group))
            for i, s in enumerate(seeds):
                _, single = traverse.multi_hop(
                    jnp.asarray(snap8.frontier_from_vids(s)),
                    jnp.int32(steps), snap8.kernel, req)
                assert np.array_equal(out[i], np.asarray(single)), \
                    (req_list, steps, s)


def test_steps_masks_sharded_identity(snap8):
    """Per-step sharded masks (the ALL-path expansion input) ==
    traverse.multi_hop_steps for every step."""
    mesh = dist.make_mesh()
    kern = dist.shard_snapshot_arrays(mesh, snap8)
    req = jnp.asarray(traverse.pad_edge_types([1]))
    f0 = jnp.asarray(snap8.frontier_from_vids([100, 103]))
    for steps in (1, 2, 4):
        sharded = np.asarray(mesh_exec.multi_hop_steps_sharded(
            mesh, f0, kern, req, steps))
        single = np.asarray(traverse.multi_hop_steps(
            f0, snap8.kernel, req, steps=steps))
        assert np.array_equal(sharded, single), steps


# ---------------------------------------------------------------------------
# distributed aggregation partials: exactness incl. the chunk boundary
# ---------------------------------------------------------------------------

def _sharded_mask_and_groups(mesh, P_, cap_e, n_groups, seed=3):
    rng = np.random.default_rng(seed)
    mask = jnp.asarray(rng.random((P_, cap_e)) < 0.5)
    gidx = jnp.asarray(
        rng.integers(0, n_groups, (P_, cap_e)).astype(np.int32))
    return mask, gidx


def test_mesh_scatter_count_chunk_boundary(monkeypatch):
    """Distributed grouped COUNT at the COUNT_CHUNK pass boundary:
    with the pass width pinned tiny (forcing many int32 passes whose
    host accumulation crosses the boundary mid-device), the counts
    must equal a plain numpy bincount — the exactness claim of the
    chunked discipline, not just the single-pass case."""
    mesh = dist.make_mesh()
    P_, cap_e, n_groups = 8, 96, 17
    mask, gidx = _sharded_mask_and_groups(mesh, P_, cap_e, n_groups)
    expect = np.bincount(
        np.asarray(gidx).reshape(-1)[np.asarray(mask).reshape(-1)],
        minlength=n_groups)
    # flat per-device length is 96: 40 forces passes [40, 40, 16] —
    # boundaries both inside and at the end of a device block
    for chunk in (40, 96, 7, 1 << 30):
        monkeypatch.setattr(aggregate, "COUNT_CHUNK", chunk)
        got = mesh_exec._mesh_scatter_count(mesh, mask, gidx, n_groups)
        assert np.array_equal(got, expect), chunk


def test_mesh_grouped_reduce_matches_host(monkeypatch):
    """mesh_grouped_reduce == a plain numpy reference on random
    values, across BOTH sum paths (device psum under the single-pass
    bound, chunked gathered partials past it) and a tiny COUNT pass
    width."""
    mesh = dist.make_mesh()
    P_, cap_e, n_groups = 8, 64, 11
    rng = np.random.default_rng(9)
    mask, gidx = _sharded_mask_and_groups(mesh, P_, cap_e, n_groups)
    vals_np = rng.integers(-2**31, 2**31, (P_, cap_e)).astype(np.int64)
    null_np = rng.random((P_, cap_e)) < 0.2

    class V:                      # the compiled-_Val duck shape
        value = jnp.asarray(vals_np.astype(np.int32))
        null = jnp.asarray(null_np)

    specs = [("COUNT", None), ("SUM", "k"), ("MIN", "k"),
             ("MAX", "k"), ("AVG", "k")]
    m = np.asarray(mask)
    mk = m & ~null_np
    g = np.asarray(gidx)
    i32 = vals_np.astype(np.int32).astype(np.int64)  # wrapped values
    exp_groups = np.nonzero(np.bincount(g.reshape(-1),
                                        weights=m.reshape(-1).astype(int),
                                        minlength=n_groups))[0]

    def reference(gi):
        sel = mk & (g == gi)
        vs = i32[sel]
        cnt = int(m[g == gi].sum())
        if vs.size == 0:
            return cnt, None, None, None, None
        s = int(sum(int(x) for x in vs))
        return (cnt, s, int(vs.min()), int(vs.max()), s / len(vs))

    for sum_bound in (1 << 23, 1):   # psum path, then chunked path
        monkeypatch.setattr(aggregate, "MAX_GROUPED_SUM_ROWS", sum_bound)
        monkeypatch.setattr(aggregate, "COUNT_CHUNK", 50)
        stats = {}
        groups, cols = mesh_exec.mesh_grouped_reduce(
            specs, mask, {"k": V}, gidx, n_groups, mesh, stats=stats)
        assert np.array_equal(groups, exp_groups)
        if sum_bound == 1:
            assert stats.get("agg_grouped_chunked", 0) >= 1
        for j, gi in enumerate(groups):
            cnt, s, lo, hi, avg = reference(int(gi))
            assert cols[0][j] == cnt
            assert cols[1][j] == s
            assert cols[2][j] == lo
            assert cols[3][j] == hi
            assert cols[4][j] == avg


# ---------------------------------------------------------------------------
# engine level: the full meshed serving surface vs the CPU pipe
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def meshed_pair():
    """(cpu_conn, meshed cluster, meshed conn, engine) over the same
    NBA data; every traversal on the TPU side runs the 8-device
    sharded path."""
    _, cpu_conn = load_nba(space="mexcpu", parts=8)
    tpu = TpuGraphEngine(mesh=dist.make_mesh())
    cluster = InProcCluster(tpu_engine=tpu)
    _, conn = load_nba(cluster, space="mextpu", parts=8)
    # pre-build the per-device window layout: the engine only kicks it
    # off-lock on first demand, and these tests assert window serving
    # deterministically rather than racing the background build
    sid = cluster.meta.get_space("mextpu").value().space_id
    snap = tpu.snapshot(sid)
    mesh_exec.ensure_sharded_aligned(tpu.mesh, snap)
    yield cpu_conn, cluster, conn, tpu
    _drain_engine(tpu)


def test_meshed_dispatcher_mixed_key_windows(meshed_pair):
    """Satellite: concurrent sessions with DIFFERING (space, steps,
    edge_types) group keys on a SHARDED snapshot — every query must
    coalesce through the dispatcher's meshed window kernel and return
    exactly the CPU pipe's rows."""
    cpu_conn, cluster, conn, tpu = meshed_pair
    queries = ["GO 2 STEPS FROM 100 OVER like YIELD like._dst",
               "GO 3 STEPS FROM 101 OVER like YIELD like._dst",
               "GO FROM 102, 103 OVER like YIELD like._dst, "
               "like.likeness",
               "GO 2 STEPS FROM 105 OVER serve YIELD serve._dst",
               # same group key as the first query, with a WHERE: the
               # window mixes filtered and unfiltered requests, so the
               # per-request compiled mask must AND into the SHARED
               # sharded window masks
               "GO 2 STEPS FROM 100 OVER like WHERE like.likeness > 60 "
               "YIELD like._dst"]
    expected = {q: sorted(map(str, cpu_conn.must(q).rows))
                for q in queries}
    before = tpu.mesh_served.get("go_batched", 0)
    errors = []

    def worker(q, reps):
        try:
            c = cluster.connect()
            c.must("USE mextpu")
            for _ in range(reps):
                got = sorted(map(str, c.must(q).rows))
                assert got == expected[q], q
        except Exception as e:   # noqa: BLE001 — surfaced below
            errors.append((q, repr(e)))

    threads = [threading.Thread(target=worker, args=(q, 3))
               for q in queries for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors[:3]
    assert tpu.mesh_served.get("go_batched", 0) > before, \
        (tpu.mesh_served, tpu.mesh_decline_reasons)
    assert tpu.stats["batched_dispatches"] > 0


def test_meshed_aggregate_pushdown(meshed_pair):
    """Grouped + ungrouped aggregation on a sharded snapshot: served
    by the distributed partials (mesh_served.agg), rows identical to
    the CPU pipe."""
    cpu_conn, _cluster, conn, tpu = meshed_pair
    before = tpu.mesh_served.get("agg", 0)
    for q in ("GO FROM 100, 101, 102 OVER serve YIELD "
              "serve.start_year AS y | YIELD COUNT(*) AS n, "
              "SUM($-.y) AS s, MIN($-.y) AS lo, MAX($-.y) AS hi, "
              "AVG($-.y) AS a",
              "GO FROM 100, 101, 102 OVER serve YIELD serve._dst AS t,"
              " serve.start_year AS y | GROUP BY $-.t YIELD $-.t AS t,"
              " COUNT(*) AS n, SUM($-.y) AS s, AVG($-.y) AS a"):
        rc, rt = cpu_conn.must(q), conn.must(q)
        assert sorted(map(repr, rc.rows)) == sorted(map(repr, rt.rows)), \
            (q, rc.rows, rt.rows)
    assert tpu.mesh_served.get("agg", 0) == before + 2, \
        (tpu.mesh_served, tpu.agg_decline_reasons)


def test_meshed_all_paths(meshed_pair):
    """ALL and NOLOOP path on a sharded snapshot: per-step sharded
    expansion + host enumeration, identical path strings to the CPU
    executor."""
    cpu_conn, _cluster, conn, tpu = meshed_pair
    before = tpu.mesh_served.get("path_all", 0)
    for q in ("FIND ALL PATH FROM 100 TO 102 OVER like UPTO 4 STEPS",
              "FIND NOLOOP PATH FROM 103 TO 100 OVER like UPTO 5 STEPS"):
        rc, rt = cpu_conn.must(q), conn.must(q)
        assert sorted(map(str, rc.rows)) == sorted(map(str, rt.rows)), q
    assert tpu.mesh_served.get("path_all", 0) == before + 2, \
        (tpu.mesh_served, tpu.path_decline_reasons)
    assert tpu.stats["path_served"] >= 2


def test_meshed_where_window(meshed_pair):
    """A WHERE-filtered window on the meshed dispatcher: the compiled
    device mask ANDs into the sharded window masks exactly as it does
    single-chip."""
    cpu_conn, _cluster, conn, tpu = meshed_pair
    q = ("GO FROM 100 OVER like WHERE like.likeness > 80 "
         "YIELD like._dst, like.likeness")
    rc, rt = cpu_conn.must(q), conn.must(q)
    assert sorted(map(str, rc.rows)) == sorted(map(str, rt.rows))


# ---------------------------------------------------------------------------
# sparse-budget staleness (satellite): churn past the threshold
# re-fits, pins are never overridden
# ---------------------------------------------------------------------------

def test_budget_recalibration_on_churn():
    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    _, conn = load_nba(cluster, space="recal", parts=4)
    sid = cluster.meta.get_space("recal").value().space_id
    # let the USE-triggered prewarm (compiles + auto-calibration)
    # finish first: its fit must not race the record planted below
    tpu.prewarm(sid, block=True)
    snap = tpu.snapshot(sid)
    assert snap is not None
    # a fit anchored BUDGET_RECAL_CHURN versions ago
    tpu.sparse_budget_calibrations[sid] = {"fitted_budget": 123,
                                           "churn_at_fit": 0}
    tpu._space_budgets[sid] = 123
    tpu._space_churn[sid] = tpu.BUDGET_RECAL_CHURN
    before = tpu.stats["budget_recalibrations"]
    t = tpu._maybe_recalibrate(sid, snap)
    assert t is not None
    t.join(timeout=120)
    assert tpu.stats["budget_recalibrations"] == before + 1
    rec = tpu.sparse_budget_calibrations.get(sid)
    assert rec is not None and rec["fitted_budget"] != 123
    assert rec["churn_at_fit"] == tpu._space_churn[sid]
    # under the threshold: nothing re-fits
    assert tpu._maybe_recalibrate(sid, snap) is None
    # a pinned budget is never touched, whatever the churn
    tpu.sparse_edge_budget = 7
    tpu._space_churn[sid] = 10 * tpu.BUDGET_RECAL_CHURN
    assert tpu._maybe_recalibrate(sid, snap) is None
    assert tpu.sparse_edge_budget == 7
    _drain_engine(tpu)


def test_budget_recalibration_via_refresh():
    """The staleness check rides the real rebuild path: refresh()
    bumps churn and, past the threshold, drops + refits the record."""
    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    _, conn = load_nba(cluster, space="recal2", parts=4)
    sid = cluster.meta.get_space("recal2").value().space_id
    tpu.prewarm(sid, block=True)
    assert tpu.snapshot(sid) is not None
    tpu.sparse_budget_calibrations[sid] = {"fitted_budget": 5,
                                           "churn_at_fit": 0}
    tpu._space_churn[sid] = tpu.BUDGET_RECAL_CHURN - 1
    with tpu._lock:
        assert tpu.refresh(sid) is not None   # churn hits the threshold
    for _ in range(600):
        if sid not in tpu._recalibrating:
            break
        import time
        time.sleep(0.05)
    assert tpu.stats["budget_recalibrations"] == 1
    rec = tpu.sparse_budget_calibrations.get(sid)
    assert rec is not None and rec["fitted_budget"] != 5
    _drain_engine(tpu)
