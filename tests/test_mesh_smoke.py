"""Tier-1-safe mesh smoke path: the bench's --mesh-dryrun tier runs in
a SUBPROCESS whose env pins a 4-virtual-device CPU mesh
(XLA_FLAGS=--xla_force_host_platform_device_count=4), so it composes
with the ROADMAP tier-1 command regardless of the parent process's
device count (conftest's 8) or backend state. The subprocess drives
the full meshed serving surface — concurrent dispatcher windows,
grouped + ungrouped aggregation, an ALL-path query — identity-checked
against a plain CPU cluster, and writes the mesh serving matrix as a
MULTICHIP json artifact."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def mesh_smoke(tmp_path_factory):
    """Run `bench.py --mesh-dryrun` on a 4-device host-emulated mesh
    in a subprocess; -> the recorded MULTICHIP dict."""
    out = tmp_path_factory.mktemp("mesh") / "MULTICHIP_smoke.json"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["BENCH_MESH_DEVICES"] = "4"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--mesh-dryrun", f"--out={out}"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, (proc.stdout[-2000:],
                                  proc.stderr[-2000:])
    with open(out) as f:
        return json.load(f)


def test_mesh_smoke_identity(mesh_smoke):
    assert mesh_smoke["n_devices"] == 4
    assert mesh_smoke["identity_ok"], mesh_smoke
    assert mesh_smoke["identity_checked"] >= 6


def test_mesh_smoke_serving_matrix(mesh_smoke):
    """Every feature the round-5 decline matrix switched off on the
    mesh must now show mesh_served > 0 (ISSUE 2 acceptance)."""
    served = mesh_smoke["mesh_served"]
    for feature in ("go_batched", "agg", "path_all"):
        assert served.get(feature, 0) > 0, (feature, mesh_smoke)
    assert mesh_smoke["sharded_queries"] > 0
