"""Native C++ KV engine tests (RocksEngine role, ref
kvstore/test/RocksEngineTest.cpp) — same surface as MemEngine plus
checkpoint persistence and the dedup hot-loop scan."""
import os

import pytest

from nebula_tpu.common import keys as ku
from nebula_tpu.kvstore.nativeengine import NativeEngine


@pytest.fixture
def eng():
    e = NativeEngine()
    yield e
    e.close()


def test_basic_ops(eng):
    assert eng.get(b"k") is None
    eng.put(b"k", b"v")
    assert eng.get(b"k") == b"v"
    eng.put(b"k", b"v2")
    assert eng.get(b"k") == b"v2"
    eng.remove(b"k")
    assert eng.get(b"k") is None
    assert eng.total_keys() == 0
    eng.put(b"empty", b"")
    assert eng.get(b"empty") == b""


def test_prefix_and_range(eng):
    eng.multi_put([(f"a{i}".encode(), str(i).encode()) for i in range(5)])
    eng.multi_put([(f"b{i}".encode(), str(i).encode()) for i in range(3)])
    assert [k for k, _ in eng.prefix(b"a")] == \
        [b"a0", b"a1", b"a2", b"a3", b"a4"]
    assert [k for k, _ in eng.range(b"a3", b"b1")] == [b"a3", b"a4", b"b0"]
    eng.remove_range(b"a1", b"a4")
    assert [k for k, _ in eng.prefix(b"a")] == [b"a0", b"a4"]
    eng.remove_prefix(b"a")
    assert [k for k, _ in eng.prefix(b"a")] == []
    assert eng.total_keys() == 3
    eng.multi_remove([b"b0", b"b1", b"b2"])
    assert eng.total_keys() == 0


def test_prefix_upper_bound_edge(eng):
    eng.put(b"\xff\xff", b"1")
    eng.put(b"\xff\xfe", b"2")
    assert len(list(eng.prefix(b"\xff"))) == 2
    assert len(list(eng.prefix(b"\xff\xff"))) == 1


def test_write_version_counts_mutations(eng):
    v0 = eng.write_version
    eng.put(b"a", b"1")
    eng.multi_put([(b"b", b"2"), (b"c", b"3")])
    eng.remove(b"a")
    assert eng.write_version == v0 + 3


def test_approximate_size(eng):
    assert eng.approximate_size() == 0
    eng.put(b"abc", b"defg")
    assert eng.approximate_size() == 7
    eng.remove(b"abc")
    assert eng.approximate_size() == 0


def test_checkpoint_roundtrip(tmp_path):
    path = str(tmp_path / "ckpt.nkv")
    e = NativeEngine(path)
    e.multi_put([(b"k%03d" % i, b"v%d" % i) for i in range(100)])
    assert e.flush().ok()
    e.close()
    e2 = NativeEngine(path)
    assert e2.total_keys() == 100
    assert e2.get(b"k050") == b"v50"
    assert [k for k, _ in e2.prefix(b"k09")] == [b"k09%d" % i
                                                for i in range(10)]
    e2.close()


def test_checkpoint_corrupt_rejected(tmp_path):
    path = str(tmp_path / "bad.nkv")
    e = NativeEngine(path)
    e.put(b"a", b"b")
    e.flush()
    e.close()
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) - 4)   # chop the trailer
    with pytest.raises(OSError):
        NativeEngine(path)


def test_dedup_scan_newest_version_wins(eng):
    """Keys are version-suffixed with inverted timestamps, so the first
    row of each (rank,dst) group is the newest (ref collectEdgeProps
    version dedupe, QueryBaseProcessor.inl:403-407)."""
    part, src, etype = 1, 100, 7
    # versions are inverted timestamps: SMALLER sorts first = newer
    k_new = ku.edge_key(part, src, etype, 0, 200, version=1000)
    k_old = ku.edge_key(part, src, etype, 0, 200, version=2000)
    k_other = ku.edge_key(part, src, etype, 0, 201, version=500)
    eng.multi_put([(k_old, b"old"), (k_new, b"new"), (k_other, b"x")])
    hits = eng.prefix_dedup(ku.edge_prefix(part, src, etype))
    assert [v for _, v in hits] == [b"new", b"x"]
    # plain scan sees all three
    assert len(list(eng.prefix(ku.edge_prefix(part, src, etype)))) == 3


def test_large_values(eng):
    blob = os.urandom(1 << 20)
    eng.put(b"big", blob)
    assert eng.get(b"big") == blob


def test_engine_under_graphstore(tmp_path):
    """NativeEngine slots into GraphStore via the engine factory seam."""
    from nebula_tpu.kvstore import GraphStore
    store = GraphStore(engine_factory=lambda sid: NativeEngine())
    store.add_part(1, 1)
    assert store.async_multi_put(1, 1, [(b"\x01a", b"1")]).ok()
    assert store.get(1, 1, b"\x01a").value() == b"1"


def test_native_codec_matches_python_columns(monkeypatch, tmp_path):
    """nbc_decode_batch column build == pure-Python column build
    (values, nulls, device arrays, string dicts, TTL)."""
    import numpy as np
    import time
    from nebula_tpu.codec import PropType, RowWriter, Schema, SchemaField
    from nebula_tpu.engine_tpu import csr as csr_mod

    schema = Schema([SchemaField("name", PropType.STRING),
                     SchemaField("age", PropType.INT),
                     SchemaField("w", PropType.DOUBLE),
                     SchemaField("ok", PropType.BOOL),
                     SchemaField("big", PropType.INT)])
    now = time.time()
    rows = []
    for i in range(7):
        w = RowWriter(schema)
        if i != 3:
            w.set("name", f"s{i % 2}")      # repeated -> shared dict codes
        w.set("age", 10 * i)
        if i != 5:
            w.set("w", i / 4)
        w.set("ok", i % 2 == 0)
        w.set("big", (1 << 40) if i == 6 else i)   # forces host-only col
        rows.append((i * 3, w.encode()))
    cap = 32

    reg_n, reg_p = {}, {}
    native_cols = csr_mod._native_build_columns(schema, cap, rows, now,
                                                reg_n, ("e",))
    assert native_cols is not None, "native lib should be available in CI"
    monkeypatch.setattr("nebula_tpu.native.available", lambda: False)
    python_cols = csr_mod._build_columns(schema, cap, rows, now,
                                         reg_p, ("e",))
    assert set(native_cols) == set(python_cols)
    for name in python_cols:
        pn, pp = native_cols[name], python_cols[name]
        assert pn.device_ok == pp.device_ok, name
        assert np.array_equal(pn.present, pp.present), name
        # read through the host_item contract: numeric mirrors are
        # plain numpy arrays with nulls riding `present`
        from nebula_tpu.engine_tpu.csr import host_item
        assert [host_item(pn, i) for i in range(cap)] == \
            [host_item(pp, i) for i in range(cap)], name
        if pp.device_vals is not None:
            assert np.array_equal(pn.device_vals, pp.device_vals,
                                  equal_nan=True), name
    assert reg_n == reg_p


def test_native_codec_ttl_rows_nulled(monkeypatch):
    import time
    from nebula_tpu.codec import PropType, RowWriter, Schema, SchemaField
    from nebula_tpu.engine_tpu import csr as csr_mod

    schema = Schema([SchemaField("ts", PropType.TIMESTAMP),
                     SchemaField("x", PropType.INT)],
                    ttl_col="ts", ttl_duration=100)
    now = time.time()
    rows = [(0, RowWriter(schema).set("ts", int(now) - 500).set("x", 1).encode()),
            (1, RowWriter(schema).set("ts", int(now)).set("x", 2).encode())]
    cols = csr_mod._native_build_columns(schema, 4, rows, now, {}, ("t",))
    assert cols is not None
    from nebula_tpu.engine_tpu.csr import host_item
    assert host_item(cols["x"], 0) is None   # expired row invisible
    assert host_item(cols["x"], 1) == 2


def test_native_codec_invalid_utf8_row_invisible(monkeypatch):
    """Both codec paths drop the ENTIRE row on invalid UTF-8."""
    import time
    from nebula_tpu.codec import PropType, RowWriter, Schema, SchemaField
    from nebula_tpu.engine_tpu import csr as csr_mod
    schema = Schema([SchemaField("s", PropType.STRING),
                     SchemaField("x", PropType.INT)])
    good = RowWriter(schema).set("s", "fine").set("x", 1).encode()
    bad = RowWriter(schema).set("s", b"\xff\xfe\xff").set("x", 2).encode()
    now = time.time()
    n_cols = csr_mod._native_build_columns(schema, 4, [(0, good), (1, bad)],
                                           now, {}, ("e",))
    from nebula_tpu.engine_tpu.csr import host_item
    assert host_item(n_cols["x"], 0) == 1
    assert host_item(n_cols["x"], 1) is None
    assert host_item(n_cols["s"], 1) is None
    import nebula_tpu.native as native
    monkeypatch.setattr(native, "available", lambda: False)
    p_cols = csr_mod._build_columns(schema, 4, [(0, good), (1, bad)],
                                    now, {}, ("e",))
    assert host_item(p_cols["x"], 1) is None
    assert host_item(p_cols["s"], 1) is None


def test_native_codec_non_numeric_ttl_never_expires(monkeypatch):
    """String ttl_col never expires — native must match the Python
    path's isinstance numeric check."""
    import time
    from nebula_tpu.codec import PropType, RowWriter, Schema, SchemaField
    from nebula_tpu.engine_tpu import csr as csr_mod
    schema = Schema([SchemaField("name", PropType.STRING),
                     SchemaField("x", PropType.INT)],
                    ttl_col="name", ttl_duration=100)
    rows = [(0, RowWriter(schema).set("name", "n").set("x", 7).encode())]
    now = time.time()
    cols = csr_mod._native_build_columns(schema, 2, rows, now, {}, ("t",))
    from nebula_tpu.engine_tpu.csr import host_item
    assert host_item(cols["x"], 0) == 7   # visible: string ttl is a no-op
