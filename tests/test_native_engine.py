"""Native C++ KV engine tests (RocksEngine role, ref
kvstore/test/RocksEngineTest.cpp) — same surface as MemEngine plus
checkpoint persistence and the dedup hot-loop scan."""
import os

import pytest

from nebula_tpu.common import keys as ku
from nebula_tpu.kvstore.nativeengine import NativeEngine


@pytest.fixture
def eng():
    e = NativeEngine()
    yield e
    e.close()


def test_basic_ops(eng):
    assert eng.get(b"k") is None
    eng.put(b"k", b"v")
    assert eng.get(b"k") == b"v"
    eng.put(b"k", b"v2")
    assert eng.get(b"k") == b"v2"
    eng.remove(b"k")
    assert eng.get(b"k") is None
    assert eng.total_keys() == 0
    eng.put(b"empty", b"")
    assert eng.get(b"empty") == b""


def test_prefix_and_range(eng):
    eng.multi_put([(f"a{i}".encode(), str(i).encode()) for i in range(5)])
    eng.multi_put([(f"b{i}".encode(), str(i).encode()) for i in range(3)])
    assert [k for k, _ in eng.prefix(b"a")] == \
        [b"a0", b"a1", b"a2", b"a3", b"a4"]
    assert [k for k, _ in eng.range(b"a3", b"b1")] == [b"a3", b"a4", b"b0"]
    eng.remove_range(b"a1", b"a4")
    assert [k for k, _ in eng.prefix(b"a")] == [b"a0", b"a4"]
    eng.remove_prefix(b"a")
    assert [k for k, _ in eng.prefix(b"a")] == []
    assert eng.total_keys() == 3
    eng.multi_remove([b"b0", b"b1", b"b2"])
    assert eng.total_keys() == 0


def test_prefix_upper_bound_edge(eng):
    eng.put(b"\xff\xff", b"1")
    eng.put(b"\xff\xfe", b"2")
    assert len(list(eng.prefix(b"\xff"))) == 2
    assert len(list(eng.prefix(b"\xff\xff"))) == 1


def test_write_version_counts_mutations(eng):
    v0 = eng.write_version
    eng.put(b"a", b"1")
    eng.multi_put([(b"b", b"2"), (b"c", b"3")])
    eng.remove(b"a")
    assert eng.write_version == v0 + 3


def test_approximate_size(eng):
    assert eng.approximate_size() == 0
    eng.put(b"abc", b"defg")
    assert eng.approximate_size() == 7
    # LSM semantics: a remove writes a tombstone, so the APPROXIMATE
    # size may retain the key's bytes until compaction folds it away
    eng.remove(b"abc")
    assert 0 <= eng.approximate_size() <= 7
    assert eng.get(b"abc") is None


def test_checkpoint_roundtrip(tmp_path):
    path = str(tmp_path / "ckpt.nkv")
    e = NativeEngine(path)
    e.multi_put([(b"k%03d" % i, b"v%d" % i) for i in range(100)])
    assert e.flush().ok()
    e.close()
    e2 = NativeEngine(path)
    assert e2.total_keys() == 100
    assert e2.get(b"k050") == b"v50"
    assert [k for k, _ in e2.prefix(b"k09")] == [b"k09%d" % i
                                                for i in range(10)]
    e2.close()


def test_checkpoint_corrupt_rejected(tmp_path):
    path = str(tmp_path / "bad.nkv")
    e = NativeEngine(path)
    e.put(b"a", b"b")
    e.flush()
    e.close()
    # the image lives under a generation name (manifest-committed)
    bases = [f for f in os.listdir(tmp_path)
             if f.startswith("bad.nkv.base")] or ["bad.nkv"]
    target = str(tmp_path / bases[0])
    with open(target, "r+b") as f:
        f.truncate(os.path.getsize(target) - 4)   # chop the trailer
    with pytest.raises(OSError):
        NativeEngine(path)


def test_dedup_scan_newest_version_wins(eng):
    """Keys are version-suffixed with inverted timestamps, so the first
    row of each (rank,dst) group is the newest (ref collectEdgeProps
    version dedupe, QueryBaseProcessor.inl:403-407)."""
    part, src, etype = 1, 100, 7
    # versions are inverted timestamps: SMALLER sorts first = newer
    k_new = ku.edge_key(part, src, etype, 0, 200, version=1000)
    k_old = ku.edge_key(part, src, etype, 0, 200, version=2000)
    k_other = ku.edge_key(part, src, etype, 0, 201, version=500)
    eng.multi_put([(k_old, b"old"), (k_new, b"new"), (k_other, b"x")])
    hits = eng.prefix_dedup(ku.edge_prefix(part, src, etype))
    assert [v for _, v in hits] == [b"new", b"x"]
    # plain scan sees all three
    assert len(list(eng.prefix(ku.edge_prefix(part, src, etype)))) == 3


def test_large_values(eng):
    blob = os.urandom(1 << 20)
    eng.put(b"big", blob)
    assert eng.get(b"big") == blob


def test_engine_under_graphstore(tmp_path):
    """NativeEngine slots into GraphStore via the engine factory seam."""
    from nebula_tpu.kvstore import GraphStore
    store = GraphStore(engine_factory=lambda sid: NativeEngine())
    store.add_part(1, 1)
    assert store.async_multi_put(1, 1, [(b"\x01a", b"1")]).ok()
    assert store.get(1, 1, b"\x01a").value() == b"1"


def test_native_codec_matches_python_columns(monkeypatch, tmp_path):
    """nbc_decode_batch column build == pure-Python column build
    (values, nulls, device arrays, string dicts, TTL)."""
    import numpy as np
    import time
    from nebula_tpu.codec import PropType, RowWriter, Schema, SchemaField
    from nebula_tpu.engine_tpu import csr as csr_mod

    schema = Schema([SchemaField("name", PropType.STRING),
                     SchemaField("age", PropType.INT),
                     SchemaField("w", PropType.DOUBLE),
                     SchemaField("ok", PropType.BOOL),
                     SchemaField("big", PropType.INT)])
    now = time.time()
    rows = []
    for i in range(7):
        w = RowWriter(schema)
        if i != 3:
            w.set("name", f"s{i % 2}")      # repeated -> shared dict codes
        w.set("age", 10 * i)
        if i != 5:
            w.set("w", i / 4)
        w.set("ok", i % 2 == 0)
        w.set("big", (1 << 40) if i == 6 else i)   # forces host-only col
        rows.append((i * 3, w.encode()))
    cap = 32

    reg_n, reg_p = {}, {}
    native_cols = csr_mod._native_build_columns(schema, cap, rows, now,
                                                reg_n, ("e",))
    assert native_cols is not None, "native lib should be available in CI"
    monkeypatch.setattr("nebula_tpu.native.available", lambda: False)
    python_cols = csr_mod._build_columns(schema, cap, rows, now,
                                         reg_p, ("e",))
    assert set(native_cols) == set(python_cols)
    for name in python_cols:
        pn, pp = native_cols[name], python_cols[name]
        assert pn.device_ok == pp.device_ok, name
        assert np.array_equal(pn.present, pp.present), name
        # read through the host_item contract: numeric mirrors are
        # plain numpy arrays with nulls riding `present`
        from nebula_tpu.engine_tpu.csr import host_item
        assert [host_item(pn, i) for i in range(cap)] == \
            [host_item(pp, i) for i in range(cap)], name
        if pp.device_vals is not None:
            assert np.array_equal(pn.device_vals, pp.device_vals,
                                  equal_nan=True), name
    assert reg_n == reg_p


def test_native_codec_ttl_rows_nulled(monkeypatch):
    import time
    from nebula_tpu.codec import PropType, RowWriter, Schema, SchemaField
    from nebula_tpu.engine_tpu import csr as csr_mod

    schema = Schema([SchemaField("ts", PropType.TIMESTAMP),
                     SchemaField("x", PropType.INT)],
                    ttl_col="ts", ttl_duration=100)
    now = time.time()
    rows = [(0, RowWriter(schema).set("ts", int(now) - 500).set("x", 1).encode()),
            (1, RowWriter(schema).set("ts", int(now)).set("x", 2).encode())]
    cols = csr_mod._native_build_columns(schema, 4, rows, now, {}, ("t",))
    assert cols is not None
    from nebula_tpu.engine_tpu.csr import host_item
    assert host_item(cols["x"], 0) is None   # expired row invisible
    assert host_item(cols["x"], 1) == 2


def test_native_codec_invalid_utf8_row_invisible(monkeypatch):
    """Both codec paths drop the ENTIRE row on invalid UTF-8."""
    import time
    from nebula_tpu.codec import PropType, RowWriter, Schema, SchemaField
    from nebula_tpu.engine_tpu import csr as csr_mod
    schema = Schema([SchemaField("s", PropType.STRING),
                     SchemaField("x", PropType.INT)])
    good = RowWriter(schema).set("s", "fine").set("x", 1).encode()
    bad = RowWriter(schema).set("s", b"\xff\xfe\xff").set("x", 2).encode()
    now = time.time()
    n_cols = csr_mod._native_build_columns(schema, 4, [(0, good), (1, bad)],
                                           now, {}, ("e",))
    from nebula_tpu.engine_tpu.csr import host_item
    assert host_item(n_cols["x"], 0) == 1
    assert host_item(n_cols["x"], 1) is None
    assert host_item(n_cols["s"], 1) is None
    import nebula_tpu.native as native
    monkeypatch.setattr(native, "available", lambda: False)
    p_cols = csr_mod._build_columns(schema, 4, [(0, good), (1, bad)],
                                    now, {}, ("e",))
    assert host_item(p_cols["x"], 1) is None
    assert host_item(p_cols["s"], 1) is None


def test_native_codec_non_numeric_ttl_never_expires(monkeypatch):
    """String ttl_col never expires — native must match the Python
    path's isinstance numeric check."""
    import time
    from nebula_tpu.codec import PropType, RowWriter, Schema, SchemaField
    from nebula_tpu.engine_tpu import csr as csr_mod
    schema = Schema([SchemaField("name", PropType.STRING),
                     SchemaField("x", PropType.INT)],
                    ttl_col="name", ttl_duration=100)
    rows = [(0, RowWriter(schema).set("name", "n").set("x", 7).encode())]
    now = time.time()
    cols = csr_mod._native_build_columns(schema, 2, rows, now, {}, ("t",))
    from nebula_tpu.engine_tpu.csr import host_item
    assert host_item(cols["x"], 0) == 7   # visible: string ttl is a no-op


# ---------------------------------------------------------------------------
# mini-LSM behavior: incremental run persistence, crash recovery,
# background merge, shared-lock readers (VERDICT r2 item 4; ref role:
# RocksEngine.cpp:123-138,360)
# ---------------------------------------------------------------------------

def _packed(rows):
    import struct
    out = []
    for k, v in rows:
        out.append(struct.pack("<I", len(k)) + k + struct.pack("<I", len(v)) + v)
    return b"".join(out), len(rows)


def test_ingest_lands_as_run_and_recovers_after_crash(tmp_path):
    """A flushed/ingested run persists incrementally: reopening WITHOUT
    any checkpoint call recovers it (the memtable alone rides the WAL,
    exactly the reference's RocksDB+WAL split)."""
    path = str(tmp_path / "lsm.nkv")
    e = NativeEngine(path)
    rows = [(b"k%06d" % i, b"v%d" % i) for i in range(5000)]
    buf, n = _packed(rows)
    assert e.ingest_packed(buf, n).ok()
    # memtable-only write on top (lost on crash, recovered via WAL above)
    e.put(b"zz-memtable-only", b"1")
    del e  # simulate crash: NO checkpoint/flush
    e2 = NativeEngine(path)
    assert e2.get(b"k000123") == b"v123"      # run survived
    assert e2.total_keys() >= 5000
    e2.close()


def test_tombstones_survive_runs_and_merge(tmp_path):
    path = str(tmp_path / "lsm2.nkv")
    e = NativeEngine(path)
    rows = [(b"a%04d" % i, b"x") for i in range(100)]
    buf, n = _packed(rows)
    assert e.ingest_packed(buf, n).ok()
    e.remove(b"a0050")
    assert e.get(b"a0050") is None
    # the deleted key stays invisible through scans too
    ks, _ = e.scan_batch(b"a")
    assert b"a0050" not in ks and len(ks) == 99
    # and through a full checkpoint + reopen
    assert e.checkpoint(path).ok()
    e.close()
    e2 = NativeEngine(path)
    assert e2.get(b"a0050") is None
    assert e2.total_keys() == 99
    e2.close()


def test_many_ingests_trigger_background_merge(tmp_path):
    """More than 8 runs kicks the background compaction; results stay
    identical through and after the merge."""
    import time as _t
    path = str(tmp_path / "lsm3.nkv")
    e = NativeEngine(path)
    for r in range(12):
        rows = [(b"r%02d-%04d" % (r, i), b"v%d" % r) for i in range(200)]
        buf, n = _packed(rows)
        assert e.ingest_packed(buf, n).ok()
    deadline = _t.time() + 10
    while _t.time() < deadline and e.total_keys() != 12 * 200:
        _t.sleep(0.05)
    assert e.total_keys() == 12 * 200
    assert e.get(b"r07-0100") == b"v7"
    ks, _ = e.scan_batch(b"r03-")
    assert len(ks) == 200
    e.close()


def test_overwrite_across_runs_newest_wins(tmp_path):
    e = NativeEngine(str(tmp_path / "lsm4.nkv"))
    buf, n = _packed([(b"dup", b"old"), (b"other", b"o")])
    assert e.ingest_packed(buf, n).ok()
    buf, n = _packed([(b"dup", b"new")])
    assert e.ingest_packed(buf, n).ok()
    assert e.get(b"dup") == b"new"
    ks, vs = e.scan_batch(b"dup")
    assert vs == [b"new"]
    e.put(b"dup", b"newest")       # memtable wins over every run
    assert e.get(b"dup") == b"newest"
    e.close()


def test_concurrent_readers_progress_during_writes():
    """Shared-lock read path: many reader threads make progress while a
    writer streams (the round-2 verdict's zero-read-parallelism
    finding). ctypes releases the GIL during native calls, so reader
    threads really do overlap inside the engine."""
    import threading
    e = NativeEngine()
    rows = [(b"c%05d" % i, b"v" * 32) for i in range(20000)]
    buf, n = _packed(rows)
    assert e.ingest_packed(buf, n).ok()
    stop = threading.Event()
    counts = [0] * 4
    errors = []

    def reader(slot):
        while not stop.is_set():
            if e.get(b"c00042") != b"v" * 32:
                errors.append("bad read")
                return
            counts[slot] += 1

    threads = [threading.Thread(target=reader, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for i in range(2000):
        e.put(b"w%05d" % i, b"x")
    stop.set()
    for t in threads:
        t.join()
    assert not errors
    assert all(c > 0 for c in counts), counts
    e.close()


def test_ingest_overwrites_memtable_entries(tmp_path):
    """Ingested rows must win over OLDER memtable writes (the engine
    freezes the memtable before landing the ingest run)."""
    e = NativeEngine(str(tmp_path / "lsm5.nkv"))
    e.put(b"dup", b"mem-old")
    e.remove(b"gone")                       # tombstone older than ingest
    buf, n = _packed([(b"dup", b"ingested"), (b"gone", b"back")])
    assert e.ingest_packed(buf, n).ok()
    assert e.get(b"dup") == b"ingested"
    assert e.get(b"gone") == b"back"
    e.close()


def test_native_multi_get_matches_get():
    """Batched lookups (one FFI call, one shared-lock hold) return
    exactly what per-key get() returns, including misses, tombstones,
    memtable overrides of run values, and empty values."""
    import struct
    from nebula_tpu.kvstore.nativeengine import NativeEngine
    e = NativeEngine()
    rows = b"".join(struct.pack("<I", 3) + b"k%02d" % i
                    + struct.pack("<I", 3) + b"v%02d" % i
                    for i in range(50))
    assert e.ingest_packed(rows, 50).ok()
    e.put(b"k07", b"override")      # memtable shadows the run
    e.remove(b"k09")                # tombstone
    e.put(b"kZZ", b"")              # empty value
    keys = ([b"k%02d" % i for i in range(50)]
            + [b"missing", b"k07", b"k09", b"kZZ"])
    batched = e.multi_get(keys)
    singles = [e.get(k) for k in keys]
    assert batched == singles
    assert batched[keys.index(b"k07")] == b"override"
    assert batched[keys.index(b"k09")] is None
    assert batched[keys.index(b"kZZ")] == b""
    assert e.multi_get([]) == []
    e.close()


def test_counting_sort_matches_numpy_and_caps_range():
    import numpy as np
    from nebula_tpu import native
    if not native.available():
        import pytest
        pytest.skip("native lib not built")
    rng = np.random.default_rng(3)
    keys = rng.integers(0, 1000, 20_000).astype(np.uint32)
    order = native.stable_counting_sort(keys, 1000)
    assert order is not None
    ref = np.argsort(keys, kind="stable")
    assert np.array_equal(order, ref)
    # a huge key range would allocate threads*n_keys*8B of histograms
    # (hundreds of GiB at 2^32) — must decline so callers fall back to
    # numpy instead of dying in malloc
    assert native.stable_counting_sort(keys, 1 << 25) is None


def test_nullable_schema_builds_missing_masks():
    """A nullable field must force real `missing` masks: the
    missing=None fast representation encodes "~present ⇒ err", which
    would silently turn explicit NULLs into EvalError when delta
    materializes the mask as ~present (round-3 advisor finding)."""
    import time
    from nebula_tpu.codec import PropType, RowWriter, Schema, SchemaField
    from nebula_tpu.engine_tpu import csr as csr_mod

    schema = Schema([SchemaField("x", PropType.INT),
                     SchemaField("opt", PropType.INT, nullable=True)])
    now = time.time()
    rows = [(0, RowWriter(schema).set("x", 1).set("opt", 5).encode()),
            (1, RowWriter(schema).set("x", 2).encode())]   # opt -> NULL
    cols = csr_mod._build_columns(schema, 4, rows, now, {}, ("t",))
    c = cols["opt"]
    assert c.missing is not None
    assert c.present[0] and not c.missing[0]          # real value
    assert not c.present[1] and not c.missing[1]      # explicit NULL
    assert not c.present[2] and c.missing[2]          # no row: err
    # the non-nullable sibling column sees the no-row slot as err too
    # (whether via a mask or the fast ~present representation)
    cx = cols["x"]
    assert cx.present[0] and cx.present[1]


def test_engine_option_hot_set_controls_flush_and_merge():
    """nkv_set_option (config-registry hook, ref role: hot-applied
    rocksdb option maps, RocksEngineConfig.cpp): a smaller flush
    threshold freezes the memtable into runs; max_runs drives merge."""
    from nebula_tpu import native
    from nebula_tpu.kvstore.nativeengine import NativeEngine
    if not native.available():
        import pytest
        pytest.skip("native lib not built")
    e = NativeEngine()
    assert e.get_option("flush_bytes") == 64 << 20
    assert e.get_option("max_runs") == 8
    assert e.get_option("nope") is None
    assert not e.set_option("nope", 1).ok()
    assert not e.set_option("flush_bytes", 16).ok()   # below floor
    assert e.run_count() == 0
    assert e.set_option("flush_bytes", 4096).ok()
    assert e.set_option("max_runs", 2).ok()
    for i in range(2000):
        e.put(b"k%05d" % i, b"v" * 64)
    assert e.run_count() >= 1
    # every key still readable through the memtable+runs merged view
    assert e.get(b"k00000") == b"v" * 64
    assert e.get(b"k01999") == b"v" * 64
    e.close()
