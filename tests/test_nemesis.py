"""Network nemesis + peer health + hedged fan-out (ISSUE 18).

Three layers under test:

- the fault-plan LINK grammar (`peer=` rules in common/faults.py) and
  the `link_actions` decision point the real TCP transport consults on
  every framed exchange — drop, added latency/jitter, blackhole
  (accept-then-hang), duplicate delivery, one-way rules;
- the transport-level injection itself against real localhost RPC
  servers (drops absorbed by the reconnect machinery, hangs bounded by
  the socket timeout AND the per-query deadline clamp, duplicates
  leaving the framed stream aligned);
- the StorageClient data-path reaction: per-peer health scoring
  (consecutive-failure + latency-outlier ejection, half-open recovery)
  and budget-capped hedged reads — plus the satellite scope contract
  that raft election/replication NEVER consults peer health, so a
  gray (blackholed) follower neither stalls the leader's pipeline nor
  loses its vote.
"""
import socket
import threading
import time

import pytest

from nebula_tpu.common.faults import Nemesis, faults
from nebula_tpu.common.status import ErrorCode
from nebula_tpu.rpc import transport
from nebula_tpu.rpc.transport import RpcError, RpcServer, proxy
from nebula_tpu.storage.client import PeerHealth, StorageClient
from nebula_tpu.storage.types import (DevicePartResult,
                                      DeviceWindowResponse, VertexData)
from raft_fixture import FAST, RpcRaftCluster


@pytest.fixture(autouse=True)
def _clean_faults():
    """The registry is process-global: never leak a link rule into
    another test (a stray blackhole would wedge unrelated RPC tests)."""
    faults.reset()
    yield
    faults.reset()


class _Echo:
    def ping(self, x):
        return x + 1


@pytest.fixture
def echo_server():
    srv = RpcServer().register("svc", _Echo()).start()
    yield srv
    srv.stop()


# ---------------------------------------------------------------------------
# link-rule grammar
# ---------------------------------------------------------------------------

def test_link_plan_parse_and_describe():
    faults.set_link_plan(
        "split:peer=a>b,hang=1;slow:peer=*>c,latency=20,jitter=10,p=0.5")
    links = faults.describe()["links"]
    assert len(links) == 2
    by_label = {l["label"]: l for l in links}
    assert by_label["split"]["peer"] == "a>b"
    assert by_label["split"]["hang"] == 1.0
    assert by_label["slow"]["peer"] == "*>c"
    assert by_label["slow"]["latency_ms"] == 20.0
    assert by_label["slow"]["jitter_ms"] == 10.0
    assert by_label["slow"]["p"] == 0.5


@pytest.mark.parametrize("bad", [
    "x:peer=a>b,hang=1,after=3",     # after= is point-spec-only
    "x:drop=0.5",                    # link arg without peer=
    "x:peer=,hang=1",                # empty peer
    "x:hang=1",                      # hang without peer
])
def test_link_plan_rejects_malformed(bad):
    with pytest.raises(ValueError):
        faults.set_plan(bad)


def test_set_link_plan_rejects_point_specs():
    with pytest.raises(ValueError):
        faults.set_link_plan("rpc.send:n=1")


def test_set_link_plan_preserves_point_specs():
    """set_link_plan swaps only the nemesis layer: a kernel/point fault
    plan armed for the same run survives link re-arming and healing."""
    faults.set_plan("rpc.send:n=1")
    faults.set_link_plan("s:peer=a>b,drop=1")
    d = faults.describe()
    assert d["links"]
    assert "rpc.send" in d["active"]          # point spec still armed
    faults.clear_links()
    d = faults.describe()
    assert not d["links"] and "rpc.send" in d["active"]


def test_link_actions_directional_and_wildcard():
    faults.set_link_plan("oneway:peer=a>b,hang=1;anon:peer=*>c,drop=1")
    assert faults.link_actions("a", "b") == {"hang": True}
    assert faults.link_actions("b", "a") is None        # reverse clean
    assert faults.link_actions("x", "b") is None        # src mismatch
    # src=None (an anonymous client) matches only wildcard-src rules
    assert faults.link_actions(None, "b") is None
    assert faults.link_actions(None, "c") == {"drop": True}
    assert faults.counts()["oneway"] == 1
    assert faults.counts()["anon"] == 1


def test_link_actions_budget_n():
    faults.set_link_plan("two:peer=*>b,drop=1,n=2")
    assert faults.link_actions("a", "b")
    assert faults.link_actions("a", "b")
    assert faults.link_actions("a", "b") is None        # budget spent
    assert faults.counts()["two"] == 2


def test_nemesis_scenario_builders():
    plan = Nemesis.symmetric_split(["a"], ["b", "c"])
    acts = []
    n = Nemesis(apply_plan=acts.append)
    n.apply(plan)
    assert n.installed == plan
    n.heal()
    assert n.installed == ""
    assert acts == [plan, ""]
    # symmetric split covers both directions of every cross pair
    faults.set_link_plan(plan)
    for a, b in (("a", "b"), ("b", "a"), ("a", "c"), ("c", "a")):
        assert faults.link_actions(a, b) == {"hang": True}
    # within a side: clean
    assert faults.link_actions("b", "c") is None
    faults.set_link_plan(Nemesis.asymmetric_split(["a"], ["b"]))
    assert faults.link_actions("a", "b") == {"hang": True}
    assert faults.link_actions("b", "a") is None        # one-way
    faults.set_link_plan(Nemesis.slow_node(["b"], latency_ms=30))
    acts = faults.link_actions("anyone", "b")
    assert acts and acts["latency_s"] == pytest.approx(0.030)


# ---------------------------------------------------------------------------
# transport injection over real localhost TCP
# ---------------------------------------------------------------------------

def test_transport_latency_injection(echo_server):
    c = proxy(echo_server.addr, "svc", timeout=5.0)
    assert c.ping(1) == 2                               # pool primed
    faults.set_link_plan(f"slow:peer=*>{echo_server.addr},latency=80")
    t0 = time.monotonic()
    assert c.ping(2) == 3
    assert time.monotonic() - t0 >= 0.07
    faults.clear_links()
    t0 = time.monotonic()
    assert c.ping(3) == 4
    assert time.monotonic() - t0 < 0.07                 # healed


def test_transport_drop_absorbed_by_retry(echo_server):
    """An injected frame drop is a ConnectionError subclass, so the
    production reconnect machinery retries it transparently."""
    c = proxy(echo_server.addr, "svc", timeout=5.0)
    assert c.ping(1) == 2
    faults.set_link_plan(f"lossy:peer=*>{echo_server.addr},drop=1,n=1")
    n0 = transport.rpc_stats["reconnects"]
    assert c.ping(41) == 42
    assert faults.counts()["lossy"] == 1
    assert transport.rpc_stats["reconnects"] - n0 >= 1


def test_transport_blackhole_bounded_then_heals(echo_server):
    """hang= accepts the connection and never answers — the gray
    shape. The client burns its (short) timeout, not forever, and the
    link serves again the moment the nemesis heals."""
    c = proxy(echo_server.addr, "svc", timeout=0.3, max_attempts=1)
    assert c.ping(1) == 2
    faults.set_link_plan(f"bh:peer=*>{echo_server.addr},hang=1")
    t0 = time.monotonic()
    with pytest.raises(RpcError):
        c.ping(2)
    dt = time.monotonic() - t0
    assert 0.2 <= dt < 2.0, dt
    faults.clear_links()
    assert c.ping(3) == 4


def test_transport_duplicate_keeps_stream_aligned(echo_server):
    """dup= sends the frame twice; the client must drain the duplicate
    response so the NEXT call on the pooled connection still reads its
    own answer (a one-frame skew poisons every later exchange)."""
    c = proxy(echo_server.addr, "svc", timeout=5.0)
    assert c.ping(1) == 2
    faults.set_link_plan(f"dup:peer=*>{echo_server.addr},dup=1,n=1")
    assert c.ping(10) == 11
    assert faults.counts()["dup"] == 1
    for i in range(5):                                  # stream aligned
        assert c.ping(i) == i + 1


# ---------------------------------------------------------------------------
# satellite: per-query deadline clamps transport waits
# ---------------------------------------------------------------------------

def test_query_deadline_clamps_hung_listener():
    """A listener that accepts and never answers must cost a caller
    its QUERY deadline, not the transport's (much larger) socket
    timeout — retry budgets must not outlive the query."""
    from nebula_tpu.common import qos

    lst = socket.socket()
    lst.bind(("127.0.0.1", 0))
    lst.listen(4)
    addr = "127.0.0.1:%d" % lst.getsockname()[1]
    try:
        c = proxy(addr, "svc", timeout=5.0)
        tok = qos.set_query_deadline(time.monotonic() + 0.4)
        try:
            t0 = time.monotonic()
            with pytest.raises(RpcError):
                c.ping(1)
            assert time.monotonic() - t0 < 2.0          # not 5s
        finally:
            qos.clear_query_deadline(tok)
    finally:
        lst.close()


def test_exhausted_deadline_balks_without_waiting():
    from nebula_tpu.common import qos

    c = proxy("127.0.0.1:1", "svc", timeout=5.0)
    tok = qos.set_query_deadline(time.monotonic() - 0.1)
    try:
        t0 = time.monotonic()
        with pytest.raises(RpcError, match="deadline"):
            c.ping(1)
        assert time.monotonic() - t0 < 0.5
    finally:
        qos.clear_query_deadline(tok)


# ---------------------------------------------------------------------------
# peer health scoring
# ---------------------------------------------------------------------------

def test_peer_health_consecutive_failures_eject_and_recover():
    ph = PeerHealth()
    for _ in range(PeerHealth.EJECT_AFTER - 1):
        ph.observe_failure("h1")
    assert not ph.ejected("h1")
    ph.observe_failure("h1")
    assert ph.ejected("h1")
    assert ph.counts["ejected"] == 1
    # live traffic reaching it in the half-open window recovers it
    ph.observe("h1", 5.0)
    assert not ph.ejected("h1")
    assert ph.counts["recovered"] == 1


def test_peer_health_latency_outlier_ejects_gray_node():
    """The gray shape: a node that never errors but is consistently
    slow gets ejected on the EWMA outlier rule (vs cross-peer median,
    past the absolute floor)."""
    ph = PeerHealth()
    for _ in range(10):
        ph.observe("fast1", 4.0)
        ph.observe("fast2", 5.0)
        ph.observe("gray", 300.0)
    assert ph.ejected("gray")
    assert not ph.ejected("fast1") and not ph.ejected("fast2")
    snap = ph.snapshot()
    assert snap["peers"]["gray"]["ejections"] >= 1


def test_peer_health_slow_answer_never_readmits():
    """A slow-but-successful answer from an ejected peer — e.g. a
    response that was already in flight at ejection time — must NOT
    re-admit it (that makes the ejection flap); it widens the
    half-open window. Only a healthy-fast answer recovers."""
    ph = PeerHealth()
    for _ in range(10):
        ph.observe("fast1", 4.0)
        ph.observe("fast2", 5.0)
        ph.observe("gray", 300.0)
    assert ph.ejected("gray")
    backoff0 = ph._peers["gray"]["backoff"]
    ph.observe("gray", 280.0)           # late in-flight slow response
    assert ph.ejected("gray")           # still out
    assert ph._peers["gray"]["backoff"] > backoff0   # window widened
    assert ph.counts["recovered"] == 0
    ph.observe("gray", 5.0)             # healed: fast answer
    assert not ph.ejected("gray")
    assert ph.counts["recovered"] == 1


def test_peer_health_never_ejects_under_absolute_floor():
    """4x the median of sub-millisecond peers is still fast — the
    OUTLIER_MIN_MS floor keeps relative outliers below it in-pool."""
    ph = PeerHealth()
    for _ in range(12):
        ph.observe("a", 1.0)
        ph.observe("b", 1.0)
        ph.observe("c", 20.0)   # 20x the median, but under 50ms
    assert not ph.ejected("c")


def test_peer_health_ejection_window_lapses():
    ph = PeerHealth()
    for _ in range(PeerHealth.EJECT_AFTER):
        ph.observe_failure("h1")
    assert ph.ejected("h1")
    ph._peers["h1"]["until"] = time.monotonic() - 0.01
    assert not ph.ejected("h1")         # half-open: traffic may probe


def test_peer_health_background_probe_recovers():
    recovered = threading.Event()

    def probe(host):
        recovered.set()
        return True

    ph = PeerHealth(probe=probe)
    ph.BASE_BACKOFF_S = 0.02            # fast probe for the test
    for _ in range(PeerHealth.EJECT_AFTER):
        ph.observe_failure("h1")
    assert recovered.wait(2.0)
    deadline = time.monotonic() + 2.0
    while ph.ejected("h1") and time.monotonic() < deadline:
        time.sleep(0.01)
    assert not ph._peers["h1"]["ejected"]
    assert ph.counts["probes"] >= 1
    ph.close()


def test_hedge_delay_tracks_p95():
    ph = PeerHealth()
    assert ph.hedge_delay_s() == PeerHealth.HEDGE_DEFAULT_S
    for _ in range(50):
        ph.observe("h", 20.0)
    assert ph.hedge_delay_s() == pytest.approx(0.020)
    for _ in range(100):
        ph.observe("h", 2000.0)
    assert ph.hedge_delay_s() == PeerHealth.HEDGE_CAP_S


# ---------------------------------------------------------------------------
# hedged device-window fan-out
# ---------------------------------------------------------------------------

class _SM:
    def num_parts(self, space_id):
        return 4


class _DevSvc:
    """In-proc device_window endpoint: answers every requested part
    with one vertex per frontier vid (after an optional delay)."""

    def __init__(self, name, delay=0.0):
        self.name = name
        self.delay = delay
        self.calls = []

    def device_window(self, req):
        self.calls.append(sorted(req.parts))
        if self.delay:
            time.sleep(self.delay)
        resp = DeviceWindowResponse(host=self.name)
        for p, vids in req.parts.items():
            resp.results[p] = DevicePartResult(
                code=ErrorCode.SUCCEEDED, mode="follower")
            resp.vertices.extend(VertexData(vid=v) for v in vids)
        return resp


def _client(svcs):
    return StorageClient(_SM(), hosts=dict(svcs),
                         part_to_host=lambda s, p: "L")


def test_hedged_read_wins_over_straggler():
    """A straggling replica's parts are re-issued to the leader after
    the hedge delay; first response wins, the window completes at
    hedge speed, and no vertex is double-counted."""
    slow = _DevSvc("B", delay=0.6)
    svcs = {"L": _DevSvc("L"), "A": _DevSvc("A"), "B": slow}
    client = _client(svcs)
    try:
        t0 = time.monotonic()
        resp = client.device_window(1, list(range(8)), [],
                                    allow_follower=True,
                                    follower_max_ms=500)
        dt = time.monotonic() - t0
        assert dt < 0.5, dt                       # did not wait out B
        assert set(resp.results) == {1, 2, 3, 4}
        assert all(r.code == ErrorCode.SUCCEEDED
                   for r in resp.results.values())
        got = sorted(v.vid for v in resp.vertices)
        assert got == list(range(8))              # complete, no dups
        assert client.hedge_stats["issued"] >= 1
        assert client.hedge_stats["won"] >= 1
        # the hedge win marked the straggler in the health scorer
        snap = client.peer_health.snapshot()
        assert snap["peers"]["B"]["straggles"] >= 1
    finally:
        client.close()


def test_hedge_budget_caps_extra_load():
    """With the token bucket drained, stragglers are NOT hedged — the
    round waits them out instead of doubling cluster load."""
    slow = _DevSvc("B", delay=0.15)
    svcs = {"L": _DevSvc("L"), "A": _DevSvc("A"), "B": slow}
    client = _client(svcs)
    try:
        client._hedge_tokens = -1000.0            # drained far below 0
        resp = client.device_window(1, list(range(8)), [],
                                    allow_follower=True,
                                    follower_max_ms=500)
        assert client.hedge_stats["issued"] == 0
        assert client.hedge_stats["capped"] >= 1
        assert all(r.code == ErrorCode.SUCCEEDED
                   for r in resp.results.values())
        assert sorted(v.vid for v in resp.vertices) == list(range(8))
    finally:
        client.close()


def test_ejected_peer_leaves_spread_candidate_set():
    svcs = {"L": _DevSvc("L"), "A": _DevSvc("A"), "B": _DevSvc("B")}
    client = _client(svcs)
    try:
        for _ in range(PeerHealth.EJECT_AFTER):
            client.peer_health.observe_failure("B")
        assert client.peer_health.ejected("B")
        resp = client.device_window(1, list(range(8)), [],
                                    allow_follower=True,
                                    follower_max_ms=500)
        assert not svcs["B"].calls                # no data traffic to B
        assert all(r.code == ErrorCode.SUCCEEDED
                   for r in resp.results.values())
        stats = client.routing_stats()
        assert stats["peer_health"]["peers"]["B"]["ejected"]
        assert "hedge" in stats
    finally:
        client.close()


# ---------------------------------------------------------------------------
# raft under nemesis: bounded in-flight + the peer-health scope contract
# ---------------------------------------------------------------------------

def test_blackholed_follower_does_not_stall_leader_pipeline(tmp_path):
    """Tentpole: blackhole ONE follower of a real-TCP raft trio. The
    leader must keep committing at quorum speed (bounded per-peer
    in-flight parks the dead send instead of re-waiting rpc_timeout
    every round), and the follower must catch up after heal."""
    c = RpcRaftCluster(3, tmp_path)
    try:
        leader = c.wait_leader()
        assert leader.append_async(b"w0").result(timeout=3) \
            is not None
        gray = next(a for a in c.addrs if a != leader.addr)
        faults.set_link_plan(f"bh:peer=*>{gray},hang=1")
        live = [a for a in c.addrs if a != gray]
        t0 = time.monotonic()
        for i in range(10):
            f = leader.append_async(b"w%d" % (i + 1))
            assert f.result(timeout=5) is not None
        c.wait_commit(11, addrs=live, timeout=5)
        dt = time.monotonic() - t0
        # sequential-gather would pay ~rpc_timeout per round; bounded
        # in-flight keeps the 10 writes well under that regime
        assert dt < 10 * FAST["rpc_timeout"], dt
        assert faults.counts().get("bh", 0) >= 1      # it really hung
        faults.clear_links()
        c.wait_commit(11, addrs=[gray], timeout=10)   # skip-and-catch-up
    finally:
        faults.clear_links()
        c.stop()


def test_gray_node_still_votes_and_catches_up(tmp_path):
    """Satellite: peer health governs only the DATA fan-out. A slow
    (gray) raft peer keeps its consensus duties: it still receives
    appends, and when the leader is partitioned away it still VOTES —
    the remaining pair elects a leader even though one of them is
    gray."""
    c = RpcRaftCluster(3, tmp_path)
    try:
        leader = c.wait_leader()
        gray = next(a for a in c.addrs if a != leader.addr)
        faults.set_link_plan(Nemesis.slow_node([gray], latency_ms=60))
        for i in range(3):
            assert leader.append_async(b"g%d" % i).result(timeout=5) \
                is not None
        c.wait_commit(3, timeout=8)                  # gray caught up
        # partition the leader away: the survivors (one gray) must
        # elect — a health-style ejection of the gray peer from raft
        # would leave no quorum here
        c.isolate(leader.addr)
        survivors = [a for a in c.addrs if a != leader.addr]
        newl = c.wait_leader(timeout=8, among=survivors)
        assert newl.addr in survivors
        assert newl.append_async(b"after").result(timeout=5) is not None
    finally:
        faults.clear_links()
        c.stop()


# ---------------------------------------------------------------------------
# /nemesis admin surface
# ---------------------------------------------------------------------------

def test_nemesis_web_surface():
    import json
    import urllib.error
    import urllib.request

    from nebula_tpu.webservice import WebService

    ws = WebService("nemesis-test")
    port = ws.start()
    try:
        url = f"http://127.0.0.1:{port}/nemesis"
        with urllib.request.urlopen(url) as r:
            assert json.loads(r.read()) == {"links": [], "fired": {}}
        req = urllib.request.Request(
            url, data=b"plan=s:peer=a>b,drop=1", method="PUT")
        with urllib.request.urlopen(req) as r:
            out = json.loads(r.read())
        assert len(out["links"]) == 1
        assert faults.link_actions("a", "b") == {"drop": True}
        # malformed plan -> 400, state unchanged
        req = urllib.request.Request(
            url, data=b"plan=s:drop=1", method="PUT")
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(req)
        assert faults.describe()["links"]
        req = urllib.request.Request(
            url + "?clear=1", data=b"", method="PUT")
        with urllib.request.urlopen(req):
            pass
        assert faults.describe()["links"] == []
    finally:
        ws.stop()
