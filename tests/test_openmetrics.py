"""/metrics format conformance (ISSUE 10 satellite).

The strict OpenMetrics parser (tests/openmetrics.py) first proves
itself on crafted good/bad documents, then scrapes the REAL
graphd/storaged/metad /metrics handlers and fails on any malformed
line, duplicate series/family, counter named without `_total`,
non-cumulative histogram, misplaced exemplar or missing `# EOF` —
today's answer to "nothing validates exposition output"."""
import json as _json
import time
import urllib.request

import pytest

from openmetrics import (OpenMetricsError, exemplar_trace_ids, parse)

GOOD = """\
# TYPE acme_requests counter
acme_requests_total 5 # {trace_id="deadbeef"} 1.5 1700000000.000
# TYPE acme_lat histogram
acme_lat_bucket{le="1"} 1 # {trace_id="cafe"} 0.5
acme_lat_bucket{le="10"} 3
acme_lat_bucket{le="+Inf"} 4
acme_lat_sum 22.5
acme_lat_count 4
# TYPE acme_up gauge
acme_up 1
# TYPE acme_info gauge
acme_info{version="1.0",name="a \\"quoted\\" x"} 1
# EOF
"""


def test_parser_accepts_conformant_document():
    fams = parse(GOOD)
    assert fams["acme_requests"].type == "counter"
    assert fams["acme_lat"].type == "histogram"
    assert fams["acme_info"].samples[0].labels["name"] == 'a "quoted" x'
    ex = exemplar_trace_ids(fams)
    assert ex == {"deadbeef": "acme_requests", "cafe": "acme_lat"}


@pytest.mark.parametrize("mutate,needle", [
    # counter sample without the _total suffix
    (lambda t: t.replace("acme_requests_total 5", "acme_requests 5"),
     "outside its family"),
    # duplicate series
    (lambda t: t.replace("acme_up 1", "acme_up 1\nacme_up 2"),
     "duplicate series"),
    # duplicate family declaration
    (lambda t: t.replace("# TYPE acme_up gauge",
                         "# TYPE acme_up gauge\n# TYPE acme_up gauge"),
     "duplicate family"),
    # missing EOF
    (lambda t: t.replace("# EOF\n", ""), "EOF"),
    # EOF in the middle of the document
    (lambda t: t.replace("# TYPE acme_up gauge",
                         "# EOF\n# TYPE acme_up gauge"),
     "after # EOF"),
    # malformed line
    (lambda t: t.replace("acme_up 1", "acme_up"), "space before value"),
    # bad number
    (lambda t: t.replace("acme_up 1", "acme_up one"), "bad number"),
    # non-cumulative histogram buckets
    (lambda t: t.replace('acme_lat_bucket{le="10"} 3',
                         'acme_lat_bucket{le="10"} 0'),
     "not cumulative"),
    # _count disagreeing with +Inf
    (lambda t: t.replace("acme_lat_count 4", "acme_lat_count 9"),
     "_count != +Inf"),
    # histogram bucket ordering
    (lambda t: t.replace('le="1"', 'le="50"'), "not ascending"),
    # exemplar on a gauge
    (lambda t: t.replace("acme_up 1",
                         'acme_up 1 # {trace_id="x"} 1'),
     "exemplar not allowed"),
    # orphan sample ahead of any TYPE
    (lambda t: "orphan 1\n" + t, "outside its family"),
    # blank line
    (lambda t: t.replace("# TYPE acme_up gauge",
                         "\n# TYPE acme_up gauge"), "blank line"),
    # unknown comment
    (lambda t: t.replace("# TYPE acme_up gauge",
                         "# FROB acme_up gauge\n"
                         "# TYPE acme_up gauge"), "comment form"),
])
def test_parser_rejects_violations(mutate, needle):
    with pytest.raises(OpenMetricsError) as ei:
        parse(mutate(GOOD))
    assert needle in str(ei.value)


def test_parser_rejects_interleaved_families():
    bad = ("# TYPE a counter\n"
           "a_total 1\n"
           "# TYPE b counter\n"
           "b_total 1\n"
           "a_total 2\n"
           "# EOF\n")
    with pytest.raises(OpenMetricsError) as ei:
        parse(bad)
    # the stray sample is both an interleave AND a would-be duplicate;
    # strict association catches it first
    assert "outside its family" in str(ei.value)


# --------------------------------------------------------------------------
# the real thing: scrape every daemon's handler
# --------------------------------------------------------------------------

def _scrape(port):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics") as r:
        assert "openmetrics-text" in r.headers.get("Content-Type", "")
        return r.read().decode()


def test_three_daemon_metrics_conformance():
    """Boot metad + storaged + graphd(--tpu), push traffic through
    every layer (device serves, storage scans, a PROFILE'd query so
    at least one histogram carries an exemplar), then strictly parse
    all three expositions."""
    from nebula_tpu.client import GraphClient
    from nebula_tpu.common.flags import graph_flags
    from nebula_tpu.daemons import (serve_graphd, serve_metad,
                                    serve_storaged)
    from nebula_tpu.engine_tpu import TpuGraphEngine

    # the dispatcher/kernel/materialize histograms this test asserts
    # populate on the graphd-local fused serve path — pin it (cluster
    # scatter/gather v2 serves remote-provider GO without them)
    graph_flags.set("cluster_device_serve", False)
    metad = serve_metad(ws_port=0)
    storaged = serve_storaged(metad.addr, load_interval=0.1, ws_port=0)
    tpu = TpuGraphEngine()
    graphd = serve_graphd(metad.addr, tpu_engine=tpu, ws_port=0)
    try:
        gc = GraphClient(graphd.addr).connect()
        for s in ("CREATE SPACE om(partition_num=2)", "USE om",
                  "CREATE TAG t(x int)", "CREATE EDGE e(w int)",
                  "INSERT VERTEX t(x) VALUES 1:(5), 2:(6), 3:(7)",
                  "INSERT EDGE e(w) VALUES 1 -> 2:(3), 2 -> 3:(4)"):
            r = gc.execute(s)
            assert r.ok(), (s, r.error_msg)
        q = "GO 2 STEPS FROM 1 OVER e YIELD e.w AS w"
        for _ in range(20):
            if gc.execute(q).rows:
                break
            time.sleep(0.05)
        r = gc.execute("PROFILE " + q)   # sampled -> exemplar source
        assert r.ok(), r.error_msg

        for port, daemon in ((graphd.ws_port, "graphd"),
                             (storaged.ws_port, "storaged"),
                             (metad.ws_port, "metad")):
            text = _scrape(port)
            fams = parse(text)   # raises with the offending line
            # the fleet join key + uptime on every daemon
            info = fams["nebula_build_info"].samples[0]
            assert info.labels["daemon"] == daemon
            assert "version" in info.labels
            assert "jax_backend" in info.labels
            up = fams["nebula_process_uptime_seconds"].samples[0]
            assert up.value >= 0
        # graphd: the migrated hot-path histograms are real histograms
        gtext = _scrape(graphd.ws_port)
        gfams = parse(gtext)
        for h in ("nebula_graph_query_latency_us",
                  "nebula_tpu_engine_dispatcher_wait_us",
                  "nebula_tpu_engine_kernel_us",
                  "nebula_tpu_engine_materialize_us"):
            assert gfams[h].type == "histogram", h
            count = [s for s in gfams[h].samples
                     if s.name == h + "_count"][0]
            assert count.value > 0, h
        # the PROFILE'd query left at least one trace exemplar
        assert exemplar_trace_ids(gfams), \
            "no exemplar on any graphd histogram after PROFILE"
        # per-tenant latency slice exists for the session's space
        assert gfams["nebula_graph_space_om_latency_us"].type \
            == "histogram"
    finally:
        graph_flags.set("cluster_device_serve", True)
        graphd.stop()
        storaged.stop()
        metad.stop()


def test_profiling_families_conformance_and_federation():
    """ISSUE 13 satellite: the continuous-profiling metric families —
    nebula_lock_wait_us_* acquire-wait histograms, the
    nebula_graph_gc_pause_us GC histogram and the
    nebula_tpu_engine_compile_us XLA-compile histogram — parse
    STRICTLY on all three daemons' /metrics, and federate through
    graphd's /cluster_metrics where the parser's per-label-series
    validation checks each instance's complete bucket ladder."""
    import gc as _gc
    import threading as _threading
    from nebula_tpu.client import GraphClient
    from nebula_tpu.common import profiler as _prof
    from nebula_tpu.common.flags import graph_flags
    from nebula_tpu.daemons import (serve_graphd, serve_metad,
                                    serve_storaged)
    from nebula_tpu.engine_tpu import TpuGraphEngine

    # the device-memory ledger gauges require a graphd-LOCAL snapshot
    # — pin the dispatcher path (cluster scatter/gather v2 keeps the
    # CSR on the storaged tier)
    graph_flags.set("cluster_device_serve", False)
    metad = serve_metad(ws_port=0)
    storaged = serve_storaged(metad.addr, load_interval=0.1, ws_port=0)
    tpu = TpuGraphEngine()
    graphd = serve_graphd(metad.addr, tpu_engine=tpu, ws_port=0)
    try:
        gc = GraphClient(graphd.addr).connect()
        for s in ("CREATE SPACE omprof(partition_num=2)", "USE omprof",
                  "CREATE TAG t(x int)", "CREATE EDGE e(w int)",
                  "INSERT VERTEX t(x) VALUES 1:(5), 2:(6), 3:(7)",
                  "INSERT EDGE e(w) VALUES 1 -> 2:(3), 2 -> 3:(4)"):
            r = gc.execute(s)
            assert r.ok(), (s, r.error_msg)
        q = "GO 2 STEPS FROM 1 OVER e YIELD e.w AS w"
        for _ in range(20):
            if gc.execute(q).rows:
                break
            time.sleep(0.05)
        # deterministic instrument activity: one contended acquire on
        # a profiled lock, one full GC pass (the webservice armed the
        # GC callbacks at boot), one noted compile (prewarm usually
        # supplies real ones, but a race-free family is the contract
        # under test, not prewarm timing)
        lk = _prof.profiled_lock("scrape_probe")

        def hold():
            with lk:
                time.sleep(0.05)

        ht = _threading.Thread(target=hold, name="scrape-holder",
                               daemon=True)
        ht.start()
        time.sleep(0.01)
        with lk:
            pass
        ht.join()
        _gc.collect()
        _prof.compiles.note("scrape-probe-sig", 1234)

        families = ("nebula_lock_wait_us_scrape_probe",
                    "nebula_graph_gc_pause_us",
                    "nebula_tpu_engine_compile_us")
        # the daemons share the process StatsManager, so every role's
        # exposition must carry the families — and parse strictly
        for port, daemon in ((graphd.ws_port, "graphd"),
                             (storaged.ws_port, "storaged"),
                             (metad.ws_port, "metad")):
            fams = parse(_scrape(port))
            for fam in families:
                assert fam in fams, (daemon, fam)
                assert fams[fam].type == "histogram", (daemon, fam)
                count = [s for s in fams[fam].samples
                         if s.name == fam + "_count"][0]
                assert count.value >= 1, (daemon, fam)
        # graphd also carries the serve-path lock sites + the
        # device-memory ledger gauges next to them
        gfams = parse(_scrape(graphd.ws_port))
        assert gfams["nebula_tpu_engine_device_mem_bytes"] \
            .samples[0].value > 0
        assert "nebula_tpu_engine_device_mem_snapshots" in gfams
        # federation: /cluster_metrics merges all three roles; the
        # strict parser validates each instance's bucket ladder per
        # label series (label-series validation)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{graphd.ws_port}/cluster_metrics"
                ) as r:
            doc = r.read().decode()
        cfams = parse(doc)
        for fam in families:
            assert fam in cfams, fam
            counts = [s for s in cfams[fam].samples
                      if s.name == fam + "_count"]
            # one complete label series per daemon instance
            assert len(counts) == 3, (fam, [s.labels for s in counts])
            roles = {s.labels.get("role") for s in counts}
            assert roles == {"graph", "storage", "meta"}, roles
            instances = {s.labels.get("instance") for s in counts}
            assert len(instances) == 3, instances
    finally:
        graph_flags.set("cluster_device_serve", True)
        graphd.stop()
        storaged.stop()
        metad.stop()


def test_flight_and_slo_endpoints_serve_on_every_daemon():
    """/flight and /slo are WebService built-ins: every daemon serves
    them (the recorder/engine are process-global, like the tracer)."""
    from nebula_tpu.daemons import serve_metad

    metad = serve_metad(ws_port=0)
    try:
        def get(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{metad.ws_port}{path}") as r:
                return _json.loads(r.read()), r.status

        body, st = get("/flight")
        assert st == 200 and "triggers" in body and "events" in body
        assert any(t["name"] == "breaker_open"
                   for t in body["triggers"])
        body, st = get("/slo")
        assert st == 200 and "objectives" in body
    finally:
        metad.stop()


def test_heat_families_conformance_and_federation(tmp_path):
    """ISSUE 14 satellite: the workload-observatory families — the
    nebula_part_heat_* per-part gauges, the nebula_heat_skew_index_*
    per-space gauges, the nebula_heat_sketch_observed counter and the
    nebula_raftex_staleness_ms native histogram — parse STRICTLY on
    every daemon's /metrics and federate through /cluster_metrics
    with instance labels. Disarming heat removes the gauge families
    from the very next scrape (the byte-identity contract)."""
    from nebula_tpu.client import GraphClient
    from nebula_tpu.common import heat as heat_mod
    from nebula_tpu.common.flags import graph_flags, storage_flags
    from nebula_tpu.daemons import (serve_graphd, serve_metad,
                                    serve_storaged)
    from nebula_tpu.engine_tpu import TpuGraphEngine
    from raft_fixture import RaftCluster

    heat_mod.accountant.reset()
    graph_flags.set("heat_enabled", True)
    storage_flags.set("heat_enabled", True)
    graph_flags.set("heat_vertices_k", 32)
    storage_flags.set("heat_vertices_k", 32)
    metad = serve_metad(ws_port=0)
    storaged = serve_storaged(metad.addr, load_interval=0.1, ws_port=0)
    tpu = TpuGraphEngine()
    graphd = serve_graphd(metad.addr, tpu_engine=tpu, ws_port=0)
    raftc = None
    try:
        gc = GraphClient(graphd.addr).connect()
        for s in ("CREATE SPACE omheat(partition_num=2)", "USE omheat",
                  "CREATE TAG t(x int)", "CREATE EDGE e(w int)",
                  "INSERT VERTEX t(x) VALUES 1:(5), 2:(6), 3:(7)",
                  "INSERT EDGE e(w) VALUES 1 -> 2:(3), 2 -> 3:(4)"):
            r = gc.execute(s)
            assert r.ok(), (s, r.error_msg)
        q = "GO 2 STEPS FROM 1 OVER e YIELD e.w AS w"
        for _ in range(20):
            if gc.execute(q).rows:
                break
            time.sleep(0.05)
        for _ in range(5):
            gc.execute(q)
        # the real raftex staleness site: a leader with followers in
        # THIS process feeds the shared raftex.staleness_ms histogram
        raftc = RaftCluster(2, tmp_path)
        leader = raftc.wait_leader()
        assert leader.append_async(b"x").result(timeout=3).name == \
            "SUCCEEDED"
        deadline = time.time() + 5
        from nebula_tpu.common.stats import stats as _stats
        while time.time() < deadline and \
                "raftex.staleness_ms" not in _stats.histogram_names():
            time.sleep(0.05)
        assert "raftex.staleness_ms" in _stats.histogram_names()

        # strict conformance on ALL THREE daemons (parse() validates
        # the whole document); graphd + storaged additionally carry
        # the per-part gauge families, every daemon the shared
        # sketch counter + staleness histogram
        for port, daemon in ((graphd.ws_port, "graphd"),
                             (storaged.ws_port, "storaged"),
                             (metad.ws_port, "metad")):
            fams = parse(_scrape(port))
            assert "nebula_heat_sketch_observed" in fams, daemon
            assert fams["nebula_heat_sketch_observed"].type == \
                "counter", daemon
            stale = fams["nebula_raftex_staleness_ms"]
            assert stale.type == "histogram", daemon
            count = [s for s in stale.samples
                     if s.name == stale.name + "_count"][0]
            assert count.value >= 1, daemon
            heat_fams = [f for f in fams
                         if f.startswith("nebula_part_heat_")]
            skew_fams = [f for f in fams
                         if f.startswith("nebula_heat_skew_index_")]
            if daemon in ("graphd", "storaged"):
                assert heat_fams, daemon
                assert skew_fams, daemon
                for f in heat_fams + skew_fams:
                    assert fams[f].type == "gauge", (daemon, f)

        # federation: /cluster_metrics strict-parses and carries the
        # part-heat families with instance labels from both roles
        with urllib.request.urlopen(
                f"http://127.0.0.1:{graphd.ws_port}/cluster_metrics"
                ) as r:
            doc = r.read().decode()
        cfams = parse(doc)
        heat_fams = [f for f in cfams
                     if f.startswith("nebula_part_heat_")]
        assert heat_fams
        insts = set()
        for f in heat_fams:
            for s in cfams[f].samples:
                insts.add(s.labels.get("instance"))
        assert len(insts) >= 2, insts     # graphd AND storaged slabs
        assert "nebula_raftex_staleness_ms" in cfams

        # kill switch: disarm -> the gauge families vanish from the
        # next scrape on every daemon that served them
        graph_flags.set("heat_enabled", False)
        storage_flags.set("heat_enabled", False)
        for port in (graphd.ws_port, storaged.ws_port):
            fams = parse(_scrape(port))
            assert not [f for f in fams
                        if f.startswith("nebula_part_heat_")]
            assert not [f for f in fams
                        if f.startswith("nebula_heat_skew_index_")]
    finally:
        if raftc is not None:
            raftc.stop()
        graphd.stop()
        storaged.stop()
        metad.stop()
        graph_flags.set("heat_enabled", True)
        storage_flags.set("heat_enabled", True)
        graph_flags.set("heat_vertices_k", 0)
        storage_flags.set("heat_vertices_k", 0)
        heat_mod.accountant.reset()
